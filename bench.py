"""Benchmark: flagship training throughput on one real TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Workloads (the 5 BASELINE.json configs + one serving extra):
  - BERT-Base pretrain step, seq 128 (headline: tokens/sec/chip)
  - ResNet-50 train step (imgs/sec/chip)
  - GPT-2-small train step, seq 1024 (tokens/sec/chip + MFU)
  - Transformer-base WMT beam-4 inference (single-executable
    lax.while_loop decode; output tokens/sec + per-sentence latency)
  - MNIST LeNet static Program/Executor train step (imgs/sec incl.
    host feed/fetch — the static-path overhead measurement)
  - LeNet int8-bundle Predictor serving (imgs/sec int8 vs fp32 +
    max prob diff -> int8_imgs_per_sec / int8_vs_fp32 extras)

All run the fused donated TrainStep (fwd+bwd+clip+update in one XLA
executable), bf16 params with f32 master weights — the standard TPU
recipe. vs_baseline compares against the reference's published-era GPU
headline numbers recorded below (BASELINE.json `published` is empty, so
these V100-fp16 figures stand in as the reference baseline).
"""
import json
import os
import sys
import time

import numpy as np

SMOKE = os.environ.get("PADDLE_TPU_BENCH_SMOKE") == "1"  # tiny-shape CPU run
CPU_FALLBACK = False  # backend-init exhausted retries -> labeled CPU run


class _Deadline(BaseException):
    """Raised by the SIGALRM watchdog; BaseException so per-leg `except
    Exception` blocks can't swallow it (the alarm is one-shot — once
    swallowed, a later hang would die JSON-less under the driver's kill)."""

# Reference-era baselines (V100 fp16, PaddlePaddle ~1.7 headline figures):
# BERT-Base pretrain seq128 ~200 seq/s = 25.6k tok/s; ResNet-50 ~980 img/s.
BASELINE_BERT_TOKENS_S = 25600.0
BASELINE_RESNET_IMGS_S = 980.0
BASELINE_GPT_TOKENS_S = 25000.0  # GPT-2-small-class LM, V100 fp16
# Transformer-base beam-4 batched decode, V100 fp16 stand-in (~50 sent/s
# at ~30 output tokens each); LeNet-MNIST through the fluid Executor on
# GPU was host-bound around 10k imgs/s.
BASELINE_WMT_TOKENS_S = 1500.0
BASELINE_LENET_IMGS_S = 10000.0

PEAK_FLOPS = {  # per-chip peak bf16 FLOP/s
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6e": 918e12,
}

# Per-leg perf targets on ONE v5e chip (VERDICT r4 Next #8) — the legs
# are sized so a healthy run should hit these; bench output records
# target + met so a regression is visible in BENCH_r*.json itself:
# - bert  B=64 L=128:  ~674 MFLOP/token (6N + attn); >=40% MFU =
#   ~117k tok/s. B=64 (8192 tok/step) keeps the MXU fed; fits 16G HBM.
# - gpt   B=16 L=1024: ~857 MFLOP/token; >=40% MFU = ~92k tok/s.
# - resnet50 B=128: ~12.3 GFLOP/img trained (3x 4.1 GFLOP fwd); conv
#   stacks reach lower MFU than transformer matmuls — expect 2000-3000
#   imgs/s on v5e (>=2x the 980 imgs/s V100 baseline), target >=2000.
MFU_TARGET_BERT = 0.40
MFU_TARGET_GPT = 0.40
RESNET50_TRAIN_FLOPS_PER_IMG = 12.3e9
IMGS_TARGET_RESNET50 = 2000.0


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def _peak_flops():
    import jax

    kind = jax.devices()[0].device_kind
    for k, v in PEAK_FLOPS.items():
        if k.lower() in kind.lower():
            return v
    return 197e12


def _mfu(n_params, n_layers, hidden, B, L, dt):
    """Model FLOPs utilization; denominator includes attention FLOPs
    (PaLM appendix B formula: 6N + 12*n_layer*d_model*L per token)."""
    flops_per_token = 6.0 * n_params + 12.0 * n_layers * hidden * L
    return flops_per_token * B * L / dt / _peak_flops()


def _time_step(step, batch, warmup=3, iters=10):
    import jax

    if SMOKE:
        warmup, iters = 1, 2

    for _ in range(warmup):
        loss = step(*batch)
    jax.block_until_ready(loss._data)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(*batch)
    jax.block_until_ready(loss._data)
    return (time.perf_counter() - t0) / iters, float(np.asarray(loss._data))


def _step_collectives(step, leg):
    """CollectiveProfile of a timed train step (obs.spmd), as one
    structured stderr JSON line + a compact dict for the bench extras.
    Single-chip legs honestly report zero collectives; never lets a
    profiling failure cost the leg its numbers."""
    try:
        prof = step.collective_profile()
    except Exception as e:
        _log(f"{leg}: collective profile failed: {type(e).__name__}: {e}")
        return None
    if prof is None:
        return None
    _log("COLLECTIVE_PROFILE " + json.dumps(
        {"leg": leg, **prof}, sort_keys=True))
    return {"n_ops": prof["n_ops"], "counts": prof["counts"],
            "total_bytes": prof["total_bytes"],
            "wire_bytes": prof["wire_bytes"]}


def bench_bert(B=64, L=128):
    import paddle_tpu as pt
    from paddle_tpu import optim
    from paddle_tpu.models.nlp.bert import (BertForPretraining, bert_base,
                                            bert_pretrain_loss)

    pt.seed(0)
    cfg = bert_base()
    model = BertForPretraining(cfg)
    model.bfloat16()
    opt = optim.AdamW(parameters=model.parameters(), learning_rate=1e-4,
                      multi_precision=True,
                      grad_clip=optim.ClipGradByGlobalNorm(1.0))
    step = pt.TrainStep(model, opt, bert_pretrain_loss)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, L)).astype("int32")
    tt = np.zeros((B, L), "int32")
    am = np.ones((B, L), "int32")
    mlm = np.where(rng.rand(B, L) < 0.15, ids, -100).astype("int32")
    nsp = rng.randint(0, 2, (B,)).astype("int32")
    dt, loss = _time_step(step, (ids, tt, am, mlm, nsp))
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens_s = B * L / dt
    mfu = _mfu(n_params, cfg.layers, cfg.hidden, B, L, dt)
    return {"tokens_per_sec": tokens_s, "step_ms": dt * 1e3, "mfu": mfu,
            "loss": loss, "params": n_params,
            "collectives": _step_collectives(step, "bert")}


def bench_resnet50(B=128, size=224):
    import paddle_tpu as pt
    from paddle_tpu import optim
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.vision import resnet50

    pt.seed(0)
    model = resnet50()
    model.bfloat16()
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                         parameters=model.parameters(),
                         multi_precision=True)
    step = pt.TrainStep(
        model, opt,
        lambda m, x, y: F.cross_entropy(
            m(x.astype("bfloat16")).astype("float32"), y))
    rng = np.random.RandomState(0)
    x = rng.randn(B, 3, size, size).astype(np.float32)
    y = rng.randint(0, 1000, (B,)).astype("int32")
    dt, loss = _time_step(step, (x, y))
    # the 12.3 GFLOP/img constant is a 224x224 figure: scale for other
    # probe sizes (conv FLOPs go with spatial area)
    flops_img = RESNET50_TRAIN_FLOPS_PER_IMG * (size / 224.0) ** 2
    mfu = flops_img * B / dt / _peak_flops()
    return {"imgs_per_sec": B / dt, "step_ms": dt * 1e3, "mfu": mfu,
            "loss": loss,
            "collectives": _step_collectives(step, "resnet50")}


def bench_gpt(B=16, L=1024):
    import paddle_tpu as pt
    from paddle_tpu import optim
    from paddle_tpu.models.nlp.gpt import GPT, GPTConfig, gpt_loss

    pt.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden=768, layers=12, heads=12,
                    max_seq=L, dropout=0.0)
    model = GPT(cfg)
    model.bfloat16()
    opt = optim.AdamW(parameters=model.parameters(), learning_rate=1e-4,
                      multi_precision=True,
                      grad_clip=optim.ClipGradByGlobalNorm(1.0))
    step = pt.TrainStep(model, opt, gpt_loss)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, L)).astype("int32")
    labels = np.roll(ids, -1, axis=1).astype("int32")
    dt, loss = _time_step(step, (ids, labels))
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens_s = B * L / dt
    mfu = _mfu(n_params, cfg.layers, cfg.hidden, B, L, dt)
    return {"tokens_per_sec": tokens_s, "step_ms": dt * 1e3, "mfu": mfu,
            "loss": loss, "params": n_params,
            "collectives": _step_collectives(step, "gpt")}


def bench_wmt_beam(B=16, L_src=32, beam=4, max_len=32):
    """Transformer-base WMT en-de beam-search inference through the
    single-executable decode (encode + static-KV-cache lax.while_loop
    beam in ONE XLA program — no per-token host sync)."""
    import paddle_tpu as pt
    from paddle_tpu.models.nlp.transformer import WMTTransformer

    pt.seed(0)
    model = WMTTransformer(32000, 32000, d_model=512, nhead=8,
                           num_layers=6, dim_feedforward=2048,
                           dropout=0.0, max_len=max_len)
    model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    src = rng.randint(2, 32000, (B, L_src)).astype("int64")
    warmup, iters = (1, 2) if SMOKE else (2, 8)
    import jax

    for _ in range(warmup):
        toks, _ = model.beam_search_decode_xla(src, beam_size=beam,
                                               max_len=max_len)
    jax.block_until_ready(toks._data)
    t0 = time.perf_counter()
    for _ in range(iters):
        toks, _ = model.beam_search_decode_xla(src, beam_size=beam,
                                               max_len=max_len)
    jax.block_until_ready(toks._data)
    dt = (time.perf_counter() - t0) / iters
    return {"tokens_per_sec": B * max_len / dt,
            "sentences_per_sec": B / dt,
            "latency_ms_per_batch": dt * 1e3, "beam": beam}


def bench_int8_predictor(B=256):
    """LeNet served via the int8 bundle (save -> quantize_inference_model
    -> Predictor): imgs/sec int8 vs fp32 through the same Predictor path.
    The int8 copy is HBM-resident with the dequant fused into the
    consumer — on small models this measures dispatch + weight-traffic,
    the serving overhead axis."""
    import tempfile

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.inference import Predictor
    from paddle_tpu.models.vision import LeNet
    from paddle_tpu.quant import quantize_inference_model

    pt.seed(0)
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            xv = pt.static.data("x", [B, 1, 28, 28], "float32")
            prob = F.softmax(LeNet()(xv), axis=-1)
    finally:
        pt.disable_static()
    exe = pt.static.Executor()
    exe.run(startup)
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "lenet")
        pt.framework.io.save_inference_model(prefix, ["x"], [prob],
                                             program=main)
        quantize_inference_model(prefix)
        p32 = Predictor(prefix)
        p8 = Predictor(prefix + "_int8")
        x = np.random.RandomState(0).randn(B, 1, 28, 28).astype("float32")
        warmup, iters = (1, 2) if SMOKE else (3, 20)

        def rate(pred):
            for _ in range(warmup):
                pred.run({"x": x})
            t0 = time.perf_counter()
            for _ in range(iters):
                out, = pred.run({"x": x})
            return B / ((time.perf_counter() - t0) / iters), out

        r32, o32 = rate(p32)
        r8, o8 = rate(p8)
        return {"imgs_per_sec_int8": r8, "imgs_per_sec_fp32": r32,
                "int8_vs_fp32": r8 / r32 if r32 else 0.0,
                "max_prob_diff": float(np.abs(o32 - o8).max())}


# ceilings for the serve leg's exit-time SLO evaluation: generous
# enough for the dispatch-bound TinyLM on the CPU smoke path, tight
# enough that a pathological scheduler/latency regression lands as a
# nonempty serve_slo_violations list in the one-line JSON
SERVE_SLO_SPEC = {"ttft_p99_ms": 30000.0, "tpot_p99_ms": 5000.0,
                  "availability": 0.9, "goodput_tps": 0.01}


def bench_serve(requests=48, rate=100.0, pages=256, page_size=16):
    """Continuous-batching serving (paddle_tpu.serving): a Poisson
    trace of mixed-length prompts through ServeEngine's paged-KV
    decode path, reporting tokens/s and p50/p99 TTFT/TPOT — the
    serving-latency axis the train legs can't see. The TinyLM is
    dispatch-bound by design: this measures the scheduler + paged
    decode step overhead, which is exactly what continuous batching
    amortizes."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench_leg",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    # journal the leg so the default serving SLO spec (generous enough
    # to hold on the CPU smoke path, but a real ceiling: a pathological
    # scheduler regression trips it) evaluates post-hoc over the real
    # per-request records — the same obs.slo.evaluate_run math
    # ``serve_bench --slo`` gates on
    slo_dir = None
    try:
        import shutil as _sh
        import tempfile as _tf

        from paddle_tpu.obs import journal as _jl

        slo_dir = _tf.mkdtemp(prefix="pt_serve_slo_")
        _jl.start_run(slo_dir)
    except Exception as e:
        _log(f"serve slo journal failed: {type(e).__name__}: {e}")
        slo_dir = None
    # weighted two-tenant trace on every round: rates proportional to
    # weights (3:1), so the measured served-token share should track
    # the weight share and tenant_share_err stays a near-zero fairness
    # canary — a scheduler/fairness regression shows up as drift here
    # before it trips any latency gate
    tenants = sb.parse_tenants(
        f"a:rate={0.75 * rate:g},weight=3;b:rate={0.25 * rate:g},weight=1")
    try:
        rep = sb.run_bench(n_requests=requests, rate=rate, pages=pages,
                           page_size=page_size, tenants=tenants)
    finally:
        if slo_dir is not None:
            _jl.end_run()
    out = {
        "tokens_per_sec": rep["tokens_per_sec"],
        "ttft_p50_ms": rep["ttft_p50_ms"],
        "ttft_p99_ms": rep["ttft_p99_ms"],
        "tpot_p50_ms": rep["tpot_p50_ms"],
        "tpot_p99_ms": rep["tpot_p99_ms"],
        "requests": rep["requests"], "finished": rep["finished"],
        "preemptions": rep["preemptions"],
        "kv_fragmentation": rep["kv_fragmentation"],
        "tenant_share_err": rep.get("tenant_share_err"),
    }
    if slo_dir is not None:
        try:
            from paddle_tpu.obs.slo import evaluate_run

            slo_rep = evaluate_run(
                slo_dir, SERVE_SLO_SPEC, duration_s=rep["wall_s"])
            out["slo_violations"] = slo_rep["violations"]
        except Exception as e:
            _log(f"serve slo eval failed: {type(e).__name__}: {e}")
        _sh.rmtree(slo_dir, ignore_errors=True)
    # replica cold-start vs warm-start: time-to-first-request of a
    # fresh ServeEngine against a fresh AOT executable cache (compiles
    # prefill + decode buckets) vs the same cache warm (hydrates) —
    # the autoscaling-speed axis the throughput numbers can't see
    try:
        import shutil
        import tempfile

        from paddle_tpu.runtime import aot as _aot
        from paddle_tpu.serving.engine import ServeEngine, TinyLM
        from paddle_tpu.serving.kv_cache import PagedKVCache

        tmpd = tempfile.mkdtemp(prefix="pt_aot_serve_")

        def first_request_ms():
            model = TinyLM(vocab_size=32, num_heads=2, head_dim=8,
                           seed=0)
            kv = PagedKVCache(32, 4, 2, 8, max_seq_len=32)
            eng = ServeEngine(model, kv, aot_cache_dir=tmpd)
            t0 = time.perf_counter()
            eng.submit([3, 1, 4, 1, 5], max_new_tokens=4)
            eng.run()
            return (time.perf_counter() - t0) * 1e3

        try:
            cold = first_request_ms()
            warm = first_request_ms()
            # one atomic update: a partial key set would KeyError
            # _score's serve extras block
            out.update({
                "cold_start_ms": cold, "warm_start_ms": warm,
                "aot_hits": _aot.resolve_cache(tmpd).stats()["hits"]})
        finally:
            shutil.rmtree(tmpd, ignore_errors=True)
    except Exception as e:
        _log(f"serve cold_start leg failed: {type(e).__name__}: {e}")
    # live SLO exporter scrape MID-RUN: an engine with requests still
    # in flight, scraped once over real localhost HTTP — the
    # autoscaler-signal-plane latency axis (obs.export) plus a sanity
    # check that the scraped running-count gauge matches the engine
    try:
        import urllib.request

        from paddle_tpu.obs import export as _export
        from paddle_tpu.serving.engine import ServeEngine, TinyLM
        from paddle_tpu.serving.kv_cache import PagedKVCache

        eng = ServeEngine(TinyLM(vocab_size=32, num_heads=2,
                                 head_dim=8, seed=0),
                          PagedKVCache(32, 4, 2, 8, max_seq_len=32))
        for prompt in ([3, 1, 4], [1, 5], [9]):
            eng.submit(prompt, max_new_tokens=6)
        eng.run(max_steps=2)  # mid-run: decodes still in flight
        expected_running = float(len(eng.scheduler.running))
        exp = _export.MetricsExporter(engines=[eng])
        port = exp.start()
        try:
            t0 = time.perf_counter()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10) as resp:
                body = resp.read().decode("utf-8")
            scrape_ms = (time.perf_counter() - t0) * 1e3
        finally:
            exp.stop()
        vals = _export.parse_prometheus_text(body)
        running = vals.get(f'paddle_tpu_serving_slo_running'
                           f'{{replica="{eng.replica_id}"}}')
        eng.run()  # drain
        out.update({
            "export_scrape_ms": scrape_ms,
            "export_gauge_ok": bool(
                running is not None and running == expected_running
                and expected_running >= 1.0)})
    except Exception as e:
        _log(f"serve export scrape leg failed: {type(e).__name__}: {e}")
    # multi-replica router leg: the same Poisson trace class through a
    # 2-replica serving.fleet Router (in-process replicas) — the
    # dispatch-layer tax (router_overhead_ms) and fleet-aggregate
    # latency axes next to the single-engine numbers
    try:
        rep2 = sb.run_bench_fleet(
            n_requests=min(requests, 24), rate=rate, replicas=2,
            pages=pages, page_size=page_size, tenants=tenants)
        out.update({
            "replicas": rep2["replicas"],
            "router_overhead_ms": rep2["router_overhead_ms"],
            "fleet_tokens_per_sec": rep2["tokens_per_sec"],
            "fleet_ttft_p99_ms": rep2["ttft_p99_ms"],
            "fleet_requeued": rep2["requeued"],
            "fleet_tenant_share_err": rep2.get("tenant_share_err"),
        })
    except Exception as e:
        _log(f"serve fleet leg failed: {type(e).__name__}: {e}")
    return out


def bench_lenet_exec(B=256, K=8):
    """MNIST LeNet through the static Program/Executor feed/fetch loop
    (BASELINE config 1) — measures compiled-program dispatch + host
    round-trip overhead, the role the fluid Executor played. Also times
    the fused multi-step path (K microbatches per lax.scan dispatch,
    ``Executor.run_steps``) and reports the compiled-call accounting
    (compiles + dispatches) for both, so BENCH records carry the
    dispatch-amortization evidence even on CPU fallback rounds."""
    import paddle_tpu as pt
    from paddle_tpu import optim
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.vision import LeNet

    pt.seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(B, 1, 28, 28).astype("float32")
    y = rng.randint(0, 10, (B,)).astype("int64")
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            xv = pt.static.data("x", [B, 1, 28, 28], "float32")
            yv = pt.static.data("y", [B], "int64")
            model = LeNet()
            loss = F.cross_entropy(model(xv), yv)
            optim.Momentum(0.01, 0.9,
                           parameters=model.parameters()).minimize(loss)
    finally:
        pt.disable_static()
    exe = pt.static.Executor()
    exe.run(startup)
    warmup, iters = (1, 2) if SMOKE else (3, 20)
    for _ in range(warmup):
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
    dt = (time.perf_counter() - t0) / iters
    res = {"imgs_per_sec": B / dt, "step_ms": dt * 1e3,
           "loss": float(np.asarray(out[0]))}
    # fused path: same program, K microbatches per compiled dispatch
    try:
        feeds = [{"x": x, "y": y}] * K
        exe.run_steps(main, feeds=feeds, fetch_list=[loss])  # warm/compile
        t0 = time.perf_counter()
        for _ in range(max(1, iters // K)):
            fused_out = exe.run_steps(main, feeds=feeds, fetch_list=[loss])
        fdt = (time.perf_counter() - t0) / max(1, iters // K)
        res.update({
            "fused_imgs_per_sec": B * K / fdt,
            "fused_step_ms": fdt / K * 1e3,
            "steps_fused": K,
            "fused_vs_loop": (B * K / fdt) / (B / dt) if dt else 0.0,
            "fused_loss": float(np.asarray(fused_out[0][-1])),
        })
    except Exception as e:
        _log(f"lenet_exec fused leg failed: {type(e).__name__}: {e}")
    cs = exe.cache_stats()
    res["compiled_calls"] = {"compiles": cs["misses"],
                             "dispatches": exe.dispatches,
                             "entries": cs["size"]}
    # AOT cold-start vs warm-start: first-run latency of a FRESH build
    # (new Program + Executor, the replica-hydration scenario) against
    # a fresh executable cache (pays XLA compile, publishes) and then
    # against the same cache warm (hydrates from disk) — the number
    # ROADMAP item 4 exists to shrink
    try:
        import shutil
        import tempfile

        from paddle_tpu.runtime import aot as _aot

        tmpd = tempfile.mkdtemp(prefix="pt_aot_bench_")

        def first_run_ms():
            pt.seed(0)
            pt.enable_static()
            try:
                m2, s2 = pt.static.Program(), pt.static.Program()
                with pt.program_guard(m2, s2):
                    xv2 = pt.static.data("x", [B, 1, 28, 28], "float32")
                    yv2 = pt.static.data("y", [B], "int64")
                    model2 = LeNet()
                    l2 = F.cross_entropy(model2(xv2), yv2)
                    optim.Momentum(
                        0.01, 0.9,
                        parameters=model2.parameters()).minimize(l2)
            finally:
                pt.disable_static()
            e2 = pt.static.Executor()
            e2.run(s2)
            t0 = time.perf_counter()
            e2.run(m2, feed={"x": x, "y": y}, fetch_list=[l2])
            return (time.perf_counter() - t0) * 1e3

        prev = _aot.configured()  # restore any caller-configured cache
        _aot.configure(tmpd)
        try:
            cold = first_run_ms()
            warm = first_run_ms()
            res.update({
                "cold_start_ms": cold, "warm_start_ms": warm,
                "aot_hits": (_aot.cache_stats() or {}).get("hits", 0)})
        finally:
            _aot.configure(prev)
            shutil.rmtree(tmpd, ignore_errors=True)
    except Exception as e:
        _log(f"lenet_exec cold_start leg failed: "
             f"{type(e).__name__}: {e}")
    return res


def _devices_blocking_guard(timeout_s):
    """jax.devices() through a worker thread: the axon tunnel client can
    BLOCK FOREVER inside PJRT init (observed live: relay down -> no
    exception, no return), and a blocked main thread means the driver's
    kill leaves no JSON. Returns (devices, error) with devices=None on
    timeout/failure."""
    import threading

    box = {}

    def work():
        try:
            import jax

            box["devs"] = jax.devices()
        except Exception as e:  # report, don't raise in the thread
            box["err"] = e

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return None, TimeoutError(f"jax.devices() blocked > {timeout_s}s "
                                  "(axon tunnel down?)")
    return box.get("devs"), box.get("err")


def _init_backend():
    """Initialize the jax backend, retrying transient tunnel failures.

    Two rounds of BENCH gates died here (rc=1/hang, no JSON): the axon
    TPU tunnel can fail its first init OR block indefinitely. Retry with
    backoff under a per-attempt timeout; after exhausting retries,
    degrade to a LABELED cpu smoke run (never bench full shapes on host
    CPU)."""
    global SMOKE, CPU_FALLBACK
    import jax

    # persistent executable cache: a re-run session (e.g. the recovery
    # watcher firing twice, or bench after probe) skips the 20-40s
    # first-compiles on the tunnel-attached chip
    try:
        from paddle_tpu import set_compilation_cache

        set_compilation_cache(os.path.join(os.path.dirname(
            os.path.abspath(__file__)), ".xla_cache"))
    except Exception as e:
        _log(f"compilation cache unavailable: {e}")
    if SMOKE:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()
    last = None
    for attempt in range(5):
        devs, err = _devices_blocking_guard(120.0)
        if devs is not None:
            _log(f"backend ok on attempt {attempt + 1}: {devs}")
            return devs
        last = err
        _log(f"backend init attempt {attempt + 1} failed: "
             f"{type(err).__name__}: {err}")
        if isinstance(err, TimeoutError):
            break  # the stuck client thread won't recover; fail fast
        try:
            import jax.extend.backend as jeb

            jeb.clear_backends()
        except Exception:
            pass
        if attempt < 4:  # no pointless sleep after the final attempt
            time.sleep(min(15.0, 2.0 ** attempt))
    # Retries exhausted (BENCH_r05: axon tunnel down for the whole
    # window -> rounds of `bench_failed` zeros). A zero teaches the
    # scoreboard nothing; a LABELED CPU number at least proves the
    # workloads still build and run. Never bench full-size shapes on
    # host CPU (hours-long stall under a per-chip TPU metric): degrade
    # to the smoke shapes and mark the run, and journal the degradation
    # so the flight record shows why this round's numbers are small.
    _log(f"backend init exhausted retries ({last}); degrading to "
         "JAX_PLATFORMS=cpu smoke shapes (metric labeled cpu_fallback)")
    try:
        from paddle_tpu.obs import journal as _journal

        if _journal.ACTIVE is not None:
            _journal.ACTIVE.event(
                "bench.backend_degraded", to="cpu",
                error=f"{type(last).__name__}: {last}")
    except Exception:
        pass
    try:
        try:
            import jax.extend.backend as jeb

            jeb.clear_backends()
        except Exception:
            pass
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:
        _log(f"CPU fallback init failed too: {type(e).__name__}: {e}")
        return None
    # the timed-out tunnel thread may still hold jax's backend-init
    # lock, and a direct jax.devices() here would block on it forever —
    # the exact no-JSON death this function exists to prevent. Probe
    # through the same worker-thread guard as the TPU attempts.
    devs, err = _devices_blocking_guard(60.0)
    if devs is None:
        _log(f"CPU fallback init failed too: {type(err).__name__}: {err}")
        return None
    SMOKE = True
    CPU_FALLBACK = True
    return devs


def _run_benches(results):
    """Mutates `results` in place so legs finished before a watchdog
    deadline still reach the JSON line."""
    global bench_bert, bench_resnet50, bench_gpt, bench_wmt_beam, \
        bench_lenet_exec, bench_int8_predictor, bench_serve
    if SMOKE:
        import functools

        bench_bert = functools.partial(bench_bert, B=2, L=128)
        bench_resnet50 = functools.partial(bench_resnet50, B=2, size=64)
        bench_gpt = functools.partial(bench_gpt, B=1, L=128)
        bench_wmt_beam = functools.partial(bench_wmt_beam, B=2, L_src=8,
                                           beam=2, max_len=8)
        bench_lenet_exec = functools.partial(bench_lenet_exec, B=8)
        bench_int8_predictor = functools.partial(bench_int8_predictor, B=8)
        bench_serve = functools.partial(bench_serve, requests=8,
                                        rate=50.0, pages=64, page_size=8)
    for name, fn in (("bert", bench_bert), ("resnet50", bench_resnet50),
                     ("gpt", bench_gpt), ("wmt_beam", bench_wmt_beam),
                     ("lenet_exec", bench_lenet_exec),
                     ("int8_predictor", bench_int8_predictor),
                     ("serve", bench_serve)):
        pallas_env0 = os.environ.get("PADDLE_TPU_PALLAS")
        for attempt in (1, 2, 3):
            try:
                t0 = time.perf_counter()
                results[name] = fn()
                _log(f"{name}: {results[name]} "
                     f"({time.perf_counter() - t0:.0f}s incl. compile)")
                break
            except Exception as e:  # keep the bench scoreable regardless
                import traceback

                _log(f"{name} FAILED (attempt {attempt}): "
                     f"{type(e).__name__}: {e}")
                _log(traceback.format_exc())
                msg = str(e)
                ml = msg.lower()
                transient = "UNAVAILABLE" in msg or "Connection" in msg
                kernel_bug = "pallas" in ml or "mosaic" in ml \
                    or "VMEM" in msg
                pallas_on = os.environ.get("PADDLE_TPU_PALLAS") \
                    not in ("0", "false", "off")
                if transient and attempt < 3:  # retry as-is first
                    time.sleep(10.0)
                    continue
                if kernel_bug and pallas_on and attempt < 3:
                    # a broken kernel must not zero the whole leg: the
                    # dense XLA path is the measurement fallback
                    _log(f"{name}: retrying with pallas disabled")
                    os.environ["PADDLE_TPU_PALLAS"] = "0"
                    results.setdefault("_extras", {})[
                        name + "_pallas_disabled"] = True
                    continue
                break
        # a kernel-bug fallback must not leak pallas-off into later legs
        if pallas_env0 is None:
            os.environ.pop("PADDLE_TPU_PALLAS", None)
        else:
            os.environ["PADDLE_TPU_PALLAS"] = pallas_env0
    gpt_fell_back = results.get("_extras", {}).get("gpt_pallas_disabled")
    if "gpt" in results and not SMOKE and not gpt_fell_back:
        # pallas-attributable delta: rerun GPT with the kernels disabled
        old = os.environ.get("PADDLE_TPU_PALLAS")
        os.environ["PADDLE_TPU_PALLAS"] = "0"
        try:
            t0 = time.perf_counter()
            results["gpt_no_pallas"] = bench_gpt()
            _log(f"gpt (pallas off): {results['gpt_no_pallas']} "
                 f"({time.perf_counter() - t0:.0f}s incl. compile)")
        except Exception as e:
            _log(f"gpt pallas-off leg FAILED: {type(e).__name__}: {e}")
        finally:
            if old is None:
                os.environ.pop("PADDLE_TPU_PALLAS", None)
            else:
                os.environ["PADDLE_TPU_PALLAS"] = old
    return results


def main():
    # The one-line JSON must print on EVERY exit path (driver contract).
    headline = {"metric": "bench_failed", "value": 0.0, "unit": "none",
                "vs_baseline": 0.0}
    extras = {}
    results = {}
    # Global watchdog: SIGALRM raises so a mid-leg compile/tunnel hang
    # still reaches the JSON print before the driver's kill.
    import signal

    def _deadline(signum, frame):
        raise _Deadline("bench deadline reached")

    deadline_s = int(os.environ.get("PADDLE_TPU_BENCH_DEADLINE", "3000"))
    try:
        signal.signal(signal.SIGALRM, _deadline)
        signal.alarm(deadline_s)
    except Exception:
        pass  # non-main-thread / platform without SIGALRM
    # Hard backstop: SIGALRM only interrupts Python bytecode — a leg
    # blocked inside a C-level PJRT call (compile/block_until_ready on a
    # dead tunnel) never runs the handler. A watchdog thread always can.
    import threading

    def _hard_exit():
        _log("hard watchdog fired; dumping partial results")
        try:
            line = json.dumps(_score(results, headline, extras))
        except Exception:
            line = json.dumps(headline)
        print(line, flush=True)
        os._exit(0)

    hard = threading.Timer(deadline_s + 90.0, _hard_exit)
    hard.daemon = True
    hard.start()
    try:
        if _init_backend() is not None:
            _run_benches(results)
    except _Deadline as e:
        _log(f"bench watchdog fired: {e}; reporting partial results")
    except Exception as e:
        import traceback

        _log(f"bench harness error: {type(e).__name__}: {e}")
        _log(traceback.format_exc())
    finally:
        try:
            hard.cancel()
        except Exception:
            pass
        try:
            signal.alarm(0)
        except Exception:
            pass
        try:
            line = json.dumps(_score(results, headline, extras))
        except Exception:
            line = json.dumps(headline)
        print(line, flush=True)
        # A wedged tunnel client thread must not stall interpreter
        # shutdown after the JSON is out.
        sys.stdout.flush()
        os._exit(0)


def _score(results, headline, extras):
    extras.update(results.pop("_extras", {}))
    # structured collective accounting per train leg (obs.spmd): rides
    # the one-line JSON so BENCH records carry comm volumes, not prose
    coll = {leg: results[leg]["collectives"]
            for leg in ("bert", "resnet50", "gpt")
            if leg in results and results[leg].get("collectives")}
    if coll:
        extras["collectives"] = coll
    if CPU_FALLBACK:
        # the numbers below came from smoke shapes on host CPU after the
        # TPU tunnel refused to init: label them so nobody reads them as
        # per-chip figures (vs_baseline stays honest-but-tiny)
        extras["backend"] = "cpu_fallback_smoke"
    if "bert" in results:
        headline = {
            "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
            "value": round(results["bert"]["tokens_per_sec"], 1),
            "unit": "tokens/s",
            "vs_baseline": round(
                results["bert"]["tokens_per_sec"] / BASELINE_BERT_TOKENS_S, 3),
        }
        extras["bert_mfu"] = round(results["bert"]["mfu"], 4)
        if not SMOKE:  # tiny-shape CPU numbers would always read false
            extras["bert_mfu_target"] = MFU_TARGET_BERT
            extras["bert_target_met"] = bool(
                results["bert"]["mfu"] >= MFU_TARGET_BERT)
    elif "gpt" in results:
        headline = {
            "metric": "gpt2_small_train_tokens_per_sec_per_chip",
            "value": round(results["gpt"]["tokens_per_sec"], 1),
            "unit": "tokens/s",
            "vs_baseline": round(
                results["gpt"]["tokens_per_sec"] / BASELINE_GPT_TOKENS_S, 3),
        }
    elif "resnet50" in results:
        headline = {
            "metric": "resnet50_train_imgs_per_sec_per_chip",
            "value": round(results["resnet50"]["imgs_per_sec"], 1),
            "unit": "imgs/s",
            "vs_baseline": round(
                results["resnet50"]["imgs_per_sec"] / BASELINE_RESNET_IMGS_S,
                3),
        }
    if "resnet50" in results:
        extras["resnet50_imgs_per_sec"] = round(
            results["resnet50"]["imgs_per_sec"], 1)
        extras["resnet50_vs_baseline"] = round(
            results["resnet50"]["imgs_per_sec"] / BASELINE_RESNET_IMGS_S, 3)
        if "mfu" in results["resnet50"]:
            extras["resnet50_mfu"] = round(results["resnet50"]["mfu"], 4)
        if not SMOKE:
            extras["resnet50_imgs_target"] = IMGS_TARGET_RESNET50
            extras["resnet50_target_met"] = bool(
                results["resnet50"]["imgs_per_sec"] >= IMGS_TARGET_RESNET50)
    if "gpt" in results:
        extras["gpt_tokens_per_sec"] = round(
            results["gpt"]["tokens_per_sec"], 1)
        extras["gpt_mfu"] = round(results["gpt"]["mfu"], 4)
        if not SMOKE:
            extras["gpt_mfu_target"] = MFU_TARGET_GPT
            extras["gpt_target_met"] = bool(
                results["gpt"]["mfu"] >= MFU_TARGET_GPT)
    if "gpt_no_pallas" in results and "gpt" in results:
        off = results["gpt_no_pallas"]["tokens_per_sec"]
        extras["gpt_tokens_per_sec_no_pallas"] = round(off, 1)
        extras["pallas_speedup"] = round(
            results["gpt"]["tokens_per_sec"] / off, 3) if off else 0.0
    if "wmt_beam" in results:
        extras["wmt_beam_tokens_per_sec"] = round(
            results["wmt_beam"]["tokens_per_sec"], 1)
        extras["wmt_beam_latency_ms"] = round(
            results["wmt_beam"]["latency_ms_per_batch"], 1)
        extras["wmt_beam_vs_baseline"] = round(
            results["wmt_beam"]["tokens_per_sec"] / BASELINE_WMT_TOKENS_S,
            3)
    if "lenet_exec" in results:
        extras["lenet_exec_imgs_per_sec"] = round(
            results["lenet_exec"]["imgs_per_sec"], 1)
        extras["lenet_exec_vs_baseline"] = round(
            results["lenet_exec"]["imgs_per_sec"] / BASELINE_LENET_IMGS_S,
            3)
        # fused-scan + compiled-call accounting rides the one-line JSON
        # on EVERY round (cpu_fallback_smoke included) so the next real-
        # TPU run lands with comparable fields
        le = results["lenet_exec"]
        if "fused_imgs_per_sec" in le:
            extras["lenet_fused_imgs_per_sec"] = round(
                le["fused_imgs_per_sec"], 1)
            extras["lenet_fused_vs_loop"] = round(le["fused_vs_loop"], 3)
            extras["steps_fused"] = le["steps_fused"]
        if "compiled_calls" in le:
            extras["compiled_calls"] = le["compiled_calls"]
        if "cold_start_ms" in le:
            # AOT executable-cache hydration evidence on EVERY round
            # (cpu_fallback_smoke included): first-run latency cache-
            # cold (XLA compile) vs cache-warm (deserialize from disk)
            extras["cold_start_ms"] = round(le["cold_start_ms"], 1)
            extras["warm_start_ms"] = round(le["warm_start_ms"], 1)
            extras["aot_hits"] = le["aot_hits"]
    if "int8_predictor" in results:
        extras["int8_imgs_per_sec"] = round(
            results["int8_predictor"]["imgs_per_sec_int8"], 1)
        extras["int8_vs_fp32"] = round(
            results["int8_predictor"]["int8_vs_fp32"], 3)
        extras["int8_max_prob_diff"] = round(
            results["int8_predictor"]["max_prob_diff"], 5)
    if "serve" in results:
        # serving latency + throughput extras on EVERY round (the
        # cpu_fallback_smoke rounds included) so the first real-TPU
        # round lands with comparable p50/p99 fields
        sv = results["serve"]
        extras["serve_tokens_per_sec"] = round(
            sv["tokens_per_sec"] or 0.0, 1)
        if sv.get("ttft_p99_ms") is not None:
            extras["serve_ttft_p50_ms"] = round(sv["ttft_p50_ms"], 2)
            extras["serve_ttft_p99_ms"] = round(sv["ttft_p99_ms"], 2)
        if sv.get("tpot_p99_ms") is not None:
            extras["serve_tpot_p50_ms"] = round(sv["tpot_p50_ms"], 2)
            extras["serve_tpot_p99_ms"] = round(sv["tpot_p99_ms"], 2)
        extras["serve_preemptions"] = sv["preemptions"]
        if sv.get("tenant_share_err") is not None:
            # per-tenant fairness canary on EVERY round
            # (cpu_fallback_smoke included): max |served-token share -
            # weight share| over the leg's weighted two-tenant trace
            extras["serve_tenant_share_err"] = round(
                sv["tenant_share_err"], 4)
        if "export_scrape_ms" in sv:
            # live SLO-exporter evidence on EVERY round
            # (cpu_fallback_smoke included): one real localhost HTTP
            # scrape mid-serve + the scraped running-gauge sanity bit
            extras["export_scrape_ms"] = round(sv["export_scrape_ms"], 2)
            extras["export_gauge_ok"] = sv["export_gauge_ok"]
        if "cold_start_ms" in sv:
            extras["serve_cold_start_ms"] = round(sv["cold_start_ms"], 1)
            extras["serve_warm_start_ms"] = round(sv["warm_start_ms"], 1)
            extras["aot_hits"] = extras.get("aot_hits", 0) + \
                sv["aot_hits"]
        if "slo_violations" in sv:
            # SLO verdict on EVERY round (cpu_fallback_smoke included):
            # the serve leg's journal evaluated against SERVE_SLO_SPEC
            extras["serve_slo_violations"] = sv["slo_violations"]
            extras["serve_slo_ok"] = not sv["slo_violations"]
        if "replicas" in sv:
            # 2-replica router evidence on EVERY round
            # (cpu_fallback_smoke included): dispatch-layer overhead
            # next to the single-engine latency fields
            extras["serve_replicas"] = sv["replicas"]
            extras["serve_router_overhead_ms"] = round(
                sv["router_overhead_ms"], 2)
            if sv.get("fleet_ttft_p99_ms") is not None:
                extras["serve_fleet_ttft_p99_ms"] = round(
                    sv["fleet_ttft_p99_ms"], 2)
            if sv.get("fleet_tenant_share_err") is not None:
                extras["serve_fleet_tenant_share_err"] = round(
                    sv["fleet_tenant_share_err"], 4)
    return {**headline, **extras}


if __name__ == "__main__":
    main()
