#!/usr/bin/env python
"""usage_report: per-tenant chargeback tables and fairness gates.

The post-hoc front door for ``paddle_tpu.obs.usage`` (the chargeback
twin of tools/run_report.py): pool every journal under a run dir
(top-level single-engine, ``router/``, ``rank_NN/``) and render the
per-tenant bill — requests, prompt/decode tokens, attributed
device-milliseconds (integer-nanosecond device-second integrals that
telescope bitwise to replica busy time), KV page-MB-seconds (the
page-seconds integral scaled by the cache's bytes/page), and exact
p99 latency columns — next to the router's fairness audit
(measured served-token share vs configured weight share).

Usage:
    python tools/usage_report.py RUN_DIR              # chargeback table
    python tools/usage_report.py RUN_DIR --json
    python tools/usage_report.py --diff BASE_DIR NEW_DIR \\
        [--fairness-drift-threshold 0.2] [--p99-threshold 0.25]
        # exit 1 when NEW drifted past the fairness threshold (and past
        # BASE's own drift — A-vs-A is clean by construction) or a
        # tenant's p99 regressed
    python tools/usage_report.py --self-test          # hand-computed
        # ManualClock fixtures, exact to the token and the nanosecond

``--self-test`` is wired into tier-1 via tests/test_tooling.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

THIS_DIR = os.path.dirname(os.path.abspath(__file__))

DEFAULT_FAIRNESS_DRIFT_THRESHOLD = 0.20  # |served share - weight share|
#                 (absolute; mirrors obs.usage.DEFAULT_FAIRNESS_DRIFT_THRESHOLD)
DEFAULT_P99_THRESHOLD = 0.25  # a tenant's p99 TTFT/e2e may grow 25%


def _load_sibling(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(THIS_DIR, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- loading -----------------------------------------------------------------


def load_usage(run_dir):
    """Pool every journal under ``run_dir`` (``obs.slo.load_any``: the
    same loader the SLO evaluator uses, so single-engine and routed
    fleet runs bill identically) into one chargeback view: the
    per-tenant rollup over every request record, the router's final
    ``tenant.summary`` (+ fairness audit), and each replica's final
    ``tenant.usage`` engine truth."""
    from paddle_tpu.obs import slo as _slo
    from paddle_tpu.obs import usage as _usage

    pooled = run_dir if isinstance(run_dir, dict) else \
        _slo.load_any(run_dir)
    rollup = _usage.rollup_requests(pooled["requests"])
    rsum = None
    replicas = {}
    for e in pooled["events"]:
        kind = e.get("kind")
        if kind == "tenant.summary":
            rsum = e   # last wins: the final truth
        elif kind == "tenant.usage":
            # keyed by replica: a relaunched incarnation's later event
            # supersedes the killed one's (which never journals anyway)
            replicas[e.get("replica")] = e
    page_bytes = None
    for e in replicas.values():
        if isinstance(e.get("page_bytes"), (int, float)):
            page_bytes = e["page_bytes"]
    out = {
        "run_dir": pooled.get("run_dir"),
        "tenants": rollup,
        "router": None if rsum is None else {
            "served_total": rsum.get("served_total"),
            "tenants": rsum.get("tenants") or {}},
        "replicas": {
            rep: {k: e.get(k)
                  for k in ("busy_ns", "prefill_ns", "decode_ns",
                            "page_bytes", "page_open", "seq_allocs",
                            "seq_frees", "tenants")}
            for rep, e in sorted(replicas.items(),
                                 key=lambda kv: str(kv[0]))},
        "page_bytes": page_bytes,
        "fairness": None if rsum is None else _usage.fairness_audit(
            rsum.get("tenants") or {}),
    }
    return out


def page_mb_s(page_ns, page_bytes):
    """KV page-MB-seconds: the pages-held x time integral (int
    pages-nanoseconds) scaled by the cache's bytes per page. None when
    the run journaled no ``tenant.usage`` event to learn the page
    geometry from."""
    if page_bytes is None or page_ns is None:
        return None
    return (page_ns / 1e9) * (page_bytes / 1e6)


# -- render ------------------------------------------------------------------


def _fmt(v, nd=3):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_usage(u, as_json=False):
    """The chargeback table: one row per tenant, a totals row, the
    fairness verdict, and each replica's busy/attribution closure."""
    if as_json:
        return json.dumps(u, indent=1, default=str, sort_keys=True)
    lines = [f"run_dir      {u.get('run_dir', '?')}"]
    hdr = (f"{'tenant':<12} {'reqs':>5} {'done':>5} {'prompt':>7} "
           f"{'decode':>7} {'preempt':>7} {'device_ms':>10} "
           f"{'page_MB_s':>10} {'queue_p99':>9} {'ttft_p99':>9} "
           f"{'tpot_p99':>9} {'e2e_p99':>9}")
    lines.append(hdr)
    tenants = u.get("tenants") or {}
    tot = {"requests": 0, "completed": 0, "prompt_tokens": 0,
           "decode_tokens": 0, "preemptions": 0, "device_ns": 0,
           "page_ns": 0}
    for t in sorted(tenants):
        d = tenants[t]
        for k in tot:
            tot[k] += int(d.get(k) or 0)
        lines.append(
            f"{t:<12} {d.get('requests', 0):>5} "
            f"{d.get('completed', 0):>5} "
            f"{d.get('prompt_tokens', 0):>7} "
            f"{d.get('decode_tokens', 0):>7} "
            f"{d.get('preemptions', 0):>7} "
            f"{_fmt((d.get('device_ns') or 0) / 1e6):>10} "
            f"{_fmt(page_mb_s(d.get('page_ns'), u.get('page_bytes'))):>10} "
            f"{_fmt(d.get('queue_ms_p99')):>9} "
            f"{_fmt(d.get('ttft_ms_p99')):>9} "
            f"{_fmt(d.get('tpot_ms_p99')):>9} "
            f"{_fmt(d.get('e2e_ms_p99')):>9}")
    if tenants:
        lines.append(
            f"{'TOTAL':<12} {tot['requests']:>5} {tot['completed']:>5} "
            f"{tot['prompt_tokens']:>7} {tot['decode_tokens']:>7} "
            f"{tot['preemptions']:>7} "
            f"{_fmt(tot['device_ns'] / 1e6):>10} "
            f"{_fmt(page_mb_s(tot['page_ns'], u.get('page_bytes'))):>10} "
            f"{'':>9} {'':>9} {'':>9} {'':>9}")
    fair = u.get("fairness")
    if fair and fair.get("tenants"):
        line = (f"fairness     max_drift={fair['max_drift']:.3f} "
                f"threshold={fair['threshold']:.3f}")
        if fair.get("worst_tenant") is not None:
            line += f" worst={fair['worst_tenant']}"
        line += " ok" if fair.get("ok") else " DRIFT"
        lines.append(line)
    for rep, e in (u.get("replicas") or {}).items():
        attributed = sum(int(d.get("device_ns") or 0)
                         for d in (e.get("tenants") or {}).values())
        busy = e.get("busy_ns")
        closed = (busy == attributed) if busy is not None else None
        line = (f"replica {rep:<4} busy_ms="
                f"{_fmt((busy or 0) / 1e6)} "
                f"attributed_ms={_fmt(attributed / 1e6)} "
                + ("TELESCOPED" if closed
                   else f"LEAK {busy} != {attributed}"))
        if e.get("page_open"):
            line += f" OPEN-PAGES={e['page_open']}"
        lines.append(line)
    return "\n".join(lines)


# -- diff (the chargeback regression gate) -----------------------------------


def diff_usage(base, new,
               fairness_drift_threshold=DEFAULT_FAIRNESS_DRIFT_THRESHOLD,
               p99_threshold=DEFAULT_P99_THRESHOLD):
    """Compare two chargeback views: the fairness gate flips when NEW's
    max drift exceeds the absolute threshold AND base's own drift (so
    A-vs-A is clean by construction); the per-tenant p99 gate flips
    when a tenant served in BOTH runs regressed its p99 TTFT/e2e by
    more than ``p99_threshold`` (relative) — the per-tenant SLO axis an
    aggregate p99 column dilutes away."""
    bfd = ((base.get("fairness") or {}).get("max_drift"))
    nfd = ((new.get("fairness") or {}).get("max_drift"))
    out = {
        "base_fairness_drift": bfd,
        "new_fairness_drift": nfd,
        "fairness_drift_regression": bool(
            nfd is not None and nfd > fairness_drift_threshold and
            (bfd is None or nfd > bfd)),
    }
    if out["fairness_drift_regression"]:
        out["fairness_worst_tenant"] = \
            (new.get("fairness") or {}).get("worst_tenant")
    p99_regressions = []
    bt, nt = base.get("tenants") or {}, new.get("tenants") or {}
    for tenant in sorted(set(bt) & set(nt)):
        for key in ("ttft_ms_p99", "e2e_ms_p99"):
            bv, nv = bt[tenant].get(key), nt[tenant].get(key)
            if isinstance(bv, (int, float)) and \
                    isinstance(nv, (int, float)) and bv > 0 and \
                    nv > bv * (1.0 + p99_threshold):
                p99_regressions.append(
                    {"tenant": tenant, "metric": key,
                     "base": bv, "new": nv, "ratio": nv / bv})
    out["p99_regressions"] = p99_regressions
    out["p99_regression"] = bool(p99_regressions)
    out["regression"] = out["fairness_drift_regression"] or \
        out["p99_regression"]
    return out


def render_diff(rep, as_json=False):
    if as_json:
        return json.dumps(rep, indent=1, default=str, sort_keys=True)
    lines = []
    for k in ("base_fairness_drift", "new_fairness_drift",
              "fairness_drift_regression", "fairness_worst_tenant",
              "p99_regression", "regression"):
        if rep.get(k) is not None:
            v = rep[k]
            lines.append(f"{k:<26} "
                         + (f"{v:.6g}" if isinstance(v, float)
                            else str(v)))
    for r in rep.get("p99_regressions") or []:
        lines.append(f"  tenant {r['tenant']} {r['metric']} "
                     f"{r['base']:.3f} -> {r['new']:.3f} "
                     f"({r['ratio']:.2f}x)")
    return "\n".join(lines)


# -- self-test ---------------------------------------------------------------


def _selftest_meter(failures):
    """Attribution arithmetic, exact to the nanosecond: the divmod
    decode split (10 ns over 3 lanes -> 4,3,3 in survivor order) and
    the telescoping invariant busy == sum(per-tenant) ==
    sum(per-request), bitwise."""
    from types import SimpleNamespace

    from paddle_tpu.obs.usage import UsageMeter

    m = UsageMeter(replica_id=7)
    reqs = [SimpleNamespace(rid=f"r{i}", tenant=t)
            for i, t in enumerate(("a", "a", "b"))]
    m.charge_prefill(reqs[0], 5e-9)           # 5 ns, tenant a
    m.charge_decode(reqs, 10e-9)              # 10 ns over 3 lanes
    if [m.request_ns[f"r{i}"] for i in range(3)] != [4 + 5, 3, 3]:
        failures.append(
            f"divmod split off: {m.request_ns} (want r0=5+4, r1=3, "
            "r2=3 — first rem lanes get the extra ns, survivor order)")
    if m.device_ns != {"a": 12, "b": 3}:
        failures.append(f"per-tenant device-ns {m.device_ns} != "
                        "{'a': 12, 'b': 3}")
    if m.busy_ns != 15 or m.prefill_ns != 5 or m.decode_ns != 10:
        failures.append(f"busy accounting off: busy={m.busy_ns} "
                        f"prefill={m.prefill_ns} decode={m.decode_ns}")
    try:
        m.verify()
    except AssertionError as e:
        failures.append(f"meter verify failed on exact fixture: {e}")
    m.charge_decode([], 1.0)  # zero survivors: charges nothing
    if m.busy_ns != 15:
        failures.append("an all-preempted (empty) decode pass must "
                        f"not count as busy: busy={m.busy_ns}")
    print("  meter          ok — 10ns/3 lanes -> 4,3,3; busy == "
          "sum(tenant) == sum(request) bitwise; empty pass not busy"
          if not failures else
          f"  meter          FAILED ({len(failures)})")
    return failures


def _selftest_pages(failures):
    """The hand-computed page-second integral: alloc 2 pages at t=0,
    extend to 3 pages at t=2, free at t=5 under a ManualClock ->
    2 pages x 2 s + 3 pages x 3 s = 13e9 pages-ns, exactly, with
    alloc==free closure."""
    from paddle_tpu.serving.kv_cache import PagedKVCache
    from paddle_tpu.serving.scheduler import ManualClock

    clk = ManualClock()
    cache = PagedKVCache(9, 8, 1, 4, max_seq_len=64)
    cache.clock = clk
    cache.alloc("s0", 16)     # 2 pages @ t=0
    clk.advance(2.0)
    cache.extend("s0", 8)     # +1 page @ t=2 (16 -> 24 tokens)
    clk.advance(3.0)
    cache.free("s0")          # close @ t=5
    got = cache.closed_page_ns("s0")
    if got != 13_000_000_000:
        failures.append(f"page integral {got} != hand-computed 13e9 "
                        "(2 pages x 2s + 3 pages x 3s)")
    pu = cache.page_usage()
    if pu["open"] or pu["seq_allocs"] != 1 or pu["seq_frees"] != 1:
        failures.append(f"alloc==free closure broken: {pu}")
    try:
        cache.verify()
    except AssertionError as e:
        failures.append(f"cache verify failed after closure: {e}")
    print("  pages          ok — 2p x 2s + 3p x 3s = 13e9 pages-ns "
          "exact, alloc==free closed"
          if not failures else
          f"  pages          FAILED ({len(failures)})")
    return failures


def _selftest_engine(failures):
    """A real TickingClock engine run billed end-to-end: every charged
    nanosecond lands on exactly one tenant (busy telescopes bitwise),
    every page-second interval closes, and the journal round-trips the
    bill token- and nanosecond-exact into the chargeback table."""
    from paddle_tpu.obs import journal as J
    from paddle_tpu.obs import usage as U
    from paddle_tpu.serving.engine import ServeEngine, TinyLM
    from paddle_tpu.serving.kv_cache import PagedKVCache
    from paddle_tpu.serving.scheduler import Scheduler

    with tempfile.TemporaryDirectory() as d:
        with J.RunJournal(d, flush_every=1, compute_flops=False):
            clk = U.TickingClock()
            cache = PagedKVCache(16, 4, 2, 8, max_seq_len=32)
            eng = ServeEngine(
                TinyLM(), cache,
                scheduler=Scheduler(cache, token_budget=64, clock=clk))
            ra = eng.submit([3, 1, 4], max_new_tokens=4, tenant="a")
            rb = eng.submit([2, 7], max_new_tokens=3, tenant="b")
            eng.run()
        if len(ra.generated) != 4 or len(rb.generated) != 3:
            failures.append(
                f"fixture run token counts off: a={len(ra.generated)} "
                f"(want 4) b={len(rb.generated)} (want 3)")
        eng.usage.verify()
        eu = U.engine_tenant_usage(eng)
        if sum(t["device_ns"] for t in eu["tenants"].values()) != \
                eng.usage.busy_ns:
            failures.append("engine_tenant_usage lost nanoseconds: "
                            f"{eu}")
        if eu["page_open"]:
            failures.append(f"open page intervals after drain: {eu}")
        u = load_usage(d)
        for tenant, want_dev, want_page in (
                ("a", eng.usage.device_ns["a"],
                 cache.closed_page_ns(ra.rid)),
                ("b", eng.usage.device_ns["b"],
                 cache.closed_page_ns(rb.rid))):
            row = (u["tenants"] or {}).get(tenant)
            if row is None:
                failures.append(f"journal lost tenant {tenant}")
                continue
            if row["device_ns"] != want_dev:
                failures.append(
                    f"journal round-trip lost nanoseconds for "
                    f"{tenant}: {row['device_ns']} != {want_dev}")
            if row["page_ns"] != want_page:
                failures.append(
                    f"journal round-trip lost page-ns for {tenant}: "
                    f"{row['page_ns']} != {want_page}")
        arow, brow = u["tenants"].get("a"), u["tenants"].get("b")
        if arow and (arow["prompt_tokens"] != 3
                     or arow["decode_tokens"] != 4):
            failures.append(f"tenant a tokens off: {arow}")
        if brow and (brow["prompt_tokens"] != 2
                     or brow["decode_tokens"] != 3):
            failures.append(f"tenant b tokens off: {brow}")
        total_dev = sum(t["device_ns"] for t in u["tenants"].values())
        if total_dev != eng.usage.busy_ns:
            failures.append(
                f"chargeback total {total_dev} != replica busy "
                f"{eng.usage.busy_ns} (telescoping broke in the "
                "journal)")
        table = render_usage(u)
        if "tenant" not in table or not any(
                ln.startswith("a ") for ln in table.splitlines()):
            failures.append(f"chargeback table lost tenants:\n{table}")
    print("  engine         ok — TickingClock run billed bitwise "
          "(journal device-ns == meter, pages closed, tokens exact)"
          if not failures else
          f"  engine         FAILED ({len(failures)})")
    return failures


def _selftest_fairness(failures):
    """The fairness-drift gate on journal fixtures: the 2x violation
    (weight-0.25 tenant served at share 0.5, drift 0.25 > 0.2) fires;
    A-vs-A is clean; a 2x per-tenant p99 regression fires the p99
    gate."""
    from paddle_tpu.obs import journal as J

    with tempfile.TemporaryDirectory() as d:
        runs = {}
        for name, share_a, ttft_a in (("clean", 0.25, 0.1),
                                      ("viol", 0.5, 0.1),
                                      ("slow", 0.25, 0.2)):
            path = os.path.join(d, name)
            j = J.RunJournal(path, flush_every=1, compute_flops=False)
            j.start()
            for i in range(4):
                j.record_request(
                    rid=f"ra{i}", state="FINISHED", tenant="a",
                    arrival_t=0.0, admit_t=0.01, first_token_t=ttft_a,
                    finish_t=0.5, prompt_tokens=4, output_tokens=4,
                    device_ns=1_000_000, page_ns=2_000_000)
                j.record_request(
                    rid=f"rb{i}", state="FINISHED", tenant="b",
                    arrival_t=0.0, admit_t=0.01, first_token_t=0.1,
                    finish_t=0.5, prompt_tokens=4, output_tokens=4,
                    device_ns=1_000_000, page_ns=2_000_000)
            j.event(
                "tenant.summary", served_total=100,
                tenants={
                    "a": {"share": share_a, "weight_share": 0.25,
                          "served_tokens": 100 * share_a},
                    "b": {"share": 1.0 - share_a, "weight_share": 0.75,
                          "served_tokens": 100 * (1 - share_a)}})
            j.close()
            runs[name] = load_usage(path)
        rep = diff_usage(runs["clean"], runs["viol"])
        if not rep["fairness_drift_regression"] or not rep["regression"]:
            failures.append(
                f"diff missed the 2x fairness violation: {rep}")
        if abs((rep["new_fairness_drift"] or 0) - 0.25) > 1e-12:
            failures.append(
                f"fairness drift {rep['new_fairness_drift']} != "
                "hand-computed 0.25")
        if rep["p99_regression"]:
            failures.append(
                f"fairness fixture false-positived the p99 gate: {rep}")
        self_rep = diff_usage(runs["viol"], runs["viol"])
        if self_rep["regression"]:
            failures.append(f"A-vs-A diff false-positived: {self_rep}")
        prep = diff_usage(runs["clean"], runs["slow"])
        if not prep["p99_regression"] or not prep["regression"]:
            failures.append(
                f"diff missed tenant a's 2x TTFT p99 regression: "
                f"{prep}")
        if any(r["tenant"] != "a" for r in prep["p99_regressions"]):
            failures.append(
                "p99 regression misattributed (only tenant a slowed): "
                f"{prep['p99_regressions']}")
        if prep["fairness_drift_regression"]:
            failures.append(
                f"p99 fixture false-positived the fairness gate: "
                f"{prep}")
        rendered = render_usage(runs["viol"])
        if "DRIFT" not in rendered:
            failures.append(
                f"render lost the fairness verdict:\n{rendered}")
        drep = render_diff(rep)
        if "fairness_drift_regression" not in drep:
            failures.append(f"render_diff lost the gate line:\n{drep}")
    print("  fairness       ok — 2x violation fires (drift exactly "
          "0.25), A-vs-A clean, per-tenant 2x p99 gate fires"
          if not failures else
          f"  fairness       FAILED ({len(failures)})")
    return failures


def self_test():
    failures = []
    failures = _selftest_meter(failures)
    failures = _selftest_pages(failures)
    failures = _selftest_engine(failures)
    failures = _selftest_fairness(failures)
    if failures:
        for f in failures:
            print(f"  FAILED — {f}")
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: divmod decode split (10ns/3 -> 4,3,3) "
          "and busy telescoping bitwise, 13e9 pages-ns integral with "
          "alloc==free closure, a TickingClock engine run billed "
          "token- and nanosecond-exact through the journal into the "
          "chargeback table, and the diff gates fire on the injected "
          "2x fairness violation and 2x per-tenant p99 regression "
          "(A-vs-A clean)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="run dir (render) or two run dirs with --diff")
    ap.add_argument("--diff", action="store_true",
                    help="diff two runs' chargeback views; exit 1 on "
                         "fairness drift or per-tenant p99 regression")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--fairness-drift-threshold", type=float,
                    default=DEFAULT_FAIRNESS_DRIFT_THRESHOLD,
                    help="allowed absolute |served share - weight "
                         "share| fairness drift per tenant")
    ap.add_argument("--p99-threshold", type=float,
                    default=DEFAULT_P99_THRESHOLD,
                    help="allowed relative per-tenant p99 TTFT/e2e "
                         "growth (--diff)")
    ap.add_argument("--self-test", action="store_true",
                    help="hand-computed ManualClock chargeback "
                         "fixtures, exact to the token and nanosecond")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two run dirs")
        rep = diff_usage(
            load_usage(args.paths[0]), load_usage(args.paths[1]),
            fairness_drift_threshold=args.fairness_drift_threshold,
            p99_threshold=args.p99_threshold)
        print(render_diff(rep, as_json=args.json))
        return 1 if rep["regression"] else 0
    if len(args.paths) != 1:
        ap.error("need one run dir (or --diff A B / --self-test)")
    print(render_usage(load_usage(args.paths[0]), as_json=args.json))
    return 0


if __name__ == "__main__":
    sys.exit(main())
