#!/usr/bin/env python
"""aot_cache: inspect, verify, evict, and warm the AOT executable cache.

The operational front door for ``paddle_tpu.runtime.aot`` — the
content-addressed on-disk cache that lets a fresh process (serving
replica, elastic relaunch, fleet probe) hydrate compiled XLA
executables instead of recompiling them.

Usage:
    python tools/aot_cache.py DIR                  # list entries
    python tools/aot_cache.py DIR --verify         # live-fingerprint check
    python tools/aot_cache.py DIR --evict --stale  # drop unloadable ones
    python tools/aot_cache.py DIR --evict --older-than 86400
    python tools/aot_cache.py DIR --evict --all
    python tools/aot_cache.py DIR --warm PREFIX [--buckets 1,4]
        # compile+publish executables for a saved inference model
        # (framework.io.save_inference_model prefix) so a replica's
        # first request hydrates instead of compiling
    python tools/aot_cache.py --self-test
        # round-trip a compiled entry through serialize/deserialize
        # (bitwise outputs, donation survival), a poisoned-fingerprint
        # envelope refusing to load, CacheKey-drift isolation, and the
        # Executor-level hydrate path

Wired into tier-1 via tests/test_tooling.py (chaos_run/obs_report/
run_report pattern).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ensure_fake_devices(n=8):
    """Standalone runs need the fake-device CPU platform configured
    BEFORE jax initializes; under pytest the conftest already did."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


# -- commands -----------------------------------------------------------------


def list_entries(cache, as_json=False):
    rows = cache.entries()
    if as_json:
        return json.dumps(rows, indent=1, default=str, sort_keys=True)
    if not rows:
        return f"(empty cache at {cache.dir})"
    lines = [f"{'digest':<16} {'kind':<16} {'bytes':>10} {'age_s':>8} "
             f"{'compile_ms':>10}  label"]
    for r in rows:
        if r.get("error"):
            lines.append(f"{r['digest'][:16]:<16} UNREADABLE "
                         f"({r['error']})")
            continue
        cm = r.get("compile_ms")
        lines.append(
            f"{r['digest'][:16]:<16} {str(r.get('kind')):<16} "
            f"{r['bytes']:>10} {r['age_s']:>8.0f} "
            f"{(f'{cm:.1f}' if cm is not None else '-'):>10}  "
            f"{r.get('label') or ''}")
    lines.append(f"{len(rows)} entries, "
                 f"{sum(r['bytes'] for r in rows)} bytes total")
    return "\n".join(lines)


def verify(cache, as_json=False):
    ok, stale = cache.verify()
    if as_json:
        return json.dumps({"ok": ok, "stale": stale})
    lines = [f"{len(ok)} entries valid for the live fingerprint"]
    for d in stale:
        lines.append(f"STALE {d[:16]} (would refuse to load; "
                     "--evict --stale clears it)")
    return "\n".join(lines)


def warm(cache, prefix, buckets):
    from paddle_tpu.runtime import aot as _aot

    before = cache.stats()["entries"]
    warmed = _aot.warm_inference_model(prefix, buckets=buckets,
                                       cache=cache)
    after = cache.stats()["entries"]
    return (f"warmed {warmed}/{len(buckets)} bucket(s) from {prefix}: "
            f"{after - before} new entries ({after} total)")


# -- self-test ----------------------------------------------------------------


def self_test():
    import tempfile

    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu.runtime import aot

    failures = []
    env_before = os.environ.pop(aot.ENV_DIR, None)
    try:
        with tempfile.TemporaryDirectory() as d:
            cache = aot.AOTCache(os.path.join(d, "cache"))

            # 1. round-trip: a donated training-style step must come
            # back from disk with bitwise outputs AND its
            # input_output_alias intact
            def step(w, x):
                g = jax.grad(lambda w: ((x @ w) ** 2).sum())(w)
                return w - 0.1 * g

            fn = jax.jit(step, donate_argnums=(0,))
            w = np.random.RandomState(0).randn(8, 8).astype(np.float32)
            x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
            structs = (jax.ShapeDtypeStruct((8, 8), np.float32),
                       jax.ShapeDtypeStruct((4, 8), np.float32))
            exe1, info1 = aot.load_or_compile(fn, structs, "self_test",
                                              cache=cache)
            if info1["source"] != "xla" or not info1["stored"]:
                failures.append(f"first compile not stored: {info1}")
            exe2, info2 = aot.load_or_compile(fn, structs, "self_test",
                                              cache=cache)
            if info2["source"] != "aot_disk":
                failures.append(f"second lookup did not hydrate: {info2}")
            r1 = np.asarray(exe1(jnp.asarray(w), jnp.asarray(x)))
            r2 = np.asarray(exe2(jnp.asarray(w), jnp.asarray(x)))
            if not np.array_equal(r1, r2):
                failures.append("hydrated executable outputs differ "
                                "bitwise from the in-process compile")
            if "input_output_alias" not in exe2.as_text():
                failures.append("donation (input_output_alias) lost in "
                                "the serialize round-trip")

            # 2. content-key drift: a different shape must produce a
            # DIFFERENT entry (miss + fresh compile), never a stale hit
            structs_b = (jax.ShapeDtypeStruct((8, 8), np.float32),
                         jax.ShapeDtypeStruct((16, 8), np.float32))
            _, info3 = aot.load_or_compile(fn, structs_b, "self_test",
                                           cache=cache)
            if info3["source"] != "xla" or \
                    info3["digest"] == info1["digest"]:
                failures.append(f"shape drift did not miss: {info3}")
            if cache.stats()["entries"] != 2:
                failures.append(f"expected 2 entries, got "
                                f"{cache.stats()}")

            # 3. poisoned fingerprint: an envelope claiming another
            # jax version must REFUSE to load — rejected on the JSON
            # header, before ANY pickled bytes are read — and fall
            # back to a fresh compile
            def poison(digest):
                path = cache._path(digest)
                hdr, trees, payload = aot._read_entry(path)
                hdr["fingerprint"] = dict(hdr["fingerprint"],
                                          jax="0.0.poisoned")
                aot._write_entry(path, hdr, trees, payload)

            poison(info1["digest"])
            loaded, reason = cache.load(info1["digest"])
            if loaded is not None or "fingerprint" not in str(reason):
                failures.append(f"poisoned fingerprint loaded anyway: "
                                f"{reason}")
            _, info4 = aot.load_or_compile(fn, structs, "self_test",
                                           cache=cache)
            if info4["source"] != "xla" or \
                    "fingerprint" not in str(info4.get("miss_reason")):
                failures.append(f"poisoned entry did not fall back to "
                                f"compile: {info4}")

            # 4. verify/evict: the (re-published) entries are valid;
            # re-poison one and --stale eviction must remove ONLY it
            poison(info1["digest"])
            ok, stale = cache.verify()
            if stale != [info1["digest"]] or len(ok) != 1:
                failures.append(f"verify misclassified: ok={ok} "
                                f"stale={stale}")
            if cache.evict(stale_only=True) != 1 or \
                    cache.stats()["entries"] != 1:
                failures.append("stale eviction removed the wrong "
                                f"entries: {cache.stats()}")
            rows = cache.entries()
            if not (len(rows) == 1 and rows[0]["kind"] == "self_test"
                    and rows[0]["bytes"] > 0):
                failures.append(f"entries() listing wrong: {rows}")

            # 5. Executor-level hydrate: a FRESH Executor over the same
            # program must fill its entry from disk — zero XLA compile
            # — with bitwise-identical fetches, and the hydrated
            # entry's donation must still pass the perf gate
            import paddle_tpu as pt
            import paddle_tpu.nn.functional as F
            from paddle_tpu import optim

            aot.configure(os.path.join(d, "exec_cache"))
            try:
                rng = np.random.RandomState(0)
                bx = rng.randn(8, 4).astype("float32")
                by = rng.randn(8, 1).astype("float32")

                def run3():
                    # a FULL fresh build per run — new Program, newly
                    # initialized params, new Executor — exactly what a
                    # second process does; only the content key links
                    # the two builds to one disk entry
                    pt.seed(0)
                    pt.enable_static()
                    try:
                        main_p = pt.static.Program()
                        startup = pt.static.Program()
                        with pt.program_guard(main_p, startup):
                            xv = pt.static.data("x", [8, 4], "float32")
                            yv = pt.static.data("y", [8, 1], "float32")
                            out = pt.static.nn.fc(xv, 4)
                            loss = F.mse_loss(out, yv)
                            optim.SGD(0.1).minimize(loss)
                    finally:
                        pt.disable_static()
                    exe = pt.static.Executor()
                    exe.run(startup)
                    return [np.asarray(exe.run(main_p,
                                               feed={"x": bx, "y": by},
                                               fetch_list=[loss])[0])
                            for _ in range(3)], \
                        next(iter(exe._cache.values()))

                la, ea = run3()
                if (ea.aot_info or {}).get("source") != "xla":
                    failures.append(f"first executor compile not "
                                    f"published: {ea.aot_info}")
                lb, eb = run3()
                if (eb.aot_info or {}).get("source") != "aot_disk":
                    failures.append(f"fresh executor did not hydrate: "
                                    f"{eb.aot_info}")
                if not all(np.array_equal(p, q)
                           for p, q in zip(la, lb)):
                    failures.append("hydrated executor loss trajectory "
                                    "differs bitwise")
                import importlib.util

                spec = importlib.util.spec_from_file_location(
                    "aot_perf_gate", os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "perf_gate.py"))
                pg = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(pg)
                hlo = pg.entry_hlo(eb)
                don = pg.donation_stats(hlo) if hlo else None
                if not don or don["count"] < 1:
                    failures.append(f"hydrated entry lost donation "
                                    f"through perf_gate: {don}")
            finally:
                aot.configure(None)
    finally:
        if env_before is not None:
            os.environ[aot.ENV_DIR] = env_before

    if failures:
        print("SELF-TEST FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print("self-test passed: serialize/deserialize round-trip is "
          "bitwise with donation intact, content-key drift misses "
          "cleanly, a poisoned-fingerprint envelope refuses to load "
          "and falls back to a fresh compile, verify/evict classify "
          "stale entries exactly, and a fresh Executor hydrates the "
          "same program from disk with a bitwise-identical trajectory "
          "and a perf-gate-verified donated carry")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", nargs="?", help="cache directory")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--evict", action="store_true")
    ap.add_argument("--stale", action="store_true",
                    help="with --evict: only fingerprint-stale entries")
    ap.add_argument("--older-than", type=float, default=None,
                    metavar="S", help="with --evict: only entries older "
                    "than S seconds")
    ap.add_argument("--all", action="store_true",
                    help="with --evict: everything")
    ap.add_argument("--warm", metavar="PREFIX", default=None,
                    help="compile+publish executables for a saved "
                    "inference model prefix")
    ap.add_argument("--buckets", default="1",
                    help="comma-separated batch buckets for --warm")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)

    if args.self_test:
        _ensure_fake_devices()
        return self_test()
    if not args.dir:
        ap.error("cache directory required (or --self-test)")
    from paddle_tpu.runtime.aot import AOTCache

    cache = AOTCache(args.dir)
    if args.evict:
        if not (args.stale or args.all or args.older_than is not None):
            ap.error("--evict needs --stale, --older-than S, or --all")
        n = cache.evict(older_than_s=args.older_than,
                        stale_only=args.stale)
        print(f"evicted {n} entries")
        return 0
    if args.warm is not None:
        _ensure_fake_devices()
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
        print(warm(cache, args.warm, buckets))
        return 0
    if args.verify:
        print(verify(cache, as_json=args.json))
        return 0
    print(list_entries(cache, as_json=args.json))
    return 0


if __name__ == "__main__":
    sys.exit(main())
