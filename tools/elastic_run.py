#!/usr/bin/env python
"""elastic_run: drive a supervised CPU gang through kill/hang/preempt
faults and prove it resumes from the newest intact checkpoint.

The operational front door for ``paddle_tpu.resilience.elastic`` (the
gang-level counterpart of tools/chaos_run.py): it launches a real
2-worker training gang under :class:`GangSupervisor`, injects — in ONE
run — a hard ``worker_kill``, a silent ``worker_hang`` (only the
heartbeat watchdog can catch it) and a ``preempt_signal`` (graceful
checkpoint-and-exit via ``resilience.graceful_shutdown``), and asserts
the surviving run's loss trajectory is BITWISE identical to an
unfaulted reference run: elasticity must not change the math.

The worker (``--worker``) is a plain static-path training loop — fc +
SGD on deterministic per-step batches — that beats its heartbeat from
the loop body, checkpoints every step with
``save_checkpoint(async_=True)`` (rank 0), resumes itself via
``load_checkpoint``'s newest-intact fallback, and honors preemption
notices at step boundaries. Faults fire at exact global steps
(``at_step``), and a per-step gang barrier (done-markers + the
published checkpoint) guarantees each fault's resume point is at/after
its step, so one inherited ``PADDLE_TPU_CHAOS`` spec fires each fault
exactly once per drill.

Usage:
    python tools/elastic_run.py                  # the 3-fault drill
    python tools/elastic_run.py --steps 16 --kill-at 4 ...
    python tools/elastic_run.py --budget-drill   # budget exhaustion
    python tools/elastic_run.py --self-test      # both, asserted

``--self-test`` is wired into tier-1 via tests/test_tooling.py; the
per-injector scenarios in tools/chaos_run.py --self-test reuse one
cached drill result via :func:`drill_result`.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import warnings

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

THIS_FILE = os.path.abspath(__file__)


def _load_sibling(name):
    """Load a sibling tool (tools/ is not a package) the way
    tests/test_tooling.py does — an importlib spec, not sys.path
    games."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(THIS_FILE), f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- the worker ---------------------------------------------------------------


def _batch(step, batch=8, dim=4):
    """Deterministic per-step batch: re-executing a step after a resume
    reproduces the exact bytes the first execution saw."""
    import numpy as np

    rng = np.random.RandomState(1000 + int(step))
    return (rng.randn(batch, dim).astype(np.float32),
            rng.randn(batch, 1).astype(np.float32))


def worker_main(args):
    """One gang member: static-path train loop with heartbeats, async
    per-step checkpoints (rank 0), chaos step hooks and graceful
    preemption. Resumes itself from the newest intact checkpoint."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid
    from paddle_tpu import resilience
    from paddle_tpu.framework import io as fio

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    shutdown = resilience.graceful_shutdown()
    hb = resilience.Heartbeat.from_env()
    out_path = os.path.join(args.out_dir, f"losses_rank{rank}.jsonl")

    pt.enable_static()
    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[8, 4])
        y = fluid.data(name="y", shape=[8, 1])
        out = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    adapter = resilience.ProgramStateAdapter(prog)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        start = fio.load_checkpoint(args.ckpt_dir, model=adapter) or 0

    def graceful_exit():
        # the boundary checkpoint is the per-step async save: make it
        # durable, then exit the code the supervisor relaunches
        # budget-free
        fio.wait_checkpoints()
        shutdown.exit_preempted()

    def barrier(step):
        """Gang lockstep: every rank's done-marker for ``step`` plus the
        published ``ckpt_<step>``. A fault fired below therefore always
        resumes at/after its own step, so ``at_step`` specs inherited
        across restarts fire exactly once per drill."""
        want = [os.path.join(args.sync_dir, f"done_{r}_{step}")
                for r in range(nranks)]
        want.append(os.path.join(args.ckpt_dir, f"ckpt_{step}"))
        deadline = time.monotonic() + args.barrier_timeout
        while not all(os.path.exists(p) for p in want):
            if shutdown.requested:
                graceful_exit()
            if time.monotonic() > deadline:
                print(f"rank {rank}: barrier timeout at step {step}",
                      file=sys.stderr)
                sys.exit(3)
            time.sleep(0.005)

    from paddle_tpu.obs import journal as _journal

    for step in range(start + 1, args.steps + 1):
        hb.beat(step)
        if shutdown.requested:
            graceful_exit()
        if _journal.ACTIVE is not None:
            # per-rank flight record (the supervisor hands each worker
            # PADDLE_TPU_RUN_DIR=<run>/rank_NN): number this record by
            # the TRAINER's global step, so a resumed incarnation
            # continues at its checkpoint step and obs.fleet aligns
            # records across ranks and attempts
            _journal.ACTIVE.sync_step(step)
        xb, yb = _batch(step)
        lv = float(np.asarray(
            exe.run(prog, feed={"x": xb, "y": yb},
                    fetch_list=[loss])[0]))
        if rank == 0:
            fio.save_checkpoint(args.ckpt_dir, step, model=adapter,
                                async_=True)
        if _journal.ACTIVE is not None:
            # make the record durable at the step boundary: a
            # worker_kill (os._exit, no atexit) must not cost this
            # step's line — the fleet aggregate's stall/skew
            # attribution reads exactly these lines
            _journal.ACTIVE.flush()
        with open(out_path, "a", encoding="utf-8") as f:
            f.write(json.dumps({"step": step, "loss": lv,
                                "hex": float(lv).hex()}) + "\n")
        open(os.path.join(args.sync_dir, f"done_{rank}_{step}"),
             "w").close()
        barrier(step)
        resilience.fire_step_chaos(step=step, rank=rank)
    fio.wait_checkpoints()
    return 0


# -- the drill ----------------------------------------------------------------


def _final_losses(out_path):
    """step -> loss hex, LAST occurrence winning: steps re-executed
    after a resume overwrite their first recording."""
    out = {}
    with open(out_path, encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            out[rec["step"]] = rec["hex"]
    return out


def _worker_cmd(steps, ckpt_dir, sync_dir, out_dir, barrier_timeout=60.0):
    return [sys.executable, THIS_FILE, "--worker",
            "--steps", str(steps), "--ckpt-dir", ckpt_dir,
            "--sync-dir", sync_dir, "--out-dir", out_dir,
            "--barrier-timeout", str(barrier_timeout)]


_WORKER_ENV = {
    # fresh worker processes must not grab a TPU, or inherit a chaos
    # spec meant for someone else; their journals skip the background
    # entry-analysis compile — that CPU contention can push a loaded
    # worker's step past the hang watchdog (the drill asserts records,
    # not FLOPs attribution)
    "JAX_PLATFORMS": "cpu",
    "PADDLE_TPU_CHAOS": "",
    "PADDLE_TPU_JOURNAL_FLOPS": "0",
    # lockdep in raise mode: a lock-order cycle in any gang worker
    # (journal, prefetcher, async checkpoint barrier — the paths this
    # drill hammers) crashes that worker and fails the drill's
    # trajectory-identity gate with a PTC004 in its journal
    "PADDLE_TPU_LOCKDEP": "1",
}


def _run_reference(root, steps):
    """Unfaulted single-worker run: the trajectory oracle."""
    import subprocess

    dirs = {n: os.path.join(root, f"ref_{n}") for n in
            ("ckpt", "sync", "out")}
    for d in dirs.values():
        os.makedirs(d)
    env = dict(os.environ)
    env.update(_WORKER_ENV)
    # the un-supervised oracle must not journal into any inherited run
    # dir (the drill's supervised gang writes per-rank subdirs instead)
    env.update({"PADDLE_TRAINER_ID": "0", "PADDLE_TRAINERS_NUM": "1",
                "PADDLE_TPU_RUN_DIR": "", "PADDLE_TPU_RANK": ""})
    r = subprocess.run(
        _worker_cmd(steps, dirs["ckpt"], dirs["sync"], dirs["out"]),
        env=env, capture_output=True, text=True)
    if r.returncode != 0:
        raise AssertionError(
            f"unfaulted reference worker failed ({r.returncode}):\n"
            f"{r.stdout}\n{r.stderr}")
    return _final_losses(os.path.join(dirs["out"], "losses_rank0.jsonl"))


def run_drill(steps=12, kill_at=3, hang_at=6, preempt_at=9,
              keep_root=False, verbose=False):
    """The acceptance drill. Returns a result dict (also cached by
    :func:`drill_result` for chaos_run's per-injector scenarios):

    - a 2-worker gang survives, in ONE supervised run, ``worker_kill``
      (rank 1, exit 9), ``worker_hang`` (rank 1; the watchdog fires) and
      ``preempt_signal`` (rank 0; graceful checkpoint-and-exit 75);
    - each relaunch resumes from the newest intact checkpoint;
    - the final per-step loss trajectory is BITWISE identical to an
      unfaulted reference run;
    - restarts/preemptions/watchdog kills/resume latency land in
      ``resilience.*`` counters and ``elastic.*`` journal events
      (supervisor journal at ``<run>/supervisor``);
    - EVERY rank journals its own flight record into
      ``<run>/rank_NN`` (per-attempt run_start headers, step records
      covering the whole trajectory) — the PR-8 worker-journal
      suppression is gone, multi-writer torn lines are impossible by
      construction.
    """
    from paddle_tpu.obs import fleet as _fleet
    from paddle_tpu.obs import metrics as _metrics
    from paddle_tpu.resilience import GangSupervisor

    assert 1 <= kill_at < hang_at < preempt_at < steps
    root = tempfile.mkdtemp(prefix="pt_elastic_drill_")
    reference = _run_reference(root, steps)

    dirs = {n: os.path.join(root, n)
            for n in ("ckpt", "sync", "out", "logs", "hb", "journal")}
    for d in dirs.values():
        os.makedirs(d)
    chaos = (f"worker_kill:at_step={kill_at},rank=1,code=9;"
             f"worker_hang:at_step={hang_at},rank=1;"
             f"preempt_signal:at_step={preempt_at},rank=0")
    env = dict(_WORKER_ENV)
    env["PADDLE_TPU_CHAOS"] = chaos
    # span tracing on: each rank's journal close exports a per-rank
    # Chrome trace next to its journal — fleet_report's self-test
    # merges them into the pid=rank fleet view off this same drill
    env["PADDLE_TPU_TRACE"] = "1"
    sup = GangSupervisor(
        _worker_cmd(steps, dirs["ckpt"], dirs["sync"], dirs["out"]),
        nprocs=2, env=env, heartbeat_dir=dirs["hb"],
        log_dir=dirs["logs"], ckpt_dir=dirs["ckpt"],
        run_dir=dirs["journal"],
        # 10s watchdog: a worker's beat gap is max(gang step time) —
        # on a small CI box two workers' first-step XLA compiles
        # serialize to ~5s, and a spurious mid-compile "hang" inserts
        # a whole extra attempt into the drill trace. The real
        # worker_hang fires in steady state, so the only cost of the
        # margin is a longer (deterministic) detection wait
        max_restarts=3, hang_timeout_s=10.0, term_grace_s=1.0,
        poll_interval_s=0.02, backoff_s=0.05, max_backoff_s=0.1, seed=0)
    before = {k: _metrics.counter(k).value
              for k in ("resilience.restarts", "resilience.preemptions",
                        "resilience.watchdog_kills")}
    t0 = time.monotonic()
    rc = sup.run()
    wall_s = time.monotonic() - t0

    faulted = _final_losses(os.path.join(dirs["out"],
                                         "losses_rank0.jsonl"))
    kinds = [a["kind"] for a in sup.state["attempts"]]
    counters = {k: _metrics.counter(k).value - before[k]
                for k in before}
    result = {
        "rc": rc, "state": sup.state, "attempt_kinds": kinds,
        "reference": reference, "faulted": faulted,
        "bitwise_match": faulted == reference,
        "counter_deltas": counters,
        "journal_dir": dirs["journal"],
        "supervisor_dir": os.path.join(dirs["journal"],
                                       _fleet.SUPERVISOR_DIR),
        "root": root, "wall_s": wall_s,
    }
    failures = []
    if rc != 0:
        failures.append(f"gang did not complete: rc={rc}")
    if kinds != ["crash", "hang", "preempt", "ok"]:
        failures.append(f"attempt outcomes {kinds} != "
                        "['crash', 'hang', 'preempt', 'ok']")
    crash = sup.state["attempts"][0] if sup.state["attempts"] else {}
    if kinds[:1] == ["crash"] and (crash.get("rank"), crash.get("code")) \
            != (1, 9):
        failures.append(f"worker_kill crash not attributed: {crash}")
    if sup.state["restarts"] != 2:
        failures.append(f"restarts {sup.state['restarts']} != 2 "
                        "(kill + hang; preemption must be budget-free)")
    if sup.state["preemptions"] != 1:
        failures.append(f"preemptions {sup.state['preemptions']} != 1")
    if sup.state["watchdog_kills"] != 1:
        failures.append(
            f"watchdog_kills {sup.state['watchdog_kills']} != 1")
    if set(faulted) != set(range(1, steps + 1)):
        failures.append(f"faulted run covered steps {sorted(faulted)}, "
                        f"want 1..{steps}")
    if faulted != reference:
        bad = [s for s in reference
               if faulted.get(s) != reference[s]][:4]
        failures.append(
            "loss trajectory diverged from the unfaulted reference at "
            f"steps {bad}: elasticity changed the math")
    for name, want in (("resilience.restarts", 2),
                       ("resilience.preemptions", 1),
                       ("resilience.watchdog_kills", 1)):
        if counters[name] != want:
            failures.append(f"{name} delta {counters[name]} != {want}")
    # fleet contract: EVERY attempt's ranks journaled parseable
    # per-rank flight records (no more PR-8 suppression), the union of
    # their step records covers the whole trajectory, and the
    # supervisor's elastic.* events landed in <run>/supervisor
    try:
        n_attempts = len(sup.state["attempts"])
        ranks = _fleet.rank_dirs(dirs["journal"])
        if sorted(ranks) != [0, 1]:
            failures.append(
                f"per-rank journals missing: found ranks "
                f"{sorted(ranks)} under {dirs['journal']}")
        covered = set()
        for r, p in sorted(ranks.items()):
            run = _fleet.load_journal(p)
            if run["parse_errors"]:
                failures.append(f"rank {r} journal has parse errors: "
                                f"{run['parse_errors'][:2]}")
            if len(run["run_starts"]) != n_attempts:
                failures.append(
                    f"rank {r} journaled {len(run['run_starts'])} "
                    f"incarnations != {n_attempts} attempts")
            hdr = run["header"] or {}
            if hdr.get("rank") != r:
                failures.append(f"rank {r} header carries rank "
                                f"{hdr.get('rank')}")
            covered |= {s["step"] for s in run["steps"]
                        if isinstance(s.get("step"), int)}
        if ranks and covered != set(range(1, steps + 1)):
            failures.append(
                f"rank journals cover steps {sorted(covered)}, want "
                f"1..{steps}")
        sup_run = _fleet.load_journal(result["supervisor_dir"])
        es = _fleet.elastic_summary(sup_run)
        if not es or es.get("restarts") != 2 or \
                es.get("watchdog_kills") != 1:
            failures.append(f"supervisor journal lost the elastic "
                            f"story: {es}")
    except Exception as e:
        failures.append(f"per-rank journal check failed: "
                        f"{type(e).__name__}: {e}")
    result["failures"] = failures
    if verbose:
        for a in sup.state["attempts"]:
            print(f"  attempt: {a}")
        print(f"  counters: {counters}  wall: {wall_s:.1f}s")
    if not keep_root and not failures:
        import shutil

        shutil.rmtree(root, ignore_errors=True)
        result["root"] = None
    return result


_DRILL_CACHE = None


def drill_result():
    """Run :func:`run_drill` once per PROCESS and cache the result —
    chaos_run's worker_kill/worker_hang/preempt_signal scenarios, this
    tool's own self-test, and fleet_report's per-rank/merged-trace
    checks each assert their own facet of the SAME drill. The cache
    lives on the (shared) ``paddle_tpu.resilience.elastic`` module,
    not here: test_tooling imports every tool as its own module
    instance, and a per-instance global would re-run the whole
    multi-process drill once per consumer. The kept scratch root is
    removed at interpreter exit."""
    global _DRILL_CACHE
    if _DRILL_CACHE is None:
        import paddle_tpu.resilience.elastic as _elastic

        shared = getattr(_elastic, "_ELASTIC_RUN_DRILL_CACHE", None)
        if shared is None:
            shared = run_drill(keep_root=True)
            _elastic._ELASTIC_RUN_DRILL_CACHE = shared
            if shared.get("root"):
                import atexit
                import shutil

                atexit.register(shutil.rmtree, shared["root"],
                                ignore_errors=True)
        _DRILL_CACHE = shared
    return _DRILL_CACHE


def run_budget_drill():
    """Restart-budget exhaustion must surface a CLEAN error carrying the
    attempt history — not a hang, not a stack of orphans."""
    from paddle_tpu.resilience import ElasticBudgetError, GangSupervisor

    sup = GangSupervisor(
        [sys.executable, "-c", "import sys; sys.exit(1)"],
        nprocs=1, max_restarts=1, poll_interval_s=0.01,
        backoff_s=0.0, jitter=0.0, term_grace_s=0.5)
    try:
        sup.run()
    except ElasticBudgetError as e:
        assert len(e.history) == 2, e.history
        assert all(a["kind"] == "crash" and a["code"] == 1
                   for a in e.history), e.history
        assert sup.state["exit_code"] == 1, sup.state
        return f"budget exhausted cleanly after {len(e.history)} attempts"
    raise AssertionError("budget exhaustion did not raise "
                         "ElasticBudgetError")


def self_test():
    failures = []
    try:
        msg = run_budget_drill()
        print(f"  budget_drill   ok — {msg}")
    except Exception as e:
        print(f"  budget_drill   FAILED — {type(e).__name__}: {e}")
        failures.append("budget_drill")

    res = drill_result()  # shared with chaos_run / fleet_report
    if res["failures"]:
        for f in res["failures"]:
            print(f"  drill          FAILED — {f}")
        failures.append("drill")
    else:
        print(f"  drill          ok — kill+hang+preempt survived, "
              f"{len(res['reference'])} steps bitwise vs reference, "
              f"per-rank journals parseable, {res['wall_s']:.1f}s")

    # the supervisor's flight record must tell the elasticity story:
    # run_report's elastic summary is how goodput loss gets attributed
    # (the supervisor journals into <run>/supervisor since the per-rank
    # journal split — workers own the rank_NN subdirs)
    rr = _load_sibling("run_report")
    es = rr.elastic_summary(rr.load_run(res["supervisor_dir"]))
    for key, want in (("restarts", 2), ("preemptions", 1),
                      ("watchdog_kills", 1)):
        if not es or es.get(key) != want:
            print(f"  journal        FAILED — elastic summary {key} "
                  f"{es and es.get(key)} != {want} ({es})")
            failures.append("journal")
            break
    else:
        if not es.get("resume_ms_p50"):
            print(f"  journal        FAILED — no resume latency "
                  f"samples in {es}")
            failures.append("journal")
        else:
            print(f"  journal        ok — {es}")
    # the drill root is SHARED (fleet_report's self-test reads the
    # same rank journals/traces later in one pytest process): cleanup
    # belongs to drill_result's atexit hook, not here
    if failures:
        print(f"self-test FAILED: {failures}")
        return 1
    print("self-test passed: the gang survives kill/hang/preemption with "
          "a bitwise-identical trajectory, and budget exhaustion is a "
          "clean error")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true",
                    help="run as a gang worker (internal)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--sync-dir", default=None)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--barrier-timeout", type=float, default=60.0)
    ap.add_argument("--kill-at", type=int, default=3)
    ap.add_argument("--hang-at", type=int, default=6)
    ap.add_argument("--preempt-at", type=int, default=9)
    ap.add_argument("--budget-drill", action="store_true",
                    help="only the restart-budget exhaustion drill")
    ap.add_argument("--keep", action="store_true",
                    help="keep the drill's scratch directory")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.worker:
        for req in ("ckpt_dir", "sync_dir", "out_dir"):
            if getattr(args, req) is None:
                ap.error(f"--worker requires --{req.replace('_', '-')}")
        return worker_main(args)
    if args.self_test:
        return self_test()
    if args.budget_drill:
        print(run_budget_drill())
        return 0
    res = run_drill(steps=args.steps, kill_at=args.kill_at,
                    hang_at=args.hang_at, preempt_at=args.preempt_at,
                    keep_root=args.keep, verbose=True)
    for f in res["failures"]:
        print(f"FAILED: {f}")
    if not res["failures"]:
        print(f"drill passed: {res['attempt_kinds']} -> bitwise-identical "
              f"trajectory over {len(res['reference'])} steps")
    return 1 if res["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
