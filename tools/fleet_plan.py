#!/usr/bin/env python
"""fleet_plan: show, verify, and self-test auto-parallel sharding plans.

The operational front door for ``paddle_tpu.fleet`` — what the planner
chose for a mesh shape and WHY: every candidate layout with its
predicted collective wire bytes and score, the chosen plan's
per-variable PartitionSpecs, per-device memory estimates, and (with
``--verify``) the predicted-vs-HLO-measured bytes per candidate, so a
cost-model drift is visible before it mis-lays-out a real run.

Usage:
    python tools/fleet_plan.py --mesh 2x4 [--demo mlp|tp_heavy]
        [--verify] [--json]
    python tools/fleet_plan.py --self-test
        # hand-computed cost fixtures (exact predicted-byte equality on
        # a pinned layout) + a live 8-fake-device auto_parallel run
        # whose plan must match the executable's CollectiveProfile
        # within 10%, + the tp-heavy model preferring dp2 x model4 over
        # pure DP with the cost delta visible

Wired into tier-1 via tests/test_tooling.py (shard_report/perf_gate
pattern).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PLAN_MISMATCH_GATE = 0.10  # predicted vs HLO-measured wire bytes


def _ensure_fake_devices(n=8):
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    import jax

    return len(jax.devices())


def _fmt_bytes(n):
    from paddle_tpu.utils.stats import format_bytes

    return format_bytes(n)


def _table(rows, headers):
    rows = [[str(c) for c in r] for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


# -- demo programs -------------------------------------------------------------


def build_demo(name="mlp", batch=16):
    """A small static Program + startup for planning demos/tests.

    ``mlp``: activation-heavy 8 -> 36 -> 1 regression MLP (hidden 36
    blocks a model axis of 8, so 2x4 layouts stay interesting).
    ``tp_heavy``: parameter-heavy 64 -> 500 -> 500 -> 8 stack (500 % 4
    == 0 but 500 % 8 != 0: pure-TP over 8 is infeasible, and the big
    weights make pure-DP's gradient exchange the dominant cost — the
    layout question the planner exists to answer).
    """
    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        if name == "mlp":
            x = fluid.data(name="x", shape=[batch, 8])
            y = fluid.data(name="y", shape=[batch, 1])
            h = fluid.layers.fc(x, size=36, act="relu")
            out = fluid.layers.fc(h, size=1)
        elif name == "tp_heavy":
            x = fluid.data(name="x", shape=[batch, 64])
            y = fluid.data(name="y", shape=[batch, 8])
            h = fluid.layers.fc(x, size=500, act="relu")
            h = fluid.layers.fc(h, size=500, act="relu")
            out = fluid.layers.fc(h, size=8)
        else:
            raise ValueError(f"unknown demo {name!r}")
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, startup, loss


# -- rendering -----------------------------------------------------------------


def render_plan(plan, verified_candidates=None):
    lines = [
        f"mesh shape   {'x'.join(map(str, plan.mesh_shape))}  "
        f"roles={list(plan.roles)}  ->  axes={plan.axes}",
        f"predicted    wire={_fmt_bytes(plan.predicted_wire_bytes)}  "
        f"by_axis={{{', '.join(f'{k}={_fmt_bytes(v)}' for k, v in sorted((plan.predicted.get('by_axis') or {}).items()))}}}",
    ]
    if plan.measured is not None:
        mism = plan.mismatch
        lines.append(
            f"measured     wire={_fmt_bytes(plan.measured_wire_bytes)}  "
            f"counts={plan.measured.get('counts')}  "
            + (f"mismatch={mism:.1%}" if mism is not None else ""))
    rows = []
    vmap = {tuple(sorted(v["axes"].items())): v
            for v in (verified_candidates or [])}
    for c in plan.candidates:
        v = vmap.get(tuple(sorted((c.get("axes") or {}).items())))
        rows.append((
            c["axes"], "yes" if c["feasible"] else "no",
            _fmt_bytes(c.get("predicted_wire_bytes")),
            _fmt_bytes(v["measured_wire_bytes"]) if v else "-",
            (f"{v['mismatch']:.1%}" if v and v.get("mismatch") is not None
             else "-"),
            f"{c['score']:.3g}" if c["feasible"] else "-",
            _fmt_bytes(c.get("param_bytes_per_device")),
            _fmt_bytes(c.get("peak_bytes_per_device")),
            c.get("note", "")))
    lines.append(_table(rows, ("layout", "ok", "predicted", "measured",
                               "mismatch", "score", "params/dev",
                               "peak/dev", "note")))
    if plan.param_specs:
        lines.append("param specs  " + ", ".join(
            f"{k}={list(v)}" for k, v in sorted(plan.param_specs.items())))
    return "\n".join(lines)


def verify_candidates(program, mesh_shape, executor=None):
    """Plan + verify EVERY feasible candidate layout (one probe compile
    each): the predicted-vs-HLO-measured table ``--verify`` prints.
    Requires the startup program to have run."""
    from paddle_tpu import fleet

    base = fleet.plan_program(program, mesh_shape)
    out = []
    for cand in base.candidates:
        if not cand["feasible"]:
            continue
        plan = fleet.plan_program(program, mesh_shape,
                                  roles=tuple(cand["roles"]))
        fleet.verify_plan(plan, program, executor=executor)
        out.append({
            "axes": dict(plan.axes),
            "predicted_wire_bytes": plan.predicted_wire_bytes,
            "measured_wire_bytes": plan.measured_wire_bytes,
            "mismatch": plan.mismatch,
        })
    return base, out


# -- self-test -----------------------------------------------------------------


def self_test():
    n = _ensure_fake_devices(8)
    if n < 8:
        print(f"self-test FAILED: needs 8 fake devices, have {n}")
        return 1
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import fleet
    import paddle_tpu.fluid as fluid

    failures = []
    pt.enable_static()
    try:
        # -- mesh fixtures: canonicalization + validation ------------------
        if fleet.canonical_axes((2, 2, 2), ("data", "data", "model")) != \
                {"data": 4, "model": 2}:
            failures.append("canonical_axes did not merge same-role axes")
        if fleet.canonical_axes((1, 8), ("model", "data")) != {"data": 8}:
            failures.append("canonical_axes kept a size-1 axis")
        layouts = {tuple(sorted(a.items()))
                   for _r, a in fleet.candidate_assignments((2, 4))}
        want = {(("data", 8),), (("data", 2), ("model", 4)),
                (("data", 4), ("model", 2)), (("model", 8),)}
        if layouts != want:
            failures.append(f"candidate_assignments((2,4)) = {layouts}, "
                            f"want {want}")
        try:
            fleet.validate_mesh_shape((3, 3), n_devices=8)
            failures.append("validate_mesh_shape accepted 3x3 on 8 devices")
        except ValueError:
            pass

        # -- hand-computed cost fixture: MLP 8->36->1, batch 16, pinned
        # dp2 x model4. Megatron pair: W1 (8,36) column, W2 (36,1) row.
        # grads all-reduce over data (d=2, ring factor 2(d-1)/d = 1):
        #   (8*36/4 + 36/4 + 36/4 + 1) elems * 4 B = 364 B
        # row-site forward all-reduce over model (t=4, factor 1.5):
        #   (16/2 rows * 1 col) * 4 B * 1.5 = 48 B       -> total 412 B
        prog, startup, loss = build_demo("mlp")
        plan = fleet.plan_program(prog, (2, 4), roles=("data", "model"))
        if plan.predicted_wire_bytes != 412:
            failures.append(
                f"hand-computed fixture: predicted {plan.predicted_wire_bytes}"
                " != 412 B (grads 364 + row-site activation 48)")
        linears = [op for op in prog.global_block.ops
                   if op.type == "linear"]  # unique_name suffixes vary
        w1, w2 = linears[0].input_names[1], linears[1].input_names[1]
        if plan.param_specs.get(w1) != (None, "model") or \
                plan.param_specs.get(w2) != ("model", None):
            failures.append(f"Megatron pairing wrong: {plan.param_specs}")

        # -- live plan-vs-CollectiveProfile: compile through the real
        # Executor and demand <= 10% mismatch, then really train
        exe = fluid.Executor()
        exe.run(startup)
        cp = fleet.auto_parallel(prog, (2, 4), executor=exe)
        got = cp._plan
        if got.measured_wire_bytes is None:
            failures.append("verify_plan produced no measured profile")
        elif got.mismatch is None or got.mismatch > PLAN_MISMATCH_GATE:
            failures.append(
                f"predicted {got.predicted_wire_bytes} vs measured "
                f"{got.measured_wire_bytes} wire bytes: mismatch "
                f"{got.mismatch} > {PLAN_MISMATCH_GATE}")
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(3):
            xb = rng.randn(16, 8).astype(np.float32)
            yb = rng.randn(16, 1).astype(np.float32)
            (lv,) = exe.run(cp, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        if not np.isfinite(losses).all():
            failures.append(f"auto-parallel training produced {losses}")
        if not any(k.plan is not None for k in exe._cache):
            failures.append("no plan-keyed executor cache entry")

        # -- tp-heavy preference: big weights, small batch, hidden 500
        # (model8 infeasible) -> dp2 x model4 must beat pure DP, with
        # the cost delta visible in the candidate table
        prog2, startup2, _loss2 = build_demo("tp_heavy")
        plan2 = fleet.plan_program(prog2, (2, 4))
        if plan2.axes != {"data": 2, "model": 4}:
            failures.append(f"tp-heavy model planned {plan2.axes}, want "
                            "{'data': 2, 'model': 4}")
        by_axes = {tuple(sorted(c["axes"].items())): c
                   for c in plan2.candidates}
        dp = by_axes.get((("data", 8),))
        tp = by_axes.get((("data", 2), ("model", 4)))
        if not dp or not tp or not dp["feasible"]:
            failures.append("tp-heavy candidate table lost pure-DP")
        elif not (dp["predicted_wire_bytes"] >
                  2 * tp["predicted_wire_bytes"]):
            failures.append(
                f"cost delta not visible: pure-DP predicts "
                f"{dp['predicted_wire_bytes']} vs dp2xmodel4 "
                f"{tp['predicted_wire_bytes']}")
        m8 = by_axes.get((("model", 8),))
        if m8 and m8["feasible"]:
            failures.append("model8 should be infeasible at hidden 500")
        txt = render_plan(plan2)
        if "layout" not in txt or "predicted" not in txt \
                or "peak/dev" not in txt:
            failures.append("render_plan lost its table")

        # -- hbm_budget (PTA013): every candidate carries a per-device
        # peak; a budget below the cheapest layout rejects EVERYTHING
        # with PTA013-coded notes, and a budget between layouts prunes
        # only the over-budget ones
        peaks = [c["peak_bytes_per_device"] for c in plan2.candidates
                 if c["feasible"]]
        if not peaks or any(not p for p in peaks):
            failures.append("candidates lost peak_bytes_per_device: "
                            f"{plan2.candidates}")
        try:
            fleet.plan_program(prog2, (2, 4), hbm_budget=1)
            failures.append("hbm_budget=1 accepted a layout")
        except ValueError as e:
            if "PTA013" not in str(e):
                failures.append(f"budget rejection lost its PTA013 "
                                f"code: {e}")
        mid = sorted(peaks)[0] + 1  # only the cheapest layout fits
        plan3 = fleet.plan_program(prog2, (2, 4), hbm_budget=mid)
        over = [c for c in plan3.candidates
                if not c["feasible"] and "PTA013" in c.get("note", "")]
        if not over:
            failures.append(f"budget {mid} marked no candidate PTA013 "
                            f"over-budget: {plan3.candidates}")
        if plan3.peak_bytes_per_device is None or \
                plan3.peak_bytes_per_device > mid:
            failures.append("budgeted plan exceeds its own budget: "
                            f"{plan3.peak_bytes_per_device} > {mid}")
    finally:
        pt.disable_static()

    for line in failures:
        print(f"  FAILED — {line}")
    if failures:
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: mesh canonicalization/validation fixtures, "
          "hand-computed 412 B cost fixture (Megatron pairing + ring "
          "factors, exact), live 8-fake-device auto_parallel whose "
          "predicted wire bytes match the compiled HLO's "
          "CollectiveProfile within 10% (plan-keyed cache entry, "
          "finite losses), the tp-heavy model preferring "
          "dp2 x model4 over pure DP with a >2x visible cost delta, "
          "and hbm_budget rejecting over-budget layouts with PTA013 "
          "(all-infeasible raises, partial budgets prune)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mesh", default="2x4",
                    help="mesh shape, e.g. 2x4 or 2,2,2")
    ap.add_argument("--demo", default="mlp",
                    choices=("mlp", "tp_heavy"),
                    help="demo model to plan")
    ap.add_argument("--verify", action="store_true",
                    help="compile every feasible candidate and print "
                         "predicted vs HLO-measured bytes")
    ap.add_argument("--hbm-budget", type=float, default=None,
                    help="per-device HBM budget in bytes; over-budget "
                         "layouts are rejected with PTA013")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--self-test", action="store_true",
                    help="hand-computed fixtures + live 8-fake-device "
                         "plan-vs-CollectiveProfile check")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()

    _ensure_fake_devices(8)
    import paddle_tpu as pt
    from paddle_tpu import fleet
    import paddle_tpu.fluid as fluid

    pt.enable_static()
    try:
        prog, startup, _loss = build_demo(args.demo)
        verified = None
        if args.verify:
            exe = fluid.Executor()
            exe.run(startup)
            plan, verified = verify_candidates(prog, args.mesh,
                                               executor=exe)
            chosen = fleet.plan_program(prog, args.mesh,
                                        hbm_budget=args.hbm_budget)
            fleet.verify_plan(chosen, prog, executor=exe)
        else:
            chosen = fleet.plan_program(prog, args.mesh,
                                        hbm_budget=args.hbm_budget)
        if args.json:
            print(json.dumps(
                {"axes": chosen.axes, "roles": list(chosen.roles),
                 "predicted": chosen.predicted,
                 "measured": chosen.measured,
                 "mismatch": chosen.mismatch,
                 "param_specs": {k: list(v) for k, v in
                                 chosen.param_specs.items()},
                 "candidates": chosen.candidates,
                 "verified": verified},
                indent=1, default=str, sort_keys=True))
        else:
            print(render_plan(chosen, verified_candidates=verified))
    finally:
        pt.disable_static()
    return 0


if __name__ == "__main__":
    sys.exit(main())
