"""Real-TPU pallas kernel probe.

Run ON HARDWARE (no CPU env trick) after any kernel change:
    python tools/tpu_probe.py
Interpret-mode tests cannot catch Mosaic lowering rejections (the
(8, 128) min-tile rule) or VMEM overflows — only a compiled run can.
Keep the tunnel to ONE process at a time (see memory: axon-tunnel-ops).
"""
import sys
import os
import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from paddle_tpu.ops.pallas import flash_attention, fused_layer_norm, softmax_cross_entropy

print("backend:", jax.default_backend(), jax.devices())

def try_case(name, fn):
    try:
        out = fn()
        jax.block_until_ready(out)
        print(f"{name}: OK")
    except Exception as e:
        msg = str(e).split("\n")[0][:300]
        print(f"{name}: FAIL {type(e).__name__}: {msg}")

# layernorm fwd+bwd, bench-ish shape
x = jnp.asarray(np.random.randn(4096, 768), jnp.bfloat16)
g = jnp.ones((768,), jnp.bfloat16)
b = jnp.zeros((768,), jnp.bfloat16)
try_case("ln fwd", lambda: fused_layer_norm(x, g, b))
def ln_grad():
    f = lambda x, g, b: jnp.sum(fused_layer_norm(x, g, b).astype(jnp.float32))
    return jax.grad(f, argnums=(0, 1, 2))(x, g, b)
try_case("ln bwd", ln_grad)

# flash attention fwd+bwd, GPT bench shape (B=8,H=12,L=1024,D=64)
q = jnp.asarray(np.random.randn(2, 12, 1024, 64), jnp.bfloat16)
try_case("flash fwd", lambda: flash_attention(q, q, q, True))
def fa_grad():
    f = lambda q: jnp.sum(flash_attention(q, q, q, True).astype(jnp.float32))
    return jax.grad(f)(q)
try_case("flash bwd", fa_grad)

# softmax CE, LM-head shape
logits = jnp.asarray(np.random.randn(1024, 50304), jnp.bfloat16)
labels = jnp.asarray(np.random.randint(0, 50304, (1024,)), jnp.int32)
try_case("ce fwd", lambda: softmax_cross_entropy(logits, labels))
def ce_grad():
    f = lambda l: jnp.sum(softmax_cross_entropy(l, labels))
    return jax.grad(f)(logits)
try_case("ce bwd", ce_grad)
