"""Real-TPU pallas kernel probe.

Run ON HARDWARE (no CPU env trick) after any kernel change:
    python tools/tpu_probe.py
Interpret-mode tests cannot catch Mosaic lowering rejections (the
(8, 128) min-tile rule) or VMEM overflows — only a compiled run can.
Keep the tunnel to ONE process at a time (see memory: axon-tunnel-ops).

Each case reports compile/run status, NUMERICAL parity vs the dense XLA
reference (a kernel that compiles but computes garbage must fail here,
not in a training run), and wall time vs the dense path. Ends with one
JSON line (probe_summary) that tools/tpu_session.sh captures.
"""
import json
import sys
import os
import time
import traceback

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from paddle_tpu.ops.pallas import (flash_attention, fused_layer_norm,
                                   softmax_cross_entropy, auto_interpret)

# Defaults so the emitter is safe even if the watchdog fires before
# the backend comes up (INTERP is only knowable after backend init)
SUMMARY = {}
INTERP = None
SMALL = os.environ.get("PADDLE_TPU_PROBE_SMALL") == "1"


def _emit_summary_and_exit(code=0):
    ok = bool(SUMMARY) and all(v.get("ok") for v in SUMMARY.values())
    print("probe_summary " + json.dumps(
        {"all_ok": ok, "interpret_mode": INTERP, "small_shapes": SMALL,
         "cases": SUMMARY}), flush=True)
    os._exit(code)


# The tunnel can block FOREVER inside PJRT (no exception) — the same
# failure bench.py guards against. A hard timer guarantees the summary
# line prints even mid-C-call; SIGALRM covers interruptible hangs.
import signal
import threading

DEADLINE_S = int(os.environ.get("PADDLE_TPU_PROBE_DEADLINE", "1200"))
_hard = threading.Timer(DEADLINE_S + 60.0, lambda: (
    print("probe hard watchdog fired", flush=True),
    _emit_summary_and_exit(1)))
_hard.daemon = True
_hard.start()
try:
    signal.signal(signal.SIGALRM,
                  lambda *_: (_ for _ in ()).throw(
                      TimeoutError("probe deadline")))
    signal.alarm(DEADLINE_S)
except Exception:
    pass

_devbox = {}
_t = threading.Thread(
    target=lambda: _devbox.update(devs=jax.devices()), daemon=True)
_t.start()
_t.join(90)
if "devs" not in _devbox:
    print("jax.devices() blocked >90s (tunnel down?)", flush=True)
    _emit_summary_and_exit(1)
print("backend:", jax.default_backend(), _devbox["devs"])
# On hardware INTERP is False (the whole point: a compiled Mosaic run);
# off-TPU it interprets so the probe harness itself stays testable.
INTERP = auto_interpret()
if INTERP:
    print("WARNING: non-TPU backend — kernels run in INTERPRET mode; "
          "this run does NOT validate Mosaic lowering")
# PADDLE_TPU_PROBE_SMALL=1 (set above) shrinks shapes so the harness
# logic can be smoke-run off-TPU (interpret mode at bench shapes takes
# hours on CPU); hardware runs use the full bench-like shapes.
ROWS, DMODEL = (256, 256) if SMALL else (4096, 768)
FB, FH, FL, FD = (1, 2, 256, 64) if SMALL else (2, 12, 1024, 64)
CE_ROWS, VOCAB = (64, 2048) if SMALL else (1024, 50304)


def _timed(fn, iters=5):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return out, (time.perf_counter() - t0) / iters


def try_case(name, fn, ref_fn=None, tol=0.03):
    """Compile+run fn; if ref_fn given, check numerical parity and
    report speedup of the kernel over the dense path.

    Parity is checked PER LEAF, relative to that leaf's own scale
    (max_abs_err <= tol * max|ref_leaf|): a dead/garbage gradient leaf
    (dx=0 next to large dgamma row-sums) must fail even when other
    leaves legitimately need a large absolute slack."""
    try:
        out, dt = _timed(fn)
        status = {"ok": True, "ms": round(dt * 1e3, 3)}
        if ref_fn is not None:
            ref, dt_ref = _timed(ref_fn)
            ref_l = jax.tree_util.tree_leaves(ref)
            out_l = jax.tree_util.tree_leaves(out)
            rel_errs = []
            for a, b in zip(out_l, ref_l):
                err = float(jnp.max(jnp.abs(
                    a.astype(jnp.float32) - b.astype(jnp.float32))))
                scale = max(float(jnp.max(jnp.abs(
                    b.astype(jnp.float32)))), 1e-6)
                rel_errs.append(err / scale)
            status["max_rel_err"] = round(max(rel_errs), 5)
            status["dense_ms"] = round(dt_ref * 1e3, 3)
            status["speedup"] = round(dt_ref / dt, 3) if dt else 0.0
            if max(rel_errs) > tol:
                status["ok"] = False
                status["why"] = ("numerical mismatch vs dense reference "
                                 f"(per-leaf rel errs {rel_errs})")
        print(f"{name}: {'OK' if status['ok'] else 'BAD'} {status}")
        SUMMARY[name] = status
    except Exception as e:
        # full diagnostics: Mosaic tiling errors carry the block shape
        # and op several lines deep — never truncate them
        print(f"{name}: FAIL {type(e).__name__}")
        traceback.print_exc()
        SUMMARY[name] = {"ok": False,
                         "error": f"{type(e).__name__}: {str(e)[:2000]}"}


def dense_attn(q, k, v, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (q.shape[-1] ** 0.5)
    if causal:
        Lq, Lk = q.shape[2], k.shape[2]
        mask = (jnp.arange(Lq)[:, None] + (Lk - Lq)) >= jnp.arange(Lk)[None]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def dense_ln(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * g.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x.dtype)


def dense_ce(logits, labels):
    lf = logits.astype(jnp.float32)
    return -jnp.take_along_axis(jax.nn.log_softmax(lf, -1),
                                labels[:, None], 1)[:, 0]


# layernorm fwd+bwd, bench-ish shape
x = jnp.asarray(np.random.randn(ROWS, DMODEL), jnp.bfloat16)
g = jnp.ones((DMODEL,), jnp.bfloat16)
b = jnp.zeros((DMODEL,), jnp.bfloat16)
try_case("ln fwd", lambda: fused_layer_norm(x, g, b, interpret=INTERP),
         lambda: dense_ln(x, g, b))
# weighted loss: a plain sum makes dy constant and true dx ~ 0
# (degenerate — any noise then reads as 100% relative error)
w_ln = jnp.asarray(np.random.randn(ROWS, DMODEL), jnp.float32)
try_case(
    "ln bwd",
    lambda: jax.grad(lambda x, g, b: jnp.sum(
        fused_layer_norm(x, g, b, interpret=INTERP).astype(jnp.float32)
        * w_ln), argnums=(0, 1, 2))(x, g, b),
    lambda: jax.grad(lambda x, g, b: jnp.sum(
        dense_ln(x, g, b).astype(jnp.float32) * w_ln),
        argnums=(0, 1, 2))(x, g, b),
    tol=0.05)  # bf16 row-sums: 5% of each leaf's own scale

# flash attention fwd+bwd, GPT bench shape
q = jnp.asarray(np.random.randn(FB, FH, FL, FD), jnp.bfloat16)
try_case("flash fwd", lambda: flash_attention(q, q, q, True, interpret=INTERP),
         lambda: dense_attn(q, q, q, True))
w_fa = jnp.asarray(np.random.randn(FB, FH, FL, FD), jnp.float32)
try_case(
    "flash bwd",
    lambda: jax.grad(lambda q: jnp.sum(
        flash_attention(q, q, q, True,
                        interpret=INTERP).astype(jnp.float32) * w_fa))(q),
    lambda: jax.grad(lambda q: jnp.sum(
        dense_attn(q, q, q, True).astype(jnp.float32) * w_fa))(q),
    tol=0.05)

# flash decode shape: 128 cached keys per new query block (Lq<Lk path)
qd = jnp.asarray(np.random.randn(FB, FH, 128, FD), jnp.bfloat16)
kd = jnp.asarray(np.random.randn(FB, FH, FL, FD), jnp.bfloat16)
try_case("flash fwd cached (Lq<Lk)",
         lambda: flash_attention(qd, kd, kd, True, interpret=INTERP),
         lambda: dense_attn(qd, kd, kd, True))

# softmax CE, LM-head shape (the VMEM-streaming case)
logits = jnp.asarray(np.random.randn(CE_ROWS, VOCAB), jnp.bfloat16)
labels = jnp.asarray(np.random.randint(0, VOCAB, (CE_ROWS,)), jnp.int32)
try_case("ce fwd", lambda: softmax_cross_entropy(logits, labels, interpret=INTERP),
         lambda: dense_ce(logits, labels))
try_case(
    "ce bwd",
    lambda: jax.grad(lambda l: jnp.sum(
        softmax_cross_entropy(l, labels, interpret=INTERP)))(logits),
    lambda: jax.grad(lambda l: jnp.sum(dense_ce(l, labels)))(logits),
    tol=0.05)

_hard.cancel()
try:
    signal.alarm(0)
except Exception:
    pass
_emit_summary_and_exit(0)
