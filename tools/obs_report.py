#!/usr/bin/env python
"""obs_report: dump, demo, and self-test paddle_tpu's telemetry.

The operational front door for ``paddle_tpu.obs`` (the role the
reference's profiler report plays): ``--demo`` drives a real train loop /
data pipeline / checkpoint cycle with tracing on and prints the metrics
table; ``--self-test`` exercises EVERY instrumented site — executor,
analysis passes, eager dispatch sampling, dataloader, resilience guards,
checkpoint IO, StepTimer — and fails if any site leaves its instruments
unregistered or untouched, so instrumentation cannot silently rot out of
a hot path.

Usage:
    python tools/obs_report.py                   # current-process metrics
    python tools/obs_report.py --demo            # run workload, report
    python tools/obs_report.py --demo --json
    python tools/obs_report.py --demo --trace-out /tmp/pt_trace.json
    python tools/obs_report.py --self-test       # every instrumented site

Wired into tier-1 via tests/test_tooling.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# every instrumented site's instruments, with the activity check the
# self-test holds them to after its workload: "count" = histogram with
# samples, "pos" = counter/gauge > 0, "reg" = registered is enough
# (gauges that may legitimately read 0 at quiesce)
REQUIRED = {
    "executor": [("executor.jit_cache.hits", "pos"),
                 ("executor.jit_cache.misses", "pos"),
                 ("executor.compile_ms", "count"),
                 ("executor.run_ms", "count"),
                 ("executor.fetch_ms", "count")],
    "analysis": [("analysis.pass.verifier.ms", "count"),
                 ("analysis.pass.lint.ms", "count")],
    "dispatch": [("dispatch.ops_total", "pos")],
    "dataloader": [("dataloader.producer_wait_ms", "count"),
                   ("dataloader.consumer_wait_ms", "count"),
                   ("dataloader.queue_depth", "reg"),
                   ("dataloader.worker_restarts", "pos")],
    "resilience": [("resilience.retries", "pos"),
                   ("resilience.steps", "pos"),
                   ("resilience.nonfinite", "pos"),
                   ("resilience.skipped", "pos")],
    "checkpoint": [("checkpoint.save_ms", "count"),
                   ("checkpoint.load_ms", "count"),
                   ("checkpoint.verify_ms", "count"),
                   ("checkpoint.saves", "pos"),
                   ("checkpoint.loads", "pos"),
                   ("checkpoint.fallbacks", "pos")],
    "step_timer": [("step_timer.step_ms", "count")],
}

# spans the demo/self-test trace must contain (the acceptance trace)
REQUIRED_SPANS = ("executor.compile", "executor.run", "dataloader.next")


def _static_loop(steps=3, feed_batches=None, guarded=False, policy_kw=None):
    """Build + run the canonical tiny static train loop."""
    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    pt.enable_static()
    try:
        pt.seed(0)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data(name="x", shape=[8, 4])
            y = fluid.data(name="y", shape=[8, 1])
            out = fluid.layers.fc(x, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(out, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        if guarded:
            from paddle_tpu.resilience import GuardedExecutor, RecoveryPolicy

            exe = GuardedExecutor(policy=RecoveryPolicy(
                sleep=lambda s: None, **(policy_kw or {})))
        else:
            exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        batches = feed_batches or [
            (rng.randn(8, 4).astype(np.float32),
             rng.randn(8, 1).astype(np.float32)) for _ in range(steps)]
        for bx, by in batches:
            exe.run(prog, feed={"x": bx, "y": by}, fetch_list=[loss])
        return exe
    finally:
        pt.disable_static()


def _drain_loader(num_workers=2, chaos_cfg=None):
    from paddle_tpu.io_.dataloader import DataLoader
    from paddle_tpu.io_.dataset import Dataset
    from paddle_tpu.resilience import inject

    class Sq(Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return np.float32(i * i)

    def drain():
        dl = DataLoader(Sq(), batch_size=4, num_workers=num_workers,
                        return_list=False)
        return [np.asarray(b) for b in dl]

    if chaos_cfg is None:
        return drain()
    with inject.chaos("loader_worker", **chaos_cfg):
        return drain()


def run_workload():
    """Touch every instrumented site once (the self-test/demo body)."""
    import warnings

    import paddle_tpu as pt
    from paddle_tpu import obs
    from paddle_tpu.framework.io import (load_checkpoint, save_checkpoint,
                                         verify_checkpoint)
    from paddle_tpu.resilience import inject
    from paddle_tpu.utils.profiler import StepTimer

    # executor + analysis: compile once, hit the jit cache twice
    _static_loop(steps=3)

    # resilience: two transient execute faults retried away, then a NaN
    # feed skipped under policy
    with inject.chaos("transient_execute", times=2):
        _static_loop(steps=3, guarded=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with inject.chaos("nan_feed", at=2, seed=7):
            _static_loop(steps=3, guarded=True,
                         policy_kw={"on_nonfinite": "skip_step"})

    # dispatch: eager ops under sampling; restore the operator's OWN
    # hook afterwards (a PADDLE_TPU_OBS_SAMPLE=N stride must survive
    # this workload, not be clobbered with stride 1)
    from paddle_tpu.core import dispatch as _dispatch

    prev_hook = _dispatch._op_metrics_hook
    obs.enable_op_sampling()
    try:
        a = pt.to_tensor(np.ones((4, 4), np.float32))
        pt.matmul(a, a)
        pt.add(a, a)
    finally:
        _dispatch.set_op_metrics_hook(prev_hook)
        obs._op_sampling = prev_hook is not None

    # dataloader: clean drain, then a worker crash absorbed by restart
    _drain_loader()
    _drain_loader(chaos_cfg={"at": 2})

    # checkpoint: save twice, verify, corrupt the newest, fall back
    import paddle_tpu.nn as nn

    with tempfile.TemporaryDirectory() as d:
        pt.seed(0)
        m = nn.Linear(4, 2)
        save_checkpoint(d, 1, model=m)
        m.weight._data = m.weight._data + 1.0
        save_checkpoint(d, 2, model=m)
        ok, problems = verify_checkpoint(os.path.join(d, "ckpt_2"))
        assert ok, problems
        with open(os.path.join(d, "ckpt_2", "model.pdparams"), "r+b") as f:
            f.truncate(8)  # torn write: manifest crc catches it
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("ignore", RuntimeWarning)
            step = load_checkpoint(d, model=nn.Linear(4, 2))
        assert step == 1, f"fallback loaded step {step}, wanted 1"

    # step timer
    t = StepTimer(skip_first=0)
    for _ in range(3):
        with t.step():
            pass
    assert t.summary()["steps"] == 3


def _check_required(snap):
    failures = []
    for site, instruments in REQUIRED.items():
        for name, kind in instruments:
            val = snap.get(name)
            if val is None:
                failures.append(f"{site}: instrument {name!r} never "
                                "registered (instrumentation removed?)")
            elif kind == "count" and not (isinstance(val, dict)
                                          and val.get("count", 0) > 0):
                failures.append(f"{site}: histogram {name!r} recorded no "
                                "samples")
            elif kind == "pos" and not (isinstance(val, (int, float))
                                        and val > 0):
                failures.append(f"{site}: {name!r} never ticked "
                                f"(value {val!r})")
    return failures


def self_test():
    from paddle_tpu import obs

    obs.metrics.reset()
    tracing_was_on = obs.tracing_enabled()
    obs.clear_trace()
    obs.enable_tracing()
    try:
        run_workload()
    finally:
        if not tracing_was_on:
            obs.disable_tracing()
    snap = obs.snapshot()
    failures = _check_required(snap)

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "trace.json")
        n = obs.export_chrome_trace(path)
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        names = {e["name"] for e in events}
        for want in REQUIRED_SPANS:
            if want not in names:
                failures.append(f"trace: no {want!r} span in the exported "
                                f"Chrome trace ({n} spans)")

    for line in sorted(failures):
        print(f"  FAILED — {line}")
    if failures:
        print(f"self-test FAILED: {len(failures)} instrumented-site "
              "check(s)")
        return 1
    total = len([i for site in REQUIRED.values() for i in site])
    print(f"self-test passed: {total} instruments across "
          f"{len(REQUIRED)} sites ticked; trace exported "
          f"{sorted(REQUIRED_SPANS)} spans")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--demo", action="store_true",
                    help="run a demo workload before reporting")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--trace-out", metavar="PATH",
                    help="export the span buffer as Chrome trace JSON")
    ap.add_argument("--self-test", action="store_true",
                    help="exercise every instrumented site and verify "
                         "its instruments tick")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()

    from paddle_tpu import obs

    if args.demo:
        obs.enable_tracing()
        run_workload()
    print(obs.report.render_json() if args.json else obs.report.render())
    if args.trace_out:
        n = obs.export_chrome_trace(args.trace_out)
        print(f"\nwrote {n} spans to {args.trace_out} "
              "(open in chrome://tracing or ui.perfetto.dev)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
