#!/usr/bin/env python
"""lint_concurrency: host-runtime lock-discipline gate.

CLI front door for ``paddle_tpu.analysis.concurrency`` — the static
half of the ``obs.lockdep`` runtime validator. Walks a Python source
tree, builds each module's lock-acquisition model, and reports:

- **PTC001** inconsistent lock-acquisition order (A->B on one path,
  B->A on another — the deadlock precondition)
- **PTC002** blocking calls under a held lock (``time.sleep``,
  ``Thread.join``, ``Popen.wait``/``communicate``, ``urlopen``,
  untimed ``queue.get`` — the PR-15 router-stall class)
- **PTC003** attributes written from both a spawned-thread target and
  a public method without a shared lock in scope (advisory)

Usage:
    python tools/lint_concurrency.py                  # lint paddle_tpu/
    python tools/lint_concurrency.py --path some/dir  # or one file
    python tools/lint_concurrency.py --json           # machine-readable
    python tools/lint_concurrency.py --self-test      # check the checker

Exit code: nonzero iff any UNWAIVED PTC001/PTC002 finding exists
(PTC003 prints but does not gate; a finding is waived by a
``# lockdep: waive`` or ``# noqa: PTC00x`` comment on its line).

``--self-test`` first runs hand-built fixtures through the lint — an
AB/BA deadlock pair, a blocking-under-lock body, an unguarded
cross-thread write, each of which MUST be caught, and a clean fixture
that MUST stay silent — then lints the real ``paddle_tpu`` tree with
the production gate. Wired into tier-1 via ``tests/test_tooling.py``,
so a future serving/fleet PR that regresses lock discipline fails CI
here, with the offending file:line in the output.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DEFAULT_PATH = os.path.join(ROOT, "paddle_tpu")


def _hint(code):
    from paddle_tpu.analysis.diagnostics import CONCURRENCY_CODES

    sev_hint = CONCURRENCY_CODES.get(code)
    return sev_hint[1] if sev_hint else ""


def _print_findings(findings, show_hints=True):
    for f in findings:
        print(f"  {f!r}")
        if show_hints and not f.waived:
            hint = _hint(f.code)
            if hint:
                print(f"      hint: {hint}")


def lint_path(path, as_json=False):
    from paddle_tpu.analysis import concurrency as C

    if os.path.isdir(path):
        findings = C.lint_tree(path)
    else:
        findings = C.lint_file(path)
    gating = C.gate_findings(findings)
    if as_json:
        print(json.dumps({
            "path": path,
            "findings": [f.as_dict() for f in findings],
            "gating": len(gating),
        }, indent=2))
    else:
        print(f"lint_concurrency: {path}")
        if findings:
            _print_findings(findings)
        waived = sum(1 for f in findings if f.waived)
        print(f"  {len(findings)} finding(s), {waived} waived, "
              f"{len(gating)} gating (unwaived PTC001/PTC002)")
    return 1 if gating else 0


# -- self-test fixtures ------------------------------------------------------

_FIXTURE_ABBA = '''
import threading

class Pool:
    def __init__(self):
        self._slots = threading.Lock()
        self._stats = threading.Lock()

    def grab(self):
        with self._slots:
            with self._stats:
                pass

    def report(self):
        with self._stats:
            with self._slots:
                pass
'''

_FIXTURE_BLOCKING = '''
import threading
import time

class Sup:
    def __init__(self):
        self._lock = threading.Lock()
        self.q = None
        self.worker = None

    def backoff(self):
        with self._lock:
            time.sleep(0.5)

    def drain(self):
        with self._lock:
            item = self.q.get()
        return item

    def reap(self):
        self._lock.acquire()
        self.worker.join()
        self._lock.release()
'''

_FIXTURE_UNGUARDED = '''
import threading

class Beacon:
    def __init__(self):
        self._lock = threading.Lock()
        self.last_seen = None
        self._t = threading.Thread(target=self._beat, daemon=True)

    def _beat(self):
        self.last_seen = 1.0

    def touch(self):
        self.last_seen = 2.0
'''

_FIXTURE_CLEAN = '''
import threading
import time

class Clean:
    """Consistent order, blocking outside critical sections, guarded
    shared writes, condition-wait on the held lock, str.join."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._cv = threading.Condition()
        self.count = 0
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._a:
            with self._b:
                self.count += 1

    def bump(self):
        with self._a:
            self.count += 1
        time.sleep(0.0)

    def wait_turn(self):
        with self._cv:
            self._cv.wait(1.0)

    def label(self, parts):
        with self._a:
            return ", ".join(parts)

    def reap(self, t):
        with self._a:
            pass
        t.join(timeout=5.0)
'''


def self_test():
    from paddle_tpu.analysis import concurrency as C

    failures = []

    def check(label, ok, detail=""):
        status = "ok" if ok else "FAIL"
        print(f"  [{status}] {label}" + (f" — {detail}" if detail
                                         and not ok else ""))
        if not ok:
            failures.append(label)

    print("lint_concurrency --self-test")

    fs = C.lint_source(_FIXTURE_ABBA, "fixture_abba.py")
    check("AB/BA inversion caught (PTC001)",
          any(f.code == "PTC001" for f in fs), repr(fs))
    check("AB/BA names both locks",
          any(set(f.locks) == {"Pool._slots", "Pool._stats"}
              for f in fs if f.code == "PTC001"), repr(fs))

    fs = C.lint_source(_FIXTURE_BLOCKING, "fixture_blocking.py")
    codes = [f.code for f in fs]
    check("sleep/untimed-get/join under lock all caught (PTC002 x3)",
          codes.count("PTC002") == 3, repr(fs))

    fs = C.lint_source(_FIXTURE_UNGUARDED, "fixture_unguarded.py")
    check("unguarded cross-thread write caught (PTC003)",
          any(f.code == "PTC003" for f in fs), repr(fs))
    check("PTC003 does not gate the exit code",
          not C.gate_findings(fs), repr(fs))

    fs = C.lint_source(_FIXTURE_CLEAN, "fixture_clean.py")
    check("clean fixture stays silent", not fs, repr(fs))

    waived_src = _FIXTURE_BLOCKING.replace(
        "time.sleep(0.5)", "time.sleep(0.5)  # lockdep: waive")
    fs = C.lint_source(waived_src, "fixture_waived.py")
    w = [f for f in fs if f.waived]
    check("waiver comment downgrades the finding",
          len(w) == 1 and len(C.gate_findings(fs)) == 2, repr(fs))

    # the production gate: the real tree must be clean
    tree = C.lint_tree(DEFAULT_PATH)
    gating = C.gate_findings(tree)
    check(f"paddle_tpu/ tree clean ({len(tree)} finding(s), "
          f"{len(gating)} gating)", not gating)
    if gating:
        _print_findings(gating)

    if failures:
        print(f"self-test FAILED: {len(failures)} check(s): {failures}")
        return 1
    print("self-test passed")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--path", default=DEFAULT_PATH,
                    help="file or directory to lint (default: paddle_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--self-test", action="store_true",
                    help="run the fixture checks + the full-tree gate")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    return lint_path(args.path, as_json=args.as_json)


if __name__ == "__main__":
    sys.exit(main())
