#!/usr/bin/env python
"""perf_gate: CPU-runnable performance gates over compiled HLO.

The real-TPU bench has been dark since r02, so perf claims need a
signal that runs in tier-1 CI: instead of timing (noisy, host-bound on
CPU), gate on the INVARIANTS that make the step fast and that XLA's own
compiled HLO proves —

- **donation**: how many input buffers the executable aliases to
  outputs (``input_output_alias``) — a donated persistable updates
  in-place in HBM; a regression here doubles parameter memory traffic.
- **op shape**: per-kind instruction counts from the optimized HLO
  (``fusion``, ``while``, ``dot``, collectives, ...) — a fused
  multi-step entry must contain exactly one ``while`` loop (the scan),
  not K unrolled bodies.
- **collective bytes**: per-step communication volume via
  ``obs.spmd.collective_profile`` — the PR-5 comm accounting, now
  assertable as a ceiling.
- **compiled-call counts**: executor compiles (jit-cache misses) and
  dispatches — the fused ``run_steps`` path must compile once and
  dispatch once per K-step window where the sequential path dispatches
  K times.

Usage:
    python tools/perf_gate.py --self-test   # canned-HLO fixtures with
        # hand-computed donation/fusion counts + a live 8-fake-device
        # scan-vs-loop compiled-call-count check
    python tools/perf_gate.py --entry-report   # live MLP demo: build,
        # run fused, print the invariant report

In-process (the way tests/test_perf_gates.py uses it):
    from tools.perf_gate import (entry_hlo, donation_stats, op_counts,
                                 check_entry, executor_call_counts)
    failures = check_entry(compiled, min_donated=2, max_while=1)

Wired into tier-1 via tests/test_tooling.py (lint/chaos/obs/run/shard
_report pattern).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ensure_fake_devices(n=8):
    """Standalone runs need the fake-device CPU platform configured
    BEFORE jax initializes; under pytest the conftest already did."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    import jax

    return len(jax.devices())


# -- HLO parsing --------------------------------------------------------------

# one alias entry inside the input_output_alias header attribute:
#   {1}: (1, {}, may-alias)   /   {0, 2}: (3, {0})
_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9,\s]*)\}:\s*\(([0-9]+),\s*\{[0-9,\s]*\}"
    r"(?:,\s*(may-alias|must-alias))?\)")

# one HLO instruction: "%name = TYPE opkind(" where TYPE is a shape or a
# tuple; group(2) is the op mnemonic (fusion, while, dot, all-reduce...)
_INSTR_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s*"
    r"([a-z][a-z0-9-]*)\(")

# a NAMED instruction inside a computation body — the schedule-order
# parse for overlap checks needs the %name to pair -start with -done
_NAMED_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"(\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s*"
    r"([a-z][a-z0-9-]*)\((.*)$")

# ops that represent real device compute for overlap purposes (an
# all-reduce separated from its -done only by bitcasts/copies hides
# nothing)
COMPUTE_OPS = frozenset(("fusion", "dot", "convolution", "reduce",
                         "while", "scatter", "sort"))

_SYNC_COLLECTIVES = frozenset(("all-reduce", "all-gather",
                               "reduce-scatter", "all-to-all",
                               "collective-permute"))


def _alias_attr(hlo_text):
    """The raw ``input_output_alias={...}`` attribute body of the entry
    module header, or None. Brace-balanced scan: the body nests braces
    ({output index} / {param path})."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return None
    i = start + len("input_output_alias={")
    depth = 1
    while i < len(hlo_text) and depth:
        if hlo_text[i] == "{":
            depth += 1
        elif hlo_text[i] == "}":
            depth -= 1
        i += 1
    return hlo_text[start + len("input_output_alias={"):i - 1]


def donation_stats(hlo_text):
    """Donated-buffer accounting from the module header's
    ``input_output_alias`` attribute: ``count`` aliased (donated)
    buffers and the ``aliases`` list of
    ``(output_index, param_number, kind)``. An executable that donates
    nothing returns count 0 (and that IS a meaningful gate failure for
    a training step: its parameter updates round-trip HBM)."""
    attr = _alias_attr(hlo_text)
    if attr is None:
        return {"count": 0, "aliases": []}
    aliases = [
        (tuple(int(x) for x in out.split(",") if x.strip()), int(param),
         kind or "must-alias")
        for out, param, kind in _ALIAS_ENTRY_RE.findall(attr)]
    return {"count": len(aliases), "aliases": aliases}


def op_counts(hlo_text, kinds=None):
    """Instruction counts per op mnemonic over the optimized HLO text
    (entry + nested computations). ``kinds`` filters to the named ops,
    reporting explicit zeros for absent ones — a gate asserting
    ``while == 1`` needs the 0, not a missing key."""
    counts = {}
    for m in _INSTR_RE.finditer(hlo_text):
        k = m.group(2)
        counts[k] = counts.get(k, 0) + 1
    if kinds is None:
        return counts
    return {k: counts.get(k, 0) for k in kinds}


def schedule_ops(hlo_text):
    """The ENTRY computation's instruction sequence as ordered
    ``(name, kind, args)`` tuples. Optimized HLO is emitted
    ``is_scheduled=true``, so textual order IS the execution schedule —
    the property the overlap gate reasons over. Falls back to the whole
    text when no ENTRY block is present (canned single-computation
    fixtures)."""
    lines = hlo_text.splitlines()
    start = next((i for i, ln in enumerate(lines)
                  if ln.lstrip().startswith("ENTRY ")), None)
    if start is not None:
        block = []
        for ln in lines[start + 1:]:
            if ln.strip() == "}":
                break
            block.append(ln)
        lines = block
    out = []
    for ln in lines:
        m = _NAMED_INSTR_RE.match(ln)
        if m is not None:
            out.append((m.group(1), m.group(3), m.group(4)))
    return out


def overlap_stats(hlo_text):
    """Comm/compute overlap structure of one scheduled HLO module —
    the CPU-runnable proof that a gradient exchange can hide behind
    compute (dist.gradcomm's reverse-topological bucket ordering):

    - ``async_pairs`` / ``async_overlapped``: ``<kind>-start`` /
      ``-done`` collective pairs, and how many have at least one real
      compute op (COMPUTE_OPS) scheduled BETWEEN start and done — the
      async backend's explicit overlap window. (XLA's CPU backend
      lowers collectives synchronously, so live CPU entries usually
      show 0 pairs; the canned fixtures pin the parse.)
    - ``interleaved``: collectives (sync or -start) with at least one
      compute op scheduled AFTER them — the overlap-enabling placement
      a sync schedule still proves: the exchange is not pushed to the
      tail where nothing could ever hide it.
    - ``collectives`` / ``compute_ops``: totals for context.
    """
    sched = schedule_ops(hlo_text)
    compute_at = [i for i, (_, kind, _) in enumerate(sched)
                  if kind in COMPUTE_OPS]
    colls = []   # (index, name, kind, is_start)
    for i, (name, kind, _) in enumerate(sched):
        if kind in _SYNC_COLLECTIVES:
            colls.append((i, name, kind, False))
        elif kind.endswith("-start") and \
                kind[:-6] in _SYNC_COLLECTIVES:
            colls.append((i, name, kind[:-6], True))
    pairs = overlapped = 0
    for i, name, kind, is_start in colls:
        if not is_start:
            continue
        # exact operand match: "%ar-start.1" must not bind to
        # "%ar-start.10"'s done
        name_re = re.compile("%" + re.escape(name) + r"(?![\w.\-])")
        done = next(
            (j for j, (_, k, args) in enumerate(sched[i + 1:], i + 1)
             if k == kind + "-done" and name_re.search(args)), None)
        if done is None:
            continue
        pairs += 1
        if any(i < c < done for c in compute_at):
            overlapped += 1
    last_compute = compute_at[-1] if compute_at else -1
    interleaved = sum(1 for i, _, _, _ in colls if i < last_compute)
    return {"collectives": len(colls), "compute_ops": len(compute_at),
            "async_pairs": pairs, "async_overlapped": overlapped,
            "interleaved": interleaved}


def entry_hlo(compiled):
    """Optimized HLO text of one Executor cache entry, lowered from the
    arg structs captured at build time. BLOCKING (pays one XLA compile)
    on first call per entry; cached on the entry thereafter. None when
    lowering fails."""
    cached = getattr(compiled, "_perf_gate_hlo", None)
    if cached is not None:
        return cached
    structs = getattr(compiled, "arg_structs", None)
    if structs is None:
        return None
    try:
        # an AOT-hydrated entry (paddle_tpu.runtime.aot) holds the
        # jax.stages.Compiled directly — its as_text() IS the hydrated
        # executable's HLO, which is exactly what the donation gate
        # must verify survived the serialize round-trip
        text = compiled.fn.as_text() \
            if not hasattr(compiled.fn, "lower") \
            else compiled.fn.lower(*structs).compile().as_text()
    except Exception:
        return None
    compiled._perf_gate_hlo = text
    return text


# -- gates --------------------------------------------------------------------


def check_hlo(hlo_text, *, min_donated=None, max_donated=None,
              min_fusion=None, max_while=None, min_while=None,
              max_collective_bytes=None, mesh=None,
              max_all_reduce=None, min_async_overlapped=None,
              min_interleaved=None):
    """Check one HLO module against invariant bounds; returns the list
    of failure strings (empty = gate passes). Only the bounds given are
    checked — a gate file states exactly what it pins."""
    failures = []
    don = donation_stats(hlo_text)["count"]
    ops = op_counts(hlo_text)
    if min_donated is not None and don < min_donated:
        failures.append(f"donated buffers {don} < required {min_donated}")
    if max_donated is not None and don > max_donated:
        failures.append(f"donated buffers {don} > allowed {max_donated}")
    if min_fusion is not None and ops.get("fusion", 0) < min_fusion:
        failures.append(
            f"fusion ops {ops.get('fusion', 0)} < required {min_fusion}")
    n_while = ops.get("while", 0)
    if max_while is not None and n_while > max_while:
        failures.append(f"while loops {n_while} > allowed {max_while} "
                        "(scan body unrolled or duplicated?)")
    if min_while is not None and n_while < min_while:
        failures.append(f"while loops {n_while} < required {min_while} "
                        "(fused path did not lower to a scan)")
    if max_all_reduce is not None:
        n_ar = ops.get("all-reduce", 0) + ops.get("all-reduce-start", 0)
        if n_ar > max_all_reduce:
            failures.append(
                f"all-reduce ops {n_ar} > allowed {max_all_reduce} "
                "(bucketing regressed to per-parameter exchanges?)")
    if min_async_overlapped is not None or min_interleaved is not None:
        ov = overlap_stats(hlo_text)
        if min_async_overlapped is not None and \
                ov["async_overlapped"] < min_async_overlapped:
            failures.append(
                f"async-overlapped collectives {ov['async_overlapped']} "
                f"< required {min_async_overlapped} "
                f"(pairs={ov['async_pairs']}: comm not hidden behind "
                "compute)")
        if min_interleaved is not None and \
                ov["interleaved"] < min_interleaved:
            failures.append(
                f"interleaved collectives {ov['interleaved']} < required "
                f"{min_interleaved} (every exchange scheduled after the "
                "last compute op — nothing can hide it)")
    if max_collective_bytes is not None:
        from paddle_tpu.obs import spmd

        prof = spmd.collective_profile(hlo_text, mesh=mesh)
        if prof["total_bytes"] > max_collective_bytes:
            failures.append(
                f"collective bytes {prof['total_bytes']} > allowed "
                f"{max_collective_bytes} ({prof['counts']})")
    return failures


def check_entry(compiled, **bounds):
    """``check_hlo`` over one Executor cache entry (lowering it on
    demand); the entry's own mesh feeds collective attribution."""
    hlo = entry_hlo(compiled)
    if hlo is None:
        return ["entry HLO unavailable (lowering failed)"]
    axes = getattr(compiled, "mesh_axes", None)
    mesh = None
    if axes is not None:
        mesh = (axes, getattr(compiled, "mesh_device_ids", None))
    return check_hlo(hlo, mesh=mesh, **bounds)


def executor_call_counts(exe):
    """Compiled-call accounting for one Executor: ``compiles`` (jit
    cache misses — one per distinct executable built) and
    ``dispatches`` (compiled-fn invocations across run/run_steps). The
    fused-path gate: K steps through ``run_steps`` must show
    compiles == 1 and dispatches == 1 where the sequential loop shows
    dispatches == K."""
    stats = exe.cache_stats()
    return {"compiles": stats["misses"], "dispatches": exe.dispatches,
            "cache_hits": stats["hits"], "entries": stats["size"]}


def journal_gates(exe, **bounds):
    """Gate every compiled entry of ``exe`` and record the verdicts in
    the active run journal (one ``perf_gate`` event per entry, with the
    failure strings and the donation/while/call-count evidence), so
    ``tools/run_report.py --diff`` can surface a gate regression as a
    run regression. Inactive journal = pure check (no side effects).
    Returns the combined failure list."""
    from paddle_tpu.obs import journal as J

    all_failures = []
    calls = executor_call_counts(exe)
    for compiled in exe._cache.values():
        failures = check_entry(compiled, **bounds)
        all_failures += failures
        if J.ACTIVE is not None:
            hlo = entry_hlo(compiled)
            don = donation_stats(hlo)["count"] if hlo else None
            ops = op_counts(hlo, kinds=("while", "fusion")) if hlo else {}
            J.ACTIVE.event(
                "perf_gate", entry_uid=compiled.program_uid,
                steps_fused=getattr(compiled, "steps", None),
                donated=don, while_ops=ops.get("while"),
                fusion_ops=ops.get("fusion"),
                failures=failures, passed=not failures,
                compiles=calls["compiles"], dispatches=calls["dispatches"])
    return all_failures


# -- donation-coverage sweep --------------------------------------------------

# model-zoo legs for the coverage sweep: (name, builder) where builder
# returns (program, startup, loss) — small shapes so the sweep runs in
# tier-1 CI. Every leg trains through run_steps and must donate its
# persistable carry on the fused entry.


def _sweep_mlp():
    return _build_mlp(batch=8)


def _sweep_lenet():
    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.vision import LeNet

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[8, 1, 28, 28])
        y = pt.static.data("y", [8], "int64")
        loss = F.cross_entropy(LeNet()(x), y)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return prog, startup, loss


def _sweep_ngram():
    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.nlp.word2vec import NGramLM

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        w = pt.static.data("w", [8, 4], "int64")
        y = pt.static.data("y", [8], "int64")
        loss = F.cross_entropy(
            NGramLM(vocab_size=64, embed_dim=8, hidden=16)(w), y)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return prog, startup, loss


SWEEP_MODELS = (("mlp", _sweep_mlp), ("lenet", _sweep_lenet),
                ("ngram_lm", _sweep_ngram))


def _sweep_feed(prog, rng):
    """One synthetic feed matching the program's data vars."""
    feed = {}
    for v in prog.global_block.vars.values():
        if not v.is_data or v.name.startswith("@"):
            continue
        shape = tuple(int(d) for d in v._data.shape)
        if not shape:
            continue
        if "int" in str(v._data.dtype):
            feed[v.name] = rng.randint(0, 10, shape).astype(
                str(v._data.dtype))
        else:
            feed[v.name] = rng.randn(*shape).astype("float32")
    return feed


def donation_sweep(models=SWEEP_MODELS, steps=2):
    """Donation-coverage sweep over the model zoo: every model trains a
    fused ``run_steps`` window and its compiled entry must (a) donate
    EVERY updated persistable (the scan carry stays in HBM) and (b)
    lower to exactly one while loop. Returns
    ``(coverage_rows, failures)`` — one row per model with the counts a
    CI log can table."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    rows, failures = [], []
    pt.enable_static()
    try:
        for name, build in models:
            pt.seed(0)
            prog, startup, loss = build()
            exe = fluid.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            feeds = [_sweep_feed(prog, rng) for _ in range(steps)]
            exe.run_steps(prog, feeds=feeds, fetch_list=[loss])
            entry = next(iter(exe._cache.values()))
            n_persist = len(entry.updated)
            hlo = entry_hlo(entry)
            donated = donation_stats(hlo)["count"] if hlo else 0
            # min_while only: conv/embedding models legally carry extra
            # while loops inside the step body on this CPU lowering —
            # the sweep pins donation coverage and the scan's existence
            entry_fails = check_entry(entry, min_donated=n_persist,
                                      min_while=1)
            rows.append({"model": name, "persistables": n_persist,
                         "donated": donated,
                         "coverage": (donated / n_persist
                                      if n_persist else None),
                         "ok": not entry_fails})
            failures += [f"{name}: {f}" for f in entry_fails]
    finally:
        pt.disable_static()
    return rows, failures


def render_sweep(rows):
    lines = [f"{'model':<12} {'persistables':>12} {'donated':>8} "
             f"{'coverage':>9}  ok"]
    for r in rows:
        cov = "?" if r["coverage"] is None else f"{r['coverage']:.0%}"
        lines.append(f"{r['model']:<12} {r['persistables']:>12} "
                     f"{r['donated']:>8} {cov:>9}  {r['ok']}")
    return "\n".join(lines)


# -- self-test ----------------------------------------------------------------

# canned HLO fixtures with HAND-COMPUTED expectations (no backend needed)
CANNED_HLO = [
    {
        "name": "training step: 2 donated params, 3 fusions, no loop",
        "hlo": "HloModule jit_step, is_scheduled=true, "
               "input_output_alias={ {1}: (1, {}, may-alias), "
               "{2}: (2, {}, may-alias) }, "
               "entry_computation_layout={(f32[16,8]{1,0}, f32[8,8]{1,0}, "
               "f32[8]{0})->(f32[], f32[8,8]{1,0}, f32[8]{0})}\n"
               "%f1 = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %p1), kind=kLoop\n"
               "%f2 = f32[8]{0} fusion(f32[8]{0} %p2), kind=kLoop\n"
               "%f3 = f32[] fusion(f32[16,8]{1,0} %p0), kind=kOutput\n"
               "%d = f32[16,8]{1,0} dot(f32[16,8]{1,0} %p0, "
               "f32[8,8]{1,0} %f1)",
        "donated": 2, "fusion": 3, "while": 0, "dot": 1,
        "aliases": [((1,), 1, "may-alias"), ((2,), 2, "may-alias")],
    },
    {
        "name": "fused scan entry: 1 while, donated carry",
        "hlo": "HloModule jit_fused, is_scheduled=true, "
               "input_output_alias={ {1}: (1, {}, may-alias) }, "
               "entry_computation_layout={(f32[4,16,8]{2,1,0}, "
               "f32[8,8]{1,0})->(f32[4]{0}, f32[8,8]{1,0})}\n"
               "%w = (s32[], f32[8,8]{1,0}, f32[4]{0}) while("
               "(s32[], f32[8,8]{1,0}, f32[4]{0}) %init), "
               "condition=%cond, body=%body\n"
               "%f1 = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %x), kind=kLoop",
        "donated": 1, "fusion": 1, "while": 1, "dot": 0,
        "aliases": [((1,), 1, "may-alias")],
    },
    {
        "name": "inference executable: nothing donated, no loop",
        "hlo": "HloModule jit_fwd, is_scheduled=true, "
               "entry_computation_layout={(f32[16,8]{1,0})->(f32[16])}\n"
               "%d = f32[16]{0} dot(f32[16,8]{1,0} %p0, f32[8]{0} %c)",
        "donated": 0, "fusion": 0, "while": 0, "dot": 1,
        "aliases": [],
    },
]


# hand-computed overlap structure fixtures: the schedule-order parse +
# start/done pairing the comm-overlap gate rests on (XLA CPU lowers
# collectives synchronously, so the async form is pinned here)
CANNED_OVERLAP = [
    {
        "name": "async all-reduce hidden behind fusion+dot",
        "hlo": "HloModule jit_step, is_scheduled=true\n"
               "ENTRY %main {\n"
               "  %p0 = f32[64]{0} parameter(0)\n"
               "  %ar-start.1 = (f32[64]{0}, f32[64]{0}) "
               "all-reduce-start(f32[64]{0} %p0), "
               "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add\n"
               "  %f1 = f32[64]{0} fusion(f32[64]{0} %p0), kind=kLoop\n"
               "  %d1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %f1, "
               "f32[8,8]{1,0} %f1)\n"
               "  %ar-done.1 = f32[64]{0} all-reduce-done("
               "(f32[64]{0}, f32[64]{0}) %ar-start.1)\n"
               "  %f2 = f32[64]{0} fusion(f32[64]{0} %ar-done.1), "
               "kind=kLoop\n"
               "  ROOT %t = (f32[64]{0}) tuple(f32[64]{0} %f2)\n"
               "}",
        # fusion+dot between start/done -> overlapped; f2 after the
        # start -> interleaved
        "stats": {"collectives": 1, "compute_ops": 3, "async_pairs": 1,
                  "async_overlapped": 1, "interleaved": 1},
    },
    {
        "name": "back-to-back start/done pair hides nothing",
        "hlo": "HloModule jit_step, is_scheduled=true\n"
               "ENTRY %main {\n"
               "  %p0 = f32[64]{0} parameter(0)\n"
               "  %f1 = f32[64]{0} fusion(f32[64]{0} %p0), kind=kLoop\n"
               "  %ar-start.2 = (f32[64]{0}, f32[64]{0}) "
               "all-reduce-start(f32[64]{0} %f1), "
               "replica_groups={{0,1}}, to_apply=%add\n"
               "  %ar-done.2 = f32[64]{0} all-reduce-done("
               "(f32[64]{0}, f32[64]{0}) %ar-start.2)\n"
               "  ROOT %t = (f32[64]{0}) tuple(f32[64]{0} %ar-done.2)\n"
               "}",
        "stats": {"collectives": 1, "compute_ops": 1, "async_pairs": 1,
                  "async_overlapped": 0, "interleaved": 0},
    },
    {
        "name": "sync bucketed exchange interleaved with backward",
        "hlo": "HloModule jit_raw, is_scheduled=true\n"
               "ENTRY %main {\n"
               "  %f1 = f32[64]{0} fusion(f32[64]{0} %p0), kind=kLoop\n"
               "  %ar.1 = f32[64]{0} all-reduce(f32[64]{0} %f1), "
               "replica_groups=[1,8]<=[8], to_apply=%add\n"
               "  %f2 = f32[32]{0} fusion(f32[64]{0} %f1), kind=kLoop\n"
               "  %ar.2 = f32[32]{0} all-reduce(f32[32]{0} %f2), "
               "replica_groups=[1,8]<=[8], to_apply=%add\n"
               "  %f3 = f32[32]{0} fusion(f32[32]{0} %ar.2), kind=kLoop\n"
               "  ROOT %t = (f32[32]{0}) tuple(f32[32]{0} %f3)\n"
               "}",
        # both sync all-reduces precede the last compute op (f3)
        "stats": {"collectives": 2, "compute_ops": 3, "async_pairs": 0,
                  "async_overlapped": 0, "interleaved": 2},
    },
    {
        # ".1" must pair with %ar-done.1, not %ar-start.10's done (a
        # substring match binds .1 -> done.10 and loses the overlap)
        "name": "start/done pairing is exact-name, not prefix",
        "hlo": "HloModule jit_step, is_scheduled=true\n"
               "ENTRY %main {\n"
               "  %p0 = f32[64]{0} parameter(0)\n"
               "  %ar-start.1 = (f32[64]{0}, f32[64]{0}) "
               "all-reduce-start(f32[64]{0} %p0), "
               "replica_groups={{0,1}}, to_apply=%add\n"
               "  %ar-start.10 = (f32[64]{0}, f32[64]{0}) "
               "all-reduce-start(f32[64]{0} %p0), "
               "replica_groups={{0,1}}, to_apply=%add\n"
               "  %ar-done.10 = f32[64]{0} all-reduce-done("
               "(f32[64]{0}, f32[64]{0}) %ar-start.10)\n"
               "  %f1 = f32[64]{0} fusion(f32[64]{0} %p0), kind=kLoop\n"
               "  %ar-done.1 = f32[64]{0} all-reduce-done("
               "(f32[64]{0}, f32[64]{0}) %ar-start.1)\n"
               "  ROOT %t = (f32[64]{0}) tuple(f32[64]{0} %ar-done.1)\n"
               "}",
        # only .1's window spans f1; .10's closes before it
        "stats": {"collectives": 2, "compute_ops": 1, "async_pairs": 2,
                  "async_overlapped": 1, "interleaved": 2},
    },
]


def _check(failures, cond, msg):
    if not cond:
        failures.append(msg)


def _build_mlp(batch=16):
    import paddle_tpu.fluid as fluid

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return prog, startup, loss


def _live_scan_vs_loop(ndev):
    """The acceptance gate, live: K=8 microbatches through run_steps
    must (a) produce a BITWISE-identical loss trajectory to 8
    sequential run() calls, (b) compile once and dispatch once where
    the loop dispatches 8 times, (c) donate the persistable carry, and
    (d) lower to exactly one while loop."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    failures = []
    K = 8
    pt.enable_static()
    try:
        rng = np.random.RandomState(0)
        feeds = [{"x": rng.randn(16, 8).astype(np.float32),
                  "y": rng.randn(16, 1).astype(np.float32)}
                 for _ in range(K)]

        pt.seed(0)
        prog, startup, loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        seq = [exe.run(prog, feed=f, fetch_list=[loss])[0] for f in feeds]
        calls = executor_call_counts(exe)
        _check(failures, calls["compiles"] == 1 and calls["dispatches"] == K,
               f"sequential loop: expected 1 compile / {K} dispatches, "
               f"got {calls}")

        pt.seed(0)
        prog2, startup2, loss2 = _build_mlp()
        exe2 = fluid.Executor()
        exe2.run(startup2)
        (traj,) = exe2.run_steps(prog2, feeds=feeds, fetch_list=[loss2])
        calls2 = executor_call_counts(exe2)
        _check(failures,
               calls2["compiles"] == 1 and calls2["dispatches"] == 1,
               f"fused run_steps: expected 1 compile / 1 dispatch for "
               f"{K} steps, got {calls2}")
        _check(failures, traj.shape == (K,),
               f"fused trajectory shape {traj.shape} != ({K},)")
        bitwise = all(
            np.asarray(s).tobytes() == np.asarray(traj[k]).tobytes()
            for k, s in enumerate(seq))
        _check(failures, bitwise,
               f"fused loss trajectory is not bitwise-identical to the "
               f"sequential one: {[float(np.asarray(s)) for s in seq]} vs "
               f"{[float(v) for v in traj]}")

        entry = next(iter(exe2._cache.values()))
        n_persist = len(entry.updated)
        _check(failures, n_persist > 0,
               "MLP entry has no updated persistables?")
        failures += [f"fused entry: {f}" for f in check_entry(
            entry, min_donated=n_persist, min_while=1, max_while=1)]
        # the sequential entry must donate too, and contain NO loop
        entry1 = next(iter(exe._cache.values()))
        failures += [f"step entry: {f}" for f in check_entry(
            entry1, min_donated=n_persist, max_while=0)]
    finally:
        pt.disable_static()
    return failures


def _live_inference_gates():
    """Inference coverage (ROADMAP item 3 leftover): the Predictor's
    compiled entries must gate like Executor entries (no loop, nothing
    donated — weights are shared across calls), and the serving decode
    step must DONATE its KV pool buffers (the invariant that keeps one
    resident pool copy across every decode step)."""
    import tempfile

    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    failures = []
    pt.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[8, 8])
            out = fluid.layers.fc(x, size=4)
        exe = fluid.Executor()
        exe.run(startup)
        with tempfile.TemporaryDirectory() as d:
            from paddle_tpu.inference import Predictor

            prefix = os.path.join(d, "m")
            pt.framework.io.save_inference_model(
                prefix, ["x"], [out], program=main)
            pred = Predictor(prefix)
            pred.run({"x": np.zeros((8, 8), np.float32)})
            stats = pred.cache_stats()
            _check(failures, stats == {"hits": 0, "misses": 1, "size": 1},
                   f"predictor call accounting: {stats}")
            for entry in pred._compiled.values():
                # inference entry: pure fn — no while loop, and NOTHING
                # donated (a donated weight would be consumed by the
                # first call; predictors share weights across calls)
                failures += [f"predictor entry: {f}" for f in
                             check_entry(entry, max_while=0,
                                         max_donated=0)]
    finally:
        pt.disable_static()

    from paddle_tpu.serving import PagedKVCache, ServeEngine, TinyLM

    eng = ServeEngine(TinyLM(num_heads=2, head_dim=8),
                      PagedKVCache(16, 4, 2, 8))
    entry = eng.decode_entry(2)
    hlo = entry_hlo(entry)
    if hlo is None:
        failures.append("serving decode entry failed to lower")
    else:
        don = donation_stats(hlo)
        _check(failures, don["count"] >= 2,
               f"paged decode step donates {don['count']} < 2 buffers "
               "(KV pool round-trips HBM every token!)")
        params = {p for _, p, _ in don["aliases"]}
        _check(failures, {0, 1} <= params,
               f"decode donation misses a KV pool (params {params}, "
               "k_pages=0 v_pages=1)")
        failures += [f"serving decode entry: {f}" for f in
                     check_entry(entry, min_donated=2)]
    return failures


def self_test():
    ndev = _ensure_fake_devices(8)
    failures = []
    for case in CANNED_HLO:
        don = donation_stats(case["hlo"])
        _check(failures, don["count"] == case["donated"],
               f"{case['name']}: donated {don['count']} != "
               f"{case['donated']}")
        _check(failures, don["aliases"] == case["aliases"],
               f"{case['name']}: aliases {don['aliases']} != "
               f"{case['aliases']}")
        ops = op_counts(case["hlo"], kinds=("fusion", "while", "dot"))
        for k in ("fusion", "while", "dot"):
            _check(failures, ops[k] == case[k],
                   f"{case['name']}: {k} count {ops[k]} != {case[k]}")
        # the bound-checker must agree with the raw counts
        _check(failures,
               check_hlo(case["hlo"], min_donated=case["donated"],
                         max_donated=case["donated"],
                         min_fusion=case["fusion"],
                         min_while=case["while"],
                         max_while=case["while"]) == [],
               f"{case['name']}: check_hlo rejects its own ground truth")
        _check(failures,
               check_hlo(case["hlo"],
                         min_donated=case["donated"] + 1) != [],
               f"{case['name']}: check_hlo missed a donation regression")

    for case in CANNED_OVERLAP:
        got = overlap_stats(case["hlo"])
        _check(failures, got == case["stats"],
               f"{case['name']}: overlap stats {got} != {case['stats']}")
    # the bound checks must accept ground truth and catch regressions
    ok = CANNED_OVERLAP[0]["hlo"]
    _check(failures,
           check_hlo(ok, min_async_overlapped=1, min_interleaved=1) == [],
           "overlap check_hlo rejects the overlapped fixture")
    _check(failures, check_hlo(CANNED_OVERLAP[1]["hlo"],
                               min_async_overlapped=1) != [],
           "overlap check_hlo missed the back-to-back pair")
    _check(failures,
           check_hlo(CANNED_OVERLAP[2]["hlo"], max_all_reduce=1) != [],
           "max_all_reduce missed the 2-all-reduce fixture")

    if ndev < 2:
        failures.append(f"need >=2 fake devices, have {ndev}")
    else:
        failures += _live_scan_vs_loop(ndev)
    failures += _live_inference_gates()

    for line in failures:
        print(f"  FAILED — {line}")
    if failures:
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: canned-HLO donation/fusion/while counts "
          "match hand-computed values, bound checks catch seeded "
          "regressions, the overlap parse pins hand-computed async-"
          "pair/interleave structure, the live 8-fake-device K=8 "
          "scan-vs-loop check holds (bitwise loss trajectory, 1 compile "
          "+ 1 dispatch vs 8, persistable carry donated, exactly one "
          "while loop), and the inference gates hold (predictor entries "
          "loop-free with nothing donated, serving decode step donates "
          "both KV pool buffers)")
    return 0


def entry_report(exe=None):
    """Human-readable invariant report over an Executor's cache (the
    --entry-report demo builds a fused MLP run first)."""
    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    if exe is None:
        import numpy as np

        pt.enable_static()
        try:
            pt.seed(0)
            prog, startup, loss = _build_mlp()
            exe = fluid.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            feeds = [{"x": rng.randn(16, 8).astype(np.float32),
                      "y": rng.randn(16, 1).astype(np.float32)}
                     for _ in range(4)]
            exe.run_steps(prog, feeds=feeds, fetch_list=[loss])
        finally:
            pt.disable_static()
    lines = [f"calls        {json.dumps(executor_call_counts(exe))}"]
    for key, compiled in exe._cache.items():
        hlo = entry_hlo(compiled)
        if hlo is None:
            lines.append(f"entry uid={compiled.program_uid}: "
                         "HLO unavailable")
            continue
        don = donation_stats(hlo)
        ops = op_counts(hlo, kinds=("fusion", "while", "dot",
                                    "all-reduce"))
        lines.append(
            f"entry uid={compiled.program_uid} "
            f"steps_fused={getattr(compiled, 'steps', None)}  "
            f"donated={don['count']}  ops={json.dumps(ops)}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--self-test", action="store_true",
                    help="canned-HLO donation/fusion accounting + live "
                         "scan-vs-loop compiled-call-count gate")
    ap.add_argument("--entry-report", action="store_true",
                    help="build + fuse a demo MLP and print its "
                         "invariant report")
    ap.add_argument("--donation-sweep", action="store_true",
                    help="train every model-zoo sweep leg through a "
                         "fused run_steps window and report per-model "
                         "donation coverage; exit 1 when any carry is "
                         "not donated")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.entry_report:
        _ensure_fake_devices(8)
        print(entry_report())
        return 0
    if args.donation_sweep:
        _ensure_fake_devices(8)
        rows, failures = donation_sweep()
        print(render_sweep(rows))
        for line in failures:
            print(f"  FAILED — {line}")
        return 1 if failures else 0
    ap.error("pass --self-test, --entry-report, or --donation-sweep")


if __name__ == "__main__":
    sys.exit(main())
