#!/usr/bin/env python
"""request_report: per-request timelines and tail-latency attribution
from a run's journals.

The CLI front door for ``paddle_tpu.obs.reqtrace`` (the read side of
the ``req.*`` lifecycle events the serving stack journals): assemble
one run's router + replica journals into per-request timelines, print
each request's exact phase decomposition (rate-limit wait / router
queue / requeue loss / scheduler queue / prefill / preemption loss /
decode — the telescope sums to e2e by construction), rank the
worst-percentile tail, and export the timelines as Perfetto request
lanes (one row per request, flow arrows across requeues).

Usage:
    python tools/request_report.py RUN_DIR              # table
    python tools/request_report.py RUN_DIR --json
    python tools/request_report.py RUN_DIR --worst 5 --key e2e_ms
    python tools/request_report.py RUN_DIR --trace-out req.json
    python tools/request_report.py --self-test

--self-test (wired into tier-1 via tests/test_tooling.py) asserts on a
ManualClock:
- a REAL pressured ServeEngine run: every attribution sums bitwise to
  its e2e, and preemption loss matches the engine's own stamp pairs;
- a hand-written routed fixture (router + 2 replica journals, one
  requeue + one rate-limit hold + one preemption): every phase equals
  its hand-computed value to the nanosecond, the timeline carries BOTH
  dispatch segments, and the exported lanes draw the cross-replica
  flow arrow.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# column labels for reqtrace.PHASES, in canonical order
PHASE_LABELS = ("rate", "router", "requeue", "sched", "prefill",
                "preempt", "decode")


def _ensure_cpu():
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


def render_table(atts):
    """Fixed-width attribution table, one row per request."""
    from paddle_tpu.obs.reqtrace import PHASES

    lines = ["  " + "rid".ljust(12) + "st".rjust(3) + "dsp".rjust(4)
             + "rq".rjust(3) + "pre".rjust(4)
             + "".join(c.rjust(10) for c in
                       ("ttft", "e2e") + PHASE_LABELS)]
    for a in atts:
        row = [a["ttft_ms"], a["e2e_ms"]] + [a[p] for p in PHASES]
        lines.append(
            "  " + str(a["rid"]).ljust(12)
            + str((a["state"] or "?")[:2]).rjust(3)
            + str(a["dispatches"]).rjust(4)
            + str(a["requeues"]).rjust(3)
            + str(a["preemptions"]).rjust(4)
            + "".join(f"{v:10.3f}" for v in row))
    return "\n".join(lines)


def render_tail(rep):
    from paddle_tpu.obs.reqtrace import PHASES

    head = (f"worst {len(rep['worst'])} of {rep['requests']} by "
            f"{rep['key']}")
    if rep["threshold"] is not None:
        head += f" (p{rep['pct']:g} >= {rep['threshold']:.3f} ms)"
    share = rep["phase_share"]
    lines = [head, render_table(rep["worst"]),
             "  phase share: " + "  ".join(
                 f"{s}={share[p]:.1%}"
                 for s, p in zip(PHASE_LABELS, PHASES)
                 if share[p] > 0)]
    return "\n".join(lines)


# -- self-test ----------------------------------------------------------------


def _check(failures, cond, msg):
    if not cond:
        failures.append(msg)


def _test_pressured_engine(failures, run_dir):
    """A REAL engine run under page pressure: journal-derived
    attribution must sum bitwise to e2e, with the preemption loss
    matching the engine's own stamp arithmetic."""
    from paddle_tpu.obs import journal, reqtrace
    from paddle_tpu.serving import (ManualClock, PagedKVCache,
                                    Scheduler, ServeEngine, TinyLM)
    from paddle_tpu.serving.engine import preempt_loss_ms

    clock = ManualClock()
    journal.start_run(run_dir)
    try:
        cache = PagedKVCache(8, 2, 2, 8, max_seq_len=8)
        eng = ServeEngine(TinyLM(num_heads=2, head_dim=8), cache,
                          scheduler=Scheduler(cache, token_budget=64,
                                              clock=clock))
        reqs = [eng.submit([1, 2], max_new_tokens=6,
                           arrival_t=clock())
                for _ in range(4)]
        for _ in range(200):
            if eng.scheduler.idle:
                break
            eng.step()
            clock.advance(0.015625)  # dyadic: float sums stay exact
        _check(failures, len(eng.finished) == 4,
               f"{len(eng.finished)}/4 requests finished")
        _check(failures, eng.scheduler.preemptions >= 1,
               "pool was sized to force >=1 preemption; got none")
    finally:
        journal.end_run()
    tls = reqtrace.assemble_run(run_dir)
    _check(failures, len(tls) == 4,
           f"assembled {len(tls)} timelines, want 4")
    preempted = 0
    for rid in sorted(tls):
        att = reqtrace.attribute(tls[rid])
        if att is None:
            failures.append(f"{rid}: finished but unattributable")
            continue
        s = reqtrace.attribution_sum(att)
        _check(failures, s == att["e2e_ms"],
               f"{rid}: phase sum {s!r} != e2e {att['e2e_ms']!r} "
               "(must be bitwise on the manual clock)")
        if att["preempt_ms"] > 0:
            preempted += 1
            req = next(r for r in reqs if r.rid == rid)
            _check(failures, att["preempt_ms"] == preempt_loss_ms(req),
                   f"{rid}: journal-derived preempt_ms "
                   f"{att['preempt_ms']!r} != engine stamps "
                   f"{preempt_loss_ms(req)!r}")
    _check(failures, preempted >= 1,
           "no request showed nonzero preemption loss under pressure")


def _test_routed_fixture(failures, run_dir, trace_path):
    """A hand-written routed run: one request rate-held 250 ms,
    dispatched to replica 0, requeued (replica death), re-dispatched
    to replica 1, preempted once mid-decode. Every phase is
    hand-computed; the timeline must span both replicas and the lane
    export must draw the cross-pid flow arrow."""
    from paddle_tpu.obs import journal as J
    from paddle_tpu.obs import reqtrace

    router = J.RunJournal(os.path.join(run_dir, J.ROUTER_DIR),
                          flush_every=1, compute_flops=False)
    router.start()
    router.event("req.submit", rid="fx-1", at=1.0, tenant="t0",
                 trace="tr-fx", cost=8, prompt_tokens=4)
    router.event("req.rate_hold", rid="fx-1", at=1.0, tenant="t0")
    router.event("req.dispatch", rid="fx-1", at=1.5, replica=0, seq=1,
                 rate_wait_ms=250.0, trace="tr-fx")
    router.event("req.requeue", rid="fx-1", at=2.0, replica=0,
                 reason="replica_exit")
    router.event("req.dispatch", rid="fx-1", at=2.25, replica=1, seq=2,
                 rate_wait_ms=250.0, trace="tr-fx")
    router.close()
    # replica 0: the victim incarnation — admitted, then died before
    # finishing (no terminal record, a torso the final record outranks)
    r0 = J.RunJournal(os.path.join(run_dir, J.rank_subdir(0)), rank=0,
                      flush_every=1, compute_flops=False)
    r0.start()
    r0.event("req.admit", rid="fx-1", at=1.75, resumed=False)
    r0.close()
    # replica 1: the final incarnation — admit 2.5, first token 2.75,
    # one decode preemption 3.0 -> resume 3.25, finish 4.0
    r1 = J.RunJournal(os.path.join(run_dir, J.rank_subdir(1)), rank=1,
                      flush_every=1, compute_flops=False)
    r1.start()
    r1.event("req.admit", rid="fx-1", at=2.5, resumed=False)
    r1.event("req.preempt", rid="fx-1", at=3.0, preemptions=1)
    r1.event("req.admit", rid="fx-1", at=3.25, resumed=True)
    r1.record_request(rid="fx-1", state="FINISHED", arrival_t=1.0,
                      admit_t=2.5, first_token_t=2.75, finish_t=4.0,
                      prompt_tokens=4, output_tokens=5, preemptions=1,
                      replica=1, trace="tr-fx")
    r1.close()

    tls = reqtrace.assemble_run(run_dir)
    t = tls.get("fx-1")
    if t is None:
        failures.append("fixture timeline did not assemble")
        return
    segs = t["segments"]
    _check(failures, [s["replica"] for s in segs] == [0, 1],
           f"segments must span replicas [0, 1]: {segs}")
    _check(failures,
           segs and segs[0]["start"] == 1.5 and segs[0]["end"] == 2.0
           and segs[1]["start"] == 2.25 and segs[1]["end"] == 4.0,
           f"segment bounds off the hand-written stamps: {segs}")
    att = reqtrace.attribute(t)
    if att is None:
        failures.append("fixture request unattributable")
        return
    # hand-computed (all dyadic, so EXACT float equality):
    #   ttft = (2.75 - 1.0) s = 1750 ms     e2e = 3000 ms
    #   rate    = 250  (the router's closed hold)
    #   router  = (1.5-1.0 + 2.25-2.0) s - rate = 750 - 250 = 500
    #   requeue = (2.0 - 1.5) s = 500       sched = (2.5 - 2.25) = 250
    #   prefill = 1750 - 1500 = 250  (== first_token - admit)
    #   preempt = (3.25 - 3.0) s = 250      decode = 3000-1750-250
    want = {"ttft_ms": 1750.0, "e2e_ms": 3000.0,
            "rate_limit_wait_ms": 250.0, "router_queue_ms": 500.0,
            "requeue_ms": 500.0, "sched_queue_ms": 250.0,
            "prefill_ms": 250.0, "preempt_ms": 250.0,
            "decode_ms": 1000.0}
    for k, v in sorted(want.items()):
        _check(failures, att[k] == v,
               f"fixture {k} {att[k]!r} != hand-computed {v!r}")
    _check(failures,
           reqtrace.attribution_sum(att) == att["e2e_ms"],
           "fixture phase telescope broke")
    _check(failures, att["trace"] == "tr-fx" and att["tenant"] == "t0",
           f"trace/tenant lost in assembly: {att}")

    out = reqtrace.write_request_trace(tls, trace_path)
    _check(failures, out["slices"] == 2,
           f"lane export {out['slices']} slices != 2 segments")
    with open(trace_path, encoding="utf-8") as f:
        evs = json.load(f)["traceEvents"]
    starts = [e for e in evs if e.get("ph") == "s"]
    ends = [e for e in evs if e.get("ph") == "f"]
    _check(failures, len(starts) == 1 and len(ends) == 1,
           f"want exactly one flow pair, got s={len(starts)} "
           f"f={len(ends)}")
    if starts and ends:
        _check(failures,
               starts[0]["pid"] == 0 and ends[0]["pid"] == 1
               and starts[0]["id"] == ends[0]["id"],
               f"flow arrow must cross pid 0 -> 1 with a shared id: "
               f"{starts[0]}, {ends[0]}")
    rep = reqtrace.tail_report(tls, key="e2e_ms", k=1)
    _check(failures, rep and rep["worst"][0]["rid"] == "fx-1",
           "tail report lost the fixture request")
    _check(failures,
           rep and abs(sum(rep["phase_share"].values()) - 1.0) < 1e-12,
           "phase shares must sum to 1")


def self_test():
    _ensure_cpu()
    failures = []
    with tempfile.TemporaryDirectory() as d:
        _test_pressured_engine(failures, os.path.join(d, "engine"))
        _test_routed_fixture(failures, os.path.join(d, "routed"),
                             os.path.join(d, "req_trace.json"))
    for line in failures:
        print(f"  FAILED — {line}")
    if failures:
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: a real pressured-engine run attributes "
          "every request's phases bitwise-exactly to its e2e on the "
          "manual clock (preemption loss matching the engine's own "
          "stamps), and the hand-written routed fixture reproduces "
          "every hand-computed phase to the nanosecond with the "
          "requeued timeline spanning both replicas and the exported "
          "request lanes drawing the cross-replica flow arrow")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", help="run dir (router/ + "
                    "rank_NN/ subdirs, or a single journal dir)")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--worst", type=int, default=0, metavar="K",
                    help="print only the K worst requests by --key")
    ap.add_argument("--key", default="ttft_ms",
                    choices=("ttft_ms", "e2e_ms"),
                    help="tail-ranking metric")
    ap.add_argument("--pct", type=float, default=99.0,
                    help="tail percentile when --worst is not given")
    ap.add_argument("--trace-out", metavar="PATH",
                    help="also write the Perfetto request lanes here")
    ap.add_argument("--self-test", action="store_true",
                    help="ManualClock-exact attribution fixtures")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.path:
        ap.error("need a run dir (or --self-test)")
    from paddle_tpu.obs import reqtrace

    tls = reqtrace.assemble_run(args.path)
    if args.trace_out:
        out = reqtrace.write_request_trace(tls, args.trace_out)
        print(f"request lanes: {out['slices']} slices "
              f"({out['events']} events) -> {out['path']}",
              file=sys.stderr)
    if args.worst or args.json:
        rep = reqtrace.tail_report(
            tls, key=args.key, pct=args.pct,
            k=args.worst if args.worst else None)
        if rep is None:
            print("no attributable requests", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(rep, sort_keys=True))
        else:
            print(render_tail(rep))
        return 0
    atts = reqtrace.attribute_run(tls)
    if not atts:
        print("no attributable requests", file=sys.stderr)
        return 1
    print(f"{len(atts)} attributed request(s) "
          f"({len(tls)} timelines):")
    print(render_table(atts))
    return 0


if __name__ == "__main__":
    sys.exit(main())
