#!/usr/bin/env python
"""run_report: render, diff, and self-test paddle_tpu run journals.

The operational front door for ``paddle_tpu.obs.journal`` (the role the
MLPerf-era run dashboards play): render one run's flight record as a
table or JSON, or diff two runs as a regression gate — step-time,
loss-curve, and collective-traffic (all-reduce bytes/step) deltas
against thresholds, exit code 1 when any regresses (usable directly as
a bench gate in CI).

Usage:
    python tools/run_report.py RUN_DIR                 # table
    python tools/run_report.py RUN_DIR --json
    python tools/run_report.py --diff BASE_DIR NEW_DIR \\
        [--step-time-threshold 0.25] [--loss-threshold 0.05]
    python tools/run_report.py --self-test             # synthetic 2-run
        # pair: asserts the diff flags the injected regression and the
        # anomaly detectors fire

Wired into tier-1 via tests/test_tooling.py (obs_report/chaos_run
pattern).
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DEFAULT_STEP_TIME_THRESHOLD = 0.25   # mean step_ms may grow 25%
DEFAULT_LOSS_THRESHOLD = 0.05        # final loss may grow 5% (relative)
DEFAULT_COMM_THRESHOLD = 0.10        # all-reduce bytes/step may grow 10%
DEFAULT_PLAN_MISMATCH_THRESHOLD = 0.10  # planner predicted-vs-measured
DEFAULT_MEMORY_DRIFT_THRESHOLD = 0.15   # static peak-HBM prediction vs
#                                         the executable's memory_analysis()
DEFAULT_QUEUE_SHARE_THRESHOLD = 0.10    # serving queue share of TTFT may
#                                         grow 10 points (absolute)
DEFAULT_FAIRNESS_DRIFT_THRESHOLD = 0.20  # |served share - weight share|
#                 (absolute; mirrors obs.usage.DEFAULT_FAIRNESS_DRIFT_THRESHOLD)


# -- loading -----------------------------------------------------------------


def _journal_files(path):
    """The journal file(s) for a run (delegates to the canonical
    ``obs.fleet`` parser — one loader for this CLI and the fleet
    aggregator)."""
    from paddle_tpu.obs import fleet as _fleet

    return _fleet.journal_files(path)


def load_run(path):
    """Parse a run's journal into {header, steps, events, anomalies,
    summary, parse_errors}. Tolerates a torn final line (a crashed
    writer) — it lands in parse_errors, everything before it loads.
    Delegates to ``obs.fleet.load_journal``, the one canonical journal
    parser (the fleet aggregator reads rank subdirs through the same
    code)."""
    from paddle_tpu.obs import fleet as _fleet

    return _fleet.load_journal(path)


def _finite_losses(run):
    return [s["loss"] for s in run["steps"]
            if isinstance(s.get("loss"), (int, float))
            and math.isfinite(s["loss"]) and not s.get("skipped")]


def _step_times(run):
    return [s["step_ms"] for s in run["steps"]
            if isinstance(s.get("step_ms"), (int, float))
            and s["step_ms"] > 0]


def _mean(xs):
    return sum(xs) / len(xs) if xs else None


def _comm_bytes_per_step(run, key="all_reduce_bytes"):
    """Mean collective bytes over the steps that carry a comm record
    (the journal attributes comm once the entry's lazy analysis lands);
    None when no step was attributed."""
    vals = [s["comm"].get(key, 0) for s in run["steps"]
            if isinstance(s.get("comm"), dict)]
    return _mean(vals)


def _pctl(xs, q):
    """Exact percentile over the raw per-request values (the journal
    keeps every request record, unlike the bounded-bucket serving
    histograms) — ONE shared definition with tools/serve_bench.py."""
    from paddle_tpu.obs.metrics import exact_percentile

    return exact_percentile(xs, q)


def request_summary(run):
    """Serving columns over the run's ``request`` records (canonical
    implementation: ``obs.fleet.request_summary``, which also merges
    them across replicas): counts by state, total preemptions, and
    exact p50/p99 TTFT/TPOT/e2e (ms). None when the run served
    nothing."""
    from paddle_tpu.obs import fleet as _fleet

    return _fleet.request_summary(run)


def elastic_summary(run):
    """Elasticity columns over the run's ``elastic.*`` events (written
    by ``resilience.elastic.GangSupervisor``; canonical implementation
    in ``obs.fleet``): restarts, budget-free preemptions, watchdog
    kills, resume-latency p50/max, resume steps, budget exhaustion.
    None when the run was never supervised."""
    from paddle_tpu.obs import fleet as _fleet

    return _fleet.elastic_summary(run)


def router_summary(run):
    """Serve-fleet router columns over the run's ``router.*`` events
    (written by ``serving.fleet.Router``; canonical implementation in
    ``obs.fleet``): dispatched/requeued/rejected counts, per-tenant
    token shares, scale events, aggregate p99 TTFT. None when the run
    never routed."""
    from paddle_tpu.obs import fleet as _fleet

    return _fleet.router_summary(run)


def render_router_line(rsum):
    """One render line for a run that routed a serve fleet."""
    line = (f"router       dispatched={rsum['dispatched']} "
            f"requeued={rsum['requeued']} rejected={rsum['rejected']} "
            f"completed={rsum['completed']}")
    if rsum.get("replicas") is not None:
        line += f" replicas={rsum['replicas']}"
    if rsum.get("scale_events"):
        line += (f" scale_events={rsum['scale_events']} "
                 f"(+{rsum.get('scale_ups') or 0}/"
                 f"-{rsum.get('scale_downs') or 0})")
    if rsum.get("tenants"):
        line += " tenants " + " ".join(
            f"{t}:{s:.2f}" for t, s in sorted(rsum["tenants"].items()))
    if rsum.get("ttft_p99_ms") is not None:
        line += f" ttft_p99={rsum['ttft_p99_ms']:.1f}ms"
    return line


def tenant_summary(run):
    """Per-tenant chargeback columns over the run's request records and
    ``tenant.*`` events (canonical implementation:
    ``obs.fleet.tenant_summary``): tokens, device-ns, page-ns, exact
    latency percentiles per tenant, plus the router's fairness audit.
    None when the run carries no tenant signal."""
    from paddle_tpu.obs import fleet as _fleet

    return _fleet.tenant_summary(run)


def render_tenant_table(tsum):
    """Render lines for a per-tenant chargeback rollup (one line per
    tenant + the fairness verdict; shared with tools/fleet_report.py
    and tools/usage_report.py via their ``_load_sibling``)."""
    lines = []
    for t, d in sorted((tsum.get("tenants") or {}).items()):
        line = (f"tenant {t:<10} req={d.get('requests', 0)} "
                f"done={d.get('completed', 0)} "
                f"tok={d.get('prompt_tokens', 0)}"
                f"+{d.get('decode_tokens', 0)} "
                f"dev_ms={(d.get('device_ns') or 0) / 1e6:.3f} "
                f"page_s={(d.get('page_ns') or 0) / 1e9:.3f}")
        if d.get("preemptions"):
            line += f" preempt={d['preemptions']}"
        for key, label in (("ttft_ms_p99", "ttft_p99"),
                           ("e2e_ms_p99", "e2e_p99")):
            if d.get(key) is not None:
                line += f" {label}={d[key]:.1f}ms"
        lines.append(line)
    fair = tsum.get("fairness")
    if fair and fair.get("tenants"):
        line = (f"fairness     max_drift={fair['max_drift']:.3f} "
                f"threshold={fair['threshold']:.3f}")
        if fair.get("worst_tenant") is not None:
            line += f" worst={fair['worst_tenant']}"
        line += " ok" if fair.get("ok") else " DRIFT"
        lines.append(line)
    return lines


def fleet_summary(path):
    """The cross-rank rollup when ``path`` holds per-rank journal
    subdirs (``rank_NN/``, written by GangSupervisor / ``dist.launch``
    workers): ``obs.fleet.aggregate`` — per-rank table, skew,
    straggler/hang attribution, merged request percentiles. None for a
    single-process run dir. ``tools/fleet_report.py`` renders the full
    table; this feeds the one-line render below."""
    from paddle_tpu.obs import fleet as _fleet

    if not _fleet.rank_dirs(path):
        return None
    return _fleet.aggregate(path)


def render_fleet_line(agg):
    """One render line for a fleet run dir (the per-rank detail lives
    in tools/fleet_report.py)."""
    skew = agg["skew"]
    line = (f"fleet        {agg['nranks']} ranks, "
            f"{agg['aligned_steps']} aligned steps")
    if skew["max"] is not None:
        line += (f", skew max={skew['max']:.3g}x @step {skew['max_step']}"
                 f" (slowest rank {skew['worst_rank']})")
    stragglers = agg.get("stragglers") or []
    if stragglers:
        line += ", stragglers: " + ", ".join(
            f"rank {s['rank']} ({s['kind']})" for s in stragglers[:4])
    return line


def plan_summary(run):
    """Auto-parallel columns over the run's ``plan`` events (one per
    ``fleet.auto_parallel`` compile): plan count, the meshes chosen,
    and the worst predicted-vs-measured wire-byte mismatch — the number
    the planner's cost model is accountable to. None when the run never
    auto-parallelized."""
    events = [e for e in run.get("events") or []
              if e.get("kind") == "plan"]
    if not events:
        return None
    mismatches = [e["mismatch"] for e in events
                  if isinstance(e.get("mismatch"), (int, float))]
    axes = []
    for e in events:
        a = e.get("axes")
        if a and a not in axes:
            axes.append(a)
    return {
        "plans": len(events),
        "axes": axes,
        "predicted_wire_bytes": [e.get("predicted_wire_bytes")
                                 for e in events],
        "measured_wire_bytes": [e.get("measured_wire_bytes")
                                for e in events],
        "max_mismatch": max(mismatches) if mismatches else None,
    }


def memory_summary(run):
    """Static-memory columns over the run's ``memory`` events (one
    predicted-only event per Executor compile, re-journaled with the
    executable's ``memory_analysis()`` total once the lazy entry
    analysis lands): entries measured, predicted/measured byte lists,
    and the worst predicted-vs-measured drift — the number the
    analysis.memory liveness walk is accountable to. None when the run
    journaled no memory events."""
    events = [e for e in run.get("events") or []
              if e.get("kind") == "memory"]
    if not events:
        return None
    measured = [e for e in events
                if isinstance(e.get("measured_peak_bytes"), (int, float))]
    drifts = [e["drift"] for e in measured
              if isinstance(e.get("drift"), (int, float))]
    return {
        "entries": len(events),
        "measured_entries": len(measured),
        "predicted_peak_bytes": [e.get("predicted_peak_bytes")
                                 for e in measured or events],
        "measured_peak_bytes": [e.get("measured_peak_bytes")
                                for e in measured],
        "max_drift": max(drifts) if drifts else None,
    }


def gate_summary(run):
    """Perf-gate columns over the run's ``perf_gate`` events (written by
    ``tools/perf_gate.journal_gates``): entries gated, failure count,
    and the failure strings — so a donation/fusion/call-count gate
    regression rides the journal into the --diff regression gate. None
    when no gates were recorded."""
    events = [e for e in run.get("events") or []
              if e.get("kind") == "perf_gate"]
    if not events:
        return None
    failures = []
    for e in events:
        failures += list(e.get("failures") or [])
    return {"entries": len(events),
            "failed_entries": sum(1 for e in events if not e.get("passed",
                                                                 True)),
            "failures": failures}


def aot_summary(run):
    """Cold-start columns over the run's ``compile`` events' AOT
    provenance (``via``: "xla" = compiled in-process, "aot_disk" =
    hydrated from the executable cache, ``runtime.aot``): entries
    hydrated vs compiled, total deserialize time, and the compile time
    the cache avoided (each hydrated event carries the ORIGINAL
    compile's wall ms from the envelope). ``engaged`` is True when an
    AOT cache actually participated (something hydrated, or an eager
    miss-compile was published) — plain lazy-jit runs also tag
    ``via="xla"`` but stay ``engaged=False`` so the render line only
    appears for AOT runs. None when no compile event carries
    provenance."""
    events = [e for e in run.get("events") or []
              if e.get("kind") == "compile"
              and e.get("via") in ("xla", "aot_disk")]
    if not events:
        return None
    hydrated = [e for e in events if e["via"] == "aot_disk"]
    compiled = [e for e in events if e["via"] == "xla"]
    des = [e["deserialize_ms"] for e in hydrated
           if isinstance(e.get("deserialize_ms"), (int, float))]
    avoided = [e["compile_ms_avoided"] for e in hydrated
               if isinstance(e.get("compile_ms_avoided"), (int, float))]
    eager = [e for e in compiled
             if isinstance(e.get("xla_compile_ms"), (int, float))]
    return {
        "entries": len(events),
        "hydrated": len(hydrated),
        "compiled": len(compiled),
        "deserialize_ms": sum(des) if des else 0.0,
        "compile_ms_avoided": sum(avoided) if avoided else None,
        "engaged": bool(hydrated or eager),
    }


def _final_loss(run, k=5):
    """Median of the last k finite losses — robust to one noisy tail
    step."""
    tail = sorted(_finite_losses(run)[-k:])
    return tail[len(tail) // 2] if tail else None


# -- render ------------------------------------------------------------------


def render_run(run, as_json=False):
    if as_json:
        return json.dumps(run, indent=1, default=str, sort_keys=True)
    hdr = run["header"] or {}
    times = _step_times(run)
    losses = _finite_losses(run)
    lines = [
        f"run_dir      {hdr.get('run_dir', '?')}",
        f"backend      {hdr.get('backend')} x{hdr.get('ndev')} "
        f"({hdr.get('device_kind', '?')})",
        f"steps        {len(run['steps'])} "
        f"({sum(1 for s in run['steps'] if s.get('skipped'))} skipped)",
    ]
    # fused windows (steps_fused=K) journal as one record per dispatch;
    # show the optimizer-step total so a fused run reads comparably
    opt_steps = sum(int(s.get("steps_fused") or 1) for s in run["steps"])
    if opt_steps != len(run["steps"]):
        lines[-1] += f", {opt_steps} optimizer steps (fused windows)"
    if losses:
        lines.append(f"loss         first={losses[0]:.6g} "
                     f"last={losses[-1]:.6g} min={min(losses):.6g}")
    if times:
        st = sorted(times)
        lines.append(
            f"step_ms      mean={_mean(times):.3f} "
            f"p50={st[len(st) // 2]:.3f} max={st[-1]:.3f}")
    comm = _comm_bytes_per_step(run)
    if comm is not None:
        total = _comm_bytes_per_step(run, "total_bytes")
        lines.append(f"comm/step    all-reduce={comm:.4g}B "
                     f"total={total:.4g}B")
    summ = run["summary"]
    if summ:
        for k in ("goodput", "mfu", "achieved_flops_per_s",
                  "examples_per_s", "steps_per_s", "comm_share"):
            if summ.get(k) is not None:
                v = summ[k]
                lines.append(f"{k:<12} "
                             f"{v:.4g}" if isinstance(v, float) else
                             f"{k:<12} {v}")
    rsum = request_summary(run)
    if rsum:
        lines.append(
            f"requests     {rsum['requests']} "
            f"({rsum['finished']} finished, {rsum['cancelled']} "
            f"cancelled, {rsum['preemptions']} preemptions, "
            f"{rsum['output_tokens']} tokens)")
        for key, label in (("ttft_ms", "ttft_ms"), ("tpot_ms", "tpot_ms"),
                           ("e2e_ms", "e2e_ms"),
                           ("queue_ms", "queue_ms")):
            if rsum.get(f"{key}_p50") is not None:
                lines.append(
                    f"{label:<12} p50={rsum[f'{key}_p50']:.3f} "
                    f"p99={rsum[f'{key}_p99']:.3f}")
    psum = plan_summary(run)
    if psum:
        mism = psum["max_mismatch"]
        lines.append(
            f"plan         {psum['plans']} auto-parallel compile(s), "
            f"axes={psum['axes']}"
            + (f", predicted-vs-measured mismatch max={mism:.1%}"
               if mism is not None else ", unverified"))
    msum = memory_summary(run)
    if msum:
        drift = msum["max_drift"]
        lines.append(
            f"memory       {msum['entries']} entries "
            f"({msum['measured_entries']} measured)"
            + (f", predicted-vs-measured drift max={drift:.1%}"
               if drift is not None else ", unmeasured"))
    gsum = gate_summary(run)
    if gsum:
        lines.append(f"perf_gates   {gsum['entries']} entries, "
                     f"{gsum['failed_entries']} failed"
                     + (f": {'; '.join(gsum['failures'][:3])}"
                        if gsum["failures"] else ""))
    asum = aot_summary(run)
    if asum and asum["engaged"]:
        line = (f"aot          {asum['hydrated']} hydrated / "
                f"{asum['compiled']} compiled")
        if asum["hydrated"]:
            line += f", deserialize {asum['deserialize_ms']:.1f}ms"
        if asum["compile_ms_avoided"]:
            line += f", compile avoided {asum['compile_ms_avoided']:.1f}ms"
        lines.append(line)
    rtsum = router_summary(run)
    if rtsum:
        lines.append(render_router_line(rtsum))
    tsum = tenant_summary(run)
    if tsum and (tsum.get("tenants") or tsum.get("fairness")):
        lines += render_tenant_table(tsum)
    esum = elastic_summary(run)
    if esum:
        line = (f"elastic      restarts={esum['restarts']} "
                f"preemptions={esum['preemptions']} "
                f"watchdog_kills={esum['watchdog_kills']}")
        if esum.get("resume_ms_p50") is not None:
            line += (f" resume_ms p50={esum['resume_ms_p50']:.0f} "
                     f"max={esum['resume_ms_max']:.0f}")
        if esum["budget_exhausted"]:
            line += " BUDGET-EXHAUSTED"
        lines.append(line)
    kinds = {}
    for e in run["events"]:
        kinds[e.get("kind")] = kinds.get(e.get("kind"), 0) + 1
    if kinds:
        lines.append("events       " + ", ".join(
            f"{k}={n}" for k, n in sorted(kinds.items())))
    if run["anomalies"]:
        lines.append("anomalies    " + ", ".join(
            f"{a['name']}@step{a.get('step')}" for a in run["anomalies"]))
    if run["parse_errors"]:
        lines.append(f"parse_errors {len(run['parse_errors'])} "
                     "(torn tail line from a crashed writer?)")
    return "\n".join(lines)


# -- diff (the regression gate) ----------------------------------------------


def diff_runs(base, new,
              step_time_threshold=DEFAULT_STEP_TIME_THRESHOLD,
              loss_threshold=DEFAULT_LOSS_THRESHOLD,
              comm_threshold=DEFAULT_COMM_THRESHOLD,
              queue_share_threshold=DEFAULT_QUEUE_SHARE_THRESHOLD,
              fairness_drift_threshold=DEFAULT_FAIRNESS_DRIFT_THRESHOLD):
    """Compare two loaded runs; regression flags flip when NEW is worse
    than BASE beyond the thresholds. Returns a plain-data report."""
    bt, nt = _mean(_step_times(base)), _mean(_step_times(new))
    bl, nl = _final_loss(base), _final_loss(new)
    bc, nc = _comm_bytes_per_step(base), _comm_bytes_per_step(new)
    out = {
        "base_mean_step_ms": bt, "new_mean_step_ms": nt,
        "step_time_ratio": (nt / bt if bt and nt else None),
        "step_time_regression": bool(
            bt and nt and nt > bt * (1.0 + step_time_threshold)),
        "base_final_loss": bl, "new_final_loss": nl,
        "loss_regression": False,
        "base_ar_bytes_per_step": bc, "new_ar_bytes_per_step": nc,
        "comm_ratio": (nc / bc if bc and nc else None),
        # a step suddenly moving >10% more all-reduce bytes is a
        # sharding/partitioner regression even when wall time hides it
        # (e.g. a bigger overlap window) — gate it like throughput.
        # A zero-all-reduce base (e.g. all-gather/reduce-scatter-only
        # TP) regressing to ANY all-reduce is the starkest case, so 0
        # is a valid baseline here, unlike step time
        "comm_regression": bool(
            bc is not None and nc is not None and
            (nc > bc * (1.0 + comm_threshold) if bc else nc > 0)),
        "base_comm_share": (base["summary"] or {}).get("comm_share"),
        "new_comm_share": (new["summary"] or {}).get("comm_share"),
        "base_anomalies": len(base["anomalies"]),
        "new_anomalies": len(new["anomalies"]),
    }
    # perf-gate fold (tools/perf_gate.journal_gates events): NEW failing
    # more structural gates than BASE — donation lost, scan unrolled,
    # call counts blown — is a regression even when wall time hides it
    bg, ng = gate_summary(base), gate_summary(new)
    bfail = (bg or {}).get("failed_entries", 0)
    nfail = (ng or {}).get("failed_entries", 0)
    out["base_gate_failures"] = bfail if bg else None
    out["new_gate_failures"] = nfail if ng else None
    out["gate_regression"] = bool(ng and nfail > bfail)
    if out["gate_regression"]:
        out["gate_failure_detail"] = (ng or {}).get("failures")
    # auto-parallel plan-mismatch column (fleet planner accountability):
    # NEW's cost model drifting >threshold off the HLO-measured bytes —
    # and off whatever BASE achieved — means the planner is choosing
    # layouts on wrong numbers, a regression even when this run's wall
    # time looks fine
    bp, np_ = plan_summary(base), plan_summary(new)
    bmis = (bp or {}).get("max_mismatch")
    nmis = (np_ or {}).get("max_mismatch")
    out["base_plan_mismatch"] = bmis
    out["new_plan_mismatch"] = nmis
    out["plan_regression"] = bool(
        nmis is not None and nmis > DEFAULT_PLAN_MISMATCH_THRESHOLD and
        (bmis is None or nmis > bmis))
    # static-memory drift (analysis.memory vs memory_analysis()): NEW's
    # peak-HBM prediction drifting >15% off the executable's own number
    # — and off whatever BASE achieved — means the planner's
    # activation-memory term (and its hbm_budget rejections) run on
    # wrong bytes, a regression even when this run's wall time is fine
    bm, nm = memory_summary(base), memory_summary(new)
    bmd = (bm or {}).get("max_drift")
    nmd = (nm or {}).get("max_drift")
    out["base_memory_drift"] = bmd
    out["new_memory_drift"] = nmd
    out["memory_regression"] = bool(
        nmd is not None and nmd > DEFAULT_MEMORY_DRIFT_THRESHOLD and
        (bmd is None or nmd > bmd))
    # AOT cold-start fold (runtime.aot provenance on compile events):
    # BASE warm-started from the executable cache but NEW compiles
    # more entries from scratch — a replica's cold start regressed
    # (cache key drifted, serialization broke, warmup stopped shipping)
    # even when this run's wall time hides it behind lazy compiles
    ba, na = aot_summary(base), aot_summary(new)
    out["base_aot_hydrated"] = (ba or {}).get("hydrated")
    out["new_aot_hydrated"] = (na or {}).get("hydrated")
    # NEW journaling no provenance at all reads as every base-hydrated
    # entry gone cold (base is the older format only when it never
    # hydrated, and then the gate is off anyway)
    new_compiled = na["compiled"] if na else \
        (ba["hydrated"] if ba else 0)
    out["aot_regression"] = bool(
        ba and ba["hydrated"] and new_compiled > ba["compiled"])
    # serving queue-share fold (reqtrace attribution signal): the
    # fraction of fleet TTFT spent in the arrival->admit queue growing
    # by more than the threshold (ABSOLUTE points) means latency
    # shifted into queueing — an admission/dispatch regression even
    # when the p99 TTFT column alone can't say WHERE the time went
    brs, nrs = request_summary(base), request_summary(new)
    bqs = (brs or {}).get("queue_share")
    nqs = (nrs or {}).get("queue_share")
    out["base_queue_share"] = bqs
    out["new_queue_share"] = nqs
    out["queue_share_regression"] = bool(
        nqs is not None and
        nqs > (bqs or 0.0) + queue_share_threshold)
    # fairness-drift fold (obs.usage fairness audit over the router's
    # tenant.summary truth): NEW's worst |served-share - weight-share|
    # exceeding the absolute threshold — and whatever drift BASE ran at
    # — means the weighted scheduler stopped honoring the configured
    # shares (a tenant is being starved or hogging), a regression even
    # when every aggregate latency column is clean. The
    # worse-than-base clause keeps A-vs-A diffs clean by construction.
    btn, ntn = tenant_summary(base), tenant_summary(new)
    bfd = ((btn or {}).get("fairness") or {}).get("max_drift")
    nfd = ((ntn or {}).get("fairness") or {}).get("max_drift")
    out["base_fairness_drift"] = bfd
    out["new_fairness_drift"] = nfd
    out["fairness_drift_regression"] = bool(
        nfd is not None and nfd > fairness_drift_threshold and
        (bfd is None or nfd > bfd))
    if out["fairness_drift_regression"]:
        out["fairness_worst_tenant"] = \
            (ntn.get("fairness") or {}).get("worst_tenant")
    if bl is not None and nl is not None:
        margin = loss_threshold * max(abs(bl), 1e-12)
        out["loss_delta"] = nl - bl
        out["loss_regression"] = bool(nl - bl > margin)
    out["regression"] = out["step_time_regression"] or \
        out["loss_regression"] or out["comm_regression"] or \
        out["gate_regression"] or out["plan_regression"] or \
        out["memory_regression"] or out["aot_regression"] or \
        out["queue_share_regression"] or \
        out["fairness_drift_regression"]
    return out


def render_diff(rep, as_json=False):
    if as_json:
        return json.dumps(rep, indent=1, default=str, sort_keys=True)

    def fmt(v):
        return f"{v:.6g}" if isinstance(v, float) else str(v)

    lines = []
    for k in ("base_mean_step_ms", "new_mean_step_ms", "step_time_ratio",
              "step_time_regression", "base_final_loss", "new_final_loss",
              "loss_delta", "loss_regression", "base_ar_bytes_per_step",
              "new_ar_bytes_per_step", "comm_ratio", "comm_regression",
              "base_comm_share", "new_comm_share",
              "base_gate_failures", "new_gate_failures",
              "gate_regression", "gate_failure_detail",
              "base_plan_mismatch", "new_plan_mismatch",
              "plan_regression",
              "base_memory_drift", "new_memory_drift",
              "memory_regression",
              "base_aot_hydrated", "new_aot_hydrated",
              "aot_regression",
              "base_queue_share", "new_queue_share",
              "queue_share_regression",
              "base_fairness_drift", "new_fairness_drift",
              "fairness_drift_regression", "fairness_worst_tenant",
              "base_anomalies", "new_anomalies", "regression"):
        if rep.get(k) is not None:
            lines.append(f"{k:<22} {fmt(rep[k])}")
    return "\n".join(lines)


# -- self-test ---------------------------------------------------------------


def _write_run(run_dir, losses, step_ms, flops=1e9, nonfinite_at=(),
               comm_bytes=None, gate_failures=(), plan_bytes=None,
               memory_bytes=None, aot=None):
    """Drive the REAL RunJournal API to produce one synthetic run."""
    from paddle_tpu.obs import journal as J

    comm = None
    if comm_bytes:
        comm = {"all_reduce_bytes": comm_bytes,
                "total_bytes": comm_bytes,
                "wire_bytes": int(comm_bytes * 1.75)}
    j = J.RunJournal(run_dir, flush_every=4, compute_flops=False)
    j.start()
    if aot is not None:
        # (hydrated, compiled) AOT-provenance compile events, the shape
        # Executor._compile writes with an executable cache active
        hyd, cmp_ = aot
        for _ in range(hyd):
            j.event("compile", uid=1, version=1, ms=2.0,
                    source="aot_disk", via="aot_disk",
                    deserialize_ms=2.0, compile_ms_avoided=40.0)
        for _ in range(cmp_):
            j.event("compile", uid=1, version=1, ms=45.0,
                    source="xla", via="xla", xla_compile_ms=40.0)
    if memory_bytes is not None:
        # one measured memory event through the real record_memory
        # path; (predicted, measured) inject the drift under test
        pred, meas = memory_bytes
        j.record_memory(predicted_bytes=pred, measured_bytes=meas,
                        entry_uid=1)
    # one perf_gate event per run (the shape journal_gates writes);
    # gate_failures injects a structural regression for the diff to flag
    j.event("perf_gate", entry_uid=1, steps_fused=None, donated=4,
            while_ops=0, fusion_ops=3, failures=list(gate_failures),
            passed=not gate_failures, compiles=1, dispatches=30)
    if plan_bytes is not None:
        # one auto-parallel plan event through the real record_plan
        # path; (predicted, measured) inject the mismatch under test
        from paddle_tpu.fleet.planner import ShardingPlan

        pred, meas = plan_bytes
        j.record_plan(ShardingPlan(
            mesh_shape=(2, 4), roles=("data", "model"),
            axes={"data": 2, "model": 4}, param_specs={}, feed_specs={},
            predicted={"wire_bytes": pred}, candidates=[],
            measured={"wire_bytes": meas}))
    for i, loss in enumerate(losses):
        if i in nonfinite_at:
            j.record_step(loss=float("nan"), step_ms=step_ms,
                          skipped=True, source="self_test")
        else:
            j.record_step(loss=loss, step_ms=step_ms, flops=flops,
                          examples=32, comm=comm, source="self_test")
    j.close()
    return j


def self_test():
    from paddle_tpu.obs import mfu

    failures = []
    mfu.set_peak_flops(2e11)  # synthetic peak so MFU is computable
    try:
        with tempfile.TemporaryDirectory() as d:
            a_dir, b_dir = os.path.join(d, "a"), os.path.join(d, "b")
            # run A: healthy — loss decays 1.0 -> ~0.1, 10ms steps,
            # 1 MiB of all-reduce per step
            _write_run(a_dir, [1.0 * (0.93 ** i) for i in range(30)],
                       step_ms=10.0, comm_bytes=1 << 20,
                       plan_bytes=(100_000, 101_000),
                       memory_bytes=(1_000_000, 980_000),
                       aot=(2, 0))
            # run B: regressed — 3x slower steps, a loss spike after
            # which the loss never recovers, a 3-step nonfinite
            # streak, and 2x the all-reduce traffic (a partitioner
            # regression the comm gate must flag)
            losses = [1.0 * (0.93 ** i) for i in range(30)]
            losses[20] = 50.0  # spike...
            for i in range(21, 30):
                losses[i] = 0.5  # ...then stuck well above run A's tail
            # run B also carries a planner whose predicted bytes drifted
            # 50% off the HLO-measured truth (plan-mismatch regression)
            # run B's static peak-HBM prediction also drifted 25% off
            # the executable's measured bytes (memory regression)
            # run B also COLD-compiles the entries run A hydrated from
            # the AOT executable cache (warm-start regression)
            _write_run(b_dir, losses, step_ms=30.0,
                       nonfinite_at=(12, 13, 14), comm_bytes=2 << 20,
                       gate_failures=("donated buffers 0 < required 4",),
                       plan_bytes=(100_000, 200_000),
                       memory_bytes=(1_000_000, 800_000),
                       aot=(0, 2))

            a, b = load_run(a_dir), load_run(b_dir)
            if a["parse_errors"] or b["parse_errors"]:
                failures.append(f"synthetic journals failed to parse: "
                                f"{a['parse_errors'] + b['parse_errors']}")
            if a["summary"] is None or not a["summary"].get("mfu"):
                failures.append("run A summary missing MFU (accounting "
                                "broke)")
            if a["summary"] and a["summary"].get("goodput") != 1.0:
                failures.append("healthy run A must have goodput 1.0, "
                                f"got {a['summary'].get('goodput')}")
            bsum = b["summary"] or {}
            if not (bsum.get("goodput") or 1.0) < 1.0:
                failures.append("run B's skipped steps must lower "
                                f"goodput, got {bsum.get('goodput')}")

            fired = {x["name"] for x in b["anomalies"]}
            for want in ("loss_spike", "nonfinite_streak"):
                if want not in fired:
                    failures.append(f"detector {want!r} did not fire on "
                                    f"the injected run-B fault (fired: "
                                    f"{sorted(fired)})")
            if {x["name"] for x in a["anomalies"]}:
                failures.append("healthy run A fired anomalies: "
                                f"{a['anomalies']}")

            rep = diff_runs(a, b)
            if not rep["step_time_regression"]:
                failures.append("diff missed the 3x step-time regression")
            if not rep["loss_regression"]:
                failures.append("diff missed the loss regression")
            if not rep["comm_regression"]:
                failures.append("diff missed the 2x all-reduce-bytes "
                                "regression")
            if rep["comm_ratio"] is None or \
                    abs(rep["comm_ratio"] - 2.0) > 1e-9:
                failures.append(f"comm_ratio {rep['comm_ratio']} != 2.0")
            if not rep["gate_regression"]:
                failures.append("diff missed the injected perf-gate "
                                "(donation) failure")
            if not rep["plan_regression"]:
                failures.append("diff missed the 50% plan predicted-vs-"
                                "measured mismatch")
            if abs((rep["new_plan_mismatch"] or 0) - 0.5) > 1e-9:
                failures.append(f"plan mismatch {rep['new_plan_mismatch']}"
                                " != hand-computed 0.5")
            if not rep["aot_regression"]:
                failures.append("diff missed the AOT warm-start "
                                "regression (base hydrated 2, new "
                                "cold-compiled 2)")
            asum = aot_summary(a)
            if not (asum and asum["hydrated"] == 2
                    and asum["compile_ms_avoided"] == 80.0):
                failures.append(f"aot_summary lost the hydration "
                                f"accounting: {asum}")
            if "aot          2 hydrated" not in render_run(a):
                failures.append("render_run lost the aot cold-start line")
            if not rep["memory_regression"]:
                failures.append("diff missed the 25% memory "
                                "predicted-vs-measured drift")
            if abs((rep["new_memory_drift"] or 0) - 0.25) > 1e-9:
                failures.append(f"memory drift {rep['new_memory_drift']}"
                                " != hand-computed 0.25 "
                                "(|1e6 - 8e5| / 8e5)")
            if "plan" not in render_run(a):
                failures.append("render_run lost the plan line")
            if "drift" not in render_run(a):
                failures.append("render_run lost the memory line")
            if "donated buffers" not in " ".join(
                    rep.get("gate_failure_detail") or ()):
                failures.append("gate_failure_detail lost the failure "
                                f"string: {rep.get('gate_failure_detail')}")
            self_rep = diff_runs(a, a)
            if self_rep["regression"]:
                failures.append(f"A-vs-A diff false-positived: {self_rep}")

        # a fleet run dir (rank_NN subdirs, no top-level journal) gets
        # the cross-rank rollup line instead of a FileNotFoundError
        from paddle_tpu.obs import journal as J2

        with tempfile.TemporaryDirectory() as d:
            for rank, ms in ((0, 10.0), (1, 20.0)):
                jj = J2.RunJournal(d, rank=rank, compute_flops=False)
                jj.start()
                for _ in range(4):
                    jj.record_step(loss=1.0, step_ms=ms)
                jj.close()
            agg = fleet_summary(d)
            if not agg or agg["nranks"] != 2:
                failures.append(f"fleet_summary missed the rank "
                                f"subdirs: {agg}")
            elif not render_fleet_line(agg).startswith(
                    "fleet        2 ranks"):
                failures.append("render_fleet_line lost the fleet line: "
                                f"{render_fleet_line(agg)}")
            if fleet_summary(os.path.join(d, "rank_00")) is not None:
                failures.append("fleet_summary false-positived on a "
                                "plain single-rank dir")

        # serving request records round-trip with EXACT percentile
        # columns (hand-computed: TTFT = 100*(i+1) ms for i in 0..9,
        # so p50 = 500 ms, p99 = 1000 ms)
        from paddle_tpu.obs import journal as J

        with tempfile.TemporaryDirectory() as d:
            j = J.RunJournal(d, compute_flops=False)
            j.start()
            for i in range(10):
                j.record_request(
                    rid=f"r{i}", state="FINISHED", arrival_t=0.0,
                    admit_t=0.01, first_token_t=0.1 * (i + 1),
                    finish_t=2.0, prompt_tokens=5, output_tokens=5,
                    pages_peak=2, preemptions=1 if i == 0 else 0)
            j.close()
            rs = request_summary(load_run(d))
            if rs is None:
                failures.append("request records did not round-trip")
            else:
                if rs["requests"] != 10 or rs["finished"] != 10:
                    failures.append(f"request counts wrong: {rs}")
                if rs["preemptions"] != 1:
                    failures.append(
                        f"preemptions {rs['preemptions']} != 1")
                if abs(rs["ttft_ms_p50"] - 500.0) > 1e-9 or \
                        abs(rs["ttft_ms_p99"] - 1000.0) > 1e-9:
                    failures.append(
                        f"ttft percentiles off hand-computed values: "
                        f"p50={rs['ttft_ms_p50']} p99={rs['ttft_ms_p99']}")
                # journal-derived TPOT: (finish - first_token)/(n-1);
                # request 0 = (2.0 - 0.1)/4 s = 475 ms exactly
                tpots = [r["tpot_ms"] for r in load_run(d)["requests"]]
                if abs(min(tpots) - 250.0) > 1e-6 or \
                        abs(max(tpots) - 475.0) > 1e-6:
                    failures.append(
                        f"tpot_ms derivation off: min={min(tpots)} "
                        f"(want 250: req 9 = (2.0-1.0)/4 s) "
                        f"max={max(tpots)} (want 475)")
                # queue_ms = (admit - arrival) = 10 ms on EVERY record,
                # so both percentiles are exactly 10.0; queue_share =
                # sum(queue)/sum(ttft) = 100/5500 = 1/55
                if rs.get("queue_ms_p50") != 10.0 or \
                        rs.get("queue_ms_p99") != 10.0:
                    failures.append(
                        f"queue_ms percentiles off hand-computed 10.0: "
                        f"p50={rs.get('queue_ms_p50')} "
                        f"p99={rs.get('queue_ms_p99')}")
                if abs((rs.get("queue_share") or 0) - 100.0 / 5500.0) \
                        > 1e-12:
                    failures.append(
                        f"queue_share {rs.get('queue_share')} != "
                        "hand-computed 100/5500")
                if "queue_ms" not in render_run(load_run(d)):
                    failures.append("render_run lost the queue_ms line")

        # the queue-share regression gate: BASE serves with 10% of TTFT
        # queued, NEW with 80% (same p99 TTFT class — only the
        # attribution shifted into queueing); the diff must flag it,
        # and NEW-vs-NEW must stay clean
        with tempfile.TemporaryDirectory() as d:
            qa, qb = os.path.join(d, "qa"), os.path.join(d, "qb")
            for path, admit in ((qa, 0.01), (qb, 0.08)):
                j = J.RunJournal(path, compute_flops=False)
                j.start()
                for i in range(8):
                    j.record_request(
                        rid=f"q{i}", state="FINISHED", arrival_t=0.0,
                        admit_t=admit, first_token_t=0.1, finish_t=0.2,
                        prompt_tokens=4, output_tokens=4)
                j.close()
            qrep = diff_runs(load_run(qa), load_run(qb))
            if not qrep["queue_share_regression"]:
                failures.append(
                    "diff missed the queue-share shift (base 10% -> "
                    f"new 80% of TTFT queued): {qrep}")
            if abs((qrep["base_queue_share"] or 0) - 0.1) > 1e-9 or \
                    abs((qrep["new_queue_share"] or 0) - 0.8) > 1e-9:
                failures.append(
                    f"queue shares off hand-computed 0.1/0.8: "
                    f"{qrep['base_queue_share']}/"
                    f"{qrep['new_queue_share']}")
            if not qrep["regression"]:
                failures.append("queue-share regression did not fold "
                                "into the top-level regression flag")
            qself = diff_runs(load_run(qb), load_run(qb))
            if qself["regression"]:
                failures.append(
                    f"NEW-vs-NEW queue diff false-positived: {qself}")

        # serve-router events round-trip into the router line (the
        # hand-computed 2-replica fixture: 9 dispatched = 8 arrivals +
        # 1 requeued re-dispatch, tenant shares 0.75/0.25)
        with tempfile.TemporaryDirectory() as d:
            j = J.RunJournal(d, compute_flops=False)
            j.start()
            j.event("router.reject", rid="r9", tenant="a",
                    reason="oversize")
            j.event("router.requeue", replica=1, reason="exit",
                    rids=["r3"])
            j.event("router.scale", direction="up", replica=2,
                    replicas=3)
            j.event("router.summary", dispatched=9, requeued=1,
                    rejected=1, completed=8, replicas=3, scale_ups=1,
                    scale_downs=0, tenants={"a": 0.75, "b": 0.25},
                    ttft_p99_ms=123.5)
            j.close()
            rsum = router_summary(load_run(d))
            if rsum is None:
                failures.append("router events did not round-trip")
            elif rsum["dispatched"] != 9 or rsum["requeued"] != 1 or \
                    rsum["requeue_events"] != 1 or \
                    rsum["scale_events"] != 1 or \
                    rsum["reject_events"] != 1 or \
                    rsum["tenants"] != {"a": 0.75, "b": 0.25}:
                failures.append(f"router_summary columns wrong: {rsum}")
            else:
                line = render_router_line(rsum)
                for want in ("dispatched=9", "requeued=1", "a:0.75",
                             "ttft_p99=123.5ms"):
                    if want not in line:
                        failures.append(
                            f"router render line lost {want!r}: {line}")

        # the fairness-drift regression gate: BASE serves tenants a/b
        # exactly at their weight shares, NEW serves weight-0.25 tenant
        # a at DOUBLE its entitlement (share 0.5 — the 2x violation) so
        # max_drift = 0.25 > the 0.2 default; the diff must flag it,
        # with the worst tenant attributed, and A-vs-A must stay clean
        with tempfile.TemporaryDirectory() as d:
            fa, fb = os.path.join(d, "fa"), os.path.join(d, "fb")
            for path, share_a in ((fa, 0.25), (fb, 0.5)):
                j = J.RunJournal(path, compute_flops=False)
                j.start()
                j.record_request(
                    rid="t0", state="FINISHED", tenant="a",
                    arrival_t=0.0, admit_t=0.01, first_token_t=0.1,
                    finish_t=0.2, prompt_tokens=4, output_tokens=4,
                    device_ns=2_000_000, page_ns=5_000_000)
                j.event(
                    "tenant.summary", served_total=100,
                    tenants={
                        "a": {"share": share_a, "weight_share": 0.25,
                              "served_tokens": 100 * share_a},
                        "b": {"share": 1.0 - share_a,
                              "weight_share": 0.75,
                              "served_tokens": 100 * (1 - share_a)}})
                j.close()
            frep = diff_runs(load_run(fa), load_run(fb))
            if not frep["fairness_drift_regression"]:
                failures.append(
                    "diff missed the 2x fairness violation (weight "
                    f"share 0.25 served at 0.5): {frep}")
            if abs((frep["new_fairness_drift"] or 0) - 0.25) > 1e-12:
                failures.append(
                    f"fairness drift {frep['new_fairness_drift']} != "
                    "hand-computed 0.25")
            if frep.get("fairness_worst_tenant") not in ("a", "b"):
                failures.append(
                    "fairness regression lost the worst tenant: "
                    f"{frep.get('fairness_worst_tenant')}")
            if not frep["regression"]:
                failures.append("fairness drift did not fold into the "
                                "top-level regression flag")
            fself = diff_runs(load_run(fb), load_run(fb))
            if fself["regression"]:
                failures.append(
                    f"A-vs-A fairness diff false-positived: {fself}")
            rendered = render_run(load_run(fb))
            if "tenant a" not in rendered or "DRIFT" not in rendered:
                failures.append(
                    "render_run lost the tenant chargeback/fairness "
                    f"lines:\n{rendered}")
            if "dev_ms=2.000" not in rendered or \
                    "page_s=0.005" not in rendered:
                failures.append(
                    "tenant table lost the device/page attribution "
                    f"columns:\n{rendered}")
    finally:
        mfu.set_peak_flops(None)

    for line in failures:
        print(f"  FAILED — {line}")
    if failures:
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: journal round-trip, MFU/goodput summary, "
          "loss_spike + nonfinite_streak detectors, the diff gate "
          "flagged the injected step-time, loss, all-reduce-bytes, "
          "perf-gate (lost donation), plan-mismatch, memory-drift AND "
          "AOT warm-start "
          "regressions (and only them), serving request records "
          "round-trip with hand-computed TTFT/TPOT/queue percentile "
          "columns and the diff flagged the injected queue-share "
          "shift, "
          "rank-subdir run dirs render the fleet rollup line, "
          "serve-router events render the dispatched/requeued/tenant-"
          "share line, and the diff flagged the injected 2x fairness "
          "violation (A-vs-A clean)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="run dir (render) or two run dirs with --diff")
    ap.add_argument("--diff", action="store_true",
                    help="diff two runs; exit 1 on regression")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--step-time-threshold", type=float,
                    default=DEFAULT_STEP_TIME_THRESHOLD,
                    help="allowed relative mean-step-time growth")
    ap.add_argument("--loss-threshold", type=float,
                    default=DEFAULT_LOSS_THRESHOLD,
                    help="allowed relative final-loss growth")
    ap.add_argument("--comm-threshold", type=float,
                    default=DEFAULT_COMM_THRESHOLD,
                    help="allowed relative all-reduce-bytes/step growth")
    ap.add_argument("--queue-share-threshold", type=float,
                    default=DEFAULT_QUEUE_SHARE_THRESHOLD,
                    help="allowed absolute growth in the serving "
                         "queue share of TTFT")
    ap.add_argument("--fairness-drift-threshold", type=float,
                    default=DEFAULT_FAIRNESS_DRIFT_THRESHOLD,
                    help="allowed absolute |served share - weight "
                         "share| fairness drift per tenant")
    ap.add_argument("--self-test", action="store_true",
                    help="synthetic 2-run pair: diff must flag the "
                         "injected regression, detectors must fire")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two run dirs")
        rep = diff_runs(load_run(args.paths[0]), load_run(args.paths[1]),
                        step_time_threshold=args.step_time_threshold,
                        loss_threshold=args.loss_threshold,
                        comm_threshold=args.comm_threshold,
                        queue_share_threshold=args.queue_share_threshold,
                        fairness_drift_threshold=args
                        .fairness_drift_threshold)
        print(render_diff(rep, as_json=args.json))
        return 1 if rep["regression"] else 0
    if len(args.paths) != 1:
        ap.error("need one run dir (or --diff A B / --self-test)")
    path = args.paths[0]
    try:
        run = load_run(path)
    except FileNotFoundError:
        # a fleet run dir has no top-level journal: the supervisor's
        # record (when present) is the closest single-run view, plus
        # the cross-rank rollup line
        agg = fleet_summary(path)
        if agg is None:
            raise
        if args.json:
            print(json.dumps(agg, indent=1, default=str,
                             sort_keys=True))
            return 0
        from paddle_tpu.obs.fleet import SUPERVISOR_DIR
        sup = os.path.join(path, SUPERVISOR_DIR)
        try:
            print(render_run(load_run(sup)))
        except FileNotFoundError:
            pass
        print(render_fleet_line(agg))
        return 0
    print(render_run(run, as_json=args.json))
    if not args.json:
        agg = fleet_summary(path)
        if agg is not None:
            print(render_fleet_line(agg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
