#!/usr/bin/env python
"""serve_bench: synthetic request traces through ``paddle_tpu.serving``.

The serving scoreboard (the role MLPerf-Inference's LoadGen plays for
the Gemma-on-TPU comparison, arXiv 2605.25645): generate an open-loop
synthetic trace — Poisson arrivals, a mixed short/long prompt and
output length distribution — drive it through a ``ServeEngine`` over
the built-in ``TinyLM``, and report per-request latency percentiles
(p50/p99 TTFT and TPOT, end-to-end) plus aggregate tokens/s and
preemption/KV-pressure counters.

Usage:
    python tools/serve_bench.py                      # default trace
    python tools/serve_bench.py --requests 64 --rate 100 --json
    python tools/serve_bench.py --pages 32 --page-size 8   # pressure
    python tools/serve_bench.py --request-report 5         # tail blame
    python tools/serve_bench.py --slo '{"ttft_p99_ms": 250}'  # SLO gate
    python tools/serve_bench.py --self-test

--self-test (wired into tier-1 via tests/test_tooling.py, like the
other five CLI tools) asserts with a DETERMINISTIC clock:
- paged-vs-dense numerics: the ragged paged decode kernel matches the
  dense reference on varying lengths crossing page boundaries;
- a hand-checked scheduler trace: token-budget admission order,
  page-pressure preemption with arrival-order requeue, no starvation;
- engine output pinned token-for-token against the dense oracle while
  preemptions occur;
- latency accounting: hand-computed TTFT values from the manual clock.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ensure_cpu():
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _pctl(xs, q):
    """Shared exact-percentile definition (see tools/run_report.py —
    diverging implementations would make the two tools' p50/p99
    columns incomparable)."""
    from paddle_tpu.obs.metrics import exact_percentile

    return exact_percentile(xs, q)


def parse_tenants(spec):
    """Parse a ``--tenants`` spec: ``name:rate=R[,weight=W];...`` —
    per-tenant Poisson arrival rate (req/s, required) and fairness
    weight (default 1.0). E.g. ``a:rate=30,weight=3;b:rate=10``."""
    out = {}
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, kvs = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"empty tenant name in {spec!r}")
        d = {"rate": None, "weight": 1.0}
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, eq, v = kv.partition("=")
            if not eq or k.strip() not in d:
                raise ValueError(
                    f"bad tenant field {kv!r} (want rate=/weight=)")
            d[k.strip()] = float(v)
        if d["rate"] is None or d["rate"] <= 0:
            raise ValueError(f"tenant {name!r} needs rate= > 0")
        if d["weight"] <= 0:
            raise ValueError(f"tenant {name!r} needs weight > 0")
        out[name] = d
    if not out:
        raise ValueError(f"empty --tenants spec {spec!r}")
    return out


def make_trace(n_requests, rate, seed=0, vocab=32, short_frac=0.7,
               short_len=(3, 12), long_len=(24, 48),
               out_len=(4, 24), tenants=None):
    """Synthetic open-loop trace: Poisson arrivals (exponential
    inter-arrival at ``rate`` req/s), 70/30 short/long prompt mix,
    uniform output lengths — deterministic in ``seed``.

    With ``tenants`` (a :func:`parse_tenants` dict) each tenant gets
    its OWN Poisson stream at its own ``rate`` (the global ``rate`` is
    ignored), ``n_requests`` split across tenants proportional to rate
    (largest-remainder, so the total is exact), and every item carries
    a ``"tenant"`` tag. The merged trace interleaves by arrival time —
    deterministic in ``seed`` and the tenant names."""
    import numpy as np

    if tenants:
        names = sorted(tenants)
        total_rate = sum(tenants[t]["rate"] for t in names)
        exact = {t: n_requests * tenants[t]["rate"] / total_rate
                 for t in names}
        counts = {t: int(exact[t]) for t in names}
        for t in sorted(names, key=lambda t: (exact[t] - counts[t], t),
                        reverse=True):
            if sum(counts.values()) >= n_requests:
                break
            counts[t] += 1
        trace = []
        for i, t in enumerate(names):
            sub = make_trace(counts[t], tenants[t]["rate"],
                             seed=seed + 7919 * (i + 1), vocab=vocab,
                             short_frac=short_frac,
                             short_len=short_len, long_len=long_len,
                             out_len=out_len)
            for item in sub:
                item["tenant"] = t
            trace += sub
        trace.sort(key=lambda r: r["arrival"])
        return trace
    rng = np.random.RandomState(seed)
    t = 0.0
    trace = []
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate))
        lo, hi = short_len if rng.rand() < short_frac else long_len
        plen = int(rng.randint(lo, hi + 1))
        trace.append({
            "arrival": t,
            "prompt": [int(x) for x in rng.randint(0, vocab, plen)],
            "max_new_tokens": int(rng.randint(out_len[0],
                                              out_len[1] + 1)),
        })
    return trace


def _tenant_extras(rows, tenants):
    """Per-tenant latency/share extras from finished-request rows
    ``(tenant, tokens, ttft_ms, e2e_ms)``: served-token share vs the
    configured weight share, per-tenant p50/p99, and the headline
    ``tenant_share_err`` = max |share - weight_share| (0.0 with < 2
    tenants — nothing to be unfair between)."""
    wsum = sum(d["weight"] for d in tenants.values())
    by_t = {t: {"finished": 0, "tokens": 0, "_ttft": [], "_e2e": []}
            for t in tenants}
    for tenant, tokens, ttft_ms, e2e_ms in rows:
        d = by_t.setdefault(tenant, {"finished": 0, "tokens": 0,
                                     "_ttft": [], "_e2e": []})
        d["finished"] += 1
        d["tokens"] += int(tokens)
        if ttft_ms is not None:
            d["_ttft"].append(ttft_ms)
        if e2e_ms is not None:
            d["_e2e"].append(e2e_ms)
    total = sum(d["tokens"] for d in by_t.values())
    out, err = {}, 0.0
    for t in sorted(by_t):
        d = by_t[t]
        share = d["tokens"] / total if total else 0.0
        wshare = tenants[t]["weight"] / wsum if t in tenants and wsum \
            else 0.0
        if len(by_t) >= 2 and total:
            err = max(err, abs(share - wshare))
        out[t] = {
            "finished": d["finished"], "tokens": d["tokens"],
            "share": share, "weight_share": wshare,
            "ttft_p50_ms": _pctl(d["_ttft"], 50),
            "ttft_p99_ms": _pctl(d["_ttft"], 99),
            "e2e_p50_ms": _pctl(d["_e2e"], 50),
            "e2e_p99_ms": _pctl(d["_e2e"], 99),
        }
    return out, err


def run_bench(n_requests=32, rate=50.0, pages=128, page_size=8,
              seed=0, token_budget=512, heads=2, head_dim=8,
              vocab=32, tenants=None):
    """Drive the trace through a real-clock engine; returns the report
    dict. Open loop: requests are submitted when their arrival time
    passes, whether or not the engine kept up (so TTFT includes queue
    time under overload, as in a real serving SLO). ``tenants`` (a
    :func:`parse_tenants` dict) tags the trace per tenant and adds the
    per-tenant share/latency extras to the report."""
    from paddle_tpu.serving import (PagedKVCache, Scheduler, ServeEngine,
                                    TinyLM)

    trace = make_trace(n_requests, rate, seed=seed, vocab=vocab,
                       tenants=tenants)
    model = TinyLM(vocab_size=vocab, num_heads=heads, head_dim=head_dim,
                   seed=seed)
    cache = PagedKVCache(pages, page_size, heads, head_dim)
    eng = ServeEngine(model, cache,
                      scheduler=Scheduler(cache,
                                          token_budget=token_budget))
    t_start = time.monotonic()
    pending = list(trace)
    rejected = 0
    while pending or not eng.scheduler.idle:
        now = time.monotonic() - t_start
        while pending and pending[0]["arrival"] <= now:
            r = pending.pop(0)
            try:
                eng.submit(r["prompt"],
                           max_new_tokens=r["max_new_tokens"],
                           arrival_t=t_start + r["arrival"],
                           tenant=r.get("tenant"))
            except ValueError:
                # admission control: a request that can NEVER fit the
                # pool is refused at the door, not served truncated
                rejected += 1
        if eng.scheduler.idle:
            if pending:  # engine ahead of the trace: wait for arrival
                time.sleep(max(0.0, pending[0]["arrival"] - now))
            continue
        if not eng.step() and not pending:
            # gridlock: queued work the pool/budget can never admit
            # and no future arrival will change that — report what
            # finished instead of busy-spinning forever
            break
    wall = time.monotonic() - t_start
    rep = _report(eng, wall, n_requests, tenants=tenants)
    rep["rejected"] = rejected
    rep["stuck"] = eng.scheduler.queue_depth
    return rep


def request_report(run_dir, k):
    """Tail-latency attribution for a journaled bench run: the K
    worst-TTFT requests with their exact phase decompositions (see
    ``paddle_tpu.obs.reqtrace``), plus the fleet-wide phase shares.
    Returns the ``tail_report`` dict (None when nothing is
    attributable — e.g. the run finished no requests)."""
    from paddle_tpu.obs import reqtrace

    try:
        tls = reqtrace.assemble_run(run_dir)
    except (FileNotFoundError, OSError):
        return None
    return reqtrace.tail_report(tls, key="ttft_ms", k=k)


def _print_request_report(rep):
    from paddle_tpu.obs.reqtrace import PHASES

    if rep is None:
        print("request report: no attributable requests")
        return
    # column labels for PHASES, in canonical order
    short = ("rate", "router", "requeue", "sched", "prefill",
             "preempt", "decode")
    print(f"worst {len(rep['worst'])} of {rep['requests']} requests "
          "by TTFT (phase ms):")
    print("  " + "rid".ljust(10) + "".join(
        c.rjust(12) for c in ("ttft", "e2e") + tuple(short)))
    for w in rep["worst"]:
        row = [w["ttft_ms"], w["e2e_ms"]] + [w[p] for p in PHASES]
        print("  " + str(w["rid"]).ljust(10)
              + "".join(f"{v:12.3f}" for v in row))
    share = rep["phase_share"]
    print("  phase share: " + "  ".join(
        f"{s}={share[p]:.1%}" for s, p in zip(short, PHASES)
        if share[p] > 0))


def _report(eng, wall_s, n_requests, tenants=None):
    fin = eng.finished
    ttft = [(r.first_token_t - r.arrival_t) * 1e3 for r in fin
            if r.first_token_t is not None]
    tpot = [(r.finish_t - r.first_token_t) * 1e3 / (len(r.generated) - 1)
            for r in fin if len(r.generated) > 1]
    e2e = [(r.finish_t - r.arrival_t) * 1e3 for r in fin]
    tokens = sum(len(r.generated) for r in fin)
    st = eng.cache.stats()
    rep = {
        "requests": n_requests, "finished": len(fin),
        "tokens": tokens, "wall_s": wall_s,
        "tokens_per_sec": tokens / wall_s if wall_s else None,
        "ttft_p50_ms": _pctl(ttft, 50), "ttft_p99_ms": _pctl(ttft, 99),
        "tpot_p50_ms": _pctl(tpot, 50), "tpot_p99_ms": _pctl(tpot, 99),
        "e2e_p50_ms": _pctl(e2e, 50), "e2e_p99_ms": _pctl(e2e, 99),
        "preemptions": eng.scheduler.preemptions,
        "engine_steps": eng.stats()["steps"],
        "kv_used_pages": st["used_pages"],
        "kv_fragmentation": st["fragmentation"],
    }
    if tenants:
        rows = [(r.tenant or "default", len(r.generated),
                 None if r.first_token_t is None
                 else (r.first_token_t - r.arrival_t) * 1e3,
                 None if r.finish_t is None
                 else (r.finish_t - r.arrival_t) * 1e3)
                for r in fin]
        rep["tenants"], rep["tenant_share_err"] = \
            _tenant_extras(rows, tenants)
    return rep


# -- fleet mode (--replicas N) ------------------------------------------------


def run_bench_fleet(n_requests=32, rate=50.0, replicas=2, pages=128,
                    page_size=8, seed=0, token_budget=512, heads=2,
                    head_dim=8, vocab=32, keep_router=False,
                    trace_kw=None, aot_cache_dir=None, tenants=None):
    """The same open-loop Poisson trace through a ``serving.fleet``
    Router over N in-process replicas: aggregate p50/p99 TTFT/TPOT
    across the whole fleet, a per-replica breakdown, and
    ``router_overhead_ms`` — wall time spent inside the router's
    dispatch/poll/health decisions (NOT engine compute), the dispatch-
    layer tax the single-engine bench can't see. ``tenants`` (a
    :func:`parse_tenants` dict) additionally configures the router's
    weighted-deficit fairness (``TenantPolicy(weight=...)``), tags
    submissions per tenant, and adds the per-tenant share/latency
    extras to the report."""
    from paddle_tpu.serving.fleet import (ReplicaPool, ReplicaSpec,
                                          Router, TenantPolicy)

    trace = make_trace(n_requests, rate, seed=seed, vocab=vocab,
                       tenants=tenants, **(trace_kw or {}))
    # an executable cache dir makes replicas 2..N hydrate the buckets
    # replica 1 compiled (warm=False: lazily, only buckets the trace
    # actually reaches)
    spec = ReplicaSpec(vocab_size=vocab, num_heads=heads,
                       head_dim=head_dim, seed=seed, pages=pages,
                       page_size=page_size, token_budget=token_budget,
                       aot_cache_dir=aot_cache_dir, warm=False)
    pool = ReplicaPool(spec, replicas=replicas, mode="local")
    router = Router(pool, tenants=None if not tenants else {
        t: TenantPolicy(weight=d["weight"])
        for t, d in tenants.items()})
    t_start = time.monotonic()
    pending = list(trace)
    rejected = 0
    router_s = 0.0
    while True:
        now = time.monotonic() - t_start
        while pending and pending[0]["arrival"] <= now:
            r = pending.pop(0)
            try:
                router.submit(r["prompt"],
                              max_new_tokens=r["max_new_tokens"],
                              arrival_t=t_start + r["arrival"],
                              tenant=r.get("tenant"))
            except ValueError:
                rejected += 1
        if not router.inflight and not router.queue_depth:
            if not pending:
                break
            time.sleep(max(0.0, pending[0]["arrival"] - now))
            continue
        t0 = time.perf_counter()
        router.check_replicas()
        router.dispatch()
        router_s += time.perf_counter() - t0
        pumped = pool.pump()
        t0 = time.perf_counter()
        router.poll()
        router_s += time.perf_counter() - t0
        if not pumped and not router.inflight and not pending:
            break  # gridlock: nothing dispatchable, nothing arriving
    wall = time.monotonic() - t_start
    rep = _fleet_report(router, wall, n_requests, tenants=tenants)
    rep["rejected"] = rejected
    rep["stuck"] = router.queue_depth
    rep["router_overhead_ms"] = router_s * 1e3
    if keep_router:
        return rep, router
    router.close()
    return rep


def _fleet_report(router, wall_s, n_requests, tenants=None):
    fin = [r for r in router.completed if r.state == "FINISHED"]
    ttft = [(r.first_token_t - r.arrival_t) * 1e3 for r in fin
            if r.first_token_t is not None]
    tpot = [(r.finish_t - r.first_token_t) * 1e3 / (len(r.tokens) - 1)
            for r in fin if len(r.tokens) > 1
            and r.first_token_t is not None]
    e2e = [(r.finish_t - r.arrival_t) * 1e3 for r in fin
           if r.finish_t is not None]
    tokens = sum(len(r.tokens) for r in fin)
    st = router.stats()
    per_replica = {}
    for r in fin:
        d = per_replica.setdefault(r.replica_id, {
            "finished": 0, "tokens": 0, "preemptions": 0,
            "requeues": 0})
        d["finished"] += 1
        d["tokens"] += len(r.tokens)
        d["preemptions"] += r.preemptions
        d["requeues"] += r.requeues
    rep = {
        "requests": n_requests, "finished": len(fin),
        "replicas": st["replicas"], "tokens": tokens, "wall_s": wall_s,
        "tokens_per_sec": tokens / wall_s if wall_s else None,
        "ttft_p50_ms": _pctl(ttft, 50), "ttft_p99_ms": _pctl(ttft, 99),
        "tpot_p50_ms": _pctl(tpot, 50), "tpot_p99_ms": _pctl(tpot, 99),
        "e2e_p50_ms": _pctl(e2e, 50), "e2e_p99_ms": _pctl(e2e, 99),
        "dispatched": st["dispatched"], "requeued": st["requeued"],
        "per_replica": per_replica,
    }
    if tenants:
        rows = [(r.tenant or "default", len(r.tokens),
                 None if r.first_token_t is None
                 else (r.first_token_t - r.arrival_t) * 1e3,
                 None if r.finish_t is None
                 else (r.finish_t - r.arrival_t) * 1e3)
                for r in fin]
        rep["tenants"], rep["tenant_share_err"] = \
            _tenant_extras(rows, tenants)
    return rep


# -- self-test ----------------------------------------------------------------


def _check(failures, cond, msg):
    if not cond:
        failures.append(msg)


def _test_paged_vs_dense(failures):
    """Kernel numerics: ragged lengths (1 token; exactly one page; a
    page-boundary crossing; multiple pages) through a SHUFFLED page
    assignment must match the dense masked reference in fp32."""
    import numpy as np
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.paged_attention import (
        dense_decode_reference, paged_decode_attention)

    rng = np.random.RandomState(0)
    B, H, D, page, P, maxp = 4, 2, 16, 8, 32, 5
    lengths = np.array([1, 8, 9, 37], np.int32)
    L = maxp * page
    k_dense = rng.randn(B, L, H, D).astype(np.float32)
    v_dense = rng.randn(B, L, H, D).astype(np.float32)
    q = rng.randn(B, H, D).astype(np.float32)
    k_pages = np.zeros((P, page, H, D), np.float32)
    v_pages = np.zeros((P, page, H, D), np.float32)
    table = np.zeros((B, maxp), np.int32)
    free = list(rng.permutation(np.arange(1, P)))
    for b in range(B):
        for p in range(-(-int(lengths[b]) // page)):
            pid = free.pop()
            table[b, p] = pid
            lo, hi = p * page, min((p + 1) * page, int(lengths[b]))
            k_pages[pid, :hi - lo] = k_dense[b, lo:hi]
            v_pages[pid, :hi - lo] = v_dense[b, lo:hi]
    out = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(table), jnp.asarray(lengths), interpret=True)
    ref = dense_decode_reference(jnp.asarray(q), jnp.asarray(k_dense),
                                 jnp.asarray(v_dense),
                                 jnp.asarray(lengths))
    err = float(jnp.abs(out - ref).max())
    _check(failures, err < 2e-5,
           f"paged kernel diverges from dense reference: max|Δ|={err}")


def _test_scheduler_trace(failures):
    """Hand-checked trace. Pool: 4 pages of 4 (3 usable). Budget 8.
    Three 4-token prompts arriving at t=0,1,2 must admit exactly
    [r1, r2] (budget exhausted), leave r3 queued on page headroom,
    then under decode growth r2 must self-preempt (r1, the oldest, is
    never a victim), requeue AHEAD of r3 (original arrival), and the
    pool must balance to zero."""
    from paddle_tpu.serving import (ManualClock, PagedKVCache, Request,
                                    Scheduler)
    from paddle_tpu.serving.kv_cache import CachePressureError

    clock = ManualClock()
    cache = PagedKVCache(4, 4, 1, 1)
    sched = Scheduler(cache, token_budget=8, clock=clock)
    reqs = []
    for i in range(3):
        clock.now = float(i)
        reqs.append(sched.submit(Request(prompt=[1, 2, 3, 4],
                                         rid=f"r{i + 1}")))
    r1, r2, r3 = reqs
    clock.now = 3.0
    b1 = sched.schedule()
    _check(failures, [r.rid for r in b1.prefills] == ["r1", "r2"],
           f"admission order {[r.rid for r in b1.prefills]} != [r1, r2]")
    _check(failures, not b1.decodes, "phantom decodes in first batch")
    _check(failures, r1.admit_t == 3.0 and r2.admit_t == 3.0,
           f"admit timestamps not from the injected clock: "
           f"{r1.admit_t}, {r2.admit_t}")
    _check(failures, sched.queue_depth == 1 and r3.state == "QUEUED",
           "r3 must stay queued (token budget spent, no page headroom)")
    # decode growth: r1 extends 4->5 tokens (takes the last free page);
    # r2's extend then hits pressure, and with r1 (oldest) protected
    # there is no victim — preempt_for returns None, r2 self-preempts
    sched.extend(r1, 1)
    hit_pressure = False
    try:
        sched.extend(r2, 1)
    except CachePressureError:
        hit_pressure = True
    _check(failures, hit_pressure, "r2's extend must hit page pressure")
    _check(failures, sched.preempt_for(r2) is None,
           "preempt_for(r2) must refuse to preempt the oldest (r1)")
    clock.now = 4.0
    sched.preempt(r2)
    _check(failures, r2.state == "PREEMPTED" and r2.preemptions == 1,
           f"r2 not preempted cleanly: {r2.state}, {r2.preemptions}")
    _check(failures, [r.rid for r in sched._queue] == ["r2", "r3"],
           f"requeue must keep arrival order, got "
           f"{[r.rid for r in sched._queue]}")
    b2 = sched.schedule()
    _check(failures, [r.rid for r in b2.decodes] == ["r1"],
           "only r1 should decode under pressure")
    _check(failures, not b2.prefills,
           "r2 cannot re-admit while r1 holds the pool")
    sched.finish(r1)
    b3 = sched.schedule()
    # r1's 2 pages return: budget 8 now admits BOTH 4-token prompts,
    # preempted r2 strictly before later-arrived r3
    _check(failures, [r.rid for r in b3.prefills] == ["r2", "r3"],
           f"re-admission must be [r2, r3] (arrival order, preempted "
           f"r2 first), got {[r.rid for r in b3.prefills]}")
    sched.finish(r2)
    sched.finish(r3)
    st = cache.stats()
    _check(failures, st["used_pages"] == 0 and cache.verify(),
           f"pool leaked pages after teardown: {st}")


def _test_engine_vs_oracle(failures):
    """End-to-end: a pressured engine (preemptions forced) must emit
    exactly the dense oracle's greedy tokens, with hand-computed TTFT
    from the manual clock and a balanced pool after a mid-flight
    cancellation."""
    import numpy as np

    from paddle_tpu.serving import (ManualClock, PagedKVCache, Scheduler,
                                    ServeEngine, TinyLM)

    model = TinyLM(vocab_size=32, num_heads=2, head_dim=8, seed=0)
    cache = PagedKVCache(6, 4, 2, 8, max_seq_len=16)
    clock = ManualClock()
    eng = ServeEngine(model, cache,
                      scheduler=Scheduler(cache, token_budget=64,
                                          clock=clock))
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(0, 32, 5)) for _ in range(3)]
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    # a 4th request cancelled mid-flight: pages must still balance
    doomed = eng.submit(list(rng.randint(0, 32, 5)), max_new_tokens=8)
    clock.advance(1.0)
    eng.step()
    eng.cancel(doomed)
    eng.run(max_steps=300)
    _check(failures, len(eng.finished) == 3,
           f"{len(eng.finished)}/3 requests finished")
    for r, p in zip(reqs, prompts):
        ref = model.reference_generate(p, 8)
        _check(failures, r.generated == ref,
               f"{r.rid} tokens {r.generated} != oracle {ref} "
               f"(preemptions={r.preemptions})")
    _check(failures, eng.scheduler.preemptions >= 1,
           "pool was sized to force >=1 preemption; got none "
           "(pressure path untested)")
    st = cache.stats()
    _check(failures, st["used_pages"] == 0 and cache.verify(),
           f"pool leaked after cancel+finish: {st}")
    # TTFT = first_token_t - arrival_t on the injected clock: every
    # request arrives at t=0.0 and the ones admitted in the FIRST step
    # (admit_t == 1.0) emit their first token inside it, so their TTFT
    # is exactly 1.0 — and at least one request MUST match, or this
    # check would be vacuous
    checked = 0
    for r in reqs:
        if r.first_token_t is not None and r.admit_t == 1.0:
            checked += 1
            _check(failures,
                   abs((r.first_token_t - r.arrival_t) - 1.0) < 1e-12,
                   f"{r.rid} TTFT {r.first_token_t - r.arrival_t} != "
                   "1.0 on the manual clock")
    _check(failures, checked >= 1,
           "TTFT check matched no request (first-step admissions "
           "should exist) — the assertion went vacuous")


def _test_router_trace(failures):
    """Hand-checked fleet dispatch on a ManualClock: least-outstanding-
    tokens with lowest-id tie-break, weighted-deficit tenant fairness,
    and a token-bucket rate limit that holds ONE tenant back without
    blocking the other."""
    from paddle_tpu.serving import ManualClock
    from paddle_tpu.serving.fleet import (ReplicaPool, ReplicaSpec,
                                          Router, TenantPolicy)

    clock = ManualClock()
    spec = ReplicaSpec(vocab_size=32, pages=64, page_size=4,
                       max_seq_len=32, token_budget=128)
    pool = ReplicaPool(spec, replicas=2, mode="local", clock=clock)
    router = Router(pool, clock=clock, tenants={
        "a": TenantPolicy(weight=1.0),
        "b": TenantPolicy(weight=1.0),
        "lim": TenantPolicy(weight=1.0, rate=1.0, burst=4.0),
    })
    # least-loaded + tie-break: costs 8, 4, 2 -> rep0 (tie: lowest id),
    # rep1 (0 < 8), rep1 again (4 < 8)
    for plen, new in ((4, 4), (2, 2), (1, 1)):
        router.submit([1] * plen, max_new_tokens=new, tenant="a")
    pairs = router.dispatch()
    _check(failures, [p[1] for p in pairs] == [0, 1, 1],
           f"least-outstanding trace {pairs} != replicas [0, 1, 1]")
    # fairness: a floods 4 x cost-4, b queues 2 x cost-4 — deficit
    # round-robin must interleave a/b, not serve a's flood first
    clock.advance(1.0)
    a = [router.submit([1, 2], max_new_tokens=2, tenant="a",
                       rid=f"a{i}") for i in range(4)]
    b = [router.submit([3, 4], max_new_tokens=2, tenant="b",
                       rid=f"b{i}") for i in range(2)]
    order = [rid for rid, _ in router.dispatch()]
    _check(failures, order == ["b0", "b1", "a0", "a1", "a2", "a3"],
           f"fairness order {order}: b (behind on served tokens) must "
           "catch up before a's flood continues")
    # rate limit: burst 4 admits one cost-4 request; the next waits for
    # the bucket (1 token/s), while an unlimited tenant sails past
    clock.advance(1.0)
    router.submit([5, 6], max_new_tokens=2, tenant="lim", rid="l0")
    router.submit([5, 6], max_new_tokens=2, tenant="lim", rid="l1")
    router.submit([7, 8], max_new_tokens=2, tenant="a", rid="a4")
    order = [rid for rid, _ in router.dispatch()]
    _check(failures, order == ["l0", "a4"],
           f"rate-limit trace {order} != ['l0', 'a4'] (l1 must wait "
           "for the bucket, a4 must not be blocked by it)")
    _check(failures, router.queue_depth == 1,
           f"l1 should still be queued, depth={router.queue_depth}")
    clock.advance(4.0)   # bucket refills 4 tokens
    order = [rid for rid, _ in router.dispatch()]
    _check(failures, order == ["l1"],
           f"after refill {order} != ['l1']")
    # rejection mirrors ServeEngine.submit: oversize at the door
    try:
        router.submit(list(range(20)), max_new_tokens=20)
        _check(failures, False, "oversize request not rejected")
    except ValueError:
        pass
    _check(failures, router.stats()["rejected"] == 1,
           "rejection not counted in router stats")
    router.close()


def _test_tenant_trace(failures):
    """Deterministic multi-tenant trace + share math: the spec parser,
    largest-remainder count split (total exact), arrival-sorted merge,
    and hand-computed ``tenant_share_err`` from ``_tenant_extras``."""
    tn = parse_tenants("a:rate=30,weight=3;b:rate=10")
    _check(failures,
           tn == {"a": {"rate": 30.0, "weight": 3.0},
                  "b": {"rate": 10.0, "weight": 1.0}},
           f"parse_tenants mis-parsed: {tn}")
    for bad in ("", "a:weight=2", "a:rate=0", "a:rate=5,burst=1"):
        try:
            parse_tenants(bad)
            _check(failures, False,
                   f"parse_tenants accepted bad spec {bad!r}")
        except ValueError:
            pass
    trace = make_trace(8, 999.0, seed=3, tenants=tn)
    counts = {}
    for r in trace:
        counts[r["tenant"]] = counts.get(r["tenant"], 0) + 1
    _check(failures, counts == {"a": 6, "b": 2},
           f"rate-proportional split {counts} != {{'a': 6, 'b': 2}} "
           "(8 requests at 30:10)")
    _check(failures,
           all(trace[i]["arrival"] <= trace[i + 1]["arrival"]
               for i in range(len(trace) - 1)),
           "merged tenant trace not sorted by arrival")
    _check(failures, trace == make_trace(8, 999.0, seed=3, tenants=tn),
           "tenant trace not deterministic in seed")
    # hand-computed shares: a serves 60 of 100 tokens (share 0.6) vs
    # weight share 0.75, b 0.4 vs 0.25 -> share_err = 0.15 both ways
    rows = [("a", 60, 1.0, 2.0), ("b", 40, 3.0, 4.0)]
    per, err = _tenant_extras(rows, tn)
    _check(failures, abs(err - 0.15) < 1e-12,
           f"tenant_share_err {err} != hand-computed 0.15")
    _check(failures,
           per["a"]["share"] == 0.6 and per["a"]["weight_share"] == 0.75
           and per["b"]["share"] == 0.4
           and per["b"]["weight_share"] == 0.25,
           f"share math off: {per}")
    _check(failures,
           per["a"]["ttft_p99_ms"] == 1.0
           and per["b"]["e2e_p99_ms"] == 4.0,
           f"per-tenant percentiles off: {per}")
    # < 2 tenants: no counterpart to be unfair to
    _, err1 = _tenant_extras([("a", 60, 1.0, 2.0)],
                             {"a": {"rate": 1.0, "weight": 1.0}})
    _check(failures, err1 == 0.0,
           f"single-tenant share_err {err1} != 0.0")


def _test_fleet_bench_gates(failures):
    """A real 2-replica fleet run on CPU: aggregate-percentile gates,
    per-replica breakdown consistency, oracle-identical tokens, and a
    LIVE HTTP scrape of the router metrics endpoint matching
    ``router.stats()`` BITWISE."""
    import urllib.request

    from paddle_tpu.obs.export import (MetricsExporter,
                                       parse_prometheus_text)
    from paddle_tpu.serving import TinyLM

    # short prompts + bounded outputs keep the tier-1 leg to the two
    # smallest prefill buckets per replica (compile cost, not coverage,
    # is what the long tail would add here)
    import shutil
    import tempfile

    _TRACE_KW = dict(short_frac=1.0, out_len=(4, 10))
    _TENANTS = parse_tenants("a:rate=100,weight=1;b:rate=100,weight=1")
    aot_dir = tempfile.mkdtemp(prefix="pt_serve_bench_aot_")
    rep, router = run_bench_fleet(n_requests=12, rate=200.0,
                                  replicas=2, pages=64, page_size=8,
                                  token_budget=256, keep_router=True,
                                  trace_kw=_TRACE_KW,
                                  aot_cache_dir=aot_dir,
                                  tenants=_TENANTS)
    try:
        _check(failures, rep["replicas"] == 2,
               f"fleet bench ran {rep['replicas']} replicas, want 2")
        _check(failures,
               rep["finished"] + rep["rejected"] == rep["requests"],
               f"requests lost: {rep['finished']} finished + "
               f"{rep['rejected']} rejected != {rep['requests']}")
        for q in ("ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms",
                  "tpot_p99_ms"):
            _check(failures, rep[q] is not None and rep[q] > 0.0,
                   f"aggregate gate {q} missing/non-positive: {rep[q]}")
        _check(failures, rep["ttft_p99_ms"] >= rep["ttft_p50_ms"],
               f"p99 {rep['ttft_p99_ms']} < p50 {rep['ttft_p50_ms']}")
        per = rep["per_replica"]
        _check(failures,
               sum(d["finished"] for d in per.values())
               == rep["finished"] and len(per) == 2,
               f"per-replica breakdown {per} does not partition "
               f"{rep['finished']} finished requests over 2 replicas")
        # oracle identity across the whole fleet (the trace is sized
        # to reject nothing; a reject would misalign the zip)
        _check(failures, rep["rejected"] == 0 and rep["finished"] == 12,
               f"fleet run should finish all 12: {rep['finished']} "
               f"finished, {rep['rejected']} rejected")
        model = TinyLM(vocab_size=32, num_heads=2, head_dim=8, seed=0)
        trace = make_trace(12, 200.0, seed=0, vocab=32,
                           tenants=_TENANTS, **_TRACE_KW)
        by_arrival = sorted(router.completed,
                            key=lambda r: r.arrival_t)
        if len(by_arrival) == len(trace):
            for r, t in zip(by_arrival, trace):
                ref = model.reference_generate(t["prompt"],
                                               t["max_new_tokens"])
                _check(failures, r.tokens == ref,
                       f"{r.rid} (replica {r.replica_id}) tokens != "
                       "single-engine oracle")
        # per-tenant extras from the live routed run: shares partition
        # the served tokens and the headline share_err is their
        # measured-vs-weight gap (weights are equal here, so it is
        # |share_a - 0.5| twice over)
        per_t = rep.get("tenants") or {}
        _check(failures, set(per_t) == {"a", "b"},
               f"fleet tenant extras missing tenants: {sorted(per_t)}")
        _check(failures,
               sum(d["tokens"] for d in per_t.values())
               == rep["tokens"],
               f"tenant token shares do not partition the total: "
               f"{per_t} vs {rep['tokens']}")
        if per_t:
            want = abs(per_t["a"]["share"] - 0.5)
            _check(failures,
                   abs(rep.get("tenant_share_err", -1.0) - want)
                   < 1e-12,
                   f"tenant_share_err {rep.get('tenant_share_err')} "
                   f"!= |share_a - 0.5| = {want}")
        # scrapeable router endpoint, gauges == stats bitwise
        st = router.stats()
        exp = MetricsExporter(engines=[], router=router)
        port = exp.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10) as resp:
                body = resp.read().decode("utf-8")
        finally:
            exp.stop()
        vals = parse_prometheus_text(body)
        pre = "paddle_tpu_fleet_router_"
        for key in ("dispatched", "completed", "requeued", "rejected",
                    "queue_depth", "replicas"):
            _check(failures, vals.get(pre + key) == float(st[key]),
                   f"scraped {key}={vals.get(pre + key)} != router "
                   f"truth {st[key]} (bitwise gate)")
        for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
            if st.get(key):
                for q in ("p50", "p99"):
                    skey = pre + key + '{q="' + q + '"}'
                    _check(
                        failures, vals.get(skey) == st[key][q],
                        f"scraped {key} {q} != stats bitwise: "
                        f"{vals.get(skey)} vs {st[key][q]}")
    finally:
        router.close()
        shutil.rmtree(aot_dir, ignore_errors=True)


def self_test():
    _ensure_cpu()
    failures = []
    _test_paged_vs_dense(failures)
    _test_scheduler_trace(failures)
    _test_engine_vs_oracle(failures)
    _test_router_trace(failures)
    _test_tenant_trace(failures)
    _test_fleet_bench_gates(failures)
    for line in failures:
        print(f"  FAILED — {line}")
    if failures:
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: paged decode matches the dense reference "
          "on ragged page-crossing batches, the hand-checked scheduler "
          "trace holds exactly (budget admission, oldest-protected "
          "preemption, arrival-order requeue, zero-leak teardown), "
          "the pressured engine reproduces the dense oracle's tokens "
          "with manual-clock-exact TTFT, the fleet router's dispatch "
          "trace is hand-exact (least-outstanding tie-break, tenant "
          "fairness, rate limits), the multi-tenant trace splits "
          "rate-proportionally with hand-exact share math, and a live "
          "2-replica run passes the aggregate-percentile gates with "
          "per-tenant shares partitioning the served tokens and the "
          "scraped router gauges bitwise-equal to router truth")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=50.0,
                    help="Poisson arrival rate (req/s)")
    ap.add_argument("--pages", type=int, default=128)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--token-budget", type=int, default=512)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=1,
                    help="N>1 routes the trace through a "
                         "serving.fleet Router over N replicas")
    ap.add_argument("--tenants", type=str, default=None, metavar="SPEC",
                    help="weighted multi-tenant trace: "
                         "'name:rate=R[,weight=W];...' (per-tenant "
                         "Poisson rate in req/s; weight drives the "
                         "router's fairness in --replicas mode). Adds "
                         "per-tenant p50/p99 + served-token share and "
                         "the tenant_share_err extra to the report")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--request-report", type=int, default=0,
                    metavar="K",
                    help="journal the run and print the K worst-TTFT "
                         "requests with exact phase attribution "
                         "(rate-limit/router-queue/requeue/sched-"
                         "queue/prefill/preempt/decode)")
    ap.add_argument("--slo", type=str, default=None, metavar="SPEC",
                    help="evaluate the run against an SLO spec at "
                         "exit (inline JSON or @path, e.g. "
                         '\'{"ttft_p99_ms": 250, "availability": '
                         "0.999}'); exit 1 on violation — works in "
                         "single-engine and --replicas mode "
                         "(tools/slo_report.py renders the same math "
                         "post-hoc)")
    ap.add_argument("--self-test", action="store_true",
                    help="deterministic kernel/scheduler/engine checks")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    _ensure_cpu()
    tenants = None if args.tenants is None else \
        parse_tenants(args.tenants)
    slo_specs = None
    if args.slo is not None:
        from paddle_tpu.obs.slo import parse_spec_arg

        slo_specs = parse_spec_arg(args.slo)
    run_dir = None
    if args.request_report > 0 or slo_specs is not None:
        import shutil
        import tempfile

        from paddle_tpu.obs import journal

        run_dir = tempfile.mkdtemp(prefix="pt_serve_bench_req_")
        journal.start_run(run_dir)
    try:
        if args.replicas > 1:
            rep = run_bench_fleet(
                n_requests=args.requests, rate=args.rate,
                replicas=args.replicas, pages=args.pages,
                page_size=args.page_size, seed=args.seed,
                token_budget=args.token_budget, tenants=tenants)
        else:
            rep = run_bench(n_requests=args.requests, rate=args.rate,
                            pages=args.pages,
                            page_size=args.page_size, seed=args.seed,
                            token_budget=args.token_budget,
                            tenants=tenants)
    finally:
        if run_dir is not None:
            journal.end_run()
    req_rep = None
    slo_rep = None
    if run_dir is not None:
        if args.request_report > 0:
            req_rep = request_report(run_dir, args.request_report)
        if slo_specs is not None:
            from paddle_tpu.obs.slo import evaluate_run

            slo_rep = evaluate_run(run_dir, slo_specs,
                                   duration_s=rep.get("wall_s"))
            rep["slo_violations"] = slo_rep["violations"]
        shutil.rmtree(run_dir, ignore_errors=True)
    if args.json:
        if req_rep is not None:
            rep["request_report"] = req_rep
        if slo_rep is not None:
            rep["slo"] = slo_rep["objectives"]
        print(json.dumps(rep, sort_keys=True))
    else:
        for k in sorted(rep):
            v = rep[k]
            if isinstance(v, (dict, list)):
                print(f"{k:<20} {json.dumps(v, sort_keys=True)}")
            elif isinstance(v, float):
                print(f"{k:<20} {v:.4g}")
            else:
                print(f"{k:<20} {v}")
        if args.request_report > 0:
            _print_request_report(req_rep)
        if slo_rep is not None:
            for row in slo_rep["objectives"]:
                tgt = row.get("threshold_ms",
                              row.get("floor", row.get("target")))
                verdict = {True: "ok", False: "VIOLATED",
                           None: "no-data"}[row["ok"]]
                val = "-" if row["value"] is None \
                    else f"{row['value']:.4g}"
                print(f"slo {row['name']:<16} value={val} "
                      f"target={tgt:g} {verdict}")
    if slo_rep is not None and slo_rep["violations"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
