#!/usr/bin/env python
"""chaos_run: run a small training loop under a named fault and report
whether the resilience layer recovered it.

The operational front door for ``paddle_tpu.resilience`` (the role the
reference's fleet HA drills play): every registered injector in
``resilience.inject.INJECTORS`` has a scenario here that (1) activates
the fault, (2) runs a real train loop / checkpoint cycle / data pipeline
through the matching guard, and (3) asserts the run COMPLETED and the
recovery the policy promises actually happened.

Usage:
    python tools/chaos_run.py nan_feed                # one scenario
    python tools/chaos_run.py nan_feed --policy rollback --steps 8
    python tools/chaos_run.py --list                  # scenarios
    python tools/chaos_run.py --self-test             # every injector

``--self-test`` additionally fails if an injector is registered WITHOUT
a scenario — you cannot add a chaos point without proving something
recovers from it. Wired into tier-1 via tests/test_tooling.py.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCENARIOS = {}


def scenario(name):
    def deco(fn):
        SCENARIOS[name] = fn
        return fn
    return deco


def _eager_parts(lr=0.1):
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optim as optim

    pt.seed(0)
    m = nn.Linear(4, 1)
    opt = optim.SGD(learning_rate=lr, parameters=m.parameters())

    def loss_fn(model, x, y):
        return F.mse_loss(model(x), y)

    return pt, m, opt, loss_fn


def _batches(steps, batch=8, dim=4):
    rng = np.random.RandomState(0)
    return [(rng.randn(batch, dim).astype(np.float32),
             rng.randn(batch, 1).astype(np.float32)) for _ in range(steps)]


def _eager_guarded_run(policy_name, steps=6, chaos_point=None, chaos_cfg=None):
    """Train under GuardedStep; returns (final weight, stats)."""
    from paddle_tpu.resilience import GuardedStep, RecoveryPolicy, inject

    pt, m, opt, loss_fn = _eager_parts()
    step = pt.TrainStep(m, opt, loss_fn, check_nan=True)
    guard = GuardedStep(step, RecoveryPolicy(
        on_nonfinite=policy_name, sleep=lambda s: None))
    data = _batches(steps)
    if chaos_point is None:
        for x, y in data:
            guard(x, y)
    else:
        with inject.chaos(chaos_point, **(chaos_cfg or {})):
            for x, y in data:
                guard(x, y)
    return np.asarray(m.weight._data), guard.stats


@scenario("nan_feed")
def run_nan_feed(policy="skip_step", steps=6):
    """NaN batch at step 3; the guarded run completes and matches an
    un-faulted run that never saw that batch."""
    if policy == "raise":
        from paddle_tpu.utils.nan_guard import NanInfError

        try:
            _eager_guarded_run(policy, steps, "nan_feed",
                               {"at": 3, "seed": 7})
        except NanInfError as e:
            return f"aborted as requested by policy 'raise': {e}"
        raise AssertionError("policy 'raise' did not abort on the NaN step")
    w_f, stats = _eager_guarded_run(policy, steps,
                                    "nan_feed", {"at": 3, "seed": 7})
    assert stats.nonfinite == 1 and stats.steps == steps - 1, stats
    # reference: same data minus the poisoned batch
    from paddle_tpu.resilience import GuardedStep, RecoveryPolicy

    pt, m, opt, loss_fn = _eager_parts()
    step = pt.TrainStep(m, opt, loss_fn, check_nan=True)
    data = _batches(steps)
    for i, (x, y) in enumerate(data):
        if i != 2:  # the batch chaos poisoned (at=3 => 3rd step)
            step(x, y)
    assert np.array_equal(w_f, np.asarray(m.weight._data)), \
        "skip_step must be bitwise 'that batch never happened'"
    return f"recovered: {stats}"


@scenario("nan_op")
def run_nan_op():
    """Eager op output corrupted; the per-op guard detects it on the
    FIRST bad op and the error carries an actionable summary."""
    import paddle_tpu as pt
    from paddle_tpu.resilience import inject
    from paddle_tpu.utils import nan_guard

    x = pt.to_tensor(np.ones((4, 4), np.float32))
    nan_guard.enable_check_nan()
    try:
        with inject.chaos("nan_op", op="matmul", seed=3):
            try:
                pt.matmul(x, x)
            except nan_guard.NanInfError as e:
                assert e.summary["num_nan"] == 1, e.summary
                assert e.summary["first_bad_index"] >= 0
                return f"detected with summary: {e.summary}"
        raise AssertionError("injected nan_op went undetected")
    finally:
        nan_guard.disable_check_nan()


def _static_parts():
    import paddle_tpu as pt
    import paddle_tpu.fluid as fluid

    pt.enable_static()
    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[8, 4])
        y = fluid.data(name="y", shape=[8, 1])
        out = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, startup, loss


def _static_guarded_run(steps=3, chaos_point=None, chaos_cfg=None,
                        policy_kw=None):
    import paddle_tpu as pt
    from paddle_tpu.resilience import GuardedExecutor, RecoveryPolicy, inject

    prog, startup, loss = _static_parts()
    try:
        gexe = GuardedExecutor(policy=RecoveryPolicy(
            sleep=lambda s: None, **(policy_kw or {})))
        gexe.run(startup)
        data = _batches(steps, batch=8)
        losses = []

        def drive():
            for x, y in data:
                out = gexe.run(prog, feed={"x": x, "y": y},
                               fetch_list=[loss])
                losses.append(None if out is None
                              else float(np.asarray(out[0])))

        if chaos_point is None:
            drive()
        else:
            with inject.chaos(chaos_point, **(chaos_cfg or {})):
                drive()
        return losses, gexe.stats
    finally:
        pt.disable_static()


@scenario("transient_compile")
def run_transient_compile():
    """First two compile attempts die transiently; bounded retry heals
    them and the fetches match an un-faulted run bitwise."""
    clean, _ = _static_guarded_run()
    faulted, stats = _static_guarded_run(
        chaos_point="transient_compile", chaos_cfg={"times": 2})
    assert faulted == clean, (faulted, clean)
    assert stats.retries == 2, stats
    return f"recovered after {stats.retries} retries; losses identical"


@scenario("transient_execute")
def run_transient_execute():
    """First two step executions die transiently; bounded retry heals
    them and the fetches match an un-faulted run bitwise."""
    clean, _ = _static_guarded_run()
    faulted, stats = _static_guarded_run(
        chaos_point="transient_execute", chaos_cfg={"times": 2})
    assert faulted == clean, (faulted, clean)
    assert stats.retries == 2, stats
    return f"recovered after {stats.retries} retries; losses identical"


@scenario("opt_compile_fail")
def run_opt_compile_fail():
    """Optimized compile fails outright; the guard degrades to
    optimize_level=0 and the run completes with identical math."""
    import warnings

    clean, _ = _static_guarded_run(policy_kw={"degrade_opt_level": False})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        faulted, stats = _static_guarded_run(
            chaos_point="opt_compile_fail", chaos_cfg={"times": 100})
    assert faulted == clean, (faulted, clean)
    assert stats.degraded == 1, stats
    return "degraded to optimize_level=0; losses identical"


def _ckpt_cycle(tmpdir, chaos_point=None, chaos_cfg=None):
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optim as optim
    from paddle_tpu.framework.io import save_checkpoint, load_checkpoint
    from paddle_tpu.resilience import SimulatedCrashError, inject

    pt.seed(0)
    m = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.1, parameters=m.parameters())
    save_checkpoint(tmpdir, 1, model=m, optimizer=opt)
    w1 = np.asarray(m.weight._data).copy()
    m.weight._data = m.weight._data + 1.0  # "train", then checkpoint again
    if chaos_point is None:
        save_checkpoint(tmpdir, 2, model=m, optimizer=opt)
    else:
        with inject.chaos(chaos_point, **(chaos_cfg or {})):
            try:
                save_checkpoint(tmpdir, 2, model=m, optimizer=opt)
            except SimulatedCrashError:
                pass  # the 'process died' mid-save
    m2 = nn.Linear(4, 2)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        step = load_checkpoint(tmpdir, model=m2)
    return step, w1, np.asarray(m2.weight._data)


@scenario("ckpt_truncate")
def run_ckpt_truncate():
    """Newest checkpoint truncated on disk; loader falls back to the
    intact previous one with bit-identical params."""
    with tempfile.TemporaryDirectory() as d:
        step, w1, w_loaded = _ckpt_cycle(d, "ckpt_truncate")
    assert step == 1 and np.array_equal(w1, w_loaded), step
    return "fell back to intact step-1 checkpoint"


@scenario("ckpt_bitflip")
def run_ckpt_bitflip():
    """One bit of the newest checkpoint flips on disk; the manifest
    checksum catches it and the loader falls back to the intact one."""
    with tempfile.TemporaryDirectory() as d:
        step, w1, w_loaded = _ckpt_cycle(d, "ckpt_bitflip", {"seed": 5})
    assert step == 1 and np.array_equal(w1, w_loaded), step
    return "checksum caught the flipped bit; fell back to step 1"


@scenario("ckpt_crash")
def run_ckpt_crash():
    """Save crashes before publish; once the orphan tmp dir goes stale
    it is cleaned, and the previous checkpoint loads."""
    import time

    with tempfile.TemporaryDirectory() as d:
        step, w1, w_loaded = _ckpt_cycle(d, "ckpt_crash")
        # backdate the orphan past the concurrent-saver grace period
        t = time.time() - 3600
        for f in os.listdir(d):
            if f.startswith(".tmp_ckpt_"):
                p = os.path.join(d, f)
                for sub in [p] + [os.path.join(p, s) for s in os.listdir(p)]:
                    os.utime(sub, (t, t))
        from paddle_tpu.framework.io import load_checkpoint
        import paddle_tpu.nn as nn
        import warnings

        m3 = nn.Linear(4, 2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            step2 = load_checkpoint(d, model=m3)
        leftovers = [f for f in os.listdir(d) if f.startswith(".tmp_ckpt_")]
    assert step == 1 and np.array_equal(w1, w_loaded), step
    assert step2 == 1 and not leftovers, (step2, leftovers)
    return "stale orphan tmp cleaned; resumed from step 1"


@scenario("loader_worker")
def run_loader_worker():
    """A prefetch worker thread is killed mid-epoch; the restart budget
    absorbs it and every batch still arrives, in order."""
    from paddle_tpu.io_.dataloader import DataLoader
    from paddle_tpu.io_.dataset import Dataset
    from paddle_tpu.resilience import inject

    class Sq(Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return np.float32(i * i)

    def collect():
        dl = DataLoader(Sq(), batch_size=4, num_workers=2,
                        return_list=False)
        return [np.asarray(b) for b in dl]

    clean = collect()
    with inject.chaos("loader_worker", at=2):
        faulted = collect()
    assert len(faulted) == len(clean) == 4
    assert all(np.array_equal(a, b) for a, b in zip(clean, faulted))
    return "worker crash absorbed; all 4 batches delivered in order"


@scenario("ckpt_slow")
def run_ckpt_slow():
    """The checkpoint writer stalls pre-publish (slow/remote fs); under
    ``async_=True`` the stall runs on the background writer thread so
    the step path never blocks, and ``ckpt_<step>`` only appears once
    the writer COMPLETED (publish-on-complete)."""
    import time

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.framework.io import load_checkpoint, save_checkpoint
    from paddle_tpu.resilience import inject

    pt.seed(0)
    m = nn.Linear(4, 2)
    with tempfile.TemporaryDirectory() as d:
        with inject.chaos("ckpt_slow", seconds=0.5):
            t0 = time.perf_counter()
            h = save_checkpoint(d, 1, model=m, async_=True)
            step_path_s = time.perf_counter() - t0
            published_early = os.path.exists(os.path.join(d, "ckpt_1"))
            path = h.result(timeout=30.0)
        assert step_path_s < 0.25, \
            f"async save held the step path {step_path_s:.3f}s"
        assert not published_early, "published before the writer finished"
        assert os.path.isdir(path), path
        m2 = nn.Linear(4, 2)
        step = load_checkpoint(d, model=m2)
        assert step == 1, step
        assert np.array_equal(np.asarray(m.weight._data),
                              np.asarray(m2.weight._data))
    return "0.5s writer stall stayed off the step path; publish-on-complete"


_ELASTIC_RUN = None
_DRILL_ROOTS_CLEANED = set()


def _elastic_drill():
    """Load tools/elastic_run.py (sibling tool, importlib spec — tools/
    is not a package) once and return its cached 3-fault gang drill:
    the worker_kill / worker_hang / preempt_signal scenarios each
    assert their own facet of ONE supervised run instead of paying for
    three."""
    global _ELASTIC_RUN
    if _ELASTIC_RUN is None:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "elastic_run",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "elastic_run.py"))
        _ELASTIC_RUN = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_ELASTIC_RUN)
    res = _ELASTIC_RUN.drill_result()
    root = res.get("root")
    if root and root not in _DRILL_ROOTS_CLEANED:
        # register cleanup only AFTER a successful drill, with the path
        # captured — a lazy drill_result() call at interpreter shutdown
        # could re-run the whole multi-process drill
        import atexit
        import shutil

        _DRILL_ROOTS_CLEANED.add(root)
        atexit.register(shutil.rmtree, root, ignore_errors=True)
    assert not res["failures"], res["failures"]
    return res


@scenario("worker_kill")
def run_worker_kill():
    """A gang worker hard-dies (``os._exit``, no cleanup) mid-run; the
    supervisor tears the whole gang down (no orphans), consumes one
    restart, and the relaunch resumes from the newest intact checkpoint
    with a bitwise-identical loss trajectory."""
    res = _elastic_drill()
    crash = res["state"]["attempts"][0]
    assert crash == {"kind": "crash", "rank": 1, "code": 9}, crash
    assert res["bitwise_match"]
    return "rank-1 kill (exit 9) relaunched; trajectory bitwise intact"


@scenario("worker_hang")
def run_worker_hang():
    """A worker stops making progress WITHOUT dying: only the heartbeat
    watchdog can see it. It SIGKILLs the wedged process and the gang
    relaunches from the newest intact checkpoint."""
    res = _elastic_drill()
    hang = res["state"]["attempts"][1]
    assert hang["kind"] == "hang" and hang["code"] == 137, hang
    assert res["state"]["watchdog_kills"] == 1, res["state"]
    return "silent hang caught by the watchdog (SIGKILL, exit 137)"


@scenario("preempt_signal")
def run_preempt_signal():
    """SIGTERM lands on a worker with ``resilience.graceful_shutdown``
    installed: it checkpoints at the next step boundary, exits 75, and
    the supervisor relaunches WITHOUT consuming the crash budget."""
    res = _elastic_drill()
    pre = res["state"]["attempts"][2]
    assert pre["kind"] == "preempt" and pre["code"] == 75, pre
    assert res["state"]["preemptions"] == 1, res["state"]
    assert res["state"]["restarts"] == 2, \
        f"preemption consumed the crash budget: {res['state']}"
    return "graceful checkpoint-and-exit 75; relaunch was budget-free"


@scenario("replica_kill")
def run_replica_kill():
    """A SERVE replica hard-dies (``os._exit``) mid-decode behind the
    ``serving.fleet`` router: its in-flight requests requeue in
    original arrival order and finish token-for-token identical to the
    single-engine oracle, and the relaunched replica hydrates every
    bucket from the shared AOT cache — zero ``via=="xla"`` compiles in
    its journal segment. (One cached 2-replica drill per process,
    shared with tests/test_serve_fleet.py.)"""
    from paddle_tpu.serving.fleet import drill

    res = drill.drill_result()
    assert not res["failures"], res["failures"]
    st = res["stats"]
    assert st["requeued"] >= 1 and st["completed"] == len(
        res["requests"]), st
    assert res["relaunch_via"]["xla"] == 0, res["relaunch_via"]
    return (f"replica kill mid-decode: {st['requeued']} requests "
            f"requeued in arrival order, all {st['completed']} "
            f"finished oracle-identical; relaunch hydrated "
            f"{res['relaunch_via']['aot_disk']} entries, 0 XLA "
            "compiles")


def self_test():
    from paddle_tpu.resilience import INJECTORS

    missing = sorted(set(INJECTORS) - set(SCENARIOS))
    if missing:
        print(f"self-test FAILED: injectors with no recovery scenario: "
              f"{missing}")
        return 1
    failures = []
    for name in sorted(SCENARIOS):
        try:
            msg = SCENARIOS[name]()
            print(f"  {name:20s} ok — {msg}")
        except Exception as e:
            print(f"  {name:20s} FAILED — {type(e).__name__}: {e}")
            failures.append(name)
    if failures:
        print(f"self-test FAILED: {failures}")
        return 1
    print("self-test passed: every registered injector's fault class ends "
          "in a completed, verified-correct run")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fault", nargs="?", help="scenario / injector name")
    ap.add_argument("--policy", default="skip_step",
                    choices=["raise", "skip_step", "rollback"],
                    help="nonfinite policy for the nan_feed scenario")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--self-test", action="store_true",
                    help="run every registered injector's scenario")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.list:
        for name in sorted(SCENARIOS):
            doc = (SCENARIOS[name].__doc__ or "").strip().splitlines()
            print(f"{name:20s} {doc[0] if doc else ''}")
        return 0
    if not args.fault:
        ap.error("a fault name is required (or --list / --self-test)")
    if args.fault not in SCENARIOS:
        ap.error(f"unknown fault {args.fault!r}; --list shows scenarios")
    if args.fault == "nan_feed":
        msg = SCENARIOS[args.fault](policy=args.policy, steps=args.steps)
    else:
        msg = SCENARIOS[args.fault]()
    print(f"{args.fault}: {msg}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
