#!/usr/bin/env python
"""fleet_report: render, diff, and self-test cross-rank fleet runs.

The operational front door for ``paddle_tpu.obs.fleet`` (the cross-rank
complement of tools/run_report.py): a fleet run dir holds one
``rank_NN/`` journal per worker (written when GangSupervisor /
``dist.launch`` hand each rank ``PADDLE_TPU_RUN_DIR=<run>/rank_NN`` +
``PADDLE_TPU_RANK``) plus the supervisor's own ``supervisor/`` record.
This CLI renders the per-rank table and cross-rank skew summary
(per-step max/median step time, slowest-rank attribution,
persistent-straggler and hung-rank detection — the per-worker skew the
MLPerf TPU-pod playbook treats as the first-order scaling diagnostic),
fuses the per-rank Chrome traces into one Perfetto file with pid=rank
lanes, and gates skew regressions between two runs.

Usage:
    python tools/fleet_report.py RUN_DIR            # table + skew
    python tools/fleet_report.py RUN_DIR --json
    python tools/fleet_report.py RUN_DIR --trace-out merged.json
    python tools/fleet_report.py --diff BASE_DIR NEW_DIR \\
        [--skew-threshold 0.25]                     # exit 1 on regression
    python tools/fleet_report.py --self-test        # canned 2-rank
        # fixtures (exact skew/straggler/percentile numbers) + a REAL
        # 2-worker GangSupervisor drill with an injected worker_hang

``--self-test`` is wired into tier-1 via tests/test_tooling.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

THIS_DIR = os.path.dirname(os.path.abspath(__file__))

DEFAULT_SKEW_THRESHOLD = 0.25  # max cross-rank skew may grow 25%
DEFAULT_TTFT_THRESHOLD = 0.25  # merged p99 TTFT may grow 25%
DEFAULT_FAIRNESS_DRIFT_THRESHOLD = 0.20  # |served share - weight share|
#                 (absolute; mirrors obs.usage.DEFAULT_FAIRNESS_DRIFT_THRESHOLD)


def _load_sibling(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(THIS_DIR, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fmt(v, nd=3):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


# -- render ------------------------------------------------------------------


def render_fleet(agg, as_json=False):
    if as_json:
        return json.dumps(agg, indent=1, default=str, sort_keys=True)
    lines = [f"fleet run    {agg.get('run_dir', '?')}",
             f"ranks        {agg['nranks']} "
             f"({agg['aligned_steps']} aligned steps"
             + (", supervised" if agg.get("supervisor") else "") + ")"]
    hdr = (f"{'rank':>4} {'steps':>6} {'last':>5} {'mean_ms':>8} "
           f"{'p50_ms':>7} {'goodput':>8} {'mfu':>7} {'ex/s':>8} "
           f"{'starts':>6} {'reqs':>5}")
    lines.append(hdr)
    hb = agg.get("heartbeat_age_s") or {}
    for rank in agg["ranks"]:
        r = agg["per_rank"][rank]
        lines.append(
            f"{rank:>4} {r['steps']:>6} {_fmt(r['last_step']):>5} "
            f"{_fmt(r['mean_step_ms']):>8} {_fmt(r['p50_step_ms']):>7} "
            f"{_fmt(r['goodput']):>8} {_fmt(r['mfu']):>7} "
            f"{_fmt(r['examples_per_s']):>8} {r['run_starts']:>6} "
            f"{r['requests']:>5}")
    skew = agg["skew"]
    if skew["max"] is not None:
        counts = ", ".join(f"rank {r}: {n}" for r, n in
                           sorted(skew["slowest_counts"].items()))
        lines.append(
            f"skew         max={skew['max']:.3g}x @step "
            f"{skew['max_step']} mean={_fmt(skew['mean'])}x over "
            f"{skew['steps_compared']} steps; slowest rank "
            f"{skew['worst_rank']} at {_fmt(skew['worst_rank_ratio'])}x "
            f"the others (slowest-per-step: {counts})")
    for s in agg.get("stragglers") or []:
        if s["kind"] == "slow":
            lines.append(
                f"straggler    rank {s['rank']} SLOW "
                f"{s['ratio']:.3g}x the gang from step "
                f"{s['first_step']} ({s['streak']} consecutive steps)")
        else:
            lines.append(
                f"straggler    rank {s['rank']} HUNG in attempt "
                f"{s['attempt']} (stopped at step {s['last_step']}, "
                f"gang reached {s['gang_reached']})"
                + (" [ambiguous]" if s.get("ambiguous") else ""))
    req = agg.get("requests")
    if req:
        lines.append(
            f"requests     {req['requests']} merged across ranks "
            f"({req['finished']} finished, {req['preemptions']} "
            f"preemptions)")
        for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
            if req.get(f"{key}_p50") is not None:
                lines.append(f"{key:<12} p50={req[f'{key}_p50']:.3f} "
                             f"p99={req[f'{key}_p99']:.3f}")
    rt = agg.get("router")
    if rt:
        # ONE router-line format: run_report owns it (both tools render
        # the same obs.fleet.router_summary dict — a second
        # hand-maintained copy here had already drifted)
        lines.append(_load_sibling("run_report").render_router_line(rt))
    tu = agg.get("tenant_usage")
    if tu and (tu.get("tenants") or tu.get("fairness")):
        # ONE tenant-table format: run_report owns it (same
        # single-owner discipline as the router line above)
        lines += _load_sibling("run_report").render_tenant_table(tu)
    sup = agg.get("supervisor")
    if sup:
        line = (f"supervisor   restarts={sup['restarts']} "
                f"preemptions={sup['preemptions']} "
                f"watchdog_kills={sup['watchdog_kills']}")
        if sup.get("resume_ms_p50") is not None:
            line += (f" resume_ms p50={sup['resume_ms_p50']:.0f} "
                     f"max={sup['resume_ms_max']:.0f}")
        if sup["budget_exhausted"]:
            line += " BUDGET-EXHAUSTED"
        lines.append(line)
    if hb:
        lines.append("heartbeats   " + ", ".join(
            f"rank {r}: {_fmt(a)}s" for r, a in sorted(hb.items())))
    rollup = []
    for key in ("goodput_min", "examples_per_s_total", "mfu_mean"):
        if agg.get(key) is not None:
            rollup.append(f"{key}={_fmt(agg[key])}")
    if rollup:
        lines.append("gang         " + " ".join(rollup))
    return "\n".join(lines)


# -- diff (the skew-regression gate) -----------------------------------------


def diff_fleets(base, new, skew_threshold=DEFAULT_SKEW_THRESHOLD,
                ttft_threshold=DEFAULT_TTFT_THRESHOLD,
                fairness_drift_threshold=(
                    DEFAULT_FAIRNESS_DRIFT_THRESHOLD)):
    """Compare two fleet aggregates; regression flips when NEW's
    cross-rank skew (or straggler count) is worse than BASE beyond the
    threshold. A perfectly balanced base (skew 1.0) regressing to ANY
    persistent straggler is flagged regardless of ratio. Serve fleets:
    the MERGED (cross-replica pooled) p99 TTFT gates the same way —
    the aggregate serving-SLO axis a per-rank skew number can't see —
    and NEW's fairness drift (worst |served share - weight share| from
    the router's tenant.summary) exceeding the absolute threshold AND
    base's own drift flags a weighted-scheduling regression (the
    worse-than-base clause keeps A-vs-A clean by construction)."""
    bs, ns = base["skew"]["max"], new["skew"]["max"]
    b_slow = sum(1 for s in base.get("stragglers") or []
                 if s["kind"] == "slow")
    n_slow = sum(1 for s in new.get("stragglers") or []
                 if s["kind"] == "slow")
    b_hang = sum(1 for s in base.get("stragglers") or []
                 if s["kind"] == "hang")
    n_hang = sum(1 for s in new.get("stragglers") or []
                 if s["kind"] == "hang")
    out = {
        "base_skew_max": bs, "new_skew_max": ns,
        "skew_ratio": (ns / bs) if bs and ns else None,
        "skew_regression": bool(
            bs is not None and ns is not None and
            ns > bs * (1.0 + skew_threshold)),
        "base_stragglers": b_slow, "new_stragglers": n_slow,
        "straggler_regression": n_slow > b_slow,
        "base_hangs": b_hang, "new_hangs": n_hang,
        "hang_regression": n_hang > b_hang,
    }
    bt = (base.get("requests") or {}).get("ttft_ms_p99")
    nt = (new.get("requests") or {}).get("ttft_ms_p99")
    out["base_ttft_p99_ms"] = bt
    out["new_ttft_p99_ms"] = nt
    out["ttft_ratio"] = (nt / bt) if bt and nt else None
    out["ttft_regression"] = bool(
        bt is not None and nt is not None and
        nt > bt * (1.0 + ttft_threshold))
    bfd = ((base.get("tenant_usage") or {}).get("fairness")
           or {}).get("max_drift")
    nfd = ((new.get("tenant_usage") or {}).get("fairness")
           or {}).get("max_drift")
    out["base_fairness_drift"] = bfd
    out["new_fairness_drift"] = nfd
    out["fairness_drift_regression"] = bool(
        nfd is not None and nfd > fairness_drift_threshold and
        (bfd is None or nfd > bfd))
    if out["fairness_drift_regression"]:
        out["fairness_worst_tenant"] = \
            ((new.get("tenant_usage") or {}).get("fairness")
             or {}).get("worst_tenant")
    out["regression"] = out["skew_regression"] or \
        out["straggler_regression"] or out["hang_regression"] or \
        out["ttft_regression"] or out["fairness_drift_regression"]
    return out


def render_diff(rep, as_json=False):
    if as_json:
        return json.dumps(rep, indent=1, default=str, sort_keys=True)
    return "\n".join(f"{k:<22} {_fmt(v, 6)}"
                     for k, v in rep.items() if v is not None)


# -- self-test ---------------------------------------------------------------


def _write_rank(run_dir, rank, step_ms, n_steps=10, requests=(),
                tenant=None):
    """One canned rank journal through the REAL RunJournal API."""
    from paddle_tpu.obs import journal as J

    j = J.RunJournal(run_dir, rank=rank, flush_every=1,
                     compute_flops=False)
    j.start()
    for i in range(1, n_steps + 1):
        j.sync_step(i)
        j.record_step(loss=1.0 / i, step_ms=step_ms, examples=8,
                      source="self_test")
    for i, ttft_ms in enumerate(requests):
        j.record_request(
            rid=f"r{rank}_{i}", state="FINISHED", arrival_t=0.0,
            admit_t=0.001, first_token_t=ttft_ms / 1e3, finish_t=2.0,
            prompt_tokens=4, output_tokens=5,
            **({"tenant": tenant} if tenant else {}))
    j.close()
    return j


def _selftest_fixtures(failures):
    from paddle_tpu.obs import fleet as F

    with tempfile.TemporaryDirectory() as d:
        skewed = os.path.join(d, "skewed")
        # rank 1 is a KNOWN 2x straggler: 20 ms steps vs rank 0's 10 ms
        # (skew = max/median-of-ranks = 20/15; straggler ratio =
        # slowest/median-of-OTHERS = 20/10 = 2.0 exactly).
        # Requests: rank 0 TTFT 100..500 ms, rank 1 600..1000 ms, so
        # the MERGED pool is 100..1000 and nearest-rank p50/p99 are
        # hand-computable: p50 = 500 ms, p99 = 1000 ms.
        _write_rank(skewed, 0, 10.0,
                    requests=[100.0, 200.0, 300.0, 400.0, 500.0])
        _write_rank(skewed, 1, 20.0,
                    requests=[600.0, 700.0, 800.0, 900.0, 1000.0])
        agg = F.aggregate(skewed)
        if agg["nranks"] != 2 or agg["aligned_steps"] != 10:
            failures.append(f"fixture alignment wrong: {agg['nranks']} "
                            f"ranks, {agg['aligned_steps']} steps")
        if abs((agg["skew"]["max"] or 0) - 20.0 / 15.0) > 1e-12:
            failures.append(f"skew max {agg['skew']['max']} != exact "
                            f"20/15")
        if agg["skew"]["worst_rank"] != 1 or \
                abs((agg["skew"]["worst_rank_ratio"] or 0) - 2.0) > 1e-12:
            failures.append(
                f"straggler attribution wrong: rank "
                f"{agg['skew']['worst_rank']} at "
                f"{agg['skew']['worst_rank_ratio']}x (want rank 1 at "
                f"2.0x)")
        if agg["skew"]["slowest_counts"] != {1: 10}:
            failures.append(f"slowest-per-step counts "
                            f"{agg['skew']['slowest_counts']} != "
                            "{1: 10}")
        slow = [s for s in agg["stragglers"] if s["kind"] == "slow"]
        if len(slow) != 1 or slow[0]["rank"] != 1 or \
                abs(slow[0]["ratio"] - 2.0) > 1e-12 or \
                slow[0]["first_step"] != 1:
            failures.append(f"persistent-straggler episode wrong: "
                            f"{slow}")
        req = agg["requests"]
        if not req or req["requests"] != 10:
            failures.append(f"merged requests lost records: {req}")
        elif abs(req["ttft_ms_p50"] - 500.0) > 1e-9 or \
                abs(req["ttft_ms_p99"] - 1000.0) > 1e-9:
            failures.append(
                f"merged percentiles off hand-computed values: "
                f"p50={req['ttft_ms_p50']} (want 500) "
                f"p99={req['ttft_ms_p99']} (want 1000)")

        # detector re-arm: a recovered episode re-fires on the next one
        rows = F.step_skew(F.align_steps(F.load_fleet(skewed)))
        det = F.StragglerDetector(factor=1.5, patience=3)
        fired = [det.update(r) for r in rows]
        if sum(1 for f in fired if f) != 1:
            failures.append("detector fired more than once per episode")
        healthy_row = dict(rows[0], slowest_vs_others=1.0)
        det2 = F.StragglerDetector(factor=1.5, patience=2)
        seq = [rows[0], rows[1], healthy_row, rows[2], rows[3]]
        refires = sum(1 for r in seq if det2.update(r))
        if refires != 2:
            failures.append(f"re-arm failed: {refires} firings across "
                            "two separated episodes (want 2)")

        # the balanced baseline: same gang, no skew
        balanced = os.path.join(d, "balanced")
        _write_rank(balanced, 0, 10.0)
        _write_rank(balanced, 1, 10.0)
        bal = F.aggregate(balanced)
        if bal["stragglers"]:
            failures.append(f"balanced fixture false-positived: "
                            f"{bal['stragglers']}")
        rep = diff_fleets(bal, agg)
        if not rep["skew_regression"] or not rep["straggler_regression"]:
            failures.append(f"diff missed the injected 2x skew "
                            f"regression: {rep}")
        self_rep = diff_fleets(agg, agg)
        if self_rep["regression"]:
            failures.append(f"A-vs-A diff false-positived: {self_rep}")
        if "straggler    rank 1 SLOW 2x" not in render_fleet(agg):
            failures.append("render lost the straggler line:\n"
                            + render_fleet(agg))

        # serve-fleet axes: a run whose merged p99 TTFT doubled
        # (rank 0: 200..1000 ms, rank 1: 1200..2000 ms -> pooled p99 =
        # 2000 ms exactly, 2x the skewed fixture's 1000 ms) must trip
        # the TTFT gate — and ONLY it (same step times as balanced)
        slower = os.path.join(d, "slower")
        _write_rank(slower, 0, 10.0,
                    requests=[200.0, 400.0, 600.0, 800.0, 1000.0])
        _write_rank(slower, 1, 10.0,
                    requests=[1200.0, 1400.0, 1600.0, 1800.0, 2000.0])
        slow_agg = F.aggregate(slower)
        trep = diff_fleets(agg, slow_agg)
        if not trep["ttft_regression"] or \
                abs((trep["ttft_ratio"] or 0) - 2.0) > 1e-9:
            failures.append(
                f"diff missed the 2x merged-p99-TTFT regression: "
                f"{trep}")
        if trep["skew_regression"] or trep["straggler_regression"]:
            failures.append(
                f"TTFT fixture false-positived a skew/straggler "
                f"regression: {trep}")

        # a router journal under <run>/router joins the aggregate and
        # renders the dispatch/requeue line
        from paddle_tpu.obs import journal as J

        rj = J.RunJournal(os.path.join(skewed, J.ROUTER_DIR),
                          rank=None, flush_every=1,
                          compute_flops=False)
        rj.start()
        rj.event("router.summary", dispatched=12, requeued=2,
                 rejected=1, completed=10, replicas=2, scale_ups=0,
                 scale_downs=0, tenants={"default": 1.0},
                 ttft_p99_ms=1000.0)
        rj.close()
        ragg = F.aggregate(skewed)
        rt = ragg.get("router")
        if not rt or rt["dispatched"] != 12 or rt["requeued"] != 2:
            failures.append(f"aggregate lost the router journal: {rt}")
        elif "router       dispatched=12 requeued=2" not in \
                render_fleet(ragg):
            failures.append("render lost the router line:\n"
                            + render_fleet(ragg))

        # the fairness-drift gate: CLEAN serves weight-0.25 tenant a
        # exactly at its entitlement, VIOL serves it at DOUBLE (share
        # 0.5 — the 2x violation, max_drift 0.25 > the 0.2 default);
        # the diff must flag it — and ONLY it — and A-vs-A stays clean
        fclean, fviol = os.path.join(d, "fclean"), os.path.join(d,
                                                                "fviol")
        for path, share_a in ((fclean, 0.25), (fviol, 0.5)):
            _write_rank(path, 0, 10.0, requests=[100.0], tenant="a")
            rj2 = J.RunJournal(os.path.join(path, J.ROUTER_DIR),
                               rank=None, flush_every=1,
                               compute_flops=False)
            rj2.start()
            rj2.event(
                "tenant.summary", served_total=100,
                tenants={
                    "a": {"share": share_a, "weight_share": 0.25,
                          "served_tokens": 100 * share_a},
                    "b": {"share": 1.0 - share_a, "weight_share": 0.75,
                          "served_tokens": 100 * (1 - share_a)}})
            rj2.close()
        aggv = F.aggregate(fviol)
        frep = diff_fleets(F.aggregate(fclean), aggv)
        if not frep["fairness_drift_regression"]:
            failures.append(
                "diff missed the 2x fairness violation (weight share "
                f"0.25 served at 0.5): {frep}")
        if abs((frep["new_fairness_drift"] or 0) - 0.25) > 1e-12:
            failures.append(
                f"fairness drift {frep['new_fairness_drift']} != "
                "hand-computed 0.25")
        if frep["skew_regression"] or frep["straggler_regression"] or \
                frep["ttft_regression"]:
            failures.append(
                f"fairness fixture false-positived another gate: "
                f"{frep}")
        if not frep["regression"]:
            failures.append("fairness drift did not fold into the "
                            "top-level fleet regression flag")
        fself = diff_fleets(aggv, aggv)
        if fself["regression"]:
            failures.append(
                f"A-vs-A fairness diff false-positived: {fself}")
        rendered = render_fleet(aggv)
        if "tenant a" not in rendered or "DRIFT" not in rendered:
            failures.append("render lost the tenant/fairness lines:\n"
                            + rendered)
    print("  fixtures       ok — exact 20/15 skew, rank-1-at-2.0x "
          "attribution, merged p50=500/p99=1000, re-arm, diff gate, "
          "2x-TTFT gate, router line, 2x-fairness-violation gate "
          "(A-vs-A clean)"
          if not failures else
          f"  fixtures       FAILED ({len(failures)})")
    return failures


def _selftest_drill(failures):
    """The acceptance drill, read off elastic_run's SHARED 3-fault
    gang drill (cached once per process — chaos_run and elastic_run's
    own self-test assert other facets of the same run): the injected
    ``worker_hang`` on rank 1 at step 6 must be attributed to rank 1
    by the per-rank JOURNALS (it stopped at 6 while the gang reached
    7), and the per-rank Chrome traces must fuse into one Perfetto
    file with a distinct pid=rank lane per worker."""
    from paddle_tpu.obs import fleet as F

    er = _load_sibling("elastic_run")
    res = er.drill_result()
    if res["failures"]:
        failures.append(f"underlying elastic drill failed: "
                        f"{res['failures']}")
        print("  hang_drill     FAILED (underlying drill)")
        return failures
    hang_at = 6  # run_drill's default worker_hang step (rank 1)
    agg = F.aggregate(res["journal_dir"])
    hangs = [s for s in agg["stragglers"] if s["kind"] == "hang"]
    if len(hangs) != 1 or hangs[0]["rank"] != 1 or \
            hangs[0].get("ambiguous"):
        failures.append(
            f"aggregate did not identify rank 1 as the hung straggler "
            f"from the journals: {agg['stragglers']}")
    elif hangs[0]["last_step"] != hang_at:
        failures.append(
            f"hung rank stopped at step {hangs[0]['last_step']}, "
            f"chaos fired at {hang_at}")
    if (agg.get("supervisor") or {}).get("watchdog_kills") != 1:
        failures.append("supervisor journal lost the watchdog kill: "
                        f"{agg.get('supervisor')}")
    # merged Perfetto trace: one distinct lane per rank
    out_path = os.path.join(tempfile.mkdtemp(prefix="pt_fleet_trace_"),
                            "merged_trace.json")
    merged = F.merge_chrome_traces(res["journal_dir"], out_path)
    if merged["sources"] < 2:
        failures.append(f"merged trace fused {merged['sources']} "
                        "sources, want both ranks' exports")
    else:
        with open(out_path, encoding="utf-8") as f:
            data = json.load(f)
        span_pids = {e["pid"] for e in data["traceEvents"]
                     if e.get("ph") == "X"}
        if not {0, 1} <= span_pids:
            failures.append(f"merged trace lanes {sorted(span_pids)} "
                            "missing a rank (want pids 0 and 1)")
    import shutil

    shutil.rmtree(os.path.dirname(out_path), ignore_errors=True)
    if not failures:
        print(f"  hang_drill     ok — journals name rank 1 (stopped "
              f"at {hang_at} while the gang reached "
              f"{hangs[0]['gang_reached']}), merged trace has pid=0/1 "
              "rank lanes")
    else:
        print("  hang_drill     FAILED")
    return failures


def self_test():
    failures = []
    failures = _selftest_fixtures(failures)
    if not failures:
        failures = _selftest_drill(failures)
    if failures:
        for f in failures:
            print(f"  FAILED — {f}")
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: canned 2-rank fixtures reproduce exact "
          "skew/straggler/percentile numbers (incl. the 2x merged-p99-"
          "TTFT serve gate and the router summary line), and a real "
          "2-worker hang drill's journals identify the hung rank and "
          "fuse into a merged per-rank Perfetto trace")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="fleet run dir (render) or two with --diff")
    ap.add_argument("--diff", action="store_true",
                    help="diff two fleet runs; exit 1 on skew/"
                         "straggler regression")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--trace-out", type=str, default=None,
                    help="write the merged per-rank Chrome trace here")
    ap.add_argument("--skew-threshold", type=float,
                    default=DEFAULT_SKEW_THRESHOLD,
                    help="allowed relative cross-rank skew growth "
                         "(--diff)")
    ap.add_argument("--ttft-threshold", type=float,
                    default=DEFAULT_TTFT_THRESHOLD,
                    help="allowed relative merged-p99-TTFT growth "
                         "(--diff, serve fleets)")
    ap.add_argument("--fairness-drift-threshold", type=float,
                    default=DEFAULT_FAIRNESS_DRIFT_THRESHOLD,
                    help="allowed absolute |served share - weight "
                         "share| fairness drift per tenant (--diff, "
                         "serve fleets)")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    from paddle_tpu.obs import fleet as F

    if args.self_test:
        return self_test()
    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two fleet run dirs")
        rep = diff_fleets(F.aggregate(args.paths[0]),
                          F.aggregate(args.paths[1]),
                          skew_threshold=args.skew_threshold,
                          ttft_threshold=args.ttft_threshold,
                          fairness_drift_threshold=args
                          .fairness_drift_threshold)
        print(render_diff(rep, as_json=args.json))
        return 1 if rep["regression"] else 0
    if len(args.paths) != 1:
        ap.error("need one fleet run dir (or --diff A B / --self-test)")
    agg = F.aggregate(args.paths[0])
    print(render_fleet(agg, as_json=args.json))
    if args.trace_out:
        merged = F.merge_chrome_traces(args.paths[0], args.trace_out)
        print(f"merged trace {merged['path']} "
              f"({merged['sources']} rank traces, "
              f"{merged['events']} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
