#!/bin/bash
# Relay-recovery watcher (VERDICT r4 "Next round" #1).
#
# Probes the axon relay every PROBE_INTERVAL seconds with a ONE-SHOT
# python process (90s thread-timeout around jax.devices(); the relay's
# failure mode is an infinite block, not an exception — see r3/r4 ops
# notes). On the first successful probe it immediately runs the strict
# serial measurement session (tools/tpu_session.sh: bench -> pallas
# probe -> publish into BASELINE.json) and exits.
#
# CRITICAL INVARIANT: never two TPU-touching processes at once. While
# this watcher runs, all other work in the repo must be CPU-only
# (PYTHONPATH=/root/repo JAX_PLATFORMS=cpu). Each probe is a fresh
# process that fully exits before the next, and the session only starts
# after a probe process has exited successfully.
set -u -o pipefail
cd "$(dirname "$0")/.."
mkdir -p "${1:-/tmp/tpu_watch}"
OUT="$(realpath "${1:-/tmp/tpu_watch}")"
PROBE_INTERVAL="${PROBE_INTERVAL:-900}"
MAX_ITERS="${MAX_ITERS:-46}"   # ~11.5h at 15min

cat > "$OUT/ping.py" <<'EOF'
import threading, sys, os, json, time
res = {"alive": False, "err": None, "t": time.time()}
def probe():
    try:
        import jax
        d = jax.devices()
        res["alive"] = True
        res["devices"] = [str(x) for x in d]
    except Exception as e:
        res["err"] = repr(e)
t = threading.Thread(target=probe, daemon=True)
t.start()
t.join(90)
if t.is_alive():
    res["err"] = "timeout_90s_blocked"
print(json.dumps(res))
os._exit(0 if res["alive"] else 1)
EOF

for i in $(seq 1 "$MAX_ITERS"); do
  ts=$(date +%H:%M:%S)
  if (cd /tmp && timeout 150 python "$OUT/ping.py" > "$OUT/last_ping.json" 2> "$OUT/last_ping.log"); then
    echo "[$ts] iter $i: RELAY ALIVE — starting serial session" | tee -a "$OUT/watch.log"
    touch "$OUT/RECOVERED"
    bash tools/tpu_session.sh "$OUT/session" 2>&1 | tee -a "$OUT/watch.log"
    rc=${PIPESTATUS[0]}
    echo "session rc=$rc" | tee -a "$OUT/watch.log"
    touch "$OUT/SESSION_DONE"
    exit $rc
  fi
  echo "[$ts] iter $i: relay dead ($(cat "$OUT/last_ping.json" 2>/dev/null))" >> "$OUT/watch.log"
  sleep "$PROBE_INTERVAL"
done
echo "watcher exhausted $MAX_ITERS iterations without recovery" | tee -a "$OUT/watch.log"
exit 2
