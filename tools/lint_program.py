#!/usr/bin/env python
"""lint_program: diagnostic report for Programs built by a user script.

The static-analysis front door for ``paddle_tpu.analysis`` (the role the
reference's ``tools/print_op_desc.py`` + the inference Analyzer's VLOG
output play): run a program-building script under static mode, then print
every verifier/lint diagnostic and the optimization-pass op-count deltas
for each Program the script left behind.

Usage:
    python tools/lint_program.py my_script.py            # lint its Programs
    python tools/lint_program.py --optimize-level 2 my_script.py
    python tools/lint_program.py --memory my_script.py   # liveness + peak HBM
    python tools/lint_program.py --memory --devices 8 my_script.py
    python tools/lint_program.py --self-test             # check the checker

``--memory`` adds the static dataflow/memory analysis
(``paddle_tpu.analysis.dataflow`` / ``.memory``) per Program: the
versioned liveness table, the predicted peak-HBM high-water mark
(per device with ``--devices N``), and the PTL104 rematerialization
candidates.

``--self-test`` builds one known-broken Program per verifier class
(dangling input, WAW clobber via record_assign, dtype drift, donated-
then-read persistable) plus a DCE victim, asserts the exact diagnostic
codes fire, and additionally checks the liveness/memory analysis
against a hand-computed 3-op fixture — exits non-zero on any miss,
wired into CI so a pass regression fails fast.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _report_for(program, optimize_level):
    from paddle_tpu.analysis import (PassContext, PassManager, VerifierPass,
                                     LintPass, default_optimize_passes)

    # no Executor.run fetch context here: root the optimization preview at
    # the graph's leaves (outputs nothing consumes), i.e. anything the
    # user could still fetch
    read = set()
    for op in program.global_block.ops:
        read.update(n for n in op.input_names if n is not None)
    leaves = [n for op in program.global_block.ops
              for n in op.output_names if n not in read]
    ctx = PassContext(program, fetch_names=leaves)
    PassManager([VerifierPass(), LintPass()]
                + default_optimize_passes(optimize_level)).run_ctx(ctx)
    return ctx


def _memory_section(program, fetch_names, devices):
    """The --memory report for one Program: liveness table, predicted
    peak bytes (per device), remat candidates (PTL104)."""
    from paddle_tpu.analysis import memory as M
    from paddle_tpu.utils.stats import format_bytes as _fmt_bytes

    est, rep = M.memory_report(program, fetch_list=list(fetch_names),
                               data_devices=devices)
    lines = ["   liveness (name/ver  kind  def->last_use  bytes  flags)"]
    for name, ver, kind, d, u, nb, flags in est.liveness.table():
        lines.append(f"     {name}@{ver:<3} {kind:<12} {d!s:>5} -> "
                     f"{u!s:<5} {_fmt_bytes(nb):>10}  {flags}")
    lines.append(
        f"   peak HBM     {_fmt_bytes(est.peak_bytes)} total "
        f"({_fmt_bytes(est.per_device_bytes)}/device over {devices}) = "
        f"args {_fmt_bytes(est.arg_bytes)} + outputs "
        f"{_fmt_bytes(est.output_bytes)} + temps "
        f"{_fmt_bytes(est.temp_peak_bytes)}"
        + (f" @ op#{est.peak_op[0]} {est.peak_op[1]}"
           if est.peak_op else ""))
    hints = [d for d in rep if d.code == "PTL104"]
    if hints:
        lines.append(f"   remat        {len(hints)} candidate(s):")
        lines += [f"     {d!r}" for d in hints]
    else:
        lines.append("   remat        no candidates (nothing big, "
                     "long-lived, and cheap to recompute)")
    return "\n".join(lines)


def lint_script(path, optimize_level, memory=False, devices=1):
    import paddle_tpu as pt
    from paddle_tpu.static_.program import Program, program_guard

    # fresh default programs so the script can't pollute (or be polluted
    # by) whatever the embedding process had recorded
    main, startup = Program(), Program()
    pt.enable_static()
    try:
        with program_guard(main, startup):
            runpy.run_path(path, run_name="__main__")
    finally:
        pt.disable_static()

    programs = []
    if main.global_block.ops:
        programs.append(("default_main_program", main))
    if startup.global_block.ops:
        programs.append(("default_startup_program", startup))
    if not programs:
        print(f"{path}: no ops were recorded into the default programs "
              "(did the script build under program_guard? pass that "
              "Program to paddle_tpu.analysis.verify_program directly)")
        return 0

    worst = 0
    for name, prog in programs:
        ctx = _report_for(prog, optimize_level)
        rep = ctx.report
        n_ops = len(prog.global_block.ops)
        print(f"== {name}: {n_ops} ops, {len(prog.global_block.vars)} vars")
        print(str(rep))
        if optimize_level > 0:
            print(f"   optimized op count: {len(ctx.ops)} "
                  f"({n_ops - len(ctx.ops)} removed at level "
                  f"{optimize_level})")
        if memory:
            print(_memory_section(prog, ctx.fetch_names, devices))
        if rep.errors():
            worst = 1
    return worst


# -- self-test --------------------------------------------------------------

def _broken_programs():
    """One hand-built malformed Program per verifier class. Yields
    (label, expected_code, program, fetch_names)."""
    import jax.numpy as jnp
    from paddle_tpu.static_.program import Operator, Program

    def base():
        p = Program()
        blk = p.global_block
        blk.create_var(name="x", shape=(2, 3), dtype="float32",
                       is_data=True)
        return p, blk

    # PTA002: op reads a name the block never declared
    p, blk = base()
    blk.create_var(name="y", shape=(2, 3), dtype="float32")
    blk.append_op(Operator("relu", lambda a: jnp.maximum(a, 0),
                           ["not_a_var"], ["y"], {}))
    yield "dangling input", "PTA002", p, ("y",)

    # PTA004: assign_to clobbers an unread op output (record_assign WAW)
    p, blk = base()
    blk.create_var(name="t", shape=(2, 3), dtype="float32")
    blk.create_var(name="u", shape=(2, 3), dtype="float32")
    blk.append_op(Operator("scale", lambda a: a * 2.0, ["x"], ["t"], {}))
    blk.append_op(Operator("scale", lambda a: a * 3.0, ["x"], ["u"], {}))
    blk.append_op(Operator("assign_to", lambda a: a, ["u"], ["t"], {}))
    blk.append_op(Operator("scale", lambda a: a * 1.0, ["t"], ["t"], {}))
    yield "WAW clobber via record_assign", "PTA004", p, ("t",)

    # PTA006: recorded dtype disagrees with what the kernel produces
    p, blk = base()
    blk.create_var(name="z", shape=(2, 3), dtype="int32")  # lie: it's f32
    blk.append_op(Operator("relu", lambda a: jnp.maximum(a, 0),
                           ["x"], ["z"], {}))
    yield "dtype drift", "PTA006", p, ("z",)

    # PTA005: recorded shape disagrees with what the kernel produces
    p, blk = base()
    blk.create_var(name="s", shape=(5, 7), dtype="float32")  # lie: (2,3)
    blk.append_op(Operator("relu", lambda a: jnp.maximum(a, 0),
                           ["x"], ["s"], {}))
    yield "shape drift", "PTA005", p, ("s",)

    # PTA007: donated persistable read after its last write
    p, blk = base()
    blk.create_var(name="w", shape=(2, 3), dtype="float32",
                   persistable=True)
    blk.create_var(name="r", shape=(2, 3), dtype="float32")
    blk.append_op(Operator("axpy", lambda a, b: a + b, ["x", "w"], ["w"], {}))
    blk.append_op(Operator("scale", lambda a: a * 2.0, ["w"], ["r"], {}))
    yield "donated-then-read persistable", "PTA007", p, ("r",)

    # PTA001: use before def
    p, blk = base()
    blk.create_var(name="tmp", shape=(2, 3), dtype="float32")
    blk.create_var(name="o", shape=(2, 3), dtype="float32")
    blk.append_op(Operator("scale", lambda a: a * 2.0, ["tmp"], ["o"], {}))
    blk.append_op(Operator("scale", lambda a: a * 0.5, ["x"], ["tmp"], {}))
    yield "use before def", "PTA001", p, ("o",)


def self_test():
    from paddle_tpu.analysis import verify_program

    failures = []
    for label, code, prog, fetch in _broken_programs():
        rep = verify_program(prog, fetch_names=fetch, raise_on_error=False)
        got = {d.code for d in rep.errors()}
        status = "ok" if code in got else f"MISSING (got {sorted(got)})"
        print(f"  {label:36s} expects {code}: {status}")
        if code not in got:
            failures.append(label)

    # DCE sanity: an unreachable op disappears, a reachable one stays
    import jax.numpy as jnp
    from paddle_tpu.analysis import run_compile_passes
    from paddle_tpu.static_.program import Operator, Program

    p = Program()
    blk = p.global_block
    blk.create_var(name="x", shape=(2,), dtype="float32", is_data=True)
    blk.create_var(name="kept", shape=(2,), dtype="float32")
    blk.create_var(name="dead", shape=(2,), dtype="float32")
    blk.append_op(Operator("scale", lambda a: a * 2.0, ["x"], ["kept"], {}))
    blk.append_op(Operator("scale", lambda a: a * 3.0, ["x"], ["dead"], {}))
    ops, _ = run_compile_passes(p, fetch_list=["kept"], optimize_level=1)
    status = "ok" if len(ops) == 1 else f"MISSING (kept {len(ops)} ops)"
    print(f"  {'dead-op elimination':36s} expects 1 live op: {status}")
    if len(ops) != 1:
        failures.append("dce")

    # liveness/memory: a hand-computed 3-op fixture.
    #   x (feed, 2x3 f32 = 24 B) -> t = scale(x); u = relu(t);
    #   o = mul(t, u); fetch o.
    # Intervals: t def@0 last_use@2, u def@1 last_use@2, o def@2
    # live-out. Peak: args 24 (x) + outputs 24 (o) + temps 48 (t and u
    # both live during op#2) = 96 B.
    from paddle_tpu.analysis import memory as M
    from paddle_tpu.analysis import dataflow as DF

    p = Program()
    blk = p.global_block
    blk.create_var(name="x", shape=(2, 3), dtype="float32", is_data=True)
    for n in ("t", "u", "o"):
        blk.create_var(name=n, shape=(2, 3), dtype="float32")
    blk.append_op(Operator("scale", lambda a: a * 2.0, ["x"], ["t"], {}))
    blk.append_op(Operator("relu", lambda a: jnp.maximum(a, 0),
                           ["t"], ["u"], {}))
    blk.append_op(Operator("multiply", lambda a, b: a * b,
                           ["t", "u"], ["o"], {}))
    live = DF.analyze(p, fetch_names=("o",))
    want = {"t": (0, 2), "u": (1, 2), "o": (2, 3)}
    got = {l.name: (l.def_idx, l.last_use) for l in live.lives
           if l.kind == "temp"}
    status = "ok" if got == want else f"MISSING (got {got})"
    print(f"  {'3-op liveness intervals':36s} expects {want}: {status}")
    if got != want:
        failures.append("liveness intervals")
    est = M.estimate_entry(p, fetch_list=["o"])
    status = "ok" if est.peak_bytes == 96 and est.temp_peak_bytes == 48 \
        else f"MISSING (peak {est.peak_bytes}, temps {est.temp_peak_bytes})"
    print(f"  {'3-op peak bytes':36s} expects 96 (temps 48): {status}")
    if est.peak_bytes != 96 or est.temp_peak_bytes != 48:
        failures.append("peak bytes")

    if failures:
        print(f"self-test FAILED: {failures}")
        return 1
    print("self-test passed: every seeded malformed-Program class is "
          "rejected with its distinct diagnostic, and the 3-op "
          "liveness/peak-bytes fixture matches the hand computation")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("script", nargs="?", help="program-building script")
    ap.add_argument("--optimize-level", type=int, default=1,
                    help="pass pipeline level to preview (0/1/2)")
    ap.add_argument("--memory", action="store_true",
                    help="add the liveness table, predicted peak HBM, "
                         "and remat candidates per Program")
    ap.add_argument("--devices", type=int, default=1,
                    help="data-parallel device count for the per-device "
                         "peak (--memory)")
    ap.add_argument("--self-test", action="store_true",
                    help="verify the verifier against seeded broken programs")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.script:
        ap.error("a script path is required unless --self-test is given")
    return lint_script(args.script, args.optimize_level,
                       memory=args.memory, devices=args.devices)


if __name__ == "__main__":
    sys.exit(main())
