#!/usr/bin/env python
"""shard_report: sharding, collective, and comm-roofline tables.

The operational front door for ``paddle_tpu.obs.spmd`` — the view the
reference's fleet layer never had (its NCCL comm was log spew): per
compiled Executor entry, how every feed/persistable/fetch is laid out
on the mesh, how many bytes of each collective kind one step moves and
over which mesh axes, and whether the step is compute- or comm-bound
against the chip's ICI bandwidth.

Usage:
    python tools/shard_report.py RUN_DIR           # from a run journal:
        # sharding events + per-step comm records -> tables
    python tools/shard_report.py RUN_DIR --json
    python tools/shard_report.py --self-test       # canned-HLO parsing
        # vs hand-computed byte volumes + a real 8-fake-device
        # with_data_parallel run (nonzero all-reduce bytes, correct
        # feed sharding, roofline math)

In-process (a live Python session), skip the CLI:
    from tools.shard_report import executor_report
    print(executor_report(exe))        # exe: paddle_tpu.static.Executor

Wired into tier-1 via tests/test_tooling.py (chaos_run/obs_report/
run_report pattern).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ensure_fake_devices(n=8):
    """Standalone runs need the fake-device CPU platform configured
    BEFORE jax initializes; under pytest the conftest already did."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    import jax

    return len(jax.devices())


def _fmt_bytes(n):
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _table(rows, headers):
    rows = [[str(c) for c in r] for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


# -- rendering ----------------------------------------------------------------


def render_sharding(summary):
    """One journal ``sharding`` event (obs.spmd.sharding_summary) as a
    table block."""
    lines = [f"entry uid={summary.get('program_uid')} "
             f"v{summary.get('program_version')}  "
             f"mesh={summary.get('mesh')}  "
             f"vars={summary.get('n_vars')}  "
             f"total={_fmt_bytes(summary.get('total_bytes'))}  "
             f"per-device={_fmt_bytes(summary.get('per_device_bytes'))}"]
    rows = [(v.get("name"), v.get("role"), v.get("spec"),
             _fmt_bytes(v.get("bytes")),
             _fmt_bytes(v.get("per_device_bytes")))
            for v in summary.get("vars", [])]
    if rows:
        lines.append(_table(rows, ("var", "role", "spec", "bytes",
                                   "bytes/dev")))
    return "\n".join(lines)


def render_collectives(profile):
    """One CollectiveProfile as a per-kind + per-axis table block."""
    if not profile or not profile.get("n_ops"):
        return "collectives  none (single-device or replicated entry)"
    rows = [(k, profile["counts"].get(k, 0),
             _fmt_bytes(profile["bytes"].get(k, 0)))
            for k in sorted(profile.get("counts", {}))]
    lines = [_table(rows, ("collective", "ops", "bytes/step"))]
    ax = profile.get("by_axis") or {}
    if ax:
        lines.append("by mesh axis: " + ", ".join(
            f"{a}={_fmt_bytes(b)}" for a, b in sorted(ax.items())))
    total = (f"total {_fmt_bytes(profile.get('total_bytes'))} "
             f"(wire {_fmt_bytes(profile.get('wire_bytes'))}")
    # the int8-payload share of the wire (dist.gradcomm quantized
    # exchange): how much of the traffic already rides compressed
    if profile.get("quant_wire_bytes"):
        total += (f", quantized wire "
                  f"{_fmt_bytes(profile['quant_wire_bytes'])}")
    lines.append(total + ")")
    return "\n".join(lines)


def render_roofline(rl):
    parts = [f"comm {_fmt_bytes(rl.get('comm_bytes'))} "
             f"(wire {_fmt_bytes(rl.get('wire_bytes'))})"]
    if rl.get("ici_bw"):
        parts.append(f"ici_bw {rl['ici_bw'] / 1e9:.0f}GB/s")
    if rl.get("comm_time_s") is not None:
        parts.append(f"comm_time {rl['comm_time_s'] * 1e6:.1f}us")
    if rl.get("compute_time_s") is not None:
        parts.append(f"compute_time {rl['compute_time_s'] * 1e6:.1f}us")
    if rl.get("comm_share") is not None:
        parts.append(f"comm_share {rl['comm_share']:.1%} "
                     f"({rl['bound']}-bound)")
    else:
        parts.append("comm_share ? (no ICI bandwidth known — set "
                     "PADDLE_TPU_ICI_BW)")
    return "roofline     " + "  ".join(parts)


# -- sources ------------------------------------------------------------------


def executor_report(exe, as_json=False):
    """Live-process report over one Executor's jit cache: sharding +
    collectives + roofline per entry. BLOCKING on first call per entry
    (pays the lazy entry_analysis compile)."""
    from paddle_tpu.obs import spmd

    blocks = []
    data = []
    stats = exe.cache_stats(per_entry=True)
    for compiled, entry in zip(exe._cache.values(),
                               stats.get("entries", [])):
        rep = spmd.sharding_summary(compiled)
        prof = entry.get("collectives")
        rl = spmd.comm_roofline(prof, flops=entry.get("flops"))
        data.append({"sharding": rep, "collectives": prof,
                     "roofline": rl})
        blocks += [render_sharding(rep), render_collectives(prof),
                   render_roofline(rl), ""]
    if as_json:
        return json.dumps(data, indent=1, default=str, sort_keys=True)
    return "\n".join(blocks).rstrip() or "executor cache is empty"


def _load_run(run_dir):
    """tools/run_report.py's rotation-aware journal loader (tools/ is
    not a package: load it the way tests/test_tooling.py does)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "run_report_for_shard_report",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "run_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load_run(run_dir)


def journal_report(run_dir, as_json=False):
    """Report from a run journal dir: the per-compile ``sharding``
    events plus the per-step comm deltas the journal recorded."""
    run = _load_run(run_dir)
    shardings = [e for e in run["events"] if e.get("kind") == "sharding"]
    comm_steps = [s for s in run["steps"] if s.get("comm")]
    agg = {"steps_with_comm": len(comm_steps)}
    if comm_steps:
        n = len(comm_steps)
        agg["all_reduce_bytes_per_step"] = sum(
            s["comm"].get("all_reduce_bytes", 0) for s in comm_steps) / n
        agg["total_bytes_per_step"] = sum(
            s["comm"].get("total_bytes", 0) for s in comm_steps) / n
        agg["wire_bytes_per_step"] = sum(
            s["comm"].get("wire_bytes", 0) for s in comm_steps) / n
        agg["quant_wire_bytes_per_step"] = sum(
            s["comm"].get("quant_wire_bytes", 0) for s in comm_steps) / n
    summ = run.get("summary") or {}
    if as_json:
        return json.dumps({"shardings": shardings, "comm": agg,
                           "summary": summ}, indent=1, default=str,
                          sort_keys=True)
    lines = [f"run_dir      {run_dir}"]
    for e in shardings:
        lines += [render_sharding(e), ""]
    if comm_steps:
        line = (
            f"comm/step    all-reduce "
            f"{_fmt_bytes(agg['all_reduce_bytes_per_step'])}  total "
            f"{_fmt_bytes(agg['total_bytes_per_step'])}  wire "
            f"{_fmt_bytes(agg['wire_bytes_per_step'])}")
        if agg.get("quant_wire_bytes_per_step"):
            line += (f"  quantized wire "
                     f"{_fmt_bytes(agg['quant_wire_bytes_per_step'])}")
        lines.append(line + f"  ({len(comm_steps)}/{len(run['steps'])} "
                            "steps attributed)")
    else:
        lines.append("comm/step    no comm-attributed steps (analysis "
                     "may not have landed before the run ended)")
    if summ.get("comm_share") is not None:
        lines.append(f"comm_share   {summ['comm_share']:.1%} "
                     f"({summ.get('comm_bound')}-bound)")
    return "\n".join(lines)


# -- self-test ----------------------------------------------------------------

# canned HLO fixtures with HAND-COMPUTED expectations (no backend needed):
# bytes convention = result-shape bytes (sync tuples summed, async -start
# bundles pick the result element; see obs/spmd.py module docstring)
CANNED_HLO = [
    {
        "name": "sync all-reduce f32[128,64], 1 group of 8",
        "hlo": "%all-reduce.1 = f32[128,64]{1,0} all-reduce("
               "f32[128,64]{1,0} %dot), channel_id=1, "
               "replica_groups=[1,8]<=[8], use_global_device_ids=true, "
               "to_apply=%add",
        # 128*64*4 = 32768 bytes; 8-ring wire factor 2*(8-1)/8 = 1.75
        "counts": {"all-reduce": 1}, "bytes": {"all-reduce": 32768},
        "total": 32768, "wire": 57344,
        "mesh": ({"data": 8}, list(range(8))), "axes": {"data": 32768},
    },
    {
        "name": "async all-gather start/done pair counts once",
        # real XLA async form: the -start's result is an
        # (operand, result) TUPLE — the parser must pick the gathered
        # result (4*256*2 = 2048 B), not sum the bundle
        "hlo": "%ag-start = (bf16[4,32]{1,0}, bf16[4,256]{1,0}) "
               "all-gather-start(bf16[4,32]{1,0} %p), "
               "replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={1}\n"
               "%ag-done = bf16[4,256]{1,0} all-gather-done("
               "(bf16[4,32]{1,0}, bf16[4,256]{1,0}) %ag-start)",
        # wire (8-1)/8 * 2048 = 1792
        "counts": {"all-gather": 1}, "bytes": {"all-gather": 2048},
        "total": 2048, "wire": 1792, "mesh": None, "axes": None,
    },
    {
        "name": "reduce-scatter + tuple all-to-all on a 2x4 mesh",
        "hlo": "%rs = f32[16,8]{1,0} reduce-scatter(f32[64,8]{1,0} %x), "
               "replica_groups=[2,4]<=[8], dimensions={0}, "
               "to_apply=%add\n"
               "%a2a = (f32[8,8]{1,0}, f32[8,8]{1,0}) all-to-all("
               "f32[8,8]{1,0} %a, f32[8,8]{1,0} %b), "
               "replica_groups=[4,2]<=[2,4]T(1,0)",
        # rs: 16*8*4 = 512 B result (one shard of 4), groups {0..3},
        #     {4..7} = 'model' axis on mesh {data:2, model:4};
        #     wire (4-1)/4 of the FULL 512*4 payload = 1536
        # a2a: tuple 2*(8*8*4) = 512 B, groups of 2 along 'data'
        #     ({0,4},{1,5},... via the T(1,0)); wire (2-1)/2 * 512 = 256
        "counts": {"reduce-scatter": 1, "all-to-all": 1},
        "bytes": {"reduce-scatter": 512, "all-to-all": 512},
        "total": 1024, "wire": 1792,
        "mesh": ({"data": 2, "model": 4}, list(range(8))),
        "axes": {"model": 512, "data": 512},
    },
    {
        "name": "collective-permute via source_target_pairs",
        "hlo": "%cp = f32[32]{0} collective-permute(f32[32]{0} %p), "
               "channel_id=3, source_target_pairs={{0,1},{1,2},{2,3},"
               "{3,0}}",
        "counts": {"collective-permute": 1},
        "bytes": {"collective-permute": 128},
        "total": 128, "wire": 128, "mesh": None, "axes": None,
    },
]


# the comm-efficient DP story as canned partitioned-HLO fixtures with
# hand-computed totals (dist.gradcomm): the same 4096-element f32
# gradient payload exchanged three ways on an 8-device ring. Shapes are
# per-partition (what entry_hlo of an SPMD module shows).
COMM_FIXTURES = {
    # 3 per-parameter all-reduces: 2048+1536+512 f32 = 16384 B,
    # ring wire 2(n-1)/n = 1.75x -> 28672 B
    "unbucketed": (
        "%ar.1 = f32[2048]{0} all-reduce(f32[2048]{0} %g0), "
        "replica_groups=[1,8]<=[8], to_apply=%add\n"
        "%ar.2 = f32[1536]{0} all-reduce(f32[1536]{0} %g1), "
        "replica_groups=[1,8]<=[8], to_apply=%add\n"
        "%ar.3 = f32[512]{0} all-reduce(f32[512]{0} %g2), "
        "replica_groups=[1,8]<=[8], to_apply=%add"),
    # ONE flat-bucket all-reduce: same 16384 B / 28672 B wire, 1 op
    "bucketed": (
        "%ar.1 = f32[4096]{0} all-reduce(f32[4096]{0} %bucket), "
        "replica_groups=[1,8]<=[8], to_apply=%add"),
    # int8 two-phase exchange (EQuARX shape): phase-1 s8 all-to-all of
    # the 8x512 chunk grid (4096 B), phase-2 s8 all-gather of the
    # reduced chunks (4096 B), plus two f32[8,1] scale all-gathers
    # (32 B each). totals 8256 B; wire (n-1)/n = 7/8 per op ->
    # 3584+3584+28+28 = 7224 B; quantized (s8) share 8192 B / 7168 B
    # wire. vs fp32 bucketed wire: 28672/7224 = 3.97x less traffic
    "quantized": (
        "%a2a = s8[8,512]{1,0} all-to-all(s8[8,512]{1,0} %q1), "
        "replica_groups=[1,8]<=[8]\n"
        "%ags1 = f32[8,1]{1,0} all-gather(f32[1,1]{1,0} %s1), "
        "replica_groups=[1,8]<=[8], dimensions={0}\n"
        "%ag = s8[4096]{0} all-gather(s8[512]{0} %q2), "
        "replica_groups=[1,8]<=[8], dimensions={0}\n"
        "%ags2 = f32[8,1]{1,0} all-gather(f32[1,1]{1,0} %s2), "
        "replica_groups=[1,8]<=[8], dimensions={0}"),
}


def _check(failures, cond, msg):
    if not cond:
        failures.append(msg)


def self_test():
    ndev = _ensure_fake_devices(8)
    import numpy as np

    from paddle_tpu.obs import spmd

    failures = []

    # 1) canned HLO vs hand-computed byte volumes / axis attribution
    for case in CANNED_HLO:
        mesh = case["mesh"]
        if mesh is not None:
            axes, ids = mesh
            mesh = (axes, np.asarray(ids).reshape(list(axes.values())))
        prof = spmd.collective_profile(case["hlo"], mesh=mesh)
        for field in ("counts", "bytes"):
            _check(failures, prof[field] == case[field],
                   f"{case['name']}: {field} {prof[field]} != "
                   f"{case[field]}")
        _check(failures, prof["total_bytes"] == case["total"],
               f"{case['name']}: total {prof['total_bytes']} != "
               f"{case['total']}")
        _check(failures, prof["wire_bytes"] == case["wire"],
               f"{case['name']}: wire {prof['wire_bytes']} != "
               f"{case['wire']}")
        if case["axes"] is not None:
            _check(failures, prof["by_axis"] == case["axes"],
                   f"{case['name']}: by_axis {prof['by_axis']} != "
                   f"{case['axes']}")

    # 1b) bucketed / unbucketed / int8-quantized exchange fixtures with
    # hand-computed totals (the dist.gradcomm wire-byte story)
    unb = spmd.collective_profile(COMM_FIXTURES["unbucketed"])
    buc = spmd.collective_profile(COMM_FIXTURES["bucketed"])
    qnt = spmd.collective_profile(COMM_FIXTURES["quantized"])
    _check(failures, unb["counts"] == {"all-reduce": 3} and
           unb["total_bytes"] == 16384 and unb["wire_bytes"] == 28672,
           f"unbucketed fixture off hand-computed totals: {unb}")
    _check(failures, buc["counts"] == {"all-reduce": 1} and
           buc["total_bytes"] == 16384 and buc["wire_bytes"] == 28672,
           f"bucketed fixture off hand-computed totals: {buc}")
    _check(failures, buc["n_ops"] < unb["n_ops"],
           "bucketing must strictly reduce collective op count")
    _check(failures, qnt["total_bytes"] == 8256 and
           qnt["wire_bytes"] == 7224,
           f"quantized fixture off hand-computed totals: {qnt}")
    _check(failures, qnt["quant_bytes"] == 8192 and
           qnt["quant_wire_bytes"] == 7168,
           f"quantized-share accounting off: {qnt}")
    _check(failures, unb["quant_wire_bytes"] == 0 and
           buc["quant_wire_bytes"] == 0,
           "fp32 fixtures must report zero quantized wire bytes")
    ratio = buc["wire_bytes"] / qnt["wire_bytes"]
    _check(failures, 3.8 < ratio < 4.2,
           f"int8 exchange wire ratio {ratio:.2f} not ~4x")
    _check(failures, "quantized wire" in render_collectives(qnt) and
           "quantized wire" not in render_collectives(buc),
           "render_collectives quantized-wire column wrong")

    # 2) real 8-fake-device with_data_parallel run: nonzero all-reduce
    # bytes, feeds sharded on 'data', per-device footprint = 1/ndev
    if ndev < 2:
        failures.append(f"need >=2 fake devices for the live check, "
                        f"have {ndev}")
    else:
        failures += _live_dp_check(ndev)

    # 3) roofline math on known numbers
    rl = spmd.comm_roofline({"total_bytes": 1000, "wire_bytes": 2000},
                            flops=1e9, peak=1e12, bw=1e9)
    _check(failures, abs(rl["comm_time_s"] - 2e-6) < 1e-12,
           f"roofline comm_time {rl['comm_time_s']} != 2e-6")
    _check(failures, abs(rl["compute_time_s"] - 1e-3) < 1e-9,
           f"roofline compute_time {rl['compute_time_s']} != 1e-3")
    _check(failures, rl["bound"] == "compute",
           f"roofline bound {rl['bound']} != compute")
    _check(failures,
           abs(rl["comm_share"] - 2e-6 / (2e-6 + 1e-3)) < 1e-9,
           f"roofline comm_share {rl['comm_share']} off")

    for line in failures:
        print(f"  FAILED — {line}")
    if failures:
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: canned-HLO collective parsing matches "
          "hand-computed byte volumes (incl. async pairs, iota replica "
          "groups, axis attribution), the bucketed/unbucketed/int8 "
          "exchange fixtures hold hand-computed totals (1 vs 3 ops, "
          "~4x wire reduction, exact quantized-share bytes), the "
          "8-device data-parallel entry reports nonzero all-reduce "
          "bytes with feeds sharded on 'data', and the comm roofline "
          "math checks out")
    return 0


def _live_dp_check(ndev):
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optim
    from paddle_tpu.obs import mfu, spmd
    from paddle_tpu.static_.compiler import CompiledProgram

    failures = []
    B = 2 * ndev
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [B, 8], "float32")
            y = pt.static.data("y", [B], "int64")
            h = pt.static.nn.fc(x, size=16, act="relu")
            logits = pt.static.nn.fc(h, size=4)
            loss = F.cross_entropy(logits, y)
            optim.Momentum(0.01, 0.9).minimize(loss)
    finally:
        pt.disable_static()
    exe = pt.static.Executor()
    exe.run(startup)
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(B, 8).astype("float32"),
            "y": rng.randint(0, 4, (B,)).astype("int64")}
    exe.run(cp, feed=feed, fetch_list=[loss])

    compiled = next(iter(exe._cache.values()))
    analysis = mfu.entry_analysis(compiled)  # blocking: off-step here
    prof = analysis.get("collectives")
    _check(failures, prof is not None and prof.get("n_ops", 0) > 0,
           f"data-parallel entry reports no collectives: {prof}")
    ar = (prof or {}).get("bytes", {}).get("all-reduce", 0)
    _check(failures, ar > 0,
           f"data-parallel grad sync must show all-reduce bytes, "
           f"got {prof}")
    _check(failures, (prof or {}).get("by_axis", {}).get("data", 0) > 0,
           f"all-reduce not attributed to the 'data' axis: "
           f"{(prof or {}).get('by_axis')}")

    rep = spmd.sharding_report(compiled)
    by_name = {r["name"]: r for r in rep["vars"]}
    _check(failures, rep["mesh"] == {"data": ndev},
           f"mesh {rep['mesh']} != {{'data': {ndev}}}")
    for name in ("x", "y"):
        r = by_name.get(name)
        _check(failures, r is not None and r["spec"] == "data",
               f"feed {name} not sharded on 'data': "
               f"{r and r['spec']}")
        _check(failures,
               r is not None and
               r["per_device_bytes"] * ndev == r["bytes"],
               f"feed {name} per-device bytes "
               f"{r and r['per_device_bytes']} != bytes/{ndev}")
    w = [r for r in rep["vars"] if r["role"].startswith("persistable")]
    _check(failures, w and all(r["spec"] == "replicated" for r in w),
           "persistables must report replicated placement")

    # the rendered report must carry the numbers (CLI contract)
    text = executor_report(exe)
    _check(failures, "all-reduce" in text and "data" in text,
           f"rendered report missing collective/mesh info:\n{text}")
    return failures


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="run-journal dir (PADDLE_TPU_RUN_DIR of a past "
                         "run)")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--self-test", action="store_true",
                    help="canned-HLO byte accounting + live 8-device "
                         "data-parallel sharding/collective checks")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.run_dir:
        ap.error("need a run dir (or --self-test); for a live process "
                 "use tools.shard_report.executor_report(exe)")
    print(journal_report(args.run_dir, as_json=args.json))
    return 0


if __name__ == "__main__":
    sys.exit(main())
