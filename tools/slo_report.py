#!/usr/bin/env python
"""slo_report: render, gate, and self-test serving SLO compliance.

The operational front door for ``paddle_tpu.obs.slo`` (the SLO
complement of tools/request_report.py): a serve run dir's journals
(top-level single-engine, ``router/``, ``rank_NN/``) carry the
evaluator's latched ``slo.fire``/``slo.clear`` events, the final
``slo.summary`` truth, and the raw per-request records. This CLI
renders the alert timeline and per-objective budget, re-evaluates a
finished run against a declarative spec (the same exact percentile
math ``serve_bench --slo`` gates on), and diffs two runs as an SLO
regression gate.

Usage:
    python tools/slo_report.py RUN_DIR               # timeline + budget
    python tools/slo_report.py RUN_DIR --json
    python tools/slo_report.py RUN_DIR \\
        --spec '{"ttft_p99_ms": 250, "availability": 0.999}'
        # also accepts --spec @spec.json; exit 1 on violation
    python tools/slo_report.py --diff BASE_DIR NEW_DIR \\
        [--spec SPEC] [--latency-threshold 0.25]     # exit 1 on regression
    python tools/slo_report.py --self-test
        # ManualClock burn-rate fixture: the 14.4x fast-burn page fires
        # at the hand-computed instant, clears on recovery, never
        # double-fires while latched; the scraped slo_burn_rate gauge is
        # bitwise-equal to the evaluator's float; the journal timeline
        # reconstructs the evaluator's alert log; A-vs-A diffs clean.

``--self-test`` is wired into tier-1 via tests/test_tooling.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DEFAULT_LATENCY_THRESHOLD = 0.25  # p99 latency may grow 25% (--diff)


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


# -- render ------------------------------------------------------------------


def report(run_dir, specs=None):
    """Everything this CLI knows about one run: the pooled journal's
    SLO timeline (``fleet.slo_summary``) plus, with a spec, the
    post-hoc ``slo.evaluate_run`` verdict."""
    from paddle_tpu.obs import fleet as F
    from paddle_tpu.obs import slo as S

    pooled = S.load_any(run_dir)
    merged = {"events": pooled["events"],
              "requests": pooled["requests"]}
    rep = {"run_dir": pooled["run_dir"],
           "slo": F.slo_summary(merged),
           "requests": len(pooled["requests"]),
           "evaluation": None}
    if specs is not None:
        rep["evaluation"] = S.evaluate_run(pooled, specs)
    return rep


def render(rep, as_json=False):
    if as_json:
        return json.dumps(rep, indent=1, default=str, sort_keys=True)
    lines = [f"slo run      {rep.get('run_dir', '?')}",
             f"requests     {rep.get('requests', 0)}"]
    slo = rep.get("slo")
    if slo is None:
        lines.append("no slo.* events in this run's journals "
                     "(evaluator not installed?)")
    else:
        lines.append(f"alerts       {slo['fires']} fired / "
                     f"{slo['clears']} cleared"
                     + (f" / still firing: "
                        f"{', '.join(slo['active_at_end'])}"
                        if slo["active_at_end"] else ""))
        if slo.get("summary"):
            lines.append(f"{'objective':<16} {'budget_left':>11} "
                         f"{'burn_5m':>8} {'fires':>6}")
            for name, row in sorted(slo["summary"].items()):
                lines.append(
                    f"{name:<16} "
                    f"{_fmt(row.get('budget_remaining')):>11} "
                    f"{_fmt(row.get('burn_5m')):>8} "
                    f"{row.get('fires', 0):>6}")
        if slo["timeline"]:
            lines.append("timeline:")
            for t in slo["timeline"]:
                verb = "FIRE " if t["kind"] == "slo.fire" else "clear"
                who = f" worst={t['worst_replica']}" \
                    if t.get("worst_replica") is not None else ""
                lines.append(
                    f"  t={_fmt(t['at'], 6):>8} {verb} "
                    f"{t['objective']}/{t['severity']} "
                    f"burn {_fmt(t['burn_short'])}|"
                    f"{_fmt(t['burn_long'])} over {t['windows']} "
                    f"(>= {_fmt(t['threshold'])}){who}")
    ev = rep.get("evaluation")
    if ev is not None:
        lines.append(f"{'objective':<16} {'kind':<13} {'value':>9} "
                     f"{'target':>9} ok")
        for row in ev["objectives"]:
            tgt = row.get("threshold_ms", row.get("floor",
                                                  row.get("target")))
            ok = {True: "yes", False: "VIOLATED",
                  None: "no-data"}[row["ok"]]
            lines.append(f"{row['name']:<16} {row['kind']:<13} "
                         f"{_fmt(row['value']):>9} {_fmt(tgt):>9} {ok}")
        if ev["violations"]:
            lines.append("VIOLATIONS: " + ", ".join(ev["violations"]))
    return "\n".join(lines)


# -- diff (regression gate) --------------------------------------------------


def diff_runs(base, new, specs=None,
              latency_threshold=DEFAULT_LATENCY_THRESHOLD):
    """SLO regression verdict between two runs: a regression is a new
    objective violation the base didn't have, more alert fires than
    the base, or (with a spec) a latency objective whose measured
    value grew more than ``latency_threshold`` relative to the base.
    A-vs-A always diffs clean."""
    brep = report(base, specs)
    nrep = report(new, specs)
    checks = []

    bf = (brep["slo"] or {}).get("fires", 0)
    nf = (nrep["slo"] or {}).get("fires", 0)
    checks.append({"check": "alert_fires", "base": bf, "new": nf,
                   "regressed": nf > bf})
    if specs is not None:
        bviol = set(brep["evaluation"]["violations"])
        nviol = set(nrep["evaluation"]["violations"])
        fresh = sorted(nviol - bviol)
        checks.append({"check": "new_violations", "base": sorted(bviol),
                       "new": sorted(nviol), "regressed": bool(fresh)})
        bvals = {r["name"]: r["value"]
                 for r in brep["evaluation"]["objectives"]}
        for row in nrep["evaluation"]["objectives"]:
            if row["kind"] != "latency":
                continue
            bv, nv = bvals.get(row["name"]), row["value"]
            if bv is None or nv is None or bv <= 0:
                continue
            growth = nv / bv - 1.0
            checks.append({"check": f"{row['name']}_growth",
                           "base": bv, "new": nv, "growth": growth,
                           "regressed": growth > latency_threshold})
    return {"base": brep["run_dir"], "new": nrep["run_dir"],
            "checks": checks,
            "regression": any(c["regressed"] for c in checks)}


def render_diff(rep, as_json=False):
    if as_json:
        return json.dumps(rep, indent=1, default=str, sort_keys=True)
    lines = [f"slo diff     {rep['base']} -> {rep['new']}"]
    for c in rep["checks"]:
        flag = "REGRESSED" if c["regressed"] else "ok"
        extra = f" (+{c['growth']:.1%})" if "growth" in c else ""
        lines.append(f"  {c['check']:<22} {_fmt(c['base'])} -> "
                     f"{_fmt(c['new'])}{extra}  {flag}")
    lines.append("REGRESSION" if rep["regression"] else "clean")
    return "\n".join(lines)


# -- self-test ---------------------------------------------------------------


def _burn_fixture(run_dir, clock):
    """Drive the canonical availability fixture under a journal:
    target 0.99, 60 s ticks, 100 requests/tick; 40 clean warmup ticks,
    20 bad ticks at 50% rejects, 30 clean recovery ticks. Returns the
    evaluator plus the tick indices where each alert fired/cleared.

    Hand computation (exact because 60 s ticks align with the window
    edges): during the bad phase the 5m window (5 ticks) saturates at
    bad fraction 0.5 -> burn 50 >= 14.4 from bad tick 5; the 30m
    window (30 ticks) holds k bad ticks out of 30 after bad tick k, so
    burn_30m = (50k/3000)/0.01 = 5k/3 >= 14.4 first at k = 9 -> the
    page (needing BOTH) fires at bad tick 9. The warn's 3h window
    falls back to full history (40+k ticks): burn_3h =
    50k/(4000+100k)/0.01 >= 6 first at k = 6 (burn_30m = 10 >= 6
    there already) -> the warn fires at bad tick 6. In recovery the
    5m window holds 5-m bad ticks after clean tick m: burn_5m =
    10(5-m) < 14.4 first at m = 4 -> the page clears at clean tick 4;
    the 30m window still holds 30-m bad ticks until m = 20, then
    shrinks -- burn_30m = 5(30-m)/3 < 6 first at m = 27 -> the warn
    clears at clean tick 27 (its 3h burn is still ~11.5x: the long
    window is the evidence, the short one the fast clear)."""
    from paddle_tpu.obs import journal as J
    from paddle_tpu.obs.slo import SLOEvaluator

    ev = SLOEvaluator({"availability": 0.99}, clock=clock,
                      interval_s=60.0, include_registry=False)
    journal = J.start_run(run_dir)
    rej, disp = [0], [0]

    def snap():
        return {"serving.router.rejected": ("counter", float(rej[0])),
                "serving.router.dispatched":
                    ("counter", float(disp[0]))}

    def tick(n_rej, n_disp):
        rej[0] += n_rej
        disp[0] += n_disp
        clock.advance(60.0)
        return ev.observe(text=snap(), now=clock())

    marks = {}   # ("page"/"warn", "fire"/"clear") -> tick index
    fire_count = 0
    for _ in range(40):
        tick(0, 100)
    for k in range(1, 21):
        for t in tick(50, 50):
            sev = t["severity"]
            if t["kind"] == "slo.fire":
                if sev == "page":
                    fire_count += 1
                marks.setdefault((sev, "fire"), k)
    for m in range(1, 31):
        for t in tick(0, 100):
            marks.setdefault((t["severity"], "clear"), m)
    ev.journal_summary()
    journal.close()
    return ev, marks, fire_count


def self_test():
    from paddle_tpu.obs import export as ex
    from paddle_tpu.obs import fleet as F
    from paddle_tpu.serving import ManualClock

    failures = []

    def check(name, cond, detail=""):
        if not cond:
            failures.append(f"{name}: {detail}")
            print(f"  FAIL {name} {detail}")
        else:
            print(f"  ok   {name}")

    with tempfile.TemporaryDirectory() as td:
        run_dir = os.path.join(td, "run")
        clock = ManualClock()
        ev, marks, fires = _burn_fixture(run_dir, clock)

        # 1. exact fire/clear instants (see _burn_fixture docstring)
        for sev, kind, want in (("page", "fire", 9),
                                ("page", "clear", 4),
                                ("warn", "fire", 6),
                                ("warn", "clear", 27)):
            got = marks.get((sev, kind))
            check(f"{sev}_{kind}s_at_hand_computed_tick", got == want,
                  f"{sev} {kind}d at tick {got}, expected {want}")
        check("page_latches_once", fires == 1,
              f"{fires} fires while latched, expected exactly 1")

        # 2. the scraped burn gauge is bitwise the evaluator's float
        vals = ex.parse_prometheus_text(ex.prometheus_text(slo=ev))
        for label in ("5m", "30m"):
            key = (f'paddle_tpu_slo_burn_rate{{objective='
                   f'"availability",window="{label}"}}')
            check(f"scraped_burn_{label}_bitwise",
                  vals.get(key) == ev.burn[("availability", label)],
                  f"{vals.get(key)!r} != "
                  f"{ev.burn[('availability', label)]!r}")
        bkey = ('paddle_tpu_slo_budget_remaining'
                '{objective="availability"}')
        check("scraped_budget_bitwise",
              vals.get(bkey) == ev.budget_left["availability"],
              f"{vals.get(bkey)!r} != "
              f"{ev.budget_left['availability']!r}")

        # 3. the journal reconstructs the evaluator's alert log
        rep = report(run_dir)
        slo = rep["slo"]
        check("journal_has_slo_events", slo is not None)
        if slo is not None:
            check("timeline_matches_alert_log",
                  [(t["at"], t["kind"], t["objective"], t["severity"])
                   for t in slo["timeline"]] ==
                  [(t["at"], t["kind"], t["objective"], t["severity"])
                   for t in ev.alert_log],
                  f"{len(slo['timeline'])} journaled vs "
                  f"{len(ev.alert_log)} in-memory transitions")
            check("summary_budget_matches_evaluator",
                  slo["summary"] is not None and
                  slo["summary"]["availability"]["budget_remaining"]
                  == ev.budget_left["availability"])
            check("nothing_firing_at_end", slo["active_at_end"] == [])

        # 4. evaluate_run on the same journal: no requests were served,
        # so availability has no signal -> no-data, not a violation
        evaluated = report(run_dir, specs={"availability": 0.99})
        row = evaluated["evaluation"]["objectives"][0]
        check("no_data_is_not_a_violation",
              row["ok"] is None and
              evaluated["evaluation"]["violations"] == [])

        # 5. A-vs-A diffs clean
        d = diff_runs(run_dir, run_dir, specs={"availability": 0.99})
        check("a_vs_a_diffs_clean", not d["regression"],
              render_diff(d))
        print(render(rep))

    if failures:
        print(f"self-test FAILED: {len(failures)} check(s)")
        return 1
    print("self-test passed: the 14.4x fast-burn page fires/clears at "
          "the hand-computed instants, scrapes bitwise, and the "
          "journal timeline reconstructs the evaluator's alert log")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="serve run dir (render) or two with --diff")
    ap.add_argument("--spec", type=str, default=None,
                    help="SLO spec: inline JSON or @path "
                         '(e.g. \'{"ttft_p99_ms": 250}\'); '
                         "exit 1 on violation")
    ap.add_argument("--diff", action="store_true",
                    help="diff two runs; exit 1 on SLO regression")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--latency-threshold", type=float,
                    default=DEFAULT_LATENCY_THRESHOLD,
                    help="allowed relative p99 latency growth (--diff "
                         "with --spec)")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()
    from paddle_tpu.obs import slo as S

    specs = None if args.spec is None else S.parse_spec_arg(args.spec)
    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two run dirs")
        rep = diff_runs(args.paths[0], args.paths[1], specs=specs,
                        latency_threshold=args.latency_threshold)
        print(render_diff(rep, as_json=args.json))
        return 1 if rep["regression"] else 0
    if len(args.paths) != 1:
        ap.error("need one run dir (or --diff A B / --self-test)")
    rep = report(args.paths[0], specs=specs)
    print(render(rep, as_json=args.json))
    if rep["evaluation"] is not None and \
            rep["evaluation"]["violations"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
