#!/bin/bash
# One-shot real-TPU measurement session. Runs everything the round needs
# from the hardware, STRICTLY SERIALLY (the axon relay dies under
# concurrent TPU processes — see r2/r3 ops notes):
#   1. bench.py            -> bench_out.json + bench_out.log
#   2. tools/tpu_probe.py  -> probe_out.log (pallas kernels on hardware)
#   3. record the bench line into BASELINE.json "published"
# Usage (default env, PYTHONPATH untouched so the axon hook loads):
#   bash tools/tpu_session.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_session_$(date +%H%M%S)}"
mkdir -p "$OUT"
echo "== bench.py (sole TPU process) -> $OUT"
python bench.py > "$OUT/bench_out.json" 2> "$OUT/bench_out.log"
echo "bench rc=$? json:"
cat "$OUT/bench_out.json"
if grep -q bench_failed "$OUT/bench_out.json"; then
  echo "bench failed (tunnel still down?) — skipping probe to avoid"
  echo "a second TPU process against a sick relay"
  exit 1
fi
echo "== tools/tpu_probe.py (after bench fully exited)"
python tools/tpu_probe.py > "$OUT/probe_out.log" 2>&1
echo "probe rc=$?"
cat "$OUT/probe_out.log"
echo "== recording published numbers into BASELINE.json"
python - "$OUT/bench_out.json" <<'EOF'
import json, sys
line = open(sys.argv[1]).read().strip().splitlines()[-1]
bench = json.loads(line)
base = json.load(open("BASELINE.json"))
base["published"] = bench
json.dump(base, open("BASELINE.json", "w"), indent=2)
print("BASELINE.json published <-", bench.get("metric"), bench.get("value"))
EOF
cp "$OUT/probe_out.log" tools/probe_hw_last.log 2>/dev/null || true
echo "== done; commit BASELINE.json + tools/probe_hw_last.log"
