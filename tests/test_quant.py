"""Quantization tests: fake-quant STE, weight-only PTQ accuracy, QAT
training loop, int8 storage (ref: contrib/slim/quantization)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optim
from paddle_tpu.quant import (fake_quantize_abs_max, quantize_abs_max,
                              dequantize, quantize_model, QuantizedLinear,
                              PostTrainingQuantization, QAT)


def _classifier(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    means = rng.randn(4, 16).astype("float32") * 2.0
    y = rng.randint(0, 4, n)
    x = (means[y] + rng.randn(n, 16) * 0.4).astype("float32")
    return x, y.astype("int64")


def _train(model, x, y, steps=40, lr=5e-3):
    opt = optim.Adam(lr, parameters=model.parameters())
    step = pt.TrainStep(model, opt,
                        lambda m, a, b: F.cross_entropy(m(a), b))
    return [float(step(x, y)) for _ in range(steps)]


def _acc(model, x, y):
    model.eval()
    logits = np.asarray(model(pt.to_tensor(x)).numpy())
    return (logits.argmax(-1) == y).mean()


class TestFakeQuant:
    def test_quant_error_bounded(self):
        rng = np.random.RandomState(0)
        x = rng.randn(64, 32).astype("float32")
        q = np.asarray(fake_quantize_abs_max(pt.to_tensor(x),
                                             bits=8).numpy())
        step = np.abs(x).max() / 127
        assert np.abs(q - x).max() <= step / 2 + 1e-6

    def test_straight_through_gradient(self):
        x = pt.to_tensor(np.linspace(-1, 1, 11).astype("float32"))
        x.stop_gradient = False
        fake_quantize_abs_max(x, bits=8).sum().backward()
        g = np.asarray(x.grad.numpy())
        np.testing.assert_allclose(g, np.ones_like(g))  # STE: all pass

    def test_per_channel_scales(self):
        w = np.stack([np.ones(4, "float32"), 100 * np.ones(4, "float32")],
                     axis=1)  # (4, 2): channels differ 100x
        q, s = quantize_abs_max(w, bits=8, channel_axis=1)
        assert q.dtype == np.int8
        deq = np.asarray(dequantize(q, s))
        np.testing.assert_allclose(deq, w, rtol=1e-2)


class TestPTQ:
    def test_weight_only_accuracy_close(self):
        x, y = _data()
        model = _classifier()
        _train(model, x, y)
        fp_acc = _acc(model, x, y)
        quantize_model(model)
        assert isinstance(model[0], QuantizedLinear)
        q_acc = _acc(model, x, y)
        assert fp_acc > 0.9
        assert q_acc >= fp_acc - 0.05, (fp_acc, q_acc)
        # weights really stored int8
        assert str(model[0].qweight.dtype) == "int8"

    def test_calibration_records_act_scales(self):
        from paddle_tpu.io_.dataset import TensorDataset
        from paddle_tpu.io_.dataloader import DataLoader

        x, y = _data(64)
        model = _classifier()
        _train(model, x, y, steps=10)
        loader = DataLoader(TensorDataset([x, y]), batch_size=16)
        ptq = PostTrainingQuantization(model, loader, batch_nums=2)
        qmodel = ptq.quantize()
        qlayers = [l for _, l in qmodel.named_sublayers()
                   if isinstance(l, QuantizedLinear)]
        assert len(qlayers) == 2
        assert all(getattr(l, "act_scale", 0) > 0 for l in qlayers)

    def test_state_dict_roundtrip_after_quant(self):
        x, y = _data(32)
        model = _classifier()
        _train(model, x, y, steps=5)
        quantize_model(model)
        sd = model.state_dict()
        model2 = quantize_model(_classifier(seed=1))
        model2.set_state_dict(sd)
        o1 = np.asarray(model(pt.to_tensor(x[:4])).numpy())
        o2 = np.asarray(model2(pt.to_tensor(x[:4])).numpy())
        np.testing.assert_allclose(o1, o2, atol=1e-6)


class TestQAT:
    def test_qat_trains_and_converts(self):
        x, y = _data()
        model = _classifier()
        qat = QAT(bits=8)
        qat.quantize(model)
        losses = _train(model, x, y, steps=50)
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        qat_acc = _acc(model, x, y)
        qat.convert(model)
        int8_acc = _acc(model, x, y)
        assert qat_acc > 0.9
        # QAT-trained weights should survive real int8 conversion
        assert int8_acc >= qat_acc - 0.05, (qat_acc, int8_acc)
