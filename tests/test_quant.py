"""Quantization tests: fake-quant STE, weight-only PTQ accuracy, QAT
training loop, int8 storage (ref: contrib/slim/quantization)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optim
from paddle_tpu.quant import (fake_quantize_abs_max, quantize_abs_max,
                              dequantize, quantize_model, QuantizedLinear,
                              PostTrainingQuantization, QAT)


def _classifier(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    means = rng.randn(4, 16).astype("float32") * 2.0
    y = rng.randint(0, 4, n)
    x = (means[y] + rng.randn(n, 16) * 0.4).astype("float32")
    return x, y.astype("int64")


def _train(model, x, y, steps=40, lr=5e-3):
    opt = optim.Adam(lr, parameters=model.parameters())
    step = pt.TrainStep(model, opt,
                        lambda m, a, b: F.cross_entropy(m(a), b))
    return [float(step(x, y)) for _ in range(steps)]


def _acc(model, x, y):
    model.eval()
    logits = np.asarray(model(pt.to_tensor(x)).numpy())
    return (logits.argmax(-1) == y).mean()


class TestFakeQuant:
    def test_quant_error_bounded(self):
        rng = np.random.RandomState(0)
        x = rng.randn(64, 32).astype("float32")
        q = np.asarray(fake_quantize_abs_max(pt.to_tensor(x),
                                             bits=8).numpy())
        step = np.abs(x).max() / 127
        assert np.abs(q - x).max() <= step / 2 + 1e-6

    def test_straight_through_gradient(self):
        x = pt.to_tensor(np.linspace(-1, 1, 11).astype("float32"))
        x.stop_gradient = False
        fake_quantize_abs_max(x, bits=8).sum().backward()
        g = np.asarray(x.grad.numpy())
        np.testing.assert_allclose(g, np.ones_like(g))  # STE: all pass

    def test_per_channel_scales(self):
        w = np.stack([np.ones(4, "float32"), 100 * np.ones(4, "float32")],
                     axis=1)  # (4, 2): channels differ 100x
        q, s = quantize_abs_max(w, bits=8, channel_axis=1)
        assert q.dtype == np.int8
        deq = np.asarray(dequantize(q, s))
        np.testing.assert_allclose(deq, w, rtol=1e-2)


class TestPTQ:
    def test_weight_only_accuracy_close(self):
        x, y = _data()
        model = _classifier()
        _train(model, x, y)
        fp_acc = _acc(model, x, y)
        quantize_model(model)
        assert isinstance(model[0], QuantizedLinear)
        q_acc = _acc(model, x, y)
        assert fp_acc > 0.9
        assert q_acc >= fp_acc - 0.05, (fp_acc, q_acc)
        # weights really stored int8
        assert str(model[0].qweight.dtype) == "int8"

    def test_calibration_records_act_scales(self):
        from paddle_tpu.io_.dataset import TensorDataset
        from paddle_tpu.io_.dataloader import DataLoader

        x, y = _data(64)
        model = _classifier()
        _train(model, x, y, steps=10)
        loader = DataLoader(TensorDataset([x, y]), batch_size=16)
        ptq = PostTrainingQuantization(model, loader, batch_nums=2)
        qmodel = ptq.quantize()
        qlayers = [l for _, l in qmodel.named_sublayers()
                   if isinstance(l, QuantizedLinear)]
        assert len(qlayers) == 2
        assert all(getattr(l, "act_scale", 0) > 0 for l in qlayers)

    def test_state_dict_roundtrip_after_quant(self):
        x, y = _data(32)
        model = _classifier()
        _train(model, x, y, steps=5)
        quantize_model(model)
        sd = model.state_dict()
        model2 = quantize_model(_classifier(seed=1))
        model2.set_state_dict(sd)
        o1 = np.asarray(model(pt.to_tensor(x[:4])).numpy())
        o2 = np.asarray(model2(pt.to_tensor(x[:4])).numpy())
        np.testing.assert_allclose(o1, o2, atol=1e-6)


class TestQAT:
    def test_qat_trains_and_converts(self):
        x, y = _data()
        model = _classifier()
        qat = QAT(bits=8)
        qat.quantize(model)
        losses = _train(model, x, y, steps=50)
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        qat_acc = _acc(model, x, y)
        qat.convert(model)
        int8_acc = _acc(model, x, y)
        assert qat_acc > 0.9
        # QAT-trained weights should survive real int8 conversion
        assert int8_acc >= qat_acc - 0.05, (qat_acc, int8_acc)


class TestStaticInt8Predictor:
    """save_inference_model -> quantize_inference_model -> Predictor
    (VERDICT r4 Missing #4; ref post_training_quantization.py:60 +
    quantization_pass.py:703 freeze pass)."""

    def _save_lenet(self, tmp_path):
        from paddle_tpu.models.vision import LeNet

        pt.seed(0)
        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.program_guard(main, startup):
                x = pt.static.data("x", [8, 1, 28, 28], "float32")
                logits = LeNet()(x)
                prob = F.softmax(logits, axis=-1)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        xs = np.random.RandomState(0).randn(8, 1, 28, 28).astype("float32")
        ref, = exe.run(main, feed={"x": xs}, fetch_list=[prob])
        prefix = str(tmp_path / "lenet")
        pt.framework.io.save_inference_model(prefix, ["x"], [prob],
                                             program=main)
        return prefix, xs, np.asarray(ref)

    def test_int8_predictor_accuracy_and_storage(self, tmp_path):
        import os

        from paddle_tpu.inference import Predictor
        from paddle_tpu.quant import quantize_inference_model

        prefix, xs, ref = self._save_lenet(tmp_path)
        quantized = quantize_inference_model(prefix, bits=8)
        # every conv/linear weight above the size floor is quantized
        assert any("conv" in n for n in quantized), quantized
        assert any("linear" in n for n in quantized), quantized

        pred = Predictor(prefix + "_int8")
        out, = pred.run({"x": xs})
        # int8 weight-only: probabilities within ~2% of fp32
        assert np.abs(out - ref).max() < 2e-2, np.abs(out - ref).max()
        assert np.argmax(out, -1).tolist() == np.argmax(ref, -1).tolist()
        # the resident copies really are int8 (HBM 4x cut), not fp32
        wdtypes = {n: str(w.dtype) for n, w in
                   zip(pred._weight_names, pred._weights)}
        assert all(wdtypes[n + "@INT8"] == "int8" for n in quantized), wdtypes
        assert not any(n in wdtypes for n in quantized)
        # bundle on disk shrinks (params dominated by fp32 fc weights)
        orig = os.path.getsize(prefix + ".pdiparams.npz")
        q = os.path.getsize(prefix + "_int8.pdiparams.npz")
        assert q < 0.5 * orig, (orig, q)

    def test_int8_bundle_runs_through_executor(self, tmp_path):
        from paddle_tpu.quant import quantize_inference_model

        prefix, xs, ref = self._save_lenet(tmp_path)
        quantize_inference_model(prefix)
        pt.enable_static()
        try:
            program, feeds, fetches = \
                pt.framework.io.load_inference_model(prefix + "_int8")
            exe = pt.static.Executor()
            out, = exe.run(program, feed={feeds[0]: xs},
                           fetch_list=fetches)
        finally:
            pt.disable_static()
        assert np.abs(np.asarray(out) - ref).max() < 2e-2

    def test_small_and_shared_weights_stay_fp32(self, tmp_path):
        """Weights under the size floor (biases are not slot-1 anyway)
        and non-quantizable-role weights keep exact fp32 copies."""
        from paddle_tpu.quant import quantize_inference_model

        prefix, _, _ = self._save_lenet(tmp_path)
        quantized = quantize_inference_model(prefix, min_elems=10 ** 9)
        assert quantized == []
        import numpy as _np

        data = _np.load(prefix + "_int8.pdiparams.npz")
        assert not [k for k in data.files if k.startswith("q!")]

    def test_requantizing_int8_bundle_refused(self, tmp_path):
        from paddle_tpu.quant import quantize_inference_model

        prefix, _, _ = self._save_lenet(tmp_path)
        quantize_inference_model(prefix)
        with pytest.raises(ValueError, match="already an int8 bundle"):
            quantize_inference_model(prefix + "_int8")

    def test_biasfree_linear_weight_quantizes(self, tmp_path):
        """F.linear with bias=None serializes as 'linear_nobias' (the LM
        -head shape) and must still quantize."""
        from paddle_tpu.inference import Predictor
        from paddle_tpu.quant import quantize_inference_model

        pt.seed(0)
        pt.enable_static()
        try:
            main, startup = pt.static.Program(), pt.static.Program()
            with pt.program_guard(main, startup):
                x = pt.static.data("x", [4, 32], "float32")
                import paddle_tpu.fluid as fluid
                w = fluid.layers.create_parameter([32, 64], "float32",
                                                  name="head_w")
                out = F.linear(x, w)
        finally:
            pt.disable_static()
        exe = pt.static.Executor()
        exe.run(startup)
        xs = np.random.RandomState(1).randn(4, 32).astype("float32")
        ref, = exe.run(main, feed={"x": xs}, fetch_list=[out])
        prefix = str(tmp_path / "head")
        pt.framework.io.save_inference_model(prefix, ["x"], [out],
                                             program=main)
        quantized = quantize_inference_model(prefix)
        assert len(quantized) == 1, quantized
        got, = Predictor(prefix + "_int8").run({"x": xs})
        np.testing.assert_allclose(got, np.asarray(ref), rtol=0.02,
                                   atol=0.02)
