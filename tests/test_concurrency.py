"""Concurrency analysis: the PTC lint, the lockdep runtime, and the
zero-overhead-off contract.

Covers the PR's acceptance criteria:
- static lint fixtures: AB/BA inversion (PTC001, both lock names),
  blocking-under-lock (PTC002 — sleep / untimed queue.get /
  Thread.join, including the acquire()/release() form), unguarded
  cross-thread writes (PTC003), the false-positive guards (str.join,
  timed join/get, Condition.wait on the held lock), waiver comments,
  one-level interprocedural ordering;
- the real paddle_tpu/ tree carries zero unwaived PTC001/PTC002;
- a synthetic two-thread AB/BA harness deterministically produces ONE
  PTC004 with BOTH witness stacks (event-sequenced — no sleeps, no
  timing luck: lockdep flags the cycle at edge-insertion time, before
  anything blocks);
- ``lockdep.held_ms.<name>`` histograms land in the metrics registry;
- ``PADDLE_TPU_LOCKDEP`` off ⇒ zero overhead: the PR-4 poison pattern
  — every lockdep hook set to raise — over the scheduler / KV-cache /
  journal / checkpoint-barrier hot paths;
- lockdep-clean assertions piggyback on the cached serve-fleet and
  elastic gang drills (no new drills: tier-1 runs on a 1-core box) —
  they live next to the other drill consumers in test_serve_fleet.py
  and test_tooling.py so the drills keep their natural late slot in
  the timeout-bounded tier-1 run.
"""
import os
import threading

import pytest

from paddle_tpu.analysis import concurrency as C
from paddle_tpu.obs import lockdep

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_lockdep():
    """Scoped lockdep: enabled (raise) inside the test, prior mode and
    graph restored after — the suite's other tests must never see a
    leftover edge."""
    prev = lockdep.mode()
    lockdep.enable(lockdep.MODE_RAISE)
    lockdep.reset()
    yield lockdep
    if prev is not None:
        lockdep.enable(prev)
    else:
        lockdep.disable()
    lockdep.reset()


# -- static lint -------------------------------------------------------------


class TestStaticLint:
    def test_abba_inversion_flagged_with_both_locks(self):
        src = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def f(self):
        with self._a:
            with self._b:
                pass

    def g(self):
        with self._b:
            with self._a:
                pass
'''
        fs = C.lint_source(src, "x.py")
        inv = [f for f in fs if f.code == "PTC001"]
        assert len(inv) == 1, fs
        assert set(inv[0].locks) == {"S._a", "S._b"}
        assert inv[0].severity == "error"
        # the message points at BOTH sites
        assert "S._a" in inv[0].message and "S._b" in inv[0].message

    def test_blocking_under_lock_all_shapes(self):
        """sleep under with-lock, untimed queue.get, Thread.join via
        the explicit acquire()/release() form."""
        src = '''
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.q = None
        self.worker = None

    def a(self):
        with self._lock:
            time.sleep(0.5)

    def b(self):
        with self._lock:
            return self.q.get()

    def c(self):
        self._lock.acquire()
        self.worker.join()
        self._lock.release()
'''
        fs = C.lint_source(src, "x.py")
        assert [f.code for f in fs] == ["PTC002"] * 3, fs
        assert all("S._lock" in f.locks for f in fs)

    def test_false_positive_guards(self):
        """str.join, os.path.join, timed join/get/wait, nonblocking
        get, and Condition.wait on the HELD lock are all benign."""
        src = '''
import os
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self.q = None

    def a(self, parts, t):
        with self._lock:
            x = ", ".join(parts)
            y = os.path.join("a", "b")
            t.join(timeout=5.0)
            z = self.q.get(timeout=1.0)
            w = self.q.get(block=False)
            return x, y, z, w

    def b(self):
        with self._cv:
            self._cv.wait(0.1)
            self._cv.wait()
'''
        fs = C.lint_source(src, "x.py")
        assert not fs, fs

    def test_release_ends_the_critical_section(self):
        src = '''
import threading
import time

_L = threading.Lock()

def f():
    _L.acquire()
    _L.release()
    time.sleep(0.5)
'''
        assert not C.lint_source(src, "x.py")

    def test_unguarded_cross_thread_write(self):
        src = '''
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self.beat = None
        self._t = threading.Thread(target=self._run)

    def _run(self):
        self.beat = 1.0

    def touch(self):
        self.beat = 2.0
'''
        fs = C.lint_source(src, "x.py")
        assert [f.code for f in fs] == ["PTC003"], fs
        assert fs[0].severity == "warning"
        # advisory: PTC003 never gates the CLI exit code
        assert not C.gate_findings(fs)

    def test_guarded_both_sides_is_silent(self):
        src = '''
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self.beat = None
        self._t = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self.beat = 1.0

    def touch(self):
        with self._lock:
            self.beat = 2.0
'''
        assert not C.lint_source(src, "x.py")

    def test_waiver_comment_downgrades(self):
        src = '''
import threading
import time

_L = threading.Lock()

def f():
    with _L:
        time.sleep(0.1)  # lockdep: waive — fixture sleep

def g():
    with _L:
        time.sleep(0.1)  # noqa: PTC002
'''
        fs = C.lint_source(src, "x.py")
        assert len(fs) == 2 and all(f.waived for f in fs), fs
        assert not C.gate_findings(fs)

    def test_one_level_interprocedural_order(self):
        """g() takes B then calls self.f() whose FIRST lock is A; h()
        takes A then B directly — inversion across the call edge."""
        src = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def f(self):
        with self._a:
            pass

    def g(self):
        with self._b:
            self.f()

    def h(self):
        with self._a:
            with self._b:
                pass
'''
        fs = C.lint_source(src, "x.py")
        assert any(f.code == "PTC001" and
                   set(f.locks) == {"S._a", "S._b"} for f in fs), fs

    def test_paddle_tpu_tree_is_clean(self):
        """The in-tree acceptance gate: zero unwaived PTC001/PTC002
        over the real source tree (true positives found during this
        PR were fixed, and future ones fail here with file:line)."""
        findings = C.lint_tree(os.path.join(ROOT, "paddle_tpu"))
        gating = C.gate_findings(findings)
        assert not gating, "\n".join(repr(f) for f in gating)


# -- lockdep runtime ---------------------------------------------------------


class TestLockdepRuntime:
    def test_off_by_default_returns_plain_primitives(self):
        assert lockdep.mode() is None
        assert type(lockdep.lock("x")) is type(threading.Lock())
        assert type(lockdep.rlock("x")) is type(threading.RLock())

    def test_two_thread_abba_cycle_deterministic(self, clean_lockdep):
        """The synthetic AB/BA harness: t1 records A->B and signals;
        t2 then attempts B->A. Lockdep flags the edge B->A at
        insertion time — BEFORE t2 blocks on A — so the test is
        deterministic with no sleeps and cannot deadlock."""
        A = lockdep.lock("t.A")
        B = lockdep.lock("t.B")
        t1_done = threading.Event()
        caught = {}

        def t1():
            with A:
                with B:
                    pass
            t1_done.set()

        def t2():
            t1_done.wait(30)
            try:
                with B:
                    with A:
                        pass
            except lockdep.LockCycleError as e:
                caught["e"] = e

        th1 = threading.Thread(target=t1)
        th2 = threading.Thread(target=t2)
        th1.start()
        th2.start()
        th1.join(30)
        th2.join(30)

        e = caught.get("e")
        assert e is not None, "PTC004 not raised"
        assert e.code == "PTC004"
        assert set(e.cycle) == {"t.A", "t.B"}
        # BOTH witness stacks: the closing acquisition and the first
        # recorded reverse-order acquisition
        assert e.new_stack and any("t2" in fr for fr in e.new_stack)
        assert e.prev_stack and any("t1" in fr for fr in e.prev_stack)
        viols = lockdep.violations()
        assert len(viols) == 1
        assert viols[0]["new_edge"] == ("t.B", "t.A")
        assert viols[0]["prev_thread"] != viols[0]["new_thread"]

    def test_warn_mode_records_without_raising(self):
        prev = lockdep.mode()
        lockdep.enable(lockdep.MODE_WARN)
        lockdep.reset()
        try:
            A = lockdep.lock("w.A")
            B = lockdep.lock("w.B")
            done = threading.Event()

            def t1():
                with A:
                    with B:
                        pass
                done.set()

            th = threading.Thread(target=t1)
            th.start()
            th.join(30)
            assert done.wait(1)
            with pytest.warns(RuntimeWarning, match="PTC004"):
                with B:
                    with A:
                        pass
            assert len(lockdep.violations()) == 1
        finally:
            if prev is not None:
                lockdep.enable(prev)
            else:
                lockdep.disable()
            lockdep.reset()

    def test_held_time_histograms_in_registry(self, clean_lockdep):
        from paddle_tpu.obs import metrics

        L = lockdep.lock("hist.demo")
        with L:
            pass
        snap = metrics.snapshot()
        assert "lockdep.held_ms.hist.demo" in snap
        hist = snap["lockdep.held_ms.hist.demo"]
        assert hist["count"] == 1

    def test_rlock_reentrancy_is_not_an_edge(self, clean_lockdep):
        R = lockdep.rlock("re.R")
        with R:
            with R:
                pass
        assert not lockdep.violations()
        assert "re.R" not in lockdep.order_graph().get("re.R", [])

    def test_consistent_order_stays_silent(self, clean_lockdep):
        A = lockdep.lock("ok.A")
        B = lockdep.lock("ok.B")
        for _ in range(3):
            with A:
                with B:
                    pass
        assert not lockdep.violations()
        assert lockdep.order_graph() == {"ok.A": ["ok.B"]}

    def test_env_install(self, monkeypatch):
        prev = lockdep.mode()
        try:
            monkeypatch.setenv("PADDLE_TPU_LOCKDEP", "warn")
            lockdep.disable()
            lockdep.install_from_env()
            assert lockdep.mode() == lockdep.MODE_WARN
            monkeypatch.setenv("PADDLE_TPU_LOCKDEP", "0")
            lockdep.disable()
            lockdep.install_from_env()
            assert lockdep.mode() is None
        finally:
            if prev is not None:
                lockdep.enable(prev)
            else:
                lockdep.disable()

    def test_wired_subsystems_use_instrumented_locks(self,
                                                     clean_lockdep):
        """With lockdep on, the wired constructors come out
        instrumented and exercising them builds the documented order
        (scheduler -> cache, scheduler -> journal as leaves) with
        zero violations."""
        from paddle_tpu.serving.kv_cache import PagedKVCache
        from paddle_tpu.serving.scheduler import Request, Scheduler

        cache = PagedKVCache(num_pages=8, page_size=4, num_heads=1,
                             head_dim=4, max_seq_len=16)
        sched = Scheduler(cache, token_budget=16)
        assert type(sched._lock).__name__ == "_DebugLock"
        assert type(cache._lock).__name__ == "_DebugLock"
        sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
        batch = sched.schedule()
        assert batch.prefills
        assert not lockdep.violations()
        graph = lockdep.order_graph()
        assert "serving.kv_cache" in \
            graph.get("serving.scheduler", [])


# -- zero-overhead-off contract (the PR-4 poison pattern) --------------------


class TestLockdepOffZeroOverhead:
    def test_hot_paths_never_touch_lockdep_when_off(self, tmp_path,
                                                    monkeypatch):
        """With PADDLE_TPU_LOCKDEP unset, the factories hand back
        plain threading primitives at construction and the steady
        state pays NOTHING: every lockdep hook is poisoned to raise,
        then the scheduler/cache/journal/checkpoint-barrier paths run
        clean."""
        assert lockdep.mode() is None

        def boom(*a, **k):
            raise AssertionError("lockdep work performed while off")

        monkeypatch.setattr(lockdep._DebugLock, "__init__", boom)
        monkeypatch.setattr(lockdep._DebugLock, "acquire", boom)
        monkeypatch.setattr(lockdep, "_note_edges", boom)
        monkeypatch.setattr(lockdep, "_emit_violation", boom)
        monkeypatch.setattr(lockdep, "_stack", boom)

        from paddle_tpu.framework.io import wait_checkpoints
        from paddle_tpu.obs.journal import RunJournal
        from paddle_tpu.serving.kv_cache import PagedKVCache
        from paddle_tpu.serving.scheduler import Request, Scheduler

        cache = PagedKVCache(num_pages=8, page_size=4, num_heads=1,
                             head_dim=4, max_seq_len=16)
        sched = Scheduler(cache, token_budget=16)
        r = sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
        assert sched.schedule().prefills == [r]
        sid = r.rid
        assert cache.length(sid) >= 0

        j = RunJournal(str(tmp_path / "run"), flush_every=1,
                       compute_flops=False).start()
        j.record_step(loss=0.5, step_ms=1.0)
        j.event("poison.check")
        j.close()

        assert wait_checkpoints() is None  # takes the async barrier


