"""Independent numerical verification against torch (CPU).

The op tests compare against hand-written numpy; this file adds a
SECOND independent implementation for the subtle-semantics ops —
conv variants (stride/padding/dilation/groups), transposed conv,
pooling, batch/layer norm, LSTM/GRU whole-sequence runs, interpolation
corner modes, and the optimizer update rules — so an agreement bug in
our numpy oracle can't hide. Tolerances are float32-accumulation level.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")  # torch is optional in this env

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

@pytest.fixture
def RNG():
    # fresh stream per test: inputs don't depend on selection order
    return np.random.RandomState(7)


def t(x):
    return torch.tensor(x)


def ours(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class TestConvParity:
    @pytest.mark.parametrize("stride,padding,dilation,groups", [
        (1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 2, 1), (1, 1, 1, 3),
    ])
    def test_conv2d(self, stride, padding, dilation, groups, RNG):
        cin = 6
        x = RNG.randn(2, cin, 11, 11).astype("float32")
        w = RNG.randn(9, cin // groups, 3, 3).astype("float32")
        b = RNG.randn(9).astype("float32")
        a = ours(F.conv2d(pt.to_tensor(x), pt.to_tensor(w),
                          pt.to_tensor(b), stride=stride, padding=padding,
                          dilation=dilation, groups=groups))
        e = torch.nn.functional.conv2d(
            t(x), t(w), t(b), stride=stride, padding=padding,
            dilation=dilation, groups=groups).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("stride,padding,output_padding", [
        (1, 0, 0), (2, 1, 1),
    ])
    def test_conv2d_transpose(self, stride, padding, output_padding, RNG):
        x = RNG.randn(2, 4, 7, 7).astype("float32")
        w = RNG.randn(4, 5, 3, 3).astype("float32")
        a = ours(F.conv2d_transpose(
            pt.to_tensor(x), pt.to_tensor(w), stride=stride,
            padding=padding, output_padding=output_padding))
        e = torch.nn.functional.conv_transpose2d(
            t(x), t(w), stride=stride, padding=padding,
            output_padding=output_padding).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

    def test_conv3d(self, RNG):
        x = RNG.randn(1, 3, 6, 6, 6).astype("float32")
        w = RNG.randn(4, 3, 2, 2, 2).astype("float32")
        a = ours(F.conv3d(pt.to_tensor(x), pt.to_tensor(w), stride=2))
        e = torch.nn.functional.conv3d(t(x), t(w), stride=2).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)


class TestPoolNormParity:
    def test_max_avg_pool(self, RNG):
        x = RNG.randn(2, 3, 9, 9).astype("float32")
        a = ours(F.max_pool2d(pt.to_tensor(x), kernel_size=3, stride=2,
                              padding=1))
        e = torch.nn.functional.max_pool2d(t(x), 3, stride=2,
                                           padding=1).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)
        a = ours(F.avg_pool2d(pt.to_tensor(x), kernel_size=2, stride=2))
        e = torch.nn.functional.avg_pool2d(t(x), 2, stride=2).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_adaptive_avg_pool(self, RNG):
        x = RNG.randn(2, 3, 10, 10).astype("float32")
        a = ours(F.adaptive_avg_pool2d(pt.to_tensor(x), 4))
        e = torch.nn.functional.adaptive_avg_pool2d(t(x), 4).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_batch_norm_train_and_eval(self, RNG):
        x = RNG.randn(4, 5, 6, 6).astype("float32")
        g = RNG.rand(5).astype("float32") + 0.5
        b = RNG.randn(5).astype("float32")
        rm = np.zeros(5, "float32")
        rv = np.ones(5, "float32")
        # train mode: batch statistics
        a = ours(F.batch_norm(pt.to_tensor(x), pt.to_tensor(rm.copy()),
                              pt.to_tensor(rv.copy()), pt.to_tensor(g),
                              pt.to_tensor(b), training=True,
                              epsilon=1e-5))
        e = torch.nn.functional.batch_norm(
            t(x), t(rm.copy()), t(rv.copy()), t(g), t(b), training=True,
            eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)
        # eval mode: running statistics
        rm2 = RNG.randn(5).astype("float32")
        rv2 = RNG.rand(5).astype("float32") + 0.5
        a = ours(F.batch_norm(pt.to_tensor(x), pt.to_tensor(rm2),
                              pt.to_tensor(rv2), pt.to_tensor(g),
                              pt.to_tensor(b), training=False,
                              epsilon=1e-5))
        e = torch.nn.functional.batch_norm(
            t(x), t(rm2), t(rv2), t(g), t(b), training=False,
            eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

    def test_layer_norm(self, RNG):
        x = RNG.randn(4, 10).astype("float32")
        g = RNG.rand(10).astype("float32") + 0.5
        b = RNG.randn(10).astype("float32")
        a = ours(F.layer_norm(pt.to_tensor(x), normalized_shape=[10],
                              weight=pt.to_tensor(g), bias=pt.to_tensor(b),
                              epsilon=1e-5))
        e = torch.nn.functional.layer_norm(t(x), [10], t(g), t(b),
                                           eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)


class TestRNNParity:
    @staticmethod
    def _port_weights(torch_rnn, ours_rnn):
        """Copy torch l0 weights (both directions when present) onto
        our layer. Gate orders agree (LSTM i,f,g,o == i,f,c,o; GRU
        r,z,n); our keys are '<cell>.<kind>' where cell '1.' is the
        reverse direction. Transpose by shape where layouts differ,
        and fail loudly on anything else."""
        sd = ours_rnn.state_dict()
        new = {}
        for k in sd:
            cell, kind = (k.split(".", 1) if "." in k else ("0", k))
            suffix = "_reverse" if cell == "1" else ""
            w = getattr(torch_rnn,
                        f"{kind}_l0{suffix}").detach().numpy()
            want = tuple(sd[k].shape)
            if want == w.shape:
                new[k] = w
            elif want == w.shape[::-1]:
                new[k] = w.T
            else:
                raise AssertionError(f"unportable layout for {k}: "
                                     f"{want} vs torch {w.shape}")
        ours_rnn.set_state_dict({k: pt.to_tensor(v)
                                 for k, v in new.items()})

    def test_lstm_sequence(self, RNG):
        D, H, B, T = 5, 7, 3, 6
        tl = torch.nn.LSTM(D, H, batch_first=True)
        ours_lstm = nn.LSTM(D, H)
        self._port_weights(tl, ours_lstm)
        x = RNG.randn(B, T, D).astype("float32")
        a_out, (a_h, a_c) = ours_lstm(pt.to_tensor(x))
        e_out, (e_h, e_c) = tl(t(x))
        np.testing.assert_allclose(ours(a_out), e_out.detach().numpy(),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            ours(a_h).reshape(-1), e_h.detach().numpy().reshape(-1),
            atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            ours(a_c).reshape(-1), e_c.detach().numpy().reshape(-1),
            atol=2e-5, rtol=2e-5)

    def test_gru_sequence(self, RNG):
        D, H, B, T = 4, 6, 2, 5
        tg = torch.nn.GRU(D, H, batch_first=True)
        ours_gru = nn.GRU(D, H)
        self._port_weights(tg, ours_gru)
        x = RNG.randn(B, T, D).astype("float32")
        a_out, a_h = ours_gru(pt.to_tensor(x))
        e_out, e_h = tg(t(x))
        np.testing.assert_allclose(ours(a_out), e_out.detach().numpy(),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            ours(a_h).reshape(-1), e_h.detach().numpy().reshape(-1),
            atol=2e-5, rtol=2e-5)


class TestOptimizerParity:
    def _run_both(self, rng, make_ours, make_torch, steps=5):
        w0 = rng.randn(4, 3).astype("float32")
        grads = [rng.randn(4, 3).astype("float32") for _ in range(steps)]

        p_t = torch.nn.Parameter(torch.tensor(w0.copy()))
        opt_t = make_torch([p_t])
        for g in grads:
            opt_t.zero_grad()
            p_t.grad = torch.tensor(g)
            opt_t.step()

        param = pt.Parameter(w0.copy())
        opt_o = make_ours([param])
        for g in grads:
            param.grad = pt.to_tensor(g)
            opt_o.step()
            opt_o.clear_grad()
        return ours(param), p_t.detach().numpy()

    def test_sgd(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.SGD(learning_rate=0.1, parameters=ps),
            lambda ps: torch.optim.SGD(ps, lr=0.1))
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_momentum(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.Momentum(learning_rate=0.1,
                                             momentum=0.9, parameters=ps),
            lambda ps: torch.optim.SGD(ps, lr=0.1, momentum=0.9))
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_adam(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.Adam(learning_rate=0.01,
                                         beta1=0.9, beta2=0.999,
                                         epsilon=1e-8, parameters=ps),
            lambda ps: torch.optim.Adam(ps, lr=0.01, betas=(0.9, 0.999),
                                        eps=1e-8))
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_adamw(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.AdamW(learning_rate=0.01,
                                          weight_decay=0.05,
                                          parameters=ps),
            lambda ps: torch.optim.AdamW(ps, lr=0.01, weight_decay=0.05))
        np.testing.assert_allclose(a, e, atol=1e-6)


class TestInterpolateParity:
    @pytest.mark.parametrize("mode,align", [
        ("bilinear", False), ("bilinear", True), ("nearest", False),
    ])
    def test_resize(self, mode, align, RNG):
        x = RNG.randn(2, 3, 6, 6).astype("float32")
        kw = {} if mode == "nearest" else {"align_corners": align}
        a = ours(F.interpolate(pt.to_tensor(x), size=[11, 11], mode=mode,
                               **kw))
        e = torch.nn.functional.interpolate(
            t(x), size=(11, 11), mode=mode,
            **({} if mode == "nearest" else {"align_corners": align})
        ).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)


class TestLossParity:
    def test_cross_entropy_with_ignore_and_weight(self, RNG):
        logits = RNG.randn(6, 5).astype("float32")
        labels = np.array([0, 3, 2, -100, 4, 1], "int64")
        w = (RNG.rand(5).astype("float32") + 0.5)
        a = ours(F.cross_entropy(pt.to_tensor(logits),
                                 pt.to_tensor(labels),
                                 weight=pt.to_tensor(w),
                                 ignore_index=-100, reduction="mean"))
        e = torch.nn.functional.cross_entropy(
            t(logits), t(labels), weight=t(w), ignore_index=-100,
            reduction="mean").numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

    def test_bce_and_kl(self, RNG):
        p = RNG.rand(8).astype("float32") * 0.9 + 0.05
        y = (RNG.rand(8) > 0.5).astype("float32")
        a = ours(F.binary_cross_entropy(pt.to_tensor(p), pt.to_tensor(y)))
        e = torch.nn.functional.binary_cross_entropy(t(p), t(y)).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

        logq = np.log(RNG.dirichlet(np.ones(4), 5).astype("float32"))
        pr = RNG.dirichlet(np.ones(4), 5).astype("float32")
        a = ours(F.kl_div(pt.to_tensor(logq), pt.to_tensor(pr),
                          reduction="batchmean"))
        e = torch.nn.functional.kl_div(t(logq), t(pr),
                                       reduction="batchmean").numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

    def test_ctc_loss(self, RNG):
        T, B, C = 12, 3, 6
        logits = RNG.randn(T, B, C).astype("float32")
        log_probs = torch.log_softmax(t(logits), dim=-1)
        labels = np.array([[1, 2, 3, 0], [2, 2, 4, 5], [5, 1, 0, 0]],
                          "int64")
        in_lens = np.array([12, 10, 9], "int64")
        lb_lens = np.array([3, 4, 2], "int64")
        a = ours(F.ctc_loss(pt.to_tensor(log_probs.numpy()),
                            pt.to_tensor(labels),
                            pt.to_tensor(in_lens), pt.to_tensor(lb_lens),
                            blank=0, reduction="none"))
        e = torch.nn.functional.ctc_loss(
            log_probs, t(labels), t(in_lens), t(lb_lens), blank=0,
            reduction="none").numpy()
        np.testing.assert_allclose(np.asarray(a).ravel(), e.ravel(),
                                   atol=2e-4, rtol=2e-4)


class TestGradParity:
    """Gradients through the same ops — catches vjp-rule bugs the
    forward-only checks can't."""

    def test_conv2d_grads(self, RNG):
        x = RNG.randn(2, 3, 8, 8).astype("float32")
        w = RNG.randn(4, 3, 3, 3).astype("float32")
        g = RNG.randn(2, 4, 4, 4).astype("float32")  # cotangent

        xo = pt.to_tensor(x)
        xo.stop_gradient = False
        wo = pt.to_tensor(w)
        wo.stop_gradient = False
        out = F.conv2d(xo, wo, stride=2, padding=1)
        (out * pt.to_tensor(g)).sum().backward()

        xt = t(x).requires_grad_(True)
        wt = t(w).requires_grad_(True)
        et = torch.nn.functional.conv2d(xt, wt, stride=2, padding=1)
        (et * t(g)).sum().backward()

        np.testing.assert_allclose(ours(xo.grad), xt.grad.numpy(),
                                   atol=3e-5, rtol=3e-5)
        np.testing.assert_allclose(ours(wo.grad), wt.grad.numpy(),
                                   atol=3e-5, rtol=3e-5)

    def test_batch_norm_train_grads(self, RNG):
        x = RNG.randn(4, 5, 6, 6).astype("float32")
        gamma = RNG.rand(5).astype("float32") + 0.5
        beta = RNG.randn(5).astype("float32")
        g = RNG.randn(4, 5, 6, 6).astype("float32")

        xo = pt.to_tensor(x)
        xo.stop_gradient = False
        go = pt.to_tensor(gamma)
        go.stop_gradient = False
        bo = pt.to_tensor(beta)
        bo.stop_gradient = False
        out = F.batch_norm(xo, pt.to_tensor(np.zeros(5, "float32")),
                           pt.to_tensor(np.ones(5, "float32")), go, bo,
                           training=True, epsilon=1e-5)
        (out * pt.to_tensor(g)).sum().backward()

        xt = t(x).requires_grad_(True)
        gt = t(gamma).requires_grad_(True)
        bt = t(beta).requires_grad_(True)
        et = torch.nn.functional.batch_norm(
            xt, torch.zeros(5), torch.ones(5), gt, bt, training=True,
            eps=1e-5)
        (et * t(g)).sum().backward()

        np.testing.assert_allclose(ours(xo.grad), xt.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(ours(go.grad), gt.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(ours(bo.grad), bt.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)

    def test_lstm_input_grads(self, RNG):
        D, H, B, T = 5, 7, 3, 6
        tl = torch.nn.LSTM(D, H, batch_first=True)
        ours_lstm = nn.LSTM(D, H)
        TestRNNParity._port_weights(tl, ours_lstm)
        x = RNG.randn(B, T, D).astype("float32")
        g = RNG.randn(B, T, H).astype("float32")

        xo = pt.to_tensor(x)
        xo.stop_gradient = False
        a_out, _ = ours_lstm(xo)
        (a_out * pt.to_tensor(g)).sum().backward()

        xt = t(x).requires_grad_(True)
        e_out, _ = tl(xt)
        (e_out * t(g)).sum().backward()

        np.testing.assert_allclose(ours(xo.grad), xt.grad.numpy(),
                                   atol=5e-5, rtol=5e-5)


class TestGeometricParity:
    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    @pytest.mark.parametrize("align", [True, False])
    @pytest.mark.parametrize("padding_mode",
                             ["zeros", "border", "reflection"])
    def test_grid_sample(self, align, padding_mode, mode, RNG):
        x = RNG.randn(2, 3, 6, 6).astype("float32")
        grid = (RNG.rand(2, 5, 5, 2).astype("float32") * 2.4 - 1.2)
        a = ours(F.grid_sample(pt.to_tensor(x), pt.to_tensor(grid),
                               mode=mode, padding_mode=padding_mode,
                               align_corners=align))
        e = torch.nn.functional.grid_sample(
            t(x), t(grid), mode=mode, padding_mode=padding_mode,
            align_corners=align).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

    def test_affine_grid(self, RNG):
        theta = RNG.randn(2, 2, 3).astype("float32")
        a = ours(F.affine_grid(pt.to_tensor(theta), [2, 3, 5, 7],
                               align_corners=True))
        e = torch.nn.functional.affine_grid(t(theta), (2, 3, 5, 7),
                                            align_corners=True).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

    def test_pixel_shuffle(self, RNG):
        x = RNG.randn(2, 8, 3, 3).astype("float32")
        a = ours(F.pixel_shuffle(pt.to_tensor(x), 2))
        e = torch.nn.functional.pixel_shuffle(t(x), 2).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_embedding_grads(self, RNG):
        table = RNG.randn(10, 4).astype("float32")
        idx = np.array([1, 3, 3, 7], "int64")
        g = RNG.randn(4, 4).astype("float32")

        to = pt.to_tensor(table)
        to.stop_gradient = False
        out = F.embedding(pt.to_tensor(idx), to)
        (out * pt.to_tensor(g)).sum().backward()

        tt = t(table).requires_grad_(True)
        et = torch.nn.functional.embedding(t(idx), tt)
        (et * t(g)).sum().backward()
        # duplicate index 3 must ACCUMULATE its two cotangent rows
        np.testing.assert_allclose(ours(to.grad), tt.grad.numpy(),
                                   atol=1e-6)


class TestPadUnfoldParity:
    @pytest.mark.parametrize("mode", ["constant", "reflect", "replicate"])
    def test_pad2d_modes(self, mode, RNG):
        x = RNG.randn(2, 3, 5, 5).astype("float32")
        pad = [1, 2, 2, 1]  # (left, right, top, bottom)
        kw = {"value": 1.5} if mode == "constant" else {}
        a = ours(F.pad(pt.to_tensor(x), pad, mode=mode, **kw))
        e = torch.nn.functional.pad(
            t(x), pad, mode=mode,
            **({"value": 1.5} if mode == "constant" else {})).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_circular_pad(self, RNG):
        x = RNG.randn(1, 2, 4, 4).astype("float32")
        a = ours(F.pad(pt.to_tensor(x), [1, 1, 1, 1], mode="circular"))
        e = torch.nn.functional.pad(t(x), [1, 1, 1, 1],
                                    mode="circular").numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_unfold_im2col(self, RNG):
        x = RNG.randn(2, 3, 7, 7).astype("float32")
        a = ours(F.unfold(pt.to_tensor(x), kernel_sizes=3, strides=2,
                          paddings=1, dilations=1))
        e = torch.nn.functional.unfold(t(x), kernel_size=3, stride=2,
                                       padding=1, dilation=1).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_trilinear_resize(self, RNG):
        x = RNG.randn(1, 2, 4, 4, 4).astype("float32")
        a = ours(F.interpolate(pt.to_tensor(x), size=[7, 6, 5],
                               mode="trilinear", align_corners=True))
        e = torch.nn.functional.interpolate(
            t(x), size=(7, 6, 5), mode="trilinear",
            align_corners=True).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)


def _port_torch_mha(torch_mha, E, prefix=""):
    """Split torch's packed in_proj ([q;k;v] rows, (out,in) layout)
    into our separate (in,out)-layout projections."""
    in_w = torch_mha.in_proj_weight.detach().numpy()      # (3E, E)
    in_b = torch_mha.in_proj_bias.detach().numpy()
    out_w = torch_mha.out_proj.weight.detach().numpy()    # (E, E)
    out_b = torch_mha.out_proj.bias.detach().numpy()
    qw, kw, vw = in_w[:E], in_w[E:2 * E], in_w[2 * E:]
    qb, kb, vb = in_b[:E], in_b[E:2 * E], in_b[2 * E:]
    return {f"{prefix}q_proj.weight": qw.T, f"{prefix}q_proj.bias": qb,
            f"{prefix}k_proj.weight": kw.T, f"{prefix}k_proj.bias": kb,
            f"{prefix}v_proj.weight": vw.T, f"{prefix}v_proj.bias": vb,
            f"{prefix}out_proj.weight": out_w.T,
            f"{prefix}out_proj.bias": out_b}


class TestAttentionParity:
    def test_multi_head_attention(self, RNG):
        """Self-attention parity with torch.nn.MultiheadAttention:
        torch packs q/k/v into in_proj; ours keeps separate
        projections — split the packed weights and port."""
        E, H, B, T = 8, 2, 3, 5
        tm = torch.nn.MultiheadAttention(E, H, batch_first=True)
        om = nn.MultiHeadAttention(E, H)

        port = _port_torch_mha(tm, E)
        om.set_state_dict({k: pt.to_tensor(v.astype("float32"))
                           for k, v in port.items()})

        x = RNG.randn(B, T, E).astype("float32")
        a = ours(om(pt.to_tensor(x)))
        e, _ = tm(t(x), t(x), t(x), need_weights=False)
        np.testing.assert_allclose(a, e.detach().numpy(), atol=3e-5,
                                   rtol=3e-5)

    def test_bidirectional_lstm(self, RNG):
        D, H, B, T = 4, 5, 2, 6
        tl = torch.nn.LSTM(D, H, batch_first=True, bidirectional=True)
        om = nn.LSTM(D, H, direction="bidirect")
        TestRNNParity._port_weights(tl, om)
        x = RNG.randn(B, T, D).astype("float32")
        a_out, (a_h, a_c) = om(pt.to_tensor(x))
        e_out, (e_h, e_c) = tl(t(x))
        np.testing.assert_allclose(ours(a_out), e_out.detach().numpy(),
                                   atol=3e-5, rtol=3e-5)
        # final states include the (num_directions, B, H) stack order
        # and the cell state (not derivable from the output sequence)
        np.testing.assert_allclose(
            ours(a_h).reshape(-1), e_h.detach().numpy().reshape(-1),
            atol=3e-5, rtol=3e-5)
        np.testing.assert_allclose(
            ours(a_c).reshape(-1), e_c.detach().numpy().reshape(-1),
            atol=3e-5, rtol=3e-5)


class TestActivationParity:
    @pytest.mark.parametrize("approximate", [False, True])
    def test_gelu_both_forms(self, approximate, RNG):
        x = RNG.randn(64).astype("float32") * 3
        a = ours(F.gelu(pt.to_tensor(x), approximate=approximate))
        e = torch.nn.functional.gelu(
            t(x), approximate="tanh" if approximate else "none").numpy()
        np.testing.assert_allclose(a, e, atol=2e-6, rtol=2e-6)

    def test_softplus_beta_threshold(self, RNG):
        # threshold switches to identity for beta*x > threshold
        x = np.array([-3.0, 0.0, 2.0, 12.0, 40.0], "float32")
        a = ours(F.softplus(pt.to_tensor(x), beta=2.0, threshold=15.0))
        e = torch.nn.functional.softplus(t(x), beta=2.0,
                                         threshold=15.0).numpy()
        np.testing.assert_allclose(a, e, atol=2e-6, rtol=2e-6)

    @pytest.mark.parametrize("name,tname,kw", [
        ("silu", "silu", {}),
        ("mish", "mish", {}),
        ("hardswish", "hardswish", {}),
        ("elu", "elu", {"alpha": 0.7}),
        ("selu", "selu", {}),
        ("leaky_relu", "leaky_relu", {}),
        ("relu6", "relu6", {}),
        ("log_sigmoid", "logsigmoid", {}),
        ("tanhshrink", "tanhshrink", {}),
        ("softsign", "softsign", {}),
    ])
    def test_elementwise(self, name, tname, kw, RNG):
        x = RNG.randn(64).astype("float32") * 3
        a = ours(getattr(F, name)(pt.to_tensor(x), **kw))
        e = getattr(torch.nn.functional, tname)(t(x), **kw).numpy()
        np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)

    def test_hardsigmoid_paddle_slope(self, RNG):
        """paddle hardsigmoid uses slope 1/6 + offset 0.5 like torch."""
        x = np.linspace(-4, 4, 33).astype("float32")
        a = ours(F.hardsigmoid(pt.to_tensor(x)))
        e = torch.nn.functional.hardsigmoid(t(x)).numpy()
        np.testing.assert_allclose(a, e, atol=3e-6)

    def test_prelu(self, RNG):
        x = RNG.randn(2, 4, 5).astype("float32")
        w = np.array([0.1, 0.2, 0.3, 0.4], "float32")
        a = ours(F.prelu(pt.to_tensor(x), pt.to_tensor(w)))
        e = torch.nn.functional.prelu(t(x), t(w)).numpy()
        np.testing.assert_allclose(a, e, atol=3e-6)


class TestMoreLossParity:
    @pytest.mark.parametrize("delta", [1.0, 0.5])
    def test_smooth_l1(self, delta, RNG):
        x = RNG.randn(16).astype("float32") * 2
        y = RNG.randn(16).astype("float32") * 2
        a = ours(F.smooth_l1_loss(pt.to_tensor(x), pt.to_tensor(y),
                                  delta=delta))
        e = torch.nn.functional.smooth_l1_loss(t(x), t(y),
                                               beta=delta).numpy()
        np.testing.assert_allclose(a, e, atol=2e-6, rtol=2e-6)

    def test_margin_ranking(self, RNG):
        a1 = RNG.randn(8).astype("float32")
        a2 = RNG.randn(8).astype("float32")
        yy = np.sign(RNG.randn(8)).astype("float32")
        a = ours(F.margin_ranking_loss(pt.to_tensor(a1),
                                       pt.to_tensor(a2),
                                       pt.to_tensor(yy), margin=0.3))
        # both define max(0, -label*(x1 - x2) + margin)
        e = torch.nn.functional.margin_ranking_loss(
            t(a1), t(a2), t(yy), margin=0.3).numpy()
        np.testing.assert_allclose(a, e, atol=2e-6, rtol=2e-6)

    def test_nll_loss(self, RNG):
        logp = torch.log_softmax(t(RNG.randn(6, 4).astype("float32")),
                                 dim=1)
        y = np.array([0, 1, 3, 2, 1, 0], "int64")
        a = ours(F.nll_loss(pt.to_tensor(logp.numpy()), pt.to_tensor(y)))
        e = torch.nn.functional.nll_loss(logp, t(y)).numpy()
        np.testing.assert_allclose(a, e, atol=2e-6, rtol=2e-6)

    def test_triplet_and_hinge(self, RNG):
        a1 = RNG.randn(5, 8).astype("float32")
        pos = RNG.randn(5, 8).astype("float32")
        neg = RNG.randn(5, 8).astype("float32")
        a = ours(F.triplet_margin_loss(pt.to_tensor(a1),
                                       pt.to_tensor(pos),
                                       pt.to_tensor(neg), margin=0.8))
        e = torch.nn.functional.triplet_margin_loss(
            t(a1), t(pos), t(neg), margin=0.8).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

        x = RNG.randn(10).astype("float32")
        yy = np.sign(RNG.randn(10)).astype("float32")
        a = ours(F.hinge_embedding_loss(pt.to_tensor(x),
                                        pt.to_tensor(yy), margin=1.0))
        e = torch.nn.functional.hinge_embedding_loss(
            t(x), t(yy), margin=1.0).numpy()
        np.testing.assert_allclose(a, e, atol=2e-6, rtol=2e-6)

    def test_cosine_similarity_and_normalize(self, RNG):
        x = RNG.randn(4, 9).astype("float32")
        y = RNG.randn(4, 9).astype("float32")
        a = ours(F.cosine_similarity(pt.to_tensor(x), pt.to_tensor(y),
                                     axis=1))
        e = torch.nn.functional.cosine_similarity(t(x), t(y),
                                                  dim=1).numpy()
        np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)
        a = ours(F.normalize(pt.to_tensor(x), p=2, axis=1))
        e = torch.nn.functional.normalize(t(x), p=2, dim=1).numpy()
        np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)


class TestConv1DParity:
    def test_conv1d_and_transpose(self, RNG):
        x = RNG.randn(2, 3, 11).astype("float32")
        w = RNG.randn(5, 3, 4).astype("float32")
        a = ours(F.conv1d(pt.to_tensor(x), pt.to_tensor(w), stride=2,
                          padding=1))
        e = torch.nn.functional.conv1d(t(x), t(w), stride=2,
                                       padding=1).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

        wt = RNG.randn(3, 5, 4).astype("float32")
        a = ours(F.conv1d_transpose(pt.to_tensor(x), pt.to_tensor(wt),
                                    stride=2, padding=1))
        e = torch.nn.functional.conv_transpose1d(t(x), t(wt), stride=2,
                                                 padding=1).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)


class TestStatsParity:
    def test_std_var_unbiased(self, RNG):
        x = RNG.randn(5, 7).astype("float32")
        for unbiased in (True, False):
            a = ours(pt.std(pt.to_tensor(x), axis=1, unbiased=unbiased))
            e = torch.std(t(x), dim=1, unbiased=unbiased).numpy()
            np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)
            a = ours(pt.var(pt.to_tensor(x), axis=1, unbiased=unbiased))
            e = torch.var(t(x), dim=1, unbiased=unbiased).numpy()
            np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)

    def test_median_even_count(self, RNG):
        # paddle median averages the two middle values on even counts
        # (numpy semantics); torch.median takes the LOWER one — compare
        # via torch.quantile(0.5) which matches paddle's convention
        x = RNG.randn(4, 6).astype("float32")
        a = ours(pt.median(pt.to_tensor(x), axis=1))
        e = torch.quantile(t(x), 0.5, dim=1).numpy()
        np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)

    def test_quantile_linear_interp(self, RNG):
        x = RNG.randn(3, 9).astype("float32")
        for q in (0.25, 0.9):
            a = ours(pt.quantile(pt.to_tensor(x), q, axis=1))
            e = torch.quantile(t(x), q, dim=1).numpy()
            np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)

    def test_kthvalue_and_cumsum(self, RNG):
        x = RNG.randn(3, 8).astype("float32")
        av, ai = pt.kthvalue(pt.to_tensor(x), 3, axis=1)
        ev, ei = torch.kthvalue(t(x), 3, dim=1)
        np.testing.assert_allclose(ours(av), ev.numpy(), atol=1e-6)
        np.testing.assert_array_equal(ours(ai), ei.numpy())
        np.testing.assert_allclose(
            ours(pt.cumsum(pt.to_tensor(x), axis=1)),
            torch.cumsum(t(x), dim=1).numpy(), atol=3e-6, rtol=3e-6)

    def test_logsumexp(self, RNG):
        x = RNG.randn(4, 6).astype("float32") * 3
        a = ours(pt.logsumexp(pt.to_tensor(x), axis=1))
        e = torch.logsumexp(t(x), dim=1).numpy()
        np.testing.assert_allclose(a, e, atol=3e-6, rtol=3e-6)


class TestTransformerLayerParity:
    def test_encoder_layer_post_norm(self, RNG):
        """Whole TransformerEncoderLayer (self-attn + FFN + residuals +
        post-norm) matches torch with dropout off and ported weights."""
        E, H, FF, B, T = 8, 2, 16, 3, 5
        tm = torch.nn.TransformerEncoderLayer(
            E, H, dim_feedforward=FF, dropout=0.0, batch_first=True,
            norm_first=False, activation="relu")
        om = nn.TransformerEncoderLayer(E, H, FF, dropout=0.0)

        port = _port_torch_mha(tm.self_attn, E, prefix="self_attn.")
        port.update({
            "linear1.weight": tm.linear1.weight.detach().numpy().T,
            "linear1.bias": tm.linear1.bias.detach().numpy(),
            "linear2.weight": tm.linear2.weight.detach().numpy().T,
            "linear2.bias": tm.linear2.bias.detach().numpy(),
            "norm1.weight": tm.norm1.weight.detach().numpy(),
            "norm1.bias": tm.norm1.bias.detach().numpy(),
            "norm2.weight": tm.norm2.weight.detach().numpy(),
            "norm2.bias": tm.norm2.bias.detach().numpy(),
        })
        sd = om.state_dict()
        assert set(port) == set(sd)
        for k, v in port.items():
            assert tuple(sd[k].shape) == v.shape, k
        om.set_state_dict({k: pt.to_tensor(v.astype("float32"))
                           for k, v in port.items()})
        om.eval()
        x = RNG.randn(B, T, E).astype("float32")
        a = ours(om(pt.to_tensor(x)))
        e = tm(t(x)).detach().numpy()
        np.testing.assert_allclose(a, e, atol=5e-5, rtol=5e-5)


def test_batchnorm_layer_momentum_convention(RNG):
    """paddle momentum is the KEEP factor (running = m*running +
    (1-m)*batch); torch's is the update factor — paddle 0.9 == torch
    0.1. Running var uses the unbiased batch estimate in both."""
    x = RNG.randn(16, 3, 4, 4).astype("float32")
    om = nn.BatchNorm2D(3, momentum=0.9)
    tm = torch.nn.BatchNorm2d(3, momentum=0.1)
    om.train()
    tm.train()
    for _ in range(3):
        om(pt.to_tensor(x))
        tm(t(x))
    sd = {k: ours(v) for k, v in om.state_dict().items()}
    mean_key = [k for k in sd if "mean" in k][0]
    var_key = [k for k in sd if "var" in k][0]
    np.testing.assert_allclose(sd[mean_key], tm.running_mean.numpy(),
                               atol=1e-6)
    np.testing.assert_allclose(sd[var_key], tm.running_var.numpy(),
                               atol=1e-5, rtol=1e-5)
    # eval output then uses the SAME running stats
    om.eval()
    tm.eval()
    np.testing.assert_allclose(ours(om(pt.to_tensor(x))),
                               tm(t(x)).detach().numpy(), atol=1e-5,
                               rtol=1e-5)


def test_gather_take_along_axis_scatter(RNG):
    """paddle gather == torch index_select; paddle take_along_axis ==
    torch gather; paddle put_along_axis == torch scatter."""
    x = RNG.randn(5, 4).astype("float32")
    idx = np.array([3, 0, 3], "int64")
    np.testing.assert_allclose(
        ours(pt.gather(pt.to_tensor(x), pt.to_tensor(idx))),
        torch.index_select(t(x), 0, t(idx)).numpy(), atol=1e-6)

    along = np.array([[0, 1, 2, 3], [3, 2, 1, 0]], "int64")
    xa = RNG.randn(4, 4).astype("float32")
    np.testing.assert_allclose(
        ours(pt.take_along_axis(pt.to_tensor(xa), pt.to_tensor(along),
                                axis=0)),
        torch.gather(t(xa), 0, t(along)).numpy(), atol=1e-6)

    vals = RNG.randn(2, 4).astype("float32")
    a = ours(pt.put_along_axis(pt.to_tensor(xa), pt.to_tensor(along),
                               pt.to_tensor(vals), axis=0))
    e = t(xa).scatter(0, t(along), t(vals)).numpy()
    np.testing.assert_allclose(a, e, atol=1e-6)


def test_grouped_dilated_conv_grads(RNG):
    """Grad parity for the grouped+dilated conv and transposed conv —
    distinct vjp paths from the plain case."""
    x = RNG.randn(2, 6, 9, 9).astype("float32")
    w = RNG.randn(9, 2, 3, 3).astype("float32")  # groups=3
    g = None

    xo = pt.to_tensor(x)
    xo.stop_gradient = False
    wo = pt.to_tensor(w)
    wo.stop_gradient = False
    out = F.conv2d(xo, wo, stride=1, padding=2, dilation=2, groups=3)
    g = RNG.randn(*out.shape).astype("float32")
    (out * pt.to_tensor(g)).sum().backward()

    xt = t(x).requires_grad_(True)
    wt = t(w).requires_grad_(True)
    et = torch.nn.functional.conv2d(xt, wt, stride=1, padding=2,
                                    dilation=2, groups=3)
    (et * t(g)).sum().backward()
    np.testing.assert_allclose(ours(xo.grad), xt.grad.numpy(),
                               atol=5e-5, rtol=5e-5)
    np.testing.assert_allclose(ours(wo.grad), wt.grad.numpy(),
                               atol=5e-5, rtol=5e-5)

    wt2 = RNG.randn(6, 4, 3, 3).astype("float32")
    xo2 = pt.to_tensor(x)
    xo2.stop_gradient = False
    wo2 = pt.to_tensor(wt2)
    wo2.stop_gradient = False
    out2 = F.conv2d_transpose(xo2, wo2, stride=2, padding=1)
    g2 = RNG.randn(*out2.shape).astype("float32")
    (out2 * pt.to_tensor(g2)).sum().backward()

    xt2 = t(x).requires_grad_(True)
    wt2_ = t(wt2).requires_grad_(True)
    et2 = torch.nn.functional.conv_transpose2d(xt2, wt2_, stride=2,
                                               padding=1)
    (et2 * t(g2)).sum().backward()
    np.testing.assert_allclose(ours(xo2.grad), xt2.grad.numpy(),
                               atol=5e-5, rtol=5e-5)
    np.testing.assert_allclose(ours(wo2.grad), wt2_.grad.numpy(),
                               atol=5e-5, rtol=5e-5)


class TestLongTailFunctionalParity:
    """Functional APIs with no prior test mention, pinned vs torch
    where torch has the op."""

    def test_pool_1d_3d(self, RNG):
        x1 = RNG.randn(2, 3, 12).astype("float32")
        np.testing.assert_allclose(
            ours(F.max_pool1d(pt.to_tensor(x1), 3, stride=2)),
            torch.nn.functional.max_pool1d(t(x1), 3, stride=2).numpy(),
            atol=1e-6)
        np.testing.assert_allclose(
            ours(F.avg_pool1d(pt.to_tensor(x1), 2, stride=2)),
            torch.nn.functional.avg_pool1d(t(x1), 2, stride=2).numpy(),
            atol=1e-6)
        np.testing.assert_allclose(
            ours(F.adaptive_avg_pool1d(pt.to_tensor(x1), 5)),
            torch.nn.functional.adaptive_avg_pool1d(t(x1), 5).numpy(),
            atol=1e-6)
        x3 = RNG.randn(1, 2, 6, 6, 6).astype("float32")
        np.testing.assert_allclose(
            ours(F.max_pool3d(pt.to_tensor(x3), 2, stride=2)),
            torch.nn.functional.max_pool3d(t(x3), 2, stride=2).numpy(),
            atol=1e-6)
        np.testing.assert_allclose(
            ours(F.avg_pool3d(pt.to_tensor(x3), 2, stride=2)),
            torch.nn.functional.avg_pool3d(t(x3), 2, stride=2).numpy(),
            atol=1e-6)

    def test_adaptive_max_pool(self, RNG):
        x = RNG.randn(2, 3, 10, 10).astype("float32")
        np.testing.assert_allclose(
            ours(F.adaptive_max_pool2d(pt.to_tensor(x), 4)),
            torch.nn.functional.adaptive_max_pool2d(t(x), 4).numpy(),
            atol=1e-6)
        x1 = RNG.randn(2, 3, 12).astype("float32")
        np.testing.assert_allclose(
            ours(F.adaptive_max_pool1d(pt.to_tensor(x1), 4)),
            torch.nn.functional.adaptive_max_pool1d(t(x1), 4).numpy(),
            atol=1e-6)

    def test_norms(self, RNG):
        x = RNG.randn(4, 6, 5, 5).astype("float32")
        g = RNG.rand(6).astype("float32") + 0.5
        b = RNG.randn(6).astype("float32")
        a = ours(F.group_norm(pt.to_tensor(x), num_groups=3,
                              weight=pt.to_tensor(g), bias=pt.to_tensor(b),
                              epsilon=1e-5))
        e = torch.nn.functional.group_norm(t(x), 3, t(g), t(b),
                                           eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)
        a = ours(F.instance_norm(pt.to_tensor(x),
                                 weight=pt.to_tensor(g),
                                 bias=pt.to_tensor(b), eps=1e-5))
        e = torch.nn.functional.instance_norm(t(x), weight=t(g),
                                              bias=t(b), eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)
        # paddle's lrn alpha is unnormalized; torch divides alpha by
        # size — paddle(alpha) == torch(alpha*size)
        a = ours(F.local_response_norm(pt.to_tensor(x), size=3,
                                       alpha=1e-4, beta=0.75, k=1.0))
        e = torch.nn.functional.local_response_norm(
            t(x), 3, alpha=3e-4, beta=0.75, k=1.0).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

    def test_shrinks_and_misc_activations(self, RNG):
        x = RNG.randn(40).astype("float32") * 2
        np.testing.assert_allclose(
            ours(F.hardshrink(pt.to_tensor(x), threshold=0.4)),
            torch.nn.functional.hardshrink(t(x), lambd=0.4).numpy(),
            atol=1e-6)
        np.testing.assert_allclose(
            ours(F.softshrink(pt.to_tensor(x), threshold=0.3)),
            torch.nn.functional.softshrink(t(x), lambd=0.3).numpy(),
            atol=1e-6)
        np.testing.assert_allclose(
            ours(F.hardtanh(pt.to_tensor(x), min=-0.7, max=0.9)),
            torch.nn.functional.hardtanh(t(x), -0.7, 0.9).numpy(),
            atol=1e-6)
        np.testing.assert_allclose(
            ours(F.celu(pt.to_tensor(x), alpha=0.8)),
            torch.nn.functional.celu(t(x), alpha=0.8).numpy(),
            atol=3e-6)

    def test_losses_and_distances(self, RNG):
        x1 = RNG.randn(5, 8).astype("float32")
        x2 = RNG.randn(5, 8).astype("float32")
        y = np.sign(RNG.randn(5)).astype("float32")
        a = ours(F.cosine_embedding_loss(pt.to_tensor(x1),
                                         pt.to_tensor(x2),
                                         pt.to_tensor(y), margin=0.2))
        e = torch.nn.functional.cosine_embedding_loss(
            t(x1), t(x2), t(y), margin=0.2).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)
        a = ours(F.pairwise_distance(pt.to_tensor(x1), pt.to_tensor(x2),
                                     p=2.0))
        e = torch.nn.functional.pairwise_distance(t(x1), t(x2),
                                                  p=2.0).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

    def test_channel_shuffle(self, RNG):
        x = RNG.randn(2, 8, 4, 4).astype("float32")
        cs = ours(F.channel_shuffle(pt.to_tensor(x), groups=2))
        e = torch.nn.functional.channel_shuffle(t(x), 2).numpy()
        np.testing.assert_allclose(cs, e, atol=1e-6)

    def test_paddle_only_ops_behave(self, RNG):
        # no torch analog: pin the documented contract directly
        sm = ours(F.sequence_mask(pt.to_tensor(
            np.array([2, 0, 3], "int64")), maxlen=4))
        np.testing.assert_array_equal(
            sm, [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])
        ls = ours(F.label_smooth(pt.to_tensor(
            np.eye(3, dtype="float32")), epsilon=0.1))
        np.testing.assert_allclose(ls.sum(1), [1, 1, 1], atol=1e-6)
        assert abs(float(ls[0, 0]) - (0.9 + 0.1 / 3)) < 1e-6
        ll = ours(F.log_loss(pt.to_tensor(
            np.array([0.2, 0.8], "float32")),
            pt.to_tensor(np.array([0.0, 1.0], "float32"))))
        # log_loss clamps with its epsilon (1e-4 default), shifting
        # the exact -log(0.8) by ~1e-4
        np.testing.assert_allclose(
            ll, [-np.log(0.8), -np.log(0.8)], atol=5e-4)


class TestRemainingFunctionalSurface:
    def test_conv3d_transpose(self, RNG):
        x = RNG.randn(1, 4, 5, 5, 5).astype("float32")
        w = RNG.randn(4, 3, 2, 2, 2).astype("float32")
        a = ours(F.conv3d_transpose(pt.to_tensor(x), pt.to_tensor(w),
                                    stride=2))
        e = torch.nn.functional.conv_transpose3d(t(x), t(w),
                                                 stride=2).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

    def test_dropout_variants_shape_contract(self, RNG):
        pt.seed(3)
        x = pt.ones([8, 4, 6, 6])
        y2 = ours(F.dropout2d(x, p=0.5, training=True))
        # dropout2d zeroes WHOLE channels: each (n, c) map all-0 or all-keep
        per_map = y2.reshape(8 * 4, -1)
        assert all(np.all(m == 0) or np.all(m != 0) for m in per_map)
        kept = per_map[per_map.sum(1) != 0]
        np.testing.assert_allclose(kept, 2.0, atol=1e-6)  # upscaled

        x3 = pt.ones([4, 3, 2, 4, 4])
        y3 = ours(F.dropout3d(x3, p=0.5, training=True))
        per_vol = y3.reshape(4 * 3, -1)
        assert all(np.all(m == 0) or np.all(m != 0) for m in per_vol)

        ya = ours(F.alpha_dropout(pt.ones([4000]), p=0.3,
                                  training=True))
        # ones input maps onto exactly torch's two affine constants
        # (kept -> a+b, dropped -> a*alpha'+b); nothing goes to 0
        torch_vals = np.unique(torch.nn.functional.alpha_dropout(
            torch.ones(4000), 0.3, True).numpy())
        np.testing.assert_allclose(np.unique(ya), torch_vals, atol=1e-4)
        assert not np.any(ya == 0)
        # and the self-normalizing contract: N(0,1) stats survive
        g = RNG.randn(20000).astype("float32")
        yg = ours(F.alpha_dropout(pt.to_tensor(g), p=0.3,
                                  training=True))
        assert abs(yg.mean()) < 0.05 and abs(yg.std() - 1.0) < 0.08

    def test_thresholded_relu_and_maxout(self, RNG):
        x = RNG.randn(32).astype("float32")
        a = ours(F.thresholded_relu(pt.to_tensor(x), threshold=0.4))
        e = torch.nn.functional.threshold(t(x), 0.4, 0.0).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)
        xm = RNG.randn(2, 6, 3).astype("float32")
        mo = ours(F.maxout(pt.to_tensor(xm), groups=2))
        assert mo.shape == (2, 3, 3)
        # ref maxouting.cc:44: output channel c maxes over the
        # CONSECUTIVE input channels [c*groups, (c+1)*groups)
        np.testing.assert_allclose(
            mo, xm.reshape(2, 3, 2, 3).max(axis=2), atol=1e-6)

    def test_gumbel_softmax_contract(self, RNG):
        pt.seed(5)
        logits = pt.to_tensor(RNG.randn(16, 5).astype("float32"))
        soft = ours(F.gumbel_softmax(logits, temperature=0.5))
        np.testing.assert_allclose(soft.sum(1), 1.0, atol=1e-5)
        hard = ours(F.gumbel_softmax(logits, temperature=0.5,
                                     hard=True))
        assert set(np.unique(hard)) <= {0.0, 1.0}
        np.testing.assert_allclose(hard.sum(1), 1.0, atol=1e-6)

    def test_npair_loss_contract(self, RNG):
        anchor = RNG.randn(4, 6).astype("float32")
        positive = RNG.randn(4, 6).astype("float32")
        labels = np.array([0, 1, 2, 3], "int64")
        val = float(ours(F.npair_loss(pt.to_tensor(anchor),
                                      pt.to_tensor(positive),
                                      pt.to_tensor(labels),
                                      l2_reg=0.0)))
        assert np.isfinite(val) and val > 0
