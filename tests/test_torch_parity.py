"""Independent numerical verification against torch (CPU).

The op tests compare against hand-written numpy; this file adds a
SECOND independent implementation for the subtle-semantics ops —
conv variants (stride/padding/dilation/groups), transposed conv,
pooling, batch/layer norm, LSTM/GRU whole-sequence runs, interpolation
corner modes, and the optimizer update rules — so an agreement bug in
our numpy oracle can't hide. Tolerances are float32-accumulation level.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")  # torch is optional in this env

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

@pytest.fixture
def RNG():
    # fresh stream per test: inputs don't depend on selection order
    return np.random.RandomState(7)


def t(x):
    return torch.tensor(x)


def ours(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class TestConvParity:
    @pytest.mark.parametrize("stride,padding,dilation,groups", [
        (1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 2, 1), (1, 1, 1, 3),
    ])
    def test_conv2d(self, stride, padding, dilation, groups, RNG):
        cin = 6
        x = RNG.randn(2, cin, 11, 11).astype("float32")
        w = RNG.randn(9, cin // groups, 3, 3).astype("float32")
        b = RNG.randn(9).astype("float32")
        a = ours(F.conv2d(pt.to_tensor(x), pt.to_tensor(w),
                          pt.to_tensor(b), stride=stride, padding=padding,
                          dilation=dilation, groups=groups))
        e = torch.nn.functional.conv2d(
            t(x), t(w), t(b), stride=stride, padding=padding,
            dilation=dilation, groups=groups).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("stride,padding,output_padding", [
        (1, 0, 0), (2, 1, 1),
    ])
    def test_conv2d_transpose(self, stride, padding, output_padding, RNG):
        x = RNG.randn(2, 4, 7, 7).astype("float32")
        w = RNG.randn(4, 5, 3, 3).astype("float32")
        a = ours(F.conv2d_transpose(
            pt.to_tensor(x), pt.to_tensor(w), stride=stride,
            padding=padding, output_padding=output_padding))
        e = torch.nn.functional.conv_transpose2d(
            t(x), t(w), stride=stride, padding=padding,
            output_padding=output_padding).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)

    def test_conv3d(self, RNG):
        x = RNG.randn(1, 3, 6, 6, 6).astype("float32")
        w = RNG.randn(4, 3, 2, 2, 2).astype("float32")
        a = ours(F.conv3d(pt.to_tensor(x), pt.to_tensor(w), stride=2))
        e = torch.nn.functional.conv3d(t(x), t(w), stride=2).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)


class TestPoolNormParity:
    def test_max_avg_pool(self, RNG):
        x = RNG.randn(2, 3, 9, 9).astype("float32")
        a = ours(F.max_pool2d(pt.to_tensor(x), kernel_size=3, stride=2,
                              padding=1))
        e = torch.nn.functional.max_pool2d(t(x), 3, stride=2,
                                           padding=1).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)
        a = ours(F.avg_pool2d(pt.to_tensor(x), kernel_size=2, stride=2))
        e = torch.nn.functional.avg_pool2d(t(x), 2, stride=2).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_adaptive_avg_pool(self, RNG):
        x = RNG.randn(2, 3, 10, 10).astype("float32")
        a = ours(F.adaptive_avg_pool2d(pt.to_tensor(x), 4))
        e = torch.nn.functional.adaptive_avg_pool2d(t(x), 4).numpy()
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_batch_norm_train_and_eval(self, RNG):
        x = RNG.randn(4, 5, 6, 6).astype("float32")
        g = RNG.rand(5).astype("float32") + 0.5
        b = RNG.randn(5).astype("float32")
        rm = np.zeros(5, "float32")
        rv = np.ones(5, "float32")
        # train mode: batch statistics
        a = ours(F.batch_norm(pt.to_tensor(x), pt.to_tensor(rm.copy()),
                              pt.to_tensor(rv.copy()), pt.to_tensor(g),
                              pt.to_tensor(b), training=True,
                              epsilon=1e-5))
        e = torch.nn.functional.batch_norm(
            t(x), t(rm.copy()), t(rv.copy()), t(g), t(b), training=True,
            eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)
        # eval mode: running statistics
        rm2 = RNG.randn(5).astype("float32")
        rv2 = RNG.rand(5).astype("float32") + 0.5
        a = ours(F.batch_norm(pt.to_tensor(x), pt.to_tensor(rm2),
                              pt.to_tensor(rv2), pt.to_tensor(g),
                              pt.to_tensor(b), training=False,
                              epsilon=1e-5))
        e = torch.nn.functional.batch_norm(
            t(x), t(rm2), t(rv2), t(g), t(b), training=False,
            eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)

    def test_layer_norm(self, RNG):
        x = RNG.randn(4, 10).astype("float32")
        g = RNG.rand(10).astype("float32") + 0.5
        b = RNG.randn(10).astype("float32")
        a = ours(F.layer_norm(pt.to_tensor(x), normalized_shape=[10],
                              weight=pt.to_tensor(g), bias=pt.to_tensor(b),
                              epsilon=1e-5))
        e = torch.nn.functional.layer_norm(t(x), [10], t(g), t(b),
                                           eps=1e-5).numpy()
        np.testing.assert_allclose(a, e, atol=3e-5, rtol=3e-5)


class TestRNNParity:
    @staticmethod
    def _port_weights(torch_rnn, ours_rnn, D, H, gates):
        """Copy torch l0 weights onto our layer by shape convention
        (gate order agrees: LSTM i,f,g,o == i,f,c,o; GRU r,z,n)."""
        wi = torch_rnn.weight_ih_l0.detach().numpy()   # (gates*H, D)
        wh = torch_rnn.weight_hh_l0.detach().numpy()
        bi = torch_rnn.bias_ih_l0.detach().numpy()
        bh = torch_rnn.bias_hh_l0.detach().numpy()
        sd = ours_rnn.state_dict()
        new = {}
        for k in sd:
            if "weight_ih" in k:
                new[k] = wi.T if tuple(sd[k].shape) == (D, gates * H) \
                    else wi
            elif "weight_hh" in k:
                new[k] = wh.T if tuple(sd[k].shape) == (H, gates * H) \
                    else wh
            elif "bias_ih" in k:
                new[k] = bi
            elif "bias_hh" in k:
                new[k] = bh
            else:
                new[k] = np.asarray(sd[k].numpy())
        ours_rnn.set_state_dict({k: pt.to_tensor(v)
                                 for k, v in new.items()})

    def test_lstm_sequence(self, RNG):
        D, H, B, T = 5, 7, 3, 6
        tl = torch.nn.LSTM(D, H, batch_first=True)
        ours_lstm = nn.LSTM(D, H)
        self._port_weights(tl, ours_lstm, D, H, gates=4)
        x = RNG.randn(B, T, D).astype("float32")
        a_out, (a_h, a_c) = ours_lstm(pt.to_tensor(x))
        e_out, (e_h, e_c) = tl(t(x))
        np.testing.assert_allclose(ours(a_out), e_out.detach().numpy(),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            ours(a_h).reshape(-1), e_h.detach().numpy().reshape(-1),
            atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            ours(a_c).reshape(-1), e_c.detach().numpy().reshape(-1),
            atol=2e-5, rtol=2e-5)

    def test_gru_sequence(self, RNG):
        D, H, B, T = 4, 6, 2, 5
        tg = torch.nn.GRU(D, H, batch_first=True)
        ours_gru = nn.GRU(D, H)
        self._port_weights(tg, ours_gru, D, H, gates=3)
        x = RNG.randn(B, T, D).astype("float32")
        a_out, a_h = ours_gru(pt.to_tensor(x))
        e_out, e_h = tg(t(x))
        np.testing.assert_allclose(ours(a_out), e_out.detach().numpy(),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            ours(a_h).reshape(-1), e_h.detach().numpy().reshape(-1),
            atol=2e-5, rtol=2e-5)


class TestOptimizerParity:
    def _run_both(self, rng, make_ours, make_torch, steps=5):
        w0 = rng.randn(4, 3).astype("float32")
        grads = [rng.randn(4, 3).astype("float32") for _ in range(steps)]

        p_t = torch.nn.Parameter(torch.tensor(w0.copy()))
        opt_t = make_torch([p_t])
        for g in grads:
            opt_t.zero_grad()
            p_t.grad = torch.tensor(g)
            opt_t.step()

        param = pt.Parameter(w0.copy())
        opt_o = make_ours([param])
        for g in grads:
            param.grad = pt.to_tensor(g)
            opt_o.step()
            opt_o.clear_grad()
        return ours(param), p_t.detach().numpy()

    def test_sgd(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.SGD(learning_rate=0.1, parameters=ps),
            lambda ps: torch.optim.SGD(ps, lr=0.1))
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_momentum(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.Momentum(learning_rate=0.1,
                                             momentum=0.9, parameters=ps),
            lambda ps: torch.optim.SGD(ps, lr=0.1, momentum=0.9))
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_adam(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.Adam(learning_rate=0.01,
                                         beta1=0.9, beta2=0.999,
                                         epsilon=1e-8, parameters=ps),
            lambda ps: torch.optim.Adam(ps, lr=0.01, betas=(0.9, 0.999),
                                        eps=1e-8))
        np.testing.assert_allclose(a, e, atol=1e-6)

    def test_adamw(self, RNG):
        a, e = self._run_both(
            RNG,
            lambda ps: pt.optimizer.AdamW(learning_rate=0.01,
                                          weight_decay=0.05,
                                          parameters=ps),
            lambda ps: torch.optim.AdamW(ps, lr=0.01, weight_decay=0.05))
        np.testing.assert_allclose(a, e, atol=1e-6)


class TestInterpolateParity:
    @pytest.mark.parametrize("mode,align", [
        ("bilinear", False), ("bilinear", True), ("nearest", False),
    ])
    def test_resize(self, mode, align, RNG):
        x = RNG.randn(2, 3, 6, 6).astype("float32")
        kw = {} if mode == "nearest" else {"align_corners": align}
        a = ours(F.interpolate(pt.to_tensor(x), size=[11, 11], mode=mode,
                               **kw))
        e = torch.nn.functional.interpolate(
            t(x), size=(11, 11), mode=mode,
            **({} if mode == "nearest" else {"align_corners": align})
        ).numpy()
        np.testing.assert_allclose(a, e, atol=2e-5, rtol=2e-5)
