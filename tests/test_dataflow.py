"""Static dataflow & memory analysis (ISSUE 11): versioned liveness
intervals on hand-computed fixtures (branchy reuse, assign_to clobber,
donated persistables), fused steps=K carry liveness, predicted-vs-
measured peak-HBM within 15% on the mlp/lenet zoo models, the new
Executor verifier checks (PTA011 use-after-donate aliasing, PTA012
plan/spec mismatch), the planner's hbm_budget/PTA013 rejection, PTL104
remat hints, and the per-entry `memory` journal event.

Runs on the 8-device virtual CPU mesh from conftest."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn.functional as F
from paddle_tpu import fleet
from paddle_tpu.analysis import dataflow as DF
from paddle_tpu.analysis import memory as M
from paddle_tpu.analysis import ProgramVerificationError
from paddle_tpu.static_.program import (Operator, Program, global_scope)


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _f32(shape):
    n = 1
    for s in shape:
        n *= s
    return n * 4


def _base(shape=(2, 3)):
    p = Program()
    blk = p.global_block
    blk.create_var(name="x", shape=shape, dtype="float32", is_data=True)
    return p, blk


def _op(blk, type_, fn, ins, outs, shape=(2, 3), dtype="float32"):
    for n in outs:
        if not blk.has_var(n):
            blk.create_var(name=n, shape=shape, dtype=dtype)
    blk.append_op(Operator(type_, fn, ins, outs, {}))


# -- liveness fixtures --------------------------------------------------------


class TestLiveness:
    def test_def_use_chains(self):
        p, blk = _base()
        _op(blk, "scale", lambda a: a * 2.0, ["x"], ["t"])
        _op(blk, "relu", lambda a: jnp.maximum(a, 0), ["t"], ["u"])
        _op(blk, "multiply", lambda a, b: a * b, ["t", "u"], ["o"])
        defs, uses = DF.def_use(blk.ops)
        assert defs == {"t": [0], "u": [1], "o": [2]}
        assert uses == {"x": [0], "t": [0, 1, 2][1:], "u": [2]}

    def test_branchy_reuse_last_use_is_the_later_branch(self):
        """One activation feeding two branches: its interval must
        extend to the LATER consumer, not close at the first."""
        p, blk = _base()
        _op(blk, "scale", lambda a: a * 2.0, ["x"], ["t"])
        _op(blk, "relu", lambda a: jnp.maximum(a, 0), ["t"], ["a"])
        _op(blk, "tanh", jnp.tanh, ["t"], ["b"])
        _op(blk, "multiply", lambda a, b: a * b, ["a", "b"], ["o"])
        live = DF.analyze(p, fetch_names=("o",))
        iv = {l.name: (l.def_idx, l.last_use) for l in live.temps()}
        assert iv["t"] == (0, 2)   # branch at op1 AND op2
        assert iv["a"] == (1, 3)
        assert iv["b"] == (2, 3)
        (o,) = live.intervals("o")
        assert o.live_out and o.last_use == 4  # fetched: live at exit
        # the walk's peak: op2 (t, a live, b defined) and op3 (a, b
        # live) both hold 3 temps... op2: t+a+b = 72; op3: a+b = 48
        est = M.estimate_entry(p, fetch_list=["o"])
        assert est.temp_peak_bytes == 3 * _f32((2, 3))
        assert est.peak_op == (2, "tanh")

    def test_assign_to_clobber_opens_a_new_version(self):
        """A clobbered name is TWO values: merging their ranges would
        keep the first alive across the clobber and inflate the peak."""
        p, blk = _base()
        _op(blk, "scale", lambda a: a * 2.0, ["x"], ["t"])
        _op(blk, "scale", lambda a: a * 3.0, ["x"], ["u"])
        _op(blk, "relu", lambda a: jnp.maximum(a, 0), ["t"], ["r"])
        _op(blk, "assign_to", lambda a: a, ["u"], ["t"])
        _op(blk, "multiply", lambda a, b: a * b, ["t", "r"], ["o"])
        live = DF.analyze(p, fetch_names=("o",))
        t_versions = live.intervals("t")
        assert [(l.version, l.def_idx, l.last_use) for l in t_versions] \
            == [(1, 0, 2), (2, 3, 4)]
        assert t_versions[0].writer == "scale"
        assert t_versions[1].writer == "assign_to"

    def test_donated_persistable_entry_version_flagged(self):
        """A re-emitted scope-held persistable: entry version is the
        donated buffer, the final write is live-out (restored into the
        Scope)."""
        p, blk = _base()
        blk.create_var(name="w", shape=(2, 3), dtype="float32",
                       persistable=True)
        _op(blk, "axpy", lambda a, b: a + b, ["x", "w"], ["w"])
        live = DF.analyze(p, fetch_names=(), scope_names={"w"})
        entry, final = live.intervals("w")
        assert entry.version == 0 and entry.donated
        assert entry.kind == "persistable"
        assert final.version == 1 and final.live_out
        assert "w" in live.donated
        # a persistable the scope does NOT hold is not donated
        live2 = DF.analyze(p, fetch_names=(), scope_names=set())
        assert "w" not in live2.donated

    def test_opt_and_comm_persistables_are_entry_values(self):
        """`@OPT@` slots and `@comm@*` state are ordinary persistables
        to the walk — they ride the donated carry like parameters."""
        p, blk = _base()
        for name in ("w@OPT@m", "@comm@ef@0"):
            blk.create_var(name=name, shape=(2, 3), dtype="float32",
                           persistable=True)
            _op(blk, "scale", lambda a: a * 0.9, [name], [name])
        live = DF.analyze(p, fetch_names=(),
                          scope_names={"w@OPT@m", "@comm@ef@0"})
        assert live.donated == {"w@OPT@m", "@comm@ef@0"}
        for name in ("w@OPT@m", "@comm@ef@0"):
            entry = live.intervals(name)[0]
            assert entry.kind == "persistable" and entry.donated


class TestMemoryEstimate:
    def test_three_op_hand_computed(self):
        """x(24B feed) -> t=scale -> u=relu -> o=mul(t,u), fetch o:
        args 24 + outputs 24 + temps 48 (t,u coexist at op2) = 96 B."""
        p, blk = _base()
        _op(blk, "scale", lambda a: a * 2.0, ["x"], ["t"])
        _op(blk, "relu", lambda a: jnp.maximum(a, 0), ["t"], ["u"])
        _op(blk, "multiply", lambda a, b: a * b, ["t", "u"], ["o"])
        est = M.estimate_entry(p, fetch_list=["o"])
        assert est.arg_bytes == 24
        assert est.output_bytes == 24
        assert est.temp_peak_bytes == 48
        assert est.peak_bytes == 96
        # t+u first coexist during op1 (relu's input and output)
        assert est.peak_op == (1, "relu")

    def test_fused_steps_scale_feeds_and_fetches_not_the_carry(self):
        """steps=K: the executable takes K-stacked feeds and returns
        K-stacked fetches, but the persistable carry and the
        per-iteration temp peak count ONCE."""
        p, blk = _base()
        blk.create_var(name="w", shape=(2, 3), dtype="float32",
                       persistable=True)
        _op(blk, "axpy", lambda a, b: a + b, ["x", "w"], ["w"])
        _op(blk, "scale", lambda a: a * 1.0, ["w"], ["loss"])
        one = M.estimate_entry(p, fetch_list=["loss"],
                               scope_names={"w"})
        four = M.estimate_entry(p, fetch_list=["loss"],
                                scope_names={"w"}, steps=4)
        assert four.liveness.steps == 4
        assert four.arg_bytes == one.arg_bytes + 3 * 24   # feeds x4
        assert four.output_bytes == 4 * one.output_bytes  # fetches x4
        assert four.temp_peak_bytes == one.temp_peak_bytes

    def test_per_device_division_under_a_plan(self, static_mode):
        prog, _startup, _loss = _mlp_program()
        plan = fleet.plan_program(prog, (2, 4),
                                  roles=("data", "model"))
        est = M.estimate_entry(prog, fetch_list=[], plan=plan)
        # params shard over model(4), batch feeds + temps over data(2)
        assert est.per_device_bytes < est.peak_bytes
        est_dp = M.estimate_entry(prog, fetch_list=[], data_devices=8)
        assert est_dp.per_device_bytes < est_dp.peak_bytes

    def test_remat_candidates_and_ptl104(self):
        """A big, cheap activation living across the whole program is
        the canonical remat candidate; PTL104 names it."""
        p, blk = _base(shape=(64, 64))
        _op(blk, "relu", lambda a: jnp.maximum(a, 0), ["x"], ["a"],
            shape=(64, 64))
        for i in range(5):  # a long chain NOT consuming `a`
            _op(blk, "scale", lambda v: v * 1.1,
                ["x" if i == 0 else f"c{i - 1}"], [f"c{i}"],
                shape=(64, 64))
        _op(blk, "multiply", lambda a, b: a * b, ["a", "c4"], ["o"],
            shape=(64, 64))
        cands = M.remat_candidates(p, fetch_list=["o"])
        assert cands and cands[0]["name"] == "a"
        assert cands[0]["writer"] == "relu"
        assert cands[0]["bytes"] == _f32((64, 64))
        assert cands[0]["span"] == 6
        _est, rep = M.memory_report(p, fetch_list=["o"])
        assert rep.has("PTL104")
        assert any(d.var == "a" for d in rep.warnings())

    def test_measured_peak_bytes_helper(self):
        assert M.measured_peak_bytes(None) is None
        assert M.measured_peak_bytes({}) is None
        assert M.measured_peak_bytes(
            {"argument_size": 100, "output_size": 50, "temp_size": 30,
             "alias_size": 40, "generated_code_size": 999}) == 140


# -- predicted vs measured (the acceptance gate) ------------------------------


def _mlp_program(batch=16):
    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=36, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, startup, loss


def _lenet_program(batch=8):
    from paddle_tpu.models.vision import LeNet

    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 1, 28, 28])
        y = pt.static.data("y", [batch], "int64")
        loss = F.cross_entropy(LeNet()(x), y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, startup, loss


def _feed_for(prog, rng):
    feed = {}
    for v in prog.global_block.vars.values():
        if not v.is_data or v.name.startswith("@"):
            continue
        shape = tuple(int(d) for d in v._data.shape)
        if "int" in str(v._data.dtype):
            feed[v.name] = rng.randint(0, 10, shape).astype(
                str(v._data.dtype))
        else:
            feed[v.name] = rng.randn(*shape).astype("float32")
    return feed


def _compile_and_measure(build):
    from paddle_tpu.obs.mfu import entry_analysis

    prog, startup, loss = build()
    exe = fluid.Executor()
    exe.run(startup)
    exe.run(prog, feed=_feed_for(prog, np.random.RandomState(0)),
            fetch_list=[loss])
    (compiled,) = exe._cache.values()
    measured = M.measured_peak_bytes(entry_analysis(compiled)["memory"])
    return compiled, measured


class TestPredictedVsMeasured:
    """The ISSUE-11 acceptance gate: the static liveness walk's
    peak-HBM prediction must land within 15% of the compiled
    executable's own memory_analysis() on the zoo models."""

    @pytest.mark.parametrize("build", [_mlp_program, _lenet_program],
                             ids=["mlp", "lenet"])
    def test_within_15_percent(self, static_mode, build):
        compiled, measured = _compile_and_measure(build)
        pred = compiled.predicted_memory
        assert pred is not None and pred["peak_bytes"] > 0
        if measured is None:
            pytest.skip("backend reports no memory_analysis()")
        drift = abs(pred["peak_bytes"] - measured) / measured
        assert drift <= 0.15, (
            f"predicted {pred['peak_bytes']} vs measured {measured}: "
            f"drift {drift:.1%} > 15% (peak_op {pred['peak_op']})")

    def test_estimate_rides_the_compiled_entry(self, static_mode):
        compiled, _ = _compile_and_measure(_mlp_program)
        est = compiled.memory_estimate
        assert est is not None
        assert est.peak_bytes == compiled.predicted_memory["peak_bytes"]
        # the breakdown adds up
        assert est.peak_bytes == est.arg_bytes + est.const_bytes + \
            est.output_bytes + est.temp_peak_bytes


# -- Executor verifier checks -------------------------------------------------


class TestExecutorChecks:
    def test_pta011_use_after_donate_alias(self, static_mode):
        """Two persistables sharing ONE scope buffer while one is
        donated: the compile must die with PTA011, not dispatch a
        use-after-free."""
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            blk = prog.global_block
            blk.create_var(name="x", shape=(2, 3), dtype="float32",
                           is_data=True)
            blk.create_var(name="w", shape=(2, 3), dtype="float32",
                           persistable=True)
            blk.create_var(name="v", shape=(2, 3), dtype="float32",
                           persistable=True)
            # v is read-only (frozen); w is re-emitted (donated) with
            # its last write ending its range — the PROGRAM is clean
            # (no PTA007); only the Scope aliasing is the hazard
            _op(blk, "axpy", lambda a, b: a + b, ["x", "v"], ["t"])
            _op(blk, "axpy2", lambda a, b: a + b, ["t", "w"], ["w"])
        shared = jnp.zeros((2, 3), jnp.float32)
        global_scope().set("w", shared)
        global_scope().set("v", shared)  # the alias
        exe = fluid.Executor()
        feed = {"x": np.zeros((2, 3), np.float32)}
        with pytest.raises(ProgramVerificationError) as ei:
            exe.run(prog, feed=feed, fetch_list=["t"])
        assert any(d.code == "PTA011" for d in ei.value.errors)
        # distinct buffers: same program compiles clean
        global_scope().set("v", jnp.zeros((2, 3), jnp.float32))
        exe.run(prog, feed=feed, fetch_list=["t"])

    def test_pta012_plan_spec_mismatch(self, static_mode):
        """Feed specs inconsistent with the installed plan surface as
        PTA012 diagnostics on the compile report (the run itself
        proceeds on the documented replicated fallback)."""
        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        prog, startup, loss = _mlp_program()
        exe = fluid.Executor()
        exe.run(startup)
        cp = fleet.auto_parallel(prog, (2, 4),
                                 roles=("data", "model"), verify=False)
        # tamper: a spec for a feed this entry never feeds, and a spec
        # that cannot fit y's (16, 1) shape on the model axis
        cp._plan.feed_specs["ghost"] = ("data",)
        cp._plan.feed_specs["y"] = ("data", "model")
        rng = np.random.RandomState(0)
        exe.run(cp, feed={"x": rng.randn(16, 8).astype(np.float32),
                          "y": rng.randn(16, 1).astype(np.float32)},
                fetch_list=[loss])
        rep = exe.last_diagnostics
        pta012 = [d for d in rep if d.code == "PTA012"]
        assert {d.var for d in pta012} >= {"ghost", "y"}
        assert not rep.errors()  # warnings: the fallback is documented

    def test_clean_plan_has_no_pta012(self, static_mode):
        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        prog, startup, loss = _mlp_program()
        exe = fluid.Executor()
        exe.run(startup)
        cp = fleet.auto_parallel(prog, (2, 4),
                                 roles=("data", "model"), verify=False)
        rng = np.random.RandomState(0)
        exe.run(cp, feed={"x": rng.randn(16, 8).astype(np.float32),
                          "y": rng.randn(16, 1).astype(np.float32)},
                fetch_list=[loss])
        assert not exe.last_diagnostics.has("PTA012")


# -- planner budget (PTA013) --------------------------------------------------


class TestPlannerBudget:
    def test_tiny_budget_rejects_everything_with_pta013(
            self, static_mode):
        prog, _startup, _loss = _mlp_program()
        with pytest.raises(ValueError) as ei:
            fleet.plan_program(prog, (2, 4), hbm_budget=1)
        assert "PTA013" in str(ei.value)

    def test_partial_budget_prunes_over_budget_candidates(
            self, static_mode):
        prog, _startup, _loss = _mlp_program()
        base = fleet.plan_program(prog, (2, 4))
        peaks = sorted(c["peak_bytes_per_device"]
                       for c in base.candidates if c["feasible"])
        assert peaks and all(p > 0 for p in peaks)
        budget = peaks[0] + 1  # only the leanest layout fits
        plan = fleet.plan_program(prog, (2, 4), hbm_budget=budget)
        assert plan.peak_bytes_per_device <= budget
        rejected = [c for c in plan.candidates
                    if not c["feasible"] and "PTA013" in c["note"]]
        assert rejected, plan.candidates
        # the memory term is priced, not just gated: every feasible
        # candidate carries a peak and the plan reports the winner's
        assert base.peak_bytes_per_device == peaks[0] or \
            base.peak_bytes_per_device in peaks

    def test_budget_rides_auto_parallel_and_env(self, static_mode,
                                                monkeypatch):
        prog, _startup, _loss = _mlp_program()
        with pytest.raises(ValueError):
            fleet.auto_parallel(prog, (2, 4), hbm_budget=1,
                                verify=False)
        monkeypatch.setenv("PADDLE_TPU_HBM_BUDGET", "1")
        with pytest.raises(ValueError):
            fleet.plan_program(prog, (2, 4))

    def test_candidate_diagnostic_object(self, static_mode):
        from paddle_tpu.fleet.planner import (PlanCandidate,
                                              _over_budget)

        cand = _over_budget(
            PlanCandidate(roles=("data",), axes={"data": 8},
                          feasible=True), 1000, 10)
        assert not cand.feasible
        assert cand.diagnostic.code == "PTA013"
        assert "PTA013" in cand.note


# -- journal memory event -----------------------------------------------------


class TestJournalMemoryEvent:
    def test_per_entry_predicted_then_measured(self, static_mode,
                                               tmp_path):
        """One memory event at compile (predicted only), a second once
        the entry's lazy analysis lands (measured + drift <= 15%);
        run_report folds them into memory_summary."""
        import importlib.util
        import os

        from paddle_tpu.obs import journal as J
        from paddle_tpu.obs.mfu import entry_analysis

        prog, startup, loss = _mlp_program()
        run_dir = str(tmp_path / "run")
        with J.RunJournal(run_dir, flush_every=1):
            exe = fluid.Executor()
            exe.run(startup)
            feed = _feed_for(prog, np.random.RandomState(0))
            exe.run(prog, feed=feed, fetch_list=[loss])
            (compiled,) = exe._cache.values()
            entry_analysis(compiled)  # blocking: the measured side
            exe.run(prog, feed=feed, fetch_list=[loss])

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "run_report", os.path.join(root, "tools", "run_report.py"))
        rr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rr)
        run = rr.load_run(run_dir)
        mem = [e for e in run["events"] if e.get("kind") == "memory"]
        assert len(mem) == 2
        predicted_only, measured = mem
        assert predicted_only["predicted_peak_bytes"] > 0
        assert predicted_only["measured_peak_bytes"] is None
        assert measured["measured_peak_bytes"] is not None
        assert measured["drift"] is not None
        assert measured["drift"] <= 0.15
        summ = rr.memory_summary(run)
        assert summ["entries"] == 2 and summ["measured_entries"] == 1
        assert summ["max_drift"] == measured["drift"]
        assert "drift" in rr.render_run(run)


# -- fluid.memory_optimize is real now ----------------------------------------


class TestMemoryOptimize:
    def test_none_in_none_out(self):
        assert fluid.memory_optimize(None) is None

    def test_returns_the_estimate(self, static_mode, capsys):
        prog, _startup, _loss = _mlp_program()
        est = fluid.memory_optimize(prog, print_log=True)
        assert isinstance(est, M.MemoryEstimate)
        assert est.peak_bytes > 0
        assert "predicted peak" in capsys.readouterr().out
