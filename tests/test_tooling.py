"""Repo tooling gates: the analysis self-lint and the pytest marker
contract ride the tier-1 command path, so a pass regression or an
unregistered marker fails fast instead of silently weakening CI."""
import configparser
import importlib.util
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_lint_program():
    return _load_tool("lint_program")


def test_lint_program_self_test_passes():
    """tools/lint_program.py --self-test: every seeded malformed-Program
    class must be rejected with its distinct diagnostic, and DCE must
    drop the seeded dead op. Run in-process (same interpreter as the
    suite) so it is part of the tier-1 gate."""
    mod = _load_lint_program()
    assert mod.main(["--self-test"]) == 0


def test_slow_marker_is_registered():
    """The tier-1 command filters with -m 'not slow'; if the marker ever
    vanishes from pytest.ini the filter silently matches nothing it
    should. Pin the registration."""
    ini = os.path.join(ROOT, "pytest.ini")
    assert os.path.exists(ini), "pytest.ini with the slow marker is gone"
    cp = configparser.ConfigParser()
    cp.read(ini)
    markers = cp.get("pytest", "markers", fallback="")
    assert any(line.strip().startswith("slow")
               for line in markers.splitlines()), \
        "the 'slow' marker must stay registered for the tier-1 filter"


def test_chaos_run_self_test_passes():
    """tools/chaos_run.py --self-test: every registered fault injector
    must have a scenario that ends in a completed, verified-correct run
    (and an injector without a scenario fails the gate). In-process so
    it rides the tier-1 command path like the lint self-test."""
    mod = _load_tool("chaos_run")
    assert mod.main(["--self-test"]) == 0


def test_obs_report_self_test_passes():
    """tools/obs_report.py --self-test: every instrumented site
    (executor, analysis passes, dispatch sampling, dataloader,
    resilience guards, checkpoint IO, StepTimer) must register AND tick
    its instruments, and the exported Chrome trace must contain the
    compile/run/dataloader spans. An instrumented site losing its
    instruments fails the gate. In-process so it rides the tier-1
    command path like the lint and chaos self-tests."""
    mod = _load_tool("obs_report")
    assert mod.main(["--self-test"]) == 0


def test_run_report_self_test_passes():
    """tools/run_report.py --self-test: a synthetic healthy/regressed
    run pair written through the real RunJournal API must round-trip the
    loader, fire the loss_spike + nonfinite_streak detectors on the
    injected faults (and stay silent on the healthy run), carry an
    MFU/goodput summary, and the diff gate must flag the injected
    step-time AND loss regressions — with no false positive on A-vs-A.
    In-process so it rides the tier-1 command path like the other
    self-tests."""
    mod = _load_tool("run_report")
    assert mod.main(["--self-test"]) == 0


def test_shard_report_self_test_passes():
    """tools/shard_report.py --self-test: canned-HLO collective parsing
    must match hand-computed byte volumes (async pairs, iota replica
    groups, mesh-axis attribution), and an 8-fake-device
    with_data_parallel entry must report nonzero all-reduce bytes with
    feeds sharded on 'data' and correct per-device footprints. In-
    process so it rides the tier-1 command path like the other
    self-tests."""
    mod = _load_tool("shard_report")
    assert mod.main(["--self-test"]) == 0


def test_perf_gate_self_test_passes():
    """tools/perf_gate.py --self-test: canned-HLO donation/fusion/while
    accounting must match hand-computed counts (and the bound checker
    must flag seeded regressions), and the live 8-fake-device check must
    hold the ISSUE-6 acceptance gate — K=8 microbatches through the
    fused lax.scan path produce a bitwise-identical loss trajectory to
    8 sequential Executor.run calls with exactly 1 compile + 1 dispatch,
    the persistable carry donated, and exactly one while loop in the
    executable. In-process so it rides the tier-1 command path like the
    other self-tests."""
    mod = _load_tool("perf_gate")
    assert mod.main(["--self-test"]) == 0


def test_serve_bench_self_test_passes():
    """tools/serve_bench.py --self-test: the ragged paged decode kernel
    must match the dense reference on page-crossing ragged batches, the
    hand-checked continuous-batching scheduler trace must hold exactly
    under a deterministic clock (token-budget admission order,
    oldest-protected preemption, arrival-order requeue, zero-leak
    teardown), and the pressured engine must reproduce the dense
    oracle's greedy tokens with manual-clock-exact TTFT. In-process so
    it rides the tier-1 command path like the other self-tests."""
    mod = _load_tool("serve_bench")
    assert mod.main(["--self-test"]) == 0


def test_usage_report_self_test_passes():
    """tools/usage_report.py --self-test: the ISSUE-20 acceptance core
    — the divmod decode split (10 ns over 3 lanes -> 4,3,3 in survivor
    order) and the busy == sum(per-tenant) == sum(per-request)
    telescoping invariant hold bitwise; the hand-computed ManualClock
    page-second integral (2 pages x 2 s + 3 pages x 3 s = 13e9
    pages-ns) closes with alloc==free; a real TickingClock engine run
    bills token- and nanosecond-exact through the journal into the
    chargeback table; and the --diff gates fire on the injected 2x
    fairness violation and 2x per-tenant p99 regression with A-vs-A
    clean. In-process so it rides the tier-1 command path like the
    other self-tests."""
    mod = _load_tool("usage_report")
    assert mod.main(["--self-test"]) == 0


def test_slo_report_self_test_passes():
    """tools/slo_report.py --self-test: the ISSUE-19 acceptance core —
    under a ManualClock the 14.4x fast-burn availability fixture must
    fire the page at the hand-computed 9th bad tick and clear it at the
    4th clean tick (the warn at bad tick 6 / clean tick 27), latch
    exactly once while firing, scrape the slo_burn_rate gauge bitwise-
    equal to the evaluator's float, and reconstruct the evaluator's
    alert log from the journaled slo.* events alone; A-vs-A must diff
    clean. In-process so it rides the tier-1 command path like the
    other self-tests."""
    mod = _load_tool("slo_report")
    assert mod.main(["--self-test"]) == 0


def test_request_report_self_test_passes():
    """tools/request_report.py --self-test: the ISSUE-18 acceptance
    core — a real pressured-engine run's journal-derived phase
    attribution must sum BITWISE to each request's e2e on the manual
    clock (preemption loss matching the engine's own stamp pairs), and
    the hand-written routed fixture (rate hold + requeue + preemption)
    must reproduce every hand-computed phase to the nanosecond, carry
    both dispatch segments, and export request lanes with the
    cross-replica flow arrow. In-process so it rides the tier-1
    command path like the other self-tests."""
    mod = _load_tool("request_report")
    assert mod.main(["--self-test"]) == 0


def test_elastic_run_self_test_passes():
    """tools/elastic_run.py --self-test: the ISSUE-8 acceptance drill —
    a real 2-worker CPU gang under GangSupervisor survives, in ONE run,
    a worker_kill (hard os._exit), a worker_hang (only the heartbeat
    watchdog can catch it) and a preempt_signal (SIGTERM -> graceful
    checkpoint-and-exit 75, relaunched budget-free), resuming each time
    from the newest intact checkpoint with a final loss trajectory
    BITWISE identical to an unfaulted reference run; restart-budget
    exhaustion surfaces a clean ElasticBudgetError with the attempt
    history; and the supervisor's journal events roll up into
    run_report's elastic summary (restarts/preemptions/watchdog kills/
    resume latency). In-process so it rides the tier-1 command path
    like the other self-tests."""
    mod = _load_tool("elastic_run")
    assert mod.main(["--self-test"]) == 0


def test_elastic_drill_ran_lockdep_enabled_and_clean():
    """The cached elastic gang drill exports PADDLE_TPU_LOCKDEP=1 to
    every worker (raise mode — a cycle crashes the worker and the
    bitwise-identity gate already fails); belt and braces, the per-rank
    journals must carry zero lockdep.cycle events."""
    import json

    mod = _load_tool("elastic_run")
    res = mod.drill_result()
    assert not res["failures"], res["failures"]
    cycles = []
    for dirpath, _dn, filenames in os.walk(res["journal_dir"]):
        for fn in filenames:
            if not fn.endswith(".jsonl"):
                continue
            with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("t") == "event" and \
                            rec.get("kind") == "lockdep.cycle":
                        cycles.append(rec)
    assert not cycles, cycles


def test_fleet_report_self_test_passes():
    """tools/fleet_report.py --self-test: the ISSUE-13 acceptance core
    — canned 2-rank journal fixtures must reproduce EXACT cross-rank
    numbers (skew max = 20/15, rank-1-at-2.0x straggler attribution
    with re-arm-per-episode detection, merged p50=500/p99=1000 request
    percentiles, the skew-regression diff gate with no A-vs-A false
    positive), and a REAL 2-worker GangSupervisor drill with one
    injected worker_hang must produce per-rank journals whose
    aggregate identifies the hung rank (from the journals, not the
    poll-noisy watchdog rank) and fuse into a merged Perfetto trace
    with one distinct lane per rank. In-process so it rides the tier-1
    command path like the other self-tests."""
    mod = _load_tool("fleet_report")
    assert mod.main(["--self-test"]) == 0


def test_fleet_plan_self_test_passes():
    """tools/fleet_plan.py --self-test: mesh canonicalization/validation
    fixtures, the hand-computed 412 B cost fixture (Megatron pairing +
    ring-factor wire accounting must be EXACT), a live 8-fake-device
    fleet.auto_parallel run whose predicted wire bytes match the
    compiled HLO's CollectiveProfile within 10% (plan-keyed cache
    entry, finite losses), and the tp-heavy model preferring
    dp2 x model4 over pure DP with a visible cost delta. In-process so
    it rides the tier-1 command path like the other self-tests."""
    mod = _load_tool("fleet_plan")
    assert mod.main(["--self-test"]) == 0


def test_aot_cache_self_test_passes():
    """tools/aot_cache.py --self-test: the ISSUE-12 acceptance core —
    a compiled entry round-trips through serialize/deserialize with
    BITWISE-identical outputs and its input_output_alias donation
    intact, a changed feed shape produces a clean content-key miss
    (never a stale load), a poisoned-fingerprint envelope refuses to
    load and falls back to a fresh compile, verify/evict classify the
    stale entry exactly, and a fresh Executor over a fresh build of the
    same program hydrates from disk with a bitwise-identical loss
    trajectory whose donated carry still passes the perf gate. In-
    process so it rides the tier-1 command path like the other
    self-tests."""
    mod = _load_tool("aot_cache")
    assert mod.main(["--self-test"]) == 0


def test_lint_concurrency_self_test_passes():
    """tools/lint_concurrency.py --self-test: the hand-built AB/BA
    deadlock, blocking-under-lock, and unguarded-write fixtures must
    each be caught (clean fixture silent, waiver comments honored),
    AND the real paddle_tpu/ tree must carry zero unwaived
    PTC001/PTC002 findings — tier-1 is the gate that keeps future
    serving/fleet PRs lock-discipline-clean. In-process so it rides
    the tier-1 command path like the other self-tests."""
    mod = _load_tool("lint_concurrency")
    assert mod.main(["--self-test"]) == 0


def test_chaos_marker_is_registered():
    """tests/test_resilience.py marks itself `chaos`; an unregistered
    marker would warn (or fail under --strict-markers). Pin it."""
    ini = os.path.join(ROOT, "pytest.ini")
    cp = configparser.ConfigParser()
    cp.read(ini)
    markers = cp.get("pytest", "markers", fallback="")
    assert any(line.strip().startswith("chaos")
               for line in markers.splitlines()), \
        "the 'chaos' marker must stay registered"


def test_lint_cli_reports_user_script(tmp_path):
    """End-to-end CLI path: a script building a Program into the default
    main program gets a printed report and exit code 0 when clean."""
    script = tmp_path / "build.py"
    script.write_text(
        "import paddle_tpu.fluid as fluid\n"
        "x = fluid.layers.data('x', [-1, 4], 'float32')\n"
        "y = fluid.layers.relu(x)\n")
    mod = _load_lint_program()
    assert mod.main([str(script)]) == 0
