"""Two-stage detection op tests (ops/rcnn.py).

Mirrors the reference surfaces: generate_proposals (detection.py:2646),
rpn_target_assign (:157), retinanet_target_assign (:370),
retinanet_detection_output (:735), distribute/collect_fpn_proposals
(:3838/:3914), psroi_pool / prroi_pool (nn.py:13439/:13504),
density_prior_box (:1800), box_decoder_and_assign (:3770),
locality_aware_nms (:3327), roi_perspective_transform (:1931),
generate_proposal_labels / generate_mask_labels (:2308/:2440),
deformable_roi_pooling (nn.py:14038), multi_box_head.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops


def t(a, dt="float32"):
    return pt.to_tensor(np.asarray(a, dt))


def test_encode_decode_roundtrip():
    from paddle_tpu.ops.rcnn import _encode_deltas, _decode_deltas
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    anchors = np.abs(rng.rand(6, 2)) * 20
    anchors = np.concatenate([anchors, anchors + 5 + rng.rand(6, 2) * 30],
                             axis=1).astype("float32")
    gts = anchors + rng.randn(6, 4).astype("float32") * 2
    enc = _encode_deltas(jnp.asarray(anchors), jnp.asarray(gts))
    dec = _decode_deltas(jnp.asarray(anchors), enc)
    assert np.allclose(np.asarray(dec), gts, atol=1e-3)


def test_generate_proposals_shapes_and_decode():
    pt.seed(0)
    rng = np.random.RandomState(0)
    B, A, H, W = 2, 3, 4, 4
    scores = t(rng.rand(B, A, H, W))
    deltas = t(rng.randn(B, A * 4, H, W) * 0.1)
    im_info = t([[32.0, 32.0, 1.0]] * B)
    anchors = t(np.tile(np.array([0, 0, 7, 7], "float32"),
                        (H, W, A, 1)))
    variances = t(np.ones((H, W, A, 4), "float32"))
    rois, probs, counts = ops.generate_proposals(
        scores, deltas, im_info, anchors, variances, pre_nms_top_n=20,
        post_nms_top_n=8, nms_thresh=0.7, min_size=1.0)
    assert list(rois.shape) == [B, 8, 4]
    assert list(probs.shape) == [B, 8]
    c = np.asarray(counts.numpy())
    assert (c >= 1).all() and (c <= 8).all()
    r = np.asarray(rois.numpy())
    assert (r >= 0).all() and (r <= 31.0 + 1e-3).all()


def test_rpn_target_assign_sampling():
    pt.seed(0)
    A = 64
    rng = np.random.RandomState(1)
    xy = rng.rand(A, 2).astype("float32") * 40
    anchors = np.concatenate([xy, xy + 8], axis=1)
    gt = np.array([[0, 0, 10, 10], [30, 30, 44, 44]], "float32")
    labels, tgt, fg, bg = ops.rpn_target_assign(
        None, None, t(anchors), None, t(gt),
        rpn_batch_size_per_im=16, rpn_fg_fraction=0.5)
    lab = np.asarray(labels.numpy())
    assert set(np.unique(lab)).issubset({-1, 0, 1})
    assert (lab == 1).sum() >= 1            # forced best-anchor positives
    assert (lab == 0).sum() <= 16
    assert list(tgt.shape) == [A, 4]


def test_retinanet_target_assign_dense():
    pt.seed(0)
    A = 32
    rng = np.random.RandomState(2)
    xy = rng.rand(A, 2).astype("float32") * 30
    anchors = np.concatenate([xy, xy + 10], axis=1)
    gt = np.array([[0, 0, 12, 12]], "float32")
    gl = np.array([3], "int32")
    cls, tgt, fg, bg, fg_num = ops.retinanet_target_assign(
        None, None, t(anchors), None, t(gt), t(gl, "int32"))
    c = np.asarray(cls.numpy())
    assert ((c == 3) | (c == 0) | (c == -1)).all()
    assert int(np.asarray(fg_num.numpy())) == (c == 3).sum()


def test_distribute_and_collect_fpn():
    rois = t([[0, 0, 10, 10],        # small -> low level
              [0, 0, 200, 200],      # large -> high level
              [0, 0, 56, 56]])
    lvl, masks, restore = ops.distribute_fpn_proposals(
        rois, min_level=2, max_level=5, refer_level=4, refer_scale=224)
    lv = np.asarray(lvl.numpy())
    assert lv[0] < lv[1]
    assert len(masks) == 4
    # collect: top-2 by score
    out, n = ops.collect_fpn_proposals(
        [t([[0, 0, 1, 1], [0, 0, 2, 2]]), t([[0, 0, 3, 3]])],
        [t([0.1, 0.9]), t([0.5])], 2, 3, post_nms_top_n=2)
    o = np.asarray(out.numpy())
    assert int(np.asarray(n.numpy())) == 2
    assert np.allclose(o[0], [0, 0, 2, 2])  # best score first


def test_psroi_pool_constant_channels():
    # constant per-channel feature: each output bin must equal the value
    # of its designated input channel
    C_out, ph, pw = 2, 2, 2
    C = C_out * ph * pw
    feat = np.zeros((1, C, 8, 8), "float32")
    for c in range(C):
        feat[0, c] = c
    rois = t([[0.0, 0.0, 8.0, 8.0]])
    out = ops.psroi_pool(t(feat), rois, C_out, 1.0, ph, pw)
    o = np.asarray(out.numpy())[0]
    for co in range(C_out):
        for i in range(ph):
            for j in range(pw):
                assert abs(o[co, i, j] - (co * ph * pw + i * pw + j)) < 1e-4


def test_prroi_pool_matches_align():
    rng = np.random.RandomState(3)
    feat = t(rng.randn(1, 3, 8, 8))
    rois = t([[1.0, 1.0, 6.0, 6.0]])
    out = ops.prroi_pool(feat, rois, 1.0, 2, 2)
    assert list(out.shape) == [1, 3, 2, 2]


def test_density_prior_box():
    fm = t(np.zeros((1, 8, 4, 4), "float32"))
    im = t(np.zeros((1, 3, 32, 32), "float32"))
    boxes, var = ops.density_prior_box(
        fm, im, densities=[2], fixed_sizes=[8.0], fixed_ratios=[1.0])
    # P = density^2 * len(fixed_ratios) = 4 per cell
    assert list(boxes.shape) == [4, 4, 4, 4]
    b = np.asarray(boxes.numpy())
    assert (b[..., 2] > b[..., 0]).all()


def test_box_decoder_and_assign():
    prior = t([[0, 0, 10, 10], [5, 5, 20, 20]])
    pvar = t(np.ones((2, 4), "float32"))
    deltas = t(np.zeros((2, 3 * 4), "float32"))   # zero deltas -> priors
    scores = t([[0.1, 0.8, 0.1], [0.6, 0.2, 0.2]])
    decoded, assigned = ops.box_decoder_and_assign(prior, pvar, deltas,
                                                   scores)
    a = np.asarray(assigned.numpy())
    p = np.asarray(prior.numpy())
    assert np.allclose(a, p, atol=1e-3)           # zero deltas decode back


def test_locality_aware_nms_merges():
    boxes = t([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]])
    scores = t([0.5, 0.5, 0.9])
    out, sc, n = ops.locality_aware_nms(boxes, scores,
                                        nms_threshold=0.3)
    assert int(np.asarray(n.numpy())) == 2        # first two merged
    o = np.asarray(out.numpy())
    s = np.asarray(sc.numpy())
    # the merged box accumulates score 0.5+0.5=1.0 > 0.9, so it's first
    assert abs(s[0] - 1.0) < 1e-3 and 0.0 < o[0][0] < 1.0
    assert np.allclose(o[1], [50, 50, 60, 60], atol=1e-3)


def test_roi_perspective_identity_quad():
    rng = np.random.RandomState(4)
    feat = rng.randn(1, 2, 8, 8).astype("float32")
    # axis-aligned quad == crop; compare against the raw window
    quad = t([[2, 2, 5, 2, 5, 5, 2, 5]])
    out = ops.roi_perspective_transform(t(feat), quad, 4, 4)
    assert list(out.shape) == [1, 2, 4, 4]
    o = np.asarray(out.numpy())
    assert abs(o[0, 0, 0, 0] - feat[0, 0, 2, 2]) < 1e-3
    assert abs(o[0, 0, 3, 3] - feat[0, 0, 5, 5]) < 1e-3


def test_generate_proposal_and_mask_labels():
    pt.seed(0)
    rois = t([[0, 0, 10, 10], [0, 0, 11, 11], [30, 30, 40, 40],
              [31, 31, 41, 41]])
    gt = t([[0, 0, 10, 10]])
    cls = t([5], "int32")
    labels, tgt, w, fg, bg, best = ops.generate_proposal_labels(
        rois, cls, None, gt, batch_size_per_im=4, fg_fraction=0.5,
        fg_thresh=0.5, bg_thresh_hi=0.5)
    lab = np.asarray(labels.numpy())
    assert (lab[:2] == 5).any()                   # overlapping rois -> fg
    masks = np.zeros((1, 64, 64), "float32")
    masks[0, :16, :16] = 1.0
    mt = ops.generate_mask_labels(None, cls, None, t(masks), rois,
                                  resolution=7, matched_gt=best,
                                  fg_mask=fg)
    m = np.asarray(mt.numpy())
    assert m.shape == (4, 7, 7)
    fgn = np.asarray(fg.numpy())
    if fgn[0]:
        assert m[0].max() == 1.0                  # roi inside the mask


def test_deformable_roi_pooling_paths():
    rng = np.random.RandomState(5)
    feat = t(rng.randn(1, 8, 8, 8))
    rois = t([[1.0, 1.0, 6.0, 6.0]])
    out = ops.deformable_roi_pooling(feat, rois, None, no_trans=True,
                                     pooled_height=2, pooled_width=2)
    assert list(out.shape) == [1, 8, 2, 2]
    ps = ops.deformable_roi_pooling(feat, rois, None, no_trans=True,
                                    pooled_height=2, pooled_width=2,
                                    position_sensitive=True)
    assert list(ps.shape) == [1, 2, 2, 2]
    trans = t(np.zeros((1, 2, 2, 2), "float32"))
    dt_ = ops.deformable_roi_pooling(feat, rois, trans, pooled_height=2,
                                     pooled_width=2)
    assert np.allclose(np.asarray(dt_.numpy()), np.asarray(out.numpy()),
                       atol=1e-4)                 # zero offsets == align


def test_retinanet_detection_output():
    pt.seed(0)
    rng = np.random.RandomState(6)
    A = 8
    xy = rng.rand(A, 2).astype("float32") * 20
    anchors = np.concatenate([xy, xy + 10], axis=1)
    deltas = t(rng.randn(1, A, 4) * 0.05)
    scores = t(np.abs(rng.rand(1, 3, A)))
    im_info = t([[32.0, 32.0, 1.0]])
    out, counts = ops.retinanet_detection_output(
        [deltas], [scores], [t(anchors)], im_info, keep_top_k=5)
    assert list(out.shape) == [1, 5, 6]


def test_multi_box_head():
    from paddle_tpu.nn.nets import multi_box_head

    pt.seed(0)
    rng = np.random.RandomState(7)
    img = t(rng.randn(2, 3, 64, 64))
    f1 = t(rng.randn(2, 8, 8, 8))
    f2 = t(rng.randn(2, 8, 4, 4))
    locs, confs, boxes, var = multi_box_head(
        [f1, f2], img, base_size=64, num_classes=5,
        aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90)
    P = boxes.shape[0]
    assert list(locs.shape) == [2, P, 4]
    assert list(confs.shape) == [2, P, 5]
    assert list(var.shape) == [P, 4]


def test_locality_aware_nms_score_threshold():
    """Sub-threshold boxes must be dropped entirely, not emitted as
    zero-coordinate detections (review regression)."""
    boxes = t([[0, 0, 10, 10], [50, 50, 60, 60]])
    scores = t([0.9, 0.05])
    out, s, n = ops.locality_aware_nms(boxes, scores, score_threshold=0.5,
                                       nms_threshold=0.3)
    assert int(np.asarray(n.numpy())) == 1
    assert np.allclose(np.asarray(out.numpy())[0], [0, 0, 10, 10])


def test_retinanet_output_clipped_to_image():
    A = 4
    anchors = np.array([[0, 0, 10, 10]] * A, "float32")
    deltas = t(np.full((1, A, 4), 2.0))
    scores = t(np.ones((1, 2, A)))
    im_info = t([[20.0, 20.0, 1.0]])
    out, cnt = ops.retinanet_detection_output(
        [deltas], [scores], [t(anchors)], im_info, keep_top_k=3,
        score_threshold=0.1)
    assert (np.asarray(out.numpy())[..., 2:] <= 19.0 + 1e-3).all()
