"""fluid.dataset (DatasetFactory / InMemoryDataset / QueueDataset) +
Executor.train_from_dataset / infer_from_dataset
(ref: python/paddle/fluid/dataset.py:22,325,847; executor.py:1369,1436).
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid


def _write_slot_file(path, xs, ys):
    """MultiSlot format: count-prefixed groups per slot (x then y)."""
    with open(path, "w") as f:
        for x, y in zip(xs, ys):
            vals = " ".join(f"{v:.6f}" for v in x)
            f.write(f"{len(x)} {vals} 1 {int(y)}\n")


def _make_files(tmp_path, n_files=2, rows=32, dim=4, seed=0):
    rng = np.random.RandomState(seed)
    W = rng.randn(dim).astype(np.float32)
    paths = []
    for i in range(n_files):
        xs = rng.randn(rows, dim).astype(np.float32)
        ys = (xs @ W > 0).astype(np.int64)
        p = str(tmp_path / f"part-{i}.txt")
        _write_slot_file(p, xs, ys)
        paths.append(p)
    return paths


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _build_program(batch, dim=4):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, dim])
        y = fluid.data(name="y", shape=[batch], dtype="int64")
        logits = fluid.layers.fc(x, size=2)
        import paddle_tpu.nn.functional as F

        loss = F.cross_entropy(logits, y)
        fluid.optimizer.Adam(learning_rate=5e-2).minimize(loss)
    return prog, startup, x, y, loss


def test_queue_dataset_batches(tmp_path, static_mode):
    paths = _make_files(tmp_path)
    prog, startup, x, y, loss = _build_program(batch=8)
    ds = fluid.DatasetFactory().create_dataset()  # QueueDataset default
    assert isinstance(ds, fluid.QueueDataset)
    ds.set_use_var([x, y])
    ds.set_batch_size(8)
    ds.set_filelist(paths)
    batches = list(ds.iter_batches())
    assert len(batches) == 8  # 64 rows / 8
    assert batches[0]["x"].shape == (8, 4)
    assert batches[0]["y"].shape == (8,)
    assert batches[0]["y"].dtype == np.int64


def test_train_from_dataset_learns(tmp_path, static_mode):
    pt.seed(0)
    paths = _make_files(tmp_path, n_files=4, rows=64)
    prog, startup, x, y, loss = _build_program(batch=16)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_use_var([x, y])
    ds.set_batch_size(16)
    ds.set_filelist(paths)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 256
    ds.set_shuffle_seed(0)
    ds.local_shuffle()
    exe = fluid.Executor()
    exe.run(startup)
    first = exe.train_from_dataset(program=prog, dataset=ds,
                                   fetch_list=[loss], print_period=0)
    l0 = float(np.asarray(first[0]))
    for _ in range(5):
        last = exe.train_from_dataset(program=prog, dataset=ds,
                                      fetch_list=[loss], print_period=0)
    assert float(np.asarray(last[0])) < l0, (l0, last)


def test_infer_from_dataset(tmp_path, static_mode):
    paths = _make_files(tmp_path)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[8, 4])
        y = fluid.data(name="y", shape=[8], dtype="int64")
        out = fluid.layers.fc(x, size=2)
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(8)
    ds.set_filelist(paths)
    exe = fluid.Executor()
    exe.run(startup)
    last = exe.infer_from_dataset(program=prog, dataset=ds,
                                  fetch_list=[out], print_period=0)
    assert np.asarray(last[0]).shape == (8, 2)


def test_pipe_command_streams_files(tmp_path, static_mode):
    """The reference pipes every file through the user command; verify
    a real transformation (drop the first line) happens."""
    paths = _make_files(tmp_path, n_files=1, rows=9)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[4, 4])
        y = fluid.data(name="y", shape=[4], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(4)
    ds.set_filelist(paths)
    ds.set_pipe_command("tail -n +2")  # 9 rows -> 8 -> two 4-batches
    assert len(list(ds.iter_batches())) == 2
    ds.set_pipe_command("false")
    with pytest.raises(RuntimeError, match="pipe_command"):
        list(ds.iter_batches())


def test_queue_dataset_cannot_shuffle(static_mode):
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()


def test_malformed_slot_line_raises(tmp_path, static_mode):
    p = str(tmp_path / "bad.txt")
    with open(p, "w") as f:
        f.write("4 1.0 2.0 3.0 4.0 1\n")  # y slot count missing values
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[1, 4])
        y = fluid.data(name="y", shape=[1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(1)
    ds.set_filelist([p])
    # native parser says "malformed ... at line N", the Python
    # fallback names the slot; both carry the file path
    with pytest.raises(ValueError, match="declares|malformed"):
        list(ds.iter_batches())


def test_unknown_datafeed_class_raises(static_mode):
    with pytest.raises(ValueError, match="does not exist"):
        fluid.DatasetFactory().create_dataset("NoSuchDataset")


def test_layers_accuracy_records_into_program(tmp_path, static_mode):
    """The book-example pattern: acc = layers.accuracy(prob, label)
    INSIDE program_guard, fetched per batch (ref layers/metric_op.py:31
    is a graph op, not a host function)."""
    import paddle_tpu.nn.functional as F

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[4, 3])
        y = fluid.data(name="y", shape=[4], dtype="int64")
        acc = fluid.layers.accuracy(F.softmax(x, axis=-1), y)
    exe = fluid.Executor()
    exe.run(startup)
    logits = np.array([[9, 0, 0], [0, 9, 0], [0, 0, 9], [9, 0, 0]],
                      np.float32)
    labels = np.array([0, 1, 2, 1], np.int64)  # 3 of 4 hit
    (a,) = exe.run(prog, feed={"x": logits, "y": labels},
                   fetch_list=[acc])
    assert abs(float(np.asarray(a)) - 0.75) < 1e-6


def test_partial_batch_drop_warns(tmp_path, static_mode):
    paths = _make_files(tmp_path, n_files=1, rows=10)  # 10 % 4 = 2 drop
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[4, 4])
        y = fluid.data(name="y", shape=[4], dtype="int64")
        out = fluid.layers.fc(x, size=2)
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(4)
    ds.set_filelist(paths)
    exe = fluid.Executor()
    exe.run(startup)
    with pytest.warns(RuntimeWarning, match="partial batch"):
        exe.infer_from_dataset(program=prog, dataset=ds,
                               fetch_list=[out], print_period=0)


def test_fetch_info_length_mismatch_raises(tmp_path, static_mode):
    paths = _make_files(tmp_path, n_files=1, rows=8)
    prog, startup, x, y, loss = _build_program(batch=8)
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(8)
    ds.set_filelist(paths)
    exe = fluid.Executor()
    exe.run(startup)
    with pytest.raises(ValueError, match="fetch_info"):
        exe.train_from_dataset(program=prog, dataset=ds,
                               fetch_list=[loss],
                               fetch_info=["a", "b"])


def test_native_and_python_parsers_agree(tmp_path, static_mode):
    """runtime/cc pt_multislot_parse must produce byte-identical batches
    to the Python fallback parser."""
    from paddle_tpu.runtime import multislot_parse

    if multislot_parse(b"1 1\n", [1], [True]) is None:
        pytest.skip("native runtime unavailable")
    paths = _make_files(tmp_path, n_files=2, rows=17)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[1, 4])
        y = fluid.data(name="y", shape=[1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(1)
    ds.set_filelist(paths)
    native = [(b["x"].copy(), b["y"].copy()) for b in ds.iter_batches()]
    # force the Python path
    ds._parse_native = lambda text, path: None
    python = [(b["x"].copy(), b["y"].copy()) for b in ds.iter_batches()]
    assert len(native) == len(python) == 34
    for (nx, ny), (px, py) in zip(native, python):
        np.testing.assert_array_equal(nx, px)
        np.testing.assert_array_equal(ny, py)
        assert nx.dtype == px.dtype and ny.dtype == py.dtype


def test_short_line_never_frame_shifts(tmp_path, static_mode):
    """A line missing its value must ERROR in both parsers — never
    silently consume tokens from the next line (data corruption)."""
    p = str(tmp_path / "short.txt")
    with open(p, "w") as f:
        f.write("1\n5\n")  # line 0 declares 1 value but has none
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        y = fluid.data(name="y", shape=[1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([y])
    ds.set_batch_size(1)
    ds.set_filelist([p])
    with pytest.raises(ValueError):
        list(ds.iter_batches())
    ds._parse_native = lambda raw, path: None  # python fallback
    with pytest.raises(ValueError):
        list(ds.iter_batches())


def test_trailing_tokens_error_in_both_parsers(tmp_path, static_mode):
    p = str(tmp_path / "trail.txt")
    with open(p, "w") as f:
        f.write("1 2.0 1 7 9\n")  # leftover '9'
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[1, 1])
        y = fluid.data(name="y", shape=[1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(1)
    ds.set_filelist([p])
    with pytest.raises(ValueError):
        list(ds.iter_batches())
    ds._parse_native = lambda raw, path: None
    with pytest.raises(ValueError, match="trailing"):
        list(ds.iter_batches())


def test_blank_lines_skipped_and_line_numbers_raw(tmp_path, static_mode):
    """Blank/whitespace-only lines are skipped by both parsers, and the
    native error reports the RAW file line number."""
    from paddle_tpu.runtime import multislot_parse

    p = str(tmp_path / "blanks.txt")
    with open(p, "w") as f:
        f.write("1 7\n\n   \n1 8\n")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        y = fluid.data(name="y", shape=[2], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([y])
    ds.set_batch_size(2)
    (b,) = list(ds.set_filelist([p]) or ds.iter_batches())
    assert b["y"].tolist() == [7, 8]
    if multislot_parse(b"1 1\n", [1], [True]) is not None:
        with pytest.raises(ValueError, match="line 3"):
            multislot_parse(b"1 7\n\n   \n1 bad\n", [1], [False])


def test_dataloader_from_dataset(tmp_path, static_mode):
    """ref reader.py:437 DataLoader.from_dataset over a slot-file
    Dataset yields executor-ready feed dicts."""
    paths = _make_files(tmp_path, n_files=1, rows=16)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[8, 4])
        y = fluid.data(name="y", shape=[8], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset()
    ds.set_use_var([x, y])
    ds.set_batch_size(8)
    ds.set_filelist(paths)
    loader = fluid.io.DataLoader.from_dataset(ds)
    feeds = list(loader())
    assert len(feeds) == 2
    assert feeds[0]["x"].shape == (8, 4)
