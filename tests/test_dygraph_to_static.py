"""dygraph→static surface (ref: fluid/dygraph/dygraph_to_static/):
ProgramTranslator get_output/get_func/get_program/get_code, the
declarative decorator, tracing-based convert_to_static parity, and the
documented design-replacement stubs for the AST rewriters.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.dygraph_to_static import (
    DygraphToStaticAst, LoopTransformer, NodeVarType, ProgramTranslator,
    convert_to_static, data_layer_not_check, declarative)


def _net(x):
    return pt.tanh(x) * 2.0 + 1.0


class TestProgramTranslator:
    def test_singleton_and_get_output(self):
        t1 = ProgramTranslator()
        t2 = ProgramTranslator.get_instance()
        assert t1 is t2
        x = pt.to_tensor(np.linspace(-1, 1, 6).astype("float32"))
        out = t1.get_output(_net, x)
        ref = _net(x)
        assert np.allclose(np.asarray(out.numpy()),
                           np.asarray(ref.numpy()), atol=1e-6)

    def test_enable_false_runs_eagerly(self):
        t = ProgramTranslator()
        t.enable(False)
        try:
            x = pt.to_tensor(np.ones(3, "float32"))
            assert t.get_func(_net) is _net
            out = t.get_output(_net, x)
            assert np.allclose(np.asarray(out.numpy()),
                               np.tanh(1.0) * 2 + 1)
        finally:
            t.enable(True)

    def test_get_program_traces_ops(self):
        t = ProgramTranslator()
        x = np.ones((4, 3), "float32")
        main, startup, inputs, outputs = t.get_program(_net, x)
        types = [op.type for op in main.global_block.ops]
        assert "tanh" in types
        assert len(inputs) == 1 and len(outputs) == 1
        # cached on second call
        again = t.get_program(_net, x)
        assert again[0] is main

    def test_get_code_returns_source(self):
        src = ProgramTranslator().get_code(_net)
        assert "def _net" in src and "tanh" in src

    def test_save_inference_model(self, tmp_path):
        t = ProgramTranslator()
        x = np.ones((2, 5), "float32")
        t.get_program(_net, x)
        d = t.save_inference_model(str(tmp_path / "m"))
        from paddle_tpu.inference.predictor import Predictor

        pred = Predictor(d)
        (out,) = pred.run({"translator_x0": x})
        assert np.allclose(out, np.tanh(x) * 2 + 1, atol=1e-6)


def test_declarative_and_convert_to_static():
    @declarative
    def f(x):
        return x * x + 3.0

    x = pt.to_tensor(np.arange(4, dtype="float32"))
    assert np.allclose(np.asarray(f(x).numpy()), [3, 4, 7, 12])

    g = convert_to_static(_net)
    out = g(x)
    assert np.allclose(np.asarray(out.numpy()),
                       np.tanh(np.arange(4, dtype="float32")) * 2 + 1,
                       atol=1e-6)


def test_ast_stubs_and_constants():
    with pytest.raises(NotImplementedError):
        DygraphToStaticAst().get_static_ast(None)
    with pytest.raises(NotImplementedError):
        LoopTransformer()
    assert NodeVarType.TENSOR == 200 and NodeVarType.BOOLEAN == 101


def test_data_layer_not_check():
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.static.program_guard(main, startup):
            v = data_layer_not_check("free", [None, 7])
            assert tuple(v.shape) == (1, 7)  # None -> placeholder
    finally:
        pt.disable_static()


def test_deep_spellings_resolve():
    from paddle_tpu.fluid.dygraph.dygraph_to_static.ast_transformer \
        import convert_to_static as c2s
    from paddle_tpu.fluid.dygraph.dygraph_to_static.static_analysis \
        import NodeVarType as NVT
    from paddle_tpu.fluid.dygraph.jit import declarative as dec

    assert c2s is convert_to_static and NVT is NodeVarType
    assert fluid.dygraph.ProgramTranslator is ProgramTranslator
    assert callable(dec)


def test_declarative_respects_enable_flag_and_kwargs():
    calls = {"eager": 0}

    def base(x, scale=1.0):
        calls["eager"] += 1
        return x * scale

    f = declarative(base)
    x = pt.to_tensor(np.ones(2, "float32"))
    t = ProgramTranslator()
    t.enable(False)
    try:
        f(x)
        assert calls["eager"] == 1  # eager when disabled
    finally:
        t.enable(True)
    f(x, scale=2.0)
    assert calls["eager"] >= 2  # kwargs route eagerly
    # get_output with kwargs also runs eagerly, not TypeError
    out = t.get_output(base, x, scale=3.0)
    assert np.allclose(np.asarray(out.numpy()), 3.0)


def test_get_code_on_declarative_and_cache_isolation():
    @declarative
    def decorated(x):
        return x + 1

    src = ProgramTranslator().get_code(decorated)
    assert "def decorated" in src

    t = ProgramTranslator()

    def make(c):
        def forward(x):  # same __name__ on purpose
            return x * c

        return forward

    a, b = make(2.0), make(5.0)
    x = np.ones((2, 2), "float32")
    main_a = t.get_program(a, x)[0]
    main_b = t.get_program(b, x)[0]
    assert main_a is not main_b  # no cross-function cache collision


def test_grayscale_load_and_transform(tmp_path):
    from PIL import Image

    import paddle_tpu.dataset as D

    p = str(tmp_path / "g.png")
    Image.fromarray(np.arange(1600, dtype=np.uint8).reshape(40, 40)
                    % 255).save(p)
    out = D.image.load_and_transform(p, 32, 24, is_train=False,
                                     is_color=False)
    assert out.shape == (24, 24)
