"""Reader-decorator + compat tests (ref: python/paddle/reader/decorator.py,
batch.py, compat.py, tensor-API 1.x aliases)."""
import numpy as np
import paddle_tpu as pt


def test_reader_decorators_and_compat():
    r = pt.batch(lambda: iter(range(7)), 3)
    assert list(r()) == [[0, 1, 2], [3, 4, 5], [6]]
    r2 = pt.batch(lambda: iter(range(7)), 3, drop_last=True)
    assert list(r2()) == [[0, 1, 2], [3, 4, 5]]
    from paddle_tpu.reader import (map_readers, shuffle, chain, compose, buffered,
                                   firstn, cache, xmap_readers, multiprocess_reader,
                                   ComposeNotAligned)
    assert list(map_readers(lambda a, b: a + b, lambda: iter([1, 2]), lambda: iter([10, 20]))()) == [11, 22]
    assert sorted(shuffle(lambda: iter(range(5)), 2)()) == [0, 1, 2, 3, 4]
    assert list(chain(lambda: iter([1]), lambda: iter([2]))()) == [1, 2]
    assert list(compose(lambda: iter([1, 2]), lambda: iter([(3, 4), (5, 6)]))()) == [(1, 3, 4), (2, 5, 6)]
    try:
        list(compose(lambda: iter([1]), lambda: iter([1, 2]))())
        raise AssertionError("compose should raise")
    except ComposeNotAligned:
        pass
    assert list(buffered(lambda: iter(range(4)), 2)()) == [0, 1, 2, 3]
    assert list(firstn(lambda: iter(range(9)), 3)()) == [0, 1, 2]
    c = cache(lambda: iter(range(3)))
    assert list(c()) == [0, 1, 2] and list(c()) == [0, 1, 2]
    assert list(xmap_readers(lambda v: v * 2, lambda: iter(range(5)), 2, 4, order=True)()) == [0, 2, 4, 6, 8]
    assert sorted(multiprocess_reader([lambda: iter([1, 2]), lambda: iter([3])])()) == [1, 2, 3]
    from paddle_tpu import compat
    assert compat.to_text(b"hi") == "hi" and compat.to_bytes("hi") == b"hi"
    assert compat.round(2.5) == 3.0 and compat.round(-2.5) == -3.0
    assert compat.floor_division(7, 2) == 3
    assert int(np.asarray(pt.div(pt.to_tensor(np.array([4.0])), pt.to_tensor(np.array([2.0]))).numpy())) == 2
    assert bool(np.asarray(pt.elementwise_equal(pt.to_tensor(np.array([1])), pt.to_tensor(np.array([1]))).numpy()))
    assert list(pt.create_tensor("float32").shape) == [1]
    print("READER/COMPAT OK")


def test_ploter_and_dump_config(tmp_path):
    """paddle.utils Ploter/dump_config (ref: utils/plot.py)."""
    from paddle_tpu.utils import Ploter, dump_config

    p = Ploter("train", "test")
    for i in range(5):
        p.append("train", i, 1.0 / (i + 1))
    p.append("test", 0, 0.5)
    path = str(tmp_path / "curves.csv")
    p.savefig(path)
    rows = open(path).read().splitlines()
    assert rows[0] == "title,step,value" and len(rows) == 7
    p.reset()
    assert not p.__plot_data__["train"].value

    class Cfg:
        def __init__(self):
            self.lr = 0.1
            self.layers = [1, 2]

    assert '"lr": 0.1' in dump_config(Cfg())
