"""fluid.contrib.decoder beam-search stack (ref: fluid/contrib/decoder/
beam_search_decoder.py): StateCell updater protocol, TrainingDecoder
teacher-forced training, BeamSearchDecoder decode parity on a learnable
chain task, and the reference error paths.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.fluid.contrib.decoder.beam_search_decoder import (
    BeamSearchDecoder, InitState, StateCell, TrainingDecoder)

V, D, H, T, B = 20, 16, 32, 5, 8


class _Setup:
    def __init__(self):
        pt.seed(0)
        self.emb = nn.Embedding(V, D)
        self.wx = nn.Linear(D, H)
        self.uh = nn.Linear(H, H, bias_attr=False)
        self.proj = nn.Linear(H, V)
        self.enc = nn.Linear(D, H)
        self.rng = np.random.RandomState(0)
        self.opt = pt.optimizer.Adam(
            learning_rate=5e-3,
            parameters=(list(self.emb.parameters())
                        + list(self.wx.parameters())
                        + list(self.uh.parameters())
                        + list(self.proj.parameters())
                        + list(self.enc.parameters())))

    @staticmethod
    def rot(x):
        return ((x - 3 + 1) % (V - 3)) + 3

    def batch(self):
        # chain task: trg[0]=src[0], trg[t]=rot(trg[t-1]) — every target
        # token is determined by the previous one, so the RNN cell can
        # learn it exactly
        src = self.rng.randint(3, V, (B, 1)).astype("int64")
        trg = np.zeros((B, T), "int64")
        trg[:, 0] = src[:, 0]
        for t in range(1, T):
            trg[:, t] = self.rot(trg[:, t - 1])
        return src, trg

    def make_cell(self, src_ids):
        h0 = pt.tanh(self.enc(pt.mean(self.emb(pt.to_tensor(src_ids)),
                                      axis=1)))
        cell = StateCell(inputs={"x": None},
                         states={"h": InitState(init=h0)}, out_state="h")

        @cell.state_updater
        def updater(c):
            x = c.get_input("x")
            h = c.get_state("h")
            c.set_state("h", pt.tanh(self.wx(x) + self.uh(h)))

        return cell

    def train(self, steps=150):
        losses = []
        for _ in range(steps):
            src, trg = self.batch()
            cell = self.make_cell(src)
            dec = TrainingDecoder(cell)
            trg_in = np.concatenate(
                [np.zeros((B, 1), "int64"), trg[:, :-1]], 1)
            trg_emb = self.emb(pt.to_tensor(trg_in))

            @dec.block
            def _(d):
                w = d.step_input(trg_emb)
                d.state_cell.compute_state(inputs={"x": w})
                score = self.proj(d.state_cell.get_state("h"))
                d.state_cell.update_states()
                d.output(score)

            logits = dec()
            loss = pt.nn.functional.cross_entropy(
                pt.reshape(logits, [B * T, V]),
                pt.to_tensor(trg.reshape(-1)), reduction="mean")
            loss.backward()
            self.opt.step()
            self.opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses


def test_train_then_beam_decode_exact():
    s = _Setup()
    losses = s.train()
    assert losses[-1] < 0.3, losses[-1]

    src, trg = s.batch()
    cell = s.make_cell(src)
    bsd = BeamSearchDecoder(
        cell, init_ids=pt.to_tensor(np.zeros((B, 1), "int64")),
        init_scores=pt.to_tensor(np.zeros((B, 1), "float32")),
        target_dict_dim=V, word_dim=D, beam_size=3, max_len=T, end_id=1)
    # share the trained embedding/projection (the reference shares them
    # by param-name save/load across the train and infer programs)
    bsd._emb, bsd._fc = s.emb, s.proj
    bsd.decode()
    ids, scores = bsd()
    assert tuple(np.asarray(ids.numpy()).shape) == (B, 3, T)
    best = np.asarray(ids.numpy())[:, 0, :]
    assert (best == trg).mean() > 0.9
    # beams are sorted by accumulated log-prob
    sc = np.asarray(scores.numpy())
    assert np.all(sc[:, 0] >= sc[:, 1] - 1e-5)


def test_state_cell_protocol_errors():
    cell = StateCell(inputs={"x": None},
                     states={"h": InitState(init=pt.zeros([2, 4]))},
                     out_state="h")
    with pytest.raises(ValueError):
        cell.compute_state(inputs={"x": pt.zeros([2, 4])})  # no updater

    @cell.state_updater
    def upd(c):
        c.set_state("h", c.get_state("h"))

    cell._reset()
    with pytest.raises(ValueError):
        cell.compute_state(inputs={"bogus": pt.zeros([2, 4])})
    with pytest.raises(ValueError):
        cell.get_state("nope")
    cell.compute_state(inputs={"x": pt.zeros([2, 4])})
    with pytest.raises(ValueError):
        cell.get_input("unfed")


def test_init_state_shapes():
    boot = pt.zeros([3, 7])
    st = InitState(shape=[5], value=1.5, init_boot=boot)
    assert tuple(st.value.shape) == (3, 5)
    assert float(np.asarray(st.value.numpy()).max()) == 1.5
    with pytest.raises(ValueError):
        InitState(shape=[5])  # needs init or init_boot


def test_training_decoder_block_rejects_with():
    dec = TrainingDecoder(StateCell(
        inputs={"x": None},
        states={"h": InitState(init=pt.zeros([2, 4]))}, out_state="h"))
    with pytest.raises(TypeError):
        dec.block()  # with-statement spelling: callable required
