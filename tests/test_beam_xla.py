"""Single-executable beam search (inference/decoder.py beam_search_xla +
MultiHeadAttention.StaticKVCache): the lax.while_loop decode must produce
the same tokens/scores as the eager per-step beam_search path.
Capability ref: fluid/layers/rnn.py:2699 beam_search (+ the fused decode
the reference's inference engine aspires to)."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.optim as optim
from paddle_tpu.models.nlp.transformer import WMTTransformer, wmt_loss
from paddle_tpu.nn.layers.transformer import MultiHeadAttention


def _tiny_trained_model(seed=0, steps=8):
    pt.seed(seed)
    rng = np.random.RandomState(seed)
    src = rng.randint(2, 30, (8, 6)).astype("int64")
    tgt_full = np.concatenate(
        [np.zeros((8, 1), "int64"), (src + 1) % 40], axis=1)
    model = WMTTransformer(30, 40, d_model=16, nhead=2, num_layers=2,
                           dim_feedforward=32, dropout=0.0, max_len=16)
    opt = optim.Adam(3e-3, parameters=model.parameters())
    step = pt.TrainStep(
        model, opt,
        lambda m, s, ti, tl: wmt_loss(m, s, ti, tl, pad_id=None))
    for _ in range(steps):
        step(src, tgt_full[:, :-1], tgt_full[:, 1:])
    model.eval()
    return model, src


def test_static_kv_cache_matches_growing_cache():
    """One incremental step via StaticKVCache == the concat Cache."""
    pt.seed(0)
    mha = MultiHeadAttention(8, 2)
    mha.eval()
    x1 = pt.to_tensor(np.random.RandomState(0).randn(2, 1, 8)
                      .astype("float32"))
    x2 = pt.to_tensor(np.random.RandomState(1).randn(2, 1, 8)
                      .astype("float32"))
    grow = mha.gen_cache(pt.to_tensor(np.zeros((2, 1, 8), "float32")))
    stat = mha.gen_static_kv_cache(2, 4, "float32")
    o1g, grow = mha(x1, x1, x1, None, grow)
    o1s, stat = mha(x1, x1, x1, None, stat)
    np.testing.assert_allclose(np.asarray(o1g.numpy()),
                               np.asarray(o1s.numpy()), rtol=1e-5)
    o2g, grow = mha(x2, x2, x2, None, grow)
    o2s, stat = mha(x2, x2, x2, None, stat)
    np.testing.assert_allclose(np.asarray(o2g.numpy()),
                               np.asarray(o2s.numpy()), rtol=1e-5)
    assert int(stat.idx) == 2


def test_xla_beam_matches_eager_beam():
    model, src = _tiny_trained_model()
    toks_e, scores_e = model.beam_search_decode(
        pt.to_tensor(src[:4]), beam_size=3, max_len=10)
    toks_x, scores_x = model.beam_search_decode_xla(
        pt.to_tensor(src[:4]), beam_size=3, max_len=10)
    np.testing.assert_array_equal(np.asarray(toks_e.numpy()),
                                  np.asarray(toks_x.numpy()))
    np.testing.assert_allclose(np.asarray(scores_e.numpy()),
                               np.asarray(scores_x.numpy()), rtol=1e-4,
                               atol=1e-5)


def test_xla_beam_return_all_sorted():
    model, src = _tiny_trained_model()
    toks, scores = model.beam_search_decode_xla(
        pt.to_tensor(src[:2]), beam_size=4, max_len=8, return_all=True)
    s = np.asarray(scores.numpy())
    assert s.shape == (2, 4)
    assert (np.diff(s, axis=1) <= 1e-6).all()  # best-first
    assert np.asarray(toks.numpy()).shape == (2, 4, 8)


def test_xla_beam_is_one_executable():
    """The decode must not sync per step: trace count == 1 and the jitted
    fn is cached across calls with the same signature."""
    model, src = _tiny_trained_model()
    model.beam_search_decode_xla(pt.to_tensor(src[:2]), beam_size=2,
                                 max_len=8)
    assert len(model._xla_decode_cache) == 1
    fn1 = next(iter(model._xla_decode_cache.values()))
    model.beam_search_decode_xla(pt.to_tensor(src[2:4]), beam_size=2,
                                 max_len=8)
    assert next(iter(model._xla_decode_cache.values())) is fn1
    # a different signature gets its own executable, the first survives
    model.beam_search_decode_xla(pt.to_tensor(src[:2]), beam_size=3,
                                 max_len=8)
    assert len(model._xla_decode_cache) == 2
