"""Comm-efficient data parallelism: bucketed, accumulated, quantized
gradient all-reduce (``dist.gradcomm``, ISSUE 9).

The reference's DataParallel coalesces per-parameter NCCL all-reduces
into ``comm_buffer_size``-MB flat buffers and its DGC/fp16 strategies
compress the payload; EQuARX (arXiv:2506.17615) quantizes the ring
all-reduce itself with error feedback. Here the exchange is explicit
jax code over per-device local gradient partials (see
dist/gradcomm.py), spanning both execution paths:

- static: ``CompiledProgram.with_data_parallel(comm_options=...)``
- eager: ``DistributedTrainStep(..., comm_options=...)`` /
  ``DataParallel(layer, comm_buffer_size=...)``

Acceptance (all CPU-runnable on the 8-fake-device mesh): bucketing
strictly reduces all-reduce op counts vs the per-parameter baseline,
int8 cuts gradient wire bytes ~4x, fp32 bucketed matches the implicit
path BITWISE on the MLP (conv models: 1e-5 — XLA orders conv partial
sums differently between the vmapped and sharded programs), int8 stays
within 5% loss-trajectory tolerance over 20 LeNet steps, and
error-feedback residuals survive checkpoint round-trips.
"""
import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import distributed as dist
from paddle_tpu import optim
from paddle_tpu.dist import gradcomm as gc
from paddle_tpu.dist.gradcomm import CommOptions

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _require8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")


@pytest.fixture
def static_mode():
    # fresh scope per test: @comm@* exchange state (EF residuals, the
    # stochastic-rounding counter) lives in the scope and must not leak
    # between tests
    pt.enable_static()
    with fluid.scope_guard(fluid.Scope()):
        yield
    pt.disable_static()


@pytest.fixture(autouse=True)
def _mesh_reset():
    yield
    dist.set_mesh(None)


def _entry_profile(exe, entry=None):
    from paddle_tpu.obs import spmd

    pg = _load_tool("perf_gate")
    if entry is None:
        entry = next(iter(exe._cache.values()))
    hlo = pg.entry_hlo(entry)
    assert hlo is not None
    return spmd.collective_profile(
        hlo, mesh=(entry.mesh_axes, entry.mesh_device_ids)), hlo


# -- bucket planning (pure host logic) ---------------------------------------


class TestBucketPlan:
    def test_size_bounded_buckets(self):
        # 3 x 256B f32 grads under a 512B cap -> [2-member, 1-member]
        entries = [(f"g{i}", (64,), np.float32) for i in range(3)]
        plan = gc.plan_buckets(
            entries, CommOptions(bucket_bytes=512, last_bucket_bytes=512),
            ndev=8)
        assert [b.names for b in plan.buckets] == [("g0", "g1"), ("g2",)]
        assert plan.buckets[0].offsets == (0, 64)
        assert plan.buckets[0].numel == 128

    def test_first_bucket_uses_last_cap(self):
        # the reference's last_comm_buffer_size: a small FIRST bucket
        # gets the earliest-ready grads onto the wire sooner
        entries = [(f"g{i}", (64,), np.float32) for i in range(4)]
        plan = gc.plan_buckets(
            entries, CommOptions(bucket_bytes=768, last_bucket_bytes=256),
            ndev=8)
        assert plan.buckets[0].names == ("g0",)
        assert plan.buckets[1].names == ("g1", "g2", "g3")

    def test_param_larger_than_cap_gets_own_bucket(self):
        entries = [("small", (8,), np.float32),
                   ("huge", (1024,), np.float32),
                   ("tail", (8,), np.float32)]
        plan = gc.plan_buckets(
            entries, CommOptions(bucket_bytes=256, last_bucket_bytes=64),
            ndev=8)
        assert [b.names for b in plan.buckets] == \
            [("small",), ("huge",), ("tail",)]
        # never split: the huge grad is one contiguous member
        assert plan.buckets[1].numel == 1024

    def test_exactly_full_bucket_closes(self):
        # two grads summing exactly to the cap share a bucket; the next
        # opens a fresh one (boundary: == cap, not > cap)
        entries = [("a", (32,), np.float32), ("b", (32,), np.float32),
                   ("c", (1,), np.float32)]
        plan = gc.plan_buckets(
            entries, CommOptions(bucket_bytes=256, last_bucket_bytes=256),
            ndev=8)
        assert [b.names for b in plan.buckets] == [("a", "b"), ("c",)]
        # padding: 1 element padded up to the 8-device multiple
        assert plan.buckets[1].numel == 1
        assert plan.buckets[1].padded == 8

    def test_flatten_unflatten_roundtrip(self):
        entries = [("a", (2, 3), np.float32), ("b", (5,), np.float32)]
        plan = gc.plan_buckets(
            entries, CommOptions(bucket_bytes=1 << 20), ndev=4)
        rng = np.random.RandomState(0)
        locals_ = {"a": jnp.asarray(rng.randn(4, 2, 3), jnp.float32),
                   "b": jnp.asarray(rng.randn(4, 5), jnp.float32)}
        flats = plan.flatten_local(locals_)
        assert flats[0].shape == (4, plan.buckets[0].padded)
        out = plan.unflatten([f.sum(0) for f in flats])
        np.testing.assert_allclose(
            np.asarray(out["a"]), np.asarray(locals_["a"].sum(0)),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(out["b"]), np.asarray(locals_["b"].sum(0)),
            rtol=1e-6)

    def test_option_validation(self):
        with pytest.raises(ValueError):
            CommOptions(bucket_bytes=0)
        with pytest.raises(ValueError):
            CommOptions(accumulate_steps=0)
        with pytest.raises(ValueError):
            CommOptions(quantize="fp8")
        with pytest.raises(ValueError):
            CommOptions(gradient_scale="median")

    def test_hash_uniform_deterministic_and_centered(self):
        a = gc.hash_uniform((1024,), jnp.uint32(7))
        b = gc.hash_uniform((1024,), jnp.uint32(7))
        c = gc.hash_uniform((1024,), jnp.uint32(8))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))
        x = np.asarray(a)
        assert x.min() >= -0.5 and x.max() < 0.5
        assert abs(x.mean()) < 0.05  # unbiased rounding noise


# -- static path -------------------------------------------------------------


def _mlp_program(lr=0.1, batch=16):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return prog, startup, loss


def _train_static(comm, steps=6, batch=16, seed=0):
    pt.seed(0)
    prog, startup, loss = _mlp_program(batch=batch)
    c = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=loss.name, comm_options=comm)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        xb = rng.randn(batch, 8).astype(np.float32)
        yb = rng.randn(batch, 1).astype(np.float32)
        (lv,) = exe.run(c, feed={"x": xb, "y": yb}, fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    return losses, exe, prog


class TestStaticComm:
    def test_fp32_bucketed_bitwise_vs_implicit(self, static_mode):
        """The acceptance pin: the explicit bucketed exchange performs
        the same per-element partial-sum additions GSPMD's implicit
        all-reduce does, so the MLP loss trajectory matches BITWISE."""
        _require8()
        base, _, _ = _train_static(None)
        buck, exe, _ = _train_static(CommOptions())
        assert base == buck, (base, buck)
        prof, _ = _entry_profile(exe)
        # 4 params + 1 loss mean implicit -> 1 bucket + 1 loss explicit
        assert prof["counts"]["all-reduce"] == 2

    def test_bucketed_strictly_fewer_all_reduces(self, static_mode):
        _require8()
        _, exe0, _ = _train_static(None, steps=1)
        _, exe1, _ = _train_static(CommOptions(), steps=1)
        p0, _ = _entry_profile(exe0)
        p1, _ = _entry_profile(exe1)
        assert p1["counts"]["all-reduce"] < p0["counts"]["all-reduce"], \
            (p1["counts"], p0["counts"])

    def test_int8_within_tolerance_and_ef_state(self, static_mode):
        _require8()
        base, _, _ = _train_static(None)
        q, exe, _ = _train_static(CommOptions(quantize="int8"))
        np.testing.assert_allclose(q, base, rtol=0.05, atol=0.02)
        # EF residual + rounding counter live as @comm@* persistables
        scope = fluid.global_scope()
        resid = scope.find_var(gc.EF_PREFIX + "0")
        assert resid is not None and resid.shape[0] == 8
        assert int(np.asarray(scope.find_var(gc.STEP_VAR))) == 6
        prof, _ = _entry_profile(exe)
        assert prof["quant_wire_bytes"] > 0

    def test_cache_key_carries_comm_axis(self, static_mode):
        _require8()
        pt.seed(0)
        prog, startup, loss = _mlp_program()
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(16, 8).astype(np.float32),
                "y": rng.randn(16, 1).astype(np.float32)}
        for comm in (None, CommOptions()):
            c = fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name, comm_options=comm)
            exe.run(c, feed=feed, fetch_list=[loss])
        comms = {k.comm for k in exe._cache
                 if k.program_uid == prog._uid}
        assert comms == {None, CommOptions().cache_axis()}

    def test_accumulate_matches_double_batch(self, static_mode):
        """accumulate_steps=2 over batch-B microbatches == one exchange
        of the mean gradient over 2B samples: the trajectory must match
        implicit DP fed the concatenated 2B batches (the reference's
        gradient-merge semantics)."""
        _require8()
        rng = np.random.RandomState(3)
        xs = rng.randn(4, 16, 8).astype(np.float32)
        ys = rng.randn(4, 16, 1).astype(np.float32)

        # baseline: 2 implicit-DP steps on the concatenated batches
        pt.seed(0)
        prog, startup, loss = _mlp_program(batch=32)
        c = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor()
        exe.run(startup)
        ref = []
        for w in range(2):
            xb = np.concatenate(xs[2 * w:2 * w + 2])
            yb = np.concatenate(ys[2 * w:2 * w + 2])
            (lv,) = exe.run(c, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
            ref.append(float(np.asarray(lv)))

        # fused window K=4, exchange once per N=2 microbatches
        pt.seed(0)
        prog2, startup2, loss2 = _mlp_program(batch=16)
        c2 = fluid.CompiledProgram(prog2).with_data_parallel(
            loss_name=loss2.name,
            comm_options=CommOptions(accumulate_steps=2))
        exe2 = fluid.Executor()
        exe2.run(startup2)
        (traj,) = exe2.run_steps(c2, feeds={"x": xs, "y": ys},
                                 fetch_list=[loss2], steps=4)
        traj = np.asarray(traj).ravel()
        assert traj.shape == (4,)
        # per-microbatch losses of window w average to the 2B-batch loss
        np.testing.assert_allclose(
            [traj[0:2].mean(), traj[2:4].mean()], ref, rtol=1e-5)
        # exactly one compiled dispatch for the whole K=4 window
        assert exe2.dispatches == 1

    def test_accumulate_requires_fused_path(self, static_mode):
        _require8()
        pt.seed(0)
        prog, startup, loss = _mlp_program()
        c = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name,
            comm_options=CommOptions(accumulate_steps=2))
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"x": np.zeros((16, 8), np.float32),
                "y": np.zeros((16, 1), np.float32)}
        with pytest.raises(ValueError, match="fused path"):
            exe.run(c, feed=feed, fetch_list=[loss])

    def test_accumulate_must_divide_window(self, static_mode):
        _require8()
        pt.seed(0)
        prog, startup, loss = _mlp_program()
        c = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name,
            comm_options=CommOptions(accumulate_steps=2))
        exe = fluid.Executor()
        exe.run(startup)
        feeds = [{"x": np.zeros((16, 8), np.float32),
                  "y": np.zeros((16, 1), np.float32)}] * 3
        with pytest.raises(ValueError, match="divide"):
            exe.run_steps(c, feeds=feeds, fetch_list=[loss])

    def test_ef_residuals_survive_checkpoint_roundtrip(self, static_mode):
        _require8()
        q, exe, prog = _train_static(CommOptions(quantize="int8"), steps=3)
        scope = fluid.global_scope()
        resid = np.asarray(scope.find_var(gc.EF_PREFIX + "0"))
        assert np.abs(resid).max() > 0  # quantization left real error
        import tempfile

        from paddle_tpu.framework import io as fio

        with tempfile.TemporaryDirectory() as d:
            fio.save_persistables(exe, d, main_program=prog)
            scope.set(gc.EF_PREFIX + "0", jnp.zeros_like(resid))
            scope.set(gc.STEP_VAR, jnp.int32(0))
            fio.load_persistables(exe, d, main_program=prog)
            np.testing.assert_array_equal(
                np.asarray(scope.find_var(gc.EF_PREFIX + "0")), resid)
            assert int(np.asarray(scope.find_var(gc.STEP_VAR))) == 3


# -- the LeNet acceptance gate (ISSUE 9) -------------------------------------


def _lenet_train(comm, steps=20, B=8):
    pt.seed(0)
    from paddle_tpu.models.vision import LeNet

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = pt.static.data("x", [B, 1, 28, 28], "float32")
        y = pt.static.data("y", [B], "int64")
        loss = F.cross_entropy(LeNet()(x), y)
        optim.Momentum(0.02, 0.9).minimize(loss)
    c = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, comm_options=comm)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        feed = {"x": rng.randn(B, 1, 28, 28).astype(np.float32),
                "y": rng.randint(0, 10, (B,)).astype(np.int64)}
        (lv,) = exe.run(c, feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    return losses, exe


class TestLeNetAcceptance:
    def test_bucketed_and_int8_acceptance(self, static_mode):
        """The ISSUE 9 acceptance bundle on the 8-fake-device
        with_data_parallel LeNet: strictly fewer all-reduce ops
        bucketed, ~4x lower gradient wire bytes int8, and both loss
        trajectories within tolerance over 20 steps (fp32 at 1e-5 —
        conv partial-sum order differs between the vmapped and sharded
        programs; the MLP pin above is bitwise — int8 at 5%)."""
        _require8()
        base, exe0 = _lenet_train(None)
        buck, exe1 = _lenet_train(CommOptions())
        quant, exe2 = _lenet_train(CommOptions(quantize="int8"))

        p0, _ = _entry_profile(exe0)
        p1, _ = _entry_profile(exe1)
        p2, _ = _entry_profile(exe2)
        # 10 LeNet params + loss mean -> 11+ implicit all-reduces;
        # bucketed: 1 bucket + loss. STRICTLY fewer, per CollectiveProfile
        assert p1["counts"]["all-reduce"] < p0["counts"]["all-reduce"]
        assert p1["n_ops"] < p0["n_ops"]
        # int8: ~4x lower gradient-exchange wire bytes (the s8 payload
        # rides all-to-all + all-gather; scales and the f32 loss
        # all-reduce are the small remainder)
        ratio = p0["wire_bytes"] / p2["wire_bytes"]
        assert 3.3 < ratio < 4.5, (p0["wire_bytes"], p2["wire_bytes"])
        assert p2["quant_wire_bytes"] > 0.9 * p2["wire_bytes"]

        np.testing.assert_allclose(buck, base, rtol=1e-5)
        np.testing.assert_allclose(quant, base, rtol=0.05, atol=0.02)

    def test_multi_bucket_overlap_structure(self, static_mode):
        """Reverse-topological bucketing, proven structurally: with
        caps forcing several buckets, every bucket's all-reduce except
        the tail is scheduled BEFORE later compute (perf_gate
        ``interleaved``) — the placement an async backend overlaps."""
        _require8()
        pg = _load_tool("perf_gate")
        _, exe = _lenet_train(
            CommOptions(bucket_bytes=64 << 10, last_bucket_bytes=16 << 10),
            steps=1)
        prof, hlo = _entry_profile(exe)
        assert prof["counts"]["all-reduce"] >= 4  # >=3 buckets + loss
        ov = pg.overlap_stats(hlo)
        assert ov["interleaved"] >= 2, ov
        # and the gate API agrees
        entry = next(iter(exe._cache.values()))
        assert pg.check_entry(entry, min_interleaved=2) == []


# -- eager path --------------------------------------------------------------


class TestEagerComm:
    def _data(self):
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype("float32")
        Y = (X @ rng.randn(8, 1)).astype("float32")
        return X, Y

    def _build(self):
        # unique_name.guard(): identical param names across builds, so
        # optimizer.state_dict() maps onto a freshly built model (the
        # reference's resume idiom — Adam moments + EF residuals are
        # keyed by parameter name)
        pt.seed(5)
        with pt.utils.unique_name.guard():
            m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 1))
            o = optim.Adam(0.05, parameters=m.parameters())
        return m, o

    @staticmethod
    def _loss(m, x, y):
        return F.mse_loss(m(x), y)

    def test_fp32_matches_implicit(self):
        _require8()
        X, Y = self._data()
        mesh = dist.init_mesh({"data": 8})
        m0, o0 = self._build()
        s0 = dist.DistributedTrainStep(m0, o0, self._loss, mesh=mesh)
        base = [float(s0(X, Y)) for _ in range(5)]
        m1, o1 = self._build()
        s1 = dist.DistributedTrainStep(m1, o1, self._loss, mesh=mesh,
                                       comm_options=CommOptions())
        got = [float(s1(X, Y)) for _ in range(5)]
        np.testing.assert_allclose(got, base, rtol=1e-4)
        prof = s1.collective_profile()
        assert prof is not None and prof["counts"]["all-reduce"] <= 2

    def test_dataparallel_wrapper_knobs_are_live(self):
        """The reference's comm_buffer_size on DataParallel now
        configures real bucketing (MIGRATING note)."""
        _require8()
        X, Y = self._data()
        mesh = dist.init_mesh({"data": 8})
        m0, o0 = self._build()
        s0 = dist.DistributedTrainStep(m0, o0, self._loss, mesh=mesh)
        base = [float(s0(X, Y)) for _ in range(3)]
        m1, o1 = self._build()
        w = dist.DataParallel(m1, comm_buffer_size=1)
        assert w.comm_options is not None
        assert w.comm_options.bucket_bytes == 1 << 20
        s1 = dist.DistributedTrainStep(w, o1, self._loss, mesh=mesh)
        got = [float(s1(X, Y)) for _ in range(3)]
        np.testing.assert_allclose(got, base, rtol=1e-4)

    def test_int8_checkpoint_roundtrip_continuity(self):
        """EF residuals ride optimizer.state_dict(): an interrupted
        int8 run restored from the checkpoint must continue EXACTLY as
        the uninterrupted one (the residual carries the rounding error
        of every past step)."""
        _require8()
        X, Y = self._data()
        mesh = dist.init_mesh({"data": 8})
        opts = CommOptions(quantize="int8")

        m0, o0 = self._build()
        s0 = dist.DistributedTrainStep(m0, o0, self._loss, mesh=mesh,
                                       comm_options=opts)
        unbroken = [float(s0(X, Y)) for _ in range(5)]

        m1, o1 = self._build()
        s1 = dist.DistributedTrainStep(m1, o1, self._loss, mesh=mesh,
                                       comm_options=opts)
        first = [float(s1(X, Y)) for _ in range(3)]
        mstate = {k: np.asarray(v) for k, v in m1.state_dict().items()}
        ostate = o1.state_dict()
        assert any(k.startswith(gc.EF_PREFIX) for k in ostate)
        assert int(ostate[gc.STEP_VAR + ".count"]) == 3

        m2, o2 = self._build()
        m2.set_state_dict(mstate)
        o2.set_state_dict(ostate)
        s2 = dist.DistributedTrainStep(m2, o2, self._loss, mesh=mesh,
                                       comm_options=opts)
        resumed = first + [float(s2(X, Y)) for _ in range(2)]
        np.testing.assert_allclose(resumed, unbroken, rtol=1e-5)

    def test_run_fused_accumulate(self):
        """run_fused with accumulate_steps=2: the exchange fires once
        per 2 microbatches inside the scan; the trajectory matches the
        N=1 comm step fed the concatenated 2B batches."""
        _require8()
        X, Y = self._data()
        rng = np.random.RandomState(7)
        Xs = np.stack([X, rng.randn(32, 8).astype("float32"),
                       X + 0.1, X - 0.1])
        Ys = np.stack([Y, (Xs[1] @ np.ones((8, 1), "float32")),
                       Y + 0.1, Y - 0.1])
        mesh = dist.init_mesh({"data": 8})

        m0, o0 = self._build()
        s0 = dist.DistributedTrainStep(m0, o0, self._loss, mesh=mesh,
                                       comm_options=CommOptions())
        ref = []
        for w in range(2):
            xb = np.concatenate(Xs[2 * w:2 * w + 2])
            yb = np.concatenate(Ys[2 * w:2 * w + 2])
            ref.append(float(s0(xb, yb)))

        m1, o1 = self._build()
        s1 = dist.DistributedTrainStep(
            m1, o1, self._loss, mesh=mesh,
            comm_options=CommOptions(accumulate_steps=2))
        losses = np.asarray(s1.run_fused([Xs, Ys], steps=4)._data).ravel()
        assert losses.shape == (4,)
        np.testing.assert_allclose(
            [losses[0:2].mean(), losses[2:4].mean()], ref, rtol=1e-4)
        # the params ended at the same point: one more identical update
        # on each side (a 2-microbatch window vs the concatenated batch)
        # must produce the same loss
        more = np.asarray(
            s1.run_fused([np.stack([X, X]), np.stack([Y, Y])],
                         steps=2)._data).ravel()
        np.testing.assert_allclose(
            more.mean(),
            float(s0(np.concatenate([X, X]), np.concatenate([Y, Y]))),
            rtol=1e-4)

    def test_accumulate_rejects_per_step_call(self):
        _require8()
        X, Y = self._data()
        mesh = dist.init_mesh({"data": 8})
        m, o = self._build()
        s = dist.DistributedTrainStep(
            m, o, self._loss, mesh=mesh,
            comm_options=CommOptions(accumulate_steps=2))
        with pytest.raises(ValueError, match="fused path"):
            s(X, Y)
        with pytest.raises(ValueError, match="divide"):
            s.run_fused([np.stack([X] * 3), np.stack([Y] * 3)], steps=3)

    def test_comm_requires_pure_dp_mesh(self):
        _require8()
        mesh = dist.init_mesh({"data": 2, "model": 4})
        m, o = self._build()
        with pytest.raises(ValueError, match="pure data-parallel"):
            dist.DistributedTrainStep(m, o, self._loss, mesh=mesh,
                                      comm_options=CommOptions())

    def test_unreached_param_update_skipped(self):
        """Params the backward never touches exchange zeros (static
        bucket layout) but must SKIP the optimizer update like the
        non-comm path — AdamW weight decay on a zero grad would
        silently shrink them."""
        _require8()
        X, Y = self._data()
        mesh = dist.init_mesh({"data": 8})
        pt.seed(5)
        with pt.utils.unique_name.guard():
            m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 1))
            unused = nn.Linear(4, 4)
            o = optim.AdamW(0.05, parameters=list(m.parameters()) +
                            list(unused.parameters()), weight_decay=0.1)
        before = {k: np.asarray(v) for k, v in
                  unused.state_dict().items()}
        s = dist.DistributedTrainStep(m, o, self._loss, mesh=mesh,
                                      models=[m, unused],
                                      comm_options=CommOptions())
        for _ in range(3):
            s(X, Y)
        for k, v in unused.state_dict().items():
            np.testing.assert_array_equal(np.asarray(v), before[k])

    def test_wrapper_comm_falls_back_on_tp_mesh(self):
        """An inherited DataParallel comm_buffer_size on a layout the
        explicit exchange can't serve warns and falls back to implicit
        GSPMD (source compat); explicit comm_options still raises."""
        _require8()
        X, Y = self._data()
        mesh = dist.init_mesh({"data": 2, "model": 4})
        m, o = self._build()
        w = dist.DataParallel(m, comm_buffer_size=25)
        with pytest.warns(RuntimeWarning, match="falls back"):
            s = dist.DistributedTrainStep(w, o, self._loss, mesh=mesh)
        assert s._comm is None
        assert np.isfinite(float(s(X, Y)))

    def test_int8_rejects_grad_scaler(self):
        """EF residuals live in loss-scale units and an overflow would
        quantize inf into them — the combination is rejected up front."""
        _require8()
        from paddle_tpu.amp import GradScaler

        mesh = dist.init_mesh({"data": 8})
        m, o = self._build()
        with pytest.raises(ValueError, match="GradScaler"):
            dist.DistributedTrainStep(
                m, o, self._loss, mesh=mesh, scaler=GradScaler(),
                comm_options=CommOptions(quantize="int8"))

    def test_indivisible_batch_rejected(self):
        """A batch no feed can shard over the mesh must raise, not run
        the full batch redundantly on every device. (P('data')-placed
        batches already fail at device_put; replicated batch_specs are
        the path that would silently replicate the compute.)"""
        _require8()
        from jax.sharding import PartitionSpec as P

        mesh = dist.init_mesh({"data": 8})
        m, o = self._build()
        s = dist.DistributedTrainStep(m, o, self._loss, mesh=mesh,
                                      batch_specs=[P(), P()],
                                      comm_options=CommOptions())
        rng = np.random.RandomState(0)
        with pytest.raises(ValueError, match="leading dim divides"):
            s(rng.randn(12, 8).astype("float32"),
              rng.randn(12, 1).astype("float32"))


class TestSplitUpdateSegment:
    class _Op:
        def __init__(self, type_, ins=(), outs=()):
            self.type, self.input_names, self.output_names = \
                type_, list(ins), list(outs)

    def test_rejects_backward_after_update(self):
        """The docstring contract: a second minimize()'s backward ops
        landing after the first update segment is a hard error, not
        silently misplaced ops."""
        ops = [self._Op("fc", ["x"], ["h"]),
               self._Op("fc@grad", ["h"], ["w@GRAD"]),
               self._Op("optimize_sgd", ["w", "w@GRAD"], ["w"]),
               self._Op("fill_ones_like", ["loss2"], ["loss2@GRAD"]),
               self._Op("fc@grad", ["loss2@GRAD"], ["v@GRAD"]),
               self._Op("optimize_sgd", ["v", "v@GRAD"], ["v"])]
        with pytest.raises(ValueError, match="AFTER the first update"):
            gc.split_update_segment(ops)

    def test_accepts_single_minimize_shape(self):
        ops = [self._Op("fc", ["x"], ["h"]),
               self._Op("fc@grad", ["h"], ["w@GRAD"]),
               self._Op("optimize_sgd", ["w", "w@GRAD"], ["w"])]
        comp, upd, cross = gc.split_update_segment(ops)
        assert len(comp) == 2 and len(upd) == 1
        assert cross == ["w@GRAD"]


# -- dataset-driven fused loop (satellite) -----------------------------------


class TestTrainFromDatasetFused:
    def _files(self, tmp_path, n_files=2, rows=64, dim=4):
        rng = np.random.RandomState(0)
        W = rng.randn(dim).astype(np.float32)
        paths = []
        for i in range(n_files):
            xs = rng.randn(rows, dim).astype(np.float32)
            ys = (xs @ W > 0).astype(np.int64)
            p = str(tmp_path / f"part-{i}.txt")
            with open(p, "w") as f:
                for xr, yr in zip(xs, ys):
                    vals = " ".join(f"{v:.6f}" for v in xr)
                    f.write(f"{len(xr)} {vals} 1 {int(yr)}\n")
            paths.append(p)
        return paths

    def _program(self, batch, dim=4):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data(name="x", shape=[batch, dim])
            y = fluid.data(name="y", shape=[batch], dtype="int64")
            logits = fluid.layers.fc(x, size=2)
            loss = F.cross_entropy(logits, y)
            fluid.optimizer.Adam(learning_rate=5e-2).minimize(loss)
        return prog, startup, x, y, loss

    def _dataset(self, paths, x, y, batch):
        ds = fluid.DatasetFactory().create_dataset()
        ds.set_use_var([x, y])
        ds.set_batch_size(batch)
        ds.set_filelist(paths)
        return ds

    def test_fused_matches_per_step(self, tmp_path, static_mode):
        """steps_per_dispatch=K drives run_steps windows straight from
        the DevicePrefetcher; the final state matches the per-step loop
        with FEWER dispatches."""
        paths = self._files(tmp_path)  # 128 rows -> 8 batches of 16
        pt.seed(0)
        prog, startup, x, y, loss = self._program(batch=16)
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.train_from_dataset(program=prog, dataset=self._dataset(
            paths, x, y, 16), fetch_list=[loss], print_period=0)
        per_step_final = float(np.asarray(out[0]))
        per_step_dispatches = exe.dispatches

        pt.seed(0)
        prog2, startup2, x2, y2, loss2 = self._program(batch=16)
        exe2 = fluid.Executor()
        exe2.run(startup2)
        out2 = exe2.train_from_dataset(
            program=prog2, dataset=self._dataset(paths, x2, y2, 16),
            fetch_list=[loss2], print_period=0, steps_per_dispatch=4)
        stacked = np.asarray(out2[0])
        assert stacked.shape == (4,)
        np.testing.assert_allclose(float(stacked[-1]), per_step_final,
                                   rtol=1e-6)
        assert exe2.dispatches < per_step_dispatches

    def test_fused_with_comm_accumulation(self, tmp_path, static_mode):
        """The whole stack composes: dataset -> prefetcher -> fused
        window -> bucketed exchange firing once per 2 microbatches."""
        _require8()
        paths = self._files(tmp_path)
        pt.seed(0)
        prog, startup, x, y, loss = self._program(batch=16)
        c = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name,
            comm_options=CommOptions(accumulate_steps=2))
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.train_from_dataset(
            program=c, dataset=self._dataset(paths, x, y, 16),
            fetch_list=[loss], print_period=0, steps_per_dispatch=4)
        stacked = np.asarray(out[0])
        assert stacked.shape == (4,)
        assert np.isfinite(stacked).all()

    def test_accum_tail_runs_as_smaller_window(self, tmp_path,
                                               static_mode):
        """With accumulate_steps=N a ragged tail cannot fall back to
        per-step run() (it rejects accumulation); whole N-multiples run
        as one smaller fused window, the remainder is dropped with a
        warning."""
        _require8()
        # 96 rows -> 6 batches of 16: one K=4 window + a 2-batch tail
        paths = self._files(tmp_path, n_files=1, rows=96)
        pt.seed(0)
        prog, startup, x, y, loss = self._program(batch=16)
        c = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name,
            comm_options=CommOptions(accumulate_steps=2))
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.train_from_dataset(
            program=c, dataset=self._dataset(paths, x, y, 16),
            fetch_list=[loss], print_period=0, steps_per_dispatch=4)
        assert np.asarray(out[0]).shape == (2,)  # the K=2 tail window
        assert exe.dispatches == 2

        # 80 rows -> 5 batches: the 1-batch remainder is dropped loudly
        paths = self._files(tmp_path, n_files=1, rows=80)
        pt.seed(0)
        prog2, startup2, x2, y2, loss2 = self._program(batch=16)
        c2 = fluid.CompiledProgram(prog2).with_data_parallel(
            loss_name=loss2.name,
            comm_options=CommOptions(accumulate_steps=2))
        exe2 = fluid.Executor()
        exe2.run(startup2)
        with pytest.warns(RuntimeWarning, match="whole N-microbatch"):
            exe2.train_from_dataset(
                program=c2, dataset=self._dataset(paths, x2, y2, 16),
                fetch_list=[loss2], print_period=0, steps_per_dispatch=4)

    def test_tail_batches_consumed(self, tmp_path, static_mode):
        """A dataset not dividing into K-windows falls back to per-step
        run() for the tail instead of dropping full batches."""
        paths = self._files(tmp_path, n_files=1, rows=48)  # 3 batches
        pt.seed(0)
        prog, startup, x, y, loss = self._program(batch=16)
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.train_from_dataset(
            program=prog, dataset=self._dataset(paths, x, y, 16),
            fetch_list=[loss], print_period=0, steps_per_dispatch=2)
        # last fetch comes from the per-step tail run: scalar loss
        assert np.asarray(out[0]).shape == ()


# -- tooling (satellite: donation sweep) -------------------------------------


@pytest.mark.slow
def test_donation_sweep_covers_model_zoo():
    """tools/perf_gate.py --donation-sweep: every sweep leg's fused
    entry must donate 100% of its persistable carry."""
    _require8()
    pg = _load_tool("perf_gate")
    rows, failures = pg.donation_sweep()
    assert failures == []
    assert {r["model"] for r in rows} == {"mlp", "lenet", "ngram_lm"}
    assert all(r["coverage"] == 1.0 for r in rows)
    assert "100%" in pg.render_sweep(rows)
