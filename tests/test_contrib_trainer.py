"""fluid.contrib Trainer/Inferencer high-level API (ref:
fluid/contrib/trainer.py, inferencer.py; book high-level-api chapters):
event loop, checkpoint save/cap/resume, test() averaging, params
round-trip into an Inferencer, and the legacy fluid.layers.data
append_batch_size semantics it depends on.
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.contrib.trainer import (BeginStepEvent,
                                              CheckpointConfig,
                                              EndStepEvent, Trainer)
from paddle_tpu.fluid.contrib.inferencer import Inferencer

RNG = np.random.RandomState(0)
W = RNG.randn(13, 1).astype("float32")


def _reader():
    def r():
        for _ in range(8):
            X = RNG.randn(4, 13).astype("float32")
            yield [(X[i], X[i] @ W) for i in range(4)]

    return r


def _train_func():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    return [loss, pred]


def _infer_func():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    return fluid.layers.fc(input=x, size=1)


def _opt_func():
    return pt.optimizer.SGD(learning_rate=0.05)


class TestTrainer:
    def test_event_loop_checkpoints_and_inference(self, tmp_path):
        ckpt = str(tmp_path / "ck")
        events, losses = [], []

        def handler(ev):
            events.append(type(ev).__name__)
            if isinstance(ev, EndStepEvent):
                losses.append(float(np.asarray(ev.metrics[0])))

        tr = Trainer(train_func=_train_func, optimizer_func=_opt_func,
                     checkpoint_config=CheckpointConfig(
                         ckpt, max_num_checkpoints=2, step_interval=4))
        tr.train(num_epochs=3, event_handler=handler, reader=_reader(),
                 feed_order=["x", "y"])
        assert losses[-1] < losses[0] * 0.5
        for name in ("BeginEpochEvent", "BeginStepEvent", "EndStepEvent",
                     "EndEpochEvent"):
            assert name in events
        # keep-last-k: at most max_num_checkpoints serials on disk
        assert 0 < len(os.listdir(ckpt)) <= 2

        test_metrics = tr.test(reader=_reader(), feed_order=["x", "y"])
        assert float(test_metrics[0]) < losses[0]

        pdir = str(tmp_path / "params")
        tr.save_params(pdir)
        inf = Inferencer(infer_func=_infer_func, param_path=pdir)
        X = RNG.randn(6, 13).astype("float32")  # any batch size works
        (out,) = inf.infer({"x": X})
        assert out.shape == (6, 1)
        assert np.abs(out - X @ W).mean() < 1.0

        # a fresh Trainer resumes from the latest serial
        tr2 = Trainer(train_func=_train_func, optimizer_func=_opt_func,
                      checkpoint_config=CheckpointConfig(
                          ckpt, max_num_checkpoints=2, step_interval=4))
        assert tr2.checkpoint_cfg.load_serial is not None

    def test_stop_and_fetch_metrics_flag(self):
        seen = {"steps": 0, "empty_metrics": False}

        def handler(ev):
            if isinstance(ev, BeginStepEvent):
                ev.fetch_metrics = False
            if isinstance(ev, EndStepEvent):
                seen["steps"] += 1
                seen["empty_metrics"] = ev.metrics == []
                tr.stop()

        tr = Trainer(train_func=_train_func, optimizer_func=_opt_func)
        tr.train(num_epochs=5, event_handler=handler, reader=_reader(),
                 feed_order=["x", "y"])
        assert seen["steps"] == 1  # stop() halts after the first step
        assert seen["empty_metrics"]

    def test_optimizer_type_check(self):
        with pytest.raises(TypeError):
            Trainer(train_func=_train_func, optimizer_func=lambda: object())


def test_legacy_data_appends_batch_dim():
    """fluid.layers.data declares PER-SAMPLE shape (ref layers/io.py:48
    append_batch_size=True); 2.x static.data takes the full shape."""
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.static.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[13], dtype="float32")
            assert tuple(x.shape) == (1, 13)  # batch placeholder prepended
            x2 = fluid.layers.data(name="x2", shape=[-1, 13],
                                   dtype="float32")
            assert tuple(x2.shape) == (1, 13)  # explicit -1 not doubled
            x3 = fluid.layers.data(name="x3", shape=[7, 13],
                                   dtype="float32",
                                   append_batch_size=False)
            assert tuple(x3.shape) == (7, 13)
    finally:
        pt.disable_static()


def test_legacy_data_2x_positional_dtype_and_negative_dims():
    """data(name, full_shape, "float32") is the 2.x positional-dtype
    call (no batch prepend); any -1/None dim also means full shape
    (ref layers/io.py append_batch_size handling)."""
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.static.program_guard(main, startup):
            a = fluid.layers.data("a", [4, 4], "float32")
            assert tuple(a.shape) == (4, 4)
            b = fluid.layers.data("b", [3, -1, 5], dtype="float32")
            assert tuple(b.shape) == (3, 1, 5)  # -1 dim: no prepend
    finally:
        pt.disable_static()


def test_resume_skips_replayed_steps(tmp_path):
    """After loading a checkpoint taken at (epoch, step), the resumed
    run must not re-apply the steps before it."""
    ckpt = str(tmp_path / "ck")
    tr = Trainer(train_func=_train_func, optimizer_func=_opt_func,
                 checkpoint_config=CheckpointConfig(ckpt, step_interval=6))
    tr.train(num_epochs=1, event_handler=lambda ev: None,
             reader=_reader(), feed_order=["x", "y"])

    tr2 = Trainer(train_func=_train_func, optimizer_func=_opt_func,
                  checkpoint_config=CheckpointConfig(ckpt,
                                                     step_interval=6))
    assert tr2.checkpoint_cfg.load_serial is not None
    steps = []

    def handler(ev):
        if isinstance(ev, EndStepEvent):
            steps.append((ev.epoch, ev.step))

    tr2.train(num_epochs=1, event_handler=handler, reader=_reader(),
              feed_order=["x", "y"])
    resumed_from = tr2.checkpoint_cfg.step_id
    assert all(s > resumed_from for e, s in steps if e == 0)


def test_inferencer_predictor_mode(tmp_path):
    """infer_func=None serves a save_inference_model bundle through the
    Predictor (the pre-existing shim contract)."""
    tr = Trainer(train_func=_train_func, optimizer_func=_opt_func)
    tr.train(num_epochs=1, event_handler=lambda ev: None,
             reader=_reader(), feed_order=["x", "y"])
    bundle = str(tmp_path / "bundle")
    tr.save_inference_model(bundle, ["x"], [1])
    with pytest.warns(Warning):
        inf = Inferencer(param_path=bundle)
    X = RNG.randn(4, 13).astype("float32")
    (out,) = inf.infer({"x": X})
    assert np.asarray(out).shape == (4, 1)
