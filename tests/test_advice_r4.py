"""Value-level regression tests for the round-4 advisor fixes
(ADVICE.md r3): polygon_box_transform x4 scale, collect_fpn_proposals
pad masking, box_decoder_and_assign clip scope, resize align_corners,
ShufflePool close/free race.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.ops as ops


def t(a, dtype="float32"):
    return pt.to_tensor(np.asarray(a, dtype))


def test_polygon_box_transform_values():
    """ref polygon_box_transform_op.cc: out = 4*id_w - in (even chans),
    4*id_h - in (odd chans) — EAST geo maps are quarter-resolution."""
    x = np.zeros((1, 2, 2, 3), np.float32)
    x[0, 0, 1, 2] = 1.0   # x-offset channel
    x[0, 1, 1, 2] = 2.0   # y-offset channel
    out = np.asarray(ops.polygon_box_transform(t(x)).numpy())
    # channel 0: 4*col - in
    exp0 = 4.0 * np.arange(3)[None, :].repeat(2, 0) - x[0, 0]
    # channel 1: 4*row - in
    exp1 = 4.0 * np.arange(2)[:, None].repeat(3, 1) - x[0, 1]
    assert np.allclose(out[0, 0], exp0)
    assert np.allclose(out[0, 1], exp1)


def test_collect_fpn_masks_pad_rows():
    """Zero-padded pad rows (score 0.0, the generate_proposals padding
    convention) must not enter the top-k, and the returned count must
    reflect only real proposals."""
    # level 1: 1 real (score 0.2) + 2 pad rows; level 2: 1 real (0.1) + 1 pad
    rois1 = t([[0, 0, 1, 1], [0, 0, 0, 0], [0, 0, 0, 0]])
    scores1 = t([0.2, 0.0, 0.0])
    rois2 = t([[0, 0, 3, 3], [0, 0, 0, 0]])
    scores2 = t([0.1, 0.0])
    out, n = ops.collect_fpn_proposals(
        [rois1, rois2], [scores1, scores2], 2, 3, post_nms_top_n=4,
        rois_num_per_level=[t([1], "int32"), t([1], "int32")])
    assert int(np.asarray(n.numpy())) == 2  # NOT min(top_n, N)=4
    o = np.asarray(out.numpy())
    assert np.allclose(o[0], [0, 0, 1, 1])   # best real first
    assert np.allclose(o[1], [0, 0, 3, 3])
    assert np.allclose(o[2:], 0.0)           # pads zeroed


def test_collect_fpn_without_counts_keeps_old_shape():
    out, n = ops.collect_fpn_proposals(
        [t([[0, 0, 1, 1]]), t([[0, 0, 3, 3]])],
        [t([0.9]), t([0.5])], 2, 3, post_nms_top_n=2)
    assert int(np.asarray(n.numpy())) == 2
    assert np.allclose(np.asarray(out.numpy())[0], [0, 0, 1, 1])


def test_box_decoder_clips_only_log_deltas():
    """ref box_decoder_and_assign_op.h:53: box_clip upper-bounds dw/dh
    only; dx/dy pass through unclipped."""
    prior = t([[0.0, 0.0, 9.0, 9.0]])          # w=h=10 (plus-one conv)
    pvar = t([1.0, 1.0, 1.0, 1.0])
    clip = 1.0
    # dx huge (should shift freely), dw huge (should clamp at clip)
    deltas = t([[100.0, 0.0, 5.0, 0.0]])
    scores = t([[1.0]])
    decoded, assigned = ops.box_decoder_and_assign(
        prior, pvar, deltas, scores, box_clip=clip)
    d = np.asarray(decoded.numpy())[0]
    cx = (d[0] + d[2] + 1) / 2.0
    w = d[2] - d[0] + 1
    assert cx > 500.0                      # dx unclipped: 100*10+4.5
    assert np.isclose(w, 10.0 * np.e, rtol=1e-3)  # dw clamped to 1.0


def test_resize_trilinear_align_corners():
    """align_corners=True: corners map to corners exactly; a 2->3 upscale
    of [0, 2] must hit the midpoint exactly (src = dst*(in-1)/(out-1))."""
    x = np.zeros((1, 1, 2, 2, 2), np.float32)
    x[0, 0, :, 0, 0] = [0.0, 2.0]
    out = np.asarray(ops.resize_trilinear(
        t(x), out_shape=[3, 2, 2], align_corners=True).numpy())
    assert np.allclose(out[0, 0, :, 0, 0], [0.0, 1.0, 2.0], atol=1e-5)
    # 2->4: corner-aligned src=dst/3 -> [0, 2/3, 4/3, 2]; align_mode=0
    # (half-pixel, ref interpolate_op.h:118 align_flag) src=(dst+.5)/2-.5
    # -> [0, .5, 1.5, 2]; align_mode=1 src=dst/2 -> [0, 1, 2, 2]
    out4 = np.asarray(ops.resize_trilinear(
        t(x), out_shape=[4, 2, 2], align_corners=True).numpy())
    assert np.allclose(out4[0, 0, :, 0, 0], [0, 2 / 3, 4 / 3, 2],
                       atol=1e-5)
    out4_hp = np.asarray(ops.resize_trilinear(
        t(x), out_shape=[4, 2, 2], align_corners=False,
        align_mode=0).numpy())
    assert np.allclose(out4_hp[0, 0, :, 0, 0], [0, 0.5, 1.5, 2],
                       atol=1e-5)
    out4_m1 = np.asarray(ops.resize_trilinear(
        t(x), out_shape=[4, 2, 2], align_corners=False,
        align_mode=1).numpy())
    assert np.allclose(out4_m1[0, 0, :, 0, 0], [0, 1, 2, 2], atol=1e-5)


def test_resize_nearest_reference_rules():
    """ref interpolate_op.h:88: nearest ignores align_mode; src index =
    floor(ratio*dst) (ratio=in/out) when not align_corners, else
    floor(ratio*dst + 0.5) with ratio=(in-1)/(out-1)."""
    import paddle_tpu.fluid.layers as L

    x = np.arange(2, dtype=np.float32).reshape(1, 1, 2, 1)
    x = np.tile(x, (1, 1, 1, 2))
    out = np.asarray(L.resize_nearest(
        t(x), out_shape=[4, 2], align_corners=False).numpy())
    assert np.allclose(out[0, 0, :, 0], [0, 0, 1, 1])  # floor(dst*0.5)
    x3 = np.arange(3, dtype=np.float32).reshape(1, 1, 3, 1)
    x3 = np.tile(x3, (1, 1, 1, 2))
    out3 = np.asarray(L.resize_nearest(
        t(x3), out_shape=[5, 2], align_corners=True).numpy())
    assert np.allclose(out3[0, 0, :, 0], [0, 1, 1, 2, 2])


def test_resize_out_size_one():
    """out==1 -> ratio 0 -> source row 0 (ref interpolate_op.h:572)."""
    import paddle_tpu.fluid.layers as L

    x = np.arange(4, dtype=np.float32).reshape(1, 1, 4, 1)
    x = np.tile(x, (1, 1, 1, 2))
    out = np.asarray(L.resize_bilinear(
        t(x), out_shape=[1, 2], align_corners=True).numpy())
    assert np.allclose(out[0, 0, 0, 0], 0.0)


def test_resize_bilinear_align_corners():
    import paddle_tpu.fluid.layers as L

    x = np.zeros((1, 1, 2, 2), np.float32)
    x[0, 0, :, 0] = [0.0, 2.0]
    out = np.asarray(L.resize_bilinear(
        t(x), out_shape=[3, 2], align_corners=True).numpy())
    assert np.allclose(out[0, 0, :, 0], [0.0, 1.0, 2.0], atol=1e-5)


def test_shuffle_pool_free_race():
    """Producers blocked in push while the pool is closed + freed: free
    must drain in-flight callers (no crash/UAF)."""
    from paddle_tpu.runtime import ShufflePool

    for _ in range(5):
        pool = ShufflePool(capacity=2, seed=7)
        stop = []

        def produce():
            i = 0
            while not stop:
                try:
                    if not pool.push(b"x" * 64):
                        return  # closed
                except Exception:
                    return
                i += 1

        threads = [threading.Thread(target=produce) for _ in range(3)]
        for th in threads:
            th.start()
        time.sleep(0.02)  # let producers fill the pool and block
        pool.close()
        pool.__del__()    # close + drain + free explicitly
        stop.append(1)
        for th in threads:
            th.join(timeout=5)
            assert not th.is_alive()
