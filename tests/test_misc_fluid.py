"""Long-tail misc ops + the fluid compatibility namespace
(ref: layers/nn.py, layers/loss.py long tail; fluid/__init__.py surface).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops


class TestMiscLosses:
    def test_cos_sim(self):
        x = np.array([[1.0, 0.0], [1.0, 1.0]], "float32")
        y = np.array([[0.0, 1.0], [1.0, 1.0]], "float32")
        out = np.asarray(ops.cos_sim(pt.to_tensor(x),
                                     pt.to_tensor(y)).numpy())
        np.testing.assert_allclose(out[:, 0], [0.0, 1.0], atol=1e-6)

    def test_dice_loss_perfect_prediction(self):
        probs = np.zeros((2, 3, 4), "float32")
        lab = np.random.RandomState(0).randint(0, 4, (2, 3, 1))
        for b in range(2):
            for i in range(3):
                probs[b, i, lab[b, i, 0]] = 1.0
        out = np.asarray(ops.dice_loss(pt.to_tensor(probs),
                                       pt.to_tensor(lab)).numpy())
        np.testing.assert_allclose(out, 0.0, atol=1e-4)

    def test_huber_loss_quadratic_linear(self):
        x = pt.to_tensor(np.array([0.5, 3.0], "float32"))
        y = pt.to_tensor(np.zeros(2, "float32"))
        out = np.asarray(ops.huber_loss(x, y, delta=1.0).numpy())
        assert out[0] == pytest.approx(0.125)
        assert out[1] == pytest.approx(2.5)  # 1*(3 - 0.5)

    def test_rank_and_margin_rank_loss(self):
        lab = pt.to_tensor(np.array([1.0], "float32"))
        l = pt.to_tensor(np.array([2.0], "float32"))
        r = pt.to_tensor(np.array([1.0], "float32"))
        rl = float(np.asarray(ops.rank_loss(lab, l, r).numpy()))
        assert rl == pytest.approx(np.log1p(np.exp(-1.0)), rel=1e-5)
        ml = float(np.asarray(ops.margin_rank_loss(
            lab, l, r, margin=0.5).numpy()))
        assert ml == 0.0
        ml2 = float(np.asarray(ops.margin_rank_loss(
            lab, r, l, margin=0.5).numpy()))
        assert ml2 == pytest.approx(1.5)

    def test_bpr_loss_prefers_true_class(self):
        good = np.array([[5.0, 0.0, 0.0]], "float32")
        bad = np.array([[0.0, 5.0, 5.0]], "float32")
        lab = np.array([[0]], "int64")
        lg = float(np.asarray(ops.bpr_loss(pt.to_tensor(good),
                                           pt.to_tensor(lab)).numpy()))
        lb = float(np.asarray(ops.bpr_loss(pt.to_tensor(bad),
                                           pt.to_tensor(lab)).numpy()))
        assert lg < lb

    def test_center_loss_updates_centers(self):
        x = np.array([[1.0, 1.0], [3.0, 3.0]], "float32")
        lab = np.array([[0], [0]], "int64")
        centers = np.zeros((2, 2), "float32")
        loss, new_c = ops.center_loss(pt.to_tensor(x), pt.to_tensor(lab),
                                      centers=pt.to_tensor(centers),
                                      alpha=0.5)
        nc = np.asarray(new_c.numpy())
        assert nc[0, 0] > 0  # moved toward the class mean
        assert nc[1, 0] == 0  # untouched class
        l = np.asarray(loss.numpy())
        assert l[0, 0] == pytest.approx(1.0)  # 0.5*(1+1)

    def test_mean_iou(self):
        pred = np.array([[0, 1, 1, 2]], "int64")
        lab = np.array([[0, 1, 2, 2]], "int64")
        miou, wrong, correct = ops.mean_iou(pt.to_tensor(pred),
                                            pt.to_tensor(lab), 3)
        # class ious: 1.0, 0.5, 0.5 -> mean 2/3
        assert float(np.asarray(miou.numpy())) == pytest.approx(2 / 3)
        np.testing.assert_array_equal(np.asarray(correct.numpy()),
                                      [1, 1, 1])


class TestMiscTensorOps:
    def test_multiplex(self):
        a = np.zeros((3, 2), "float32")
        b = np.ones((3, 2), "float32")
        idx = np.array([[0], [1], [0]], "int32")
        out = np.asarray(ops.multiplex(
            [pt.to_tensor(a), pt.to_tensor(b)],
            pt.to_tensor(idx)).numpy())
        np.testing.assert_allclose(out[:, 0], [0, 1, 0])

    def test_crop_tensor_and_unstack(self):
        x = np.arange(24, dtype="float32").reshape(2, 3, 4)
        out = np.asarray(ops.crop_tensor(
            pt.to_tensor(x), shape=[1, 2, 2], offsets=[1, 1, 2]).numpy())
        np.testing.assert_allclose(out[0], x[1, 1:3, 2:4])
        parts = ops.unstack(pt.to_tensor(x), axis=1)
        assert len(parts) == 3
        np.testing.assert_allclose(np.asarray(parts[1].numpy()), x[:, 1])

    def test_bilinear_tensor_product(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 3).astype("float32")
        y = rng.randn(4, 5).astype("float32")
        w = rng.randn(2, 3, 5).astype("float32")
        out = np.asarray(ops.bilinear_tensor_product(
            pt.to_tensor(x), pt.to_tensor(y),
            weight=pt.to_tensor(w)).numpy())
        want = np.einsum("nd,kde,ne->nk", x, w, y)
        np.testing.assert_allclose(out, want, atol=1e-4)

    def test_add_position_encoding(self):
        x = np.zeros((1, 4, 6), "float32")
        out = np.asarray(ops.add_position_encoding(
            pt.to_tensor(x), alpha=0.0, beta=1.0).numpy())
        assert out[0, 0, 0] == pytest.approx(0.0)       # sin(0)
        assert out[0, 0, 3] == pytest.approx(1.0)       # cos(0)
        assert abs(out[0, 1, 0] - np.sin(1.0)) < 1e-5

    def test_temporal_shift_moves_channels(self):
        x = np.arange(2 * 4, dtype="float32") \
            .reshape(2, 4, 1, 1)  # NT=2 (N=1, T=2), C=4
        out = np.asarray(ops.temporal_shift(
            pt.to_tensor(x.copy()), seg_num=2,
            shift_ratio=0.25).numpy())
        # channel 0 shifts backward: frame0 gets 0, frame1 gets frame0's
        assert out[0, 0, 0, 0] == 0.0
        assert out[1, 0, 0, 0] == x[0, 0, 0, 0]
        # untouched channels stay
        np.testing.assert_allclose(out[:, 2:], x[:, 2:])

    def test_affine_channel(self):
        x = np.ones((1, 2, 2, 2), "float32")
        s = np.array([2.0, 3.0], "float32")
        b = np.array([1.0, -1.0], "float32")
        out = np.asarray(ops.affine_channel(
            pt.to_tensor(x), pt.to_tensor(s), pt.to_tensor(b)).numpy())
        assert out[0, 0, 0, 0] == 3.0 and out[0, 1, 0, 0] == 2.0

    def test_gather_tree(self):
        ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], "int64")
        parents = np.array([[[0, 0]], [[1, 0]], [[1, 0]]], "int64")
        out = np.asarray(ops.gather_tree(
            pt.to_tensor(ids), pt.to_tensor(parents)).numpy())
        # beam 0 backtrace: t2 tok 5 (parent 1), t1 tok 4 (parent 0),
        # t0 tok 1 -> [1, 4, 5]
        np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])

    def test_clip_by_norm(self):
        x = np.array([3.0, 4.0], "float32")
        out = np.asarray(ops.clip_by_norm(pt.to_tensor(x), 1.0).numpy())
        np.testing.assert_allclose(np.linalg.norm(out), 1.0, rtol=1e-5)
        keep = np.asarray(ops.clip_by_norm(pt.to_tensor(x), 10.0).numpy())
        np.testing.assert_allclose(keep, x)

    def test_fsp_matrix(self):
        rng = np.random.RandomState(1)
        a = rng.randn(2, 3, 4, 4).astype("float32")
        b = rng.randn(2, 5, 4, 4).astype("float32")
        out = np.asarray(ops.fsp_matrix(pt.to_tensor(a),
                                        pt.to_tensor(b)).numpy())
        want = np.einsum("bchw,bdhw->bcd", a, b) / 16
        np.testing.assert_allclose(out, want, atol=1e-4)

    def test_ctc_greedy_decoder(self):
        # argmax path: [1, 1, blank, 2, 2, blank] -> [1, 2]
        T, C = 6, 4
        probs = np.zeros((1, T, C), "float32")
        path = [1, 1, 3, 2, 2, 3]
        for t, c in enumerate(path):
            probs[0, t, c] = 1.0
        dec, lens = ops.ctc_greedy_decoder(probs, blank=3)
        assert int(np.asarray(lens.numpy())[0]) == 2
        np.testing.assert_array_equal(np.asarray(dec.numpy())[0, :2],
                                      [1, 2])


class TestFluidCompat:
    def test_static_fc_pipeline(self):
        import paddle_tpu.fluid as fluid

        pt.seed(0)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[16, 8], dtype="float32")
            y = fluid.data(name="y", shape=[16, 1], dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(pred - y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        X = rng.randn(16, 8).astype("float32")
        Y = (X @ rng.randn(8).astype("float32")).reshape(16, 1)
        losses = [float(exe.run(main, feed={"x": X, "y": Y},
                                fetch_list=[loss])[0])
                  for _ in range(40)]
        assert losses[-1] < losses[0] * 0.1

    def test_alias_surface(self):
        import paddle_tpu.fluid as fluid

        x = pt.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
        assert float(fluid.layers.reduce_sum(x)) == 10.0
        out = fluid.layers.elementwise_max(x, x * 0 + 2.5)
        assert float(np.asarray(out.numpy()).min()) == 2.5
        assert list(np.asarray(fluid.layers.shape(x).numpy())) == [2, 2]
        assert int(fluid.layers.rank(x)) == 2
        sched = fluid.layers.piecewise_decay([10], [0.1, 0.01])
        assert sched.get_lr() == 0.1

    def test_dygraph_guard_and_variable(self):
        import paddle_tpu.fluid as fluid

        with fluid.dygraph.guard():
            v = fluid.dygraph.to_variable(np.ones((2, 2), "float32"))
            lin = fluid.dygraph.Linear(2, 3)
            out = lin(v)
            assert list(out.shape) == [2, 3]

    def test_compat_program_guard_restores_mode(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu import static_

        assert not static_.in_static_mode()
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            assert static_.in_static_mode()
        assert not static_.in_static_mode()


class TestReviewRegressions:
    def test_crop_tensor_minus_one_respects_offset(self):
        x = np.arange(20, dtype="float32").reshape(5, 4)
        out = np.asarray(ops.crop_tensor(
            pt.to_tensor(x), shape=[-1, 2], offsets=[2, 0]).numpy())
        assert out.shape == (3, 2)
        np.testing.assert_allclose(out, x[2:, :2])

    def test_target_assign_negative_padding_dropped(self):
        x = np.arange(8, dtype="float32").reshape(1, 2, 4)
        match = np.array([[0, -1, -1]], "int32")
        negs = np.array([[2, -1]], "int32")  # -1 is padding, NOT prior 0
        out, w = ops.target_assign(pt.to_tensor(x), pt.to_tensor(match),
                                   negative_indices=pt.to_tensor(negs),
                                   mismatch_value=0)
        w = np.asarray(w.numpy())[0]
        assert w[0, 0] == 1.0  # matched positive untouched
        assert w[1, 0] == 0.0  # unmined stays ignored
        assert w[2, 0] == 1.0  # the listed negative

    def test_fluid_decay_steps_semantics(self):
        import paddle_tpu.fluid as fluid

        s = fluid.layers.exponential_decay(0.1, decay_steps=100,
                                           decay_rate=0.5)
        for _ in range(100):
            s.step()
        assert s.get_lr() == pytest.approx(0.05, rel=1e-6)
        s2 = fluid.layers.inverse_time_decay(0.1, decay_steps=10,
                                             decay_rate=1.0)
        for _ in range(10):
            s2.step()
        assert s2.get_lr() == pytest.approx(0.05, rel=1e-6)

    def test_movielens_api_callables(self):
        from paddle_tpu import dataset

        assert dataset.movielens.max_user_id() > 0
        assert dataset.movielens.max_job_id() == 20
        s = next(dataset.movielens.train()())
        assert len(s) == 8 and 1 <= s[-1] <= 5
