"""End-to-end pallas routing test: a tiny GPT trains with the pallas
kernels force-enabled (interpret on CPU) as the LIVE code path —
layernorm, flash attention, and softmax-CE all route through
ops/pallas/ — and the first-step loss matches the dense path exactly.
(Compiled-mode TPU validation is tools/tpu_probe.py.)"""
import numpy as np
import paddle_tpu as pt
from paddle_tpu import optim
from paddle_tpu.ops import pallas as pk
from paddle_tpu.models.nlp.gpt import GPT, GPTConfig, gpt_loss


def test_pallas_routing_end_to_end():
    pk.set_enabled(True)   # force the pallas routing; auto_interpret -> CPU
    try:
        _run()
    finally:
        pk.set_enabled(None)


def _run():
    pt.seed(0)
    # shapes chosen to satisfy the pallas gates: L%128==0, D%64==0, V%128==0
    cfg = GPTConfig(vocab_size=512, hidden=128, layers=2, heads=2, max_seq=128,
                    dropout=0.0)
    model = GPT(cfg)
    opt = optim.AdamW(parameters=model.parameters(), learning_rate=3e-3,
                      grad_clip=optim.ClipGradByGlobalNorm(1.0))
    step = pt.TrainStep(model, opt, gpt_loss)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 128)).astype("int32")
    labels = np.roll(ids, -1, axis=1).astype("int32")

    losses = []
    for i in range(8):
        losses.append(float(np.asarray(step(ids, labels)._data)))
    print("losses:", [round(x, 3) for x in losses])
    assert all(np.isfinite(x) for x in losses), losses
    assert losses[-1] < losses[0] - 0.5, f"no learning: {losses[0]} -> {losses[-1]}"

    # parity: same model, pallas off, must agree on the loss value closely
    pk.set_enabled(False)
    pt.seed(0)
    model2 = GPT(cfg)
    opt2 = optim.AdamW(parameters=model2.parameters(), learning_rate=3e-3,
                       grad_clip=optim.ClipGradByGlobalNorm(1.0))
    step2 = pt.TrainStep(model2, opt2, gpt_loss)
    l_dense = float(np.asarray(step2(ids, labels)._data))
    assert abs(l_dense - losses[0]) < 1e-2, (l_dense, losses[0])
    print(f"pallas-vs-dense first-step loss parity: {losses[0]:.4f} vs {l_dense:.4f}")
    print("DRIVE OK")
