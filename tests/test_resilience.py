"""Chaos suite for paddle_tpu.resilience (ISSUE 2 tentpole).

One recovery test per injected fault class; where the policy promises
equivalence, the recovered run is compared against an un-faulted
reference BITWISE (skip_step == "that batch never happened" for RNG-free
models; retry/degrade/checkpoint-fallback == identical results).
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu import resilience
from paddle_tpu.framework.io import (CheckpointError, load_checkpoint,
                                     save_checkpoint, verify_checkpoint)
from paddle_tpu.io_.dataloader import DataLoader
from paddle_tpu.io_.dataset import Dataset
from paddle_tpu.resilience import (GuardedExecutor, GuardedStep,
                                   RecoveryPolicy, inject)
from paddle_tpu.utils import nan_guard

pytestmark = pytest.mark.chaos

NOSLEEP = dict(sleep=lambda s: None)


# -- helpers -----------------------------------------------------------------


def _eager_step(lr=0.1, **step_kw):
    pt.seed(0)
    m = nn.Linear(4, 1)
    opt = optim.SGD(learning_rate=lr, parameters=m.parameters())

    def loss_fn(model, x, y):
        return F.mse_loss(model(x), y)

    return m, pt.TrainStep(m, opt, loss_fn, check_nan=True, **step_kw)


def _batches(steps, batch=8, dim=4):
    rng = np.random.RandomState(0)
    return [(rng.randn(batch, dim).astype(np.float32),
             rng.randn(batch, 1).astype(np.float32)) for _ in range(steps)]


def _weights_after(skip_index=None, steps=6):
    """Un-faulted reference run, optionally omitting one batch."""
    m, step = _eager_step()
    for i, (x, y) in enumerate(_batches(steps)):
        if i != skip_index:
            step(x, y)
    return np.asarray(m.weight._data)


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _build_static(batch=8):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 4])
        y = fluid.data(name="y", shape=[batch, 1])
        out = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, startup, loss


def _static_losses(gexe, steps=3, skip_index=None):
    pt.seed(0)
    prog, startup, loss = _build_static()
    gexe.run(startup)
    out = []
    for i, (x, y) in enumerate(_batches(steps)):
        if i == skip_index:
            continue
        r = gexe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])
        out.append(None if r is None else float(np.asarray(r[0])))
    return out


# -- nonfinite step x each policy (fault class: nan_feed) --------------------


class TestNanStepPolicies:
    def test_policy_raise_aborts(self):
        _, step = _eager_step()
        guard = GuardedStep(step, RecoveryPolicy(on_nonfinite="raise",
                                                 **NOSLEEP))
        data = _batches(4)
        with inject.chaos("nan_feed", at=2, seed=7):
            guard(*data[0])
            with pytest.raises(nan_guard.NanInfError):
                guard(*data[1])

    def test_policy_skip_matches_batch_omitted_run(self):
        m, step = _eager_step()
        guard = GuardedStep(step, RecoveryPolicy(on_nonfinite="skip_step",
                                                 **NOSLEEP))
        with inject.chaos("nan_feed", at=3, seed=7):
            for x, y in _batches(6):
                guard(x, y)
        assert guard.stats.skipped == 1 and guard.stats.steps == 5
        ref = _weights_after(skip_index=2)  # at=3 => 3rd step poisoned
        assert np.array_equal(np.asarray(m.weight._data), ref), \
            "skip_step must be bitwise 'that batch never happened'"

    def test_policy_rollback_matches_with_unit_cadence(self):
        m, step = _eager_step()
        guard = GuardedStep(step, RecoveryPolicy(
            on_nonfinite="rollback", snapshot_every=1, **NOSLEEP))
        with inject.chaos("nan_feed", at=3, seed=7):
            for x, y in _batches(6):
                guard(x, y)
        assert guard.stats.rollbacks == 1
        ref = _weights_after(skip_index=2)
        assert np.array_equal(np.asarray(m.weight._data), ref)

    def test_rollback_on_first_step_falls_back_to_prestep_state(self):
        """A NaN on the very first guarded step, before any verified-good
        snapshot exists, must restore the pre-step state (not a missing/
        empty last-good snapshot) and keep training."""
        m, step = _eager_step()
        guard = GuardedStep(step, RecoveryPolicy(
            on_nonfinite="rollback", snapshot_every=3, **NOSLEEP))
        with inject.chaos("nan_feed", at=1, seed=7):
            for x, y in _batches(6):
                guard(x, y)
        assert guard.stats.rollbacks == 1
        ref = _weights_after(skip_index=0)
        assert np.array_equal(np.asarray(m.weight._data), ref)

    def test_policy_rollback_coarse_cadence_loses_to_last_snapshot(self):
        """snapshot_every=2: the rollback restores the older snapshot —
        the run completes and ends finite (exact value is the cadence
        trade-off, documented rather than promised)."""
        m, step = _eager_step()
        guard = GuardedStep(step, RecoveryPolicy(
            on_nonfinite="rollback", snapshot_every=2, **NOSLEEP))
        with inject.chaos("nan_feed", at=4, seed=7):
            for x, y in _batches(6):
                guard(x, y)
        assert guard.stats.rollbacks == 1
        assert np.isfinite(np.asarray(m.weight._data)).all()

    def test_skipped_step_advances_gradscaler(self):
        from paddle_tpu.amp import GradScaler

        sc = GradScaler(init_loss_scaling=1024.0, decr_ratio=0.5,
                        decr_every_n_nan_or_inf=1)
        _, step = _eager_step()
        guard = GuardedStep(step, RecoveryPolicy(on_nonfinite="skip_step",
                                                 **NOSLEEP), scaler=sc)
        with inject.chaos("nan_feed", at=1, seed=7):
            assert guard(*_batches(1)[0]) is None
        assert sc.loss_scaling == 512.0  # notify_skip shrank the scale
        assert sc.state_dict()["bad_steps"] == 0  # decr reset after shrink

    def test_guard_requires_nonfinite_flag(self):
        m = nn.Linear(4, 1)
        opt = optim.SGD(learning_rate=0.1, parameters=m.parameters())
        step = pt.TrainStep(m, opt, lambda mm, x, y: F.mse_loss(mm(x), y))
        with pytest.raises(ValueError, match="check_nan"):
            GuardedStep(step, RecoveryPolicy(on_nonfinite="skip_step"))


# -- eager per-op corruption (fault class: nan_op) ---------------------------


class TestNanOpDetection:
    def test_injected_op_corruption_detected_with_summary(self):
        x = pt.to_tensor(np.full((3, 3), 2.0, np.float32))
        nan_guard.enable_check_nan()
        try:
            with inject.chaos("nan_op", op="matmul", seed=1):
                with pytest.raises(nan_guard.NanInfError) as ei:
                    pt.matmul(x, x)
        finally:
            nan_guard.disable_check_nan()
        s = ei.value.summary
        assert s["num_nan"] == 1 and s["num_inf"] == 0
        assert 0 <= s["first_bad_index"] < 9
        assert s["finite_min"] == s["finite_max"] == 12.0

    def test_nan_summary_fields(self):
        a = np.array([1.0, np.nan, -np.inf, 4.0], np.float32)
        s = nan_guard.nonfinite_summary(a)
        assert s["num_nan"] == 1 and s["num_inf"] == 1
        assert s["first_bad_index"] == 1
        assert s["finite_min"] == 1.0 and s["finite_max"] == 4.0
        with pytest.raises(nan_guard.NanInfError) as ei:
            nan_guard.check_numerics(a, "grads")
        assert ei.value.summary["num_nan"] == 1
        assert "first_bad_flat_index=1" in str(ei.value)


# -- transient compile/execute (retry) + optimized-compile degrade -----------


class TestTransientRecovery:
    def test_transient_compile_retry_matches_clean(self, static_mode):
        clean = _static_losses(GuardedExecutor(
            policy=RecoveryPolicy(**NOSLEEP)))
        gexe = GuardedExecutor(policy=RecoveryPolicy(**NOSLEEP))
        with inject.chaos("transient_compile", times=2):
            faulted = _static_losses(gexe)
        assert faulted == clean
        assert gexe.stats.retries == 2

    def test_transient_execute_retry_matches_clean(self, static_mode):
        clean = _static_losses(GuardedExecutor(
            policy=RecoveryPolicy(**NOSLEEP)))
        gexe = GuardedExecutor(policy=RecoveryPolicy(**NOSLEEP))
        with inject.chaos("transient_execute", times=2):
            faulted = _static_losses(gexe)
        assert faulted == clean
        assert gexe.stats.retries == 2

    def test_retry_budget_exhaustion_raises(self, static_mode):
        gexe = GuardedExecutor(policy=RecoveryPolicy(max_retries=1,
                                                     **NOSLEEP))
        with inject.chaos("transient_compile", times=10):
            with pytest.raises(inject.TransientChaosError):
                _static_losses(gexe)

    def test_opt_level_degradation(self, static_mode):
        clean = _static_losses(GuardedExecutor(
            policy=RecoveryPolicy(degrade_opt_level=False, **NOSLEEP)))
        gexe = GuardedExecutor(policy=RecoveryPolicy(**NOSLEEP))
        with inject.chaos("opt_compile_fail", times=100):
            with pytest.warns(RuntimeWarning, match="optimize_level=0"):
                faulted = _static_losses(gexe)
        assert faulted == clean
        assert gexe.stats.degraded == 1 and gexe._degraded

    def test_retry_backoff_is_bounded_and_deterministic(self):
        pol = RecoveryPolicy(backoff=0.1, backoff_factor=2.0,
                             max_backoff=0.25)
        assert [pol.backoff_for(i) for i in range(4)] == \
            [0.1, 0.2, 0.25, 0.25]
        slept = []
        pol2 = RecoveryPolicy(max_retries=2, backoff=0.1,
                              sleep=slept.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise resilience.TransientError("flap")
            return "ok"

        out, attempts = resilience.retry_call(flaky, pol2)
        assert out == "ok" and attempts == 3 and len(slept) == 2


# -- static-path nonfinite policy (GuardedExecutor) --------------------------


class TestStaticNonfinitePolicy:
    def test_skip_step_matches_batch_omitted_run(self, static_mode):
        ref = _static_losses(GuardedExecutor(
            policy=RecoveryPolicy(**NOSLEEP)), steps=4, skip_index=1)
        gexe = GuardedExecutor(policy=RecoveryPolicy(
            on_nonfinite="skip_step", **NOSLEEP))
        with inject.chaos("nan_feed", at=2, seed=3, var="x"):
            faulted = _static_losses(gexe, steps=4)
        assert gexe.stats.skipped == 1
        # drop the skipped-step None: remaining losses must be bitwise
        # identical to the run that never saw that batch
        assert [v for v in faulted if v is not None] == ref

    def test_raise_policy_raises_on_nan_fetch(self, static_mode):
        gexe = GuardedExecutor(policy=RecoveryPolicy(**NOSLEEP))
        with inject.chaos("nan_feed", at=1, seed=3, var="x"):
            with pytest.raises(nan_guard.NanInfError):
                _static_losses(gexe, steps=1)

    def test_default_nan_feed_target_skips_internal_lr_feed(self,
                                                           static_mode):
        """With no var= config the injector must poison a USER feed, not
        the executor's internal '@lr' (which sorts first): the default
        drill then behaves like test_skip_step_matches_batch_omitted_run."""
        ref = _static_losses(GuardedExecutor(
            policy=RecoveryPolicy(**NOSLEEP)), steps=4, skip_index=1)
        gexe = GuardedExecutor(policy=RecoveryPolicy(
            on_nonfinite="skip_step", **NOSLEEP))
        with inject.chaos("nan_feed", at=2, seed=3) as inj:
            faulted = _static_losses(gexe, steps=4)
        assert inj.fired == 1
        assert gexe.stats.skipped == 1
        assert [v for v in faulted if v is not None] == ref

    def test_fault_in_committed_state_detected_same_step(self,
                                                         static_mode):
        """A NaN learning rate poisons the committed weights while the
        fetched loss (computed from PRE-update state) stays finite. The
        state scan must catch it the SAME step — one step late, the
        guard would snapshot the poisoned weights as 'good' and then
        restore poison forever."""
        ref = _static_losses(GuardedExecutor(
            policy=RecoveryPolicy(**NOSLEEP)), steps=5, skip_index=1)
        gexe = GuardedExecutor(policy=RecoveryPolicy(
            on_nonfinite="skip_step", **NOSLEEP))
        with inject.chaos("nan_feed", at=2, var="@lr"):
            faulted = _static_losses(gexe, steps=5)
        assert gexe.stats.skipped == 1, gexe.stats
        # run recovered: later steps train normally and match the
        # reference in which that (no-effect) step never happened
        assert [v for v in faulted if v is not None] == ref

    def test_static_rollback_before_first_refresh_uses_pre(self,
                                                           static_mode):
        """Executor-path twin of the first-step rollback fallback: with a
        coarse cadence, a fault before any verified-good snapshot exists
        restores this run's pre-state instead of livelocking on an
        empty last-good."""
        ref = _static_losses(GuardedExecutor(
            policy=RecoveryPolicy(**NOSLEEP)), steps=4, skip_index=0)
        gexe = GuardedExecutor(policy=RecoveryPolicy(
            on_nonfinite="rollback", snapshot_every=10, **NOSLEEP))
        with inject.chaos("nan_feed", at=1, seed=3, var="x"):
            faulted = _static_losses(gexe, steps=4)
        assert gexe.stats.rollbacks == 1 and gexe.stats.steps == 3
        assert [v for v in faulted if v is not None] == ref

    def test_scan_state_opt_out(self, static_mode):
        """scan_state=False restores the documented fetch-only detection
        for programs whose fetches legitimately contain inf."""
        gexe = GuardedExecutor(policy=RecoveryPolicy(
            on_nonfinite="skip_step", **NOSLEEP), scan_state=False)
        with inject.chaos("nan_feed", at=2, var="@lr"):
            faulted = _static_losses(gexe, steps=2)
        # the NaN-lr step's finite fetch passes; only the NEXT step's
        # NaN fetch trips detection — the documented trade-off
        assert faulted[-1] is None or gexe.stats.skipped == 0


# -- checkpoint integrity ----------------------------------------------------


def _age_tmp(path, secs=3600):
    """Backdate a tmp artifact past the orphan-cleanup grace period."""
    t = time.time() - secs
    for f in [path] + [os.path.join(path, f) for f in os.listdir(path)]:
        os.utime(f, (t, t))


def _ckpt_pair(tmp_path):
    """Two checkpoints; returns (dir, weights at step 1, weights at 2)."""
    pt.seed(0)
    m = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.1, parameters=m.parameters())
    save_checkpoint(str(tmp_path), 1, model=m, optimizer=opt)
    w1 = np.asarray(m.weight._data).copy()
    m.weight._data = m.weight._data + 1.0
    save_checkpoint(str(tmp_path), 2, model=m, optimizer=opt)
    return str(tmp_path), w1, np.asarray(m.weight._data).copy()


class TestCheckpointIntegrity:
    def test_manifest_written_and_verifies(self, tmp_path):
        d, _, _ = _ckpt_pair(tmp_path)
        path = os.path.join(d, "ckpt_2")
        assert os.path.exists(os.path.join(path, "manifest.json"))
        ok, problems = verify_checkpoint(path)
        assert ok and not problems

    @pytest.mark.parametrize("point,cfg", [
        ("ckpt_truncate", {}),
        ("ckpt_bitflip", {"seed": 5}),
    ])
    def test_corrupt_newest_falls_back(self, tmp_path, point, cfg):
        pt.seed(0)
        m = nn.Linear(4, 2)
        opt = optim.SGD(learning_rate=0.1, parameters=m.parameters())
        save_checkpoint(str(tmp_path), 1, model=m, optimizer=opt)
        w1 = np.asarray(m.weight._data).copy()
        m.weight._data = m.weight._data + 1.0
        with inject.chaos(point, **cfg):
            save_checkpoint(str(tmp_path), 2, model=m, optimizer=opt)
        ok, problems = verify_checkpoint(os.path.join(str(tmp_path),
                                                      "ckpt_2"))
        assert not ok and problems
        m2 = nn.Linear(4, 2)
        with pytest.warns(RuntimeWarning, match="falling back"):
            step = load_checkpoint(str(tmp_path), model=m2)
        assert step == 1
        assert np.array_equal(np.asarray(m2.weight._data), w1)

    def test_crashed_save_leaves_orphan_then_cleaned(self, tmp_path):
        pt.seed(0)
        m = nn.Linear(4, 2)
        save_checkpoint(str(tmp_path), 1, model=m)
        w1 = np.asarray(m.weight._data).copy()
        with inject.chaos("ckpt_crash"):
            with pytest.raises(resilience.SimulatedCrashError):
                save_checkpoint(str(tmp_path), 2, model=m)
        orphans = [f for f in os.listdir(str(tmp_path))
                   if f.startswith(".tmp_ckpt_")]
        assert orphans
        # a FRESH tmp dir may belong to a live concurrent saver: the
        # loader must leave it alone...
        m2 = nn.Linear(4, 2)
        step = load_checkpoint(str(tmp_path), model=m2)
        assert step == 1
        assert np.array_equal(np.asarray(m2.weight._data), w1)
        assert any(f.startswith(".tmp_ckpt_")
                   for f in os.listdir(str(tmp_path)))
        # ...but once it has gone stale (no writes for the grace period)
        # it is a crash artifact and gets cleaned
        _age_tmp(os.path.join(str(tmp_path), orphans[0]))
        with pytest.warns(RuntimeWarning, match="orphaned"):
            assert load_checkpoint(str(tmp_path), model=m2) == 1
        assert not any(f.startswith(".tmp_ckpt_")
                       for f in os.listdir(str(tmp_path)))

    def test_garbage_dirs_ignored(self, tmp_path):
        d, w1, w2 = _ckpt_pair(tmp_path)
        os.makedirs(os.path.join(d, "ckpt_latest"))  # non-numeric garbage
        os.makedirs(os.path.join(d, "ckpt_1x2"))
        m2 = nn.Linear(4, 2)
        with pytest.warns(RuntimeWarning, match="non-checkpoint"):
            step = load_checkpoint(d, model=m2)
        assert step == 2
        assert np.array_equal(np.asarray(m2.weight._data), w2)

    def test_all_corrupt_raises_not_silent_restart(self, tmp_path):
        d, _, _ = _ckpt_pair(tmp_path)
        for name in ("ckpt_1", "ckpt_2"):
            p = os.path.join(d, name, "model.pdparams")
            with open(p, "r+b") as f:
                f.truncate(10)
        with pytest.warns(RuntimeWarning):
            with pytest.raises(CheckpointError, match="every checkpoint"):
                load_checkpoint(d, model=nn.Linear(4, 2))

    def test_explicit_step_corrupt_raises(self, tmp_path):
        d, _, _ = _ckpt_pair(tmp_path)
        p = os.path.join(d, "ckpt_2", "model.pdparams")
        with open(p, "r+b") as f:
            f.truncate(10)
        with pytest.raises(CheckpointError):
            load_checkpoint(d, model=nn.Linear(4, 2), step=2)
        with pytest.raises(CheckpointError, match="no checkpoint for step"):
            load_checkpoint(d, step=99)

    def test_malformed_but_valid_json_manifest_falls_back(self, tmp_path):
        """A bit-flip can leave manifest.json parseable with a broken
        shape: that must read as 'corrupt checkpoint' (fallback), not
        crash the loader with KeyError."""
        import json

        d, w1, _ = _ckpt_pair(tmp_path)
        mpath = os.path.join(d, "ckpt_2", "manifest.json")
        with open(mpath, "w") as f:
            json.dump({"files": {"model.pdparams": {"siz": 1}}}, f)
        m2 = nn.Linear(4, 2)
        with pytest.warns(RuntimeWarning, match="falling back"):
            assert load_checkpoint(d, model=m2) == 1
        assert np.array_equal(np.asarray(m2.weight._data), w1)

    def test_deep_verify_catches_array_level_edit(self, tmp_path):
        """verify_checkpoint's deep pass checks per-array crcs, so even a
        file whose file-level digest was regenerated around an edited
        array is caught and the culprit array is named."""
        import binascii
        import pickle

        d, _, _ = _ckpt_pair(tmp_path)
        p = os.path.join(d, "ckpt_2", "model.pdparams")
        with open(p, "rb") as f:
            state = pickle.load(f)
        key = sorted(state)[0]
        state[key] = state[key] + 1.0  # tampered array
        blob = pickle.dumps(state, protocol=4)
        with open(p, "wb") as f:
            f.write(blob)
        mpath = os.path.join(d, "ckpt_2", "manifest.json")
        import json

        with open(mpath) as f:
            manifest = json.load(f)
        manifest["files"]["model.pdparams"] = {  # regenerated file digest
            "size": len(blob),
            "crc32": binascii.crc32(blob) & 0xFFFFFFFF}
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        ok, problems = verify_checkpoint(os.path.join(d, "ckpt_2"))
        assert not ok and "per-array checksum mismatch" in problems[0]

    def test_rotation_survives_garbage(self, tmp_path):
        d = str(tmp_path)
        os.makedirs(os.path.join(d, "ckpt_latest"))
        m = nn.Linear(2, 2)
        for s in range(1, 6):
            save_checkpoint(d, s, model=m, keep_last=2)
        kept = sorted(f for f in os.listdir(d) if f.startswith("ckpt_")
                      and f[5:].isdigit())
        assert kept == ["ckpt_4", "ckpt_5"]


# -- DataLoader worker faults ------------------------------------------------


class _Sq(Dataset):
    def __init__(self, n=16):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.float32(i * i)


class TestLoaderWorkerRecovery:
    def _collect(self, **kw):
        dl = DataLoader(_Sq(), batch_size=4, num_workers=2,
                        return_list=False, **kw)
        return [np.asarray(b) for b in dl]

    def test_dead_worker_restarts_and_order_holds(self):
        clean = self._collect()
        with inject.chaos("loader_worker", at=2):
            faulted = self._collect()
        assert len(faulted) == len(clean) == 4
        assert all(np.array_equal(a, b) for a, b in zip(clean, faulted))

    def test_budget_exhausted_surfaces_error_no_hang(self):
        t0 = time.monotonic()
        with inject.chaos("loader_worker", at=1, times=100):
            with pytest.raises(inject.WorkerCrashChaos):
                self._collect(max_worker_restarts=1)
        assert time.monotonic() - t0 < 30  # surfaced, did not hang

    def test_deterministic_bad_sample_still_raises(self):
        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                if i == 2:
                    raise ValueError("bad sample")
                return np.float32(i)

        dl = DataLoader(Bad(), batch_size=1, num_workers=2)
        with pytest.raises(ValueError, match="bad sample"):
            list(dl)

    def test_shutdown_joins_workers(self):
        before = threading.active_count()
        for _ in range(3):
            dl = DataLoader(_Sq(64), batch_size=4, num_workers=4)
            it = iter(dl)
            next(it)
            it.close()  # abandon mid-epoch: generator finally -> shutdown
        deadline = time.monotonic() + 10
        while threading.active_count() > before and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before, \
            "abandoned DataLoader iterators leaked worker threads"


class TestDevicePrefetcherRecovery:
    """Fault paths of the PR-6 double-buffered device feed
    (io_.dataloader.DevicePrefetcher), extending the PR-2 worker-fault
    contract to the device_put stage: errors surface IN BATCH ORDER,
    and shutdown never hangs or leaks the feeder thread."""

    def test_upstream_raise_mid_prefetch_surfaces_in_order(self):
        from paddle_tpu.io_.dataloader import prefetch_to_device

        def src():
            yield {"x": np.zeros(2, np.float32)}
            yield {"x": np.ones(2, np.float32)}
            raise RuntimeError("decoder blew up mid-prefetch")

        it = prefetch_to_device(src(), depth=2)
        assert float(np.asarray(next(it)["x"])[0]) == 0.0
        assert float(np.asarray(next(it)["x"])[0]) == 1.0
        with pytest.raises(RuntimeError, match="mid-prefetch"):
            next(it)
        with pytest.raises(StopIteration):  # dead stage stays dead
            next(it)

    def test_device_put_failure_surfaces_in_order(self):
        """A transfer-stage failure (here: a sharding callable that
        rejects batch 1) arrives at batch 1's position — batch 0, which
        was prefetched before it, still arrives first."""
        from paddle_tpu.io_.dataloader import prefetch_to_device

        calls = []

        def bad_sharding(batch):
            import jax

            calls.append(1)
            if len(calls) == 2:
                raise ValueError("device_put rejected layout")
            return jax.device_put(batch)

        src = [{"x": np.full(2, i, np.float32)} for i in range(4)]
        it = prefetch_to_device(src, shardings=bad_sharding, depth=2)
        first = next(it)
        assert float(np.asarray(first["x"])[0]) == 0.0
        with pytest.raises(ValueError, match="rejected layout"):
            for _ in range(3):
                next(it)

    def test_shutdown_mid_stream_no_hang_no_leak(self):
        from paddle_tpu.io_.dataloader import DevicePrefetcher

        before = threading.active_count()
        t0 = time.monotonic()
        for _ in range(3):
            # unbounded source + tiny queue: the feeder is guaranteed
            # to be BLOCKED on a full queue when shutdown fires
            def src():
                i = 0
                while True:
                    yield {"x": np.full(2, i, np.float32)}
                    i += 1

            pf = DevicePrefetcher(src(), depth=1)
            next(pf)
            pf.shutdown()
            pf.shutdown()  # idempotent
        assert time.monotonic() - t0 < 10, "shutdown hung"
        deadline = time.monotonic() + 10
        while threading.active_count() > before and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before, \
            "DevicePrefetcher leaked feeder threads"

    def test_generator_wrapper_cleans_up_on_consumer_raise(self):
        from paddle_tpu.io_.dataloader import prefetch_to_device

        before = threading.active_count()
        src = [{"x": np.zeros(2, np.float32)}] * 100
        with pytest.raises(KeyError):
            for batch in prefetch_to_device(src, depth=2):
                raise KeyError("consumer failed")  # finally -> shutdown
        deadline = time.monotonic() + 10
        while threading.active_count() > before and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before

    def test_empty_source_terminates(self):
        from paddle_tpu.io_.dataloader import prefetch_to_device

        assert list(prefetch_to_device([], depth=2)) == []


# -- activation plumbing -----------------------------------------------------


class TestChaosPlumbing:
    def test_context_manager_scopes_activation(self):
        assert not inject.ACTIVE
        with inject.chaos("transient_compile", times=1):
            assert "transient_compile" in inject.ACTIVE
        assert not inject.ACTIVE

    def test_env_var_activation(self):
        pts = inject.install_from_env(
            "transient_compile:times=2; nan_feed:at=3,seed=1,var=x")
        try:
            assert sorted(pts) == ["nan_feed", "transient_compile"]
            assert inject.ACTIVE["transient_compile"].times == 2
            assert inject.ACTIVE["nan_feed"].cfg["var"] == "x"
        finally:
            inject.clear()
        assert not inject.ACTIVE

    def test_unknown_point_rejected(self):
        with pytest.raises(KeyError, match="unknown chaos point"):
            with inject.chaos("nonexistent"):
                pass
        with pytest.raises(KeyError):
            inject.install_from_env("nonexistent:times=1")

    def test_every_injector_is_deterministic_hit_counted(self):
        with inject.chaos("transient_compile", at=2, times=1) as inj:
            inj_fire = lambda: inject.fire("transient_compile")  # noqa: E731
            inj_fire()  # hit 1: below `at`
            with pytest.raises(inject.TransientChaosError):
                inj_fire()  # hit 2: fires
            inj_fire()  # hit 3: budget (times=1) spent
            assert inj.hits == 3 and inj.fired == 1

    def test_nan_feed_budget_survives_uncorruptible_hits(self):
        """A hit whose feed has no corruptible target (name typo,
        int-only feed) must NOT consume the firing budget — the fault
        still lands on the next eligible feed."""
        with inject.chaos("nan_feed", var="X_typo", times=1) as inj:
            out = inject.fire("nan_feed", {"x": np.ones(3, np.float32)})
            assert np.isfinite(out["x"]).all() and inj.fired == 0
            out = inject.fire("nan_feed", {"i": np.arange(3)})  # int-only
            assert inj.fired == 0
        with inject.chaos("nan_feed", times=1) as inj:
            out = inject.fire("nan_feed", {"i": np.arange(3)})  # int-only
            assert inj.fired == 0
            out = inject.fire("nan_feed", {"x": np.ones(3, np.float32)})
            assert inj.fired == 1 and np.isnan(out["x"]).sum() == 1

    def test_disabled_chaos_leaves_hot_path_alone(self):
        """Injection fully disabled => the Executor hook is one empty-dict
        test and the dispatcher hook is None (no per-step host sync)."""
        assert not inject.ACTIVE
        from paddle_tpu.core import dispatch

        assert dispatch._chaos_op_hook is None
        with inject.chaos("nan_op"):
            assert dispatch._chaos_op_hook is not None
        assert dispatch._chaos_op_hook is None
