"""Cluster topology model, filesystem wrappers, cloud env helpers
(ref: python/paddle/distributed/{utils,fs_wrapper,cloud_utils,
launch_ps}.py) — the launch-script support surface.
"""
import os

import pytest

from paddle_tpu.dist.utils import (Cluster, Pod, Trainer, add_arguments,
                                   find_free_ports, get_cluster,
                                   get_host_name_ip)
from paddle_tpu.dist.fs_wrapper import FS, BDFS, LocalFS
from paddle_tpu.dist import cloud_utils, launch_ps


class TestClusterModel:
    def test_get_cluster_topology(self):
        ips = ["10.0.0.1", "10.0.0.2"]
        cluster, pod = get_cluster(ips, "10.0.0.2", [6170, 6171], [0, 1])
        assert cluster.pods_nranks() == 2
        assert cluster.trainers_nranks() == 4
        assert pod.rank == 1 and pod.addr == "10.0.0.2"
        eps = cluster.trainers_endpoints()
        assert eps[0] == "10.0.0.1:6170" and eps[-1] == "10.0.0.2:6171"
        assert [t.rank for p in cluster.pods for t in p.trainers] == \
            [0, 1, 2, 3]
        assert cluster.get_pod_by_id(0).addr == "10.0.0.1"
        # equality is structural
        c2, _ = get_cluster(ips, "10.0.0.1", [6170, 6171], [0, 1])
        assert cluster == c2
        c3, _ = get_cluster(ips, "10.0.0.1", [7000, 7001], [0, 1])
        assert cluster != c3
        assert pod.get_visible_gpus() == "0,1"

    def test_free_ports_and_host(self):
        ports = find_free_ports(3)
        assert len(ports) == 3
        hn = get_host_name_ip()
        assert hn is None or len(hn) == 2

    def test_add_arguments_bool(self):
        import argparse

        p = argparse.ArgumentParser()
        add_arguments("use_thing", bool, False, "a flag", p)
        assert p.parse_args(["--use_thing", "True"]).use_thing is True
        assert p.parse_args(["--use_thing", "0"]).use_thing is False


class TestFS:
    def test_local_fs_roundtrip(self, tmp_path):
        fs = LocalFS()
        d = str(tmp_path / "a")
        fs.mkdir(d)
        assert fs.stat(d) and fs.list_dirs(str(tmp_path)) == ["a"]
        f = str(tmp_path / "a" / "x.txt")
        open(f, "w").write("hi")
        assert "x.txt" in fs.ls_dir(d)
        fs.mv(f, str(tmp_path / "a" / "y.txt"))
        fs.download(d, str(tmp_path / "b"))
        assert open(tmp_path / "b" / "y.txt").read() == "hi"
        fs.delete(str(tmp_path / "a" / "y.txt"))
        fs.delete(d)
        assert not fs.stat(d)
        assert not fs.need_upload_download()
        assert isinstance(fs, FS)

    def test_bdfs_descope(self):
        with pytest.raises(NotImplementedError):
            BDFS()


class TestCloudUtils:
    def test_env_driven_cluster(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINERS", "10.1.1.1,10.1.1.2")
        monkeypatch.setenv("POD_IP", "10.1.1.2")
        monkeypatch.setenv("PADDLE_PORT", "7100")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        cluster, pod = cloud_utils.get_cloud_cluster(selected_gpus=[0])
        assert cluster.pods_nranks() == 2 and pod.addr == "10.1.1.2"
        assert cluster.trainers_endpoints()[0] == "10.1.1.1:7100"
        assert cloud_utils.get_trainers_num() == 2

    def test_defaults_without_env(self, monkeypatch):
        for k in ("PADDLE_TRAINERS", "POD_IP", "PADDLE_PORT",
                  "PADDLE_TRAINERS_NUM"):
            monkeypatch.delenv(k, raising=False)
        cluster, pod = cloud_utils.get_cloud_cluster()
        assert cluster.pods_nranks() == 1
        assert cloud_utils.get_trainers_num() == 1


def test_launch_ps_descope():
    with pytest.raises(NotImplementedError):
        launch_ps.launch()


def test_alias_spellings():
    import importlib

    a = importlib.import_module("paddle_tpu.distributed.utils")
    b = importlib.import_module("paddle_tpu.dist.utils")
    assert a is b
    importlib.import_module("paddle_tpu.distributed.fs_wrapper")
    importlib.import_module("paddle_tpu.distributed.cloud_utils")


def test_review_regressions(tmp_path, monkeypatch):
    """r5 review fixes: upload copies (source survives), port/trainer
    mismatch gets a clear assertion, stray POD_IP without the env node
    list doesn't crash, and termination reaps processes."""
    import subprocess
    import sys

    fs = LocalFS()
    src = tmp_path / "ckpt.bin"
    src.write_text("weights")
    fs.upload(str(src), str(tmp_path / "up.bin"))
    assert src.exists()  # copy, not rename
    assert (tmp_path / "up.bin").read_text() == "weights"

    with pytest.raises(AssertionError, match="one port per trainer"):
        get_cluster(["127.0.0.1"], "127.0.0.1", [6170], [0, 1])

    for k in ("PADDLE_TRAINERS", "PADDLE_PORT"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("POD_IP", "10.9.9.9")  # k8s noise, no env list
    cluster, pod = cloud_utils.get_cloud_cluster()
    assert pod.addr == "127.0.0.1"

    from paddle_tpu.dist.utils import terminate_local_procs

    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(60)"])
    terminate_local_procs([proc])
    assert proc.poll() is not None  # reaped, no zombie


def test_eq_tolerates_foreign_types_and_pod_ip_required(monkeypatch):
    assert Trainer() != None  # noqa: E711  (NotImplemented -> False)
    assert Pod() != "x"
    assert Cluster() != None  # noqa: E711
    monkeypatch.setenv("PADDLE_TRAINERS", "10.0.0.1,10.0.0.2")
    monkeypatch.delenv("POD_IP", raising=False)
    with pytest.raises(ValueError, match="POD_IP"):
        cloud_utils.get_cloud_cluster()


def test_launch_helper_functions(monkeypatch):
    """ref launch.py helpers: get_gpus resolves against visible devices;
    get_cluster_from_args builds the topology from parsed args."""
    import types

    from paddle_tpu.dist.launch import get_cluster_from_args, get_gpus

    monkeypatch.delenv("CUDA_VISIBLE_DEVICES", raising=False)
    monkeypatch.delenv("TPU_VISIBLE_DEVICES", raising=False)
    assert get_gpus("0,2") == [0, 2]
    monkeypatch.setenv("CUDA_VISIBLE_DEVICES", "2,0")
    assert get_gpus("0,2") == [1, 0]  # remapped to visible indices
    with pytest.raises(ValueError):
        get_gpus("7")

    args = types.SimpleNamespace(cluster_node_ips="10.0.0.1,10.0.0.2",
                                 node_ip="10.0.0.2", started_port=7000)
    cluster, pod = get_cluster_from_args(args, [0, 1])
    assert cluster.trainers_nranks() == 4 and pod.addr == "10.0.0.2"

    # this module's own --ips spelling works too, node from node_rank
    args2 = types.SimpleNamespace(ips="10.0.0.1,10.0.0.2", node_rank=1,
                                  started_port=7000)
    _, pod2 = get_cluster_from_args(args2, [0])
    assert pod2.addr == "10.0.0.2"
    # selected_gpus=None enumerates the visible devices
    monkeypatch.setenv("CUDA_VISIBLE_DEVICES", "0,1,2")
    assert get_gpus(None) == [0, 1, 2]
    cluster3, _ = get_cluster_from_args(args2, None)
    assert cluster3.trainers_nranks() == 6  # 2 nodes x 3 devices
    # unknown node ip raises with context, not a bare index error
    bad = types.SimpleNamespace(ips="10.0.0.1", node_ip="9.9.9.9")
    with pytest.raises(ValueError, match="node list"):
        get_cluster_from_args(bad, [0])
    with pytest.raises(ValueError, match="ips"):
        get_cluster_from_args(types.SimpleNamespace(), [0])
