"""Debug dump tests: program -> graphviz dot, jaxpr/HLO dumps
(ref: fluid/graphviz.py, debugger.py draw_block_graphviz)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.models.vision import LeNet
from paddle_tpu.utils.debug import (program_to_dot, draw_program,
                                    dump_jaxpr, dump_hlo)


def _lenet_program():
    pt.seed(0)
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [4, 1, 28, 28], "float32")
            loss = F.cross_entropy(LeNet()(x),
                                   pt.static.data("y", [4], "int64"))
    finally:
        pt.disable_static()
    return main


def test_program_to_dot_structure():
    dot = program_to_dot(_lenet_program())
    assert dot.startswith("digraph")
    assert '"v_x"' in dot and "conv2d" in dot
    assert "->" in dot and dot.rstrip().endswith("}")


def test_draw_program_writes_dot(tmp_path):
    p = draw_program(_lenet_program(), str(tmp_path / "lenet.dot"))
    text = open(p).read()
    assert "digraph" in text and "shape=box" in text


def test_dump_jaxpr_layer(tmp_path):
    model = LeNet()
    model.eval()
    x = np.zeros((2, 1, 28, 28), "float32")
    path = str(tmp_path / "lenet.jaxpr")
    text = dump_jaxpr(model, x, path=path)
    assert "conv_general_dilated" in text
    assert open(path).read() == text


def test_dump_hlo_function():
    def f(a, b):
        return (a * b).sum()

    text = dump_hlo(f, np.ones((4, 4), "float32"),
                    np.ones((4, 4), "float32"))
    assert "HloModule" in text or "module" in text
