"""Distributed tests on the virtual 8-device CPU mesh (SURVEY §4):
collectives, DP parity vs single-device, TP parity, ring attention vs
dense, MoE, pipeline parity."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optim as optim
import paddle_tpu.nn.functional as F
from paddle_tpu import distributed as dist


@pytest.fixture(autouse=True)
def _mesh_reset():
    yield
    dist.set_mesh(None)


def _require8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")


def _require_partial_manual():
    from paddle_tpu.dist import pipeline as _pipe

    if not _pipe.partial_manual_supported():
        pytest.skip("partial-manual shard_map (manual pipe ring + auto "
                    "tp axis) is unsupported on this jax/XLA line")


class TestMesh:
    def test_init_mesh_infer(self):
        _require8()
        m = dist.init_mesh({"data": 2, "model": -1})
        assert m.shape == {"data": 2, "model": 4}
        assert dist.mesh_axis_size("model") == 4

    def test_init_mesh_bad_product(self):
        _require8()
        with pytest.raises(ValueError):
            dist.init_mesh({"data": 3})


class TestCollectives:
    def test_all_reduce_eager(self):
        _require8()
        dist.init_mesh({"data": 8})
        x = pt.to_tensor(np.arange(8, dtype="float32"))
        out = dist.all_reduce(x)
        # each shard holds 1 element; psum makes every element the sum
        np.testing.assert_allclose(out.numpy(), np.full(8, np.arange(8).sum()))

    def test_all_gather_inside_shard_map(self):
        _require8()
        m = dist.init_mesh({"data": 8})

        def f(x):
            return jax.lax.all_gather(x, "data", tiled=True)

        x = jnp.arange(8, dtype=jnp.float32)
        out = jax.shard_map(f, mesh=m, in_specs=P("data"),
                            out_specs=P("data"))(x)
        assert out.shape == (64,)

    def test_reduce_scatter(self):
        _require8()
        dist.init_mesh({"data": 8})
        x = pt.to_tensor(np.ones(64, "float32"))
        out = dist.reduce_scatter(x)
        # global length shrinks by the axis size; every element is the sum
        # of the 8 shards' contributions
        np.testing.assert_allclose(out.numpy(), np.full(8, 8.0))

    def test_broadcast(self):
        _require8()
        dist.init_mesh({"data": 8})
        x = pt.to_tensor(np.arange(8, dtype="float32"))
        out = dist.broadcast(x, src=3)
        np.testing.assert_allclose(out.numpy(), np.full(8, 3.0))

    def test_ppermute_ring(self):
        _require8()
        dist.init_mesh({"data": 8})
        x = pt.to_tensor(np.arange(8, dtype="float32"))
        perm = [(i, (i + 1) % 8) for i in range(8)]
        out = dist.ppermute(x, perm)
        np.testing.assert_allclose(out.numpy(), np.roll(np.arange(8), 1))


class TestDataParallel:
    def test_dp_matches_single_device(self):
        _require8()
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype("float32")
        Y = (X @ rng.randn(8, 1)).astype("float32")

        def build():
            pt.seed(5)
            m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
            o = optim.Adam(0.05, parameters=m.parameters())
            return m, o

        # single-device fused baseline
        m1, o1 = build()
        s1 = pt.TrainStep(m1, o1, lambda m, x, y: F.mse_loss(m(x), y))
        base = [float(s1(X, Y)) for _ in range(5)]

        # 8-way data parallel
        mesh = dist.init_mesh({"data": 8})
        m2, o2 = build()  # pt.seed(5) makes init identical to m1's
        s2 = dist.DistributedTrainStep(m2, o2,
                                       lambda m, x, y: F.mse_loss(m(x), y),
                                       mesh=mesh)
        got = [float(s2(X, Y)) for _ in range(5)]
        np.testing.assert_allclose(got, base, rtol=2e-3)

    def test_dataparallel_wrapper_identity(self):
        m = nn.Linear(4, 2)
        w = dist.DataParallel(m)
        x = pt.to_tensor(np.ones((3, 4), "float32"))
        np.testing.assert_allclose(w(x).numpy(), m(x).numpy())
        assert "weight" in w.state_dict()


class TestTensorParallel:
    def test_column_row_parity(self):
        _require8()
        mesh = dist.init_mesh({"data": 2, "model": 4})
        rng = np.random.RandomState(1)
        x = rng.randn(6, 16).astype("float32")

        col = dist.ColumnParallelLinear(16, 32, gather_output=False)
        row = dist.RowParallelLinear(32, 8, input_is_parallel=True)

        with mesh:
            y = row(col(pt.to_tensor(x)))
        want = (x @ col.weight.numpy() + col.bias.numpy()) @ \
            row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(y.numpy(), want, rtol=1e-4, atol=1e-4)

    def test_vocab_parallel_embedding(self):
        _require8()
        mesh = dist.init_mesh({"model": 8})
        emb = dist.VocabParallelEmbedding(64, 16)
        ids = pt.to_tensor(np.array([[1, 5], [63, 0]]))
        with mesh:
            out = emb(ids)
        np.testing.assert_allclose(out.numpy(),
                                   emb.weight.numpy()[ids.numpy()], rtol=1e-5)

    def test_parallel_cross_entropy(self):
        _require8()
        mesh = dist.init_mesh({"model": 8})
        logits = np.random.RandomState(2).randn(4, 32).astype("float32")
        labels = np.array([0, 5, 31, 7])
        pce = dist.ParallelCrossEntropy()
        with mesh:
            loss = pce(pt.to_tensor(logits), pt.to_tensor(labels))
        want = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels),
                               reduction="none").numpy()
        np.testing.assert_allclose(loss.numpy(), want, rtol=1e-4)


class TestRingAttention:
    def test_matches_dense(self):
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        rng = np.random.RandomState(3)
        q = rng.randn(2, 4, 32, 16).astype("float32")
        k = rng.randn(2, 4, 32, 16).astype("float32")
        v = rng.randn(2, 4, 32, 16).astype("float32")
        out = dist.ring_attention(pt.to_tensor(q), pt.to_tensor(k),
                                  pt.to_tensor(v), axis_name="sp")
        dense = F.sdpa_bhld(pt.to_tensor(q), pt.to_tensor(k),
                            pt.to_tensor(v))
        np.testing.assert_allclose(out.numpy(), dense.numpy(), rtol=2e-3,
                                   atol=2e-3)

    def test_causal_matches_dense(self):
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        rng = np.random.RandomState(4)
        q = rng.randn(1, 2, 16, 8).astype("float32")
        out = dist.ring_attention(pt.to_tensor(q), pt.to_tensor(q),
                                  pt.to_tensor(q), axis_name="sp",
                                  causal=True)
        dense = F.sdpa_bhld(pt.to_tensor(q), pt.to_tensor(q),
                            pt.to_tensor(q), is_causal=True)
        np.testing.assert_allclose(out.numpy(), dense.numpy(), rtol=2e-3,
                                   atol=2e-3)

    def test_grad_flows(self):
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        q = pt.to_tensor(np.random.randn(1, 2, 16, 8).astype("float32"),
                         stop_gradient=False)
        out = dist.ring_attention(q, q, q, axis_name="sp")
        pt.mean(out).backward()
        assert q.grad is not None and np.isfinite(q.grad.numpy()).all()

    def test_grads_match_dense(self):
        """VALUE parity of the backward through the ppermute ring (a
        finite-but-wrong gradient would train long-context models to
        garbage while every finiteness check stays green). Weighted loss
        so dOut is non-constant; causal on to cover the masked path."""
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        rng = np.random.RandomState(5)
        qa = rng.randn(1, 2, 32, 8).astype("float32")
        ka = rng.randn(1, 2, 32, 8).astype("float32")
        va = rng.randn(1, 2, 32, 8).astype("float32")
        w = rng.randn(1, 2, 32, 8).astype("float32")

        def grads(attn_fn, **kw):
            q = pt.to_tensor(qa, stop_gradient=False)
            k = pt.to_tensor(ka, stop_gradient=False)
            v = pt.to_tensor(va, stop_gradient=False)
            out = attn_fn(q, k, v, **kw)
            (out * pt.to_tensor(w)).sum().backward()
            return [t.grad.numpy() for t in (q, k, v)]

        ring = grads(lambda q, k, v, **kw: dist.ring_attention(
            q, k, v, axis_name="sp", **kw), causal=True)
        dense = grads(F.sdpa_bhld, is_causal=True)
        for g_ring, g_dense, name in zip(ring, dense, "qkv"):
            np.testing.assert_allclose(
                g_ring, g_dense, rtol=2e-3, atol=2e-3,
                err_msg=f"d{name} diverges between ring and dense")

    def test_no_mesh_fallback(self):
        q = pt.to_tensor(np.random.randn(1, 2, 8, 4).astype("float32"))
        out = dist.ring_attention(q, q, q)
        dense = F.sdpa_bhld(q, q, q)
        np.testing.assert_allclose(out.numpy(), dense.numpy(), rtol=1e-5)


class TestMoE:
    def test_dense_moe_forward_backward(self):
        x = pt.to_tensor(np.random.RandomState(5).randn(16, 8).astype("float32"),
                         stop_gradient=False)
        moe = dist.MoEMLP(8, 16, num_experts=4)
        out = moe(x)
        assert out.shape == [16, 8]
        (pt.mean(out) + moe.aux_loss * 0.01).backward()
        assert moe.w1.grad is not None

    def test_expert_parallel_matches_dense(self):
        _require8()
        rng = np.random.RandomState(6)
        x = rng.randn(32, 8).astype("float32")
        # generous capacity: no token dropping, so group-local (EP) gating
        # and global (dense) gating agree exactly
        moe = dist.MoEMLP(8, 16, num_experts=8, capacity_factor=8.0)
        dense_out = moe(pt.to_tensor(x)).numpy()
        mesh = dist.init_mesh({"expert": 8})
        with mesh:
            ep_out = moe(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(ep_out, dense_out, rtol=2e-3, atol=2e-3)

    def test_gating_capacity(self):
        logits = jnp.asarray(np.random.RandomState(7).randn(16, 4),
                             dtype=jnp.float32)
        combine, dispatch, aux = dist.top2_gating(logits, capacity=4)
        assert combine.shape == (16, 4, 4)
        # no slot may hold more than one token
        per_slot = np.asarray(dispatch).sum(axis=0)
        assert per_slot.max() <= 1.0 + 1e-6
        assert float(aux) > 0


class TestPipeline:
    def test_pipeline_matches_sequential(self):
        _require8()
        mesh = dist.init_mesh({"pipe": 8})
        rng = np.random.RandomState(8)
        n_stages = 8
        D = 16
        Ws = rng.randn(n_stages, D, D).astype("float32") * 0.3
        bs = rng.randn(n_stages, D).astype("float32") * 0.1

        def stage_fn(params, x):
            W, b = params
            return jnp.tanh(x @ W + b)

        X = rng.randn(8, D).astype("float32")
        out = dist.pipeline_forward(stage_fn, (jnp.asarray(Ws), jnp.asarray(bs)),
                                    X, num_microbatches=4, mesh=mesh)
        want = X
        for s in range(n_stages):
            want = np.tanh(want @ Ws[s] + bs[s])
        np.testing.assert_allclose(np.asarray(out), want, rtol=2e-3,
                                   atol=2e-3)

    def test_pipeline_grads(self):
        _require8()
        mesh = dist.init_mesh({"pipe": 8})
        rng = np.random.RandomState(9)
        Ws = jnp.asarray(rng.randn(8, 8, 8).astype("float32") * 0.3)

        def stage_fn(W, x):
            return jnp.tanh(x @ W)

        X = jnp.asarray(rng.randn(4, 8).astype("float32"))

        def loss_fn(Ws):
            out = dist.pipeline_forward(stage_fn, Ws, X, num_microbatches=2,
                                        mesh=mesh)
            return jnp.mean(out ** 2)

        g = jax.grad(loss_fn)(Ws)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


class TestFleet:
    def test_fleet_init_builds_mesh(self):
        _require8()
        strat = dist.DistributedStrategy()
        strat.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        dist.fleet.init(is_collective=True, strategy=strat)
        m = dist.get_mesh()
        assert m.shape == {"data": 2, "model": 4}

    def test_distributed_optimizer_passthrough(self):
        opt = optim.SGD(0.1, parameters=nn.Linear(2, 2).parameters())
        out = dist.fleet.distributed_optimizer(opt)
        assert out is opt


class TestCollectiveReviewRegressions:
    def test_dist_function_not_shadowed(self):
        import paddle_tpu

        out = paddle_tpu.dist(pt.to_tensor(np.array([1.0, 2.0])),
                              pt.to_tensor(np.array([1.0, 4.0])), p=2)
        np.testing.assert_allclose(float(out), 2.0)

    def test_all_reduce_scalar_identity(self):
        _require8()
        dist.init_mesh({"data": 8})
        s = pt.to_tensor(np.float32(3.5))
        out = dist.all_reduce(s)
        np.testing.assert_allclose(float(out), 3.5)

    def test_all_reduce_prod_negative(self):
        _require8()
        dist.init_mesh({"data": 8})
        vals = np.array([-2, -2, 1, 1, 1, 1, 1, 1], "float32")
        out = dist.all_reduce(pt.to_tensor(vals), op=dist.ReduceOp.PROD)
        np.testing.assert_allclose(out.numpy(), np.full(8, 4.0), rtol=1e-4)

    def test_all_gather_eager_identity_and_list(self):
        _require8()
        dist.init_mesh({"data": 8})
        x = pt.to_tensor(np.arange(16, dtype="float32"))
        out = dist.all_gather(x)
        np.testing.assert_allclose(out.numpy(), np.arange(16))
        parts = []
        dist.all_gather(parts, tensor=x)
        assert len(parts) == 8 and parts[0].shape == [2]

    def test_scatter(self):
        _require8()
        dist.init_mesh({"data": 8})
        chunks = [pt.to_tensor(np.full(2, float(i), "float32"))
                  for i in range(8)]
        out = dist.scatter(pt.zeros([16]), tensor_list=chunks, src=0)
        np.testing.assert_allclose(out.numpy(),
                                   np.repeat(np.arange(8.0), 2))

    def test_sharded_opt_state(self):
        _require8()
        mesh = dist.init_mesh({"data": 8})
        m = nn.Linear(16, 8)
        o = optim.Adam(0.01, parameters=m.parameters())
        s = dist.DistributedTrainStep(m, o,
                                      lambda mm, x, y: F.mse_loss(mm(x), y),
                                      mesh=mesh, shard_opt_state=True)
        st = o._accumulators[m.weight.name]
        assert "data" in str(st["moment1"].sharding.spec)
        x = np.random.randn(16, 16).astype("float32")
        y = np.random.randn(16, 8).astype("float32")
        l0 = float(s(x, y))
        for _ in range(3):
            l1 = float(s(x, y))
        assert l1 < l0


class TestEagerCollectiveShapes:
    """VERDICT r1 item 9: non-divisible eager collectives must raise, not
    silently return the input unreduced."""

    def test_odd_leading_dim_raises(self):
        from paddle_tpu.dist import env as denv
        from paddle_tpu.dist import collective as C

        mesh = denv.init_mesh({"data": 8})
        try:
            x = pt.to_tensor(np.arange(9, dtype="float32"))
            with pytest.raises(ValueError, match="not divisible"):
                C.all_reduce(x)
        finally:
            denv.set_mesh(None)

    def test_scalar_is_identity(self):
        from paddle_tpu.dist import env as denv
        from paddle_tpu.dist import collective as C

        mesh = denv.init_mesh({"data": 8})
        try:
            x = pt.to_tensor(np.float32(3.5))
            out = C.all_reduce(x)
            assert float(out.numpy()) == 3.5
        finally:
            denv.set_mesh(None)

    def test_divisible_reduces(self):
        from paddle_tpu.dist import env as denv
        from paddle_tpu.dist import collective as C

        mesh = denv.init_mesh({"data": 8})
        try:
            x = pt.to_tensor(np.arange(8, dtype="float32"))
            out = C.all_reduce(x)
            np.testing.assert_allclose(out.numpy(), np.full(8, 28.0))
        finally:
            denv.set_mesh(None)


class TestGPTPipeline:
    """The pp leg of the 4D flagship: real GPT blocks through the GPipe
    schedule, parity vs the sequential model (SURVEY §2 #23/#38)."""

    def _model(self, layers=4):
        from paddle_tpu.models.nlp.gpt import GPT, gpt_tiny

        pt.seed(0)
        cfg = gpt_tiny(dropout=0.0)
        cfg.layers = layers
        return GPT(cfg)

    def test_forward_parity_pp2(self):
        _require8()
        from paddle_tpu.models.nlp.gpt import GPTPipeline

        model = self._model(layers=4)  # 2 blocks per stage
        model.eval()
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("pipe",))
        dist.set_mesh(mesh)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, model.cfg.vocab_size, (4, 16)).astype("int64")
        try:
            with mesh:
                pipe = GPTPipeline(model, num_microbatches=2)
                got = np.asarray(pipe(pt.to_tensor(ids)).numpy())
        finally:
            dist.set_mesh(None)
        want = np.asarray(model(pt.to_tensor(ids)).numpy())
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_forward_parity_dp2_pp2(self):
        _require8()
        from paddle_tpu.models.nlp.gpt import GPTPipeline

        model = self._model(layers=2)
        model.eval()
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ("data", "pipe"))
        dist.set_mesh(mesh)
        rng = np.random.RandomState(1)
        ids = rng.randint(0, model.cfg.vocab_size, (4, 16)).astype("int64")
        try:
            with mesh:
                pipe = GPTPipeline(model, num_microbatches=2,
                                   batch_axis="data")
                got = np.asarray(pipe(pt.to_tensor(ids)).numpy())
        finally:
            dist.set_mesh(None)
        want = np.asarray(model(pt.to_tensor(ids)).numpy())
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_train_step_loss_decreases_pp2(self):
        _require8()
        from paddle_tpu.models.nlp.gpt import GPTPipeline

        model = self._model(layers=2)
        model.eval()
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("pipe",))
        dist.set_mesh(mesh)
        rng = np.random.RandomState(2)
        ids = rng.randint(0, model.cfg.vocab_size, (4, 16)).astype("int64")
        labels = np.roll(ids, -1, axis=1)
        try:
            with mesh:
                pipe = GPTPipeline(model, num_microbatches=2)
                step = jax.jit(pipe.train_step_fn(lr=1e-1))
                stacked = pipe.stacked
                losses = []
                for _ in range(4):
                    loss, stacked = step(stacked, jnp.asarray(ids),
                                         jnp.asarray(labels))
                    losses.append(float(loss))
        finally:
            dist.set_mesh(None)
        assert np.isfinite(losses).all(), losses
        assert losses[-1] < losses[0], losses

    def test_forward_parity_dp2_tp2_pp2(self):
        """The composed 3-axis flagship (VERDICT r4 Next #3): TP-layer
        blocks inside the GPipe schedule over Mesh(('data','model','pipe'))
        — 'model' stays an auto (GSPMD) axis inside the manual
        shard_map, so the same executable carries dp + tp + pp."""
        _require8()
        _require_partial_manual()
        from paddle_tpu.models.nlp.gpt import GPTPipeline

        model = self._model(layers=2)
        model.eval()
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                    ("data", "model", "pipe"))
        dist.set_mesh(mesh)
        rng = np.random.RandomState(3)
        ids = rng.randint(0, model.cfg.vocab_size, (4, 16)).astype("int64")
        try:
            with mesh:
                pipe = GPTPipeline(model, num_microbatches=2,
                                   batch_axis="data")
                got = np.asarray(pipe(pt.to_tensor(ids)).numpy())
        finally:
            dist.set_mesh(None)
        want = np.asarray(model(pt.to_tensor(ids)).numpy())
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_train_step_dp2_tp2_pp2_one_executable(self):
        """One jitted dp2 x tp2 x pp2 train step: loss decreases AND the
        compiled HLO really carries both parallelism mechanisms —
        collective-permute (the pp ring) and all-reduce (tp partial sums
        / dp grad sync)."""
        _require8()
        _require_partial_manual()
        from paddle_tpu.models.nlp.gpt import GPTPipeline

        model = self._model(layers=2)
        model.eval()
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                    ("data", "model", "pipe"))
        dist.set_mesh(mesh)
        rng = np.random.RandomState(4)
        ids = rng.randint(0, model.cfg.vocab_size, (4, 16)).astype("int64")
        labels = np.roll(ids, -1, axis=1)
        try:
            with mesh:
                pipe = GPTPipeline(model, num_microbatches=2,
                                   batch_axis="data")
                step = jax.jit(pipe.train_step_fn(lr=1e-1))
                txt = step.lower(pipe.stacked, jnp.asarray(ids),
                                 jnp.asarray(labels)).compile().as_text()
                assert "collective-permute" in txt, "pp ring missing"
                assert "all-reduce" in txt, "tp/dp reductions missing"
                stacked = pipe.stacked
                losses = []
                for _ in range(4):
                    loss, stacked = step(stacked, jnp.asarray(ids),
                                         jnp.asarray(labels))
                    losses.append(float(loss))
        finally:
            dist.set_mesh(None)
        assert np.isfinite(losses).all(), losses
        assert losses[-1] < losses[0], losses

    def test_uneven_layers_raise(self):
        _require8()
        from paddle_tpu.models.nlp.gpt import GPTPipeline

        model = self._model(layers=3)  # 3 layers on 2 stages
        model.eval()
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("pipe",))
        dist.set_mesh(mesh)
        ids = np.zeros((2, 8), "int64")
        try:
            with mesh, pytest.raises(AssertionError):
                GPTPipeline(model, num_microbatches=2)(pt.to_tensor(ids))
        finally:
            dist.set_mesh(None)


class TestAllToAllAttention:
    """Ulysses-style sequence parallelism (dist/ulysses.py): a2a to head
    sharding, local dense attention, a2a back — must match dense."""

    def test_matches_dense(self):
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        rng = np.random.RandomState(5)
        q = rng.randn(2, 8, 32, 16).astype("float32")
        k = rng.randn(2, 8, 32, 16).astype("float32")
        v = rng.randn(2, 8, 32, 16).astype("float32")
        out = dist.all_to_all_attention(pt.to_tensor(q), pt.to_tensor(k),
                                        pt.to_tensor(v), axis_name="sp")
        dense = F.sdpa_bhld(pt.to_tensor(q), pt.to_tensor(k),
                            pt.to_tensor(v))
        np.testing.assert_allclose(out.numpy(), dense.numpy(), rtol=2e-3,
                                   atol=2e-3)

    def test_causal_and_grads(self):
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        q = pt.to_tensor(np.random.RandomState(6)
                         .randn(1, 8, 16, 8).astype("float32"),
                         stop_gradient=False)
        out = dist.all_to_all_attention(q, q, q, axis_name="sp",
                                        causal=True)
        dense = F.sdpa_bhld(q, q, q, is_causal=True)
        np.testing.assert_allclose(out.numpy(), dense.numpy(), rtol=2e-3,
                                   atol=2e-3)
        pt.mean(out).backward()
        assert q.grad is not None and np.isfinite(q.grad.numpy()).all()

    def test_grads_match_dense(self):
        """VALUE parity of the backward through both all-to-alls (same
        rationale as the ring grad-parity test)."""
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        rng = np.random.RandomState(7)
        qa = rng.randn(1, 8, 32, 8).astype("float32")
        ka = rng.randn(1, 8, 32, 8).astype("float32")
        va = rng.randn(1, 8, 32, 8).astype("float32")
        w = rng.randn(1, 8, 32, 8).astype("float32")

        def grads(attn_fn, **kw):
            q = pt.to_tensor(qa, stop_gradient=False)
            k = pt.to_tensor(ka, stop_gradient=False)
            v = pt.to_tensor(va, stop_gradient=False)
            out = attn_fn(q, k, v, **kw)
            (out * pt.to_tensor(w)).sum().backward()
            return [t.grad.numpy() for t in (q, k, v)]

        a2a = grads(lambda q, k, v, **kw: dist.all_to_all_attention(
            q, k, v, axis_name="sp", **kw), causal=True)
        dense = grads(F.sdpa_bhld, is_causal=True)
        for g_a, g_d, name in zip(a2a, dense, "qkv"):
            np.testing.assert_allclose(
                g_a, g_d, rtol=2e-3, atol=2e-3,
                err_msg=f"d{name} diverges between a2a and dense")

    def test_head_divisibility_error(self):
        _require8()
        mesh = dist.init_mesh({"sp": 8})
        q = pt.to_tensor(np.random.randn(1, 4, 16, 8).astype("float32"))
        try:
            dist.all_to_all_attention(q, q, q, axis_name="sp")
            raise AssertionError("expected ValueError")
        except ValueError as e:
            assert "divisible" in str(e)

    def test_no_mesh_fallback(self):
        q = pt.to_tensor(np.random.randn(1, 2, 8, 4).astype("float32"))
        out = dist.all_to_all_attention(q, q, q)
        dense = F.sdpa_bhld(q, q, q)
        np.testing.assert_allclose(out.numpy(), dense.numpy(), rtol=1e-5)


class TestShardedFusedDecode:
    def test_tp_sharded_generate_xla_parity(self):
        """The single-executable GPT decode under a ('data','model')
        mesh (tensor-parallel serving) must produce the same tokens as
        the unsharded decode — GSPMD shards the QKV/FFN projections per
        the Column/RowParallel constraints inside the one executable."""
        _require8()
        from paddle_tpu.models.nlp.gpt import GPT, gpt_tiny

        cfg = gpt_tiny(dropout=0.0)
        pt.seed(7)
        model = GPT(cfg)
        model.eval()
        ids = np.random.RandomState(4).randint(
            0, cfg.vocab_size, (2, 8)).astype("int64")
        base = np.asarray(model.generate_xla(
            ids, max_new_tokens=6, temperature=0.0).numpy())
        mesh = dist.init_mesh({"data": 2, "model": 4})
        try:
            with mesh:
                sharded = np.asarray(model.generate_xla(
                    ids, max_new_tokens=6, temperature=0.0).numpy())
        finally:
            dist.set_mesh(None)
        np.testing.assert_array_equal(base, sharded)
        # mesh is part of the executable identity: two cache entries
        assert len(model._xla_gen_cache) == 2
