"""paddle_tpu.serving (PR 7): paged KV cache, ragged paged decode
attention, continuous-batching scheduler, ServeEngine.

Covers the PR's acceptance contract:
- paged decode attention matches the dense reference within fp32
  tolerance on ragged batches (varying lengths, page-boundary
  crossings), in interpret mode under JAX_PLATFORMS=cpu;
- scheduler tests are deterministic (injectable clock): admission
  under a token budget, preemption/requeue under page pressure, and
  no-starvation are asserted exactly;
- the KV pool buffer is donated across decode steps and the allocator
  never leaks pages — alloc==free after a chaos-killed request;
- serving.* histograms report sane p50/p99;
- journal request records carry the full lifecycle.
"""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu import obs
from paddle_tpu.obs import journal, metrics
from paddle_tpu.ops.pallas.paged_attention import (dense_decode_reference,
                                                   paged_decode_attention)
from paddle_tpu.serving import (CANCELLED, FINISHED, ManualClock,
                                PagedKVCache, PageAllocationError,
                                Request, Scheduler, ServeEngine, TinyLM)
from paddle_tpu.serving.kv_cache import CachePressureError


@pytest.fixture(autouse=True)
def _no_global_journal():
    yield
    if journal.ACTIVE is not None:
        journal.ACTIVE.close()
    journal.ACTIVE = None


# -- kv cache ----------------------------------------------------------------


class TestPagedKVCache:
    def test_alloc_extend_free_accounting(self):
        c = PagedKVCache(9, 4, 2, 8)
        assert c.alloc("a", 5) == [1, 2]          # lowest-id-first
        assert c.alloc("b", 4) == [3]
        assert c.extend("a", 1) == []             # 6th token: page 2
        assert c.extend("a", 3) == [4]            # 9th token: new page
        st = c.stats()
        assert st["used_pages"] == 4 and st["free_pages"] == 4
        assert st["tokens"] == 13
        assert c.free("a") == 3 and c.free("b") == 1
        assert c.free("ghost") == 0               # idempotent teardown
        st = c.stats()
        assert st["used_pages"] == 0 and st["free_pages"] == 8
        assert c.verify()

    def test_fragmentation_stats(self):
        c = PagedKVCache(9, 8, 1, 1)
        c.alloc("a", 9)                           # 2 pages, 9/16 used
        st = c.stats()
        assert st["utilization"] == pytest.approx(9 / 16)
        assert st["fragmentation"] == pytest.approx(7 / 16)

    def test_exhaustion_is_all_or_nothing(self):
        c = PagedKVCache(6, 4, 1, 1)              # 5 usable pages
        c.alloc("a", 8)                           # 2 pages
        c.alloc("b", 12)                          # 3 pages -> 0 free
        with pytest.raises(PageAllocationError):
            c.alloc("c", 4)
        # the failed alloc held NOTHING
        assert c.stats()["free_pages"] == 0 and "c" not in c.sequences()
        with pytest.raises(PageAllocationError):
            c.extend("a", 1)                      # page 2 full at 8
        assert c.length("a") == 8                 # length unchanged

    def test_null_page_reserved_and_tables_padded(self):
        c = PagedKVCache(4, 4, 1, 1)
        pages = c.alloc("a", 4)
        assert c.NULL_PAGE == 0 and 0 not in pages
        t = c.padded_page_tables(["a"], width=3)
        assert t.tolist() == [[pages[0], 0, 0]]
        assert t.dtype == np.int32

    def test_write_slots_address_the_newest_token(self):
        c = PagedKVCache(8, 4, 1, 1)
        c.alloc("a", 4)
        c.extend("a", 1)                          # token 5 -> page[1], off 0
        pages, offs = c.write_slots(["a"])
        assert offs[0] == 0 and pages[0] == c.page_table("a")[1]

    def test_max_seq_len_enforced(self):
        c = PagedKVCache(4, 4, 1, 1, max_seq_len=8)
        with pytest.raises(ValueError):
            c.alloc("a", 9)
        c.alloc("a", 8)
        with pytest.raises(ValueError):
            c.extend("a", 1)

    def test_max_seq_len_cannot_exceed_pool_capacity(self):
        # advertising more than the pool holds would defeat the
        # engine's submit-time oversize rejection (permanent FIFO stall)
        with pytest.raises(ValueError):
            PagedKVCache(4, 4, 1, 1, max_seq_len=64)

    def test_engine_rejects_mismatched_scheduler_cache(self):
        model = TinyLM(num_heads=2, head_dim=8)
        a = PagedKVCache(8, 4, 2, 8)
        b = PagedKVCache(8, 4, 2, 8)
        with pytest.raises(ValueError):
            ServeEngine(model, a, scheduler=Scheduler(b))


# -- paged decode attention kernel -------------------------------------------


class TestPagedDecodeAttention:
    @pytest.mark.parametrize("lengths", [
        [1, 7, 8, 23],     # ragged: single token, page-1 edge, crossing
        [16, 16, 16, 16],  # uniform, exact page multiples
        [3, 40, 9, 1],     # long vs short mix
    ])
    def test_matches_dense_reference_on_ragged_batches(self, lengths):
        rng = np.random.RandomState(0)
        B, H, D, page, P = len(lengths), 2, 16, 8, 32
        maxp = 5
        lengths = np.asarray(lengths, np.int32)
        L = maxp * page
        k_dense = rng.randn(B, L, H, D).astype(np.float32)
        v_dense = rng.randn(B, L, H, D).astype(np.float32)
        q = rng.randn(B, H, D).astype(np.float32)
        k_pages = np.zeros((P, page, H, D), np.float32)
        v_pages = np.zeros((P, page, H, D), np.float32)
        table = np.zeros((B, maxp), np.int32)
        free = list(rng.permutation(np.arange(1, P)))  # shuffled pages
        for b in range(B):
            for p in range(-(-int(lengths[b]) // page)):
                pid = free.pop()
                table[b, p] = pid
                lo = p * page
                hi = min(lo + page, int(lengths[b]))
                k_pages[pid, :hi - lo] = k_dense[b, lo:hi]
                v_pages[pid, :hi - lo] = v_dense[b, lo:hi]
        out = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), jnp.asarray(lengths), interpret=True)
        ref = dense_decode_reference(
            jnp.asarray(q), jnp.asarray(k_dense), jnp.asarray(v_dense),
            jnp.asarray(lengths))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_zero_length_lane_yields_zeros(self):
        # padded batch lanes (length 0, null-page table) must not NaN
        q = jnp.ones((1, 2, 8), jnp.float32)
        kp = jnp.ones((4, 4, 2, 8), jnp.float32)
        out = paged_decode_attention(
            q, kp, kp, jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((1,), jnp.int32), interpret=True)
        assert np.all(np.asarray(out) == 0.0)


# -- scheduler (deterministic under ManualClock) -----------------------------


class TestScheduler:
    def _mk(self, pages=4, page_size=4, budget=8):
        clock = ManualClock()
        cache = PagedKVCache(pages, page_size, 1, 1)
        return clock, cache, Scheduler(cache, token_budget=budget,
                                       clock=clock)

    def test_admission_under_token_budget_is_exact(self):
        clock, cache, s = self._mk(pages=16, budget=10)
        reqs = [s.submit(Request(prompt=[1] * 4, rid=f"r{i}"))
                for i in range(4)]
        clock.advance(5.0)
        b = s.schedule()
        # 10-token budget: two 4-token prefills fit, the third blocks
        assert [r.rid for r in b.prefills] == ["r0", "r1"]
        assert s.queue_depth == 2
        assert all(r.admit_t == 5.0 for r in b.prefills)
        assert reqs[2].admit_t is None
        # next step: 2 decodes (2 tokens) + r2's prefill (4) fit in 10
        b2 = s.schedule()
        assert [r.rid for r in b2.decodes] == ["r0", "r1"]
        assert [r.rid for r in b2.prefills] == ["r2", "r3"]

    def test_fifo_head_never_skipped(self):
        clock, cache, s = self._mk(pages=16, budget=6)
        s.submit(Request(prompt=[1] * 8, rid="big"))
        s.submit(Request(prompt=[1] * 2, rid="small"))
        b = s.schedule()
        # strict FIFO: the 8-token head exceeds budget 6, and the
        # 2-token request must NOT jump the line (starvation guarantee)
        assert not b.prefills and s.queue_depth == 2

    def test_preemption_requeues_by_arrival_and_balances_pool(self):
        clock, cache, s = self._mk(pages=4, budget=8)
        r1 = s.submit(Request(prompt=[1] * 4, rid="r1"))
        clock.advance(1.0)
        r2 = s.submit(Request(prompt=[1] * 4, rid="r2"))
        clock.advance(1.0)
        r3 = s.submit(Request(prompt=[1] * 4, rid="r3"))
        s.schedule()                              # admits r1, r2
        s.extend(r1, 1)                           # takes the last page
        with pytest.raises(CachePressureError):
            s.extend(r2, 1)
        assert s.preempt_for(r2) is None          # r1 (oldest) protected
        s.preempt(r2)
        assert r2.state == "PREEMPTED" and r2.preemptions == 1
        assert [r.rid for r in s._queue] == ["r2", "r3"]
        s.finish(r1)
        assert [r.rid for r in s.schedule().prefills] == ["r2", "r3"]
        s.finish(r2)
        s.finish(r3)
        assert cache.stats()["used_pages"] == 0 and cache.verify()

    def test_preempt_for_picks_youngest_not_oldest(self):
        clock, cache, s = self._mk(pages=16, budget=64)
        reqs = [s.submit(Request(prompt=[1] * 4, rid=f"r{i}"))
                for i in range(3)]
        s.schedule()
        victim = s.preempt_for(reqs[0])
        assert victim is reqs[2]                  # youngest admitted
        assert reqs[2].state == "PREEMPTED"
        assert s.running == [reqs[0], reqs[1]]

    def test_queue_depth_gauge_tracks(self):
        clock, cache, s = self._mk(pages=16)
        g = metrics.gauge("serving.queue_depth")
        s.submit(Request(prompt=[1, 2]))
        s.submit(Request(prompt=[1, 2]))
        assert g.value == 2
        s.schedule()
        assert g.value == 0


# -- engine ------------------------------------------------------------------


def _pressured_engine(seed=0, pages=6, page_size=4, max_seq_len=16,
                      budget=64):
    model = TinyLM(vocab_size=32, num_heads=2, head_dim=8, seed=seed)
    cache = PagedKVCache(pages, page_size, 2, 8, max_seq_len=max_seq_len)
    clock = ManualClock()
    eng = ServeEngine(model, cache, scheduler=Scheduler(
        cache, token_budget=budget, clock=clock))
    return model, cache, clock, eng


class TestServeEngine:
    def test_matches_dense_oracle_token_for_token(self):
        model, cache, clock, eng = _pressured_engine(pages=64,
                                                     max_seq_len=64)
        rng = np.random.RandomState(1)
        pairs = []
        for _ in range(5):
            prompt = list(rng.randint(0, 32, rng.randint(3, 20)))
            pairs.append((eng.submit(prompt, max_new_tokens=10), prompt))
            clock.advance(0.01)
        eng.run()
        assert len(eng.finished) == 5
        for r, prompt in pairs:
            assert r.generated == model.reference_generate(prompt, 10)

    def test_correct_under_preemption_and_pool_balances(self):
        model, cache, clock, eng = _pressured_engine()
        rng = np.random.RandomState(2)
        pairs = []
        for _ in range(3):
            prompt = list(rng.randint(0, 32, 5))
            pairs.append((eng.submit(prompt, max_new_tokens=8), prompt))
            clock.advance(0.01)
        eng.run(max_steps=300)
        assert eng.scheduler.preemptions >= 1
        for r, prompt in pairs:
            assert r.generated == model.reference_generate(prompt, 8)
        # FIFO no-starvation: completion follows arrival
        assert [r.rid for r in eng.finished] == [r.rid for r, _ in pairs]
        assert cache.stats()["used_pages"] == 0 and cache.verify()

    def test_chaos_killed_request_leaks_nothing(self):
        model, cache, clock, eng = _pressured_engine(pages=16)
        victim = eng.submit([1, 2, 3, 4, 5], max_new_tokens=8)
        other = eng.submit([6, 7, 8], max_new_tokens=4)
        eng.step()                                # both prefilled
        assert cache.stats()["used_pages"] > 0
        eng.cancel(victim)                        # killed mid-flight
        assert victim.state == CANCELLED
        eng.run(max_steps=50)
        assert other.state == FINISHED
        st = cache.stats()
        assert st["used_pages"] == 0 and st["sequences"] == 0
        assert cache.verify()

    def test_eos_stops_decode(self):
        model, cache, clock, eng = _pressured_engine(pages=64,
                                                     max_seq_len=64)
        ref = model.reference_generate([3, 1, 4], 10)
        eos = ref[3]                              # force an early stop
        stop = ref.index(eos)                     # first occurrence wins
        r = eng.submit([3, 1, 4], max_new_tokens=10, eos_id=eos)
        eng.run()
        assert r.generated == ref[:stop + 1]
        assert r.generated[-1] == eos and len(r.generated) < 10

    def test_latency_histograms_sane_p50_p99(self):
        metrics.reset()
        model, cache, clock, eng = _pressured_engine(pages=64,
                                                     max_seq_len=64)
        for i in range(4):
            eng.submit([1 + i, 2, 3], max_new_tokens=6)
            clock.advance(0.05)
        while not eng.scheduler.idle:
            eng.step()
            clock.advance(0.01)                   # 10ms per step
        snap = metrics.snapshot()
        for name in ("serving.ttft_ms", "serving.tpot_ms",
                     "serving.e2e_ms"):
            h = snap[name]
            assert h["count"] > 0, name
            assert 0 <= h["p50"] <= h["p99"] <= h["max"], (name, h)
        # every decode step advanced the clock 10ms: TPOT p50 == 10ms
        assert snap["serving.tpot_ms"]["p50"] == pytest.approx(10.0,
                                                               rel=0.01)
        assert snap["serving.ttft_ms"]["count"] == 4
        assert snap["serving.e2e_ms"]["count"] == 4

    def test_oversize_request_rejected_at_submit(self):
        # prompt + max_new - 1 > max_seq_len can NEVER fit: refuse at
        # the door instead of ValueError-ing mid-decode (which would
        # kill the loop for every other in-flight request)
        _, _, _, eng = _pressured_engine(pages=16, max_seq_len=8)
        with pytest.raises(ValueError):
            eng.submit([1, 2, 3, 4, 5], max_new_tokens=8)

    def test_scheduler_direct_oversize_truncates_not_crashes(self):
        # submitted straight to the scheduler (bypassing engine
        # validation): the decode loop finishes it truncated and the
        # pool balances — no mid-loop ValueError, no page leak
        model, cache, clock, eng = _pressured_engine(pages=16,
                                                     max_seq_len=8)
        req = eng.scheduler.submit(Request(prompt=[1, 2, 3, 4, 5],
                                           max_new_tokens=8))
        eng.run(max_steps=50)
        assert req.state == FINISHED and 0 < len(req.generated) < 8
        assert cache.stats()["used_pages"] == 0 and cache.verify()

    def test_cancel_clears_last_emit_bookkeeping(self):
        _, _, clock, eng = _pressured_engine(pages=16)
        req = eng.submit([1, 2, 3], max_new_tokens=8)
        eng.step()           # prefill emits a token -> _last_emit entry
        eng.step()
        assert req.rid in eng._last_emit
        eng.cancel(req)
        assert req.rid not in eng._last_emit

    def test_budget_unschedulable_request_rejected_at_submit(self):
        # a context the token budget can never admit would block the
        # FIFO head forever (silent starvation of everything behind it)
        model = TinyLM(vocab_size=32, num_heads=2, head_dim=8)
        cache = PagedKVCache(64, 4, 2, 8)
        eng = ServeEngine(model, cache, scheduler=Scheduler(
            cache, token_budget=16, clock=ManualClock()))
        with pytest.raises(ValueError):
            eng.submit([1] * 12, max_new_tokens=8)    # worst 19 > 16
        eng.submit([1] * 12, max_new_tokens=5)        # worst 16 fits
        eng.run()
        assert len(eng.finished) == 1

    def test_capacity_boundary_request_readmits_after_preemption(self):
        # a preemption-resumed context already at its deepest
        # (prompt + max_new - 1 == max_seq_len) needs NO +1 headroom:
        # demanding it would refuse re-admission forever
        from paddle_tpu.serving import PREEMPTED

        clock = ManualClock()
        cache = PagedKVCache(4, 4, 1, 1)              # 3 usable pages
        s = Scheduler(cache, token_budget=16, clock=clock)
        r = s.submit(Request(prompt=[1] * 9, max_new_tokens=4))
        s.schedule()
        r.generated = [1, 1, 1]                       # context now 12
        s.preempt(r)
        assert r.state == PREEMPTED
        b = s.schedule()
        # cost 12 == worst 12 == max_seq_len: 3 pages, admissible
        assert b.prefills == [r]
        s.finish(r)
        assert cache.stats()["used_pages"] == 0

    def test_scheduler_direct_unservable_prompt_rejected_in_schedule(
            self):
        # a prompt longer than max_seq_len submitted scheduler-direct
        # must be rejected terminally by schedule(), not ValueError out
        # of the serve loop (stranding the popped request stateless)
        model, cache, clock, eng = _pressured_engine(pages=16,
                                                     max_seq_len=16)
        healthy = eng.submit([1, 2, 3], max_new_tokens=4)
        doomed = eng.scheduler.submit(Request(prompt=[1] * 17,
                                              max_new_tokens=2))
        eng.run(max_steps=50)
        assert healthy.state == FINISHED
        assert doomed.state == CANCELLED
        assert doomed.finish_t is not None
        assert cache.stats()["used_pages"] == 0 and cache.verify()

    def test_prefill_length_buckets_are_geometric(self):
        from paddle_tpu.serving.engine import _len_bucket

        assert _len_bucket(3, 8) == 8        # floor = page_size
        assert _len_bucket(129, 8) == 256
        assert _len_bucket(256, 8) == 256
        # lengths 129..256 share ONE compiled prefill, not 128 of them
        assert len({_len_bucket(n, 8) for n in range(129, 257)}) == 1

    def test_cancel_after_finish_is_a_noop(self):
        _, cache, clock, eng = _pressured_engine(pages=16)
        req = eng.submit([1, 2, 3], max_new_tokens=3)
        eng.run()
        assert req.state == FINISHED
        finish_t = req.finish_t
        n_finished = len(eng.finished)
        eng.cancel(req)                               # the async race
        assert req.state == FINISHED                  # not rewritten
        assert req.finish_t == finish_t
        assert len(eng.finished) == n_finished

    def test_geometry_mismatch_rejected(self):
        with pytest.raises(ValueError):
            ServeEngine(TinyLM(num_heads=2, head_dim=8),
                        PagedKVCache(8, 4, 4, 8))
        # the engine drives layer 0 only: a multi-layer pool would
        # silently waste HBM — reject it
        with pytest.raises(ValueError):
            ServeEngine(TinyLM(num_heads=2, head_dim=8),
                        PagedKVCache(8, 4, 2, 8, num_layers=2))

    def test_zero_max_new_tokens_rejected(self):
        with pytest.raises(ValueError):
            Request(prompt=[1, 2], max_new_tokens=0)
        _, _, _, eng = _pressured_engine(pages=16)
        with pytest.raises(ValueError):
            eng.submit([1, 2, 3], max_new_tokens=0)

    def test_decode_table_width_tracks_context_not_pool(self):
        # a big pool must NOT widen every decode step's page table:
        # the kernel grid is (B, width), so width rides the batch's
        # actual max context pages (bucketed), keeping per-token K/V
        # traffic O(context)
        model = TinyLM(vocab_size=32, num_heads=2, head_dim=8)
        cache = PagedKVCache(256, 4, 2, 8)        # table_width 255
        eng = ServeEngine(model, cache, scheduler=Scheduler(
            cache, token_budget=64, clock=ManualClock()))
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run()
        widths = {e.table_width for e in eng._decode_fns.values()}
        assert widths and max(widths) <= 4, widths

    def test_decode_entry_exposes_perf_gate_shape(self):
        _, _, _, eng = _pressured_engine(pages=8)
        entry = eng.decode_entry(2)
        assert callable(entry.fn) and len(entry.arg_structs) == 7
        assert entry.arg_structs[0].shape[0] == eng.cache.num_layers


# -- journal request records -------------------------------------------------


class TestServingJournal:
    def test_request_records_carry_full_lifecycle(self, tmp_path):
        run_dir = str(tmp_path / "run")
        obs.start_run(run_dir, flush_every=1)
        model, cache, clock, eng = _pressured_engine()
        rng = np.random.RandomState(3)
        for _ in range(3):
            eng.submit(list(rng.randint(0, 32, 5)), max_new_tokens=8)
            clock.advance(0.5)
        killed = eng.submit([1, 2], max_new_tokens=4)
        eng.step()
        eng.cancel(killed)
        while not eng.scheduler.idle:
            eng.step()
            clock.advance(0.001)
        obs.end_run()
        recs = [json.loads(l) for l in
                open(os.path.join(run_dir, "journal.jsonl"))
                if l.strip()]
        reqs = [r for r in recs if r["t"] == "request"]
        assert len(reqs) == 4
        by_state = {}
        for r in reqs:
            by_state.setdefault(r["state"], []).append(r)
        assert len(by_state["FINISHED"]) == 3
        assert len(by_state["CANCELLED"]) == 1
        for r in by_state["FINISHED"]:
            assert r["arrival_t"] <= r["admit_t"] <= r["first_token_t"] \
                <= r["finish_t"]
            assert r["output_tokens"] == 8 and r["pages_peak"] >= 1
            assert r["ttft_ms"] >= 0 and r["e2e_ms"] >= r["ttft_ms"]
            assert "tpot_ms" in r
        total_preempt = sum(r.get("preemptions", 0) for r in reqs)
        assert total_preempt == eng.scheduler.preemptions >= 1
        # serving compile events rode along
        compiles = [r for r in recs if r["t"] == "event"
                    and r.get("kind") == "compile"
                    and r.get("source") == "serving"]
        assert {c["entry"] for c in compiles} >= {"prefill", "decode"}

    def test_run_report_serving_columns(self, tmp_path):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "serve_run_report", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(
                    __file__))), "tools", "run_report.py"))
        rr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rr)

        run_dir = str(tmp_path / "run")
        obs.start_run(run_dir, flush_every=1)
        model, cache, clock, eng = _pressured_engine(pages=64,
                                                     max_seq_len=64)
        eng.submit([1, 2, 3], max_new_tokens=4)
        clock.advance(0.25)
        while not eng.scheduler.idle:
            eng.step()
            clock.advance(0.01)
        obs.end_run()
        run = rr.load_run(run_dir)
        rs = rr.request_summary(run)
        assert rs["requests"] == rs["finished"] == 1
        assert rs["output_tokens"] == 4
        # admission + first token happen at t=0.25: TTFT exactly 250ms
        assert rs["ttft_ms_p50"] == pytest.approx(250.0)
        assert rs["tpot_ms_p50"] == pytest.approx(10.0)
        rendered = rr.render_run(run)
        assert "requests" in rendered and "ttft_ms" in rendered
