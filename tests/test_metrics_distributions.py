"""Metrics + distributions + profiler tests (ref: fluid/tests test_metrics.py,
test_distributions.py, test_profiler.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import metrics, distribution
from paddle_tpu.utils.profiler import StepTimer


class TestMetrics:
    def test_accuracy_topk(self):
        m = metrics.Accuracy(topk=(1, 2))
        pred = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1],
                         [0.3, 0.3, 0.4], [0.2, 0.5, 0.3]], "float32")
        lab = np.array([1, 0, 1, 2])
        m.update(pred, lab)
        top1, top2 = m.accumulate()
        assert top1 == pytest.approx(0.5)
        # row [.3,.3,.4] lab=1: fluid top_k tie-breaks by smallest index,
        # so top-2 = {2, 0} and the label misses -> 3/4
        assert top2 == pytest.approx(0.75)

    def test_accuracy_tie_and_degenerate(self):
        """Stable-index tie-break (fluid top_k CPU order): constant logits
        must NOT score perfect accuracy, and ignore-index labels miss."""
        const = np.zeros((4, 10), "float32")
        assert metrics.accuracy(const, np.array([0, 1, 5, 9]), k=1) \
            == pytest.approx(0.25)  # only label 0 is in top-1
        assert metrics.accuracy(const, np.array([0, 1, 5, 9]), k=2) \
            == pytest.approx(0.5)
        pred = np.array([[0.1, 0.9], [0.9, 0.1]], "float32")
        assert metrics.accuracy(pred, np.array([-100, 0]), k=1) \
            == pytest.approx(0.5)  # ignore-index is a miss, not a crash
        nan = np.full((2, 3), np.nan, "float32")
        assert metrics.accuracy(nan, np.array([0, 1]), k=3) == 0.0

    def test_accuracy_streaming(self):
        m = metrics.Accuracy()
        m.update(np.array([[0.9, 0.1]]), np.array([0]))
        m.update(np.array([[0.9, 0.1]]), np.array([1]))
        assert m.accumulate() == pytest.approx(0.5)
        m.reset()
        assert m.accumulate() == 0.0

    def test_precision_recall_f1(self):
        pred = np.array([0.9, 0.8, 0.3, 0.6], "float32")
        lab = np.array([1, 0, 1, 1])
        p = metrics.Precision(); p.update(pred, lab)
        r = metrics.Recall(); r.update(pred, lab)
        f = metrics.F1(); f.update(pred, lab)
        assert p.accumulate() == pytest.approx(2 / 3)
        assert r.accumulate() == pytest.approx(2 / 3)
        assert f.accumulate() == pytest.approx(2 / 3)

    def test_auc_perfect_and_random(self):
        rng = np.random.RandomState(0)
        lab = rng.randint(0, 2, 2000)
        perfect = metrics.Auc()
        perfect.update(lab * 0.9 + 0.05, lab)
        assert perfect.accumulate() > 0.99
        rand = metrics.Auc()
        rand.update(rng.rand(2000), lab)
        assert abs(rand.accumulate() - 0.5) < 0.05

    def test_regression_metrics(self):
        pred = np.array([1.0, 2.0, 3.0])
        lab = np.array([2.0, 2.0, 1.0])
        mae = metrics.MAE(); mae.update(pred, lab)
        mse = metrics.MSE(); mse.update(pred, lab)
        rmse = metrics.RMSE(); rmse.update(pred, lab)
        assert mae.accumulate() == pytest.approx(1.0)
        assert mse.accumulate() == pytest.approx(5 / 3)
        assert rmse.accumulate() == pytest.approx(np.sqrt(5 / 3))

    def test_functional_accuracy_and_tensors(self):
        logits = pt.to_tensor(np.array([[0.2, 0.8], [0.7, 0.3]], "float32"))
        lab = pt.to_tensor(np.array([1, 1]))
        assert metrics.accuracy(logits, lab) == pytest.approx(0.5)


class TestDistributions:
    def test_normal_sample_logprob_kl(self):
        pt.seed(0)
        d = distribution.Normal(0.0, 1.0)
        s = d.sample((20000,))
        assert abs(float(s.numpy().mean())) < 0.05
        assert abs(float(s.numpy().std()) - 1.0) < 0.05
        lp = d.log_prob(pt.to_tensor(np.float32(0.0)))
        assert float(lp.numpy()) == pytest.approx(-0.9189385, rel=1e-5)
        q = distribution.Normal(1.0, 2.0)
        kl = distribution.kl_divergence(d, q)
        expect = 0.5 * ((1 / 4) + (1 / 4) - 1 - np.log(1 / 4))
        assert float(kl.numpy()) == pytest.approx(expect, rel=1e-5)

    def test_uniform(self):
        pt.seed(1)
        d = distribution.Uniform(2.0, 4.0)
        s = d.sample((5000,))
        v = s.numpy()
        assert v.min() >= 2.0 and v.max() < 4.0
        assert float(d.log_prob(pt.to_tensor(np.float32(3.0))).numpy()) == \
            pytest.approx(-np.log(2.0))
        assert float(d.log_prob(pt.to_tensor(np.float32(5.0))).numpy()) == \
            -np.inf
        assert float(d.entropy().numpy()) == pytest.approx(np.log(2.0))

    def test_categorical(self):
        pt.seed(2)
        logits = np.log(np.array([0.2, 0.3, 0.5], "float32"))
        d = distribution.Categorical(logits)
        s = d.sample((20000,)).numpy()
        freq = np.bincount(s, minlength=3) / 20000
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)
        lp = d.log_prob(pt.to_tensor(np.array(2)))
        assert float(lp.numpy()) == pytest.approx(np.log(0.5), rel=1e-4)
        ent = d.entropy()
        expect = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        assert float(ent.numpy()) == pytest.approx(expect, rel=1e-4)

    def test_bernoulli_and_kl(self):
        pt.seed(3)
        d = distribution.Bernoulli(probs=0.7)
        s = d.sample((20000,)).numpy()
        assert abs(s.mean() - 0.7) < 0.02
        q = distribution.Bernoulli(probs=0.5)
        kl = distribution.kl_divergence(d, q)
        expect = 0.7 * np.log(0.7 / 0.5) + 0.3 * np.log(0.3 / 0.5)
        assert float(kl.numpy()) == pytest.approx(expect, rel=1e-4)

    def test_sampling_inside_jit(self):
        """Draws use the framework PRNG chain: trace-safe + reproducible."""
        import jax

        def draw():
            pt.seed(42)
            d = distribution.Normal(0.0, 1.0)
            return d.sample((4,)).numpy()

        a, b = draw(), draw()
        np.testing.assert_array_equal(a, b)


class TestProfiler:
    def test_step_timer(self):
        t = StepTimer(skip_first=1)
        for _ in range(4):
            with t.step():
                pass
        s = t.summary()
        assert s["steps"] == 3
        assert s["mean_ms"] >= 0.0
        t.reset()
        assert t.summary() == {"steps": 0}
