"""Top-level ``fluid.*`` export parity.

Walks the reference's effective ``fluid.__all__`` — the literal list in
/root/reference/python/paddle/fluid/__init__.py:94-131 plus the module
``__all__``s it concatenates (framework, executor, trainer_desc,
inferencer, transpiler, parallel_executor, lod_tensor, data_feed_desc,
compiler, backward) — and asserts every name resolves on
``paddle_tpu.fluid``. VERDICT r3 Missing #3.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid

# framework.__all__ + executor.__all__ + trainer_desc.__all__ +
# inferencer.__all__ + transpiler.__all__ + parallel_executor.__all__ +
# lod_tensor.__all__ + data_feed_desc.__all__ + compiler.__all__ +
# backward.__all__ (extracted from the reference tree)
REF_MODULE_ALL = [
    "Program", "default_startup_program", "default_main_program",
    "program_guard", "name_scope", "cuda_places", "cpu_places",
    "cuda_pinned_places", "in_dygraph_mode", "is_compiled_with_cuda",
    "Variable", "load_op_library", "require_version", "device_guard",
    "set_flags", "get_flags",
    "Executor", "global_scope", "scope_guard",
    "TrainerDesc", "MultiTrainer", "DistMultiTrainer", "PipelineTrainer",
    "DistributeTranspiler", "memory_optimize", "release_memory",
    "HashName", "RoundRobin", "DistributeTranspilerConfig",
    "ParallelExecutor",
    "create_lod_tensor", "create_random_int_lodtensor",
    "DataFeedDesc",
    "CompiledProgram", "ExecutionStrategy", "BuildStrategy",
    "append_backward", "gradients",
]

# the literal tail of the reference __all__ (fluid/__init__.py:97-131)
REF_LITERAL_ALL = [
    "io", "initializer", "embedding", "one_hot", "layers", "contrib",
    "data", "dygraph", "enable_dygraph", "disable_dygraph", "transpiler",
    "nets", "optimizer", "learning_rate_decay", "backward", "regularizer",
    "LoDTensor", "LoDTensorArray", "CPUPlace", "CUDAPlace",
    "CUDAPinnedPlace", "Tensor", "ParamAttr", "WeightNormParamAttr",
    "DataFeeder", "clip", "profiler", "unique_name", "Scope",
    "install_check", "save", "load", "VarBase",
]

# submodules imported (not in __all__ but reachable as fluid.<name>)
REF_SUBMODULES = ["framework", "executor", "average", "evaluator",
                  "metrics", "incubate", "compiler", "lod_tensor",
                  "trainer_desc", "parallel_executor"]


@pytest.mark.parametrize("name", sorted(set(REF_MODULE_ALL +
                                            REF_LITERAL_ALL)))
def test_export_resolves(name):
    assert getattr(fluid, name, None) is not None, name


@pytest.mark.parametrize("name", REF_SUBMODULES)
def test_submodule_reachable(name):
    # a handful are folded into siblings here rather than 1:1 modules
    folded = {"framework": fluid, "executor": fluid,
              "compiler": fluid, "parallel_executor": fluid,
              "lod_tensor": fluid}
    if name in folded and not hasattr(fluid, name):
        pytest.skip(f"{name} folded into fluid top level")
    assert getattr(fluid, name, None) is not None


def test_weighted_average():
    avg = fluid.average.WeightedAverage()
    avg.add(value=2.0, weight=1)
    avg.add(value=4.0, weight=2)
    assert np.isclose(avg.eval(), 10.0 / 3.0)
    avg.reset()
    with pytest.raises(ValueError):
        avg.eval()


def test_lod_tensor_roundtrip():
    t = fluid.create_lod_tensor(
        np.arange(10, dtype=np.float32).reshape(5, 2), [[2, 3]])
    assert t.lod() == [[0, 2, 5]]
    assert t.recursive_sequence_lengths() == [[2, 3]]
    assert t.has_valid_recursive_sequence_lengths()
    with pytest.raises(ValueError):
        fluid.create_lod_tensor(np.zeros((4, 1)), [[2, 3]])
    r = fluid.create_random_int_lodtensor([[1, 2]], base_shape=[3],
                                          low=0, high=9)
    assert np.asarray(r).shape == (3, 3)


def test_lod_tensor_from_nested_list():
    t = fluid.create_lod_tensor([[1, 2], [3, 4, 5]], None)
    assert np.asarray(t).shape == (5, 1)
    assert t.recursive_sequence_lengths() == [[2, 3]]


def test_evaluator_edit_distance():
    ev = fluid.evaluator.EditDistance()
    ev.update(np.array([1.0, 3.0]), 2)
    avg, err = ev.eval()
    assert np.isclose(avg, 2.0)


def test_trainer_desc_containers():
    td = fluid.TrainerDesc()
    td.set_thread(4)
    assert td.proto_desc["thread_num"] == 4
    with pytest.raises(NotImplementedError):
        fluid.MultiTrainer().run()
    fd = fluid.DataFeedDesc()
    fd.set_batch_size(128)
    assert "128" in fd.desc()


def test_install_check_runs(capsys):
    fluid.install_check.run_check()
    assert "successfully" in capsys.readouterr().out


def test_fluid_backward_module_path():
    """fluid-era call shape: fluid.backward.append_backward(loss)."""
    pt.enable_static()
    try:
        prog = fluid.Program()
        with fluid.program_guard(prog):
            x = fluid.data(name="x", shape=[4, 3])
            w = fluid.layers.create_parameter([3, 1])
            loss = fluid.layers.reduce_mean(fluid.layers.mul(x, w))
            params_grads = fluid.backward.append_backward(loss)
        assert params_grads
    finally:
        pt.disable_static()


def test_top_level_module_parity():
    """Every module directory/file of the reference's python/paddle/
    top level resolves on paddle_tpu (ref: python/paddle/__init__.py)."""
    top = ["batch", "compat", "dataset", "device", "distributed",
           "distribution", "fleet", "fluid", "framework", "io", "metric",
           "nn", "optimizer", "reader", "regularizer", "sysconfig",
           "tensor", "utils"]
    missing = [n for n in top if getattr(pt, n, None) is None]
    assert not missing, missing
    assert callable(pt.sysconfig.get_include)
    assert pt.tensor.concat is pt.ops.concat


def test_data_feeder_submodule():
    """from paddle.fluid import data_feeder must work and carry the
    validator trio (ref: fluid/data_feeder.py:74-99)."""
    from paddle_tpu.fluid import data_feeder

    assert data_feeder.DataFeeder is fluid.DataFeeder
    assert data_feeder.convert_dtype("int64") in ("int32", "int64")
    with pytest.raises(TypeError, match="must be one of"):
        data_feeder.check_variable_and_dtype(
            pt.to_tensor([1.0]), "x", ["int32", "int64"], "cast")
    with pytest.raises(TypeError, match="type of 'x'"):
        data_feeder.check_type([1.0], "x", (pt.Tensor,), "cast")
    # a correct input passes silently
    data_feeder.check_variable_and_dtype(
        pt.to_tensor([1.0]), "x", ["float32"], "cast")


def test_reader_submodule_from_generator():
    """fluid.io.DataLoader.from_generator feeds an Executor loop
    (ref: fluid/reader.py:179)."""
    from paddle_tpu.fluid import reader as freader

    assert fluid.io.DataLoader is freader.DataLoader
    assert fluid.io.PyReader is freader.PyReader
    pt.enable_static()
    try:
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data(name="x", shape=[4, 3])
            y = fluid.data(name="y", shape=[4, 1])
            out = fluid.layers.fc(x, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(out, y))
        loader = freader.DataLoader.from_generator(feed_list=[x, y],
                                                   capacity=8)
        rng = np.random.RandomState(0)

        def gen():
            for _ in range(3):
                yield [rng.randn(4, 3).astype("float32"),
                       rng.randn(4, 1).astype("float32")]

        loader.set_batch_generator(gen)
        exe = fluid.Executor()
        exe.run(startup)
        seen = 0
        for feed in loader():
            (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
            assert np.isfinite(np.asarray(lv)).all()
            seen += 1
        assert seen == 3
    finally:
        pt.disable_static()


def test_pyreader_sample_generator_batches():
    from paddle_tpu.fluid.reader import PyReader

    r = PyReader(feed_list=None, capacity=4, return_list=True)
    r.decorate_sample_generator(
        lambda: iter([(np.full((2,), i, np.float32),) for i in range(5)]),
        batch_size=2)
    batches = list(r())
    assert len(batches) == 2  # drop_last drops the 5th sample
    assert batches[0][0].shape == (2, 2)


def test_contrib_utils_submodule():
    """fluid.contrib.utils resolves by attribute AND dotted import; the
    PS lookup-table surgery carries the recorded §4b descope error."""
    import importlib

    from paddle_tpu.fluid.contrib import utils

    assert importlib.import_module(
        "paddle_tpu.fluid.contrib.utils") is utils
    assert hasattr(utils, "HDFSClient")
    client = utils.HDFSClient(hadoop_home="/nonexistent")
    with pytest.raises(RuntimeError, match="hadoop"):
        client.ls("/")
    with pytest.raises(NotImplementedError, match="4b"):
        utils.convert_dist_to_sparse_program(None)


def test_nn_clip_and_top_level_dataloader():
    """paddle.nn.ClipGradBy* + paddle.DataLoader (2.x surfaces)."""
    import paddle_tpu.optim as optim

    assert pt.nn.ClipGradByGlobalNorm is optim.ClipGradByGlobalNorm
    assert pt.nn.ClipGradByNorm is optim.ClipGradByNorm
    assert pt.nn.ClipGradByValue is optim.ClipGradByValue
    assert pt.DataLoader is pt.io.DataLoader


def test_fluid_submodule_names_resolve():
    """Module-name spellings fluid-era scripts use (ref fluid/__init__
    .py:34-84): from paddle.fluid import core/framework/executor/..."""
    import importlib

    for name in ("core", "framework", "executor", "compiler",
                 "parallel_executor", "data_feed_desc", "data_generator",
                 "inferencer", "distribute_lookup_table"):
        mod = importlib.import_module(f"paddle_tpu.fluid.{name}")
        assert getattr(fluid, name) is mod, name
    assert fluid.framework.Program is fluid.Program
    assert fluid.executor.global_scope is fluid.global_scope
    assert fluid.core.LoDTensor is fluid.LoDTensor
    assert fluid.parallel_executor.ParallelExecutor is \
        fluid.ParallelExecutor
    assert fluid.fleet is fluid.incubate.fleet
    assert fluid.monkey_patch_variable() is None
    with pytest.raises(NotImplementedError, match="4b"):
        fluid.distribute_lookup_table.find_distributed_lookup_table()


def test_fluid_framework_module_surface():
    """The framework-module helpers scripts actually call."""
    assert fluid.framework.grad_var_name("w") == "w@GRAD"
    assert len(fluid.framework.cpu_places(2)) == 2
    pt.enable_static()
    try:
        assert fluid.framework.in_dygraph_mode() is False
    finally:
        pt.disable_static()
    assert fluid.framework.in_dygraph_mode() is True
