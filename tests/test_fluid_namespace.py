"""Top-level ``fluid.*`` export parity.

Walks the reference's effective ``fluid.__all__`` — the literal list in
/root/reference/python/paddle/fluid/__init__.py:94-131 plus the module
``__all__``s it concatenates (framework, executor, trainer_desc,
inferencer, transpiler, parallel_executor, lod_tensor, data_feed_desc,
compiler, backward) — and asserts every name resolves on
``paddle_tpu.fluid``. VERDICT r3 Missing #3.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid

# framework.__all__ + executor.__all__ + trainer_desc.__all__ +
# inferencer.__all__ + transpiler.__all__ + parallel_executor.__all__ +
# lod_tensor.__all__ + data_feed_desc.__all__ + compiler.__all__ +
# backward.__all__ (extracted from the reference tree)
REF_MODULE_ALL = [
    "Program", "default_startup_program", "default_main_program",
    "program_guard", "name_scope", "cuda_places", "cpu_places",
    "cuda_pinned_places", "in_dygraph_mode", "is_compiled_with_cuda",
    "Variable", "load_op_library", "require_version", "device_guard",
    "set_flags", "get_flags",
    "Executor", "global_scope", "scope_guard",
    "TrainerDesc", "MultiTrainer", "DistMultiTrainer", "PipelineTrainer",
    "DistributeTranspiler", "memory_optimize", "release_memory",
    "HashName", "RoundRobin", "DistributeTranspilerConfig",
    "ParallelExecutor",
    "create_lod_tensor", "create_random_int_lodtensor",
    "DataFeedDesc",
    "CompiledProgram", "ExecutionStrategy", "BuildStrategy",
    "append_backward", "gradients",
]

# the literal tail of the reference __all__ (fluid/__init__.py:97-131)
REF_LITERAL_ALL = [
    "io", "initializer", "embedding", "one_hot", "layers", "contrib",
    "data", "dygraph", "enable_dygraph", "disable_dygraph", "transpiler",
    "nets", "optimizer", "learning_rate_decay", "backward", "regularizer",
    "LoDTensor", "LoDTensorArray", "CPUPlace", "CUDAPlace",
    "CUDAPinnedPlace", "Tensor", "ParamAttr", "WeightNormParamAttr",
    "DataFeeder", "clip", "profiler", "unique_name", "Scope",
    "install_check", "save", "load", "VarBase",
]

# submodules imported (not in __all__ but reachable as fluid.<name>)
REF_SUBMODULES = ["framework", "executor", "average", "evaluator",
                  "metrics", "incubate", "compiler", "lod_tensor",
                  "trainer_desc", "parallel_executor"]


@pytest.mark.parametrize("name", sorted(set(REF_MODULE_ALL +
                                            REF_LITERAL_ALL)))
def test_export_resolves(name):
    assert getattr(fluid, name, None) is not None, name


@pytest.mark.parametrize("name", REF_SUBMODULES)
def test_submodule_reachable(name):
    # a handful are folded into siblings here rather than 1:1 modules
    folded = {"framework": fluid, "executor": fluid,
              "compiler": fluid, "parallel_executor": fluid,
              "lod_tensor": fluid}
    if name in folded and not hasattr(fluid, name):
        pytest.skip(f"{name} folded into fluid top level")
    assert getattr(fluid, name, None) is not None


def test_weighted_average():
    avg = fluid.average.WeightedAverage()
    avg.add(value=2.0, weight=1)
    avg.add(value=4.0, weight=2)
    assert np.isclose(avg.eval(), 10.0 / 3.0)
    avg.reset()
    with pytest.raises(ValueError):
        avg.eval()


def test_lod_tensor_roundtrip():
    t = fluid.create_lod_tensor(
        np.arange(10, dtype=np.float32).reshape(5, 2), [[2, 3]])
    assert t.lod() == [[0, 2, 5]]
    assert t.recursive_sequence_lengths() == [[2, 3]]
    assert t.has_valid_recursive_sequence_lengths()
    with pytest.raises(ValueError):
        fluid.create_lod_tensor(np.zeros((4, 1)), [[2, 3]])
    r = fluid.create_random_int_lodtensor([[1, 2]], base_shape=[3],
                                          low=0, high=9)
    assert np.asarray(r).shape == (3, 3)


def test_lod_tensor_from_nested_list():
    t = fluid.create_lod_tensor([[1, 2], [3, 4, 5]], None)
    assert np.asarray(t).shape == (5, 1)
    assert t.recursive_sequence_lengths() == [[2, 3]]


def test_evaluator_edit_distance():
    ev = fluid.evaluator.EditDistance()
    ev.update(np.array([1.0, 3.0]), 2)
    avg, err = ev.eval()
    assert np.isclose(avg, 2.0)


def test_trainer_desc_containers():
    td = fluid.TrainerDesc()
    td.set_thread(4)
    assert td.proto_desc["thread_num"] == 4
    with pytest.raises(NotImplementedError):
        fluid.MultiTrainer().run()
    fd = fluid.DataFeedDesc()
    fd.set_batch_size(128)
    assert "128" in fd.desc()


def test_install_check_runs(capsys):
    fluid.install_check.run_check()
    assert "successfully" in capsys.readouterr().out


def test_fluid_backward_module_path():
    """fluid-era call shape: fluid.backward.append_backward(loss)."""
    pt.enable_static()
    try:
        prog = fluid.Program()
        with fluid.program_guard(prog):
            x = fluid.data(name="x", shape=[4, 3])
            w = fluid.layers.create_parameter([3, 1])
            loss = fluid.layers.reduce_mean(fluid.layers.mul(x, w))
            params_grads = fluid.backward.append_backward(loss)
        assert params_grads
    finally:
        pt.disable_static()


def test_top_level_module_parity():
    """Every module directory/file of the reference's python/paddle/
    top level resolves on paddle_tpu (ref: python/paddle/__init__.py)."""
    top = ["batch", "compat", "dataset", "device", "distributed",
           "distribution", "fleet", "fluid", "framework", "io", "metric",
           "nn", "optimizer", "reader", "regularizer", "sysconfig",
           "tensor", "utils"]
    missing = [n for n in top if getattr(pt, n, None) is None]
    assert not missing, missing
    assert callable(pt.sysconfig.get_include)
    assert pt.tensor.concat is pt.ops.concat
