"""Recompute + NaN-guard tests (SURVEY §2.12 / §5; ref FLAGS_check_nan_inf
in framework/operator.cc:41 and fleet RecomputeOptimizer)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu.utils import nan_guard


class TestRecompute:
    def _block(self):
        pt.seed(0)
        return nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 16))

    def test_same_output_and_grads(self):
        blk = self._block()
        x = pt.to_tensor(np.random.RandomState(0).randn(4, 16)
                         .astype("float32"), stop_gradient=False)

        out_plain = blk(x)
        loss_plain = (out_plain * out_plain).mean()
        loss_plain.backward()
        g_plain = {n: p.grad.numpy().copy()
                   for n, p in blk.named_parameters()}
        for _, p in blk.named_parameters():
            p.clear_grad()

        out_rc = pt.recompute(blk, x)
        loss_rc = (out_rc * out_rc).mean()
        loss_rc.backward()
        np.testing.assert_allclose(out_rc.numpy(), out_plain.numpy(),
                                   rtol=1e-6)
        for n, p in blk.named_parameters():
            np.testing.assert_allclose(p.grad.numpy(), g_plain[n],
                                       rtol=1e-5, atol=1e-6)

    def test_recompute_inside_train_step(self):
        """jax.checkpoint region compiles into the fused step and trains."""
        pt.seed(1)
        blk = self._block()
        head = nn.Linear(16, 1)
        opt = optim.Adam(1e-2, parameters=list(blk.parameters()) +
                         list(head.parameters()))

        def loss_fn(model, x, y):
            h = pt.recompute(model, x)
            return F.mse_loss(head(h), y)

        step = pt.TrainStep(blk, opt, loss_fn, models=[blk, head])
        X = np.random.RandomState(0).randn(16, 16).astype("float32")
        Y = np.random.RandomState(1).randn(16, 1).astype("float32")
        losses = [float(step(X, Y)) for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_gpt_recompute_parity(self):
        from paddle_tpu.models.nlp import GPT, gpt_tiny, gpt_loss

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 1024, (2, 16)).astype("int64")
        labels = np.roll(ids, -1, 1)

        def loss_with(flag):
            pt.seed(7)
            cfg = gpt_tiny(dropout=0.0, use_recompute=flag)
            m = GPT(cfg)
            loss = gpt_loss(m, pt.to_tensor(ids), pt.to_tensor(labels))
            loss.backward()
            g = [p.grad.numpy().copy() for _, p in
                 sorted(m.named_parameters()) if p.grad is not None]
            return float(loss.numpy()), g

        l0, g0 = loss_with(False)
        l1, g1 = loss_with(True)
        assert np.isclose(l0, l1, rtol=1e-5)
        for a, b in zip(g0, g1):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestNanGuard:
    def test_eager_op_check_names_op(self):
        nan_guard.enable_check_nan()
        try:
            x = pt.to_tensor(np.array([1.0, -1.0], "float32"))
            with pytest.raises(nan_guard.NanInfError, match="op 'log'"):
                pt.log(x)  # log(-1) = nan
        finally:
            nan_guard.disable_check_nan()

    def test_check_numerics_nested(self):
        good = {"a": pt.to_tensor(np.ones(3, "float32"))}
        nan_guard.check_numerics(good, "state")
        bad = {"a": [pt.to_tensor(np.array([np.inf], "float32"))]}
        with pytest.raises(nan_guard.NanInfError, match=r"state\.a\[0\]"):
            nan_guard.check_numerics(bad, "state")

    def test_train_step_check_nan_raises(self):
        pt.seed(0)
        m = nn.Linear(4, 1)
        opt = optim.SGD(0.1, parameters=m.parameters())

        def loss_fn(model, x, y, bad):
            # bad=1 -> factor overflows to inf -> loss and grads go inf
            return F.mse_loss(model(x), y) * \
                (1.0 + bad * np.float32(1e38)) ** 2

        step = pt.TrainStep(m, opt, loss_fn, check_nan=True)
        X = np.random.RandomState(0).randn(8, 4).astype("float32")
        Y = np.random.RandomState(1).randn(8, 1).astype("float32")
        step(X, Y, np.float32(0.0))  # clean: no raise
        with pytest.raises(nan_guard.NanInfError, match="step"):
            step(X, Y, np.float32(1.0))
