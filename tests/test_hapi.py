"""High-level Model API tests (ref: the reference's high-level-api book
suite — train whole models through a trainer abstraction in a few lines).
"""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import optim, metrics
from paddle_tpu.hapi import Model, EarlyStopping
from paddle_tpu.io_.dataset import TensorDataset
from paddle_tpu.models.vision import LeNet


_MEANS = np.random.RandomState(1234).randn(10, 1, 28, 28) \
    .astype("float32") * 2.0


def _mnist_like(n=64, classes=10, seed=0):
    """Shared class means + per-split noise: train/test are the same task."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = _MEANS[y] + rng.randn(n, 1, 28, 28).astype("float32") * 0.5
    return TensorDataset([x, y.astype("int64")])


def test_mnist_fit_evaluate_predict():
    """The 10-line MNIST recipe: Model(LeNet()).prepare(...).fit(...)."""
    pt.seed(0)
    train_ds = _mnist_like(64)
    test_ds = _mnist_like(32, seed=1)
    m = Model(LeNet())
    m.prepare(optim.Adam(2e-3, parameters=m.parameters()),
              F.cross_entropy, metrics.Accuracy())
    hist = m.fit(train_ds, epochs=8, batch_size=32, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0], hist
    res = m.evaluate(test_ds, batch_size=32, verbose=0)
    assert res["acc"] > 0.5, res
    preds = m.predict(test_ds, batch_size=32)
    assert preds[0].shape == (32, 10)


def test_train_eval_batch_and_save_load(tmp_path):
    pt.seed(0)
    ds = _mnist_like(32)
    m = Model(LeNet())
    m.prepare(optim.Adam(1e-3, parameters=m.parameters()),
              F.cross_entropy, metrics.Accuracy())
    x, y = ds[0]
    xb = np.stack([np.asarray(ds[i][0]) for i in range(8)])
    yb = np.asarray([ds[i][1] for i in range(8)])
    l0 = m.train_batch([xb], [yb])
    assert np.isfinite(l0)
    path = str(tmp_path / "ck")
    m.save(path)
    m2 = Model(LeNet())
    m2.prepare(optim.Adam(1e-3, parameters=m2.parameters()),
               F.cross_entropy)
    m2.load(path)
    p1 = m.predict_batch([xb])
    p2 = m2.predict_batch([xb])
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_early_stopping_stops():
    pt.seed(0)
    ds = _mnist_like(32)
    m = Model(LeNet())
    m.prepare(optim.Adam(0.0, parameters=m.parameters()),  # lr 0: no change
              F.cross_entropy, metrics.Accuracy())
    es = EarlyStopping(monitor="loss", patience=1)
    hist = m.fit(ds, eval_data=ds, epochs=10, batch_size=32, verbose=0,
                 callbacks=[es])
    assert len(hist["loss"]) < 10  # stopped long before 10 epochs


def test_summary_counts_params():
    m = Model(LeNet())
    info = m.summary()
    n = sum(int(np.prod(p.shape)) for p in m.parameters())
    assert info["total_params"] == n
