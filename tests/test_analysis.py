"""paddle_tpu.analysis: verifier diagnostics, optimization passes, and the
Executor wiring (verify always, optimize behind optimize_level).

Every verifier error class gets a hand-built broken Program asserting the
EXACT diagnostic code; the pass tests assert op-count reduction AND
bitwise-identical fetches vs the unoptimized replay (the passes must be
invisible to numerics by construction)."""
import gc

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
from paddle_tpu.analysis import (CSEPass, DeadOpEliminationPass,
                                 ProgramVerificationError, lint_program,
                                 run_compile_passes, verify_program)
from paddle_tpu.static_.program import Operator, Program, global_scope


def _data_var(blk, name="x", shape=(2, 3)):
    return blk.create_var(name=name, shape=shape, dtype="float32",
                          is_data=True)


# -- verifier: one broken Program per diagnostic class ----------------------


def test_verifier_dangling_input_pta002():
    p = Program()
    blk = p.global_block
    _data_var(blk)
    blk.create_var(name="y", shape=(2, 3), dtype="float32")
    blk.append_op(Operator("relu", lambda a: jnp.maximum(a, 0),
                           ["nowhere"], ["y"], {}))
    with pytest.raises(ProgramVerificationError) as ei:
        verify_program(p, fetch_names=("y",))
    assert [d.code for d in ei.value.errors] == ["PTA002"]
    assert ei.value.errors[0].var == "nowhere"


def test_verifier_use_before_def_pta001():
    p = Program()
    blk = p.global_block
    _data_var(blk)
    blk.create_var(name="tmp", shape=(2, 3), dtype="float32")
    blk.create_var(name="o", shape=(2, 3), dtype="float32")
    # reads tmp before the op that defines it
    blk.append_op(Operator("scale", lambda a: a * 2.0, ["tmp"], ["o"], {}))
    blk.append_op(Operator("scale", lambda a: a * 0.5, ["x"], ["tmp"], {}))
    rep = verify_program(p, fetch_names=("o",), raise_on_error=False)
    assert "PTA001" in [d.code for d in rep.errors()]


def test_verifier_duplicate_output_pta003():
    p = Program()
    blk = p.global_block
    _data_var(blk)
    blk.create_var(name="y", shape=(2, 3), dtype="float32")
    blk.append_op(Operator("twin", lambda a: (a, a * 2), ["x"],
                           ["y", "y"], {}))
    rep = verify_program(p, raise_on_error=False)
    assert "PTA003" in [d.code for d in rep.errors()]


def test_verifier_waw_clobber_via_record_assign_pta004():
    """The seeded WAW class: set_value overwrites a computed value no op
    ever read — built through the REAL recording path."""
    pt.enable_static()
    try:
        main = pt.static.Program()
        with fluid.program_guard(main):
            x = fluid.layers.data("x", [-1, 4], "float32")
            t = fluid.layers.relu(x)       # writes t ... which nothing reads
            z = fluid.layers.scale(x, scale=3.0)
            t.set_value(z)                 # assign_to clobbers t
            fluid.layers.scale(t, scale=1.0)
    finally:
        pt.disable_static()
    rep = verify_program(main, raise_on_error=False)
    codes = [d.code for d in rep.errors()]
    assert "PTA004" in codes
    # and the Executor refuses to compile it
    exe = fluid.Executor()
    with pytest.raises(ProgramVerificationError):
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[t])


def test_verifier_shape_drift_pta005():
    p = Program()
    blk = p.global_block
    _data_var(blk)
    blk.create_var(name="s", shape=(5, 7), dtype="float32")  # lie: (2,3)
    blk.append_op(Operator("relu", lambda a: jnp.maximum(a, 0),
                           ["x"], ["s"], {}))
    rep = verify_program(p, raise_on_error=False)
    assert [d.code for d in rep.errors()] == ["PTA005"]


def test_verifier_dtype_drift_pta006():
    p = Program()
    blk = p.global_block
    _data_var(blk)
    blk.create_var(name="z", shape=(2, 3), dtype="int32")  # lie: float32
    blk.append_op(Operator("relu", lambda a: jnp.maximum(a, 0),
                           ["x"], ["z"], {}))
    rep = verify_program(p, raise_on_error=False)
    assert [d.code for d in rep.errors()] == ["PTA006"]


def test_verifier_donated_then_read_pta007():
    """Donated (updated) persistable read after its last write: the class
    that breaks the Executor's buffer-donation discipline."""
    p = Program()
    blk = p.global_block
    _data_var(blk)
    blk.create_var(name="w@acc", shape=(2, 3), dtype="float32",
                   persistable=True)
    blk.create_var(name="r", shape=(2, 3), dtype="float32")
    blk.append_op(Operator("axpy", lambda a, b: a + b,
                           ["x", "w@acc"], ["w@acc"], {}))
    blk.append_op(Operator("scale", lambda a: a * 2.0, ["w@acc"], ["r"], {}))
    rep = verify_program(p, fetch_names=("r",), raise_on_error=False)
    assert [d.code for d in rep.errors()] == ["PTA007"]
    assert rep.errors()[0].var == "w@acc"
    # through the Executor (scope-held persistable => donated): rejected
    global_scope().set("w@acc", jnp.ones((2, 3), jnp.float32))
    try:
        exe = fluid.Executor()
        with pytest.raises(ProgramVerificationError):
            exe.run(p, feed={"x": np.ones((2, 3), np.float32)},
                    fetch_list=["r"])
    finally:
        del global_scope()._vars["w@acc"]  # don't leak into other tests
    # a persistable the Scope does NOT hold is never donated: a
    # written-then-read one is plain env state and must verify clean
    p2 = Program()
    blk2 = p2.global_block
    _data_var(blk2)
    blk2.create_var(name="stat", shape=(2, 3), dtype="float32",
                    persistable=True)
    blk2.create_var(name="r2", shape=(2, 3), dtype="float32")
    blk2.append_op(Operator("copy", lambda a: a * 1.0, ["x"], ["stat"], {}))
    blk2.append_op(Operator("scale", lambda a: a * 2.0, ["stat"],
                            ["r2"], {}))
    rep = verify_program(p2, fetch_names=("r2",), scope_names=set(),
                         raise_on_error=False)
    assert rep.errors() == []
    out = exe.run(p2, feed={"x": np.ones((2, 3), np.float32)},
                  fetch_list=["r2"])
    np.testing.assert_array_equal(out[0], np.full((2, 3), 2.0, np.float32))


def test_verifier_passes_clean_training_program():
    """A real forward+backward+update program must verify clean — the
    checks may not false-positive on the optimizer's in-place writes."""
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [-1, 4], "float32")
            y = fluid.layers.data("y", [-1, 1], "float32")
            h = fluid.layers.fc(x, size=8, act="relu")
            pred = fluid.layers.fc(h, size=1)
            loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
            opt = fluid.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        rep = verify_program(main, fetch_names=(loss.name,),
                             raise_on_error=False)
        assert rep.errors() == []
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.run(main,
                      feed={"x": np.random.randn(8, 4).astype(np.float32),
                            "y": np.random.randn(8, 1).astype(np.float32)},
                      fetch_list=[loss])
        assert np.isfinite(out[0]).all()
    finally:
        pt.disable_static()


# -- satellites -------------------------------------------------------------


def test_dynamic_dim_mask_and_static_dim_feed_warning():
    pt.enable_static()
    try:
        main = pt.static.Program()
        with fluid.program_guard(main):
            x = pt.static.data("x", [-1, 3], "float32")
            fluid.layers.relu(x)
        assert x.dynamic_dims == (0,)
        assert x.shape == [1, 3]  # placeholder 1, mask remembers dim 0
    finally:
        pt.disable_static()
    # dynamic dim 0 may vary freely: no warning
    rep = verify_program(main, feed_shapes={"x": ((64, 3), "float32")},
                         raise_on_error=False)
    assert not rep.has("PTA009")
    # static dim 1 contradicted: PTA009 warning, NOT a deep XLA failure
    with pytest.warns(RuntimeWarning, match="declared static shape"):
        rep = verify_program(main, feed_shapes={"x": ((64, 5), "float32")},
                             raise_on_error=False)
    assert rep.has("PTA009")
    assert rep.errors() == []  # a warning: the program still re-traces


def test_program_uid_monotonic_and_cache_keyed_on_uid():
    uids = [Program()._uid for _ in range(3)]
    assert uids == sorted(uids) and len(set(uids)) == 3
    # a GC'd program's id() can be recycled; its _uid can not
    p1 = Program()
    uid1 = p1._uid
    del p1
    gc.collect()
    assert Program()._uid > uid1

    pt.enable_static()
    try:
        main = pt.static.Program()
        with fluid.program_guard(main):
            x = fluid.layers.data("x", [-1, 4], "float32")
            out = fluid.layers.relu(x)
        exe = fluid.Executor()
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[out])
        assert any(k.program_uid == main._uid for k in exe._cache)
        assert not any(k.program_uid == id(main) for k in exe._cache)
    finally:
        pt.disable_static()


def test_clone_carries_random_seed_and_replays_identically():
    pt.enable_static()
    pt.seed(1234)
    try:
        main = pt.static.Program()
        main.random_seed = 7
        with fluid.program_guard(main):
            x = fluid.layers.data("x", [-1, 16], "float32")
            d = fluid.layers.dropout(x, 0.5)
            out = fluid.layers.reduce_sum(d)
        clone = main.clone(for_test=False)
        assert clone.random_seed == 7
        exe = fluid.Executor()
        feed = {"x": np.random.randn(4, 16).astype(np.float32)}
        a = exe.run(main, feed=feed, fetch_list=[out])[0]
        b = exe.run(clone, feed=feed, fetch_list=[out])[0]
        # the PRNG key is a captured constant carried by the clone: the
        # stochastic replay is bitwise reproducible across clones
        np.testing.assert_array_equal(a, b)
    finally:
        pt.disable_static()


# -- optimization passes ----------------------------------------------------


def _train_program_with_dropout():
    """Forward + loss + appended backward: a training program whose
    eval-mode clone carries a neutered dropout and a dead grad chain."""
    main = pt.static.Program()
    with fluid.program_guard(main):
        x = fluid.layers.data("x", [-1, 8], "float32")
        y = fluid.layers.data("y", [-1, 1], "float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        h = fluid.layers.dropout(h, 0.5)
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        fluid.backward.append_backward(loss)
    return main, loss


def test_dce_on_eval_clone_removes_ops_and_keeps_fetches_bitwise():
    pt.enable_static()
    try:
        main, loss = _train_program_with_dropout()
        test_prog = main.clone(for_test=True)
        exe = fluid.Executor()
        feed = {"x": np.random.randn(4, 8).astype(np.float32),
                "y": np.random.randn(4, 1).astype(np.float32)}
        ref = exe.run(test_prog, feed=feed, fetch_list=[loss.name],
                      optimize_level=0)[0]
        opt = exe.run(test_prog, feed=feed, fetch_list=[loss.name],
                      optimize_level=1)[0]
        np.testing.assert_array_equal(ref, opt)  # bitwise identical
        stats = exe.last_diagnostics.pass_stats
        removed = (stats["dead_op_elimination"]["removed"]
                   + stats["forward_identity"]["removed"])
        # the whole grad chain + the neutered dropout are unreachable
        assert removed >= 1
        assert stats["dead_op_elimination"]["removed"] >= 1
        assert stats["forward_identity"]["removed"] >= 1
    finally:
        pt.disable_static()


def test_cse_merges_duplicate_pure_ops():
    pt.enable_static()
    try:
        main = pt.static.Program()
        with fluid.program_guard(main):
            x = fluid.layers.data("x", [-1, 6], "float32")
            a = fluid.layers.relu(x)
            b = fluid.layers.relu(x)       # identical pure op: CSE fodder
            out = fluid.layers.reduce_sum(a + b)
        exe = fluid.Executor()
        feed = {"x": np.random.randn(3, 6).astype(np.float32)}
        ref = exe.run(main, feed=feed, fetch_list=[out],
                      optimize_level=0)[0]
        opt = exe.run(main, feed=feed, fetch_list=[out],
                      optimize_level=2)[0]
        np.testing.assert_array_equal(ref, opt)
        assert exe.last_diagnostics.pass_stats["cse"]["removed"] >= 1
    finally:
        pt.disable_static()


def test_cse_respects_inplace_redefinition():
    """Two textually identical ops must NOT merge when an assign_to
    redefines their input between them (value-version keying)."""
    p = Program()
    blk = p.global_block
    _data_var(blk, "x", (4,))
    for n in ("a", "b", "c"):
        blk.create_var(name=n, shape=(4,), dtype="float32")
    from paddle_tpu.ops._base import OP_REGISTRY, register

    if "t_double" not in OP_REGISTRY:
        register("t_double")(lambda v: v * 2.0)
    fn = OP_REGISTRY["t_double"]
    blk.append_op(Operator("t_double", fn, ["x"], ["a"], {}))
    blk.append_op(Operator("assign_to", lambda v: v, ["a"], ["x"], {}))
    blk.append_op(Operator("t_double", fn, ["x"], ["b"], {}))  # new x!
    blk.append_op(Operator("axpy", lambda u, v: u + v, ["a", "b"], ["c"], {}))
    from paddle_tpu.analysis import PassContext

    ops = CSEPass().rewrite(PassContext(p, fetch_names=("c",)))
    assert len(ops) == 4  # nothing merged


def test_forward_identity_blocked_when_source_overwritten_later():
    """A p=0 dropout must NOT be forwarded when a later assign_to
    redefines its SOURCE: readers of the dropout output would silently
    see the new value (stale-rename regression)."""
    import jax

    from paddle_tpu.ops._base import OP_REGISTRY

    p = Program()
    blk = p.global_block
    _data_var(blk, "x", (2,))
    key = jax.random.PRNGKey(0)
    blk.create_var(name="k", shape=key.shape, dtype=key.dtype)
    p._constants["k"] = key
    blk.create_var(name="c", shape=(2,), dtype="float32")
    p._constants["c"] = jnp.asarray([100.0, 100.0])
    blk.create_var(name="h", shape=(2,), dtype="float32")
    blk.create_var(name="y", shape=(2,), dtype="float32")
    blk.append_op(Operator("dropout", OP_REGISTRY["dropout"], ["x", "k"],
                           ["h"], {"p": 0.0, "mode": "upscale_in_train"}))
    blk.append_op(Operator("assign_to", lambda v: v, ["c"], ["x"], {}))
    blk.append_op(Operator("scale", lambda a: a * 1.0, ["h"], ["y"], {}))
    exe = fluid.Executor()
    feed = {"x": np.asarray([1.0, 2.0], np.float32)}
    ref = exe.run(p, feed=feed, fetch_list=["y"], optimize_level=0)[0]
    opt = exe.run(p, feed=feed, fetch_list=["y"], optimize_level=1)[0]
    np.testing.assert_array_equal(ref, opt)
    np.testing.assert_array_equal(ref, [1.0, 2.0])  # NOT the assigned 100s


def test_cse_blocked_when_merged_source_overwritten_later():
    """Two identical pure ops must NOT merge when the survivor's output
    is overwritten in place after the merge point."""
    from paddle_tpu.analysis import PassContext
    from paddle_tpu.ops._base import OP_REGISTRY, register

    if "t_exp" not in OP_REGISTRY:
        register("t_exp")(jnp.exp)
    fn = OP_REGISTRY["t_exp"]
    p = Program()
    blk = p.global_block
    _data_var(blk, "x", (2,))
    blk.create_var(name="c", shape=(2,), dtype="float32")
    p._constants["c"] = jnp.asarray([7.0, 7.0])
    for n in ("a", "b", "u", "y"):
        blk.create_var(name=n, shape=(2,), dtype="float32")
    blk.append_op(Operator("t_exp", fn, ["x"], ["a"], {}))
    blk.append_op(Operator("scale", lambda v: v * 1.0, ["a"], ["u"], {}))
    blk.append_op(Operator("t_exp", fn, ["x"], ["b"], {}))  # merge bait
    blk.append_op(Operator("assign_to", lambda v: v, ["c"], ["a"], {}))
    blk.append_op(Operator("t_exp", fn, ["b"], ["y"], {}))
    ops = CSEPass().rewrite(PassContext(p, fetch_names=("u", "y")))
    assert len(ops) == 5  # nothing merged: 'a' is clobbered after the bait
    exe = fluid.Executor()
    feed = {"x": np.asarray([1.0, 2.0], np.float32)}
    ref = exe.run(p, feed=feed, fetch_list=["y"], optimize_level=0)[0]
    opt = exe.run(p, feed=feed, fetch_list=["y"], optimize_level=2)[0]
    np.testing.assert_array_equal(ref, opt)


def test_dce_preserves_persistable_updates():
    """Ops feeding only a persistable's final value are NOT dead."""
    p = Program()
    blk = p.global_block
    _data_var(blk, "x", (4,))
    blk.create_var(name="stat", shape=(4,), dtype="float32",
                   persistable=True)
    blk.create_var(name="o", shape=(4,), dtype="float32")
    blk.append_op(Operator("upd", lambda a, s: a + s, ["x", "stat"],
                           ["stat"], {}))
    blk.append_op(Operator("id", lambda a: a * 1.0, ["x"], ["o"], {}))
    from paddle_tpu.analysis import PassContext

    ctx = PassContext(p, fetch_names=("o",))
    ops = DeadOpEliminationPass().rewrite(ctx)
    assert [op.type for op in ops] == ["upd", "id"]


# -- lint -------------------------------------------------------------------


def test_lint_unused_feed_stale_fetch_and_dead_constant():
    pt.enable_static()
    try:
        main = pt.static.Program()
        with fluid.program_guard(main):
            x = fluid.layers.data("x", [-1, 4], "float32")
            unused = fluid.layers.data("unused", [-1, 4], "float32")
            out = fluid.layers.relu(x)
        other = pt.static.Program()
        with fluid.program_guard(other):
            fx = fluid.layers.data("x", [-1, 4], "float32")
            foreign = fluid.layers.relu(fx)
        # a constant nothing consumes
        main._constants["orphan_const"] = jnp.zeros((2,), jnp.float32)
        rep = lint_program(main, fetch_list=[out, foreign])
        codes = set(rep.codes())
        assert {"PTL101", "PTL102", "PTL103"} <= codes
        assert rep.errors() == []  # lint is warnings-only
        # explicit stale flag is honored too
        out._stale = True
        rep = lint_program(main, fetch_list=[out])
        assert rep.has("PTL102")
    finally:
        pt.disable_static()


# -- wiring -----------------------------------------------------------------


def test_append_backward_runs_structural_verifier():
    """autodiff output is itself checked: corrupting the program before
    append_backward surfaces as a coded diagnostic, not an XLA error."""
    pt.enable_static()
    try:
        main = pt.static.Program()
        with fluid.program_guard(main):
            x = fluid.layers.data("x", [-1, 4], "float32")
            h = fluid.layers.fc(x, size=4)
            loss = fluid.layers.reduce_mean(h)
            # sabotage: an op referencing a name that does not exist
            main.global_block.append_op(Operator(
                "broken", lambda a: a, ["ghost_var"], [loss.name], {}))
            with pytest.raises(ProgramVerificationError):
                fluid.backward.append_backward(loss)
    finally:
        pt.disable_static()


def test_optimize_level_0_compiles_full_program():
    pt.enable_static()
    try:
        main = pt.static.Program()
        with fluid.program_guard(main):
            x = fluid.layers.data("x", [-1, 4], "float32")
            fluid.layers.scale(x, scale=2.0)       # dead at level>=1
            out = fluid.layers.relu(x)
        exe = fluid.Executor()
        feed = {"x": np.ones((2, 4), np.float32)}
        exe.run(main, feed=feed, fetch_list=[out], optimize_level=0)
        assert exe.last_diagnostics.pass_stats == {}
        exe.run(main, feed=feed, fetch_list=[out], optimize_level=1)
        assert exe.last_diagnostics.pass_stats[
            "dead_op_elimination"]["removed"] == 1
    finally:
        pt.disable_static()
