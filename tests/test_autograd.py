"""Dygraph autograd tests (ref model: tests/unittests/test_imperative_basic.py)."""
import numpy as np

import paddle_tpu as pt


def _leaf(data):
    t = pt.to_tensor(data, stop_gradient=False)
    return t


def test_simple_backward():
    x = _leaf([2.0, 3.0])
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_and_branching():
    x = _leaf([1.0, 2.0])
    a = x * 2
    b = a + x          # x contributes twice
    loss = (b * b).sum()
    loss.backward()
    # b = 3x, loss = 9x^2, dloss/dx = 18x
    np.testing.assert_allclose(x.grad.numpy(), [18.0, 36.0])


def test_grad_accumulation_until_clear():
    x = _leaf([1.0])
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient_blocks():
    x = _leaf([1.0, 1.0])
    y = pt.to_tensor([5.0, 5.0])  # stop_gradient=True
    loss = (x * y).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
    assert y.grad is None


def test_detach():
    x = _leaf([2.0])
    y = (x * x).detach()
    z = y * 3
    assert z.stop_gradient


def test_no_grad_context():
    x = _leaf([2.0])
    with pt.no_grad():
        y = x * x
    assert y.stop_gradient


def test_matmul_grad():
    a = _leaf(np.random.randn(3, 4).astype(np.float32))
    b = _leaf(np.random.randn(4, 5).astype(np.float32))
    (a @ b).sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), np.ones((3, 5)) @ b.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(), a.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_paddle_grad_api():
    x = _leaf([3.0])
    y = x * x
    (gx,) = pt.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [6.0])


def test_grad_intermediate():
    x = _leaf([2.0])
    h = x * x
    y = h * 3.0
    (gh,) = pt.grad(y, h)
    np.testing.assert_allclose(gh.numpy(), [3.0])


def test_grad_allow_unused():
    x = _leaf([1.0])
    z = _leaf([1.0])
    y = x * 2
    gx, gz = pt.grad(y, [x, z], allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_double_backward_raises_without_retain():
    x = _leaf([1.0])
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()  # ok: retained once
    g1 = x.grad.numpy()
    np.testing.assert_allclose(g1, [4.0])


def test_backward_through_slice_and_concat():
    x = _leaf(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = pt.concat([x[0:1], x[1:2] * 2], axis=0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 1, 1], [2, 2, 2]])


def test_hook():
    x = _leaf([1.0])
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_softmax_ce_style_grad():
    logits = _leaf(np.random.randn(4, 10).astype(np.float32))
    labels = np.random.randint(0, 10, (4,))
    p = pt.ops.activation.log_softmax(logits)
    picked = pt.gather_nd(p, pt.to_tensor(np.stack([np.arange(4), labels], axis=1)))
    loss = -picked.mean()
    loss.backward()
    sm = np.exp(p.numpy())
    onehot = np.eye(10)[labels]
    expect = (sm - onehot) / 4
    np.testing.assert_allclose(logits.grad.numpy(), expect, atol=1e-5)
