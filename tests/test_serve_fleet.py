"""serving.fleet: router dispatch traces, tenant fairness/rate limits,
replica failure/requeue, autoscaler hysteresis, the multi-process kill
drill.

Covers the PR's acceptance contract:
- ManualClock dispatch traces are EXACT: least-outstanding-tokens with
  lowest-id tie-break, weighted-deficit tenant fairness, token-bucket
  rate limits that hold one tenant without blocking another;
- router rejection mirrors single-engine ``ServeEngine.submit``
  semantics (oversize / budget-unschedulable / vocab range);
- a killed replica's in-flight requests requeue preserving their
  original ``arrival_t`` AND first-dispatch ``admit_t``, re-dispatch
  in arrival order, and still finish token-for-token equal to the
  dense oracle;
- drain (scale-down) semantics vs kill: a draining replica finishes
  its in-flight work where it is (no requeue) and accepts nothing new;
- ReplicaSupervisor budgets: crash/hang consume per-replica restarts,
  preemptions don't, exhaustion raises with the failure history;
- Autoscaler hysteresis on synthetic SLO series: breach patience,
  low patience, cooldown, min/max clamps — exact decision sequences;
- the multi-process drill (shared with ``tools/chaos_run.py
  replica_kill``): 2 worker replicas, one os._exit'd mid-decode, all
  requests finish oracle-identical and the relaunched replica journals
  ZERO ``via=="xla"`` compiles (AOT-warm from the shared cache).
"""
import atexit
import shutil
import tempfile

import numpy as np
import pytest

from paddle_tpu.serving import FINISHED, ManualClock, TinyLM
from paddle_tpu.serving.fleet import (Autoscaler, ReplicaPool,
                                      ReplicaSpec, Router, TenantPolicy,
                                      TokenBucket)


# one executable cache for every in-process fleet in this module: the
# tests share one TinyLM/pool geometry, so the first replica to build a
# bucket publishes it and every later engine HYDRATES — the suite pays
# each distinct compile once instead of once per replica per test
# (dogfooding the exact scale-up mechanism the drill proves)
_AOT_DIR = tempfile.mkdtemp(prefix="pt_serve_fleet_aot_")
atexit.register(shutil.rmtree, _AOT_DIR, ignore_errors=True)


def _local_fleet(n=2, clock=None, tenants=None, **spec_kw):
    clock = clock or ManualClock()
    kw = dict(vocab_size=32, pages=64, page_size=4, max_seq_len=32,
              token_budget=128, aot_cache_dir=_AOT_DIR, warm=False)
    kw.update(spec_kw)
    from paddle_tpu.resilience import ReplicaSupervisor

    pool = ReplicaPool(ReplicaSpec(**kw), replicas=n, mode="local",
                       clock=clock,
                       supervisor=ReplicaSupervisor(sleep=lambda s: None))
    return Router(pool, clock=clock, tenants=tenants), pool, clock


def _drive(router, clock, max_iters=500, dt=0.01):
    for _ in range(max_iters):
        router.step()
        clock.advance(dt)
        if not router.inflight and not router.queue_depth:
            return
    raise AssertionError("fleet did not drain")


class TestDispatchTraces:
    def test_least_outstanding_with_deterministic_tie_break(self):
        router, pool, clock = _local_fleet()
        # costs 8, 4, 2: rep0 (tie -> lowest id), rep1 (0<8), rep1 (4<8)
        for plen, new in ((4, 4), (2, 2), (1, 1)):
            router.submit([1] * plen, max_new_tokens=new)
        pairs = router.dispatch()
        assert [p[1] for p in pairs] == [0, 1, 1]
        # repeatably deterministic: identical fresh fleet -> same trace
        router2, _, _ = _local_fleet()
        for plen, new in ((4, 4), (2, 2), (1, 1)):
            router2.submit([1] * plen, max_new_tokens=new)
        assert [p[1] for p in router2.dispatch()] == \
            [p[1] for p in pairs]
        router.close()
        router2.close()

    def test_tenant_fairness_interleaves_by_served_deficit(self):
        router, pool, clock = _local_fleet(tenants={
            "a": TenantPolicy(weight=1.0), "b": TenantPolicy(weight=1.0)})
        for i in range(4):
            router.submit([1, 2], max_new_tokens=2, tenant="a",
                          rid=f"a{i}")
        for i in range(2):
            router.submit([3, 4], max_new_tokens=2, tenant="b",
                          rid=f"b{i}")
        order = [rid for rid, _ in router.dispatch()]
        # equal deficits alternate (alphabetical tie-break), strict
        # arrival order inside each tenant
        assert order == ["a0", "b0", "a1", "b1", "a2", "a3"]
        router.close()

    def test_weighted_tenant_gets_proportional_share(self):
        router, pool, clock = _local_fleet(tenants={
            "big": TenantPolicy(weight=2.0),
            "small": TenantPolicy(weight=1.0)})
        for i in range(6):
            router.submit([1, 2], max_new_tokens=2, tenant="big",
                          rid=f"g{i}")
            router.submit([3, 4], max_new_tokens=2, tenant="small",
                          rid=f"s{i}")
        order = [rid for rid, _ in router.dispatch()]
        # weight 2 drains twice as fast: per 4-token request, big's
        # deficit grows half as quickly -> g,g pattern per s
        assert order[:6] == ["g0", "s0", "g1", "g2", "s1", "g3"]
        router.close()

    def test_rate_limit_holds_one_tenant_without_blocking_others(self):
        router, pool, clock = _local_fleet(tenants={
            "lim": TenantPolicy(rate=1.0, burst=4.0)})
        router.submit([5, 6], max_new_tokens=2, tenant="lim", rid="l0")
        router.submit([5, 6], max_new_tokens=2, tenant="lim", rid="l1")
        router.submit([7, 8], max_new_tokens=2, tenant="free",
                      rid="f0")
        # zero deficits tie alphabetically (free < lim); l0's burst
        # covers it, l1 exhausts the bucket and must wait
        assert [r for r, _ in router.dispatch()] == ["f0", "l0"]
        assert router.queue_depth == 1          # l1 waits on the bucket
        clock.advance(3.9)
        assert router.dispatch() == []          # 3.9 tokens < cost 4
        clock.advance(0.2)
        assert [r for r, _ in router.dispatch()] == ["l1"]
        router.close()

    def test_token_bucket_refill_math(self):
        b = TokenBucket(rate=2.0, burst=10.0, now=0.0)
        assert b.take(10, 0.0) and not b.peek(1, 0.0)
        assert not b.take(5, 2.0)    # refilled 4 < 5
        assert b.take(5, 2.5)        # refilled 5
        assert b.peek(10, 100.0) and b.level == 10.0  # capped at burst


class TestRejection:
    def test_rejection_matches_engine_submit_semantics(self):
        router, pool, clock = _local_fleet(pages=16, max_seq_len=16,
                                           token_budget=32)
        eng = pool.replicas[0].engine
        for prompt, new in (([1] * 12, 8),    # > max_seq_len
                            ([1] * 4, 40),    # > max_seq_len
                            ([1, 2], 0),      # max_new < 1
                            ([], 4),          # empty prompt
                            ([99], 4)):       # vocab range
            with pytest.raises(ValueError):
                router.submit(prompt, max_new_tokens=new)
            with pytest.raises(ValueError):
                eng.submit(prompt, max_new_tokens=new)
        assert router.stats()["rejected"] == 5
        router.close()

    def test_duplicate_live_rid_rejected(self):
        router, pool, clock = _local_fleet()
        router.submit([1, 2], max_new_tokens=2, rid="x")
        with pytest.raises(ValueError, match="already queued"):
            router.submit([3, 4], max_new_tokens=2, rid="x")  # queued
        router.dispatch()
        with pytest.raises(ValueError, match="already queued"):
            router.submit([3, 4], max_new_tokens=2, rid="x")  # in flight
        _drive(router, clock)
        # a TERMINAL rid may be reused (retry-with-same-id pattern)
        router.submit([3, 4], max_new_tokens=2, rid="x")
        router.close()

    def test_cost_above_tenant_burst_rejected_at_door(self):
        # a request costlier than its tenant's bucket capacity would
        # head-block that tenant FOREVER (the bucket caps at burst)
        router, pool, clock = _local_fleet(tenants={
            "lim": TenantPolicy(rate=5.0, burst=10.0)})
        with pytest.raises(ValueError, match="burst"):
            router.submit([1] * 5, max_new_tokens=6, tenant="lim")
        # the same request sails through for an unlimited tenant
        router.submit([1] * 5, max_new_tokens=6, tenant="free")
        assert router.stats()["rejected"] == 1
        router.close()

    def test_budget_unschedulable_rejected_at_door(self):
        # cap the scheduler budget BELOW the pool capacity: a request
        # that fits the pool but can never be admitted must be refused
        router, pool, clock = _local_fleet(pages=64, max_seq_len=32,
                                           token_budget=16)
        with pytest.raises(ValueError, match="token_budget"):
            router.submit([1] * 10, max_new_tokens=10)
        router.close()


class TestFailureAndRequeue:
    def test_kill_requeues_preserving_arrival_and_admit_t(self):
        router, pool, clock = _local_fleet()
        clock.advance(1.0)
        reqs = [router.submit([1, 2, 3], max_new_tokens=4,
                              arrival_t=1.0 + i * 0.1, rid=f"r{i}")
                for i in range(4)]
        clock.advance(1.0)
        router.dispatch()
        admits = {r.rid: r.admit_t for r in reqs}
        assert all(t == 2.0 for t in admits.values())
        victims = [r for r in reqs if r.replica_id == 1]
        assert victims
        # late arrival queued BEHIND the victims' original positions
        late = router.submit([4, 5], max_new_tokens=2, rid="late",
                             arrival_t=9.0)
        pool.replicas[1].kill()
        swept = router.check_replicas()
        assert [(rid, reason) for rid, reason, _ in swept] == \
            [(1, "exit")]
        assert router.stats()["requeued"] == len(victims)
        for v in victims:
            assert v.state == "QUEUED" and v.requeues == 1
            assert v.admit_t == admits[v.rid]   # admit_t preserved
        clock.advance(1.0)
        order = [rid for rid, _ in router.dispatch()]
        # requeued victims re-dispatch in original arrival order,
        # strictly before the later arrival
        assert order == [v.rid for v in
                         sorted(victims, key=lambda r: r.arrival_t)] \
            + ["late"]
        _drive(router, clock)
        model = TinyLM(vocab_size=32, seed=0)
        for r in reqs + [late]:
            assert r.state == FINISHED
            assert r.tokens == model.reference_generate(
                r.prompt, r.max_new_tokens)
        router.close()

    def test_relaunch_consumes_supervisor_budget(self):
        from paddle_tpu.resilience import (ElasticBudgetError,
                                           ReplicaSupervisor)

        sup = ReplicaSupervisor(max_restarts=2, backoff_s=0.0,
                                sleep=lambda s: None)
        clock = ManualClock()
        pool = ReplicaPool(
            ReplicaSpec(vocab_size=32, pages=16, page_size=4,
                        max_seq_len=16, token_budget=64),
            replicas=2, mode="local", clock=clock, supervisor=sup)
        router = Router(pool, clock=clock)
        for _ in range(2):
            pool.replicas[1].kill()
            router.check_replicas()
        assert sup.restarts == {1: 2}
        assert len(pool.active()) == 2   # relaunched both times
        pool.replicas[1].kill()
        with pytest.raises(ElasticBudgetError) as ei:
            router.check_replicas()
        assert len(ei.value.history) == 3
        # preemptions never consume the budget
        sup2 = ReplicaSupervisor(max_restarts=1, sleep=lambda s: None)
        for _ in range(5):
            sup2.note_failure(0, kind="preempt")
        assert sup2.preemptions == {0: 5} and sup2.restarts == {}
        router.close()

    def test_drain_finishes_in_place_kill_requeues(self):
        router, pool, clock = _local_fleet()
        a = router.submit([1, 2, 3], max_new_tokens=4, rid="a")
        b = router.submit([4, 5, 6], max_new_tokens=4, rid="b")
        router.dispatch()
        assert (a.replica_id, b.replica_id) == (0, 1)
        draining = pool.replicas[1]
        draining.drain()
        # no new dispatches to a draining replica...
        c = router.submit([7, 8], max_new_tokens=2, rid="c")
        router.dispatch()
        assert c.replica_id == 0
        # ...but its in-flight request finishes where it is: no requeue
        _drive(router, clock)
        assert b.state == FINISHED and b.requeues == 0
        assert b.replica_id == 1
        # drained empty -> retired by poll()
        assert draining.state == "RETIRED"
        assert [r.replica_id for r in pool.active()] == [0]
        assert router.stats()["requeued"] == 0
        router.close()


class TestAutoscaler:
    def test_hysteresis_cooldown_and_bounds(self):
        clock = ManualClock()
        asc = Autoscaler(min_replicas=1, max_replicas=3,
                         queue_high=8.0, queue_low=1.0,
                         ttft_p99_slo_ms=100.0, breach_patience=2,
                         low_patience=3, cooldown_s=10.0, clock=clock)
        hot = {"queue_depth": 20.0, "ttft_p99_ms": 50.0}
        idle = {"queue_depth": 0.0, "ttft_p99_ms": 50.0}
        # one breach is noise; the second (patience 2) scales up
        assert asc.observe(hot, replicas=1) is None
        assert asc.observe(hot, replicas=1) == "up"
        # cooldown swallows further breaches...
        clock.advance(5.0)
        assert asc.observe(hot, replicas=2) is None
        assert asc.observe(hot, replicas=2) is None
        # ...until it expires (patience already re-accumulated)
        clock.advance(6.0)
        assert asc.observe(hot, replicas=2) == "up"
        # at max_replicas, breaches can't scale further
        clock.advance(11.0)
        assert asc.observe(hot, replicas=3) is None
        assert asc.observe(hot, replicas=3) is None
        # idle takes low_patience consecutive quiet ticks
        assert asc.observe(idle, replicas=3) is None
        assert asc.observe(idle, replicas=3) is None
        assert asc.observe(idle, replicas=3) == "down"
        # a breach mid-quiet resets the low counter
        clock.advance(11.0)
        assert asc.observe(idle, replicas=2) is None
        assert asc.observe(hot, replicas=2) is None   # resets lows
        assert asc.observe(idle, replicas=2) is None
        assert asc.observe(idle, replicas=2) is None
        assert asc.observe(idle, replicas=2) == "down"
        # never below min_replicas
        clock.advance(11.0)
        for _ in range(6):
            assert asc.observe(idle, replicas=1) is None

    def test_ttft_slo_breach_scales_up(self):
        clock = ManualClock()
        asc = Autoscaler(max_replicas=2, queue_high=100.0,
                         ttft_p99_slo_ms=200.0, breach_patience=1,
                         cooldown_s=0.0, clock=clock)
        assert asc.observe({"queue_depth": 0.0, "ttft_p99_ms": 350.0},
                           replicas=1) == "up"
        assert asc.decisions[-1][2].startswith("ttft_p99")

    def test_signals_from_scrape_round_trip(self):
        router, pool, clock = _local_fleet()
        router.submit([1, 2, 3], max_new_tokens=4)
        router.dispatch()
        sig = Autoscaler.signals_from_scrape(router.exposition())
        assert sig["queue_depth"] == 0.0
        assert sig["replicas"] == 2
        router.close()

    def test_autoscale_tick_scales_up_then_drains_down(self):
        clock = ManualClock()
        asc = Autoscaler(min_replicas=1, max_replicas=3,
                         queue_high=2.0, queue_low=0.0,
                         breach_patience=1, low_patience=1,
                         cooldown_s=0.0, clock=clock)
        router, pool, clock = _local_fleet(n=1, clock=clock)
        for i in range(6):   # deep queue, nothing dispatched yet
            router.submit([1, 2], max_new_tokens=2, rid=f"q{i}")
        router.autoscaler = asc
        assert router.autoscale_tick() == "up"
        assert len(pool.active()) == 2
        assert router.scale_ups == 1
        router.autoscaler = None   # drive without mid-run decisions
        _drive(router, clock)
        router.autoscaler = asc
        # idle fleet: next tick drains ONE replica (never the last)
        decision = router.autoscale_tick()
        assert decision == "down" and router.scale_downs == 1
        draining = [r for r in pool.replicas if r.draining]
        assert len(draining) == 1
        router.poll()    # empty drain retires immediately
        assert len(pool.active()) == 1
        assert router.autoscale_tick() != "down"   # last replica holds
        router.close()


class TestFleetObservability:
    def test_router_gauges_scrape_bitwise(self):
        from paddle_tpu.obs import export as obs_export

        router, pool, clock = _local_fleet()
        for i in range(3):
            router.submit([1, 2, 3], max_new_tokens=3)
        router.dispatch()
        _drive(router, clock)
        st = router.stats()
        vals = obs_export.parse_prometheus_text(
            "\n".join(obs_export.router_lines(router)) + "\n")
        pre = "paddle_tpu_fleet_router_"
        for key in ("queue_depth", "inflight", "dispatched", "requeued",
                    "rejected", "completed", "replicas"):
            assert vals[pre + key] == float(st[key])
        for key in ("ttft_ms", "e2e_ms"):
            for q in ("p50", "p99"):
                assert vals[pre + key + '{q="' + q + '"}'] == \
                    st[key][q]
        for rep_id, d in st["per_replica"].items():
            assert vals[pre + 'outstanding_tokens{replica="'
                        + str(rep_id) + '"}'] == \
                float(d["outstanding_tokens"])
        router.close()

    def test_merge_expositions_sums_identical_series(self):
        from paddle_tpu.obs.export import (merge_expositions,
                                           parse_prometheus_text)

        a = ("# TYPE paddle_tpu_serving_tokens_generated counter\n"
             "paddle_tpu_serving_tokens_generated 10\n"
             "# TYPE paddle_tpu_serving_slo_running gauge\n"
             'paddle_tpu_serving_slo_running{replica="0"} 2\n')
        b = ("# TYPE paddle_tpu_serving_tokens_generated counter\n"
             "paddle_tpu_serving_tokens_generated 32\n"
             "# TYPE paddle_tpu_serving_slo_running gauge\n"
             'paddle_tpu_serving_slo_running{replica="1"} 1\n')
        merged = merge_expositions([a, b])
        vals = parse_prometheus_text(merged)
        # identical keys sum (process-wide counters across workers)...
        assert vals["paddle_tpu_serving_tokens_generated"] == 42.0
        # ...labelled per-replica series pass through verbatim
        assert vals['paddle_tpu_serving_slo_running{replica="0"}'] == 2.0
        assert vals['paddle_tpu_serving_slo_running{replica="1"}'] == 1.0
        assert merged.count(
            "# TYPE paddle_tpu_serving_tokens_generated counter") == 1

    def test_local_fleet_oracle_identity_across_replicas(self):
        router, pool, clock = _local_fleet(n=3)
        rng = np.random.RandomState(11)
        prompts = [list(map(int, rng.randint(0, 32, rng.randint(3, 8))))
                   for _ in range(9)]
        reqs = [router.submit(p, max_new_tokens=5) for p in prompts]
        router.dispatch()
        _drive(router, clock)
        assert {r.replica_id for r in reqs} == {0, 1, 2}
        model = TinyLM(vocab_size=32, seed=0)
        for r, p in zip(reqs, prompts):
            assert r.tokens == model.reference_generate(p, 5)
        router.close()


class TestReplicaLifecycleRegressions:
    """Pins the failure-path fixes: a buffered ``ready`` must survive
    ``poll()``, a warming replica gets the startup grace (not the
    steady-state hang timeout), and the supervisor backoff never sleeps
    the router thread — the spawn defers to a later health sweep."""

    def _bare_process_replica(self, **spec_kw):
        """ProcessReplica's protocol surface without a live subprocess
        (white-box: these paths are what the multi-process drill only
        exercises when the race actually fires)."""
        import threading
        from collections import deque

        from paddle_tpu.serving.fleet.pool import ProcessReplica

        rep = ProcessReplica.__new__(ProcessReplica)
        rep.replica_id, rep.attempt = 1, 1
        rep.state = "STARTING"
        rep.last_failure = None
        rep._ledger = {}
        rep._events = deque()
        rep._lock = threading.Lock()
        rep._drained = False
        rep.metrics_url = None
        rep.spec = ReplicaSpec(**spec_kw)
        return rep

    def test_poll_promotes_buffered_ready(self):
        # a background relaunch's ready line landing between the health
        # sweep and poll() must promote STARTING -> READY, not vanish
        # with the drained batch (stuck-STARTING = silent capacity loss)
        rep = self._bare_process_replica()
        rep._events.append({"t": "ready", "metrics_port": 4242})
        rep._events.append({"t": "stats", "steps": 7})
        assert rep.poll() == []
        assert rep.state == "READY"
        assert rep.metrics_url == "http://127.0.0.1:4242/metrics"

    def test_starting_replica_gets_full_startup_grace(self, tmp_path):
        import os
        import time as _time

        rep = self._bare_process_replica(hang_timeout_s=0.01,
                                         startup_timeout_s=3600.0)
        hb = tmp_path / "hb.json"
        hb.write_text("{}")
        old = _time.time() - 120.0
        os.utime(hb, (old, old))
        rep.hb_path = str(hb)
        rep.spawned_at = _time.monotonic() - 1.0

        class _Alive:
            def poll(self):
                return None

        rep.proc = _Alive()
        # the worker beats once at boot then warms WITHOUT beating: a
        # stale beat while STARTING is a warm in progress, not a hang
        assert rep.health() is None
        rep.state = "READY"   # post-ready, the same staleness IS a hang
        assert rep.health() == "hung"

    def test_relaunch_backoff_defers_spawn_without_blocking(self):
        from paddle_tpu.resilience import ReplicaSupervisor

        clock = ManualClock()
        slept = []
        pool = ReplicaPool(
            ReplicaSpec(vocab_size=32, pages=16, page_size=4,
                        max_seq_len=16, token_budget=64),
            replicas=2, mode="local", clock=clock, max_replicas=2,
            supervisor=ReplicaSupervisor(backoff_s=5.0, jitter=0.0,
                                         sleep=slept.append))
        router = Router(pool, clock=clock)
        pool.replicas[1].kill()
        router.check_replicas()
        assert slept == []   # the router thread never sleeps a backoff
        assert [r.replica_id for r in pool.active()] == [0]
        # the parked relaunch still counts toward the replica cap: a
        # scale-up during the backoff must not overshoot max_replicas
        assert pool.at_capacity()
        with pytest.raises(RuntimeError, match="max_replicas"):
            pool.scale_up()
        clock.advance(4.9)
        router.check_replicas()   # still inside the backoff window
        assert [r.replica_id for r in pool.active()] == [0]
        clock.advance(0.2)
        router.check_replicas()   # not-before passed -> health sweep spawns
        assert sorted(r.replica_id for r in pool.active()) == [0, 1]
        fresh = [r for r in pool.active() if r.replica_id == 1][0]
        assert fresh.attempt == 1
        router.close()

    def test_autoscale_up_at_pool_capacity_holds(self):
        from paddle_tpu.resilience import ReplicaSupervisor

        clock = ManualClock()
        asc = Autoscaler(min_replicas=1, max_replicas=5,
                         queue_high=1.0, breach_patience=1,
                         cooldown_s=0.0, clock=clock)
        pool = ReplicaPool(
            ReplicaSpec(vocab_size=32, pages=16, page_size=4,
                        max_seq_len=16, token_budget=64),
            replicas=1, mode="local", clock=clock, max_replicas=1,
            supervisor=ReplicaSupervisor(sleep=lambda s: None))
        router = Router(pool, clock=clock, autoscaler=asc)
        for i in range(4):
            router.submit([1, 2], max_new_tokens=2, rid=f"q{i}")
        # the pool's cap can sit below the autoscaler's: "up" holds
        assert router.autoscale_tick() is None
        assert len(pool.active()) == 1 and router.scale_ups == 0
        router.close()

    def test_live_tenant_policy_update_takes_effect(self):
        router, pool, clock = _local_fleet()
        router.submit([1, 2], max_new_tokens=2, tenant="t", rid="t0")
        assert [r for r, _ in router.dispatch()] == ["t0"]  # unlimited
        # queued while unlimited (sails past the submit-time guard)...
        big = router.submit([1] * 4, max_new_tokens=4, tenant="t",
                            rid="big")            # cost 8
        # ...then rate-limit the LIVE tenant (unlimited -> rated): the
        # stale first-sight bucket must not keep serving
        router.tenants["t"] = TenantPolicy(rate=1.0, burst=4.0)
        router.submit([1, 2], max_new_tokens=2, tenant="t", rid="t1")
        router.submit([1, 2], max_new_tokens=2, tenant="t", rid="t2")
        # big (cost 8 > NEW burst 4) could never dispatch: evicted as
        # REJECTED, not left to gridlock the tenant queue forever
        assert [r for r, _ in router.dispatch()] == ["t1"]
        assert big.state == "REJECTED"
        assert router.stats()["rejected"] == 1
        assert router.queue_depth == 1    # t2 waits on the NEW bucket
        clock.advance(4.1)
        assert [r for r, _ in router.dispatch()] == ["t2"]
        # IN-PLACE mutation of the live policy object must apply too
        # (the cache compares a value snapshot, not the instance)
        router.tenants["t"].rate = 8.0
        router.tenants["t"].burst = 8.0
        router.submit([1, 2], max_new_tokens=2, tenant="t", rid="t4")
        router.submit([1, 2], max_new_tokens=2, tenant="t", rid="t5")
        assert [r for r, _ in router.dispatch()] == ["t4", "t5"]
        router.close()


class TestMultiProcessDrill:
    def test_replica_kill_drill_end_to_end(self):
        """The acceptance drill (cached per process, shared with
        tools/chaos_run.py): 2 worker replicas, one killed mid-decode,
        everything finishes oracle-identical, relaunch is AOT-warm."""
        from paddle_tpu.serving.fleet import drill

        res = drill.drill_result()
        assert not res["failures"], res["failures"]
        assert res["stats"]["requeued"] >= 1
        assert all(r["state"] == FINISHED for r in res["requests"])
        for r, ref in zip(res["requests"], res["oracle"]):
            assert r["tokens"] == ref
        assert res["relaunch_via"]["xla"] == 0
        assert res["relaunch_via"]["aot_disk"] >= 2
        assert res["incarnations"] >= 2

    def test_drill_fleet_report_aggregates(self):
        """The drill's run dir is a real fleet run dir: per-rank
        request records merge and the router journal renders."""
        from paddle_tpu.obs import fleet as obs_fleet
        from paddle_tpu.serving.fleet import drill

        res = drill.drill_result()
        assert not res["failures"], res["failures"]
        agg = obs_fleet.aggregate(res["run_dir"])
        assert agg["nranks"] == 2
        req = agg["requests"]
        assert req and req["finished"] >= len(res["requests"])
        rt = agg["router"]
        assert rt and rt["dispatched"] == res["stats"]["dispatched"]
        assert rt["requeued"] == res["stats"]["requeued"]
        assert rt["requeue_events"] >= 1

    def test_drill_requeued_timelines_span_both_replicas(self):
        """Satellite of the reqtrace tentpole, on the CACHED drill: a
        requeued request's assembled timeline carries BOTH dispatch
        segments (victim + re-dispatched replica), its attribution
        shows the requeue loss, and the merged Perfetto export draws
        the cross-pid flow arrow — from journals alone (the workers
        run with span tracing off, so there are no trace files)."""
        from paddle_tpu.obs import reqtrace
        from paddle_tpu.serving.fleet import drill

        res = drill.drill_result()
        assert not res["failures"], res["failures"]
        assert res["requeued_rids"]
        for rid in res["requeued_rids"]:
            segs = res["request_timelines"][rid]
            assert len(segs) >= 2
            assert len({s["replica"] for s in segs}) >= 2
            att = res["request_attribution"][rid]
            assert att["requeue_ms"] > 0
            assert att["dispatches"] >= 2
            # the telescoped phases land on e2e (wall clock here, so
            # close — the nanosecond-exact gate is the ManualClock
            # fixture in tools/request_report.py --self-test)
            assert abs(reqtrace.attribution_sum(att) -
                       att["e2e_ms"]) < 1e-6
        # the crossing is visible in the merged trace
        assert res["merged_trace"]["request_slices"] >= 2
        assert set(res["cross_flow_rids"]) & set(res["requeued_rids"])

    def test_drill_ran_lockdep_enabled_and_clean(self):
        """The cached kill drill runs every worker under
        PADDLE_TPU_LOCKDEP=1 and the parent router side under a scoped
        enable (raise mode): zero PTC004 anywhere."""
        from paddle_tpu.serving.fleet import drill

        res = drill.drill_result()
        assert not res["failures"], res["failures"]
        ld = res["lockdep"]
        assert ld["mode"] == "raise"
        assert ld["parent_cycles"] == []
        assert ld["worker_cycles"] == []
