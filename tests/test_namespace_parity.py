"""fluid optimizer/metrics/dygraph/framework namespace parity tests.

Mirrors the reference __all__ surfaces of fluid/optimizer.py,
fluid/metrics.py (EditDistance, DetectionMAP), fluid/framework.py
(places, flags, device_guard), fluid/clip.py (ErrorClipByValue,
set_gradient_clip), fluid/profiler.py, and fluid/dygraph/* (layer
catalogue, LR decays, save/load_dygraph, ParallelEnv, TracedLayer).
"""
import numpy as np
import pytest
import paddle_tpu as pt
import paddle_tpu.fluid.dygraph as D
import paddle_tpu.fluid as fluid
from paddle_tpu import optim, metrics
import paddle_tpu.ops as ops
from paddle_tpu.nn.layer import Layer
from paddle_tpu.optim.clip import set_gradient_clip


def test_fluid_namespace_parity_drive():
    pt.seed(0)


    class M(Layer):
        def __init__(self):
            super().__init__()
            self.w = self.create_parameter((2,))


    m = None
    for Opt in (optim.DecayedAdagradOptimizer, optim.LarsMomentumOptimizer,
                optim.DpsgdOptimizer):
        m = M()
        o = Opt(0.1, parameters=m.parameters())
        for _ in range(5):
            loss = ops.sum(m.w * m.w)
            loss.backward()
            o.step(); o.clear_grad()
    m = M()
    o = optim.DGCMomentumOptimizer(0.1, 0.9, parameters=m.parameters())
    loss = ops.sum(m.w * m.w); loss.backward(); o.step(); o.clear_grad()
    print("optimizers ok")

    ma = optim.ModelAverage(0.15, parameters=m.parameters())
    ma.step(); ma.apply(); ma.restore()
    ro = optim.RecomputeOptimizer(optim.SGD(0.1, parameters=m.parameters()))
    loss = ops.sum(m.w * m.w); ro.minimize(loss)
    po = optim.PipelineOptimizer(optim.SGD(0.1, parameters=m.parameters()))
    print("wrappers ok")

    set_gradient_clip(optim.ClipGradByGlobalNorm(1.0))
    o2 = optim.SGD(0.1, parameters=m.parameters())
    assert o2._grad_clip is not None
    set_gradient_clip(None)

    ed = metrics.EditDistance()
    ed.update(np.array([0.0, 2.0]), 2)
    avg, err = ed.eval()
    assert avg == 1.0 and err == 0.5
    m_ap = metrics.DetectionMAP(map_type="11point")
    det = np.array([[0, 0.9, 0, 0, 10, 10], [1, 0.8, 20, 20, 30, 30]], "float32")
    gt = np.array([[0, 0, 0, 10, 10], [1, 20, 20, 30, 30]], "float32")
    m_ap.update(det, gt)
    assert abs(m_ap.eval() - 1.0) < 1e-6
    print("metrics ok")

    assert len(fluid.cpu_places(2)) == 2
    fluid.set_flags({"FLAGS_foo": 1})
    assert fluid.get_flags("FLAGS_foo")["FLAGS_foo"] == 1
    with fluid.device_guard("cpu"):
        pass
    print("places/flags ok")

    x = pt.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
    assert list(D.Pool2D(2, "avg", 2)(x).shape) == [2, 3, 4, 4]
    pr = D.PRelu("channel", channel=3)
    assert list(pr(x).shape) == [2, 3, 8, 8]
    sn = D.SpectralNorm()
    w = pt.to_tensor(np.random.randn(6, 4).astype("float32"))
    assert list(sn(w).shape) == [6, 4]
    btp = D.BilinearTensorProduct(4, 5, 3)
    out = btp(pt.to_tensor(np.random.randn(2, 4).astype("float32")),
              pt.to_tensor(np.random.randn(2, 5).astype("float32")))
    assert list(out.shape) == [2, 3]
    nce_l = D.NCE(20, 6)
    l = nce_l(pt.to_tensor(np.random.randn(4, 6).astype("float32")),
              pt.to_tensor(np.random.randint(0, 20, (4, 1))))
    gu = D.GRUUnit(3 * 5)
    nh, rh, g = gu(pt.to_tensor(np.random.randn(2, 15).astype("float32")),
                   pt.to_tensor(np.zeros((2, 5), "float32")))
    assert list(nh.shape) == [2, 5]
    tc = D.TreeConv(4, 6, 2, max_depth=2)
    nodes = pt.to_tensor(np.random.randn(1, 5, 4).astype("float32"))
    edges = pt.to_tensor(np.array([[[0, 1], [0, 2], [1, 3], [0, 0]]], "float32"))
    o = tc(nodes, edges)
    assert list(o.shape) == [1, 5, 6, 2], o.shape
    print("dygraph layers ok")

    import tempfile
    pth = tempfile.mktemp()
    D.save_dygraph(m.state_dict(), pth)
    params, opt_state = D.load_dygraph(pth)
    assert len(params) >= 1
    assert D.enabled()
    env = D.ParallelEnv()
    assert env.nranks >= 1
    bs = D.BackwardStrategy(); bs.sort_sum_gradient = True
    gfn = D.dygraph_to_static_func(lambda a: a * 2)
    print("dygraph utils ok")
    print("NAMESPACE OK")


def test_reference_namespace_all_resolved():
    """Audit: every __all__ name of the reference fluid sub-namespaces
    resolves in the matching paddle_tpu namespace."""
    import ast, os

    def get_all(path):
        names = []
        for node in ast.walk(ast.parse(open(path).read())):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        try:
                            names += ast.literal_eval(node.value)
                        except Exception:
                            pass
        return set(names)

    base = "/root/reference/python/paddle/fluid/"
    if not os.path.isdir(base):
        return
    import paddle_tpu.fluid as PF
    import paddle_tpu.fluid.dygraph as D2
    import paddle_tpu.metrics as MM
    import paddle_tpu.nn.initializer as II
    import paddle_tpu.optim as OO
    import paddle_tpu.optim.clip as CC
    import paddle_tpu.utils.profiler as PP

    checks = {
        "framework.py": dir(PF) + dir(pt.static),
        "metrics.py": dir(MM),
        "initializer.py": dir(II),
        "clip.py": dir(CC),
        "optimizer.py": dir(OO),
        "profiler.py": dir(PP),
    }
    for mod, ours in checks.items():
        missing = sorted(n for n in get_all(base + mod)
                         if n not in set(ours))
        assert missing == [], f"{mod}: {missing}"
    dyg = set()
    for f in os.listdir(base + "dygraph/"):
        if f.endswith(".py"):
            dyg |= get_all(base + "dygraph/" + f)
    missing = sorted(n for n in dyg if n not in set(dir(D2)))
    assert missing == [], f"dygraph: {missing}"


def test_static_2x_surface():
    """paddle.static.create_parameter / static.nn.* resolve and build
    (2.x static spellings next to the fluid ones)."""
    import numpy as np

    import paddle_tpu as pt

    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [4, 8])
            w = pt.static.create_parameter([8, 2])
            h = pt.static.nn.fc(x, size=2)
        exe = pt.static.Executor()
        exe.run(startup)
        (o,) = exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                       fetch_list=[h])
        assert np.asarray(o).shape == (4, 2)
        assert callable(pt.static.nn.conv2d)
        assert callable(pt.static.nn.batch_norm)
    finally:
        pt.disable_static()


def test_reference_paddle_nn_surface_resolves():
    """Every name the reference's python/paddle/nn/__init__.py binds via
    explicit imports (it has no real __all__ — only a commented-out one)
    resolves on paddle_tpu.nn."""
    import ast

    import paddle_tpu.nn as nn

    tree = ast.parse(open(
        "/root/reference/python/paddle/nn/__init__.py").read())
    names = set()
    for node in tree.body:
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name)
    assert names, "harvested nothing from the reference file"
    missing = sorted(n for n in names if not hasattr(nn, n)
                     and not n.startswith("_"))
    assert not missing, missing


def test_reference_paddle_toplevel_surface_resolves():
    """Every name the reference's python/paddle/__init__.py binds (explicit
    imports + __all__) resolves on paddle_tpu — including the long-tail
    check_import_scipy and the fill_constant creation alias."""
    import ast

    tree = ast.parse(open("/root/reference/python/paddle/__init__.py").read())
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    names.update(ast.literal_eval(node.value))
    assert names, "harvested nothing from the reference file"
    missing = sorted(n for n in names if not hasattr(pt, n)
                     and not n.startswith("_"))
    assert not missing, missing
    # the Windows scipy probe is callable and a no-op off-Windows
    pt.check_import_scipy("posix")


def test_2x_module_import_spellings():
    """Reference scripts import the 2.x surfaces as MODULES (ref:
    python/paddle/__init__.py package binds; distributed/launch.py is
    run as ``python -m paddle.distributed.launch``). Each dotted name
    must resolve through the import system, not just attribute access,
    and land on the same object the attribute exposes."""
    import importlib
    import subprocess
    import sys

    for spelling, attr_path in [
        ("paddle_tpu.tensor", "tensor"),
        ("paddle_tpu.tensor.creation", None),
        ("paddle_tpu.io", "io"),
        ("paddle_tpu.metric", "metric"),
        ("paddle_tpu.optimizer", "optimizer"),
        ("paddle_tpu.regularizer", "regularizer"),
        ("paddle_tpu.distributed", "distributed"),
        ("paddle_tpu.distributed.launch", None),
        ("paddle_tpu.fleet", "fleet"),
        ("paddle_tpu.imperative", "imperative"),
        ("paddle_tpu.static", "static"),
        ("paddle_tpu.device", "device"),
    ]:
        mod = importlib.import_module(spelling)
        if attr_path:
            assert getattr(pt, attr_path) is mod, spelling
    assert pt.tensor.concat is pt.concat
    assert pt.io.DataLoader is pt.DataLoader

    # python -m paddle_tpu.distributed.launch resolves (runpy path);
    # --help exits 0 without spawning workers
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch", "--help"],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo"})
    assert r.returncode == 0, r.stderr[-500:]


def test_alias_submodules_share_identity():
    """Submodules imported through an alias package must be the SAME
    module object as the real spelling — a re-executed duplicate would
    carry independent state (e.g. a second dist/env.py whose mesh
    globals the real collectives never see)."""
    import importlib

    a = importlib.import_module("paddle_tpu.distributed.env")
    b = importlib.import_module("paddle_tpu.dist.env")
    assert a is b
    c = importlib.import_module("paddle_tpu.io.dataloader")
    d = importlib.import_module("paddle_tpu.io_.dataloader")
    assert c is d
    assert c.DataLoader is pt.DataLoader
    e = importlib.import_module("paddle_tpu.static.program")
    f = importlib.import_module("paddle_tpu.static_.program")
    assert e is f


def test_fleet_module_superset_of_singleton():
    """Both fleet spellings — the old ``distributed.fleet`` module and
    the ``paddle_tpu.fleet`` auto-parallel package that now owns the
    top-level alias — must expose the full singleton API via PEP 562
    forwarding (old fleet.* call sites resolve unchanged)."""
    import importlib

    m = importlib.import_module("paddle_tpu.distributed.fleet")
    m.init_worker()
    m.stop_worker()
    assert m.worker_num() >= 1
    assert callable(m.build_train_step)
    with pytest.raises(AttributeError):
        m.definitely_not_an_attr

    pkg = importlib.import_module("paddle_tpu.fleet")
    assert pt.fleet is pkg
    pkg.init_worker()
    pkg.stop_worker()
    assert pkg.worker_num() >= 1
    assert callable(pkg.build_train_step)
    assert pkg.DistributedStrategy is m.DistributedStrategy
    assert callable(pkg.auto_parallel)  # the new surface rides the alias
    with pytest.raises(AttributeError):
        pkg.definitely_not_an_attr
