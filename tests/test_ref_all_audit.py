"""Reference-wide export audit: every name declared in ANY ``__all__``
across the reference's python/paddle tree must resolve on the mapped
paddle_tpu namespace. This is the line-by-line completeness check for
SURVEY.md §2 — a name may resolve to a working implementation OR to a
recorded-descope raiser (the import must succeed either way; §4b
descopes are about behavior, not import errors).
"""
import ast
import importlib
import pathlib
import warnings

import pytest

REF = pathlib.Path("/root/reference/python/paddle")

# reference-side __all__ entries that are not real export names
_REF_ARTIFACTS = {
    # conll05.py __all__ has a malformed entry 'test, get_dict' (one
    # string); the audit splits it, nothing to skip beyond that
}


def _harvest():
    out = []
    for py in sorted(REF.rglob("*.py")):
        rel = py.relative_to(REF)
        if {"tests", "proto", "libs"} & set(rel.parts):
            continue
        try:
            with warnings.catch_warnings():
                # the reference's own docstrings carry invalid escape
                # sequences; their SyntaxWarnings aren't ours to fix
                warnings.simplefilter("ignore", SyntaxWarning)
                tree = ast.parse(py.read_text())
        except SyntaxError:
            continue
        names = []
        for node in tree.body:
            target = None
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        target = node.value
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id == "__all__":
                target = node.value
            if target is not None:
                try:
                    vals = ast.literal_eval(target)
                except ValueError:
                    continue
                for v in vals:
                    # a reference-side typo packs several names in one
                    # string ('test, get_dict' in dataset/conll05.py)
                    names.extend(x.strip() for x in v.split(","))
        names = [n for n in names if n and not n.startswith("_")
                 and n not in _REF_ARTIFACTS]
        if names:
            out.append((str(rel), names))
    return out


def _candidates(path):
    """Namespaces a reference module's exports may resolve on: the
    same dotted path (module import OR attribute chain), each parent,
    and the flat fluid/top-level namespaces the reference star-imports
    into."""
    mods = []

    def by_import(name):
        try:
            mods.append(importlib.import_module(name))
            return True
        except ImportError:
            return False

    def by_attr_chain(dotted):
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            try:
                obj = importlib.import_module(".".join(parts[:i]))
            except ImportError:
                continue
            try:
                for attr in parts[i:]:
                    obj = getattr(obj, attr)
            except AttributeError:
                continue
            mods.append(obj)
            return True
        return False

    dotted = "paddle_tpu." + path[:-3].replace("/", ".")
    if dotted.endswith(".__init__"):
        dotted = dotted[: -len(".__init__")]
    by_import(dotted) or by_attr_chain(dotted)
    parts = dotted.split(".")
    for i in range(len(parts) - 1, 1, -1):
        prefix = ".".join(parts[:i])
        by_import(prefix) or by_attr_chain(prefix)
    if path.startswith("fluid/"):
        # ref fluid/__init__ star-imports framework/executor/layers
        for extra in ("paddle_tpu.fluid", "paddle_tpu.fluid.layers"):
            by_import(extra)
    if path.startswith(("nn/", "tensor/", "framework/")):
        by_import("paddle_tpu.nn")
        by_import("paddle_tpu.nn.functional")
    by_import("paddle_tpu")
    return mods


def test_every_reference_export_resolves():
    report = _harvest()
    assert len(report) > 100, "harvest looks broken"
    total = sum(len(names) for _, names in report)
    assert total > 700, "harvest looks broken"
    missing = []
    for path, names in report:
        cands = _candidates(path)
        for n in names:
            if not any(hasattr(m, n) for m in cands):
                missing.append(f"{path}: {n}")
    assert not missing, (
        f"{len(missing)}/{total} reference exports unresolved:\n"
        + "\n".join(missing))


def test_new_dataset_helpers_behave():
    """The audit's last closures are real: image loaders decode, the
    tar batcher writes batches, movielens info tables agree with the
    readers' id spaces."""
    import io
    import pickle
    import tarfile
    import tempfile

    import numpy as np
    from PIL import Image

    import paddle_tpu.dataset as D

    # image loaders
    img = Image.fromarray(
        (np.arange(48).reshape(4, 4, 3) * 5).astype("uint8"))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    arr = D.image.load_image_bytes(buf.getvalue())
    assert arr.shape == (4, 4, 3)
    gray = D.image.load_image_bytes(buf.getvalue(), is_color=False)
    assert gray.shape == (4, 4)

    with tempfile.TemporaryDirectory() as d:
        import os

        p = os.path.join(d, "im.png")
        img.resize((40, 40)).save(p)
        out = D.image.load_and_transform(p, 32, 24, is_train=False)
        assert out.shape == (3, 24, 24)

        # tar batcher
        tar_path = os.path.join(d, "imgs.tar")
        with tarfile.open(tar_path, "w") as tf:
            tf.add(p, arcname="im.png")
        meta = D.image.batch_images_from_tar(
            tar_path, "unit", {"im.png": 7}, num_per_batch=2)
        batch_file = open(meta).read().splitlines()[0]
        blob = pickle.load(open(batch_file, "rb"))
        assert blob["label"] == [7]
        assert D.image.load_image_bytes(blob["data"][0]).ndim == 3

    # movielens info tables
    ui = D.movielens.user_info()
    mi = D.movielens.movie_info()
    assert len(ui) == D.movielens.max_user_id()
    assert max(m.index for m in mi.values()) == D.movielens.max_movie_id()
    first = mi[1].value()
    assert isinstance(first[0], int) and first[1] and first[2]
    assert D.movielens.age_table[0] == 1
    u = ui[1].value()
    assert u[2] < len(D.movielens.age_table)

    # imdb build_dict is the corpus dict
    assert D.imdb.build_dict() == D.imdb.word_dict()
