"""AOT executable cache (``paddle_tpu.runtime.aot``): cross-process
hydration, content-key drift, and per-site wiring.

The ISSUE-12 acceptance gates live here: a second process cold-starting
over a warm cache must record ZERO in-process XLA compiles in its run
journal and produce bitwise-identical fetches; any CacheKey drift
(changed feed shape, fused step count, parallelism layout) must MISS
and recompile — a stale load is structurally impossible because the key
is a content hash of the lowered module.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.runtime import aot

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_process_cache():
    """Tests drive the cache explicitly; none may leak one into the
    suite (configure() state or env would silently flip EVERY later
    compile onto the eager AOT path)."""
    saved = os.environ.pop(aot.ENV_DIR, None)
    yield
    aot.configure(None)
    if saved is not None:
        os.environ[aot.ENV_DIR] = saved


def _load_events(run_dir, kinds=("compile",)):
    evs = []
    for name in sorted(os.listdir(run_dir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(run_dir, name)) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if r.get("t") == "event" and r.get("kind") in kinds:
                    evs.append(r)
    return evs


# -- cross-process hydration (the acceptance gate) ---------------------------


_PROC_SCRIPT = """
import os, sys
sys.path.insert(0, {root!r})
import numpy as np
import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu import optim

pt.seed(0)
rng = np.random.RandomState(0)
x = rng.randn(8, 4).astype("float32")
y = rng.randn(8, 1).astype("float32")
pt.enable_static()
try:
    main, startup = pt.static.Program(), pt.static.Program()
    with pt.program_guard(main, startup):
        xv = pt.static.data("x", [8, 4], "float32")
        yv = pt.static.data("y", [8, 1], "float32")
        h = pt.static.nn.fc(xv, 16, act="relu")
        out = pt.static.nn.fc(h, 1)
        loss = F.mse_loss(out, yv)
        optim.SGD(0.1).minimize(loss)
finally:
    pt.disable_static()
exe = pt.static.Executor()
exe.run(startup)
# two per-step dispatches + one fused K=2 window: both the single-step
# and the steps=K scan entries must ride the cache
outs = [np.asarray(exe.run(main, feed={{"x": x, "y": y}},
                           fetch_list=[loss])[0]) for _ in range(2)]
fused = exe.run_steps(main, feeds=[{{"x": x, "y": y}}] * 2,
                      fetch_list=[loss])
np.savez(os.path.join({out!r}), steps=np.stack(outs),
         fused=np.asarray(fused[0]))
"""


def _run_proc(tmp_path, tag, cache_dir):
    run_dir = str(tmp_path / f"run_{tag}")
    out = str(tmp_path / f"out_{tag}.npz")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PADDLE_TPU_AOT_CACHE=cache_dir, PADDLE_TPU_RUN_DIR=run_dir)
    r = subprocess.run(
        [sys.executable, "-c",
         _PROC_SCRIPT.format(root=ROOT, out=out)],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    return run_dir, np.load(out)


def test_second_process_cold_start_zero_compiles_bitwise(tmp_path):
    """Process A compiles + publishes; process B runs the SAME build
    with zero in-process XLA compiles — every compile event is
    via="aot_disk" — and bitwise-identical per-step AND fused
    fetches."""
    cache_dir = str(tmp_path / "cache")
    run_a, out_a = _run_proc(tmp_path, "a", cache_dir)
    run_b, out_b = _run_proc(tmp_path, "b", cache_dir)

    ev_a = _load_events(run_a)
    assert ev_a and all(e.get("via") == "xla" for e in ev_a), ev_a
    ev_b = _load_events(run_b)
    # THE gate: a warm cold start compiles nothing in-process
    assert ev_b and [e for e in ev_b if e.get("via") == "xla"] == [], ev_b
    assert sum(e.get("via") == "aot_disk" for e in ev_b) >= 2  # step+fused
    for e in ev_b:
        assert e.get("deserialize_ms", 0) >= 0
    assert np.array_equal(out_a["steps"], out_b["steps"])
    assert np.array_equal(out_a["fused"], out_b["fused"])


# -- content-key drift --------------------------------------------------------


def _build_fc(batch):
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optim

    pt.seed(0)
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            xv = pt.static.data("x", [batch, 4], "float32")
            yv = pt.static.data("y", [batch, 1], "float32")
            loss = F.mse_loss(pt.static.nn.fc(xv, 4), yv)
            optim.SGD(0.1).minimize(loss)
    finally:
        pt.disable_static()
    return main, startup, loss


def _first_entry(exe):
    return next(iter(exe._cache.values()))


def test_cachekey_drift_misses_and_recompiles(tmp_path):
    """Changed feed shape, fused step count, or parallelism layout each
    produce a DIFFERENT content digest: a fresh compile, never a stale
    load — and the recompiled entries coexist in the cache."""
    cache = aot.configure(str(tmp_path / "cache"))
    rng = np.random.RandomState(0)

    def run(batch, steps=None, dp=False):
        main, startup, loss = _build_fc(batch)
        prog = main
        if dp:
            from paddle_tpu.static_.compiler import CompiledProgram

            prog = CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
        exe = pt.static.Executor()
        exe.run(startup)
        feed = {"x": rng.randn(batch, 4).astype("float32"),
                "y": rng.randn(batch, 1).astype("float32")}
        if steps:
            exe.run_steps(prog, feeds=[feed] * steps, fetch_list=[loss])
        else:
            exe.run(prog, feed=feed, fetch_list=[loss])
        return _first_entry(exe).aot_info

    base = run(8)
    assert base["source"] == "xla" and base["stored"]
    digests = {base["digest"]}
    for info in (run(16),            # feed-shape drift
                 run(8, steps=2),    # fused-K drift
                 run(8, steps=4),    # a different K is a different scan
                 run(8, dp=True)):   # layout drift (sharded module)
        assert info["source"] == "xla", info   # miss -> fresh compile
        assert info["digest"] not in digests, "stale digest reused"
        digests.add(info["digest"])
    # and the original still hydrates (nothing evicted or clobbered)
    again = run(8)
    assert again["source"] == "aot_disk", again
    assert cache.stats()["entries"] == len(digests)


# -- per-site wiring ----------------------------------------------------------


def test_trainstep_hydrates_bitwise(tmp_path):
    """Eager path: a rebuilt TrainStep over the same model (identical
    param names = identical calling convention; the opt-state dict
    keys are part of the digest) hydrates its per-signature executable
    from disk and reproduces the first build's loss trajectory
    bitwise. A model with DIFFERENT param names must miss instead —
    its treedef is a different calling convention."""
    import jax.numpy as jnp

    import paddle_tpu.nn as nn

    cache = aot.configure(str(tmp_path / "cache"))
    x = np.random.RandomState(0).randn(8, 16).astype("float32")
    y = np.random.RandomState(1).randn(8, 4).astype("float32")
    pt.seed(0)
    m = nn.Linear(16, 4)
    init = [np.asarray(p._data).copy() for p in m.parameters()]

    def losses():
        for p, a in zip(m.parameters(), init):
            p._data = jnp.asarray(a)  # rewind to the pristine replica
        opt = pt.optim.SGD(parameters=m.parameters(), learning_rate=0.1)
        step = pt.TrainStep(m, opt,
                            lambda mm, a, b: ((mm(a) - b) ** 2).mean())
        return [float(np.asarray(step(x, y)._data)) for _ in range(3)]

    la = losses()
    assert cache.stats()["stores"] == 1
    lb = losses()
    assert cache.stats()["hits"] == 1
    assert la == lb  # bitwise: identical executable, identical inputs

    # same math, new param NAMES: treedef drift -> a clean miss
    m2 = nn.Linear(16, 4)
    opt2 = pt.optim.SGD(parameters=m2.parameters(), learning_rate=0.1)
    pt.TrainStep(m2, opt2,
                 lambda mm, a, b: ((mm(a) - b) ** 2).mean())(x, y)
    assert cache.stats()["stores"] == 2


def test_predictor_warm_export_and_hydration(tmp_path):
    """save_inference_model with a cache active ships a warm batch-1
    entry (the Predictor-path executable); a fresh Predictor then
    hydrates it and matches a cache-less Predictor bitwise."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.io import save_inference_model
    from paddle_tpu.inference.predictor import Config, Predictor

    prefix = str(tmp_path / "model" / "m")
    pt.seed(0)
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            xv = pt.static.data("x", [1, 8], "float32")
            out = F.softmax(pt.static.nn.fc(xv, 4))
        exe = pt.static.Executor()
        exe.run(startup)
        cache = aot.configure(str(tmp_path / "cache"))
        save_inference_model(prefix, [xv], [out], exe,
                             program=main)
    finally:
        pt.disable_static()
        aot.configure(None)
    assert cache.stats()["stores"] >= 1  # the warm export published

    x = np.random.RandomState(0).randn(1, 8).astype("float32")
    oracle = Predictor(Config(prefix)).run({"x": x})[0]

    cfg = Config(prefix)
    cfg.aot_cache_dir = cache.dir
    hits0 = cache.stats()["hits"]
    got = Predictor(cfg).run({"x": x})[0]
    assert cache.stats()["hits"] == hits0 + 1
    assert np.array_equal(oracle, got)


def test_serve_engine_hydrates_identical_tokens(tmp_path):
    """A rebuilt ServeEngine replica hydrates its prefill + decode
    bucket executables from disk and generates identical tokens."""
    from paddle_tpu.serving.engine import ServeEngine, TinyLM
    from paddle_tpu.serving.kv_cache import PagedKVCache

    cache_dir = str(tmp_path / "cache")

    def serve():
        model = TinyLM(vocab_size=32, num_heads=2, head_dim=8, seed=3)
        kv = PagedKVCache(16, 4, 2, 8, max_seq_len=16)
        eng = ServeEngine(model, kv, aot_cache_dir=cache_dir)
        r = eng.submit([3, 1, 4, 1, 5], max_new_tokens=6)
        eng.run()
        return list(r.generated)

    toks_a = serve()
    cache = aot.resolve_cache(cache_dir)
    stores = cache.stats()["stores"]
    assert stores >= 2  # prefill bucket + decode bucket
    toks_b = serve()
    assert cache.stats()["hits"] >= 2
    assert cache.stats()["stores"] == stores  # nothing recompiled
    assert toks_a == toks_b


def test_hydrated_entry_keeps_donation(tmp_path):
    """perf_gate.donation_stats on a hydrated Executor entry: the
    donated persistable carry survives the serialize round-trip (the
    acceptance criterion's donation check)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "pg_aot", os.path.join(ROOT, "tools", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)

    aot.configure(str(tmp_path / "cache"))
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 4).astype("float32"),
            "y": rng.randn(8, 1).astype("float32")}

    def entry():
        main, startup, loss = _build_fc(8)
        exe = pt.static.Executor()
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        return _first_entry(exe)

    entry()                       # publish
    hydrated = entry()            # hydrate
    assert (hydrated.aot_info or {}).get("source") == "aot_disk"
    stats = pg.donation_stats(pg.entry_hlo(hydrated))
    assert stats["count"] >= 1, stats
