"""Sequence-labeling family tests: CRF (brute-force parity), chunk_eval,
edit_distance (numpy DP parity), NCE/hsigmoid/sampled-softmax, and a
label-semantic-roles-style BiLSTM-CRF book training test
(ref: tests/book/test_label_semantic_roles.py)."""
import itertools

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops


def _crf_brute(emission, labels_all, trans, length):
    """Brute-force log Z and gold scores for tiny (L, T)."""
    start, stop, pair = trans[0], trans[1], trans[2:]
    T = emission.shape[1]

    def score(path):
        s = start[path[0]] + emission[0, path[0]]
        for t in range(1, length):
            s += pair[path[t - 1], path[t]] + emission[t, path[t]]
        return s + stop[path[length - 1]]

    scores = [score(p) for p in
              itertools.product(range(T), repeat=length)]
    return np.logaddexp.reduce(scores), score


class TestCRF:
    def _setup(self, B=2, L=4, T=3, seed=0):
        rng = np.random.RandomState(seed)
        em = rng.randn(B, L, T).astype("float32")
        trans = rng.randn(T + 2, T).astype("float32") * 0.5
        lab = rng.randint(0, T, (B, L)).astype("int64")
        return em, trans, lab

    def test_nll_matches_bruteforce(self):
        em, trans, lab = self._setup()
        B, L, T = em.shape
        nll = np.asarray(ops.linear_chain_crf(
            pt.to_tensor(em), pt.to_tensor(lab),
            transition=pt.to_tensor(trans)).numpy())
        for b in range(B):
            logz, score = _crf_brute(em[b], None, trans, L)
            want = logz - score(lab[b])
            assert nll[b] == pytest.approx(want, rel=1e-4)

    def test_nll_respects_length(self):
        em, trans, lab = self._setup()
        B, L, T = em.shape
        lens = np.array([2, 3], "int32")
        nll = np.asarray(ops.linear_chain_crf(
            pt.to_tensor(em), pt.to_tensor(lab),
            length=pt.to_tensor(lens),
            transition=pt.to_tensor(trans)).numpy())
        for b in range(B):
            logz, score = _crf_brute(em[b], None, trans, lens[b])
            want = logz - score(lab[b])
            assert nll[b] == pytest.approx(want, rel=1e-4)

    def test_decoding_matches_bruteforce(self):
        em, trans, _ = self._setup(seed=3)
        B, L, T = em.shape
        path, best = ops.crf_decoding(pt.to_tensor(em),
                                      transition=pt.to_tensor(trans))
        path = np.asarray(path.numpy())
        best = np.asarray(best.numpy())
        for b in range(B):
            _, score = _crf_brute(em[b], None, trans, L)
            want_path = max(itertools.product(range(T), repeat=L),
                            key=score)
            np.testing.assert_array_equal(path[b], want_path)
            assert best[b] == pytest.approx(score(want_path), rel=1e-4)

    def test_crf_grads_flow(self):
        em, trans, lab = self._setup()
        emt = pt.to_tensor(em); emt.stop_gradient = False
        trt = pt.to_tensor(trans); trt.stop_gradient = False
        nll = ops.linear_chain_crf(emt, pt.to_tensor(lab), transition=trt)
        nll.mean().backward()
        assert np.isfinite(np.asarray(emt.grad.numpy())).all()
        assert np.abs(np.asarray(trt.grad.numpy())).sum() > 0


class TestChunkEval:
    def test_iob_perfect(self):
        # 2 types, IOB: B0=0 I0=1 B1=2 I1=3 O=4
        label = np.array([[0, 1, 4, 2, 3, 4]], "int64")
        p, r, f1, ni, nl, nc = ops.chunk_eval(label, label, "IOB", 2)
        assert (p, r, f1) == (1.0, 1.0, 1.0)
        assert ni == nl == nc == 2

    def test_iob_partial(self):
        label = np.array([[0, 1, 4, 2, 3, 4]], "int64")
        pred = np.array([[0, 1, 4, 4, 2, 4]], "int64")  # 2nd chunk moved
        p, r, f1, ni, nl, nc = ops.chunk_eval(pred, label, "IOB", 2)
        assert nc == 1 and nl == 2 and ni == 2
        assert p == pytest.approx(0.5) and r == pytest.approx(0.5)

    def test_seq_length_mask(self):
        label = np.array([[0, 1, 0, 0]], "int64")
        pred = np.array([[0, 1, 4, 4]], "int64")
        p, r, f1, ni, nl, nc = ops.chunk_eval(
            pred, label, "IOB", 2,
            seq_length=np.array([2], "int64"))
        assert nc == 1 and nl == 1 and ni == 1 and f1 == 1.0


def _edit_np(h, r):
    dp = np.zeros((len(h) + 1, len(r) + 1), np.int64)
    dp[:, 0] = np.arange(len(h) + 1)
    dp[0, :] = np.arange(len(r) + 1)
    for i in range(1, len(h) + 1):
        for j in range(1, len(r) + 1):
            dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                           dp[i - 1, j - 1] + (h[i - 1] != r[j - 1]))
    return dp[-1, -1]


class TestEditDistance:
    def test_matches_numpy_dp(self):
        rng = np.random.RandomState(1)
        hyp = rng.randint(0, 5, (4, 7)).astype("int64")
        ref = rng.randint(0, 5, (4, 9)).astype("int64")
        hl = np.array([7, 5, 3, 7], "int64")
        rl = np.array([9, 4, 9, 1], "int64")
        d, n = ops.edit_distance(hyp, ref, normalized=False,
                                 input_length=hl, label_length=rl)
        d = np.asarray(d.numpy())
        assert int(np.asarray(n.numpy())) == 4
        for b in range(4):
            assert d[b] == _edit_np(hyp[b, :hl[b]], ref[b, :rl[b]])

    def test_normalized_and_ignored(self):
        hyp = np.array([[1, 0, 2, 0]], "int64")
        ref = np.array([[1, 2, 3]], "int64")
        d, _ = ops.edit_distance(hyp, ref, normalized=True,
                                 ignored_tokens=[0])
        # hyp -> [1,2]; ref [1,2,3]: distance 1, normalized by 3
        assert float(np.asarray(d.numpy())[0]) == pytest.approx(1 / 3)


class TestSampledLosses:
    def test_nce_trains_classifier(self):
        rng = np.random.RandomState(0)
        V, D, B = 32, 8, 16
        pt.seed(0)
        W = pt.to_tensor(rng.randn(V, D).astype("float32") * 0.1)
        W.stop_gradient = False
        x = rng.randn(B, D).astype("float32")
        y = rng.randint(0, V, (B,)).astype("int64")
        loss0 = None
        for i in range(60):
            loss = ops.nce(pt.to_tensor(x), pt.to_tensor(y), V,
                           num_neg_samples=8, weight=W).mean()
            if loss0 is None:
                loss0 = float(loss)
            loss.backward()
            W._replace(W._data - 0.5 * W.grad._data)
            W.grad = None
        assert float(loss) < loss0
        # full softmax accuracy should now favor the true class
        logits = x @ np.asarray(W.numpy()).T
        assert (logits.argmax(-1) == y).mean() > 0.5

    def test_hsigmoid_loss_decreases_and_classifies(self):
        rng = np.random.RandomState(1)
        C, D, B = 8, 16, 32
        pt.seed(1)
        W = pt.to_tensor(rng.randn(C - 1, D).astype("float32") * 0.1)
        W.stop_gradient = False
        x = rng.randn(B, D).astype("float32")
        y = rng.randint(0, C, (B,)).astype("int64")
        losses = []
        for i in range(80):
            loss = ops.hsigmoid(pt.to_tensor(x), pt.to_tensor(y), C,
                                weight=W).mean()
            losses.append(float(loss))
            loss.backward()
            W._replace(W._data - 0.5 * W.grad._data)
            W.grad = None
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_sampled_softmax_close_to_full(self):
        """With num_samples ~ vocab the sampled loss tracks full CE."""
        rng = np.random.RandomState(2)
        V, D, B = 16, 8, 64
        pt.seed(2)
        x = rng.randn(B, D).astype("float32")
        W = rng.randn(V, D).astype("float32") * 0.5
        y = rng.randint(0, V, (B,)).astype("int64")
        loss = ops.sampled_softmax_with_cross_entropy(
            input=pt.to_tensor(x), label=pt.to_tensor(y),
            weight=pt.to_tensor(W), num_samples=200)
        full = x @ W.T
        full = full - full.max(-1, keepdims=True)
        logp = full - np.log(np.exp(full).sum(-1, keepdims=True))
        want = -logp[np.arange(B), y]
        got = float(np.asarray(loss.numpy()).mean())
        # sampled-with-replacement underestimates slightly; just require
        # the same ballpark
        assert abs(got - want.mean()) / want.mean() < 0.35


class TestSemanticRolesBook:
    def test_bilstm_crf_trains(self):
        """Compact label-semantic-roles recipe: embedding -> BiLSTM ->
        linear emissions -> CRF loss; viterbi F1 improves
        (ref: tests/book/test_label_semantic_roles.py)."""
        import paddle_tpu.nn as nn
        from paddle_tpu import optim

        rng = np.random.RandomState(0)
        V, T, B, L, D = 40, 5, 16, 8, 16
        pt.seed(0)

        # synthetic task: tag depends on word id bucket
        words = rng.randint(0, V, (B, L)).astype("int64")
        tags = (words % T).astype("int64")

        class Tagger(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, D)
                self.lstm = nn.LSTM(D, D, direction="bidirect")
                self.fc = nn.Linear(2 * D, T)
                self.trans = self.create_parameter(
                    [T + 2, T], default_initializer=pt.nn.initializer
                    .Normal(0.0, 0.1))

            def forward(self, w):
                h, _ = self.lstm(self.emb(w))
                return self.fc(h)

        model = Tagger()
        opt = optim.Adam(5e-3, parameters=model.parameters())

        def loss_fn(m, w, t):
            em = m(w)
            return ops.linear_chain_crf(em, t, transition=m.trans).mean()

        step = pt.TrainStep(model, opt, loss_fn)
        losses = [float(step(words, tags)) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        em = model(pt.to_tensor(words))
        path, _ = ops.crf_decoding(em, transition=model.trans)
        acc = (np.asarray(path.numpy()) == tags).mean()
        assert acc > 0.8, acc


class TestChunkEvaluator:
    def test_streaming_counts(self):
        from paddle_tpu.metrics import ChunkEvaluator

        m = ChunkEvaluator(chunk_scheme="IOB", num_chunk_types=2)
        label = np.array([[0, 1, 4, 2, 3, 4]], "int64")
        pred = np.array([[0, 1, 4, 4, 2, 4]], "int64")
        m.update(pred, label)
        m.update(label, label)
        p, r, f1 = m.accumulate()
        assert p == pytest.approx(3 / 4)
        assert r == pytest.approx(3 / 4)
        m.reset()
        assert m.accumulate() == (0.0, 0.0, 0.0)
