"""Flight recorder (obs.journal) + MFU/goodput (obs.mfu) + anomaly
detectors (obs.anomaly): the per-run telemetry layer over PR 3's
process-wide instruments.

Covers the PR's acceptance contract:
- a GuardedStep training loop under chaos (nonfinite feed +
  transient_execute) journals step records, retry/skip events, a fired
  nonfinite_streak anomaly, and an MFU/goodput run summary;
- with no journal configured the hooks perform zero journal work beyond
  a single None check (asserted by poisoning the RunJournal methods);
- two threads stepping one journal interleave to valid JSONL;
- an exception mid-run still yields a parseable postmortem file.
"""
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import obs, optim
from paddle_tpu.obs import anomaly, journal, mfu
from paddle_tpu.resilience import (GuardedExecutor, GuardedStep,
                                   RecoveryPolicy, inject)

NOSLEEP = {"sleep": lambda s: None}


@pytest.fixture(autouse=True)
def _no_global_journal():
    """Tests install journals explicitly; never leak one across tests."""
    yield
    if journal.ACTIVE is not None:
        journal.ACTIVE.close()
    journal.ACTIVE = None


def _read_journal(run_dir):
    out = []
    with open(os.path.join(run_dir, "journal.jsonl")) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def _load_run_report():
    """The tools/run_report.py module, loaded the way test_tooling's
    _load_tool does (tools/ is not a package)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "run_report_under_test", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "run_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _eager_guard(policy_kw=None):
    pt.seed(0)
    m = nn.Linear(4, 1)
    opt = optim.SGD(learning_rate=0.1, parameters=m.parameters())
    step = pt.TrainStep(m, opt, lambda mm, x, y: F.mse_loss(mm(x), y),
                        check_nan=True)
    pol = RecoveryPolicy(**{"on_nonfinite": "skip_step", **NOSLEEP,
                            **(policy_kw or {})})
    return GuardedStep(step, pol)


def _batches(n, batch=8):
    rng = np.random.RandomState(0)
    return [(rng.randn(batch, 4).astype(np.float32),
             rng.randn(batch, 1).astype(np.float32)) for _ in range(n)]


def _static_loop(exe, steps=3):
    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[8, 4])
        y = fluid.data(name="y", shape=[8, 1])
        out = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe.run(startup)
    for bx, by in _batches(steps):
        exe.run(prog, feed={"x": bx, "y": by}, fetch_list=[loss])


# -- acceptance: guarded chaos run produces the full flight record -----------


class TestGuardedChaosRun:
    def test_journal_has_steps_retries_skips_anomaly_and_summary(
            self, tmp_path):
        run_dir = str(tmp_path / "run")
        obs.start_run(run_dir, flush_every=1)
        guard = _eager_guard()
        # nonfinite feed for 3 CONSECUTIVE steps (the streak detector's
        # default threshold) + two transient execute faults retried away
        with inject.chaos("nan_feed", at=3, times=3, seed=7):
            with inject.chaos("transient_execute", times=2):
                for x, y in _batches(8):
                    guard(x, y)
        assert guard.stats.skipped == 3 and guard.stats.retries == 2
        summary = obs.end_run()

        recs = _read_journal(run_dir)
        types = {}
        for r in recs:
            types[r["t"]] = types.get(r["t"], 0) + 1
        assert types.get("run_start") == 1 and types.get("run_end") == 1
        assert types.get("step") == 8

        steps = [r for r in recs if r["t"] == "step"]
        assert sum(1 for s in steps if s.get("skipped")) == 3
        good = [s for s in steps if not s.get("skipped")]
        assert all(isinstance(s["loss"], float) for s in good)
        assert all(s.get("step_ms", 0) > 0 for s in steps)

        kinds = [r["kind"] for r in recs if r["t"] == "event"]
        assert kinds.count("resilience.retry") == 2
        assert kinds.count("resilience.skipped") == 3
        assert kinds.count("resilience.nonfinite") == 3
        assert "chaos.activate" in kinds  # the drill is in the record

        fired = {r["name"] for r in recs if r["t"] == "anomaly"}
        assert "nonfinite_streak" in fired

        # MFU/goodput summary: 8 productive-attempted, 3 skipped + 2
        # retried burned; eager path has no cost_analysis flops => mfu
        # is None but goodput accounting must be exact
        assert summary["goodput"] == pytest.approx(5 / 10)
        assert summary["skipped_steps"] == 3 and summary["retries"] == 2
        end = [r for r in recs if r["t"] == "run_end"][0]
        assert end["summary"]["goodput"] == pytest.approx(5 / 10)
        assert "mfu" in end["summary"]

    def test_static_guarded_executor_steps_and_flops(self, tmp_path):
        run_dir = str(tmp_path / "run")
        obs.start_run(run_dir, flush_every=1)
        pt.enable_static()
        try:
            gexe = GuardedExecutor(policy=RecoveryPolicy(**NOSLEEP))
            with inject.chaos("transient_execute", times=1):
                _static_loop(gexe, steps=3)
        finally:
            pt.disable_static()
        obs.end_run()
        recs = _read_journal(run_dir)
        steps = [r for r in recs if r["t"] == "step"]
        assert len(steps) == 3 and all(
            s["source"] == "executor" for s in steps)
        # first step carries the compile (jit-cache miss delta), later
        # ones are hits; CPU cost_analysis reports flops for MFU
        assert steps[0]["jit_cache"]["misses"] >= 1
        assert steps[-1]["jit_cache"]["hits"] >= 1
        assert [r for r in recs if r["t"] == "event"
                and r["kind"] == "compile"]
        assert all(s.get("examples") == 8 for s in steps)
        summary = [r for r in recs if r["t"] == "run_end"][0]["summary"]
        assert summary["retries"] == 1
        if steps[0].get("flops"):  # backend-dependent, exact when there
            assert summary["achieved_flops_per_s"] > 0

    def test_static_skip_reclassifies_executor_step(self, tmp_path):
        run_dir = str(tmp_path / "run")
        obs.start_run(run_dir, flush_every=1)
        pt.enable_static()
        try:
            gexe = GuardedExecutor(policy=RecoveryPolicy(
                on_nonfinite="skip_step", **NOSLEEP))
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                with inject.chaos("nan_feed", at=2, seed=7):
                    _static_loop(gexe, steps=3)
        finally:
            pt.disable_static()
        assert gexe.stats.skipped == 1
        summary = obs.end_run()
        assert summary["skipped_steps"] == 1
        assert summary["productive_steps"] == 2
        # a NaN that reaches the fetches is durable in the step line
        # itself (nonfinite flag) — no reclassify needed
        recs = _read_journal(run_dir)
        bad = [r for r in recs if r["t"] == "step" and r.get("nonfinite")]
        assert len(bad) == 1
        rr = _load_run_report()
        run = rr.load_run(run_dir)
        assert len(rr._finite_losses(run)) == 2  # NaN step excluded
        # lazy backend event folded back into the header by the loader
        assert run["header"]["backend"] == "cpu"

    def test_late_skip_reclassifies_durably(self, tmp_path):
        """The scan_state case: the executor records a productive step
        (finite loss) and the guard discards it AFTERWARDS. The step's
        JSONL line is already flushed, so the correction must ride the
        resilience.skipped event and be applied by the loader."""
        run_dir = str(tmp_path / "run")
        j = journal.RunJournal(run_dir, flush_every=1,
                               compute_flops=False).start()
        j.record_step(loss=1.0, step_ms=5.0, source="executor")
        j.record_step(loss=0.9, step_ms=5.0, source="executor")
        ev = j.event("resilience.skipped", source="guarded_executor")
        assert ev["reclassified_step"] == 2
        j.close()
        assert j.accounting.skipped == 1 and j.accounting.productive == 1
        rr = _load_run_report()
        run = rr.load_run(run_dir)
        flags = [s.get("skipped", False) for s in run["steps"]]
        assert flags == [False, True]  # durable despite the early flush
        assert rr._finite_losses(run) == [1.0]

    def test_eager_skip_never_reclassifies_a_static_step(self, tmp_path):
        """Mixed usage: a static eval step followed by an eager
        GuardedStep skip must not reclassify the (unrelated) executor
        step — the eager guard records its own skipped step."""
        run_dir = str(tmp_path / "run")
        obs.start_run(run_dir, flush_every=1)
        pt.enable_static()
        try:
            _static_loop(fluid.Executor(), steps=1)  # productive eval
        finally:
            pt.disable_static()
        guard = _eager_guard()
        with inject.chaos("nan_feed", at=1, times=1, seed=7):
            guard(*_batches(1)[0])  # skipped eager step
        summary = obs.end_run()
        assert summary["productive_steps"] == 1  # the eval step survives
        assert summary["skipped_steps"] == 1     # counted exactly once
        recs = _read_journal(run_dir)
        assert not any("reclassified_step" in r for r in recs
                       if r["t"] == "event")

    def test_second_run_into_same_dir_keeps_rotated_parts(self, tmp_path):
        """Rotation numbering must continue across runs into one dir —
        a fresh instance restarting at journal.1.jsonl would os.replace
        over the first run's rotated history."""
        run_dir = str(tmp_path / "run")
        for _ in range(2):
            j = journal.RunJournal(run_dir, flush_every=1, max_bytes=600,
                                   compute_flops=False).start()
            for i in range(20):
                j.record_step(loss=float(i), step_ms=1.0)
            j.close()
        run = _load_run_report().load_run(run_dir)
        assert not run["parse_errors"]
        assert len(run["steps"]) == 40  # nothing clobbered
        assert run["header"] is not None  # run 1's header survives too


# -- zero-overhead contract --------------------------------------------------


class TestInactiveHooksDoNothing:
    def test_step_paths_never_touch_a_journal_when_inactive(
            self, tmp_path, monkeypatch):
        """With ACTIVE None, the hooks must be a single None check: every
        RunJournal entry point is poisoned to raise — and so are the
        PR-5 SPMD observability entry points (sharding summaries, device
        gauges) — and the executor, guarded step, StepTimer, dataloader,
        and checkpoint paths must still run clean."""
        assert journal.ACTIVE is None

        def boom(*a, **k):
            raise AssertionError("journal work performed while inactive")

        for name in ("record_step", "record_executor_run",
                     "record_request", "record_memory", "event",
                     "note_step_ms", "sync_step", "postmortem"):
            monkeypatch.setattr(journal.RunJournal, name, boom)
        # the per-compile sharding event and device telemetry must also
        # stay behind the ACTIVE/tracing gates
        from paddle_tpu.obs import spmd

        monkeypatch.setattr(spmd, "sharding_summary", boom)
        monkeypatch.setattr(spmd, "update_device_gauges", boom)
        # the fleet aggregator and SLO exporter are PULL-only readers:
        # nothing on a step/serve path may ever invoke them unprompted
        from paddle_tpu.obs import export as obs_export
        from paddle_tpu.obs import fleet as obs_fleet

        monkeypatch.setattr(obs_fleet, "load_journal", boom)
        monkeypatch.setattr(obs_fleet, "load_fleet", boom)
        monkeypatch.setattr(obs_fleet, "aggregate", boom)
        monkeypatch.setattr(obs_fleet, "merge_chrome_traces", boom)
        monkeypatch.setattr(obs_fleet, "router_summary", boom)
        monkeypatch.setattr(obs_export, "prometheus_text", boom)
        monkeypatch.setattr(obs_export, "write_textfile", boom)
        monkeypatch.setattr(obs_export, "router_lines", boom)
        monkeypatch.setattr(obs_export, "scrape", boom)
        monkeypatch.setattr(obs_export, "merge_expositions", boom)
        monkeypatch.setattr(obs_export.MetricsExporter, "render", boom)
        # the reqtrace reader (timeline assembly / attribution / lane
        # export) is pull-only too: the serve path writes req.* events
        # through the same ACTIVE gate and must never read them back
        from paddle_tpu.obs import reqtrace as obs_reqtrace

        for name in ("assemble", "assemble_run", "attribute",
                     "attribute_run", "tail_report",
                     "request_lane_events", "write_request_trace"):
            monkeypatch.setattr(obs_reqtrace, name, boom)
        # the SLO engine (PR 19) is strictly opt-in: with no evaluator
        # installed on the router and no statusz consumer, nothing on a
        # step/serve path may window a snapshot, evaluate a burn rate,
        # or render the status plane
        from paddle_tpu.obs import slo as obs_slo
        from paddle_tpu.obs import timeseries as obs_timeseries

        monkeypatch.setattr(obs_timeseries.SeriesStore, "observe", boom)
        monkeypatch.setattr(obs_timeseries.SeriesStore, "sample", boom)
        monkeypatch.setattr(obs_timeseries, "registry_snapshot", boom)
        monkeypatch.setattr(obs_timeseries, "exposition_snapshot", boom)
        monkeypatch.setattr(obs_slo.SLOEvaluator, "observe", boom)
        monkeypatch.setattr(obs_slo, "evaluate_run", boom)
        monkeypatch.setattr(obs_slo, "load_any", boom)
        monkeypatch.setattr(obs_export, "statusz_data", boom)
        monkeypatch.setattr(obs_export, "render_statusz_html", boom)
        monkeypatch.setattr(obs_export, "slo_engine_lines", boom)
        monkeypatch.setattr(obs_export.MetricsExporter,
                            "render_statusz", boom)
        monkeypatch.setattr(obs_fleet, "slo_summary", boom)
        # the tenant chargeback plane (PR 20) is pull-only too: the
        # meter/cache accumulate plain ints on the hot path, but
        # nothing on a step/serve path may ever roll up, audit, or
        # render a tenant view unprompted — every reader is poisoned
        # while the tenant-tagged lifecycles below run in full
        from paddle_tpu.obs import usage as obs_usage

        for name in ("engine_tenant_usage", "router_tenant_usage",
                     "fairness_audit", "fairness_record",
                     "rollup_requests", "merge_tenant_rollups",
                     "tenant_slo_slices"):
            monkeypatch.setattr(obs_usage, name, boom)
        monkeypatch.setattr(obs_export, "tenant_lines", boom)
        monkeypatch.setattr(obs_fleet, "tenant_summary", boom)
        monkeypatch.setattr(obs_fleet, "merged_tenant_summary", boom)

        pt.enable_static()
        try:
            _static_loop(fluid.Executor(), steps=2)
        finally:
            pt.disable_static()

        guard = _eager_guard()
        with inject.chaos("nan_feed", at=1, seed=7):
            for x, y in _batches(2):
                guard(x, y)

        from paddle_tpu.utils.profiler import StepTimer

        t = StepTimer(skip_first=0)
        with t.step():
            pass

        from paddle_tpu.framework.io import load_checkpoint, save_checkpoint

        d = str(tmp_path / "ckpt")
        m = nn.Linear(4, 2)
        save_checkpoint(d, 1, model=m)
        assert load_checkpoint(d, model=nn.Linear(4, 2)) == 1

        # serving hooks (PR 7): a full engine lifecycle — compile,
        # prefill, decode, preemption-free finish — and a Predictor
        # run must also perform zero journal work when inactive
        from paddle_tpu.serving import PagedKVCache, ServeEngine, TinyLM

        eng = ServeEngine(TinyLM(num_heads=2, head_dim=8),
                          PagedKVCache(16, 4, 2, 8))
        req = eng.submit([1, 2, 3], max_new_tokens=2, tenant="t0")
        eng.run(max_steps=20)
        assert req.state == "FINISHED" and len(req.generated) == 2
        eng.cancel(eng.submit([1], max_new_tokens=1, tenant="t1"))
        # metering kept charging (always-on ints) while every reader
        # stayed poisoned — the engine's truth is there to pull later
        assert eng.usage.busy_ns > 0 and "t0" in eng.usage.device_ns

        # serve-fleet hooks (router dispatch/requeue/scale, replica
        # pool spawn/death/retire): a full routed lifecycle — submit,
        # dispatch, a killed replica's requeue + relaunch, drain-down,
        # rejection — must perform zero journal/export work when
        # inactive (every router.* / fleet.* event is ACTIVE-guarded;
        # the exporters are pull-only)
        from paddle_tpu.serving import ManualClock
        from paddle_tpu.serving.fleet import (ReplicaPool, ReplicaSpec,
                                              Router)
        from paddle_tpu.resilience import ReplicaSupervisor

        fclock = ManualClock()
        fpool = ReplicaPool(
            ReplicaSpec(vocab_size=32, pages=32, page_size=4,
                        max_seq_len=16, token_budget=64),
            replicas=2, mode="local", clock=fclock,
            supervisor=ReplicaSupervisor(sleep=lambda s: None))
        frouter = Router(fpool, clock=fclock)
        fr = frouter.submit([1, 2, 3], max_new_tokens=2, tenant="t0")
        with pytest.raises(ValueError):
            frouter.submit([1] * 30, max_new_tokens=30,
                           tenant="t1")  # reject path (tenant-tagged)
        frouter.dispatch()
        fpool.replicas[fr.replica_id].kill()
        frouter.check_replicas()           # requeue + relaunch
        for _ in range(30):
            frouter.step()
            fclock.advance(0.01)
            if not frouter.inflight and not frouter.queue_depth:
                break
        assert fr.state == "FINISHED" and fr.requeues == 1
        drainee = fpool.active()[-1]
        drainee.drain()
        frouter.poll()                     # retire path
        frouter.close()

        # reqtrace write hooks (PR 18): a pressured engine run forcing
        # preemption, resume, and decode-step marks (the req.preempt /
        # req.admit(resumed) / req.decode_mark emit sites) must also
        # collapse to the single None check when inactive
        from paddle_tpu.serving import Scheduler

        pcache = PagedKVCache(8, 2, 2, 8, max_seq_len=8)
        peng = ServeEngine(TinyLM(num_heads=2, head_dim=8), pcache,
                           scheduler=Scheduler(pcache,
                                               token_budget=64))
        preqs = [peng.submit([1, 2], max_new_tokens=6,
                             tenant=f"t{i % 2}")
                 for i in range(4)]
        peng.run(max_steps=200)
        assert all(r.state == "FINISHED" for r in preqs)
        assert peng.scheduler.preemptions >= 1
        # the page-second integrals closed (alloc==free) and the
        # preempting run still metered both tenants — always-on
        # accumulation, pull-only reads
        assert not pcache.page_usage()["open"]
        assert set(peng.usage.device_ns) == {"t0", "t1"}

        import tempfile

        from paddle_tpu.framework.io import save_inference_model
        from paddle_tpu.inference import Predictor

        pt.enable_static()
        try:
            prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, startup):
                xi = fluid.data(name="x", shape=[2, 4])
                oi = fluid.layers.fc(xi, size=2)
            exe = fluid.Executor()
            exe.run(startup)
            with tempfile.TemporaryDirectory() as td:
                prefix = os.path.join(td, "m")
                save_inference_model(prefix, ["x"], [oi], program=prog)
                Predictor(prefix).run(
                    {"x": np.zeros((2, 4), np.float32)})
        finally:
            pt.disable_static()


# -- concurrency + crash safety ----------------------------------------------


class TestJournalDurability:
    def test_two_threads_interleave_to_valid_jsonl(self, tmp_path):
        run_dir = str(tmp_path / "run")
        j = journal.RunJournal(run_dir, flush_every=3,
                               compute_flops=False).start()
        errs = []

        def work(tid):
            try:
                for i in range(100):
                    j.record_step(loss=float(i), step_ms=1.0,
                                  source=f"thread{tid}")
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=work, args=(k,)) for k in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        j.close()
        assert not errs
        recs = _read_journal(run_dir)  # every line must json.loads
        steps = [r for r in recs if r["t"] == "step"]
        assert len(steps) == 200
        assert sorted(r["step"] for r in steps) == list(range(1, 201))

    def test_exception_mid_run_yields_parseable_postmortem(self, tmp_path):
        run_dir = str(tmp_path / "run")
        guard = _eager_guard(policy_kw={"max_retries": 1})
        with pytest.raises(inject.TransientChaosError):
            with journal.RunJournal(run_dir, flush_every=100) as j:
                with inject.chaos("transient_execute", times=5):
                    for x, y in _batches(4):  # retry budget dies mid-run
                        guard(x, y)
        assert j.closed
        pm = json.load(open(os.path.join(run_dir, journal.POSTMORTEM_FILE)))
        assert pm["exception"]["type"] == "TransientChaosError"
        assert pm["last_events"]  # the retry that preceded the death
        assert pm["summary"]["retries"] >= 1
        # the journal itself closed cleanly despite the big flush_every
        recs = _read_journal(run_dir)
        assert recs[-1]["t"] == "run_end"

    def test_rotation_keeps_every_record(self, tmp_path):
        run_dir = str(tmp_path / "run")
        j = journal.RunJournal(run_dir, flush_every=1, max_bytes=2048,
                               compute_flops=False).start()
        for i in range(100):
            j.record_step(loss=float(i), step_ms=1.0)
        j.close()
        parts = [f for f in os.listdir(run_dir)
                 if f.startswith("journal.") and f.endswith(".jsonl")]
        assert len(parts) > 1  # rotated at least once
        # the CLI loader reads rotated parts oldest-first: every record
        # survives rotation
        run = _load_run_report().load_run(run_dir)
        assert not run["parse_errors"]
        assert len(run["steps"]) == 100
        assert run["summary"]["productive_steps"] == 100


# -- detectors + accounting (unit level) -------------------------------------


class TestDetectors:
    def test_loss_spike_and_rearm(self):
        det = anomaly.LossSpike(window=8, factor=8.0, min_steps=4)
        for i in range(6):
            assert det.update({"loss": 1.0 + 0.01 * i}) is None
        fired = det.update({"loss": 100.0})
        assert fired and fired["loss"] == 100.0
        # a sustained excursion fires ONCE (docstring contract), and a
        # recovery re-arms the detector for the next excursion
        assert det.update({"loss": 120.0}) is None
        assert det.update({"loss": 1.0}) is None
        assert det.update({"loss": 100.0})

    def test_plateau_fires_once_per_plateau(self):
        det = anomaly.LossPlateau(window=5, rel_eps=1e-3)
        fires = [det.update({"loss": 1.0}) for _ in range(20)]
        assert sum(1 for f in fires if f) == 1

    def test_nonfinite_streak_resets(self):
        det = anomaly.NonfiniteStreak(threshold=2)
        assert det.update({"loss": 1.0}) is None
        assert det.update({"skipped": True}) is None
        assert det.update({"skipped": True})  # streak hits 2
        assert det.update({"skipped": True}) is None  # once per streak
        assert det.update({"loss": 1.0}) is None
        assert det.update({"loss": float("nan")}) is None
        assert det.update({"nonfinite": True})  # new streak

    def test_throughput_drop_and_rearm(self):
        det = anomaly.ThroughputDrop(window=8, factor=2.0, min_steps=4)
        for _ in range(6):
            assert det.update({"step_ms": 10.0}) is None
        assert det.update({"step_ms": 50.0})
        assert det.update({"step_ms": 50.0}) is None  # same slowdown
        assert det.update({"step_ms": 10.0}) is None  # recovery re-arms
        assert det.update({"step_ms": 55.0})

    def test_ttft_spike_and_rearm(self):
        det = anomaly.TtftSpike(window=8, factor=6.0, min_steps=4,
                                floor_ms=0.5)
        for i in range(6):
            assert det.update({"ttft_ms": 10.0 + 0.1 * i}) is None
        assert det.update({"ttft_ms": 200.0})
        # a sustained latency excursion fires ONCE; recovery re-arms
        assert det.update({"ttft_ms": 250.0}) is None
        assert det.update({"ttft_ms": 10.0}) is None
        assert det.update({"ttft_ms": 200.0})
        # records without a TTFT field (training steps) are ignored
        assert det.update({"loss": 1.0, "step_ms": 5.0}) is None

    def test_serving_detectors_env_spec(self):
        dets = anomaly.serving_detectors("")
        assert sorted(d.name for d in dets) == \
            sorted(anomaly.SERVING_DETECTORS)
        tuned = anomaly.serving_detectors(
            "ttft_spike:factor=3;loss_spike:factor=99")
        spike = [d for d in tuned
                 if isinstance(d, anomaly.TtftSpike)][0]
        # non-serving names in the shared env spec are ignored here
        assert spike.factor == 3.0
        assert not any(isinstance(d, anomaly.LossSpike) for d in tuned)
        assert anomaly.serving_detectors("off") == []

    def test_starvation_ratio_and_rearm(self):
        det = anomaly.DataloaderStarvation(ratio=0.5, min_wait_ms=1.0,
                                           min_steps=1)
        assert det.update({"step_ms": 10.0, "dl_wait_ms": 2.0}) is None
        assert det.update({"step_ms": 10.0, "dl_wait_ms": 8.0})
        assert det.update({"step_ms": 10.0, "dl_wait_ms": 9.0}) is None
        assert det.update({"step_ms": 10.0, "dl_wait_ms": 1.0}) is None
        assert det.update({"step_ms": 10.0, "dl_wait_ms": 8.0})

    def test_env_spec_overrides_and_off(self):
        dets = anomaly.default_detectors("nonfinite_streak:threshold=7")
        streak = [d for d in dets
                  if isinstance(d, anomaly.NonfiniteStreak)][0]
        assert streak.threshold == 7
        assert anomaly.default_detectors("off") == []
        with pytest.raises(KeyError):
            anomaly.default_detectors("nope:x=1")

    def test_engine_ticks_counter_and_callback_errors_are_swallowed(self):
        obs.metrics.reset()
        hits = []

        def cb(fired):
            hits.append(fired)
            raise RuntimeError("buggy reaction")

        eng = anomaly.AnomalyEngine(
            [anomaly.NonfiniteStreak(threshold=1)], callback=cb)
        out = eng.observe({"step": 5, "skipped": True})
        assert out and hits and hits[0]["name"] == "nonfinite_streak"
        assert obs.metrics.counter("anomaly.nonfinite_streak").value == 1


class TestMFU:
    def test_goodput_math(self):
        assert mfu.goodput(8, 1, 1) == pytest.approx(0.8)
        assert mfu.goodput(0, 0, 0) is None

    def test_accounting_summary(self):
        acc = mfu.MFUAccounting(peak=1e12)
        for _ in range(4):
            acc.record(step_ms=10.0, flops=5e9, examples=32)
        acc.record(step_ms=10.0, productive=False)
        acc.note_retry()
        s = acc.summary()
        assert s["goodput"] == pytest.approx(4 / 6)
        assert s["achieved_flops_per_s"] == pytest.approx(5e11)
        assert s["mfu"] == pytest.approx(0.5)
        assert s["examples_per_s"] == pytest.approx(128 / 0.05)

    def test_peak_override(self, monkeypatch):
        mfu.set_peak_flops(123.0)
        try:
            assert mfu.peak_flops() == 123.0
        finally:
            mfu.set_peak_flops(None)
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "456")
        assert mfu.peak_flops() == 456.0

    def test_entry_attribution_via_cache_stats(self):
        pt.enable_static()
        try:
            exe = fluid.Executor()
            _static_loop(exe, steps=2)
        finally:
            pt.disable_static()
        stats = exe.cache_stats(per_entry=True)
        assert {"hits", "misses", "size", "entries"} <= set(stats)
        assert len(stats["entries"]) == stats["size"] == 1
        e = stats["entries"][0]
        assert e["optimize_level"] == 1
        # CPU XLA reports memory/cost analysis: bytes and flops land
        assert e["memory_bytes"] is None or e["memory_bytes"] > 0
        # pinned default shape unchanged (test_obs relies on it)
        assert set(exe.cache_stats()) == {"hits", "misses", "size"}


class TestStatsHardening:
    def test_cost_dict_list_valued_entries(self):
        from paddle_tpu.utils import stats

        ca = {"flops": [1.0, 2.0], "bytes accessed": 7,
              "utilization": "n/a", "weird": object()}
        out = stats._cost_dict(ca)
        assert out["flops"] == 3.0 and out["bytes accessed"] == 7.0
        assert "utilization" not in out and "weird" not in out

    def test_cost_dict_list_of_dicts_sums(self):
        from paddle_tpu.utils import stats

        out = stats._cost_dict([{"flops": 2.0}, {"flops": 3.0},
                                "junk"])
        assert out == {"flops": 5.0}

    def test_cost_dict_none_and_junk(self):
        from paddle_tpu.utils import stats

        assert stats._cost_dict(None) == {}
        assert stats._cost_dict(object()) == {}
        assert stats._cost_dict({"x": np.float32(1.5)}) == {"x": 1.5}
        assert stats._cost_dict({"x": np.zeros(())})["x"] == 0.0
        assert stats._cost_dict({"x": np.zeros(3)}) == {}
