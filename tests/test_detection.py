"""Detection op tests: numpy parity for every op (SURVEY §2 #3 breadth;
ref: python/paddle/fluid/layers/detection.py, tests/unittests/test_*_op.py
style — compare against slow reference implementations)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops


def _rand_boxes(rng, n, scale=1.0):
    """n random valid [x1, y1, x2, y2] boxes."""
    xy1 = rng.rand(n, 2) * 0.6 * scale
    wh = (rng.rand(n, 2) * 0.4 + 0.05) * scale
    return np.concatenate([xy1, xy1 + wh], axis=1).astype("float32")


def _iou_np(a, b):
    n, m = len(a), len(b)
    out = np.zeros((n, m), np.float32)
    for i in range(n):
        for j in range(m):
            xx1 = max(a[i, 0], b[j, 0]); yy1 = max(a[i, 1], b[j, 1])
            xx2 = min(a[i, 2], b[j, 2]); yy2 = min(a[i, 3], b[j, 3])
            inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
            ua = ((a[i, 2] - a[i, 0]) * (a[i, 3] - a[i, 1])
                  + (b[j, 2] - b[j, 0]) * (b[j, 3] - b[j, 1]) - inter)
            out[i, j] = inter / ua if ua > 0 else 0.0
    return out


class TestIoU:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = _rand_boxes(rng, 5)
        b = _rand_boxes(rng, 7)
        got = np.asarray(ops.iou_similarity(
            pt.to_tensor(a), pt.to_tensor(b)).numpy())
        np.testing.assert_allclose(got, _iou_np(a, b), atol=1e-5)

    def test_known_value(self):
        x = np.array([[0., 0., 2., 2.]], "float32")
        y = np.array([[1., 1., 3., 3.]], "float32")
        got = float(np.asarray(ops.iou_similarity(
            pt.to_tensor(x), pt.to_tensor(y)).numpy()).reshape(()))
        assert got == pytest.approx(1.0 / 7.0, abs=1e-6)


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(1)
        priors = _rand_boxes(rng, 6)
        targets = _rand_boxes(rng, 4)
        var = [0.1, 0.1, 0.2, 0.2]
        enc = ops.box_coder(pt.to_tensor(priors), var,
                            pt.to_tensor(targets),
                            code_type="encode_center_size")
        assert list(enc.shape) == [4, 6, 4]
        dec = ops.box_coder(pt.to_tensor(priors), var, enc,
                            code_type="decode_center_size", axis=0)
        got = np.asarray(dec.numpy())
        # decoding the encoding of target t against prior p returns t
        for t in range(4):
            for p in range(6):
                np.testing.assert_allclose(got[t, p], targets[t],
                                           atol=1e-5)

    def test_decode_without_var(self):
        priors = np.array([[0.1, 0.1, 0.5, 0.5]], "float32")
        deltas = np.zeros((1, 1, 4), "float32")
        dec = ops.box_coder(pt.to_tensor(priors), None,
                            pt.to_tensor(deltas),
                            code_type="decode_center_size")
        np.testing.assert_allclose(np.asarray(dec.numpy())[0, 0],
                                   priors[0], atol=1e-6)


class TestPriorBox:
    def test_shapes_and_range(self):
        feat = pt.zeros([1, 8, 4, 4])
        img = pt.zeros([1, 3, 64, 64])
        boxes, vars_ = ops.prior_box(feat, img, min_sizes=[16.0],
                                     max_sizes=[32.0],
                                     aspect_ratios=[2.0], flip=True,
                                     clip=True)
        # priors per cell: 1 (min) + 1 (max) + 2 (ar 2, 1/2) = 4
        assert list(boxes.shape) == [4, 4, 4, 4]
        b = np.asarray(boxes.numpy())
        assert (b >= 0).all() and (b <= 1).all()
        assert (b[..., 2] >= b[..., 0]).all()
        v = np.asarray(vars_.numpy())
        np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])

    def test_center_offset(self):
        feat = pt.zeros([1, 8, 2, 2])
        img = pt.zeros([1, 3, 32, 32])
        boxes, _ = ops.prior_box(feat, img, min_sizes=[8.0])
        b = np.asarray(boxes.numpy())
        # cell (0,0) center at (0.5*16)/32 = 0.25
        cx = (b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2
        assert cx == pytest.approx(0.25, abs=1e-6)


class TestAnchorGenerator:
    def test_pixel_anchors(self):
        feat = pt.zeros([1, 8, 2, 3])
        anchors, vars_ = ops.anchor_generator(
            feat, anchor_sizes=[32.0], aspect_ratios=[1.0],
            stride=[16.0, 16.0])
        a = np.asarray(anchors.numpy())
        assert a.shape == (2, 3, 1, 4)
        # first cell center (8, 8), size 32 -> [-8, -8, 24, 24]
        np.testing.assert_allclose(a[0, 0, 0], [-8, -8, 24, 24], atol=1e-4)


class TestBoxClip:
    def test_clip(self):
        boxes = pt.to_tensor(np.array(
            [[[-5.0, -5.0, 30.0, 40.0]]], "float32"))
        im_info = pt.to_tensor(np.array([[20.0, 25.0, 1.0]], "float32"))
        out = np.asarray(ops.box_clip(boxes, im_info).numpy())
        np.testing.assert_allclose(out[0, 0], [0, 0, 24, 19])


def _nms_np(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    sup = np.zeros(len(boxes), bool)
    for i in order:
        if sup[i]:
            continue
        keep.append(i)
        ious = _iou_np(boxes[i:i + 1], boxes)[0]
        sup |= ious > thresh
        sup[i] = True
    return keep


class TestNMS:
    def test_single_class_matches_numpy(self):
        rng = np.random.RandomState(3)
        boxes = _rand_boxes(rng, 16, scale=10.0)
        scores = rng.rand(16).astype("float32")
        keep = np.asarray(ops.nms(pt.to_tensor(boxes),
                                  pt.to_tensor(scores), 0.4).numpy())
        want = np.zeros(16, bool)
        want[_nms_np(boxes, scores, 0.4)] = True
        np.testing.assert_array_equal(keep, want)

    def test_multiclass_padded_output(self):
        rng = np.random.RandomState(4)
        B, M, C = 2, 12, 3
        boxes = _rand_boxes(rng, B * M, scale=10.0).reshape(B, M, 4)
        scores = rng.rand(B, C, M).astype("float32")
        out, counts = ops.multiclass_nms(
            pt.to_tensor(boxes), pt.to_tensor(scores),
            score_threshold=0.5, nms_top_k=8, keep_top_k=10,
            nms_threshold=0.4, background_label=0)
        o = np.asarray(out.numpy())
        c = np.asarray(counts.numpy())
        assert o.shape == (B, 10, 6)
        for b in range(B):
            n = c[b]
            # valid rows: class != -1, scores sorted descending
            assert (o[b, :n, 0] >= 0).all()
            assert (o[b, n:, 0] == -1).all()
            assert (np.diff(o[b, :n, 1]) <= 1e-6).all()
            assert (o[b, :n, 0] != 0).all()  # background dropped
            assert (o[b, :n, 1] >= 0.5).all()

    def test_multiclass_agrees_with_per_class_numpy(self):
        rng = np.random.RandomState(5)
        M = 10
        boxes = _rand_boxes(rng, M, scale=8.0).reshape(1, M, 4)
        scores = rng.rand(1, 2, M).astype("float32")
        out, counts = ops.multiclass_nms(
            pt.to_tensor(boxes), pt.to_tensor(scores),
            score_threshold=0.3, nms_top_k=M, keep_top_k=M * 2,
            nms_threshold=0.5, background_label=-1)
        got = np.asarray(out.numpy())[0]
        n = int(np.asarray(counts.numpy())[0])
        want = []
        for c in range(2):
            s = scores[0, c].copy()
            ok = s >= 0.3
            keep = _nms_np(boxes[0][ok], s[ok], 0.5)
            idx = np.where(ok)[0][keep]
            want += [(c, s[i], *boxes[0][i]) for i in idx]
        want.sort(key=lambda r: -r[1])
        assert n == len(want)
        for row, w in zip(got[:n], want):
            assert int(row[0]) == w[0]
            np.testing.assert_allclose(row[1:], w[1:], atol=1e-5)


class TestYolo:
    def test_yolo_box_decode(self):
        B, A, C, H, W = 1, 2, 3, 2, 2
        anchors = [10, 14, 23, 27]
        x = np.zeros((B, A * (5 + C), H, W), "float32")
        img = np.array([[64, 64]], "int32")
        boxes, scores = ops.yolo_box(pt.to_tensor(x), pt.to_tensor(img),
                                     anchors, C, 0.01, 32)
        b = np.asarray(boxes.numpy())
        s = np.asarray(scores.numpy())
        assert b.shape == (1, A * H * W, 4)
        assert s.shape == (1, A * H * W, C)
        # zero logits -> sigmoid 0.5: cell(0,0) anchor0 center = 0.5/2
        cx = (b[0, 0, 0] + b[0, 0, 2]) / 2
        assert cx == pytest.approx(0.5 / W * 64, rel=1e-5)
        # width = exp(0)*10 / (2*32) * 64 = 10
        assert b[0, 0, 2] - b[0, 0, 0] == pytest.approx(10.0, rel=1e-5)
        # scores = cls_sig * obj_sig = 0.25
        assert s[0, 0, 0] == pytest.approx(0.25, rel=1e-5)

    def test_yolo_box_conf_threshold(self):
        x = np.zeros((1, 16, 2, 2), "float32")
        img = np.array([[64, 64]], "int32")
        _, scores = ops.yolo_box(pt.to_tensor(x), pt.to_tensor(img),
                                 [10, 14, 23, 27], 3, 0.6, 32)
        assert (np.asarray(scores.numpy()) == 0).all()  # 0.5 < 0.6

    def test_yolov3_loss_trains(self):
        rng = np.random.RandomState(6)
        B, C, H, W = 2, 4, 4, 4
        A = 3
        anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
                   116, 90, 156, 198, 373, 326]
        mask = [0, 1, 2]
        x = pt.to_tensor(rng.randn(B, A * (5 + C), H, W)
                         .astype("float32") * 0.1)
        x.stop_gradient = False
        gt_box = np.zeros((B, 3, 4), "float32")
        gt_box[:, 0] = [0.5, 0.5, 0.1, 0.12]  # one real gt, rest padding
        gt_label = np.zeros((B, 3), "int64")
        loss = ops.yolov3_loss(x, pt.to_tensor(gt_box),
                               pt.to_tensor(gt_label), anchors, mask, C,
                               ignore_thresh=0.7, downsample_ratio=32)
        assert list(loss.shape) == [B]
        total = loss.sum()
        total.backward()
        g = np.asarray(x.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_yolov3_loss_padding_does_not_clobber_real_gt(self):
        """A padding row landing on anchor 0 / cell (0,0) must not
        overwrite a real gt's targets (regression: scatter .set clobber)."""
        rng = np.random.RandomState(11)
        B, C, H, W = 1, 2, 4, 4
        anchors = [10, 14, 23, 27]
        mask = [0, 1]
        x = pt.to_tensor(rng.randn(B, 2 * (5 + C), H, W)
                         .astype("float32") * 0.1)
        gt1 = np.zeros((B, 1, 4), "float32")
        gt1[0, 0] = [0.1, 0.1, 0.15, 0.2]  # cell (0,0)
        lab1 = np.ones((B, 1), "int64")
        gt2 = np.zeros((B, 2, 4), "float32")
        gt2[0, 0] = gt1[0, 0]  # same gt + one all-zero padding row
        lab2 = np.concatenate([lab1, np.zeros((B, 1), "int64")], axis=1)
        l1 = float(ops.yolov3_loss(x, pt.to_tensor(gt1),
                                   pt.to_tensor(lab1), anchors, mask, C,
                                   0.7, 32).sum())
        l2 = float(ops.yolov3_loss(x, pt.to_tensor(gt2),
                                   pt.to_tensor(lab2), anchors, mask, C,
                                   0.7, 32).sum())
        assert l1 == pytest.approx(l2, rel=1e-6), (l1, l2)

    def test_yolov3_loss_gt_score_weights(self):
        """Mixup gt_score scales positive terms: score 0.5 must sit
        between score 0 (background-only) and score 1."""
        rng = np.random.RandomState(12)
        B, C, H, W = 1, 2, 2, 2
        anchors = [10, 14, 23, 27]
        x = pt.to_tensor(rng.randn(B, 2 * (5 + C), H, W)
                         .astype("float32") * 0.1)
        gt = np.array([[[0.5, 0.5, 0.2, 0.2]]], "float32")
        lab = np.ones((B, 1), "int64")

        def loss_at(s):
            return float(ops.yolov3_loss(
                x, pt.to_tensor(gt), pt.to_tensor(lab), anchors, [0, 1],
                C, 0.7, 32,
                gt_score=pt.to_tensor(np.full((B, 1), s, "float32"))
            ).sum())

        l0, l5, l1 = loss_at(0.0), loss_at(0.5), loss_at(1.0)
        assert l0 < l5 < l1, (l0, l5, l1)

    def test_nms_eta_adaptive_keeps_more(self):
        """nms_eta < 1 decays the threshold, so it can only suppress
        MORE than fixed-threshold NMS (fewer or equal boxes kept)."""
        rng = np.random.RandomState(13)
        M = 12
        boxes = _rand_boxes(rng, M, scale=10.0).reshape(1, M, 4)
        scores = rng.rand(1, 1, M).astype("float32")
        _, c_fixed = ops.multiclass_nms(
            pt.to_tensor(boxes), pt.to_tensor(scores), 0.1, M, M,
            nms_threshold=0.9, background_label=-1)
        _, c_adapt = ops.multiclass_nms(
            pt.to_tensor(boxes), pt.to_tensor(scores), 0.1, M, M,
            nms_threshold=0.9, nms_eta=0.5, background_label=-1)
        assert int(np.asarray(c_adapt.numpy())[0]) <= \
            int(np.asarray(c_fixed.numpy())[0])

    def test_yolov3_loss_ignores_padding_rows(self):
        B, C, H, W = 1, 2, 2, 2
        anchors = [10, 14, 23, 27]
        mask = [0, 1]
        x = pt.to_tensor(np.zeros((B, 2 * (5 + C), H, W), "float32"))
        empty = ops.yolov3_loss(
            x, pt.to_tensor(np.zeros((B, 2, 4), "float32")),
            pt.to_tensor(np.zeros((B, 2), "int64")), anchors, mask, C,
            ignore_thresh=0.7, downsample_ratio=32)
        one = ops.yolov3_loss(
            x, pt.to_tensor(np.array([[[0.5, 0.5, 0.2, 0.2],
                                       [0, 0, 0, 0]]], "float32")),
            pt.to_tensor(np.zeros((B, 2), "int64")), anchors, mask, C,
            ignore_thresh=0.7, downsample_ratio=32)
        assert float(one.sum()) > float(empty.sum())


def _roi_align_np(feat, roi, ph, pw, scale, sr):
    C, H, W = feat.shape
    x1, y1, x2, y2 = roi * scale
    rw = max(x2 - x1, 1.0)
    rh = max(y2 - y1, 1.0)
    out = np.zeros((C, ph, pw), np.float32)
    for j in range(ph):
        for i in range(pw):
            acc = np.zeros(C, np.float32)
            for sj in range(sr):
                for si in range(sr):
                    yy = y1 + (j * sr + sj + 0.5) * rh / ph / sr
                    xx = x1 + (i * sr + si + 0.5) * rw / pw / sr
                    yy = min(max(yy, 0.0), H - 1.0)
                    xx = min(max(xx, 0.0), W - 1.0)
                    y0, x0 = int(np.floor(yy)), int(np.floor(xx))
                    y1_, x1_ = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
                    wy, wx = yy - y0, xx - x0
                    acc += (feat[:, y0, x0] * (1 - wy) * (1 - wx)
                            + feat[:, y0, x1_] * (1 - wy) * wx
                            + feat[:, y1_, x0] * wy * (1 - wx)
                            + feat[:, y1_, x1_] * wy * wx)
            out[:, j, i] = acc / (sr * sr)
    return out


class TestRoiOps:
    def test_roi_align_matches_numpy(self):
        rng = np.random.RandomState(7)
        feat = rng.randn(1, 3, 8, 8).astype("float32")
        rois = np.array([[2.0, 2.0, 12.0, 12.0],
                         [0.0, 0.0, 6.0, 4.0]], "float32")
        got = np.asarray(ops.roi_align(
            pt.to_tensor(feat), pt.to_tensor(rois), pooled_height=2,
            pooled_width=2, spatial_scale=0.5, sampling_ratio=2).numpy())
        for r in range(2):
            want = _roi_align_np(feat[0], rois[r], 2, 2, 0.5, 2)
            np.testing.assert_allclose(got[r], want, atol=1e-4)

    def test_roi_align_grads(self):
        feat = pt.to_tensor(np.random.RandomState(8)
                            .randn(1, 2, 6, 6).astype("float32"))
        feat.stop_gradient = False
        rois = pt.to_tensor(np.array([[1.0, 1.0, 5.0, 5.0]], "float32"))
        out = ops.roi_align(feat, rois, 2, 2, 1.0, sampling_ratio=2)
        out.sum().backward()
        g = np.asarray(feat.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_roi_pool_max_semantics(self):
        feat = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0.0, 0.0, 3.0, 3.0]], "float32")
        got = np.asarray(ops.roi_pool(
            pt.to_tensor(feat), pt.to_tensor(rois), pooled_height=2,
            pooled_width=2, spatial_scale=1.0).numpy())
        np.testing.assert_allclose(got[0, 0], [[5, 7], [13, 15]])

    def test_rois_num_counts_semantics(self):
        """rois_num is the fluid per-image COUNT vector, not batch ids."""
        feat = np.stack([np.zeros((1, 4, 4), "float32"),
                         np.ones((1, 4, 4), "float32")])
        rois = np.array([[0.0, 0.0, 3.0, 3.0]] * 3, "float32")
        counts = np.array([2, 1], "int32")  # 2 rois img0, 1 roi img1
        got = np.asarray(ops.roi_pool(
            pt.to_tensor(feat), pt.to_tensor(rois), 1, 1, 1.0,
            rois_num=pt.to_tensor(counts)).numpy())
        assert got[0, 0, 0, 0] == 0.0 and got[1, 0, 0, 0] == 0.0
        assert got[2, 0, 0, 0] == 1.0
        with pytest.raises(ValueError):
            ops.roi_pool(pt.to_tensor(feat), pt.to_tensor(rois), 1, 1,
                         1.0, rois_num=pt.to_tensor(
                             np.array([1, 1], "int32")))


class TestFocalLoss:
    def test_matches_formula(self):
        rng = np.random.RandomState(9)
        x = rng.randn(6, 3).astype("float32")
        label = np.array([0, 1, 2, 3, 1, 0], "int64")
        fg = np.float32(4.0)
        got = np.asarray(ops.sigmoid_focal_loss(
            pt.to_tensor(x), pt.to_tensor(label), pt.to_tensor(fg),
            gamma=2.0, alpha=0.25).numpy())
        p = 1 / (1 + np.exp(-x))
        t = np.zeros_like(x)
        for i, l in enumerate(label):
            if l > 0:
                t[i, l - 1] = 1.0
        ce = -(t * np.log(p) + (1 - t) * np.log(1 - p))
        w = (0.25 * t + 0.75 * (1 - t)) * np.abs(t - p) ** 2.0
        np.testing.assert_allclose(got, w * ce / 4.0, atol=1e-5)
