"""CPU-runnable perf gates (ISSUE 6 tentpole #3): HLO invariants —
donated-buffer counts, op-shape counts, collective bytes — plus
compiled-call-count gates over the fused lax.scan step path. These are
the tier-1 stand-in for the dark real-TPU bench: a perf regression that
changes WHAT gets compiled (donation lost, scan unrolled, extra
dispatches, comm blow-up) fails here without a single timing."""
import importlib.util
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate_for_tests", os.path.join(ROOT, "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def pg():
    return _perf_gate()


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def _build_mlp(batch=16, lr=0.05):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return prog, startup, loss


def _feeds(K, batch=16):
    rng = np.random.RandomState(0)
    return [{"x": rng.randn(batch, 8).astype(np.float32),
             "y": rng.randn(batch, 1).astype(np.float32)}
            for _ in range(K)]


# -- HLO parsing units (canned, no backend work) -----------------------------


def test_donation_parse_canned(pg):
    hlo = ("HloModule m, input_output_alias={ {1}: (2, {}, may-alias), "
           "{3}: (4, {}) }, entry_computation_layout={()->()}")
    d = pg.donation_stats(hlo)
    assert d["count"] == 2
    assert d["aliases"] == [((1,), 2, "may-alias"), ((3,), 4, "must-alias")]
    assert pg.donation_stats("HloModule m\n%x = f32[] add(...)") == \
        {"count": 0, "aliases": []}


def test_op_counts_canned(pg):
    hlo = ("%a = f32[8]{0} fusion(f32[8]{0} %p), kind=kLoop\n"
           "%b = (s32[], f32[8]{0}) while((s32[], f32[8]{0}) %i), "
           "body=%body\n"
           "%c = f32[8,8]{1,0} dot(f32[8,8]{1,0} %x, f32[8,8]{1,0} %y)\n"
           "%d = f32[8]{0} all-reduce(f32[8]{0} %z), replica_groups={}")
    counts = pg.op_counts(hlo, kinds=("fusion", "while", "dot",
                                      "all-reduce", "convolution"))
    assert counts == {"fusion": 1, "while": 1, "dot": 1, "all-reduce": 1,
                      "convolution": 0}


def test_check_hlo_flags_regressions(pg):
    hlo = ("HloModule m, input_output_alias={ {1}: (1, {}, may-alias) }, "
           "entry_computation_layout={()->()}\n"
           "%f = f32[8]{0} fusion(f32[8]{0} %p), kind=kLoop")
    assert pg.check_hlo(hlo, min_donated=1, min_fusion=1, max_while=0) == []
    assert pg.check_hlo(hlo, min_donated=2)  # donation regression
    assert pg.check_hlo(hlo, min_while=1)    # scan disappeared
    assert pg.check_hlo(hlo, min_fusion=2)   # fusion regression


# -- the acceptance gate: K=8 fused scan vs 8 sequential runs ----------------


def test_k8_fused_scan_bitwise_one_compile_one_dispatch(pg, static_mode):
    """ISSUE 6 acceptance: K=8 microbatches through run_steps produce a
    BITWISE-identical loss trajectory to 8 sequential Executor.run
    calls, with exactly 1 compile + 1 dispatch (vs 8 dispatches), the
    persistable carry donated, and exactly one while loop (the scan) in
    the fused executable."""
    K = 8
    feeds = _feeds(K)

    pt.seed(0)
    prog, startup, loss = _build_mlp()
    exe = fluid.Executor()
    exe.run(startup)
    seq = [exe.run(prog, feed=f, fetch_list=[loss])[0] for f in feeds]
    calls = pg.executor_call_counts(exe)
    assert calls["compiles"] == 1 and calls["dispatches"] == K, calls

    pt.seed(0)
    prog2, startup2, loss2 = _build_mlp()
    exe2 = fluid.Executor()
    exe2.run(startup2)
    (traj,) = exe2.run_steps(prog2, feeds=feeds, fetch_list=[loss2])
    calls2 = pg.executor_call_counts(exe2)
    assert calls2["compiles"] == 1 and calls2["dispatches"] == 1, calls2
    assert traj.shape == (K,)
    for k, s in enumerate(seq):
        assert np.asarray(s).tobytes() == np.asarray(traj[k]).tobytes(), \
            (k, float(np.asarray(s)), float(traj[k]))

    entry = next(iter(exe2._cache.values()))
    n_persist = len(entry.updated)
    assert n_persist >= 4  # 2 fc layers: w + b each
    assert pg.check_entry(entry, min_donated=n_persist,
                          min_while=1, max_while=1) == []
    # rerunning the same window is a cache hit, one more dispatch
    exe2.run_steps(prog2, feeds=feeds, fetch_list=[loss2])
    calls2 = pg.executor_call_counts(exe2)
    assert calls2["compiles"] == 1 and calls2["dispatches"] == 2
    assert calls2["cache_hits"] == 1


def test_sequential_entry_donates_and_has_no_loop(pg, static_mode):
    """The single-step executable keeps its donation invariant (params
    update in place) and must NOT contain a while loop."""
    pt.seed(0)
    prog, startup, loss = _build_mlp()
    exe = fluid.Executor()
    exe.run(startup)
    exe.run(prog, feed=_feeds(1)[0], fetch_list=[loss])
    entry = next(iter(exe._cache.values()))
    assert pg.check_entry(entry, min_donated=len(entry.updated),
                          max_while=0) == []


def test_dp_fused_entry_keeps_collectives_in_loop(pg, static_mode):
    """Fused + data-parallel: the grad all-reduce must survive inside
    the scan body (one all-reduce instruction in the while body — it
    executes once per microbatch), and the fused DP trajectory must
    match sequential DP runs bitwise."""
    import jax

    if jax.local_device_count() < 2:
        pytest.skip("needs the 8-fake-device mesh")
    K = 4
    feeds = _feeds(K)

    pt.seed(0)
    prog, startup, loss = _build_mlp()
    cp = fluid.CompiledProgram(prog).with_data_parallel(loss_name=loss.name)
    exe = fluid.Executor()
    exe.run(startup)
    seq = [exe.run(cp, feed=f, fetch_list=[loss])[0] for f in feeds]

    pt.seed(0)
    prog2, startup2, loss2 = _build_mlp()
    cp2 = fluid.CompiledProgram(prog2).with_data_parallel(
        loss_name=loss2.name)
    exe2 = fluid.Executor()
    exe2.run(startup2)
    (traj,) = exe2.run_steps(cp2, feeds=feeds, fetch_list=[loss2])
    for k, s in enumerate(seq):
        assert np.asarray(s).tobytes() == np.asarray(traj[k]).tobytes(), \
            (k, float(np.asarray(s)), float(traj[k]))

    entry = next(iter(exe2._cache.values()))
    hlo = pg.entry_hlo(entry)
    from paddle_tpu.obs import spmd

    prof = spmd.collective_profile(
        hlo, mesh=(entry.mesh_axes, entry.mesh_device_ids))
    assert prof["counts"].get("all-reduce", 0) >= 1, prof
    assert prof["bytes"].get("all-reduce", 0) > 0, prof
    # and the fused key is a distinct, named cache axis
    keys = list(exe2._cache)
    assert all(k.data_parallel for k in keys)
    assert any(k.steps == K for k in keys)


def test_cache_key_named_fields(static_mode):
    """CacheKey replaces the positional tuple: new axes are named, and
    distinct K values are distinct entries of the same program."""
    from paddle_tpu.static_.executor import CacheKey

    pt.seed(0)
    prog, startup, loss = _build_mlp()
    exe = fluid.Executor()
    exe.run(startup)
    feeds = _feeds(4)
    exe.run(prog, feed=feeds[0], fetch_list=[loss])
    exe.run_steps(prog, feeds=feeds[:2], fetch_list=[loss])
    exe.run_steps(prog, feeds=feeds, fetch_list=[loss])
    keys = list(exe._cache)
    assert all(isinstance(k, CacheKey) for k in keys)
    assert {k.steps for k in keys} == {None, 2, 4}
    assert all(k.program_uid == prog._uid for k in keys)
    assert all(k.data_parallel is False for k in keys)


def test_fetch_async_returns_jax_arrays_no_numpy(static_mode):
    """fetch_async=True hands back raw jax arrays (no numpy conversion,
    no Tensor wrapper) whose values still match the synced fetch."""
    import jax

    pt.seed(0)
    prog, startup, loss = _build_mlp()
    exe = fluid.Executor()
    exe.run(startup)
    f = _feeds(1)[0]
    (lazy,) = exe.run(prog, feed=f, fetch_list=[loss], fetch_async=True)
    assert isinstance(lazy, jax.Array)
    pt.seed(0)
    prog2, startup2, loss2 = _build_mlp()
    exe2 = fluid.Executor()
    exe2.run(startup2)
    (synced,) = exe2.run(prog2, feed=f, fetch_list=[loss2])
    assert np.asarray(lazy).tobytes() == np.asarray(synced).tobytes()
