"""Fleet observability (obs.fleet) + live SLO export (obs.export):
the cross-rank layer over the per-rank flight recorder.

Covers the PR's acceptance contract:
- rank-aware journals: explicit rank / env PADDLE_TPU_RANK both land
  in <run_dir>/rank_NN without double-nesting;
- hand-built 2-rank fixtures with a KNOWN 2x straggler: exact skew
  numbers, slowest-rank attribution, persistent-straggler detection
  (re-arm style), one preempted/resumed attempt aligning last-wins,
  and merged p50/p99 request percentiles across replicas;
- merged Chrome traces carry one distinct pid lane per rank (device
  counter lanes rank-namespaced, never colliding);
- the Prometheus exporter's scraped TTFT/TPOT values match
  ServeEngine.stats() EXACTLY on a deterministic ManualClock trace,
  over both render() and a real localhost HTTP scrape; textfile
  export is atomic.
"""
import json
import os
import urllib.request

import pytest

from paddle_tpu import obs
from paddle_tpu.obs import export as obs_export
from paddle_tpu.obs import fleet, journal, trace


@pytest.fixture(autouse=True)
def _no_global_journal():
    yield
    if journal.ACTIVE is not None:
        journal.ACTIVE.close()
    journal.ACTIVE = None


def _write_rank(run_dir, rank, step_ms, n_steps=10, start_step=1,
                requests=(), **journal_kw):
    j = journal.RunJournal(run_dir, rank=rank, flush_every=1,
                           compute_flops=False, **journal_kw)
    j.start()
    for i in range(start_step, start_step + n_steps):
        j.sync_step(i)
        j.record_step(loss=1.0 / i, step_ms=step_ms, examples=8,
                      source="fixture")
    for i, ttft_ms in enumerate(requests):
        j.record_request(rid=f"r{rank}_{i}", state="FINISHED",
                         arrival_t=0.0, first_token_t=ttft_ms / 1e3,
                         finish_t=2.0, prompt_tokens=4, output_tokens=5)
    j.close()
    return j


# -- rank-aware journals ------------------------------------------------------


class TestRankJournals:
    def test_explicit_rank_lands_in_rank_subdir(self, tmp_path):
        j = _write_rank(str(tmp_path), 3, 10.0, n_steps=2)
        assert j.run_dir == str(tmp_path / "rank_03")
        run = fleet.load_journal(str(tmp_path / "rank_03"))
        assert run["header"]["rank"] == 3
        assert len(run["steps"]) == 2

    def test_env_rank_with_preassigned_subdir_does_not_nest(
            self, tmp_path, monkeypatch):
        """The GangSupervisor contract: PADDLE_TPU_RUN_DIR already IS
        <run>/rank_01 and PADDLE_TPU_RANK=1 — no rank_01/rank_01."""
        sub = tmp_path / "rank_01"
        monkeypatch.setenv("PADDLE_TPU_RANK", "1")
        j = journal.RunJournal(str(sub), compute_flops=False).start()
        j.record_step(loss=1.0, step_ms=1.0)
        j.close()
        assert j.rank == 1
        assert j.run_dir == str(sub)
        assert not (sub / "rank_01").exists()
        assert fleet.rank_dirs(str(tmp_path)) == {1: str(sub)}

    def test_sync_step_numbers_records_by_global_step(self, tmp_path):
        _write_rank(str(tmp_path), 0, 10.0, n_steps=3, start_step=5)
        run = fleet.load_journal(str(tmp_path / "rank_00"))
        assert [s["step"] for s in run["steps"]] == [5, 6, 7]


# -- cross-rank aggregation ---------------------------------------------------


class TestFleetAggregate:
    def _skewed(self, tmp_path):
        _write_rank(str(tmp_path), 0, 10.0,
                    requests=[100.0, 200.0, 300.0, 400.0, 500.0])
        _write_rank(str(tmp_path), 1, 20.0,
                    requests=[600.0, 700.0, 800.0, 900.0, 1000.0])
        return fleet.aggregate(str(tmp_path))

    def test_exact_skew_numbers_and_attribution(self, tmp_path):
        agg = self._skewed(tmp_path)
        assert agg["nranks"] == 2 and agg["aligned_steps"] == 10
        # skew = max/median over ranks = 20/15; straggler magnitude =
        # slowest / median of the OTHERS = 20/10 = 2.0 exactly
        assert agg["skew"]["max"] == pytest.approx(20.0 / 15.0,
                                                   abs=1e-12)
        assert agg["skew"]["worst_rank"] == 1
        assert agg["skew"]["worst_rank_ratio"] == pytest.approx(
            2.0, abs=1e-12)
        assert agg["skew"]["slowest_counts"] == {1: 10}
        slow = [s for s in agg["stragglers"] if s["kind"] == "slow"]
        assert len(slow) == 1
        assert slow[0]["rank"] == 1
        assert slow[0]["ratio"] == pytest.approx(2.0, abs=1e-12)
        assert slow[0]["first_step"] == 1

    def test_merged_request_percentiles(self, tmp_path):
        """TTFT 100..1000 ms across the two replicas: the merged pool's
        nearest-rank p50 is 500 ms, p99 is 1000 ms — per-replica
        percentiles would NOT produce these (rank 0 alone p50=300)."""
        agg = self._skewed(tmp_path)
        req = agg["requests"]
        assert req["requests"] == 10 and req["finished"] == 10
        assert req["ttft_ms_p50"] == pytest.approx(500.0, abs=1e-9)
        assert req["ttft_ms_p99"] == pytest.approx(1000.0, abs=1e-9)

    def test_balanced_gang_has_no_stragglers(self, tmp_path):
        _write_rank(str(tmp_path), 0, 10.0)
        _write_rank(str(tmp_path), 1, 10.0)
        agg = fleet.aggregate(str(tmp_path))
        assert agg["stragglers"] == []
        assert agg["skew"]["max"] == pytest.approx(1.0)

    def test_preempted_attempt_aligns_last_wins(self, tmp_path):
        """One rank restarts (a preempted attempt) and re-executes
        steps 3..5: alignment keeps the LAST record per (rank, step),
        and the incarnation count survives in run_starts."""
        _write_rank(str(tmp_path), 0, 10.0, n_steps=5)
        _write_rank(str(tmp_path), 1, 10.0, n_steps=3)       # dies at 3
        _write_rank(str(tmp_path), 1, 30.0, n_steps=3,       # resumes
                    start_step=3)
        flt = fleet.load_fleet(str(tmp_path))
        run1 = flt["ranks"][1]
        assert len(run1["run_starts"]) == 2
        aligned = fleet.align_steps(flt)
        assert [row["step"] for row in aligned] == [1, 2, 3, 4, 5]
        # step 3 was re-executed by incarnation 2: last record wins
        assert aligned[2]["by_rank"][1]["step_ms"] == 30.0
        assert aligned[2]["by_rank"][1]["_incarnation"] == 2
        per = fleet.aggregate(flt)["per_rank"][1]
        assert per["run_starts"] == 2 and per["last_step"] == 5

    def test_comm_rollup_sums_per_rank_means(self, tmp_path):
        for rank in (0, 1):
            j = journal.RunJournal(str(tmp_path), rank=rank,
                                   flush_every=1, compute_flops=False)
            j.start()
            for i in range(1, 4):
                j.sync_step(i)
                j.record_step(loss=1.0, step_ms=10.0,
                              comm={"total_bytes": 1000 * (rank + 1),
                                    "wire_bytes": 1750,
                                    "all_reduce_bytes": 500})
            j.close()
        agg = fleet.aggregate(str(tmp_path))
        assert agg["per_rank"][0]["comm_bytes_per_step"] == 1000.0
        assert agg["per_rank"][1]["comm_bytes_per_step"] == 2000.0
        assert agg["comm_bytes_per_step_total"] == 3000.0

    def test_reclassify_event_stays_in_its_incarnation(self, tmp_path):
        """Incarnation 1 discards step 2 AFTER its line flushed (the
        correction rides a resilience.skipped event), then crashes;
        incarnation 2 re-runs step 2 cleanly into the same file. The
        loader must flag incarnation 1's record, never the clean
        re-run."""
        run_dir = str(tmp_path / "rank_00")
        j = journal.RunJournal(run_dir, rank=0, flush_every=1,
                               compute_flops=False).start()
        j.sync_step(1)
        j.record_step(loss=1.0, step_ms=5.0, source="executor")
        j.sync_step(2)
        j.record_step(loss=0.9, step_ms=5.0, source="executor")
        j.event("resilience.skipped", source="guarded_executor")
        j.close()
        j2 = journal.RunJournal(run_dir, rank=0, flush_every=1,
                                compute_flops=False).start()
        j2.sync_step(2)  # the resume re-executes step 2, cleanly
        j2.record_step(loss=0.9, step_ms=5.0, source="executor")
        j2.close()
        run = fleet.load_journal(run_dir)
        flags = [(s["_incarnation"], s["step"], bool(s.get("skipped")))
                 for s in run["steps"]]
        assert flags == [(1, 1, False), (1, 2, True), (2, 2, False)]
        # alignment keeps the clean incarnation-2 record for step 2
        aligned = fleet.align_steps({"ranks": {0: run}})
        assert not aligned[1]["by_rank"][0].get("skipped")

    def test_budget_exhausted_hang_is_attributed(self, tmp_path):
        """A terminal hang (restart budget spent) emits
        elastic.budget_exhausted instead of elastic.restart — the most
        postmortem-relevant hang must still get journal-side rank
        attribution."""
        _write_rank(str(tmp_path), 0, 10.0, n_steps=4)
        _write_rank(str(tmp_path), 1, 10.0, n_steps=3)  # stops first
        sup = str(tmp_path / fleet.SUPERVISOR_DIR)
        j = journal.RunJournal(sup, compute_flops=False).start()
        j.event("elastic.start", nprocs=2)
        j.event("elastic.budget_exhausted", restarts=0,
                last_kind="hang", last_rank=0, last_code=137)
        j.close()
        hangs = [s for s in fleet.aggregate(str(tmp_path))["stragglers"]
                 if s["kind"] == "hang"]
        assert len(hangs) == 1
        # journals say rank 1 (lowest last step), NOT the watchdog's
        # poll-noisy rank 0
        assert hangs[0]["rank"] == 1 and hangs[0]["watchdog_rank"] == 0
        assert hangs[0]["last_step"] == 3

    def test_rank_base_gives_global_identity(self, tmp_path):
        """A node-1 supervisor (rank_base=nproc) must hand its workers
        GLOBAL rank dirs/ids and keep its own journal out of node 0's
        supervisor/ — two nodes sharing one run_dir never co-write."""
        import subprocess  # noqa: F401 (spawned via GangSupervisor)
        import sys

        from paddle_tpu.resilience import GangSupervisor

        run = str(tmp_path / "run")
        probe = ("import os,json;"
                 "open(os.environ['PT_PROBE_OUT']+'/'+"
                 "os.environ['PADDLE_TPU_RANK'],'w')"
                 ".write(json.dumps([os.environ['PADDLE_TPU_RUN_DIR'],"
                 "os.environ['PADDLE_TRAINER_ID']]))")
        out = tmp_path / "probe"
        out.mkdir()
        sup = GangSupervisor(
            [sys.executable, "-c", probe], nprocs=2, rank_base=4,
            run_dir=run, env={"PT_PROBE_OUT": str(out)},
            poll_interval_s=0.01, term_grace_s=1.0)
        assert sup.run() == 0
        got = {fn: json.load(open(out / fn)) for fn in os.listdir(out)}
        assert sorted(got) == ["4", "5"]
        assert got["4"] == [os.path.join(run, "rank_04"), "4"]
        assert got["5"] == [os.path.join(run, "rank_05"), "5"]
        assert os.path.isfile(os.path.join(
            run, "supervisor_04", "journal.jsonl"))
        assert not os.path.exists(os.path.join(run, "supervisor"))
        # the READERS see the node-1 supervisor too (a suffixed
        # journal nobody loads would be a silently-orphaned record)
        assert fleet.supervisor_dirs(run) == {
            4: os.path.join(run, "supervisor_04")}
        _write_rank(run, 4, 10.0, n_steps=2)
        _write_rank(run, 5, 10.0, n_steps=2)
        flt = fleet.load_fleet(run)
        assert 4 in flt["supervisors"]
        agg = fleet.aggregate(run)
        assert agg["supervisor"] is not None  # node-1 events rolled up
        assert agg["supervisor"]["completed"]

    def test_multinode_hang_scoped_to_its_node(self, tmp_path):
        """Two nodes share one run_dir, both with an attempt-1 hang
        restart: each supervisor's attribution must only consider ITS
        rank slice (attempt counters are per-supervisor)."""
        run = str(tmp_path)
        for rank, steps in ((0, 5), (1, 3), (4, 5), (5, 2)):
            _write_rank(run, rank, 10.0, n_steps=steps)
        for base in (0, 4):
            name = "supervisor" if base == 0 else f"supervisor_{base:02d}"
            j = journal.RunJournal(os.path.join(run, name),
                                   compute_flops=False).start()
            j.event("elastic.restart", failure="hang", rank=0,
                    attempt=0, restarts_used=1)
            j.close()
        hangs = {(s["rank"], s["last_step"])
                 for s in fleet.stall_attribution(fleet.load_fleet(run))}
        # node 0 slice {0,1}: rank 1 stopped at 3; node 1 slice {4,5}:
        # rank 5 stopped at 2 — never rank 1 vs rank 5 cross-matched
        assert hangs == {(1, 3), (5, 2)}

    def test_straggler_detector_rearms_per_episode(self):
        rows = [{"step": i, "slowest": 1, "slowest_vs_others": r}
                for i, r in enumerate(
                    [2.0, 2.0, 2.0, 2.0,    # episode 1 (fires at 3rd)
                     1.0,                   # recovery re-arms
                     2.0, 2.0, 2.0])]       # episode 2 (fires again)
        det = fleet.StragglerDetector(factor=1.5, patience=3)
        fired = [det.update(r) for r in rows]
        assert [bool(f) for f in fired] == [
            False, False, True, False, False, False, False, True]

    def test_rank_change_resets_the_streak(self):
        rows = [{"step": 0, "slowest": 0, "slowest_vs_others": 2.0},
                {"step": 1, "slowest": 1, "slowest_vs_others": 2.0},
                {"step": 2, "slowest": 1, "slowest_vs_others": 2.0}]
        det = fleet.StragglerDetector(factor=1.5, patience=2)
        assert [bool(det.update(r)) for r in rows] == \
            [False, False, True]


# -- merged Chrome traces -----------------------------------------------------


class TestMergedTraces:
    def _export_rank_trace(self, run_dir, rank):
        os.makedirs(os.path.join(run_dir, fleet.rank_subdir(rank)),
                    exist_ok=True)
        prev = trace.current_rank()
        trace.enable_tracing()
        trace.clear_trace()
        try:
            trace.set_rank(rank)
            with trace.span("work", rank=rank):
                pass
            trace.device_counter(0, "bytes_in_use", 123.0)
            trace.export_chrome_trace(os.path.join(
                run_dir, fleet.rank_subdir(rank), fleet.TRACE_FILE))
        finally:
            trace.set_rank(prev)
            trace.disable_tracing()
            trace.clear_trace()

    def test_rank_lanes_never_collide(self, tmp_path):
        """Two ranks, each with a span and a device-0 counter: the
        merged trace keeps one host lane per rank (pid=rank) and puts
        each rank's device 0 in its own namespace slice."""
        run_dir = str(tmp_path)
        for rank in (0, 1):
            # each rank needs a journal for rank_dirs discovery
            _write_rank(run_dir, rank, 10.0, n_steps=1)
            self._export_rank_trace(run_dir, rank)
        out = str(tmp_path / "merged.json")
        res = fleet.merge_chrome_traces(run_dir, out)
        assert res["sources"] == 2
        with open(out, encoding="utf-8") as f:
            events = json.load(f)["traceEvents"]
        span_pids = {e["pid"] for e in events if e["ph"] == "X"}
        assert span_pids == {0, 1}
        dev_pids = {e["pid"] for e in events if e["ph"] == "C"}
        assert dev_pids == {
            trace.DEVICE_PID_BASE,
            trace.DEVICE_PID_BASE + trace.RANK_PID_STRIDE}
        names = {e["pid"]: e["args"]["name"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names[0] == "rank 00" and names[1] == "rank 01"

    def test_merge_is_idempotent_on_unnamespaced_exports(self,
                                                         tmp_path):
        """A worker that exported WITHOUT a rank identity (pid =
        os.getpid(), device lane = DEVICE_PID_BASE + id) still merges
        into the correct rank lanes — the remap recovers the device
        slot mod RANK_PID_STRIDE. Host spans are classified by the
        source's counter pids, NOT pid magnitude: on hosts with
        pid_max raised past DEVICE_PID_BASE an OS pid can exceed the
        device band (the 2_000_000 span below) and must still land on
        the rank lane."""
        run_dir = str(tmp_path)
        _write_rank(run_dir, 2, 10.0, n_steps=1)
        raw = {"traceEvents": [
            {"ph": "X", "pid": 2_000_000, "tid": 1, "name": "s",
             "ts": 0, "dur": 1, "args": {}},
            {"ph": "C", "pid": trace.DEVICE_PID_BASE + 7,
             "name": "bytes_in_use", "ts": 0, "args": {"value": 1.0}},
        ]}
        with open(os.path.join(run_dir, "rank_02", fleet.TRACE_FILE),
                  "w") as f:
            json.dump(raw, f)
        out = str(tmp_path / "merged.json")
        fleet.merge_chrome_traces(run_dir, out)
        with open(out, encoding="utf-8") as f:
            events = json.load(f)["traceEvents"]
        assert {e["pid"] for e in events if e["ph"] == "X"} == {2}
        assert {e["pid"] for e in events if e["ph"] == "C"} == {
            trace.DEVICE_PID_BASE + 2 * trace.RANK_PID_STRIDE + 7}


# -- live SLO export ----------------------------------------------------------


def _manual_clock_engine():
    """A deterministic served trace: ManualClock timestamps, so
    stats() percentiles are exact rationals the exporter must
    reproduce bit-for-bit."""
    from paddle_tpu.serving import PagedKVCache, ServeEngine, TinyLM
    from paddle_tpu.serving.scheduler import ManualClock

    clock = ManualClock()
    eng = ServeEngine(TinyLM(num_heads=2, head_dim=8),
                      PagedKVCache(32, 4, 2, 8, max_seq_len=32),
                      clock=clock)
    for prompt in ([1, 2, 3], [4, 5], [6]):
        eng.submit(prompt, max_new_tokens=3, arrival_t=clock())
    # advance the clock unevenly so ttft/tpot differ per request
    for dt in (0.010, 0.007, 0.005, 0.003, 0.002, 0.001, 0.001):
        clock.advance(dt)
        if not eng.step():
            break
    eng.run()
    assert eng.stats()["finished"] == 3
    return eng


class TestExporter:
    def test_scrape_matches_engine_stats_exactly(self):
        eng = _manual_clock_engine()
        st = eng.stats()
        text = obs_export.prometheus_text(engines=[eng])
        vals = obs_export.parse_prometheus_text(text)
        rep = eng.replica_id
        for key in ("ttft_ms", "tpot_ms", "e2e_ms"):
            for q in ("p50", "p99"):
                name = (f'paddle_tpu_serving_slo_{key}'
                        f'{{replica="{rep}",q="{q}"}}')
                assert vals[name] == st[key][q], \
                    f"{name}: scraped {vals[name]} != stats {st[key][q]}"
        assert vals[f'paddle_tpu_serving_slo_queue_depth'
                    f'{{replica="{rep}"}}'] == st["queue_depth"]
        assert vals[f'paddle_tpu_serving_slo_finished'
                    f'{{replica="{rep}"}}'] == 3.0

    def test_http_endpoint_serves_the_same_snapshot(self):
        eng = _manual_clock_engine()
        st = eng.stats()
        exp = obs_export.MetricsExporter(engines=[eng])
        port = exp.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10) as resp:
                assert resp.status == 200
                body = resp.read().decode("utf-8")
        finally:
            exp.stop()
        vals = obs_export.parse_prometheus_text(body)
        name = (f'paddle_tpu_serving_slo_ttft_ms'
                f'{{replica="{eng.replica_id}",q="p99"}}')
        assert vals[name] == st["ttft_ms"]["p99"]
        # the registry rides along: serving counters are in the scrape
        assert "paddle_tpu_serving_requests_finished" in vals

    def test_live_engine_discovery(self):
        from paddle_tpu.serving.engine import live_engines

        eng = _manual_clock_engine()
        assert eng in live_engines()
        # no explicit engine list: the exporter finds it by itself
        text = obs_export.prometheus_text()
        assert (f'paddle_tpu_serving_slo_finished'
                f'{{replica="{eng.replica_id}"}}') in text

    def test_rank_heartbeat_age_gauges(self, tmp_path):
        _write_rank(str(tmp_path), 0, 10.0, n_steps=1)
        _write_rank(str(tmp_path), 1, 10.0, n_steps=1)
        text = obs_export.prometheus_text(engines=[],
                                          run_dir=str(tmp_path))
        vals = obs_export.parse_prometheus_text(text)
        for rank in (0, 1):
            age = vals[f'paddle_tpu_rank_heartbeat_age_seconds'
                       f'{{rank="{rank}"}}']
            assert 0.0 <= age < 3600.0

    def test_textfile_export_is_atomic(self, tmp_path):
        path = str(tmp_path / "metrics.prom")
        obs_export.write_textfile(path, engines=[])
        body = open(path, encoding="utf-8").read()
        assert body.endswith("\n")
        assert "# TYPE" in body
        assert not [fn for fn in os.listdir(str(tmp_path))
                    if fn.startswith("metrics.prom.tmp")]

    def test_histogram_exposition_shape(self):
        reg = obs.metrics.Registry()
        h = reg.histogram("unit.test_ms", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        reg.counter("unit.hits").inc(7)
        lines = obs_export.registry_lines(reg)
        text = "\n".join(lines)
        assert 'paddle_tpu_unit_test_ms_bucket{le="1.0"} 1' in text
        assert 'paddle_tpu_unit_test_ms_bucket{le="10.0"} 2' in text
        assert 'paddle_tpu_unit_test_ms_bucket{le="+Inf"} 3' in text
        assert "paddle_tpu_unit_test_ms_count 3" in text
        assert "paddle_tpu_unit_test_ms_sum 55.5" in text
        assert "# TYPE paddle_tpu_unit_hits counter" in text
        assert "paddle_tpu_unit_hits 7.0" in text


class TestExpositionRoundTrip:
    """ISSUE 19: the fleet signal plane's scrape/merge algebra. The
    new ``slo_*`` gauge families ride the same merged exposition the
    autoscaler and the SLO evaluator read — a merge that wrongly
    summed their labeled series (or broke histogram ``_bucket``
    cumulativity) would silently corrupt burn rates fleet-wide."""

    def _evaluator_with_signal(self):
        from paddle_tpu.obs import slo as obs_slo
        from paddle_tpu.serving import ManualClock

        clock = ManualClock()
        ev = obs_slo.SLOEvaluator(
            {"availability": 0.99}, clock=clock, interval_s=60.0,
            include_registry=False)
        rej, disp = 0.0, 0.0
        for i in range(49):   # 40 clean ticks, then 9 at 50% rejects
            bad = 50 if i >= 40 else 0
            rej += bad
            disp += 100 - bad
            clock.advance(60.0)
            ev.observe(
                text={"serving.router.rejected": ("counter", rej),
                      "serving.router.dispatched": ("counter", disp)},
                now=clock())
        return ev

    def test_merge_passes_labeled_slo_gauges_verbatim(self):
        """A fleet front-end merges its own exposition (registry +
        live SLO engine) with a remote replica's: every ``slo_*``
        burn/budget/alert gauge and every per-replica latency gauge is
        a single-source labeled series, so it must survive the merge
        VERBATIM (bitwise equal to the evaluator's float) — while
        identical unlabeled counter keys across sources sum."""
        ev = self._evaluator_with_signal()
        rega = obs.metrics.Registry()
        rega.counter("unit.hits").inc(3)
        regb = obs.metrics.Registry()
        regb.counter("unit.hits").inc(4)
        local = obs_export.prometheus_text(engines=[], registry=rega,
                                           slo=ev)
        eng = _manual_clock_engine()
        remote = obs_export.prometheus_text(engines=[eng],
                                            registry=regb)
        vals = obs_export.parse_prometheus_text(
            obs_export.merge_expositions([local, remote]))

        for w in ("1m", "5m", "30m", "3h"):
            key = (f'paddle_tpu_slo_burn_rate{{objective='
                   f'"availability",window="{w}"}}')
            assert vals[key] == ev.burn[("availability", w)]
        assert vals['paddle_tpu_slo_budget_remaining'
                    '{objective="availability"}'] == \
            ev.budget_left["availability"]
        # 9 bad ticks at 50%: both ladder rungs are latched
        for sev in ("page", "warn"):
            assert vals[f'paddle_tpu_slo_alert_active{{objective='
                        f'"availability",severity="{sev}"}}'] == 1.0
        st = eng.stats()
        assert vals[f'paddle_tpu_serving_slo_ttft_ms'
                    f'{{replica="{eng.replica_id}",q="p99"}}'] == \
            st["ttft_ms"]["p99"]
        assert vals["paddle_tpu_unit_hits"] == 7.0  # 3 + 4, summed

    def test_merged_histograms_keep_the_cumulative_invariant(self):
        """Merging two replicas' expositions of the same histogram
        family must yield a series that is still a valid Prometheus
        histogram: per-``le`` values summed, non-decreasing in bound
        order, ``_bucket{le="+Inf"} == _count``, ``# TYPE`` declared
        once — and ``timeseries.exposition_snapshot`` must reconstruct
        the pooled bucket layout from the merged text."""
        from paddle_tpu.obs import timeseries as obs_ts

        rega = obs.metrics.Registry()
        for v in (0.5, 5.0, 50.0, 500.0):
            rega.histogram("unit.lat_ms",
                           buckets=(1.0, 10.0, 100.0)).observe(v)
        regb = obs.metrics.Registry()
        for v in (0.7, 7.0, 7.0):
            regb.histogram("unit.lat_ms",
                           buckets=(1.0, 10.0, 100.0)).observe(v)
        texts = ["\n".join(obs_export.registry_lines(r)) + "\n"
                 for r in (rega, regb)]
        merged = obs_export.merge_expositions(texts)
        vals = obs_export.parse_prometheus_text(merged)

        n = "paddle_tpu_unit_lat_ms"
        series = [vals[f'{n}_bucket{{le="1.0"}}'],
                  vals[f'{n}_bucket{{le="10.0"}}'],
                  vals[f'{n}_bucket{{le="100.0"}}'],
                  vals[f'{n}_bucket{{le="+Inf"}}']]
        assert series == [2.0, 5.0, 6.0, 7.0]
        assert all(a <= b for a, b in zip(series, series[1:]))
        assert series[-1] == vals[n + "_count"] == 7.0
        va, vb = (obs_export.parse_prometheus_text(t) for t in texts)
        assert vals[n + "_sum"] == va[n + "_sum"] + vb[n + "_sum"]
        assert merged.count(f"# TYPE {n} histogram") == 1

        kind, (bounds, cum, count, total) = \
            obs_ts.exposition_snapshot(merged)[n]
        assert kind == "histogram"
        assert bounds == (1.0, 10.0, 100.0)
        # 3 finite bounds + the overflow slot (derived from _count,
        # never from the parsed +Inf line)
        assert cum == (2, 5, 6, 7)
        assert count == 7 and total == vals[n + "_sum"]
