"""fluid.io var-level save/load + transpiler namespace tests.
Ref: python/paddle/fluid/io.py __all__ (save/load_params, persistables,
program state) and transpiler/__init__.py."""
import numpy as np
import os
import tempfile
import paddle_tpu as pt
import paddle_tpu.fluid as fluid


def test_fluid_io_var_save_load():

    pt.enable_static()
    prog = pt.static.Program()
    startup = pt.static.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", [4, 3], "float32")
        y = fluid.layers.fc(x, 2)
    exe = fluid.Executor()
    exe.run(startup)
    out1 = exe.run(prog, feed={"x": np.ones((4, 3), "float32")}, fetch_list=[y])[0]

    d = tempfile.mkdtemp()
    fluid.io.save_params(exe, d, prog)
    params = fluid.io.get_program_parameter(prog)
    assert len(params) >= 1
    pv = fluid.io.get_program_persistable_vars(prog)
    assert len(pv) >= len(params)

    state = fluid.io.load_program_state(os.path.join(d, "__params__.npz"))
    assert len(state) == len(params)
    # zero out, reload, verify restored
    zeroed = {k: np.zeros_like(v) for k, v in state.items()}
    fluid.io.set_program_state(prog, zeroed)
    out_z = exe.run(prog, feed={"x": np.ones((4, 3), "float32")}, fetch_list=[y])[0]
    assert np.allclose(np.asarray(out_z), 0.0)
    fluid.io.load_params(exe, d, prog)
    out2 = exe.run(prog, feed={"x": np.ones((4, 3), "float32")}, fetch_list=[y])[0]
    assert np.allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)

    fluid.io.save_persistables(exe, d, prog)
    fluid.io.load_persistables(exe, d, prog)
    assert callable(fluid.io.batch)
    pt.disable_static()
    print("FLUID IO OK")


def test_transpiler_namespace():
    import pytest
    import paddle_tpu.fluid as fluid

    cfg = fluid.DistributeTranspilerConfig()
    assert cfg.sync_mode
    t = fluid.DistributeTranspiler(cfg)
    with pytest.raises(NotImplementedError):
        t.transpile(0)
    assert fluid.memory_optimize(None) is None
    assert fluid.release_memory(None) is None
    from paddle_tpu.fluid.transpiler import HashName, RoundRobin

    rr = RoundRobin(["a", "b"])
    assert rr.dispatch(["v1", "v2", "v3"]) == ["a", "b", "a"]
    hn = HashName(["a", "b"])
    d = hn.dispatch(["v1", "v1"])
    assert d[0] == d[1]
