"""AMP tests (model: reference contrib/tests/test_image_classification_fp16
and mixed_precision unit tests — auto_cast lists, loss scaling, decorate)."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu import amp


class TestAutoCast:
    def test_white_op_computes_half(self):
        a = pt.to_tensor(np.random.randn(16, 16).astype("float32"))
        b = pt.to_tensor(np.random.randn(16, 16).astype("float32"))
        with amp.auto_cast(dtype="bfloat16"):
            out = pt.matmul(a, b)
        assert out.dtype == "bfloat16"
        out2 = pt.matmul(a, b)
        assert out2.dtype == "float32"

    def test_black_op_stays_f32(self):
        x = pt.to_tensor(np.random.randn(8, 8).astype("float32"))
        with amp.auto_cast(dtype="bfloat16"):
            h = pt.matmul(x, x)            # bf16
            s = F.softmax(h)               # black: cast back to f32
        assert s.dtype == "float32"

    def test_custom_lists(self):
        x = pt.to_tensor(np.random.randn(4, 4).astype("float32"))
        with amp.auto_cast(custom_black_list=["matmul"]):
            out = pt.matmul(x, x)
        assert out.dtype == "float32"
        with amp.auto_cast(custom_white_list=["softmax"]):
            out = F.softmax(pt.matmul(x, x))
        assert out.dtype == "bfloat16"

    def test_disabled_passthrough(self):
        x = pt.to_tensor(np.random.randn(4, 4).astype("float32"))
        with amp.auto_cast(enable=False):
            out = pt.matmul(x, x)
        assert out.dtype == "float32"

    def test_grads_arrive_in_param_dtype(self):
        m = nn.Linear(8, 4)
        x = pt.to_tensor(np.random.randn(2, 8).astype("float32"))
        with amp.auto_cast(dtype="bfloat16"):
            loss = m(x).astype("float32").sum()
        loss.backward()
        assert m.weight.grad is not None
        assert m.weight.grad.dtype == "float32"  # same dtype as the param

    def test_train_step_with_autocast_loss(self):
        """auto_cast inside loss_fn is traced into the fused step."""
        pt.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = optim.Adam(1e-2, parameters=m.parameters())

        def loss_fn(model, x, y):
            with amp.auto_cast(dtype="bfloat16"):
                out = model(x)
            return F.mse_loss(out.astype("float32"), y)

        step = pt.TrainStep(m, opt, loss_fn)
        X = np.random.RandomState(0).randn(32, 8).astype("float32")
        Y = np.random.RandomState(1).randn(32, 1).astype("float32")
        losses = [float(step(X, Y)) for _ in range(8)]
        assert losses[-1] < losses[0]


class TestLossScalers:
    def test_dynamic_scaler_state_machine(self):
        sc = amp.DynamicLossScaler(init_loss_scaling=1024.0, incr_ratio=2.0,
                                   decr_ratio=0.5, incr_every_n_steps=2)
        st = sc.state()
        st = sc.update_state(st, jnp.bool_(False))
        assert float(st["scale"]) == 1024.0 and int(st["good"]) == 1
        st = sc.update_state(st, jnp.bool_(False))   # hits incr_every_n=2
        assert float(st["scale"]) == 2048.0 and int(st["good"]) == 0
        st = sc.update_state(st, jnp.bool_(True))    # overflow halves
        assert float(st["scale"]) == 1024.0 and int(st["good"]) == 0

    def test_static_scaler_fixed(self):
        sc = amp.StaticLossScaler(128.0)
        st = sc.state()
        st2 = sc.update_state(st, jnp.bool_(True))
        assert float(st2["scale"]) == 128.0

    def test_fused_step_skips_update_on_inf(self):
        """A loss that goes inf must leave params untouched and halve the
        scale; a clean loss must update params."""
        pt.seed(0)
        m = nn.Linear(4, 1)
        opt = optim.SGD(0.1, parameters=m.parameters())
        scaler = amp.DynamicLossScaler(init_loss_scaling=8.0,
                                       incr_every_n_steps=1000)

        def loss_fn(model, x, y, bad):
            # bad=1 blows the loss (and so the grads) up to inf
            return F.mse_loss(model(x), y) * (1.0 + bad * np.float32(1e38))

        step = pt.TrainStep(m, opt, loss_fn, scaler=scaler)
        X = np.random.RandomState(0).randn(8, 4).astype("float32")
        Y = np.random.RandomState(1).randn(8, 1).astype("float32")
        w0 = m.weight.numpy().copy()
        step(X, Y, np.float32(1.0))  # overflow step
        np.testing.assert_array_equal(m.weight.numpy(), w0)
        assert float(step._scaler_state["scale"]) == 4.0

        step(X, Y, np.float32(0.0))  # clean step
        assert not np.allclose(m.weight.numpy(), w0)
        assert float(step._scaler_state["scale"]) == 4.0

    def test_grad_scaler_eager_protocol(self):
        pt.seed(1)
        m = nn.Linear(4, 1)
        opt = optim.SGD(0.1, parameters=m.parameters())
        scaler = amp.GradScaler(init_loss_scaling=16.0)
        X = pt.to_tensor(np.random.randn(8, 4).astype("float32"))
        Y = pt.to_tensor(np.random.randn(8, 1).astype("float32"))
        w0 = m.weight.numpy().copy()
        loss = F.mse_loss(m(X), Y)
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        assert not np.allclose(m.weight.numpy(), w0)
        assert scaler.loss_scaling == 16.0  # no overflow, no growth yet


class TestDecorate:
    def test_o2_casts_model_and_enables_master(self):
        m = nn.Linear(8, 8)
        opt = optim.Adam(1e-3, parameters=m.parameters())
        m2, opt2 = amp.decorate(m, opt, level="O2", dtype="bfloat16")
        assert m2.weight.dtype == "bfloat16"
        assert opt2._multi_precision
        # master weights materialize on first state access
        opt2._state_for(m2.weight)
        assert opt2._accumulators[m2.weight.name]["master"].dtype == \
            jnp.float32

    def test_o2_trains(self):
        pt.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = optim.Adam(1e-2, parameters=m.parameters())
        m, opt = amp.decorate(m, opt, level="O2", dtype="bfloat16")

        def loss_fn(model, x, y):
            return F.mse_loss(model(x.astype("bfloat16")).astype("float32"),
                              y)

        step = pt.TrainStep(m, opt, loss_fn)
        X = np.random.RandomState(0).randn(32, 8).astype("float32")
        Y = np.random.RandomState(1).randn(32, 1).astype("float32")
        losses = [float(step(X, Y)) for _ in range(10)]
        assert losses[-1] < losses[0]


class TestReviewFixes:
    def test_decr_every_n_nan_or_inf(self):
        sc = amp.DynamicLossScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=2)
        st = sc.state()
        st = sc.update_state(st, jnp.bool_(True))
        assert float(st["scale"]) == 1024.0      # 1st bad: no shrink yet
        st = sc.update_state(st, jnp.bool_(True))
        assert float(st["scale"]) == 512.0       # 2nd consecutive: shrink
        st = sc.update_state(st, jnp.bool_(True))
        assert float(st["scale"]) == 512.0       # counter reset
        st = sc.update_state(st, jnp.bool_(False))
        st = sc.update_state(st, jnp.bool_(True))
        assert float(st["scale"]) == 512.0       # non-consecutive: no shrink

    def test_skipped_step_freezes_buffers(self):
        """BN running stats must not absorb an overflowed forward."""
        pt.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.Linear(8, 1))
        opt = optim.SGD(0.1, parameters=m.parameters())
        scaler = amp.DynamicLossScaler(init_loss_scaling=8.0,
                                       decr_every_n_nan_or_inf=1)

        def loss_fn(model, x, y, bad):
            # overflow the LOSS (grads go inf); the BN stats in this
            # forward still receive a normal EMA update we must discard
            return F.mse_loss(model(x), y) * \
                (1.0 + bad * np.float32(1e38)) ** 2

        step = pt.TrainStep(m, opt, loss_fn, scaler=scaler)
        X = np.random.RandomState(0).randn(8, 4).astype("float32")
        Y = np.random.RandomState(1).randn(8, 1).astype("float32")
        step(X, Y, np.float32(0.0))  # clean step primes the stats
        bn = m[1]
        mean0 = bn._mean.numpy().copy()
        step(X, Y, np.float32(1.0))  # overflowed step must be a no-op
        np.testing.assert_array_equal(bn._mean.numpy(), mean0)
        # sanity: a clean step DOES move the stats
        step(X, Y, np.float32(0.0))
        assert not np.array_equal(bn._mean.numpy(), mean0)

    def test_fleet_amp_enables_half_compute(self):
        from paddle_tpu.dist.fleet import DistributedStrategy, fleet
        from paddle_tpu.dist import env as denv

        strat = DistributedStrategy()
        strat.dp_degree = -1
        strat.amp = True
        strat.amp_configs = {"dtype": "bfloat16"}
        denv.set_mesh(None)
        fleet.init(strategy=strat)
        try:
            pt.seed(0)
            m = nn.Linear(8, 8)
            opt = optim.SGD(0.1, parameters=m.parameters())
            seen = {}

            def loss_fn(model, x):
                out = model(x)           # matmul under auto_cast -> bf16
                seen["dtype"] = out.dtype
                return (out.astype("float32") ** 2).mean()

            step = fleet.build_train_step(m, opt, loss_fn)
            step(np.random.RandomState(0).randn(8, 8).astype("float32"))
            assert str(seen["dtype"]) == "bfloat16"
        finally:
            denv.set_mesh(None)
