"""Inference engine tests: Predictor over a saved program, and the
generic decode library (beam/greedy/dynamic_decode).

Model: reference inference/api/analysis_predictor.h (Predictor contract),
python/paddle/fluid/layers/rnn.py dynamic_decode/beam_search semantics.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
import paddle_tpu.optim as optim
from paddle_tpu import ops
from paddle_tpu.inference import (Predictor, Config, beam_search,
                                  greedy_search, BeamSearchDecoder,
                                  dynamic_decode, tile_beam, gather_beams)
from paddle_tpu.models.vision import LeNet


def _save_lenet(tmp_path):
    pt.seed(0)
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.program_guard(main, startup):
            x = pt.static.data("x", [8, 1, 28, 28], "float32")
            model = LeNet()
            logits = model(x)
            prob = F.softmax(logits, axis=-1)
    finally:
        pt.disable_static()
    exe = pt.static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(0).randn(8, 1, 28, 28).astype("float32")
    ref, = exe.run(main, feed={"x": xs}, fetch_list=[prob])
    prefix = str(tmp_path / "lenet")
    pt.framework.io.save_inference_model(prefix, ["x"], [prob],
                                         program=main)
    return prefix, xs, ref


class TestPredictor:
    def test_save_load_parity(self, tmp_path):
        prefix, xs, ref = _save_lenet(tmp_path)
        pred = Predictor(prefix)
        assert pred.get_input_names() == ["x"]
        out, = pred.run({"x": xs})
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_list_feed_and_call(self, tmp_path):
        prefix, xs, ref = _save_lenet(tmp_path)
        pred = Predictor(prefix)
        out, = pred([xs])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_batch_bucketing(self, tmp_path):
        """Odd batch sizes reuse one bucket-sized executable; results are
        unpadded and correct."""
        prefix, xs, ref = _save_lenet(tmp_path)
        cfg = Config(prefix)
        pred = Predictor(cfg)
        out5, = pred.run({"x": xs[:5]})
        assert out5.shape[0] == 5
        np.testing.assert_allclose(out5, ref[:5], rtol=1e-5, atol=1e-6)
        out7, = pred.run({"x": xs[:7]})
        assert out7.shape[0] == 7
        np.testing.assert_allclose(out7, ref[:7], rtol=1e-5, atol=1e-6)
        # 5 and 7 both pad to the 8-bucket -> one compiled executable
        assert len(pred._compiled) == 1

    def test_bucketing_disabled_compiles_per_shape(self, tmp_path):
        prefix, xs, _ = _save_lenet(tmp_path)
        cfg = Config(prefix)
        cfg.disable_batch_bucketing()
        pred = Predictor(cfg)
        pred.run({"x": xs[:3]})
        pred.run({"x": xs[:5]})
        assert len(pred._compiled) == 2

    def test_missing_feed_raises(self, tmp_path):
        prefix, xs, _ = _save_lenet(tmp_path)
        pred = Predictor(prefix)
        with pytest.raises(KeyError):
            pred.run({})

    def test_weights_isolated_from_scope(self, tmp_path):
        """Predictor must not be corrupted by later global-scope writes."""
        prefix, xs, ref = _save_lenet(tmp_path)
        pred = Predictor(prefix)
        from paddle_tpu.static_.program import global_scope

        for n in pred._weight_names:
            global_scope().set(n, pt.zeros([1])._data)
        out, = pred.run({"x": xs})
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


# -- decode library ---------------------------------------------------------


def _toy_step(transitions):
    """Deterministic stepwise model over a tiny Markov chain: logits
    depend only on the previous token. transitions: (V, V) numpy."""
    T = np.asarray(transitions, np.float32)

    def step_fn(tok, state, t):
        logits = ops.to_tensor(T)[ops.reshape(tok, [-1])]
        return logits, state

    return step_fn


class TestBeamSearch:
    def test_beam_equals_greedy_when_beam1(self):
        rng = np.random.RandomState(0)
        T = rng.randn(6, 6).astype("float32")
        step = _toy_step(T)
        g_toks, _ = greedy_search(step, None, 2, bos_id=0, eos_id=5,
                                  max_len=6)
        b_toks, _ = beam_search(step, None, 2, bos_id=0, eos_id=5,
                                beam_size=1, max_len=6, length_penalty=0.0)
        np.testing.assert_array_equal(np.asarray(g_toks.numpy()),
                                      np.asarray(b_toks.numpy()))

    def test_beam_beats_greedy(self):
        """Classic trap: the greedy first step leads into a low-probability
        continuation; beam search must recover the higher-scoring path."""
        # vocab: 0=bos 1 2 3=eos
        # from bos: token1 slightly better than token2 (greedy takes 1)
        # from 1: forced low-prob spread; from 2: near-certain eos
        T = np.array([
            [-9., 0.0, -0.1, -9.],     # bos -> prefers 1
            [-9., -2., -2., -2.],      # after 1: everything bad (log 1/3ish)
            [-9., -9., -9., 0.0],      # after 2: eos certain
            [-9., -9., -9., 0.0],      # eos absorbing
        ], "float32")
        step = _toy_step(T)
        g_toks, _ = greedy_search(step, None, 1, bos_id=0, eos_id=3,
                                  max_len=4)
        b_toks, b_scores = beam_search(step, None, 1, bos_id=0, eos_id=3,
                                       beam_size=3, max_len=4,
                                       length_penalty=0.0)
        g = np.asarray(g_toks.numpy())[0]
        b = np.asarray(b_toks.numpy())[0]
        assert g[1] == 1, g          # greedy falls into the trap
        assert b[1] == 2 and b[2] == 3, b  # beam takes 2 -> eos

    def test_beam_scores_sorted_and_finite(self):
        rng = np.random.RandomState(1)
        T = rng.randn(8, 8).astype("float32")
        toks, scores = beam_search(_toy_step(T), None, 3, bos_id=0,
                                   eos_id=7, beam_size=4, max_len=7,
                                   return_all=True)
        s = np.asarray(scores.numpy())
        assert s.shape == (3, 4)
        assert np.isfinite(s[:, 0]).all()
        assert (np.diff(s, axis=1) <= 1e-5).all()  # sorted best-first

    def test_state_gather(self):
        """Beam reordering must permute state leaves on the merged dim."""
        state = {"a": pt.to_tensor(np.arange(8, dtype=np.float32)
                                   .reshape(4, 2))}
        # B=2, K=2; swap beams of batch 0, keep batch 1
        idx = pt.to_tensor(np.array([[1, 0], [0, 1]], np.int64))
        out = gather_beams(state, idx, 2, 2)
        np.testing.assert_array_equal(
            np.asarray(out["a"].numpy()),
            np.array([[2, 3], [0, 1], [4, 5], [6, 7]], np.float32))

    def test_tile_beam(self):
        x = pt.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
        t = tile_beam(x, 3)
        assert list(t.shape) == [6, 2]
        np.testing.assert_array_equal(np.asarray(t.numpy())[:3],
                                      np.tile([[1., 2.]], (3, 1)))


class TestDynamicDecode:
    def test_matches_functional_beam(self):
        rng = np.random.RandomState(2)
        T = rng.randn(6, 6).astype("float32")
        step = _toy_step(T)
        dec = BeamSearchDecoder(step, start_token=0, end_token=5,
                                beam_size=3, length_penalty=0.0)
        (seqs, scores), _ = dynamic_decode(dec, inits=(2, None),
                                           max_step_num=5)
        f_toks, f_scores = beam_search(step, None, 2, bos_id=0, eos_id=5,
                                       beam_size=3, max_len=6,
                                       length_penalty=0.0, return_all=True)
        np.testing.assert_allclose(np.asarray(scores.numpy()),
                                   np.asarray(f_scores.numpy()), rtol=1e-5)
        s = np.asarray(seqs.numpy())
        f = np.asarray(f_toks.numpy())
        if s.shape[-1] < f.shape[-1]:  # dynamic_decode stopped early;
            pad = np.full(s.shape[:-1] + (f.shape[-1] - s.shape[-1],), 5,
                          s.dtype)  # post-finish positions are all eos
            s = np.concatenate([s, pad], axis=-1)
        np.testing.assert_array_equal(s, f)


class TestWMTBeam:
    def test_wmt_beam_decode_runs(self):
        from paddle_tpu.models.nlp.transformer import WMTTransformer

        pt.seed(0)
        model = WMTTransformer(src_vocab=32, tgt_vocab=32, d_model=16,
                               nhead=2, num_layers=1, dim_feedforward=32,
                               max_len=10, dropout=0.0)
        model.eval()
        src = np.random.RandomState(0).randint(2, 32, (2, 5)).astype("int64")
        toks, scores = model.beam_search_decode(pt.to_tensor(src),
                                                beam_size=3, max_len=8)
        t = np.asarray(toks.numpy())
        assert t.shape == (2, 8)
        assert (t[:, 0] == model.bos_id).all()
        assert np.isfinite(np.asarray(scores.numpy())).all()

    def test_wmt_beam1_matches_greedy(self):
        from paddle_tpu.models.nlp.transformer import WMTTransformer

        pt.seed(0)
        model = WMTTransformer(src_vocab=32, tgt_vocab=32, d_model=16,
                               nhead=2, num_layers=1, dim_feedforward=32,
                               max_len=10, dropout=0.0)
        model.eval()
        src = np.random.RandomState(1).randint(2, 32, (2, 5)).astype("int64")
        g = np.asarray(model.greedy_decode(pt.to_tensor(src),
                                           max_len=8).numpy())
        b, _ = model.beam_search_decode(pt.to_tensor(src), beam_size=1,
                                       max_len=8, length_penalty=0.0)
        b = np.asarray(b.numpy())
        # greedy pads nothing after eos; compare up to first eos per row
        for gi, bi in zip(g, b):
            L = min(len(gi), len(bi))
            stop = L
            for j in range(L):
                if gi[j] == model.eos_id:
                    stop = j + 1
                    break
            np.testing.assert_array_equal(gi[:stop], bi[:stop])
