"""Elastic gang supervision + async checkpointing (ISSUE 8 tentpole).

In-process units for the worker-side primitives (heartbeats, graceful
shutdown, jittered/deadlined retries, async saves) plus small REAL
subprocess gangs under :class:`GangSupervisor` — crash propagation,
budget-free preemption, the heartbeat watchdog, and budget exhaustion
are all exercised with live processes, not mocks. The full 3-fault
training drill (bitwise trajectory vs an unfaulted run) rides tier-1
separately via ``tools/elastic_run.py --self-test`` in test_tooling.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
import paddle_tpu.nn as nn
from paddle_tpu.framework.io import (load_checkpoint, save_checkpoint,
                                     wait_checkpoints)
from paddle_tpu.resilience import (ElasticBudgetError, GangSupervisor,
                                   GracefulShutdown, Heartbeat,
                                   ProgramStateAdapter, RecoveryPolicy,
                                   SimulatedCrashError, TransientError,
                                   inject, normalize_exit_code, retry_call)
from paddle_tpu.resilience.elastic import PREEMPTED_EXIT_CODE

pytestmark = pytest.mark.chaos

FAST = dict(poll_interval_s=0.01, term_grace_s=1.0, backoff_s=0.0,
            jitter=0.0)


def _py(code):
    return [sys.executable, "-c", code]


# -- exit codes --------------------------------------------------------------


def test_normalize_exit_code():
    assert normalize_exit_code(0) == 0
    assert normalize_exit_code(7) == 7
    assert normalize_exit_code(-9) == 137   # SIGKILL
    assert normalize_exit_code(-15) == 143  # SIGTERM
    assert normalize_exit_code(None) is None


# -- policy: jitter + deadline (satellite) -----------------------------------


def test_backoff_jitter_is_seeded_and_bounded():
    p = RecoveryPolicy(backoff=1.0, backoff_factor=1.0, max_backoff=10.0,
                       jitter=0.5, jitter_seed=42)
    u = np.random.RandomState(42).uniform(-1.0, 1.0)
    assert p.backoff_for(0) == pytest.approx(1.0 * (1.0 + 0.5 * u))
    # deterministic: same (seed, attempt) -> same delay, and a replay
    # of the whole schedule is identical
    assert [p.backoff_for(i) for i in range(4)] == \
        [p.backoff_for(i) for i in range(4)]
    for i in range(4):
        assert 0.5 <= p.backoff_for(i) <= 1.5
    # different seeds de-synchronize (ranks seeded differently must not
    # stampede in lockstep)
    q = RecoveryPolicy(backoff=1.0, backoff_factor=1.0, max_backoff=10.0,
                       jitter=0.5, jitter_seed=43)
    assert q.backoff_for(0) != p.backoff_for(0)


def test_backoff_jitter_applies_after_the_cap():
    # clamping jittered delays back under max_backoff would re-sync
    # exactly the long (capped) retries; the spread must survive the cap
    p = RecoveryPolicy(backoff=100.0, max_backoff=1.0, jitter=0.5,
                       jitter_seed=0)
    u = np.random.RandomState(0).uniform(-1.0, 1.0)
    assert p.backoff_for(0) == pytest.approx(1.0 * (1.0 + 0.5 * u))


def test_zero_jitter_keeps_exact_backoff():
    p = RecoveryPolicy(backoff=0.5, backoff_factor=2.0, max_backoff=2.0)
    assert [p.backoff_for(i) for i in range(4)] == [0.5, 1.0, 2.0, 2.0]


def test_jitter_fraction_validated():
    with pytest.raises(ValueError, match="jitter"):
        RecoveryPolicy(jitter=1.5)


def test_retry_call_deadline_stops_with_budget_left():
    clock = [0.0]
    p = RecoveryPolicy(max_retries=5, backoff=1.0, backoff_factor=1.0,
                       max_backoff=1.0,
                       sleep=lambda s: clock.__setitem__(0, clock[0] + s))
    calls = [0]

    def fn():
        calls[0] += 1
        raise TransientError("still down")

    with pytest.raises(TransientError):
        retry_call(fn, p, deadline_s=2.5, clock=lambda: clock[0])
    # attempts 1..2 retried (elapsed+delay <= 2.5); the 3rd attempt's
    # next sleep would land at 3.0 > 2.5 -> raise with 3 retries of
    # budget still unspent
    assert calls[0] == 3


def test_retry_call_without_deadline_spends_full_budget():
    p = RecoveryPolicy(max_retries=2, backoff=0.0, sleep=lambda s: None)
    calls = [0]

    def fn():
        calls[0] += 1
        raise TransientError("down")

    with pytest.raises(TransientError):
        retry_call(fn, p)
    assert calls[0] == 3


# -- worker-side primitives --------------------------------------------------


def test_heartbeat_noop_without_path_and_beats_with_one(tmp_path):
    hb = Heartbeat(None)
    hb.beat(step=1)  # must be safe to call unconditionally
    assert hb.beats == 0

    path = str(tmp_path / "hb.json")
    hb = Heartbeat(path)
    hb.beat(step=7)
    with open(path) as f:
        rec = json.load(f)
    assert rec["pid"] == os.getpid() and rec["step"] == 7
    assert hb.beats == 1
    before = os.path.getmtime(path)
    time.sleep(0.02)
    hb.beat(step=8)
    assert os.path.getmtime(path) >= before  # mtime is the signal


def test_heartbeat_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_HEARTBEAT_FILE", raising=False)
    assert Heartbeat.from_env().path is None
    monkeypatch.setenv("PADDLE_TPU_HEARTBEAT_FILE",
                       str(tmp_path / "hb.json"))
    hb = Heartbeat.from_env()
    hb.beat()
    assert os.path.exists(hb.path)


def test_graceful_shutdown_catches_sigterm_and_restores():
    prev = signal.getsignal(signal.SIGTERM)
    with GracefulShutdown(signals=(signal.SIGTERM,)) as sh:
        assert not sh.requested
        os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
        assert sh.requested and sh.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is prev
    with pytest.raises(SystemExit) as ei:
        sh.exit_preempted()
    assert ei.value.code == PREEMPTED_EXIT_CODE == 75


# -- async checkpointing -----------------------------------------------------


def _linear(seed=0):
    pt.seed(seed)
    return nn.Linear(4, 2)


def test_async_save_matches_sync_bitwise(tmp_path):
    m = _linear()
    d_sync, d_async = str(tmp_path / "s"), str(tmp_path / "a")
    save_checkpoint(d_sync, 3, model=m)
    h = save_checkpoint(d_async, 3, model=m, async_=True)
    assert h.result(timeout=30.0) == os.path.join(d_async, "ckpt_3")
    assert h.done()
    ms, ma = _linear(1), _linear(2)
    assert load_checkpoint(d_sync, model=ms) == 3
    assert load_checkpoint(d_async, model=ma) == 3
    assert np.array_equal(np.asarray(ms.weight._data),
                          np.asarray(ma.weight._data))


def test_async_save_never_blocks_the_step_loop(tmp_path):
    """THE acceptance assertion: with the serialized write stalled 0.6s
    (ckpt_slow), ``save_checkpoint(async_=True)`` must return in a
    fraction of that — the write happens on the writer thread — and the
    checkpoint must only be published once the writer completed."""
    m = _linear()
    d = str(tmp_path / "ck")
    with inject.chaos("ckpt_slow", seconds=0.6):
        t0 = time.perf_counter()
        h = save_checkpoint(d, 1, model=m, async_=True)
        step_path_s = time.perf_counter() - t0
        assert not os.path.exists(os.path.join(d, "ckpt_1"))
        # the step loop keeps running while the writer stalls; a load
        # issued NOW must neither sweep the live writer's tmp dir nor
        # see a half-written checkpoint
        assert load_checkpoint(d, model=_linear(1)) is None
        # the writer thread creates the tmp dir on its own schedule —
        # give it its (stalled, unpublished) moment rather than racing
        # its first makedirs; the 0.6s stall guarantees it is still
        # unpublished when the tmp dir appears
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            if os.path.isdir(d) and any(
                    f.startswith(".tmp_ckpt_1") for f in os.listdir(d)):
                break
            time.sleep(0.005)
        assert any(f.startswith(".tmp_ckpt_1") for f in os.listdir(d))
        assert not os.path.exists(os.path.join(d, "ckpt_1"))
        h.result(timeout=30.0)
    assert step_path_s < 0.3, \
        f"async save held the step path {step_path_s:.3f}s of a 0.6s write"
    assert os.path.isdir(os.path.join(d, "ckpt_1"))
    assert load_checkpoint(d, model=_linear(1)) == 1


def test_save_barriers_on_previous_inflight_save(tmp_path):
    m = _linear()
    d = str(tmp_path / "ck")
    with inject.chaos("ckpt_slow", seconds=0.4):
        h1 = save_checkpoint(d, 1, model=m, async_=True)
        # the next save (sync or async) first barriers on h1: rotation
        # and publish stay strictly ordered
        save_checkpoint(d, 2, model=m)
    assert h1.done() and h1.error is None
    names = sorted(f for f in os.listdir(d) if f.startswith("ckpt_"))
    assert names == ["ckpt_1", "ckpt_2"]


def test_async_writer_failure_surfaces_once_then_clears(tmp_path):
    m = _linear()
    d = str(tmp_path / "ck")
    save_checkpoint(d, 1, model=m)  # the intact fallback target
    with inject.chaos("ckpt_crash"):
        h = save_checkpoint(d, 2, model=m, async_=True)
        with pytest.raises(SimulatedCrashError):
            wait_checkpoints(timeout=30.0)
    assert h.error is not None
    assert wait_checkpoints() is None  # settled: raised once, cleared
    # the dead writer published nothing (not even a corrupt dir the
    # loader would have to skip): step 1 IS the newest intact checkpoint
    m2 = _linear(1)
    assert load_checkpoint(d, model=m2) == 1
    assert np.array_equal(np.asarray(m.weight._data),
                          np.asarray(m2.weight._data))


def test_wait_checkpoints_idle_returns_none():
    assert wait_checkpoints() is None


def test_writer_killed_mid_save_leaves_only_tmp_orphan(tmp_path):
    """A process that dies WHILE the async writer is serializing (the
    ckpt_slow stall window) must leave only a ``.tmp_ckpt_*`` orphan:
    publish never ran, the previous checkpoint stays the newest intact
    one, and the stale orphan is swept on the next load."""
    d = str(tmp_path / "ck")
    script = f"""
import os, sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.framework.io import save_checkpoint
from paddle_tpu.resilience import inject

pt.seed(0)
m = nn.Linear(4, 2)
save_checkpoint({d!r}, 1, model=m)
inject.install_from_env("ckpt_slow:seconds=120")
h = save_checkpoint({d!r}, 2, model=m, async_=True)
tmp = os.path.join({d!r}, ".tmp_ckpt_2")
deadline = time.monotonic() + 30
while not os.path.exists(os.path.join(tmp, "manifest.json")):
    if time.monotonic() > deadline:
        sys.exit(99)
    time.sleep(0.01)
os._exit(17)  # machine loss: writer dies inside the stall, pre-publish
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PADDLE_TPU_RUN_DIR="")
    r = subprocess.run(_py(script), env=env, capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 17, (r.returncode, r.stdout, r.stderr)
    names = sorted(os.listdir(d))
    assert "ckpt_1" in names and "ckpt_2" not in names, names
    assert ".tmp_ckpt_2" in names, names
    # age the orphan past the concurrent-saver grace period: the next
    # load sweeps it and resumes from the newest INTACT checkpoint
    t = time.time() - 3600
    orphan = os.path.join(d, ".tmp_ckpt_2")
    for p in [orphan] + [os.path.join(orphan, f)
                         for f in os.listdir(orphan)]:
        os.utime(p, (t, t))
    m2 = _linear(1)
    assert load_checkpoint(d, model=m2) == 1
    assert not any(f.startswith(".tmp_ckpt_")
                   for f in os.listdir(d))


# -- ProgramStateAdapter (static path) ---------------------------------------


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def test_program_state_adapter_roundtrip(static_mode, tmp_path):
    from paddle_tpu.static_.program import global_scope

    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[4, 4])
        fluid.layers.fc(x, size=2)
    exe = fluid.Executor()
    exe.run(startup)
    adapter = ProgramStateAdapter(prog)
    state = adapter.state_dict()
    assert state and all(isinstance(v, np.ndarray)
                         for v in state.values())
    d = str(tmp_path / "ck")
    save_checkpoint(d, 5, model=adapter)
    for k, v in state.items():  # "the machine died": zero everything
        global_scope().set(k, np.zeros_like(v))
    assert load_checkpoint(d, model=adapter) == 5
    state2 = adapter.state_dict()
    assert set(state2) == set(state)
    for k in state:
        assert np.array_equal(state[k], state2[k]), k


def test_program_state_adapter_rejects_unrun_startup(static_mode):
    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[4, 4])
        fluid.layers.fc(x, size=2)
    from paddle_tpu.static_.program import Scope

    adapter = ProgramStateAdapter(prog, scope=Scope())  # never ran startup
    with pytest.raises(ValueError, match="startup"):
        adapter.state_dict()


# -- the gang supervisor (real subprocesses) ---------------------------------


def test_supervisor_clean_gang_returns_zero():
    sup = GangSupervisor(_py("import sys; sys.exit(0)"), nprocs=2, **FAST)
    assert sup.run() == 0
    assert sup.state["attempts"] == [{"kind": "ok"}]
    assert sup.state["restarts"] == 0 and sup.state["preemptions"] == 0
    assert not os.path.exists(sup.heartbeat_dir)  # own tmp dir cleaned


def test_supervisor_budget_exhaustion_is_a_clean_error():
    sup = GangSupervisor(_py("import sys; sys.exit(7)"), nprocs=1,
                         max_restarts=1, **FAST)
    with pytest.raises(ElasticBudgetError) as ei:
        sup.run()
    hist = ei.value.history
    assert [a["kind"] for a in hist] == ["crash", "crash"]
    assert all(a["code"] == 7 for a in hist)
    assert sup.state["exit_code"] == 7  # the worker's EXACT code
    assert sup.state["restarts"] == 1   # one relaunch was granted


def test_supervisor_normalizes_signal_deaths():
    sup = GangSupervisor(
        _py("import os, signal; os.kill(os.getpid(), signal.SIGABRT)"),
        nprocs=1, max_restarts=0, **FAST)
    with pytest.raises(ElasticBudgetError):
        sup.run()
    assert sup.state["attempts"][0]["code"] == 134  # 128 + SIGABRT


def test_supervisor_relaunches_crash_then_succeeds():
    def cmd(rank, attempt):
        return _py(f"import sys; sys.exit({9 if attempt == 0 else 0})")

    sup = GangSupervisor(cmd, nprocs=2, max_restarts=3, **FAST)
    assert sup.run() == 0
    assert [a["kind"] for a in sup.state["attempts"]] == ["crash", "ok"]
    assert sup.state["attempts"][0]["code"] == 9
    assert sup.state["restarts"] == 1


def test_supervisor_preemption_is_budget_free():
    def cmd(rank, attempt):
        code = PREEMPTED_EXIT_CODE if attempt == 0 else 0
        return _py(f"import sys; sys.exit({code})")

    # max_restarts=0: any budget-consuming failure would raise
    sup = GangSupervisor(cmd, nprocs=2, max_restarts=0, **FAST)
    assert sup.run() == 0
    assert [a["kind"] for a in sup.state["attempts"]] == ["preempt", "ok"]
    assert sup.state["preemptions"] == 1 and sup.state["restarts"] == 0


def test_supervisor_watchdog_kills_hung_worker():
    hang = ("import os, time\n"
            "open(os.environ['PADDLE_TPU_HEARTBEAT_FILE'], 'w')"
            ".write('{}')\n"
            "time.sleep(120)\n")

    def cmd(rank, attempt):
        return _py(hang if attempt == 0 else "import sys; sys.exit(0)")

    sup = GangSupervisor(cmd, nprocs=1, max_restarts=1,
                         hang_timeout_s=0.3, **FAST)
    t0 = time.monotonic()
    assert sup.run() == 0
    assert time.monotonic() - t0 < 30  # detected, never waited out 120s
    assert [a["kind"] for a in sup.state["attempts"]] == ["hang", "ok"]
    assert sup.state["attempts"][0]["code"] == 137  # SIGKILLed
    assert sup.state["watchdog_kills"] == 1
    assert sup.state["restarts"] == 1  # a hang consumes the budget


def test_supervisor_startup_timeout_catches_never_beating_worker():
    def cmd(rank, attempt):
        return _py("import time; time.sleep(120)" if attempt == 0
                   else "import sys; sys.exit(0)")

    sup = GangSupervisor(cmd, nprocs=1, max_restarts=1,
                         hang_timeout_s=60.0, startup_timeout_s=0.3,
                         **FAST)
    assert sup.run() == 0
    assert [a["kind"] for a in sup.state["attempts"]] == ["hang", "ok"]


def test_supervisor_teardown_leaves_no_orphans():
    marker_dir = tempfile.mkdtemp(prefix="pt_orphan_")
    pid_file = os.path.join(marker_dir, "pid_{rank}")
    survivor = (f"import os, time\n"
                f"open({pid_file!r}.format("
                f"rank=os.environ['PADDLE_TRAINER_ID']), 'w')"
                f".write(str(os.getpid()))\n"
                f"time.sleep(120)\n")

    def cmd(rank, attempt):
        if attempt > 0:
            return _py("import sys; sys.exit(0)")
        if rank == 0:
            return _py("import sys, time; time.sleep(0.3); sys.exit(5)")
        return _py(survivor)

    sup = GangSupervisor(cmd, nprocs=2, max_restarts=1, **FAST)
    assert sup.run() == 0
    # the crash of rank 0 must have torn rank 1 down, not orphaned it
    with open(pid_file.format(rank=1)) as f:
        pid = int(f.read())
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        os.kill(pid, signal.SIGKILL)
        raise AssertionError(f"survivor pid {pid} was orphaned")
    import shutil

    shutil.rmtree(marker_dir, ignore_errors=True)


def test_supervisor_backoff_is_seeded_and_jittered(tmp_path):
    sup = GangSupervisor(["true"], seed=3, backoff_s=1.0,
                         backoff_factor=2.0, max_backoff_s=8.0,
                         jitter=0.25, heartbeat_dir=str(tmp_path / "a"))
    a = [sup._backoff(i) for i in range(4)]
    b = [sup._backoff(i) for i in range(4)]
    assert a == b  # same seed -> same drill, replayable
    for i, v in enumerate(a):
        base = min(1.0 * 2.0 ** i, 8.0)
        assert base * 0.75 <= v <= base * 1.25
    other = GangSupervisor(["true"], seed=4, backoff_s=1.0,
                           backoff_factor=2.0, max_backoff_s=8.0,
                           jitter=0.25, heartbeat_dir=str(tmp_path / "b"))
    assert [other._backoff(i) for i in range(4)] != a


# -- dist.launch failure handling (satellite) --------------------------------


def test_wait_gang_terminates_survivors_and_keeps_exact_code():
    from paddle_tpu.dist.launch import _wait_gang

    bad = subprocess.Popen(_py("import sys, time; time.sleep(0.2); "
                               "sys.exit(3)"))
    survivor = subprocess.Popen(_py("import time; time.sleep(120)"))
    t0 = time.monotonic()
    rc = _wait_gang([(bad, None), (survivor, None)])
    assert rc == 3  # the first failure's EXACT code, not an OR-collapse
    assert time.monotonic() - t0 < 30
    assert survivor.wait(timeout=10) is not None  # terminated, reaped


def test_wait_gang_normalizes_signal_death():
    from paddle_tpu.dist.launch import _wait_gang

    p = subprocess.Popen(_py("import os, signal; "
                             "os.kill(os.getpid(), signal.SIGKILL)"))
    assert _wait_gang([(p, None)]) == 137


def test_wait_gang_all_zero():
    from paddle_tpu.dist.launch import _wait_gang

    procs = [(subprocess.Popen(_py("import sys; sys.exit(0)")), None)
             for _ in range(2)]
    assert _wait_gang(procs) == 0


def test_launch_elastic_smoke(tmp_path):
    """--elastic end-to-end through dist.launch: a worker that preempts
    itself once (exit 75) then completes; the supervisor absorbs it
    budget-free."""
    from paddle_tpu.dist import launch as L

    script = tmp_path / "w.py"
    script.write_text(
        "import os, sys\n"
        "m = os.path.join(os.path.dirname(__file__), "
        "'seen_' + os.environ.get('PADDLE_TPU_ELASTIC_ATTEMPT', '0'))\n"
        "open(m, 'w').close()\n"
        "sys.exit(75 if os.environ['PADDLE_TPU_ELASTIC_ATTEMPT'] == '0' "
        "else 0)\n")
    args = L._parse_args(["--nproc_per_node", "1", "--elastic",
                          "--max_restarts", "0", str(script)])
    assert L.launch(args) == 0
    assert (tmp_path / "seen_0").exists()
    assert (tmp_path / "seen_1").exists()


# -- worker-side chaos hook --------------------------------------------------


def test_fire_step_chaos_rank_and_step_gating():
    from paddle_tpu.resilience.elastic import fire_step_chaos

    # inactive chaos: the hook is a no-op (one truthiness test)
    fire_step_chaos(step=1, rank=0)
    # rank-gated preempt_signal must only hit the targeted rank, and
    # only at its step
    with inject.chaos("preempt_signal", at_step=5, rank=1):
        with GracefulShutdown(signals=(signal.SIGTERM,)) as sh:
            fire_step_chaos(step=5, rank=0)   # wrong rank
            fire_step_chaos(step=4, rank=1)   # wrong step
            assert not sh.requested
            fire_step_chaos(step=5, rank=1)   # exact hit
            assert sh.requested
            sh.requested = False
            fire_step_chaos(step=5, rank=1)   # times=1: never re-fires
            assert not sh.requested


def test_resume_latency_histogram_covers_minutes():
    """Gang resumes live in the seconds-to-minutes band; the histogram
    must resolve there instead of clamping past 30s into overflow."""
    from paddle_tpu.obs import metrics as m

    h = m.histogram("resilience.resume_ms")
    assert h.buckets == m.WIDE_MS_BUCKETS
    assert h.buckets[-1] == 600000.0
    assert m.WIDE_MS_BUCKETS[:len(m.DEFAULT_MS_BUCKETS)] == \
        m.DEFAULT_MS_BUCKETS


def test_worker_hang_injector_bounded_seconds():
    from paddle_tpu.resilience.elastic import fire_step_chaos

    with inject.chaos("worker_hang", seconds=0.2):
        t0 = time.perf_counter()
        fire_step_chaos(step=1, rank=0)
        assert time.perf_counter() - t0 >= 0.2
