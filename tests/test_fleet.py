"""paddle_tpu.fleet auto-parallel (ISSUE 10): mesh-shape sweep, planner
cost-model accountability, Executor plan-axis integration, gradcomm
composition, journal plan events, and the old-API shims.

Runs on the 8-device virtual CPU mesh from conftest. Loss-parity
tolerances follow the test_static_dp / test_gradcomm matmul precedent
(fp32 reassociation across layouts: rtol 1e-4 / atol 1e-5)."""
import os
import tempfile

import numpy as np
import pytest

import jax

import paddle_tpu as pt
import paddle_tpu.fluid as fluid
from paddle_tpu import fleet
from paddle_tpu import distributed as dist


def _require8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


@pytest.fixture(autouse=True)
def _mesh_reset():
    yield
    dist.set_mesh(None)


def _build_mlp(hidden=36, batch=16, lr=0.1):
    """8 -> hidden -> 1 regression MLP; hidden=36 divides 2 and 4 but
    not 8, so a model axis of 8 is infeasible and 2x4-style layouts
    stay interesting."""
    pt.seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[batch, 8])
        y = fluid.data(name="y", shape=[batch, 1])
        h = fluid.layers.fc(x, size=hidden, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return prog, startup, loss


def _param_names(prog):
    """(w1, b1, w2) of the demo MLP by program structure — unique_name
    suffixes advance across tests, so never hardcode them."""
    linears = [op for op in prog.global_block.ops if op.type == "linear"]
    return (linears[0].input_names[1], linears[0].input_names[2],
            linears[1].input_names[1])


def _train(exe, prog_like, loss, steps=4, batch=16):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(steps):
        xb = rng.randn(batch, 8).astype(np.float32)
        yb = rng.randn(batch, 1).astype(np.float32)
        (lv,) = exe.run(prog_like, feed={"x": xb, "y": yb},
                        fetch_list=[loss])
        out.append(float(np.asarray(lv)))
    return out


class TestMeshShapes:
    def test_parse_and_validate(self):
        assert fleet.parse_mesh_shape("2x4") == (2, 4)
        assert fleet.parse_mesh_shape(8) == (8,)
        assert fleet.parse_mesh_shape([2, 2, 2]) == (2, 2, 2)
        with pytest.raises(ValueError):
            fleet.parse_mesh_shape("nope")
        with pytest.raises(ValueError):
            fleet.validate_mesh_shape((3, 3), n_devices=8)
        assert fleet.validate_mesh_shape((2, 4), n_devices=8) == (2, 4)

    def test_canonical_axes_merge_and_order(self):
        assert fleet.canonical_axes((2, 2, 2),
                                    ("data", "data", "model")) == \
            {"data": 4, "model": 2}
        assert fleet.canonical_axes((1, 8), ("model", "data")) == \
            {"data": 8}
        # canonical axis order is fixed regardless of role-tuple order
        m = fleet.build_mesh({"model": 2, "data": 4},
                             devices=jax.devices())
        assert m.axis_names == ("data", "model")

    def test_candidates_respect_shape_grouping(self):
        # 1x8 cannot express dp2 x tp4 — the shape constrains the space
        one8 = {tuple(sorted(a.items()))
                for _r, a in fleet.candidate_assignments((1, 8))}
        assert one8 == {(("data", 8),), (("model", 8),)}
        cube = {tuple(sorted(a.items()))
                for _r, a in fleet.candidate_assignments((2, 2, 2))}
        assert (("data", 2), ("model", 4)) in cube
        assert (("data", 4), ("model", 2)) in cube


class TestPlanner:
    def test_megatron_pairing_and_bias(self, static_mode):
        prog, _startup, _loss = _build_mlp()
        w1, b1, w2 = _param_names(prog)
        plan = fleet.plan_program(prog, (2, 4), roles=("data", "model"))
        assert plan.param_specs[w1] == (None, "model")
        assert plan.param_specs[b1] == ("model",)
        assert plan.param_specs[w2] == ("model", None)
        # the row bias adds after the partial-sum all-reduce: replicated
        assert len(plan.param_specs) == 3
        assert plan.feed_specs["x"] == ("data",)

    def test_opt_state_follows_param(self, static_mode):
        prog, _s, _l = _build_mlp()
        w1, _b1, _w2 = _param_names(prog)
        plan = fleet.plan_program(prog, (2, 4), roles=("data", "model"))
        assert plan.spec_for(f"{w1}@OPT@moment1",
                             (8, 36)) == (None, "model")
        # a scalar slot can't wear the param's 2-D spec: replicate
        assert plan.spec_for(f"{w1}@OPT@beta1_pow", ()) == ()

    def test_indivisible_batch_infeasible(self, static_mode):
        prog, _s, _l = _build_mlp(batch=6)  # 6 % 8 != 0, 6 % 4 != 0
        with pytest.raises(ValueError, match="no feasible layout"):
            fleet.plan_program(prog, (1, 8), roles=("data", "data"))

    def test_pure_dp_required_for_comm_options(self, static_mode):
        from paddle_tpu.dist.gradcomm import CommOptions

        prog, _s, _l = _build_mlp()
        with pytest.raises(ValueError, match="pure"):
            fleet.auto_parallel(prog, (2, 4), roles=("data", "model"),
                                comm_options=CommOptions(), verify=False)


class TestMeshSweep:
    """ISSUE-10 acceptance: the same model auto-planned on 1x8, 2x4,
    and 2x2x2 trains to identical loss, with shard_report-verified
    collective mixes per shape and predicted wire bytes within 10% of
    the HLO-measured CollectiveProfile."""

    SHAPES = ((1, 8), (2, 4), (2, 2, 2))

    def test_sweep_identical_loss_and_verified_mix(self, static_mode):
        _require8()
        exe = fluid.Executor()
        prog0, startup0, loss0 = _build_mlp()
        exe.run(startup0)
        base = _train(exe, prog0, loss0)

        for shape in self.SHAPES:
            prog, startup, loss = _build_mlp()
            exe.run(startup)
            cp = fleet.auto_parallel(prog, shape, executor=exe)
            plan = cp._plan
            # predicted wire bytes vs the compiled HLO's profile
            assert plan.measured_wire_bytes is not None, shape
            assert plan.mismatch is not None and plan.mismatch <= 0.10, \
                (shape, plan.predicted_wire_bytes,
                 plan.measured_wire_bytes)
            # the collective mix matches the plan's axes: every byte is
            # attributed to a planned mesh axis (no stray '?' traffic)
            meas_axes = set((plan.measured.get("by_axis") or {}))
            assert meas_axes <= set(plan.axes), (shape, plan.measured)
            got = _train(exe, cp, loss)
            np.testing.assert_allclose(
                got, base, rtol=1e-4, atol=1e-5,
                err_msg=f"auto-parallel on {shape} diverged from the "
                        "single-device baseline")

    def test_shapes_choose_expected_layouts(self, static_mode):
        _require8()
        prog, _s, _l = _build_mlp()
        # hidden 36: model axis of 8 infeasible -> 1x8 must fall back
        # to pure DP; 2x4 and 2x2x2 can (and should) use tp
        assert fleet.plan_program(prog, (1, 8)).axes == {"data": 8}
        assert "model" in fleet.plan_program(prog, (2, 4)).axes
        assert "model" in fleet.plan_program(prog, (2, 2, 2)).axes


class TestExecutorIntegration:
    def test_plan_is_a_cache_axis(self, static_mode):
        _require8()
        exe = fluid.Executor()
        prog, startup, loss = _build_mlp()
        exe.run(startup)
        cp_dp = fleet.auto_parallel(prog, (1, 8),
                                    roles=("data", "data"), verify=False)
        cp_tp = fleet.auto_parallel(prog, (2, 4),
                                    roles=("data", "model"), verify=False)
        _train(exe, cp_dp, loss, steps=1)
        _train(exe, cp_tp, loss, steps=1)
        plan_keys = [k for k in exe._cache if k.plan is not None]
        assert len(plan_keys) == 2  # two plans, two executables
        assert len({k.plan for k in plan_keys}) == 2

    def test_run_steps_fused_with_plan(self, static_mode):
        _require8()
        exe = fluid.Executor()
        prog, startup, loss = _build_mlp()
        exe.run(startup)
        cp = fleet.auto_parallel(prog, (2, 4), verify=False)
        rng = np.random.RandomState(0)
        feeds = [{"x": rng.randn(16, 8).astype(np.float32),
                  "y": rng.randn(16, 1).astype(np.float32)}
                 for _ in range(3)]
        (stacked,) = exe.run_steps(cp, feeds=feeds, fetch_list=[loss])
        seq = []
        prog2, startup2, loss2 = _build_mlp()
        exe.run(startup2)
        cp2 = fleet.auto_parallel(prog2, (2, 4), verify=False)
        for f in feeds:
            (lv,) = exe.run(cp2, feed=f, fetch_list=[loss2])
            seq.append(float(np.asarray(lv)))
        np.testing.assert_allclose(np.asarray(stacked).ravel(), seq,
                                   rtol=1e-4, atol=1e-5)

    def test_pure_dp_plan_composes_with_gradcomm(self, static_mode):
        _require8()
        from paddle_tpu.dist.gradcomm import CommOptions

        exe = fluid.Executor()
        # implicit-GSPMD DP baseline
        prog0, startup0, loss0 = _build_mlp()
        exe.run(startup0)
        cp0 = fluid.CompiledProgram(prog0).with_data_parallel(
            loss_name=loss0.name)
        base = _train(exe, cp0, loss0)
        # auto-parallel pure-DP plan + explicit bucketed exchange
        prog, startup, loss = _build_mlp()
        exe.run(startup)
        cp = fleet.auto_parallel(
            prog, (1, 8), roles=("data", "data"), verify=False,
            comm_options=CommOptions(bucket_bytes=1 << 20))
        got = _train(exe, cp, loss)
        np.testing.assert_allclose(got, base, rtol=1e-4, atol=1e-5)
        key = [k for k in exe._cache
               if k.plan is not None and k.comm is not None]
        assert key, "plan + comm axes must both ride the cache key"


class TestJournalAndReport:
    def test_plan_event_and_report_line(self, static_mode):
        _require8()
        import importlib.util

        from paddle_tpu.obs import journal as J

        with tempfile.TemporaryDirectory() as d:
            run_dir = os.path.join(d, "run")
            with J.RunJournal(run_dir, compute_flops=False):
                exe = fluid.Executor()
                prog, startup, loss = _build_mlp()
                exe.run(startup)
                cp = fleet.auto_parallel(prog, (2, 4), executor=exe)
                _train(exe, cp, loss, steps=1)
            spec = importlib.util.spec_from_file_location(
                "run_report_for_fleet", os.path.join(
                    os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    "tools", "run_report.py"))
            rr = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(rr)
            run = rr.load_run(run_dir)
            plans = [e for e in run["events"] if e.get("kind") == "plan"]
            assert plans, "no plan event journaled"
            # the probe compile journals an unverified plan event, the
            # verification a verified one, the training compile a third
            # (measured already on the plan): assert on the verified one
            ev = [e for e in plans if e.get("measured_wire_bytes")
                  is not None][0]
            assert ev["axes"] == cp._plan.axes
            assert ev["predicted_wire_bytes"] == \
                cp._plan.predicted_wire_bytes
            assert ev["measured_wire_bytes"] == \
                cp._plan.measured_wire_bytes
            assert ev["mismatch"] is not None and ev["mismatch"] <= 0.10
            psum = rr.plan_summary(run)
            assert psum and psum["plans"] >= 1
            assert "plan" in rr.render_run(run)
            # self-diff carries the mismatch columns, no regression
            rep = rr.diff_runs(run, run)
            assert rep["new_plan_mismatch"] is not None
            assert not rep["plan_regression"]


class TestEagerPath:
    def test_auto_step_matches_hand_built_dp2_tp2(self):
        _require8()
        from paddle_tpu import optim
        from paddle_tpu.models.nlp.gpt import GPT, gpt_tiny, gpt_loss

        cfg = gpt_tiny(dropout=0.0)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype("int32")
        labels = np.roll(ids, -1, 1).astype("int32")

        pt.seed(7)
        model_a = GPT(gpt_tiny(dropout=0.0))
        opt_a = optim.AdamW(parameters=model_a.parameters(),
                            learning_rate=1e-3)
        step_a = fleet.auto_parallel_step(
            model_a, opt_a, gpt_loss, mesh_shape=(2, 2),
            roles=("data", "model"), batch_example=(ids, labels))
        assert step_a.plan.axes == {"data": 2, "model": 2}
        la = [float(np.asarray(step_a(ids, labels)._data))
              for _ in range(2)]

        pt.seed(7)
        model_b = GPT(gpt_tiny(dropout=0.0))
        opt_b = optim.AdamW(parameters=model_b.parameters(),
                            learning_rate=1e-3)
        mesh = dist.init_mesh(
            {"data": 2, "model": 2},
            devices=np.asarray(jax.devices()[:4]).reshape(2, 2))
        step_b = dist.DistributedTrainStep(model_b, opt_b, gpt_loss,
                                           mesh=mesh, batch_axis="data")
        lb = [float(np.asarray(step_b(ids, labels)._data))
              for _ in range(2)]
        np.testing.assert_allclose(la, lb, rtol=1e-4, atol=1e-5)

        pa = step_a.collective_profile()
        pb = step_b.collective_profile()
        assert pa is not None and pb is not None
        # the auto-planned step reproduces the hand-built recipe's
        # collective mix, op for op and byte for byte
        assert pa["counts"] == pb["counts"]
        assert pa["total_bytes"] == pb["total_bytes"]

    def test_pure_tp_plan_replicates_batch(self):
        _require8()
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu import optim

        pt.seed(0)
        col = dist.ColumnParallelLinear(16, 32, gather_output=False)
        row = dist.RowParallelLinear(32, 4, input_is_parallel=True)
        model = nn.Sequential(col, row)
        opt = optim.SGD(0.1, parameters=model.parameters())
        step = fleet.auto_parallel_step(
            model, opt, lambda m, x, y: F.mse_loss(m(x), y),
            mesh_shape=(8,), roles=("model",))
        assert step.plan.axes == {"model": 8}
        x = np.random.randn(6, 16).astype("float32")  # 6 need not split
        y = np.random.randn(6, 4).astype("float32")
        loss = float(np.asarray(step(x, y)._data))
        assert np.isfinite(loss)


class TestOldAPIShims:
    def test_old_surface_preserved(self):
        # the reference incubate/fleet spellings resolve on the package
        assert callable(fleet.init)
        assert callable(fleet.distributed_optimizer)
        assert fleet.worker_num() >= 1
        assert fleet.worker_index() == 0
        assert fleet.is_first_worker()
        strat = fleet.DistributedStrategy()
        assert strat.mp_degree == 1
        # PEP 562 forwarding of the singleton's remaining surface
        fleet.init_worker()
        fleet.stop_worker()
        import importlib

        old = importlib.import_module("paddle_tpu.dist.fleet")
        assert fleet.DistributedStrategy is old.DistributedStrategy
