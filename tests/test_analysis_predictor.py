"""Fluid-era deploy API (ref: pybind/inference_api.cc,
analysis_predictor.cc): the `from paddle.fluid.core import
AnalysisConfig, create_paddle_predictor` + zero-copy protocol every 1.x
deployment script uses, served by the shape-bucketed Predictor.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid


@pytest.fixture(scope="module")
def bundle(tmp_path_factory):
    """Train a tiny static net and save an inference bundle."""
    d = tmp_path_factory.mktemp("deploy")
    prefix = str(d / "model")
    pt.enable_static()
    try:
        main, startup = pt.static.Program(), pt.static.Program()
        with pt.static.program_guard(main, startup):
            x = pt.static.data("x", [4, 8], "float32")
            h = fluid.layers.fc(x, size=16, act="relu")
            out = fluid.layers.fc(h, size=3)
        exe = pt.static.Executor()
        exe.run(startup)
        from paddle_tpu.framework.io import save_inference_model

        save_inference_model(prefix, ["x"], [out], exe, program=main)
        ref = exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                      fetch_list=[out])[0]
    finally:
        pt.disable_static()
    return prefix, np.asarray(ref)


class TestAnalysisPredictor:
    def test_core_import_spelling(self):
        from paddle_tpu.fluid.core import (AnalysisConfig,
                                           create_paddle_predictor)

        assert callable(create_paddle_predictor)
        cfg = AnalysisConfig("/tmp/nope")
        cfg.disable_gpu()
        cfg.switch_use_feed_fetch_ops(False)
        cfg.enable_memory_optim()
        cfg.set_cpu_math_library_num_threads(4)
        assert cfg.cpu_math_library_num_threads() == 4
        assert not cfg.use_gpu()

    def test_zero_copy_protocol(self, bundle):
        prefix, ref = bundle
        from paddle_tpu.fluid.core import (AnalysisConfig,
                                           create_paddle_predictor)

        config = AnalysisConfig(prefix)
        config.disable_gpu()
        config.switch_use_feed_fetch_ops(False)
        predictor = create_paddle_predictor(config)
        names = predictor.get_input_names()
        assert names == ["x"]
        inp = predictor.get_input_tensor(names[0])
        data = np.ones((4, 8), "float32")
        inp.reshape([4, 8])
        inp.copy_from_cpu(data.ravel())
        assert predictor.zero_copy_run()
        out_t = predictor.get_output_tensor(
            predictor.get_output_names()[0])
        out = out_t.copy_to_cpu()
        assert np.allclose(out, ref, atol=1e-5)
        assert out_t.shape() == [4, 3]

    def test_paddle_tensor_run_path(self, bundle):
        prefix, ref = bundle
        from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                          create_paddle_predictor)

        predictor = create_paddle_predictor(AnalysisConfig(prefix))
        t = PaddleTensor(np.ones((4, 8), "float32"), name="x")
        (out,) = predictor.run([t])
        assert isinstance(out, PaddleTensor)
        assert np.allclose(out.as_ndarray(), ref, atol=1e-5)

    def test_dir_and_pdmodel_resolution(self, bundle, tmp_path):
        prefix, ref = bundle
        from paddle_tpu.inference import (AnalysisConfig,
                                          create_paddle_predictor)

        # a directory holding exactly one bundle resolves
        import os

        d = os.path.dirname(prefix)
        p1 = create_paddle_predictor(AnalysisConfig(d))
        assert p1.get_input_names() == ["x"]
        # the .pdmodel path spelling resolves too
        p2 = create_paddle_predictor(
            AnalysisConfig(prefix + ".pdmodel"))
        assert p2.get_input_names() == ["x"]

    def test_errors(self, bundle):
        prefix, _ = bundle
        from paddle_tpu.inference import (AnalysisConfig,
                                          create_paddle_predictor)

        predictor = create_paddle_predictor(AnalysisConfig(prefix))
        with pytest.raises(KeyError):
            predictor.get_input_tensor("bogus")
        with pytest.raises(ValueError):
            predictor.zero_copy_run()  # nothing staged
        with pytest.raises(NotImplementedError):
            AnalysisConfig(prefix).enable_tensorrt_engine()
