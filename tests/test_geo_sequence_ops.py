"""Image geometric ops (grid_sample/affine_grid/pixel (un)shuffle/
space_to_depth) and sequence_* breadth — numpy parity tests
(ref: layers/nn.py:12182 grid_sampler, affine_grid; sequence_lod.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops


class TestAffineGridSample:
    def test_identity_affine_roundtrip(self):
        """Identity theta + grid_sample reproduces the input."""
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 5, 7).astype("float32")
        theta = np.tile(np.array([[[1., 0., 0.], [0., 1., 0.]]],
                                 "float32"), (2, 1, 1))
        grid = ops.affine_grid(pt.to_tensor(theta), [2, 3, 5, 7])
        out = ops.grid_sample(pt.to_tensor(x), grid)
        np.testing.assert_allclose(np.asarray(out.numpy()), x, atol=1e-5)

    def test_horizontal_flip(self):
        rng = np.random.RandomState(1)
        x = rng.randn(1, 1, 4, 6).astype("float32")
        theta = np.array([[[-1., 0., 0.], [0., 1., 0.]]], "float32")
        grid = ops.affine_grid(pt.to_tensor(theta), [1, 1, 4, 6])
        out = np.asarray(ops.grid_sample(pt.to_tensor(x), grid).numpy())
        np.testing.assert_allclose(out, x[:, :, :, ::-1], atol=1e-5)

    def test_translation_zero_padding(self):
        x = np.ones((1, 1, 4, 4), "float32")
        # shift right by a full half-extent: left half samples OOB
        theta = np.array([[[1., 0., -1.], [0., 1., 0.]]], "float32")
        grid = ops.affine_grid(pt.to_tensor(theta), [1, 1, 4, 4])
        out = np.asarray(ops.grid_sample(pt.to_tensor(x), grid,
                                         padding_mode="zeros").numpy())
        assert out[0, 0, 0, 0] == 0.0  # pulled from beyond the left edge
        assert out[0, 0, 0, -1] == 1.0

    def test_border_padding_and_nearest(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        theta = np.array([[[2., 0., 0.], [0., 2., 0.]]], "float32")
        grid = ops.affine_grid(pt.to_tensor(theta), [1, 1, 4, 4])
        out = np.asarray(ops.grid_sample(
            pt.to_tensor(x), grid, mode="nearest",
            padding_mode="border").numpy())
        assert out[0, 0, 0, 0] == 0.0  # clamped to corner
        assert out[0, 0, -1, -1] == 15.0

    def test_grid_sample_grads(self):
        rng = np.random.RandomState(2)
        x = pt.to_tensor(rng.randn(1, 2, 6, 6).astype("float32"))
        x.stop_gradient = False
        theta = pt.to_tensor(np.array(
            [[[0.8, 0.1, 0.05], [-0.1, 0.9, -0.05]]], "float32"))
        theta.stop_gradient = False
        grid = ops.affine_grid(theta, [1, 2, 6, 6])
        out = ops.grid_sample(x, grid)
        out.sum().backward()
        assert np.isfinite(np.asarray(x.grad.numpy())).all()
        assert np.abs(np.asarray(theta.grad.numpy())).sum() > 0


class TestShuffleOps:
    def test_pixel_shuffle_unshuffle_roundtrip(self):
        rng = np.random.RandomState(3)
        x = rng.randn(2, 8, 3, 5).astype("float32")
        up = ops.pixel_shuffle(pt.to_tensor(x), 2)
        assert list(up.shape) == [2, 2, 6, 10]
        back = ops.pixel_unshuffle(up, 2)
        np.testing.assert_allclose(np.asarray(back.numpy()), x, atol=1e-6)

    def test_space_to_depth_blocks(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        out = np.asarray(ops.space_to_depth(pt.to_tensor(x), 2).numpy())
        assert out.shape == (1, 4, 2, 2)
        # channel 0 holds the top-left element of each 2x2 block
        np.testing.assert_allclose(out[0, 0], [[0, 2], [8, 10]])

    def test_space_to_depth_multichannel_layout(self):
        """Reference layout is block-offset-major: out channel
        (by*bs + bx)*C + c — distinct from pixel_unshuffle when C > 1."""
        x = np.arange(8, dtype="float32").reshape(1, 2, 2, 2)
        out = np.asarray(ops.space_to_depth(pt.to_tensor(x), 2).numpy())
        assert out.shape == (1, 8, 1, 1)
        # offset (0,0): channels [x[0,0,0], x[1,0,0]] = [0, 4], then
        # offset (0,1): [1, 5], (1,0): [2, 6], (1,1): [3, 7]
        np.testing.assert_allclose(out[0, :, 0, 0],
                                   [0, 4, 1, 5, 2, 6, 3, 7])


class TestSequenceSteps:
    def test_first_and_last_step(self):
        x = np.arange(24, dtype="float32").reshape(2, 4, 3)
        lens = np.array([2, 4], "int32")
        first = np.asarray(ops.sequence_first_step(
            pt.to_tensor(x), pt.to_tensor(lens)).numpy())
        last = np.asarray(ops.sequence_last_step(
            pt.to_tensor(x), pt.to_tensor(lens)).numpy())
        np.testing.assert_allclose(first, x[:, 0])
        np.testing.assert_allclose(last[0], x[0, 1])
        np.testing.assert_allclose(last[1], x[1, 3])

    def test_sequence_softmax_masked(self):
        x = np.array([[1.0, 2.0, 3.0, 50.0]], "float32")
        lens = np.array([3], "int32")
        out = np.asarray(ops.sequence_softmax(
            pt.to_tensor(x), pt.to_tensor(lens)).numpy())
        assert out[0, 3] == 0.0
        np.testing.assert_allclose(out[0, :3].sum(), 1.0, atol=1e-6)
        want = np.exp(x[0, :3]) / np.exp(x[0, :3]).sum()
        np.testing.assert_allclose(out[0, :3], want, atol=1e-6)


class TestSequenceConv:
    def test_matches_numpy_window(self):
        rng = np.random.RandomState(4)
        B, L, D, F = 2, 5, 3, 4
        x = rng.randn(B, L, D).astype("float32")
        w = rng.randn(3 * D, F).astype("float32")
        lens = np.array([5, 3], "int32")
        out = np.asarray(ops.sequence_conv(
            pt.to_tensor(x), filter_size=3, weight=pt.to_tensor(w),
            lengths=pt.to_tensor(lens)).numpy())
        for b in range(B):
            for t in range(L):
                ctx = []
                for o in (-1, 0, 1):
                    p = t + o
                    if 0 <= p < lens[b]:
                        ctx.append(x[b, p])
                    else:
                        ctx.append(np.zeros(D, "float32"))
                want = np.concatenate(ctx) @ w
                np.testing.assert_allclose(out[b, t], want, atol=1e-5)


class TestSequenceReshape:
    def test_rechunk(self):
        x = np.arange(24, dtype="float32").reshape(2, 2, 6)
        out = np.asarray(ops.sequence_reshape(pt.to_tensor(x), 3).numpy())
        assert out.shape == (2, 4, 3)
        np.testing.assert_allclose(out.reshape(2, -1), x.reshape(2, -1))
        with pytest.raises(ValueError):
            ops.sequence_reshape(pt.to_tensor(x), 5)


class TestSequenceScatter:
    def test_add_and_overwrite(self):
        x = np.zeros((2, 5, 2), "float32")
        idx = np.array([[0, 2], [1, 9]], "int64")  # 9 out of range
        upd = np.ones((2, 2, 2), "float32")
        lens = np.array([5, 5], "int32")
        out = np.asarray(ops.sequence_scatter(
            pt.to_tensor(x), pt.to_tensor(idx), pt.to_tensor(upd),
            lengths=pt.to_tensor(lens)).numpy())
        assert out[0, 0, 0] == 1.0 and out[0, 2, 0] == 1.0
        assert out[1, 1, 0] == 1.0
        assert out.sum() == 6.0  # OOB row dropped
        # add semantics accumulate
        out2 = np.asarray(ops.sequence_scatter(
            pt.to_tensor(out), pt.to_tensor(idx), pt.to_tensor(upd),
            lengths=pt.to_tensor(lens)).numpy())
        assert out2[0, 0, 0] == 2.0


class TestSequenceEnumerate:
    def test_windows(self):
        x = np.array([[1, 2, 3, 4]], "int64")
        out = np.asarray(ops.sequence_enumerate(
            pt.to_tensor(x), 2, pad_value=0).numpy())
        np.testing.assert_array_equal(
            out[0], [[1, 2], [2, 3], [3, 4], [4, 0]])

    def test_respects_lengths(self):
        x = np.array([[1, 2, 3, 4]], "int64")
        out = np.asarray(ops.sequence_enumerate(
            pt.to_tensor(x), 2, pad_value=-1,
            lengths=pt.to_tensor(np.array([3], "int32"))).numpy())
        np.testing.assert_array_equal(
            out[0], [[1, 2], [2, 3], [3, -1], [-1, -1]])


class TestSequenceSlice:
    def test_slice_per_row(self):
        x = np.arange(20, dtype="float32").reshape(2, 10)
        off = np.array([2, 5], "int64")
        ln = np.array([3, 2], "int64")
        out, lens = ops.sequence_slice(pt.to_tensor(x), pt.to_tensor(off),
                                       pt.to_tensor(ln))
        o = np.asarray(out.numpy())
        assert o.shape == (2, 3)
        np.testing.assert_allclose(o[0], [2, 3, 4])
        np.testing.assert_allclose(o[1], [15, 16, 0])  # padded past len


class TestMiscOps:
    def test_shuffle_channel(self):
        x = np.arange(8, dtype="float32").reshape(1, 4, 1, 2)
        out = np.asarray(ops.shuffle_channel(pt.to_tensor(x), 2).numpy())
        # groups [0,1][2,3] -> interleave: [0,2,1,3]
        np.testing.assert_allclose(out[0, :, 0, 0], [0, 4, 2, 6])
        with pytest.raises(ValueError):
            ops.shuffle_channel(pt.to_tensor(x), 3)

    def test_im2sequence(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        out = np.asarray(ops.im2sequence(pt.to_tensor(x), filter_size=2,
                                         stride=2).numpy())
        assert out.shape == (1, 4, 4)
        np.testing.assert_allclose(out[0, 0], [0, 1, 4, 5])
        np.testing.assert_allclose(out[0, 3], [10, 11, 14, 15])

    def test_row_conv_lookahead(self):
        x = np.arange(12, dtype="float32").reshape(1, 4, 3)
        w = np.zeros((2, 3), "float32")
        w[1] = 1.0  # pure one-step lookahead
        out = np.asarray(ops.row_conv(pt.to_tensor(x),
                                      weight=pt.to_tensor(w)).numpy())
        np.testing.assert_allclose(out[0, :3], x[0, 1:])
        np.testing.assert_allclose(out[0, 3], np.zeros(3))
